"""Three-step PERT inference driver.

TPU-native re-design of ``pert_infer_scRT.run_pert_model``
(reference: pert_model.py:649-901):

  Step 1 — G1/2 cells, each doubled as G1 (rep=0) and G2 (rep=1)
           (reference: pert_model.py:228-251, 718-729), cn/rep observed;
           learns lambda + per-library GC beta means/stds.
  Step 2 — S cells with cn/rep enumerated; beta_means conditioned from
           step 1, lambda fixed; learns rho, a, tau, u, betas, pi
           (reference: pert_model.py:777-830).
  Step 3 — (optional) the pre-trained S model applied to the G1/2 cells
           with rho/a/beta_means conditioned, clone-consensus CN prior
           (reference: pert_model.py:832-899), to catch mislabelled phases.

Each step is one compiled ``lax.while_loop`` fit (see ``infer.svi``); step
transitions pass fitted values as conditioning arrays, and every step
boundary is checkpointed (the reference keeps all state in memory only).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from scdna_replication_tools_tpu.config import ColumnConfig, PertConfig
from scdna_replication_tools_tpu.data.loader import (
    PertData,
    attach_dense_columns,
    pad_cells,
    pad_loci,
)
from scdna_replication_tools_tpu.infer import checkpoint as ckpt
from scdna_replication_tools_tpu.infer import manifest as manifest_mod
from scdna_replication_tools_tpu.infer.svi import FitResult, fit_map
from scdna_replication_tools_tpu.utils import faults as faults_mod
from scdna_replication_tools_tpu.models import priors
from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    cell_entropy_aggregates,
    constrained,
    decode_discrete,
    entropy_aggregates_from_planes,
    init_params,
    per_cell_objective,
    pert_loss,
    ppc_discrepancy,
)
from scdna_replication_tools_tpu.obs import heartbeat as heartbeat_mod
from scdna_replication_tools_tpu.obs import meter as meter_mod
from scdna_replication_tools_tpu.obs import metrics as metrics_mod
from scdna_replication_tools_tpu.obs.controller import ControllerPolicy
from scdna_replication_tools_tpu.ops.gc import gc_features
from scdna_replication_tools_tpu.ops.stats import guess_times, pearson_matrix
from scdna_replication_tools_tpu.obs.runlog import RunLog
from scdna_replication_tools_tpu.ops.transforms import (
    to_positive,
    to_unit_interval,
)
from scdna_replication_tools_tpu.utils import profiling
from scdna_replication_tools_tpu.parallel.mesh import (
    CELLS_AXIS,
    LOCI_AXIS,
    make_mesh,
    replicate_fixed,
    shard_batch,
    shard_params,
)


def _pad_etas(etas: np.ndarray, target_cells: int,
              target_loci: Optional[int] = None) -> np.ndarray:
    """Pad the cells (and optionally loci) axes of an etas tensor with a
    diploid-concentrated prior.  Padding with all-ones would make the
    ploidy guess (argmax of etas) zero for the pad cells and NaN the
    masked loss (see models/pert.py ``_cell_ploidies``); a concentrated
    diploid row keeps every term finite while the masks zero its
    contribution."""
    P = etas.shape[-1]
    dip = min(2, P - 1)
    if target_loci is not None and etas.shape[1] < target_loci:
        pad = target_loci - etas.shape[1]
        pad_block = np.ones((etas.shape[0], pad, P), etas.dtype)
        pad_block[..., dip] = 100.0
        etas = np.concatenate([etas, pad_block], axis=1)
    if etas.shape[0] < target_cells:
        pad = target_cells - etas.shape[0]
        pad_row = np.ones(etas.shape[1:], etas.dtype)
        pad_row[..., dip] = 100.0
        etas = np.concatenate(
            [etas, np.broadcast_to(pad_row, (pad,) + etas.shape[1:])], axis=0)
    return etas


def _loci_mask_arr(data: PertData):
    """(loci,) float mask for PertBatch, or None when all loci are real.

    Returning None for an all-true mask keeps the compiled loss free of
    dead all-ones multiplies in the common unpadded case."""
    if data.loci_mask is None or data.loci_mask.all():
        return None
    return jnp.asarray(data.loci_mask.astype(np.float32))


@dataclasses.dataclass
class StepOutput:
    fit: FitResult
    spec: PertModelSpec
    fixed: dict
    batch: PertBatch
    wall_time: float


@dataclasses.dataclass(frozen=True)
class _PertLossFn:
    """Value-hashable loss callable for the program cache.

    Two fits whose (spec, mesh) are equal — and whose arguments share
    shapes/dtypes/shardings — are the SAME XLA program; closing over
    spec/mesh in a fresh lambda per step hid that equality from every
    cache layer (jax.jit keys on callable identity), so each step paid
    its own trace_to_jaxpr + compile.  A frozen dataclass compares and
    hashes by value, which lets ``infer.svi``'s AOT program cache (and,
    transitively, the persistent compilation cache) dedupe the builds.
    """

    spec: PertModelSpec
    mesh: object = None  # jax.sharding.Mesh is hashable

    def __call__(self, params, fixed, batch):
        return pert_loss(self.spec, params, fixed, batch, mesh=self.mesh)


class PertInference:
    """Orchestrates the three SVI steps on dense inputs.

    ``clone_idx_s`` / ``clone_idx_g1`` are dense integer clone assignments
    aligned with the cell axes of ``s_data`` / ``g1_data`` (the pandas
    facade produces them from ``clone_col``).
    """

    def __init__(
        self,
        s_data: PertData,
        g1_data: PertData,
        config: PertConfig = PertConfig(),
        clone_idx_s: Optional[np.ndarray] = None,
        clone_idx_g1: Optional[np.ndarray] = None,
        num_clones: int = 0,
        run_log: Optional[RunLog] = None,
        metrics: Optional[metrics_mod.MetricsRegistry] = None,
    ):
        if config.resume not in ("auto", "force", "off"):
            # validate BEFORE any manifest mutation below: a typo'd
            # resume value must not cost durable resume state
            raise ValueError(
                f"resume must be 'auto', 'force' or 'off', got "
                f"{config.resume!r}")
        # fail fast on the optimizer knobs too (resolve_fused_adam /
        # moment_jnp_dtype raise on unknown values) — surfacing a typo
        # inside the step-2 fit would waste the whole step-1 fit first
        from scdna_replication_tools_tpu.ops import adam_kernel
        self._fused_adam = adam_kernel.resolve_fused_adam(
            config.fused_adam)
        adam_kernel.moment_jnp_dtype(config.optimizer_state_dtype)
        self.s = s_data
        self.g1 = g1_data
        self.config = config
        self.clone_idx_s = clone_idx_s
        self.clone_idx_g1 = clone_idx_g1
        self.num_clones = num_clones
        self.L = s_data.num_libraries
        self.mirror_rescue_stats = None  # filled by _mirror_rescue
        # per-cell rescue outcome for the QC table: {"candidates": idx
        # array, "accepted": idx array} into the step-2 cell axis
        self._rescue_cells = None
        # end-to-end phase ledger: every stage of steps 1-3 (build, h2d,
        # trace, compile, fit, decode, packaging...) accumulates here so
        # callers (api.scRT, tools/full_pipeline_bench) can report where
        # the wall-clock actually went
        self.phases = profiling.PhaseTimer()
        # structured run telemetry (obs/runlog.py): when the caller (the
        # api facade, a bench tool) owns a session it passes the log in
        # (run()'s re-entrant session wrapper then defers to it); a
        # directly-driven runner creates its own from the config
        self.run_log = run_log if run_log is not None \
            else RunLog.create(config.telemetry_path)
        # typed metrics registry (obs/metrics.py): fed by the RunLog
        # emit seam + the PhaseTimer sink, exported as metrics_snapshot
        # events at step boundaries (+ a final one at run_end) and,
        # when configured, an atomically-rewritten Prometheus textfile.
        # Installed process-wide like the fault plan — the newest
        # runner's registry wins, so counters never leak across runs
        self._owns_metrics = metrics is None
        self.metrics = metrics if metrics is not None \
            else metrics_mod.MetricsRegistry.create(
                textfile_path=config.metrics_textfile)
        metrics_mod.install(self.metrics)
        # the phase sink is pinned to THIS registry (not resolved from
        # the process-global seam at call time): a worker interleaving
        # a worker-level log with per-request runs must never cross-
        # feed phase seconds between their registries
        metrics_mod.attach_phase_sink(self.phases, registry=self.metrics)
        # the log's final run_end snapshot comes from THIS registry —
        # and the emit seam routes every event this log records into it
        self.run_log.metrics_registry = self.metrics
        # device-cost attribution ledger (obs/meter.py): rides the
        # RunLog so every dispatch site (svi chunk loop, compile
        # resolution, the decode/PPC slabs below) books billed
        # device-seconds, waste and effective work against this run;
        # close_run lands its summary as run_end's `meter` section
        meter_scope = {"run": "pert"}
        if config.request_id:
            meter_scope["request"] = str(config.request_id)
        self.meter = meter_mod.CostLedger(scope=meter_scope)
        self.meter.metrics_registry = self.metrics
        self.run_log.meter_ledger = self.meter
        # causal span tracing (obs/spans.py): wire a tracer onto the
        # log when the config asks for one and the caller (the facade)
        # has not already attached it — phases become spans through the
        # on_add chain, the chunk loop records fit/chunk spans via the
        # runlog.current() seam, and RunLog.session opens the root
        # 'run' span.  Tracing off = no tracer = a log with zero
        # span bytes (the schema-v8 gating contract).
        if config.trace_spans \
                and getattr(self.run_log, "tracer", None) is None:
            from scdna_replication_tools_tpu.obs import spans as spans_mod
            spans_mod.attach_tracer(
                self.run_log, spans_mod.tracer_for_run(config))
        if getattr(self.run_log, "tracer", None) is not None:
            from scdna_replication_tools_tpu.obs import spans as spans_mod
            spans_mod.attach_phase_sink(self.phases, self.run_log.tracer)
        if config.request_id and run_log is None:
            # serving-worker identity: folded into run_start so the
            # fleet index can group per-request logs (`--request`).
            # Directly-driven runners only — the api facade stamps the
            # log it owns itself, before its session opens
            self.run_log.add_context(request_id=str(config.request_id))
        if config.slab_width and run_log is None:
            # batched-serving provenance (worker --max-batch): this
            # run was one block of a width-K slab
            self.run_log.add_context(slab_width=int(config.slab_width))
        # persistent XLA compilation cache (no-op when already configured
        # or disabled): repeated runs skip the per-step-program compiles
        self.compile_cache_dir = profiling.enable_persistent_compile_cache(
            config.compile_cache_dir)
        # persistent AOT EXECUTABLE store (infer/aotcache.py): activated
        # per runner construction, so resume and elastic mesh-shrink
        # re-entries (each builds a fresh runner) probe the store and
        # skip XLA entirely on a digest hit.  The digest embeds the
        # PROGRAM-shaping config hash: NON_HASH_FIELDS' complement
        # MINUS the execution-only path fields (AOT_EXECUTION_ONLY_
        # FIELDS) — the serve worker moves checkpoint_dir per request,
        # and a restarted worker must still disk-hit its predecessor's
        # executables.  Newest runner wins, like the faults install
        # below.
        import dataclasses as _dc

        from scdna_replication_tools_tpu.config import \
            AOT_EXECUTION_ONLY_FIELDS
        from scdna_replication_tools_tpu.infer import aotcache as \
            aotcache_mod
        from scdna_replication_tools_tpu.obs.runlog import _config_digest

        aot_cfg = _dc.asdict(config)
        for field in AOT_EXECUTION_ONLY_FIELDS:
            aot_cfg.pop(field, None)
        aotcache_mod.activate(config.executable_cache_dir,
                              config_digest=_config_digest(aot_cfg))
        # fault-injection plan (utils/faults.py): config/env-gated,
        # deterministic, inert (a single global None check per site)
        # unless a spec is present.  Installed unconditionally — the
        # newest runner's config wins, so a resume run with faults=None
        # cannot inherit a previous run's plan in the same process
        faults_mod.install(faults_mod.resolve_plan(config.faults))
        # live run-health heartbeat (obs/heartbeat.py): EVERY process
        # publishes health/host_<rank>.json — unlike the RunLog, whose
        # create() no-ops on rank > 0, the whole point is per-host
        # visibility.  Installed process-wide (newest runner wins, like
        # the registry and fault plan above); run() writes the terminal
        # state on completion/Exception — BaseException (preemption)
        # deliberately leaves the last heartbeat to go stale, which is
        # how the watcher flags the host presumed-lost.
        self._heartbeat = None
        hb_dir = heartbeat_mod.resolve_dir(config.heartbeat_dir,
                                           config.checkpoint_dir)
        if hb_dir:
            from scdna_replication_tools_tpu.obs.runlog import \
                _config_digest as _hb_digest
            from scdna_replication_tools_tpu.parallel.distributed import (
                process_rank_and_count,
            )

            hb_rank, hb_count = process_rank_and_count()
            self._heartbeat = heartbeat_mod.RunHeartbeat(
                hb_dir,
                interval_seconds=config.heartbeat_interval_seconds,
                process_index=hb_rank, process_count=hb_count,
                config_digest=_hb_digest(config))
            heartbeat_mod.install(self._heartbeat)
            heartbeat_mod.attach_phase_sink(self.phases)
        # durable run manifest (infer/manifest.py): the resume ledger of
        # the checkpoint directory — identity (config hash + data
        # fingerprint) decides whether existing checkpoints belong to
        # THIS workload, per-step statuses record how far prior attempts
        # got.  resume='auto' restores only fingerprint-verified state;
        # a mismatch under 'auto' voids the stale step ledger.
        self._manifest = None
        self._resume_ok = False
        self._resume_reason = "checkpointing disabled"
        # steps THIS process has checkpointed: a transient retry may
        # always resume what this very run wrote, even when the
        # directory's prior identity could not be verified (fresh dir,
        # or a mismatch that reset the ledger) — the files carry the
        # current identity by construction
        self._steps_written: set = set()
        if config.checkpoint_dir:
            from scdna_replication_tools_tpu.obs.runlog import \
                _config_digest

            # everything the fit consumes, not just reads: changed CN
            # states, clone assignments or the RT prior also invalidate
            # old checkpoints (the priors/conditioning they shaped)
            local_fp = manifest_mod.data_fingerprint(
                s_data.reads, g1_data.reads, s_data.states,
                g1_data.states, clone_idx_s, clone_idx_g1,
                s_data.rt_prior)
            # multi-host identity: each rank digests what IT loaded,
            # the combined fingerprint is the deduped fingerprint-of-
            # fingerprints (host-count-portable while every host loads
            # the full batch — see infer/manifest.py)
            host_fps = manifest_mod.all_host_fingerprints(local_fp)
            fingerprint = manifest_mod.combined_fingerprint(host_fps)
            from scdna_replication_tools_tpu.parallel.distributed import (
                process_rank_and_count,
            )

            proc_index, _ = process_rank_and_count()
            cfg_hash = _config_digest(config)
            m = manifest_mod.RunManifest.load(config.checkpoint_dir)
            self._resume_ok, self._resume_reason = m.match(
                cfg_hash, fingerprint, host_fingerprint=local_fp,
                process_index=proc_index)
            # the per-host fallback judges LOCAL data: make the verdict
            # SPMD-consistent (any rank's refusal refuses everywhere)
            # or a split verdict would desynchronize the lockstep fit.
            # Every rank enters the allgather — a verdict-gated call
            # would itself deadlock on the exact split it guards against
            agreed = manifest_mod.consensus_ok(self._resume_ok)
            if self._resume_ok and not agreed:
                self._resume_ok = False
                self._resume_reason = (
                    "a peer process refused the data fingerprint "
                    "(split per-host verdict — resuming on partial "
                    "agreement would desynchronize the ranks)")
            had_identity = m.doc.get("data_fingerprint") is not None
            reset = (config.resume == "off"
                     or (had_identity and not self._resume_ok
                         and config.resume != "force"))
            if reset and proc_index == 0:
                # voiding the ledger must also retire the FILES: once
                # this run's identity lands in the manifest, surviving
                # stale checkpoints would fingerprint-verify for the
                # next run and restore params fitted to other data.
                # Process 0 only — N ranks racing the renames on one
                # shared directory would half-quarantine generations.
                ckpt.quarantine_stale(config.checkpoint_dir)
            m.begin_run(cfg_hash, fingerprint,
                        run_log_path=self.run_log.path,
                        reset_steps=reset, host_fingerprints=host_fps)
            from scdna_replication_tools_tpu.parallel.distributed import (
                barrier,
            )

            # peers must not race ahead and load a checkpoint process 0
            # is mid-quarantine / mid-commit on
            barrier("pert-manifest/begin_run")
            self._manifest = m
            if had_identity and not self._resume_ok \
                    and config.resume == "auto":
                profiling.logger.warning(
                    "checkpoint dir %s: %s — starting fresh (use "
                    "resume='force' to override)", config.checkpoint_dir,
                    self._resume_reason)
        if config.rho_from_rt_prior and s_data.rt_prior is None:
            # fail fast: surfacing this inside run_step2 would waste the
            # whole step-1 fit first
            raise ValueError(
                "rho_from_rt_prior=True but no RT-prior column was found "
                "in the input (rt_prior_col); provide the column or drop "
                "the flag")
        self._mesh = None
        ls = config.loci_shards
        if config.num_shards is None or config.num_shards == 0:
            # None/0 = use every local device
            self._mesh = make_mesh(loci_shards=ls)
        elif config.num_shards > 1 or ls > 1:
            self._mesh = make_mesh(config.num_shards, loci_shards=ls)
        if self._mesh is not None:
            # realized device topology: folded into run_start when the
            # session is not yet open, a `note` event otherwise
            self.run_log.add_context(mesh={
                "axes": {str(k): int(v)
                         for k, v in self._mesh.shape.items()},
                "num_devices": int(len(self._mesh.devices.flat)),
            })

    # -- batches ----------------------------------------------------------

    def _enum_impl(self) -> str:
        """Resolve the 'auto' enumerated-likelihood implementation
        (shared policy: ops.enum_kernel.resolve_enum_impl).  When a mesh
        is active the Pallas kernel runs per-device via shard_map — see
        models.pert._enum_bin_loglik."""
        from scdna_replication_tools_tpu.ops.enum_kernel import (
            resolve_enum_impl,
        )
        return resolve_enum_impl(self.config.enum_impl)

    def _gamma_feats(self, data: PertData) -> jnp.ndarray:
        return gc_features(jnp.asarray(data.gammas), self.config.K)

    def _eta_batch_fields(self, etas_padded: np.ndarray) -> dict:
        """PertBatch kwargs for the CN prior: the compact (eta_idx, eta_w)
        planes when the prior is one-hot structured (priors.sparsify_etas)
        and the config allows it, else the dense etas tensor."""
        return priors.eta_batch_fields(
            etas_padded, allow_sparse=self.config.sparse_etas)

    def _maybe_shard(self, batch: PertBatch, params: dict):
        """Place batch + params on the current mesh (single- or
        multi-host).

        Multi-process bridge: the loader still materialises the full
        batch in every process, so each host slices the cells-rows its
        ``HostShard`` assigns before ``shard_*_multihost`` assembles
        the global jax.Arrays — the placement-level contract is the
        production one even while the loader catches up (ROADMAP 1).
        This is also the RESHARDING seam: checkpointed state loads as
        full host arrays and lands here to be re-placed on whatever
        mesh this run built, whatever mesh wrote it."""
        if self._mesh is None:
            return batch, params
        import jax

        if jax.process_count() > 1:
            from scdna_replication_tools_tpu.parallel import (
                distributed as dist,
            )

            shard = dist.HostShard.for_this_process(
                int(np.asarray(batch.reads).shape[0]))
            local_batch = dist.slice_local_batch(batch, shard)
            local_params = dist.slice_local_params(params, shard)
            return (dist.shard_batch_multihost(self._mesh, local_batch,
                                               shard),
                    dist.shard_params_multihost(self._mesh, local_params,
                                                shard))
        return shard_batch(self._mesh, batch), shard_params(self._mesh, params)

    def _place_params(self, params: dict) -> dict:
        """Place a host-materialised parameter pytree on the current
        mesh (identity placement to the default device when no mesh).

        The mirror rescue's splice (and any other host-side param
        surgery) must route through this: rebuilding leaves with bare
        ``jnp.asarray`` silently DE-SHARDS the model state, forcing
        every downstream decode/QC pass onto one device — the exact
        failure ``test_sharded_partial_fit_resume_is_exact`` pins."""
        if self._mesh is None:
            return {k: jnp.asarray(v) for k, v in params.items()}
        import jax

        if jax.process_count() > 1:
            from scdna_replication_tools_tpu.parallel import (
                distributed as dist,
            )

            ncells = int(np.asarray(params["tau_raw"]).shape[0])
            shard = dist.HostShard.for_this_process(ncells)
            return dist.shard_params_multihost(
                self._mesh, dist.slice_local_params(params, shard), shard)
        return shard_params(self._mesh,
                            {k: jnp.asarray(v) for k, v in params.items()})

    def _place_opt_state(self, opt_state, num_cells: int):
        """Re-place a checkpoint-restored optimizer state onto the
        current mesh: mu/nu leaves inherit their parameter's
        PartitionSpec (the dict key IS the parameter name), everything
        else (the step count) replicates.  Host arrays from ANY saved
        topology come out as arrays of THIS one — the optimizer-state
        half of a resharding resume."""
        if opt_state is None or self._mesh is None:
            return opt_state
        import jax
        from jax.sharding import NamedSharding

        from scdna_replication_tools_tpu import layout
        from scdna_replication_tools_tpu.parallel import distributed as dist
        from scdna_replication_tools_tpu.parallel.mesh import loci_axis

        specs = layout.param_specs(loci_axis(self._mesh))
        multiproc = jax.process_count() > 1
        shard = dist.HostShard.for_this_process(num_cells) if multiproc \
            else None
        leaves, treedef = jax.tree_util.tree_flatten_with_path(opt_state)
        placed = []
        for path, leaf in leaves:
            name = None
            for key in reversed(path):
                if isinstance(key, jax.tree_util.DictKey):
                    name = key.key
                    break
            spec = specs.get(name, layout.replicated_spec())
            if multiproc:
                local = leaf
                axis = layout.param_cells_axis(name) if name else None
                if axis is not None:
                    local = dist.slice_cells_axis(leaf, axis, shard)
                placed.append(dist._place(self._mesh, local, spec,
                                          shard.num_global_cells))
            else:
                placed.append(jax.device_put(
                    leaf, NamedSharding(self._mesh, spec)))
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(opt_state),
            placed)

    def _warn_if_enum_tensor_huge(self, spec: PertModelSpec,
                                  batch: PertBatch) -> None:
        """The XLA broadcast path materialises the (cells, loci, P, 2)
        enumeration tensor (plus AD residuals of the same order); past a
        few GB per device that is OOM territory the reference simply
        crashes into (its README's 20kb-bin warning).  Warn with the
        knobs that avoid it: the fused kernel never materialises the
        tensor, cell_chunk scans it in slabs, sharding divides it."""
        from scdna_replication_tools_tpu.ops.enum_kernel import (
            enum_impl_backend,
        )
        if spec.step1 or enum_impl_backend(spec.enum_impl) != "xla":
            return
        cells, loci = batch.reads.shape
        if self._mesh is not None:
            cells = -(-cells // self._mesh.shape[CELLS_AXIS])
            loci = -(-loci // self._mesh.shape.get(LOCI_AXIS, 1))
        if spec.cell_chunk:
            # chunking bounds the live slab, not the whole tensor — the
            # per-chunk slab can still blow the budget at high loci
            cells = min(cells, spec.cell_chunk)
        gb = cells * loci * spec.P * 2 * 4 / 1e9
        if gb > 2.0:
            profiling.logger.warning(
                "enumeration tensor is %.1f GB per device on the XLA "
                "path (%d cells x %d loci x %d states x 2); consider "
                "enum_impl='pallas' (TPU), cell_chunk=..., or more "
                "shards before this OOMs", gb, cells, loci, spec.P)

    def _pad(self, data: PertData) -> PertData:
        mult = 1
        loci_mult = 1
        if self._mesh is not None:
            mult *= self._mesh.shape[CELLS_AXIS]
            loci_mult = self._mesh.shape.get(LOCI_AXIS, 1)
        if self.config.cell_chunk:
            assert self._mesh is None, (
                "cell_chunk is a single-device memory knob; use sharding "
                "for multi-device runs")
            mult *= self.config.cell_chunk
        # shape-bucket targets (PertConfig.pad_cells_to/pad_loci_to):
        # pad up to the bucket dims ON TOP of the shard-multiple
        # padding, so every request the serving worker admits into one
        # bucket produces identically-shaped batches — and therefore
        # hits the resident AOT program cache instead of compiling.
        # A population larger than its target simply pads to the
        # multiple as before (the worker's bucket selection refuses
        # oversized requests before they reach the runner).
        cells_min = self.config.pad_cells_to
        loci_min = self.config.pad_loci_to
        if mult > 1 or cells_min:
            data = pad_cells(data, mult, minimum=cells_min)
        if loci_mult > 1 or loci_min:
            data = pad_loci(data, loci_mult, minimum=loci_min)
        return data

    def g1_g2_doubled_batch(self) -> Tuple[PertBatch, PertData]:
        """Step-1 batch: every G1 cell appears as G1 (rep=0) and G2 (rep=1).

        Mirrors ``make_g1_g2_training_data`` (reference:
        pert_model.py:228-251) on the cells axis.
        """
        g1 = self._pad(self.g1)
        reads = np.concatenate([g1.reads, g1.reads], axis=0)
        states = np.concatenate([g1.states, g1.states], axis=0)
        libs = np.concatenate([g1.libs, g1.libs])
        mask = np.concatenate([g1.cell_mask, g1.cell_mask]).astype(np.float32)
        rep = np.concatenate([
            np.zeros_like(g1.reads), np.ones_like(g1.reads)], axis=0)
        batch = PertBatch(
            reads=jnp.asarray(reads),
            libs=jnp.asarray(libs),
            gamma_feats=self._gamma_feats(g1),
            mask=jnp.asarray(mask),
            cn_obs=jnp.asarray(states),
            rep_obs=jnp.asarray(rep),
            loci_mask=_loci_mask_arr(g1),
        )
        return batch, g1

    # -- CN priors --------------------------------------------------------

    def build_etas(self) -> np.ndarray:
        """CN prior concentrations for the S cells, per ``cn_prior_method``
        (reference: pert_model.py:668-716)."""
        cfg = self.config
        method = cfg.cn_prior_method
        P = cfg.P
        s = self.s
        num_cells, num_loci = s.reads.shape

        if method == "hmmcopy":
            if s.states is None:
                raise ValueError("hmmcopy prior requires S-phase CN states")
            return priors.cn_prior_from_states(s.states, P, cfg.cn_prior_weight)

        if method == "diploid":
            dip = np.full((num_cells, num_loci), 2.0, np.float32)
            return priors.cn_prior_from_states(dip, P, cfg.cn_prior_weight)

        if method in ("g1_cells", "g1_clones", "g1_composite"):
            clone_profiles = priors.consensus_clone_profiles(
                self.g1.states, self.clone_idx_g1, self.num_clones,
                states=self.g1.states)
            if method == "g1_clones":
                return priors.clone_cn_prior(
                    self.clone_idx_s, clone_profiles, P, cfg.cn_prior_weight)
            if method == "g1_composite":
                return priors.composite_cn_prior(
                    s.reads, self.clone_idx_s, self.g1.reads, self.g1.states,
                    self.clone_idx_g1, clone_profiles, P, J=cfg.J)
            # g1_cells: single best-correlated G1 cell's states
            # (reference: pert_model.py:671-701)
            corr = np.asarray(pearson_matrix(s.reads, self.g1.reads))
            if self.clone_idx_s is not None:
                same = self.clone_idx_s[:, None] == self.clone_idx_g1[None, :]
                corr = np.where(same, corr, -np.inf)
            best = np.argmax(corr, axis=1)
            return priors.cn_prior_from_states(
                self.g1.states[best], P, cfg.cn_prior_weight)

        # uniform fallback (reference: pert_model.py:713-716)
        return priors.uniform_prior(num_cells, num_loci, P)

    def build_etas_step3(self) -> np.ndarray:
        """Clone-consensus prior for the G1 cells (reference:
        pert_model.py:853-854)."""
        clone_profiles = priors.consensus_clone_profiles(
            self.g1.states, self.clone_idx_g1, self.num_clones,
            states=self.g1.states)
        return priors.clone_cn_prior(
            self.clone_idx_g1, clone_profiles, self.config.P,
            self.config.cn_prior_weight)

    # -- steps ------------------------------------------------------------

    def _controller_active(self, min_iter, max_iter) -> bool:
        """The documented inert conditions (config.py, OBSERVABILITY.md)
        in ONE place for both the in-fit controller and the step-2
        rescue gate: the controller needs a flight recorder to read
        (``fit_diag_every > 0``) and a budget that is not pinned exact
        (``min_iter < max_iter`` — e.g. the donation/resume exactness
        tests run min == max and must see the untouched fixed
        trajectory, with no gating anywhere)."""
        cfg = self.config
        return bool(cfg.controller and cfg.fit_diag_every
                    and int(min_iter) < int(max_iter))

    def _fit(self, spec, batch, fixed, t_init, max_iter, min_iter,
             step_name) -> StepOutput:
        """One step fit under the recovery ladder (utils/faults.py):

        * **transient** failures (tunnel drops, UNAVAILABLE) retry with
          bounded exponential backoff — and because the chunked driver
          saved an in-flight checkpoint on the way out, each retry
          RESUMES the fit rather than restarting it;
        * **oom** / **hang** abort with the resumable artifact that
          same save left behind (plus a ``degrade`` audit event) — the
          next ``--resume auto`` run continues mid-budget;
        * **hostloss** (and REPEATED OOM — the first sharded OOM gets
          one audited same-mesh re-entry, since shrinking raises
          per-device load) in a SHARDED fit walks the **elastic
          rung**: rebuild a smaller mesh (halve the cells axis,
          ultimately one device), re-place the last checkpoint through
          the normal resume path, and continue — every shrink audited
          as a ``degrade mesh_shrink`` event with before/after
          topology; when the ladder is exhausted the fit aborts with
          the resumable artifact like any other OOM;
        * **preemption** (BaseException) propagates untouched after the
          graceful save: the process is going away;
        * **deterministic** errors propagate immediately — retrying a
          real bug only hides it.
        """
        cfg = self.config

        def attempt():
            try:
                return self._fit_once(spec, batch, fixed, t_init,
                                      max_iter, min_iter, step_name)
            except Exception as exc:
                kind = faults_mod.classify_exception(exc)
                if kind in ("oom", "hang", "hostloss") \
                        and not self._shrink_eligible(kind):
                    self.run_log.emit(
                        "degrade", step=step_name,
                        action=("watchdog_abort" if kind == "hang"
                                else "abort_resumable"),
                        error_class=kind,
                        error=f"{type(exc).__name__}: {str(exc)[:300]}",
                        detail=("fit aborted on a non-retryable "
                                f"{kind}; the in-flight checkpoint "
                                "(when checkpointing is enabled) makes "
                                "the next --resume auto run continue "
                                "mid-budget"))
                raise

        # transient classification, deterministic backoff and the
        # `retry` audit event all live in ONE place (utils/faults.py);
        # each retry re-enters _fit_once, whose _load_resumable picks
        # up the in-flight checkpoint — retries RESUME, not restart.
        # The outer loop is the ELASTIC rung: a hostloss/OOM that
        # escapes the retry ladder shrinks the mesh (bounded — each
        # pass halves the cells extent) and re-enters, which re-places
        # the emergency checkpoint on the smaller topology.
        oom_count = 0
        while True:
            try:
                return faults_mod.retry_call(
                    attempt, label=f"{step_name}/fit",
                    max_attempts=int(cfg.retry_max_attempts),
                    base_delay=float(cfg.retry_backoff_seconds))
            except Exception as exc:
                kind = faults_mod.classify_exception(exc)
                if kind == "oom":
                    # shrinking the cells axis RAISES per-device load
                    # (fewer devices carry the same cells), so the rung
                    # engages only on REPEATED OOM as the recovery
                    # contract specifies: the first sharded OOM gets
                    # one audited same-mesh re-entry (resuming the
                    # in-flight checkpoint — an allocator spike or
                    # fragmentation clears; a genuine roofline OOM
                    # recurs immediately and then walks the ladder).
                    # hostloss shrinks at once: the device is GONE.
                    oom_count += 1
                    if oom_count == 1 and self._shrink_eligible(kind):
                        self.run_log.emit(
                            "retry", label=f"{step_name}/fit-oom",
                            attempt=1, max_attempts=2,
                            delay_seconds=0.0, error_class=kind,
                            error=(f"{type(exc).__name__}: "
                                   f"{str(exc)[:300]}"))
                        profiling.logger.warning(
                            "sharded fit OOM at %s: one same-mesh "
                            "re-entry from the last checkpoint before "
                            "the elastic rung engages", step_name)
                        continue
                if not self._try_mesh_shrink(step_name, kind, exc):
                    raise

    def _shrink_eligible(self, kind: str) -> bool:
        """Would :meth:`_try_mesh_shrink` accept this failure class?

        Elastic shrink is an IN-PROCESS remedy: it needs a mesh with
        more than one device left, a single controlling process (a
        multi-host window change goes through preempt -> resume on the
        next window's shape instead — the checkpoints are topology-
        portable precisely so that path works), and a hostloss/OOM
        class.  ``PertConfig.elastic_mesh`` turns the rung off."""
        from scdna_replication_tools_tpu.parallel.mesh import shrink_mesh

        if not self.config.elastic_mesh or self._mesh is None:
            return False
        if kind not in ("hostloss", "oom"):
            return False
        import jax

        if jax.process_count() > 1:
            return False
        return shrink_mesh(self._mesh) is not None

    def _try_mesh_shrink(self, step_name: str, kind: str,
                         exc: BaseException) -> bool:
        """One rung of the elastic ladder: swap ``self._mesh`` for its
        halved-cells successor and audit the transition.  Returns False
        (caller re-raises) when the failure class or topology is not
        eligible — including ladder exhaustion, which the `attempt`
        audit already recorded as ``abort_resumable``."""
        from scdna_replication_tools_tpu.parallel.mesh import (
            mesh_topology,
            shrink_mesh,
        )

        if not self._shrink_eligible(kind):
            return False
        new_mesh = shrink_mesh(self._mesh)
        if new_mesh is None:
            return False
        before = mesh_topology(self._mesh)
        after = mesh_topology(new_mesh)
        self._mesh = new_mesh
        self.run_log.add_context(mesh={
            "axes": after,
            "num_devices": int(len(new_mesh.devices.flat)),
        })
        self.run_log.emit(
            "degrade", step=step_name, action="mesh_shrink",
            error_class=kind,
            error=f"{type(exc).__name__}: {str(exc)[:300]}",
            from_topology={"mesh_axes": before},
            to_topology={"mesh_axes": after},
            detail=(f"elastic rung: {kind} in a sharded fit — mesh "
                    f"shrunk {before} -> {after}; the fit re-enters "
                    "through the resume path and re-places the last "
                    "checkpoint on the smaller topology"))
        profiling.logger.warning(
            "elastic mesh shrink (%s at %s): %s -> %s — re-entering the "
            "fit from the last checkpoint", kind, step_name, before,
            after)
        return True

    def _load_resumable(self, step_name, max_iter, spec, fixed, batch):
        """Resume-mode + manifest-aware checkpoint restore for one step.

        Returns a completed :class:`StepOutput` (restore, no refit), a
        ``(params0, opt_state0, losses_prefix, resume_ctrl)`` tuple for
        a partial fit, or None for a fresh fit.  Every outcome that
        touched a checkpoint emits a ``resume`` event so the decision
        is reproducible from the artifact alone.
        """
        cfg = self.config
        if cfg.resume == "off" and step_name not in self._steps_written:
            # 'off' ignores PRE-EXISTING state; a transient retry still
            # resumes the checkpoints this very run wrote
            return None
        if cfg.resume == "auto" and not self._resume_ok \
                and step_name not in self._steps_written:
            # only audit a refusal when there was something to refuse
            if os.path.exists(os.path.join(
                    cfg.checkpoint_dir, f"pert_{step_name}.npz")):
                self.run_log.emit(
                    "resume", step=step_name, mode=cfg.resume,
                    action="fresh", fingerprint_verified=False,
                    reason=self._resume_reason)
            return None
        try:
            restored = ckpt.load_step(cfg.checkpoint_dir, step_name)
        except ckpt.CheckpointCorrupt as exc:
            # graceful degradation: a corrupt artifact (and no valid
            # retained predecessor) costs a refit, never the run
            self.run_log.emit("degrade", step=step_name,
                              action="checkpoint_discarded",
                              error_class="corrupt",
                              detail=str(exc)[:500])
            profiling.logger.warning("%s — refitting %s from scratch",
                                     exc, step_name)
            return None
        if restored is None:
            return None
        params, losses, extra = restored
        params = {k: jnp.asarray(v) for k, v in params.items()}
        num_iters = int(extra.get("meta.num_iters", len(losses)))
        converged = bool(extra.get("meta.converged", True))
        nan_abort = bool(extra.get("meta.nan_abort", False))
        resume_ctrl = ckpt.restore_controller_state(extra)
        # resharding resume: compare the checkpoint's topology stamp
        # against THIS run's.  Any mesh restores onto any mesh — the
        # loaded leaves are full host arrays that _maybe_shard /
        # _place_opt_state re-place from the layout contract — but the
        # geometry change is audited: bit-exact continuation holds only
        # when the reduction geometry is unchanged; a cross-topology
        # resume is parity-gated by the chaos matrix instead (see
        # tests/test_topology_resume.py), and only a DATA mismatch
        # refuses (the manifest gate above).  Pre-v4 checkpoints carry
        # no stamp: geometry unknown, recorded as unstamped.
        saved_topo = extra.get("meta.topology") \
            if isinstance(extra.get("meta.topology"), dict) else None
        from scdna_replication_tools_tpu.parallel.distributed import (
            process_topology,
        )

        cur_topo = process_topology(self._mesh)
        resharded = False
        if saved_topo is not None:
            resharded = (
                saved_topo.get("mesh_axes") != cur_topo["mesh_axes"]
                or int(saved_topo.get("process_count", 1))
                != int(cur_topo["process_count"]))
        reshard_fields = dict(
            resharded=bool(resharded),
            from_topology=({"mesh_axes": saved_topo.get("mesh_axes"),
                            "process_count":
                                saved_topo.get("process_count")}
                           if saved_topo is not None else None),
            to_topology={"mesh_axes": cur_topo["mesh_axes"],
                         "process_count": cur_topo["process_count"]})
        if resharded:
            profiling.logger.warning(
                "resharding resume for %s: checkpoint topology %s -> "
                "current %s (bit-exact only when the reduction geometry "
                "is unchanged; the continued trajectory is parity-"
                "gated, not identical)", step_name,
                reshard_fields["from_topology"],
                reshard_fields["to_topology"])
        # a controller-extended budget survives in the resume state (a
        # fit killed past max_iter but inside its extended budget is
        # still PARTIAL) — but a GROWN config budget wins: resuming
        # with a larger max_iter is the documented budget-growth
        # workflow, and the saved (smaller) budget must not mark the
        # step complete before the new budget ran
        budget = int(max_iter)
        if resume_ctrl:
            budget = max(int(resume_ctrl["budget"]), budget)
            resume_ctrl["budget"] = budget
        completed = bool(converged or nan_abort or num_iters >= budget)
        self.run_log.emit(
            "checkpoint", action="load", step=step_name,
            path=str(cfg.checkpoint_dir), num_iters=num_iters,
            completed=completed)
        own_write = step_name in self._steps_written
        self.run_log.emit(
            "resume", step=step_name, mode=cfg.resume,
            action="restored" if completed else "resumed",
            from_iter=num_iters,
            fingerprint_verified=bool(self._resume_ok or own_write),
            reason=("checkpoint written by this run (retry resume)"
                    if own_write and not self._resume_ok
                    else self._resume_reason),
            **reshard_fields)
        if completed:
            # completed step: restore, no refit — but PLACE the
            # restored host arrays on this run's mesh first: the
            # decode/QC/conditioning consumers downstream run over
            # these params, and raw numpy would silently de-shard them
            # onto one device (the same failure _place_params pins for
            # the rescue splice).  budget must be a real integer — the
            # rescue gate's control_decision event types it as such in
            # the schema, restored fits included
            fit = FitResult(params=self._place_params(params),
                            losses=losses,
                            num_iters=num_iters, converged=converged,
                            nan_abort=nan_abort,
                            budget=max(budget, num_iters))
            if self._manifest is not None:
                self._manifest.update_step(step_name, "complete",
                                           num_iters=num_iters)
            return StepOutput(fit, spec, fixed, batch, 0.0)
        # partial step: resume from the saved iteration with Adam
        # moments (and, for chunked fits, the controller ledger) intact
        # — exact continuation of the trajectory.  The moments' stored
        # dtype is part of that contract: resuming float32 moments
        # under optimizer_state_dtype='bfloat16' (or vice versa) CANNOT
        # be bit-exact — the continued trajectory would silently
        # diverge from both the uninterrupted run and a fresh fit — so
        # a dtype mismatch refuses loudly instead of degrading.
        saved_dt = str(extra.get("meta.opt_moment_dtype", "float32"))
        has_opt = any(k.startswith("opt.") for k in extra)
        if has_opt and saved_dt != cfg.optimizer_state_dtype:
            raise ValueError(
                f"checkpoint for {step_name} in {cfg.checkpoint_dir} "
                f"stores Adam moments as {saved_dt} but this run "
                f"configures optimizer_state_dtype="
                f"{cfg.optimizer_state_dtype!r}: a mid-budget resume "
                "across moment dtypes cannot be bit-exact — rerun with "
                f"optimizer_state_dtype='{saved_dt}', or resume='off' "
                "to refit the step fresh")
        opt_state0 = ckpt.restore_opt_state(
            extra, params, cfg.learning_rate, cfg.adam_b1, cfg.adam_b2)
        losses_prefix = np.asarray(losses)[:num_iters]
        return params, opt_state0, losses_prefix, resume_ctrl

    def _fit_once(self, spec, batch, fixed, t_init, max_iter, min_iter,
                  step_name) -> StepOutput:
        cfg = self.config
        params0 = opt_state0 = losses_prefix = None
        resume_ctrl = None
        if cfg.checkpoint_dir:
            loaded = self._load_resumable(step_name, max_iter, spec,
                                          fixed, batch)
            if isinstance(loaded, StepOutput):
                return loaded
            if loaded is not None:
                params0, opt_state0, losses_prefix, resume_ctrl = loaded

        # phase-boundary injection site: a preemption here models the
        # classic kill-between-steps window
        faults_mod.point(f"{step_name}/start")
        # HBM high-water before the step's programs run, so the
        # per-phase delta in the snapshots is attributable to the step.
        # self.metrics, not the process-global seam: this run's samples
        # must land in this run's registry even when another run is
        # interleaved in the same process (the serving worker)
        self.metrics.sample_device_memory()
        if self._manifest is not None:
            self._manifest.update_step(
                step_name, "in_flight",
                num_iters=len(losses_prefix)
                if losses_prefix is not None else 0)

        if self._mesh is not None and jax.process_count() == 1:
            # the conditioning dict may still be committed to a
            # PREVIOUS mesh (elastic shrink re-enters the fit inside
            # this process) — commit it to the current one, replicated;
            # an unchanged mesh makes this an identity.  Multi-process
            # fixed leaves are already global arrays of the live mesh
            # (a multi-host topology change goes through process
            # restart, which rebuilds them).
            fixed = replicate_fixed(self._mesh, fixed)
        if params0 is None:
            with self.phases.phase(f"{step_name}/init"):
                params0 = init_params(spec, batch, fixed, t_init=t_init)
        self._warn_if_enum_tensor_huge(spec, batch)
        with self.phases.phase(f"{step_name}/h2d"):
            # resharding + an explicit barrier so the async host->device
            # transfers jnp.asarray enqueued are accounted here, not
            # silently folded into the fit phase.  A checkpoint-restored
            # optimizer state re-places alongside the params — the
            # restored leaves are full host arrays from WHATEVER
            # topology wrote them (resharding resume), and the fit
            # program expects them on this run's mesh.
            batch, params0 = self._maybe_shard(batch, params0)
            if opt_state0 is not None and self._mesh is not None:
                opt_state0 = self._place_opt_state(
                    opt_state0, int(batch.reads.shape[0]))
            batch, params0, fixed = jax.block_until_ready(
                (batch, params0, fixed))
        from scdna_replication_tools_tpu.ops.enum_kernel import (
            enum_impl_backend,
        )
        mesh = self._mesh \
            if enum_impl_backend(spec.enum_impl) != "xla" else None

        loss_fn = _PertLossFn(spec=spec, mesh=mesh)

        if not spec.step1:
            # analytic per-iteration HBM traffic of this step's fused
            # iteration (ops/enum_kernel.planes_per_iter) — a STABLE
            # gauge, so it rides the metrics_snapshot events into the
            # fleet index and the regression gate holds encoding wins
            # (binary vs categorical, bf16 vs f32 moments)
            from scdna_replication_tools_tpu.ops.enum_kernel import (
                enum_impl_binary,
                planes_per_iter,
            )
            self.metrics.gauge(
                "pert_planes_moved_per_iter",
                labels={"step": step_name}).set(planes_per_iter(
                    spec.P, binary=enum_impl_binary(spec.enum_impl),
                    sparse_etas=spec.sparse_etas,
                    moment_dtype=cfg.optimizer_state_dtype))

        controller = None
        if self._controller_active(min_iter, max_iter):
            controller = ControllerPolicy.from_config(cfg, max_iter)

        checkpoint_cb = None
        if cfg.checkpoint_dir:
            # the durability sink of the chunked driver: periodic
            # in-fit saves (every checkpoint_every chunks) and the
            # emergency save on an escaping exception both land here
            def checkpoint_cb(*, params, opt_state, losses, num_iters,
                              state=None, exact=True, coordinated=True):
                extra = ckpt.pack_controller_state(state) if state \
                    else None
                path = ckpt.save_step(
                    cfg.checkpoint_dir, step_name, params, losses,
                    opt_state=opt_state, num_iters=int(num_iters),
                    converged=False, nan_abort=False, extra=extra,
                    mesh=self._mesh, coordinate=coordinated)
                self._steps_written.add(step_name)
                self.run_log.emit(
                    "checkpoint", action="save", step=step_name,
                    path=str(cfg.checkpoint_dir),
                    num_iters=int(num_iters), completed=False)
                if not exact:
                    self.run_log.emit(
                        "degrade", step=step_name,
                        action="inexact_checkpoint",
                        detail=(f"optimizer state was unavailable at "
                                f"the emergency save (mid-chunk "
                                f"abort); a resume restarts the Adam "
                                f"moments at iteration {num_iters} — "
                                f"the documented rescue tolerance"))
                if self._manifest is not None:
                    self._manifest.update_step(
                        step_name, "in_flight",
                        num_iters=int(num_iters), checkpoint=path,
                        exact=bool(exact))

        # injection site at the fit dispatch itself ({step}/fit):
        # distinct from {step}/chunk (inside the chunked driver's host
        # loop) so a chaos spec can fail a WHOLE step fit on its first
        # attempt — the serve suite's per-request isolation case
        # (`oom@step2/fit#1` on one queued request) fires here, walks
        # the normal abort-resumable audit, and must take down only
        # that request, never the worker
        faults_mod.point(f"{step_name}/fit")
        t0 = time.perf_counter()
        with self.meter.context(step=step_name,
                                **self._meter_attrs(step_name, batch)), \
                profiling.trace(cfg.profile_dir):
            fit = fit_map(loss_fn, params0, (fixed, batch),
                          max_iter=max_iter, min_iter=min_iter,
                          rel_tol=cfg.rel_tol,
                          learning_rate=cfg.learning_rate,
                          b1=cfg.adam_b1, b2=cfg.adam_b2,
                          opt_state0=opt_state0,
                          losses_prefix=losses_prefix,
                          diag_every=cfg.fit_diag_every,
                          doctor_thresholds=dict(
                              window=cfg.doctor_window,
                              slope_tol=cfg.doctor_slope_tol,
                              var_tol=cfg.doctor_var_tol,
                              grad_ratio=cfg.doctor_grad_ratio),
                          controller=controller,
                          escalate_dir=cfg.checkpoint_dir,
                          escalate_tag=step_name,
                          checkpoint_every=cfg.checkpoint_every,
                          checkpoint_cb=checkpoint_cb,
                          resume_state=resume_ctrl,
                          compile_deadline=cfg.watchdog_compile_seconds,
                          chunk_deadline=cfg.watchdog_chunk_seconds,
                          fused_adam=self._fused_adam,
                          moment_dtype=cfg.optimizer_state_dtype)
        wall = time.perf_counter() - t0
        for key in ("trace", "compile", "fit"):
            self.phases.add(f"{step_name}/{key}", fit.timings.get(key, 0.0))
        profiling.log_step_summary(step_name, fit, wall,
                                   int(batch.reads.shape[0]))
        self._emit_fit_events(step_name, fit, wall,
                              int(batch.reads.shape[0]),
                              prior_iters=(len(losses_prefix)
                                           if losses_prefix is not None
                                           else 0))

        if cfg.checkpoint_dir:
            completed = bool(fit.converged or fit.nan_abort
                             or fit.num_iters >= (fit.budget
                                                  if fit.budget is not None
                                                  else max_iter))
            with self.phases.phase(f"{step_name}/checkpoint"):
                ckpt.save_step(cfg.checkpoint_dir, step_name,
                               ckpt.host_view(fit.params),
                               fit.losses,
                               opt_state=ckpt.host_view(fit.opt_state),
                               num_iters=fit.num_iters,
                               converged=fit.converged,
                               nan_abort=fit.nan_abort,
                               mesh=self._mesh)
            self._steps_written.add(step_name)
            self.run_log.emit("checkpoint", action="save", step=step_name,
                              path=str(cfg.checkpoint_dir),
                              num_iters=fit.num_iters,
                              completed=completed)
            if self._manifest is not None:
                self._manifest.update_step(
                    step_name,
                    "complete" if completed else "in_flight",
                    num_iters=fit.num_iters)
        # phase-boundary injection site: the step's outputs are durably
        # committed — a preemption here must resume at the NEXT step
        faults_mod.point(f"{step_name}/end")
        # phase-boundary metrics export: device-memory sample +
        # metrics_snapshot event + atomic textfile refresh.  Accounted
        # as its own phase — the >=95%-coverage invariant must absorb
        # the export cost, however small
        with self.phases.phase(f"{step_name}/metrics"):
            self.metrics.emit_snapshot(self.run_log, f"{step_name}/end")
        return StepOutput(fit, spec, fixed, batch, wall)

    @staticmethod
    def _finite(value):
        """float(value), or None when non-finite — NaN/Infinity are not
        valid strict (RFC 8259) JSON, and the poisoned entries are
        exactly the information for a diverged fit."""
        v = float(value)
        return v if np.isfinite(v) else None

    def _meter_attrs(self, step_name: str, batch) -> dict:
        """Cost-attribution context of one step's dispatches: the REAL
        (unpadded) cell count — effective work units are real cells x
        iterations — plus the bucket-contract ``pad_frac``, the billed
        fraction burnt computing planes for padding cells/loci the
        decode discards (step 3 fits the G1 axis; steps 1-2 the S
        axis)."""
        real = self.g1 if step_name == "step3" else self.s
        padded_cells = int(batch.reads.shape[0])
        padded_loci = int(batch.reads.shape[1])
        real_cells = min(int(real.num_cells), padded_cells)
        real_loci = min(int(real.num_loci), padded_loci)
        pad_frac = 1.0 - (real_cells * real_loci) \
            / max(padded_cells * padded_loci, 1)
        return {
            "cells": real_cells,
            "pad_frac": round(max(pad_frac, 0.0), 6),
            "bucket": f"c{padded_cells}xl{padded_loci}",
        }

    def _emit_fit_events(self, step_name: str, fit: FitResult, wall: float,
                         num_cells: int, prior_iters: int = 0) -> None:
        """``fit_end`` (always) + ``nan_abort`` (on a poisoned fit, with
        the loss-trajectory tail — the post-mortem a terminal scroll
        loses) for one completed step fit.

        ``prior_iters``: iterations restored from a checkpoint — counted
        in ``iters`` (the fit's total) but NOT in the throughput rates,
        whose wall covers only the resumed segment."""
        # the controller's audit trail first — the decisions happened
        # DURING the fit the fit_end event summarises
        for decision in fit.decisions:
            self.run_log.emit("control_decision", step=step_name,
                              **decision)
        iters = max(fit.num_iters - prior_iters, 1)
        diag = None
        if fit.diagnostics is not None and len(fit.diagnostics["iter"]):
            # the ring keeps the LAST <=DIAG_RING samples, so on long
            # fits this is a trailing WINDOW of the trajectory, not its
            # start — the window bounds ride along so readers (and the
            # report's grad-norm column) cannot mistake the oldest
            # surviving sample for the fit's first iteration
            d = fit.diagnostics
            diag = {
                "every": int(d["every"]),
                "samples": int(len(d["iter"])),
                "window_start_iter": int(d["iter"][0]),
                "window_end_iter": int(d["iter"][-1]),
                "grad_norm_first": self._finite(d["grad_norm"][0]),
                "grad_norm_last": self._finite(d["grad_norm"][-1]),
                "grad_norm_max": self._finite(np.max(d["grad_norm"])),
                "param_norm_last": self._finite(d["param_norm"][-1]),
            }
        self.run_log.emit(
            "fit_end", step=step_name, iters=int(fit.num_iters),
            resumed_from_iter=(int(prior_iters) if prior_iters else None),
            final_loss=(float(fit.losses[-1])
                        if len(fit.losses) and np.isfinite(fit.losses[-1])
                        else None),
            converged=bool(fit.converged), nan_abort=bool(fit.nan_abort),
            wall_seconds=round(wall, 4),
            iters_per_second=round(iters / max(wall, 1e-9), 2),
            cells_per_second=round(num_cells * iters / max(wall, 1e-9), 1),
            num_cells=num_cells,
            program_cache=fit.timings.get("program_cache"),
            diagnostics=diag)
        if self.config.qc and fit.health is not None:
            # the convergence doctor's verdict (obs/doctor.py) as its own
            # event: fit_end records WHAT the loop measured, fit_health
            # records what it MEANS — queryable without re-deriving the
            # thresholds from the loss trajectory
            h = fit.health
            self.run_log.emit(
                "fit_health", step=step_name, verdict=h["verdict"],
                reason=h["reason"],
                drift=self._finite(h["drift"]) if h["drift"] is not None
                else None,
                rel_var=self._finite(h["rel_var"])
                if h["rel_var"] is not None else None,
                window=int(h.get("window", 0)),
                grad_decay=self._finite(h["grad_decay"])
                if h["grad_decay"] is not None else None,
                converged=bool(fit.converged),
                nan_abort=bool(fit.nan_abort))
        if fit.nan_abort:
            tail = [self._finite(v) for v in fit.losses[-20:]]
            self.run_log.emit("nan_abort", step=step_name,
                              iters=int(fit.num_iters), loss_tail=tail)

    def run_step1(self) -> StepOutput:
        iters = self.config.resolved_iters()
        with self.phases.phase("step1/build"):
            batch, _ = self.g1_g2_doubled_batch()
            spec = PertModelSpec(
                P=self.config.P, K=self.config.K, L=self.L,
                tau_mode="beta_default", step1=True,
                cell_chunk=self.config.cell_chunk)
        return self._fit(spec, batch, {}, None,
                         iters["max_iter_step1"], iters["min_iter_step1"],
                         "step1")

    def run_step2(self, step1: StepOutput, etas: np.ndarray) -> StepOutput:
        iters = self.config.resolved_iters()
        with self.phases.phase("step2/build"):
            c1 = constrained(step1.spec, step1.fit.params, step1.fixed)
            fixed = {
                "beta_means": c1["beta_means"],  # pert_model.py:782-787
                "lamb": c1["lamb"],            # pert_model.py:801 (lamb=...)
            }
            cond_rho = bool(self.config.rho_from_rt_prior)
            # initial S-phase times from the real (unpadded) cells/loci only
            t_init_real, _, _ = guess_times(jnp.asarray(self.s.reads),
                                            jnp.asarray(etas),
                                            float(self.config.upsilon),
                                            loci_mask=self.s.loci_mask)
            s = self._pad(self.s)
            etas_padded = _pad_etas(etas, s.num_cells, s.num_loci)
            t_init = np.pad(np.asarray(t_init_real),
                            (0, s.num_cells - self.s.num_cells),
                            constant_values=0.4)
            if cond_rho:
                # the conditioning branch the reference defined but never
                # exercised (model_s's rho0, pert_model.py:568-570); rho has
                # no prior term either way (Beta(1,1) logpdf = 0).  The
                # loader only divides by the max (reference:
                # pert_model.py:254-257), so a prior column with negative
                # values (repli-seq log-ratios) would leave rho outside
                # [0, 1] — clamp to the learned path's domain.
                fixed["rho"] = jnp.clip(
                    jnp.asarray(s.rt_prior, jnp.float32), 0.0, 1.0)
            eta_fields = self._eta_batch_fields(etas_padded)
            batch = PertBatch(
                reads=jnp.asarray(s.reads),
                libs=jnp.asarray(s.libs),
                gamma_feats=self._gamma_feats(s),
                mask=jnp.asarray(s.cell_mask.astype(np.float32)),
                loci_mask=_loci_mask_arr(s),
                **eta_fields,
            )
            spec = PertModelSpec(
                P=self.config.P, K=self.config.K, L=self.L,
                tau_mode="param", step1=False, cond_beta_means=True,
                cond_rho=cond_rho,
                fixed_lamb=True, sparse_etas="eta_idx" in eta_fields,
                cell_chunk=self.config.cell_chunk,
                enum_impl=self._enum_impl())
        out = self._fit(spec, batch, fixed, t_init,
                        iters["max_iter"], iters["min_iter"], "step2")
        self._step2_data = s
        if jax.process_count() > 1:
            # the rescue's slice/splice (and the no-rescue hint's tau
            # read) fetch host copies of the fitted params — a
            # non-addressable global array cannot be fetched on one
            # host.  Until the multi-host decode lands (ROADMAP 1),
            # rescue is a single-controlling-process surface, same gate
            # as the elastic rung.
            profiling.logger.info(
                "step 2: mirror rescue skipped on a %d-process run "
                "(host-side splice needs addressable params)",
                jax.process_count())
        elif self.config.mirror_rescue:
            # controller active: the rescue sub-fit runs only when the
            # QC signals say a candidate is SUSPECT (extreme-boundary
            # tau or high posterior entropy) instead of always-on; the
            # gate verdict lands as a control_decision event either way.
            # An inert controller (same conditions as the in-fit path)
            # leaves the rescue always-on and emits nothing.
            run_rescue = self._gate_rescue(out, batch) \
                if self._controller_active(iters["min_iter"],
                                           iters["max_iter"]) else True
            if run_rescue:
                with self.phases.phase("step2/rescue"):
                    out = self._mirror_rescue(out, batch)
        else:
            # reference-faithful path: no behaviour change, but surface
            # the symptom the opt-in rescue exists for
            cfg = self.config
            _, cand = self._mirror_candidates(out, batch)
            if cand.size:
                profiling.logger.info(
                    "step 2: %d cells fitted at boundary tau (outside "
                    "[%.2f, %.2f]) — if their profiles look fully "
                    "replicated this may be the tau mirror degeneracy; "
                    "consider mirror_rescue=True",
                    cand.size, cfg.mirror_tau_lo, cfg.mirror_tau_hi)
        return out

    def _mirror_candidates(self, out: StepOutput, batch: PertBatch):
        """(tau, candidate indices) — the boundary-tau cells the rescue
        would process; shared by the rescue and the no-rescue hint so
        the hint can never report a different cell set.  Reads tau from
        tau_raw alone (constrained() would also materialise log_pi/pi)."""
        cfg = self.config
        tau = np.asarray(to_unit_interval(out.fit.params["tau_raw"]))
        mask = np.asarray(batch.mask)
        cand = np.flatnonzero(((tau < cfg.mirror_tau_lo)
                               | (tau > cfg.mirror_tau_hi)) & (mask > 0.5))
        return tau, cand

    def _gate_rescue(self, out: StepOutput, batch: PertBatch) -> bool:
        """Controller gate for the mirror rescue (ISSUE 6 / ROADMAP 5):
        run the sub-fit only when a boundary-tau candidate is also
        SUSPECT — fitted tau within ``controller_rescue_extreme_tau`` of
        0/1 (true mirror victims land at ~0.005; genuinely early/late-S
        cells higher) or flagged high-entropy by the posterior-
        confidence QC signals (frac of low-confidence bins above
        ``qc_frac_thresh``).  Replaces the always-on heuristic: a cohort
        whose boundary cells are confident, non-extreme fits (the
        legitimately-early/late-S case the candidate cap exists for)
        skips the whole refit-and-reject cycle.  The entropy signal is
        consulted only when the extreme-tau test alone has not already
        gated the rescue IN, and only when ``qc`` is enabled —
        ``--no-qc`` leaves the extreme-tau test as the sole gate.

        Emits one ``control_decision`` event (action ``rescue`` /
        ``rescue_skip``) carrying the trigger signals; on a skip, the
        rescue bookkeeping (stats, QC candidate flags, the ``rescue``
        event) is still produced so downstream consumers see the same
        surface as a 0-accepted pass.
        """
        cfg = self.config
        tau, cand = self._mirror_candidates(out, batch)
        trigger: dict = {"candidates": int(cand.size)}
        thresholds = {
            "mirror_tau_lo": float(cfg.mirror_tau_lo),
            "mirror_tau_hi": float(cfg.mirror_tau_hi),
            "extreme_tau": float(cfg.controller_rescue_extreme_tau),
            "entropy_thresh": float(cfg.qc_entropy_thresh),
            "frac_thresh": float(cfg.qc_frac_thresh),
        }
        run = False
        if cand.size:
            extremity = np.minimum(tau[cand], 1.0 - tau[cand])
            extreme = extremity < cfg.controller_rescue_extreme_tau
            run = bool(extreme.any())
            trigger.update(
                extreme_tau_count=int(extreme.sum()),
                suspect_count=int(extreme.sum()),
                min_extremity=self._finite(extremity.min()))
            if not run and not cfg.qc:
                # --no-qc opts out of the whole posterior-confidence
                # surface, the gate's entropy signal included — the
                # gate then decides on the extreme-tau test alone
                # (also avoiding an entropy decode program the
                # packaging pass would never build to share)
                trigger["qc"] = "off"
            elif not run:
                # posterior-confidence signal, on device — consulted
                # only when the cheap extreme-tau test alone has not
                # already gated the rescue IN (an extreme candidate is
                # suspect regardless of entropy, so the decode sweep
                # would change nothing).  Full-cohort on purpose: the
                # slab program is shape-stable and shared with the
                # packaging decode, where a candidates-only sub-batch
                # would recompile per candidate count.  The aggregates
                # are NOT cached for packaging: packaging needs the
                # per-bin entropy PLANES (the model_cn_entropy column),
                # and keeping two (cells, loci) f32 planes alive in HBM
                # across the step-3 fit to save one gate sweep inverts
                # the footprint priorities — packaging recomputes them
                # inside the decode pass it runs anyway.
                with self.phases.phase("step2/rescue_gate"):
                    # cell_chunk default (auto-slab) so the compiled
                    # slab program is the SAME one packaging reuses
                    _, frac_low, mean_rep = jax.device_get(
                        cell_entropy_aggregates(
                            out.spec, out.fit.params, out.fixed, batch,
                            entropy_thresh=cfg.qc_entropy_thresh))
                high_ent = np.asarray(frac_low)[cand] > cfg.qc_frac_thresh
                run = bool(high_ent.any())
                trigger.update(
                    high_entropy_count=int(high_ent.sum()),
                    suspect_count=int(high_ent.sum()),
                    max_frac_low_conf=self._finite(
                        np.asarray(frac_low)[cand].max()),
                    mean_rep_entropy=self._finite(
                        float(np.mean(np.asarray(mean_rep)[cand]))))
        self.run_log.emit(
            "control_decision", step="step2",
            action="rescue" if run else "rescue_skip",
            iter=int(out.fit.num_iters),
            # schema types budget as integer; a fit built outside the
            # controlled driver falls back to the iterations it ran
            budget=int(out.fit.budget if out.fit.budget is not None
                       else out.fit.num_iters),
            trigger=trigger, thresholds=thresholds,
            detail=("mirror rescue gated IN: suspect boundary-tau "
                    "candidates present" if run else
                    "mirror rescue gated OUT: no suspect boundary-tau "
                    "candidates (no wasted refit-and-reject sub-fit)"))
        if not run:
            # same downstream surface as a 0-accepted rescue pass
            self.mirror_rescue_stats = {"candidates": int(cand.size),
                                        "accepted": 0}
            self._rescue_cells = {"candidates": cand.copy(),
                                  "accepted": np.zeros(0, cand.dtype)}
            self._emit_rescue_event()
            profiling.logger.info(
                "mirror rescue skipped by the controller: %d boundary-"
                "tau candidate(s), none extreme or high-entropy",
                cand.size)
        return run

    def _mirror_rescue(self, out: StepOutput, batch: PertBatch) -> StepOutput:
        """Post-step-2 mirror-basin rescue (``PertConfig.mirror_rescue``).

        The step-2 objective is mirror-degenerate at the S-phase extremes:
        a nearly-fully-replicated cell at read rate u is
        likelihood-equivalent to an unreplicated cell at rate ~2u, and
        the u prior's mean tracks the fitted tau (pert_model.py:597-600),
        so both basins are self-consistent for the reference's prior-free
        ``expose_tau`` param (pert_model.py:583) — whichever basin
        ``guess_times`` lands in wins, and its skew heuristic
        (pert_model.py:387-400) mis-reads near-uniform profiles.

        Cells whose fitted tau lies outside [mirror_tau_lo, mirror_tau_hi]
        are re-fit from the mirrored initialisation (tau' = 1 - tau; u is
        re-seeded by its own prior at tau', which is exactly the mirrored
        rate) with every global site (rho, a, beta_means, lambda)
        conditioned at the step-2 fit, and each cell keeps whichever
        parameterisation scores the higher per-cell log-joint
        (models.pert.per_cell_objective).  Per-cell selection makes the
        pass strictly objective-improving; a beyond-reference capability,
        default off.

        Step 2 only, by design: in step 3 the population is G1/2 cells,
        for which tau ~ 0 is the CORRECT fit — boundary tau is the norm
        there, not a degeneracy symptom, and a rescue pass would re-fit
        (and reject) most of the cohort for nothing.

        Checkpoint interplay: the step-2 checkpoint stores the
        PRE-rescue params (saved inside _fit), so a resume from a
        completed step-2 checkpoint re-runs the rescue — deterministic,
        and costs one sub-fit compile.
        """
        cfg = self.config
        tau, cand = self._mirror_candidates(out, batch)
        self.mirror_rescue_stats = {"candidates": int(cand.size),
                                    "accepted": 0}
        self._rescue_cells = {"candidates": cand.copy(),
                              "accepted": np.zeros(0, cand.dtype)}
        if cand.size == 0:
            self._emit_rescue_event()
            return out
        if cand.size > cfg.mirror_max_cells:
            # bound the sub-fit: most boundary-extreme first (mirrored
            # cells sit at tau ~ 0.005; genuinely early-S cells land
            # higher) — see PertConfig.mirror_max_cells
            extremity = np.minimum(tau[cand], 1.0 - tau[cand])
            cand = cand[np.argsort(extremity)[:cfg.mirror_max_cells]]
            profiling.logger.info(
                "mirror rescue: capping %d candidates to the %d most "
                "boundary-extreme (PertConfig.mirror_max_cells)",
                self.mirror_rescue_stats["candidates"],
                cfg.mirror_max_cells)
            self.mirror_rescue_stats["capped_to"] = int(cand.size)

        def _take(x):
            return None if x is None else jnp.asarray(np.asarray(x)[cand])

        sub_batch = PertBatch(
            reads=_take(batch.reads),
            libs=_take(batch.libs),
            gamma_feats=batch.gamma_feats,
            mask=jnp.ones((cand.size,), jnp.float32),
            loci_mask=batch.loci_mask,
            etas=_take(batch.etas),
            eta_idx=_take(batch.eta_idx),
            eta_w=_take(batch.eta_w),
        )
        # all global sites conditioned: the rescue fit moves ONLY the
        # candidates' per-cell sites, so splicing them back cannot shift
        # the other cells' objective
        spec = dataclasses.replace(out.spec, cond_rho=True, cond_a=True,
                                   cell_chunk=None)
        fixed = dict(out.fixed)
        fixed["rho"] = jnp.asarray(fixed["rho"]) if out.spec.cond_rho \
            else to_unit_interval(out.fit.params["rho_raw"])
        fixed["a"] = jnp.asarray(fixed["a"]) if out.spec.cond_a \
            else to_positive(out.fit.params["a_raw"])

        # np.array (copy): np.asarray of a jax array is a read-only view,
        # and the accepted cells are spliced into these buffers below.
        # The pi parameter's key depends on the encoding ('pi_logits'
        # categorical / 'pi_bin_logits' binary) but both are
        # (planes, cells, loci), so the slice/splice code is shared.
        pi_key = "pi_bin_logits" if out.spec.binary_pi else "pi_logits"
        params_np = {k: np.array(v) for k, v in out.fit.params.items()}
        orig_sub = {
            "tau_raw": jnp.asarray(params_np["tau_raw"][cand]),
            "u": jnp.asarray(params_np["u"][cand]),
            "betas": jnp.asarray(params_np["betas"][cand]),
            pi_key: jnp.asarray(params_np[pi_key][:, cand, :]),
            "beta_stds_raw": jnp.asarray(params_np["beta_stds_raw"]),
        }

        t_flip = np.clip(1.0 - tau[cand], 0.05, 0.95).astype(np.float32)
        params0 = init_params(spec, sub_batch, fixed, t_init=t_flip)
        # warm-seed the sites the flip does NOT mirror: beta_stds (the
        # betas-prior width the candidates are later SCORED under — a
        # cold logspace init would optimise them against a different
        # width than the acceptance comparison uses) and the incumbent
        # GC coefficients (basin-independent).  The seeds must be
        # genuinely FRESH buffers (np.array copy before device_put):
        # fit_map DONATES params0, and jnp.asarray of an already-put
        # numpy array returns the SAME zero-copy device buffer — donating
        # it would let the compiled fit recycle memory that orig_sub and
        # params_np (both read after the fit: acceptance scoring, splice)
        # still alias, silently corrupting the comparison.
        params0["beta_stds_raw"] = jnp.asarray(
            np.array(params_np["beta_stds_raw"]))
        params0["betas"] = jnp.asarray(np.array(params_np["betas"][cand]))

        fit = fit_map(_PertLossFn(spec=spec), params0, (fixed, sub_batch),
                      max_iter=cfg.mirror_max_iter,
                      min_iter=cfg.mirror_min_iter,
                      rel_tol=cfg.rel_tol, learning_rate=cfg.learning_rate,
                      b1=cfg.adam_b1, b2=cfg.adam_b2,
                      fused_adam=self._fused_adam,
                      moment_dtype=cfg.optimizer_state_dtype)

        # compare under the ORIGINAL beta_stds (a global pyro param the
        # sub-fit also moves; discarding its drift keeps the per-cell
        # ranking apples-to-apples and the spliced params consistent)
        rescued = dict(fit.params)
        rescued["beta_stds_raw"] = orig_sub["beta_stds_raw"]
        obj_orig = np.asarray(per_cell_objective(spec, orig_sub, fixed,
                                                 sub_batch))
        obj_new = np.asarray(per_cell_objective(spec, rescued, fixed,
                                                sub_batch))
        accept = obj_new > obj_orig
        self.mirror_rescue_stats["accepted"] = int(accept.sum())
        profiling.logger.info(
            "mirror rescue: %d boundary-tau candidates, %d accepted "
            "(per-cell log-joint improved)", cand.size, int(accept.sum()))
        tau_new = np.asarray(to_unit_interval(np.asarray(fit.params
                                                         ["tau_raw"])))
        deltas = (tau_new - tau[cand])[accept]
        self._emit_rescue_event(deltas)
        if not accept.any():
            return out

        keep = cand[accept]
        self._rescue_cells["accepted"] = keep.copy()
        res_np = {k: np.asarray(v) for k, v in rescued.items()}
        for key in ("tau_raw", "u", "betas"):
            params_np[key][keep] = res_np[key][accept]
        params_np[pi_key][:, keep, :] = res_np[pi_key][:, accept, :]
        # re-place on the production mesh: the splice worked on host
        # copies, and handing back single-device arrays would de-shard
        # every downstream decode/QC pass
        new_params = self._place_params(params_np)
        new_fit = dataclasses.replace(out.fit, params=new_params)
        return dataclasses.replace(out, fit=new_fit)

    def _emit_rescue_event(self, tau_deltas=None) -> None:
        """Telemetry ``rescue`` event from ``mirror_rescue_stats`` +
        per-accepted-cell tau deltas (capped at 64 entries — enough to
        see the mirror flips without bloating the log)."""
        stats = self.mirror_rescue_stats or {}
        deltas = (np.asarray(tau_deltas, np.float64)
                  if tau_deltas is not None else np.zeros(0))
        self.run_log.emit(
            "rescue", step="step2",
            candidates=int(stats.get("candidates", 0)),
            accepted=int(stats.get("accepted", 0)),
            capped_to=stats.get("capped_to"),
            tau_deltas=[self._finite(round(float(d), 4))
                        for d in deltas[:64]],
            tau_mean_abs_delta=(
                self._finite(round(float(np.mean(np.abs(deltas))), 4))
                if deltas.size else None))

    def run_step3(self, step1: StepOutput, step2: StepOutput) -> StepOutput:
        iters = self.config.resolved_iters()
        with self.phases.phase("step3/build"):
            c1 = constrained(step1.spec, step1.fit.params, step1.fixed)
            c2 = constrained(step2.spec, step2.fit.params, step2.fixed)
            fixed = {
                "beta_means": c1["beta_means"],
                "lamb": c1["lamb"],
                "rho": c2["rho"],                 # pert_model.py:844-851
                "a": c2["a"],
            }
            etas2_real = self.build_etas_step3()
            t_init2_real, _, _ = guess_times(jnp.asarray(self.g1.reads),
                                             jnp.asarray(etas2_real),
                                             float(self.config.upsilon),
                                             loci_mask=self.g1.loci_mask)
            g1 = self._pad(self.g1)
            etas2 = _pad_etas(etas2_real, g1.num_cells, g1.num_loci)
            t_init2 = np.pad(np.asarray(t_init2_real),
                             (0, g1.num_cells - self.g1.num_cells),
                             constant_values=0.4)
            eta_fields = self._eta_batch_fields(etas2)
            batch = PertBatch(
                reads=jnp.asarray(g1.reads),
                libs=jnp.asarray(g1.libs),
                gamma_feats=self._gamma_feats(g1),
                mask=jnp.asarray(g1.cell_mask.astype(np.float32)),
                loci_mask=_loci_mask_arr(g1),
                **eta_fields,
            )
            spec = PertModelSpec(
                P=self.config.P, K=self.config.K, L=self.L,
                tau_mode="param", step1=False, cond_beta_means=True,
                cond_rho=True, cond_a=True, fixed_lamb=True,
                sparse_etas="eta_idx" in eta_fields,
                cell_chunk=self.config.cell_chunk,
                enum_impl=self._enum_impl())
        out = self._fit(spec, batch, fixed, t_init2,
                        iters["max_iter_step3"], iters["min_iter_step3"],
                        "step3")
        self._step3_data = g1
        return out

    # -- per-cell model-health QC -----------------------------------------

    def build_cell_qc(self, out: StepOutput, data: PertData,
                      qc_stats: dict,
                      timer: Optional[profiling.PhaseTimer] = None,
                      step_name: str = "step2",
                      ) -> pd.DataFrame:
        """Per-cell QC table for a fitted step + ``cell_qc_summary`` event.

        ``qc_stats`` carries the posterior-entropy aggregates (and tau)
        the packaging pass already fetched (``package_step_output``'s
        ``qc_collect``), so the only new device work here is the
        posterior-predictive check.  Returns a DataFrame with one row
        per real cell: tau, entropy aggregates, PPC deviance/z-score,
        mirror-rescue status, boolean QC flags with reasons — the
        structured answer to "which cells should I not trust?" that the
        scatter plots the reference relies on cannot give at scale.
        """
        cfg = self.config
        timer = timer or self.phases
        spec, params, fixed, batch = (out.spec, out.fit.params, out.fixed,
                                      out.batch)
        n = int(np.sum(data.cell_mask)) if data.cell_mask is not None \
            else data.num_cells
        cell_ids = list(data.cell_ids)[:n]

        ppc_dropped = False
        with timer.phase("qc/ppc"):
            key = jax.random.PRNGKey(cfg.seed)
            # the MAP planes the packaging decode already produced ride
            # along in qc_stats, so the PPC never re-enumerates the
            # joint tensor (its replicate draws are the only new device
            # work); the h2d of two int planes is noise next to that
            maps = (qc_stats["cn_map"], qc_stats["rep_map"]) \
                if "cn_map" in qc_stats else None
            try:
                faults_mod.point("qc/ppc")
                ppc_t0 = time.perf_counter()
                ppc_dev, ppc_z = jax.device_get(ppc_discrepancy(
                    spec, params, fixed, batch, key,
                    num_replicates=cfg.qc_ppc_replicates, maps=maps))
                self.meter.book_exec(
                    kind="ppc", seconds=time.perf_counter() - ppc_t0,
                    ctx={"step": step_name,
                         **self._meter_attrs(step_name, batch)})
                ppc_dev = np.asarray(ppc_dev)[:n]
                ppc_z = np.asarray(ppc_z)[:n]
            except Exception as exc:
                if faults_mod.classify_exception(exc) != "oom":
                    raise
                # degradation ladder, QC rung: the PPC is an optional
                # health surface — drop it rather than kill a run whose
                # inference results are already computed and durable
                ppc_dropped = True
                ppc_dev = np.full(n, np.nan, np.float64)
                ppc_z = np.full(n, np.nan, np.float64)
                self.run_log.emit(
                    "degrade", step=step_name, action="drop_ppc",
                    error_class="oom",
                    detail=("posterior-predictive check OOMed — PPC "
                            "columns are NaN and the ppc_outlier flag "
                            "is disabled for this run"),
                    error=f"{type(exc).__name__}: {str(exc)[:300]}")
                profiling.logger.warning(
                    "cell QC: PPC dropped after OOM (%s)", exc)

        with timer.phase("qc/package"):
            tau = np.asarray(qc_stats["tau"])[:n]
            mean_ent = np.asarray(qc_stats["mean_cn_entropy"])[:n]
            max_ent = np.asarray(qc_stats["max_cn_entropy"])[:n]
            frac_low = np.asarray(qc_stats["frac_low_conf"])[:n]
            mean_rep = np.asarray(qc_stats["mean_rep_entropy"])[:n]

            rescue_cand = np.zeros(n, bool)
            rescue_acc = np.zeros(n, bool)
            if self._rescue_cells is not None:
                c = self._rescue_cells["candidates"]
                a = self._rescue_cells["accepted"]
                rescue_cand[c[c < n]] = True
                rescue_acc[a[a < n]] = True

            finite = np.isfinite(tau) & np.isfinite(mean_ent)
            if not ppc_dropped:
                # a degraded (dropped) PPC leaves NaN columns that must
                # not flag every cell non_finite — the drop is audited,
                # not punished
                finite &= np.isfinite(ppc_z)
            # NaN comparisons are False, so a poisoned cell lands only in
            # non_finite — the one flag that subsumes the others
            flag_arrays = {
                "high_entropy": frac_low > cfg.qc_frac_thresh,
                "ppc_outlier": ppc_z > cfg.qc_ppc_z,
                "boundary_tau": ((tau < cfg.mirror_tau_lo)
                                 | (tau > cfg.mirror_tau_hi)),
                "non_finite": ~finite,
            }
            # flag strings assembled per FLAG column (4 vectorised
            # passes), not per cell — a million-cell table must not pay
            # millions of interpreter iterations here
            flags = np.full(n, "", object)
            for name, arr in flag_arrays.items():
                sep = np.where(flags == "", "", ",")
                flags = np.where(arr, flags + sep + name, flags)
            flagged = flags != ""

            df = pd.DataFrame({
                "cell_id": cell_ids,
                "model_tau": tau,
                "mean_cn_entropy": mean_ent,
                "max_cn_entropy": max_ent,
                "frac_low_conf": frac_low,
                "mean_rep_entropy": mean_rep,
                "ppc_deviance": ppc_dev,
                "ppc_z": ppc_z,
                "rescue_candidate": rescue_cand,
                "rescue_accepted": rescue_acc,
                # 'qc_flags', not 'flags': pandas reserves .flags as a
                # DataFrame/Series property, which would shadow
                # attribute access to the column
                "qc_flags": flags,
                "qc_pass": ~flagged,
            })

            # flagged-cell detail capped at 64 entries (like rescue's
            # tau_deltas), most-suspect first: PPC outliers by z, then
            # the rest by low-confidence fraction
            order = np.argsort(-(np.nan_to_num(ppc_z, nan=np.inf,
                                               posinf=np.inf)
                                 + np.nan_to_num(frac_low, nan=1.0)))
            worst = order[flagged[order]][:64]
            self.run_log.emit(
                "cell_qc_summary", step=step_name,
                num_cells=int(n), num_flagged=int(flagged.sum()),
                flag_counts={k: int(v.sum())
                             for k, v in flag_arrays.items() if v.any()},
                thresholds={
                    "entropy_thresh": float(cfg.qc_entropy_thresh),
                    "frac_thresh": float(cfg.qc_frac_thresh),
                    "ppc_z": float(cfg.qc_ppc_z),
                    "ppc_replicates": int(cfg.qc_ppc_replicates),
                },
                entropy_hist=[int(v) for v in np.histogram(
                    mean_ent[np.isfinite(mean_ent)], bins=10,
                    range=(0.0, 1.0))[0]],
                mean_cn_entropy_mean=self._finite(np.nanmean(mean_ent))
                if n else None,
                ppc_z_max=self._finite(np.nanmax(ppc_z))
                if n and np.isfinite(ppc_z).any() else None,
                flagged_cells=[{
                    "cell_id": str(cell_ids[i]),
                    "reasons": flags[i].split(","),
                    "tau": self._finite(tau[i]),
                    "frac_low_conf": self._finite(frac_low[i]),
                    "ppc_z": self._finite(ppc_z[i]),
                } for i in worst])
            profiling.logger.info(
                "cell QC: %d/%d cells flagged (%s)", int(flagged.sum()), n,
                ", ".join(f"{k}={int(v.sum())}"
                          for k, v in flag_arrays.items() if v.any())
                or "all clean")
        return df

    # -- full pipeline ----------------------------------------------------

    def run(self):
        """Run steps 1-3; returns (step1, step2, step3-or-None).

        A directly-driven runner (no api facade) opens its own telemetry
        session here — ``RunLog.session`` is re-entrant, so when the
        facade already owns the open log this wrapper is a pass-through
        and the facade's ``run_end`` (which also covers decode/packaging)
        is the one that closes the file.
        """
        try:
            with self.run_log.session(config=self.config,
                                      timer=self.phases):
                step1 = self.run_step1()
                # timed separately from step2/build: at genome scale the
                # CN prior (g1_composite / pearson_matrix over a
                # (cells, loci, P) tensor) is its own multi-second stage
                # (step 3's twin is timed inside step3/build because it
                # happens there)
                with self.phases.phase("step2/prior"):
                    etas = self.build_etas()
                step2 = self.run_step2(step1, etas)
                step3 = self.run_step3(step1, step2) \
                    if self.config.run_step3 else None
            # telemetry-disabled runs get no run_end (and so no final
            # snapshot event) — the textfile export must still land
            self.metrics.write_textfile()
            if self._manifest is not None:
                # durable cost record: the fleet index and pert_meter
                # read device-seconds/goodput from the manifest when a
                # run has no telemetry stream
                self._manifest.doc["meter"] = self.meter.summary()
                self._manifest.save()
        except Exception as exc:
            # terminal heartbeat on ERROR only: a BaseException
            # (SimulatedPreemption, KeyboardInterrupt, SIGKILL-adjacent
            # teardown) must NOT write a terminal state — the stale
            # heartbeat it leaves behind is exactly what pert_watch's
            # freshness ladder flags as presumed-lost
            if self._heartbeat is not None:
                self._heartbeat.close("error", error=exc)
                heartbeat_mod.uninstall(self._heartbeat)
            raise
        finally:
            # a directly-driven runner owns its registry's lifetime; a
            # facade-owned registry outlives the runner (packaging and
            # the facade's own run_end still feed it)
            if self._owns_metrics:
                metrics_mod.uninstall(self.metrics)
        if self._heartbeat is not None:
            self._heartbeat.close("done")
            heartbeat_mod.uninstall(self._heartbeat)
        return step1, step2, step3


# ---------------------------------------------------------------------------
# output packaging (pandas parity)
# ---------------------------------------------------------------------------

def _decode_with_degradation(spec, params, fixed, batch, data,
                             hmm_self_prob, want_entropy: bool,
                             phase_prefix: str):
    """The packaging decode under the OOM degradation ladder.

    Returns ``(decoded, ent_planes, want_entropy)``.  On a classified
    RESOURCE_EXHAUSTED the ladder walks: halve the decode slab (three
    times — each halving halves the live joint tensor), then drop the
    optional QC entropy surfaces (two fewer output planes per slab and
    no QC pass downstream), then re-raise — at which point every step's
    results are already in durable checkpoints, so the abort is
    resumable.  Every rung is audited as a ``degrade`` RunLog event.
    Deterministic errors propagate from the first attempt untouched.
    """
    from scdna_replication_tools_tpu.models import pert as pert_mod
    from scdna_replication_tools_tpu.obs import runlog as _runlog

    num_loci = batch.reads.shape[1]
    auto_chunk = max(1, pert_mod._DECODE_SLAB_BYTES
                     // max(num_loci * spec.P * 2 * 4, 1))

    def _decode(chunk, entropy):
        faults_mod.point(f"{phase_prefix}/decode")
        if hmm_self_prob is not None:
            from scdna_replication_tools_tpu.models.pert import (
                decode_discrete_hmm,
            )
            chroms = data.loci.get_level_values(0)
            restart = jnp.asarray(
                np.r_[1.0, (chroms[1:] != chroms[:-1]).astype(np.float32)])
            out = decode_discrete_hmm(
                spec, params, fixed, batch, restart, hmm_self_prob,
                want_entropy=entropy)
        else:
            out = decode_discrete(spec, params, fixed, batch,
                                  want_entropy=entropy,
                                  cell_chunk=chunk)
        if entropy:
            return out[:3], out[3:]
        return out, None

    # rung 0 is the normal path (auto slab); rungs 1-3 halve it.  The
    # HMM decode has no slab knob (its Viterbi pass is whole-genome per
    # cell), so its ladder goes straight from the normal attempt to
    # dropping the QC surfaces — re-running an identical decode three
    # times would only triple the OOM wait
    if hmm_self_prob is not None:
        ladder = [None]
    else:
        ladder = [None] + [max(1, auto_chunk >> k) for k in (1, 2, 3)]
    last_exc = None
    for rung, chunk in enumerate(ladder):
        try:
            decoded, ent_planes = _decode(chunk, want_entropy)
            return decoded, ent_planes, want_entropy
        except Exception as exc:
            if faults_mod.classify_exception(exc) != "oom":
                raise
            last_exc = exc
            if rung == len(ladder) - 1:
                break
            _runlog.current().emit(
                "degrade", step=phase_prefix, action="halve_decode_slab",
                detail=(f"decode OOM at slab={chunk or auto_chunk} "
                        f"cells — retrying at {max(1, auto_chunk >> (rung + 1))}"),
                error=f"{type(exc).__name__}: {str(exc)[:300]}")
    if want_entropy:
        # next rung: drop the optional QC surfaces and retry once at
        # the smallest slab
        _runlog.current().emit(
            "degrade", step=phase_prefix, action="drop_qc_surfaces",
            detail=("decode still OOM at the smallest slab — dropping "
                    "the posterior-entropy planes (model_cn_entropy "
                    "column and the per-cell QC table) for this run"),
            error=f"{type(last_exc).__name__}: {str(last_exc)[:300]}")
        try:
            decoded, ent_planes = _decode(ladder[-1], False)
            return decoded, ent_planes, False
        except Exception as exc:
            if faults_mod.classify_exception(exc) != "oom":
                raise
            last_exc = exc
    _runlog.current().emit(
        "degrade", step=phase_prefix, action="abort_resumable",
        error_class="oom",
        detail=("decode OOM after the full degradation ladder; step "
                "checkpoints are durable, so the run is resumable"),
        error=f"{type(last_exc).__name__}: {str(last_exc)[:300]}")
    raise last_exc


def package_step_output(
    cn_long: pd.DataFrame,
    data: PertData,
    step: StepOutput,
    lamb: float,
    losses_g: np.ndarray,
    losses_s: np.ndarray,
    cols: ColumnConfig = ColumnConfig(),
    hmm_self_prob: Optional[float] = None,
    mirror_rescue_stats: Optional[dict] = None,
    timer: Optional[profiling.PhaseTimer] = None,
    phase_prefix: str = "s",
    qc_collect: Optional[dict] = None,
    qc_entropy_thresh: float = 0.5,
) -> Tuple[pd.DataFrame, pd.DataFrame]:
    """Decode discretes + attach fitted values to the long-form contract.

    Mirrors ``package_s_output`` (reference: pert_model.py:466-538): adds
    model_cn_state, model_rep_state, model_tau, model_u, model_rho columns
    to ``cn_long`` and builds the supplementary param/loss table
    (model_lambda, model_a, loss_g, loss_s).  The reference melts each
    dense output into a long frame and inner-merges; here the decode
    planes stay on device until ONE bulk fetch, and the long columns are
    attached by array-native gathers (``data.loader.attach_dense_columns``)
    with identical inner-join semantics.

    ``hmm_self_prob`` switches the per-bin argmax decode for the
    genome-smoothed Viterbi CN decode (models/hmm.py) with that
    self-transition probability.  ``timer`` (optional) records the
    decode/fetch/package phases under ``{phase_prefix}/...``.

    ``qc_collect`` (a dict, mutated in place) opts in the
    posterior-confidence pass: the decode slabs additionally return the
    per-bin normalized CN/rep posterior entropies
    (``models.pert.entropy_from_joint``), per-cell aggregates (mean/max
    entropy, fraction of bins above ``qc_entropy_thresh``) are reduced
    ON DEVICE, everything rides the same one-bulk-fetch, the long
    output gains a per-bin ``model_cn_entropy`` column, and
    ``qc_collect`` receives the per-cell aggregate arrays (+ tau) that
    ``PertInference.build_cell_qc`` turns into the QC table.
    """
    spec, params, fixed, batch = step.spec, step.fit.params, step.fixed, step.batch
    timer = timer or profiling.PhaseTimer()
    want_entropy = qc_collect is not None
    with timer.phase(f"{phase_prefix}/decode"):
        from scdna_replication_tools_tpu.obs import meter as _meter
        from scdna_replication_tools_tpu.obs import runlog as _runlog

        decode_t0 = time.perf_counter()
        decoded, ent_planes, want_entropy = _decode_with_degradation(
            spec, params, fixed, batch, data, hmm_self_prob,
            want_entropy, phase_prefix)
        ledger = _meter.ledger_of(_runlog.current())
        if ledger is not None:
            # the decode/PPC slabs run at the fit's padded shape too —
            # book their device time with the same bucket attribution
            # (no iteration work units: goodput counts fit progress)
            padded = (int(batch.reads.shape[0]),
                      int(batch.reads.shape[1]))
            real = (min(int(data.num_cells), padded[0]),
                    min(int(data.num_loci), padded[1]))
            ledger.book_exec(
                kind="decode",
                seconds=time.perf_counter() - decode_t0,
                ctx={"step": f"{phase_prefix}/decode",
                     "bucket": f"c{padded[0]}xl{padded[1]}",
                     "pad_frac": round(max(
                         1.0 - (real[0] * real[1])
                         / max(padded[0] * padded[1], 1), 0.0), 6)})
        if qc_collect is not None and not want_entropy:
            # the degradation ladder dropped the optional QC surfaces;
            # tell the caller so it skips the QC table instead of
            # KeyError-ing on the missing aggregates
            qc_collect["degraded"] = True
            qc_collect = None
        c = constrained(spec, params, fixed)

    n = int(np.sum(data.cell_mask)) if data.cell_mask is not None \
        else data.num_cells
    cell_ids = list(data.cell_ids)[:n]

    qc_device = None
    if want_entropy:
        with timer.phase(f"{phase_prefix}/qc_aggregate"):
            # per-cell confidence aggregates reduced on device — the
            # fetch moves (cells,) vectors, not extra (cells, loci)
            # planes beyond the one entropy map the output carries.
            # Same reduction the rescue gate consumes standalone.
            cn_ent, rep_ent = ent_planes
            qc_device = entropy_aggregates_from_planes(
                cn_ent, rep_ent, batch.effective_loci_mask(),
                qc_entropy_thresh, want_max=True)

    with timer.phase(f"{phase_prefix}/fetch"):
        # one bulk device->host transfer for every packaged plane; only
        # the CN entropy map comes down — the rep-entropy plane's sole
        # consumer is its on-device per-cell aggregate (qc_device)
        ((cn_map, rep_map, p_rep), tau, u, rho, a_c, cn_ent_host,
         qc_host) = jax.device_get(
            (decoded, c["tau"], c["u"], c["rho"], c["a"],
             ent_planes[0] if want_entropy else None, qc_device))

    with timer.phase(f"{phase_prefix}/package"):
        cn_long = cn_long.copy()
        cn_long[cols.chr_col] = cn_long[cols.chr_col].astype(str)
        per_bin = {"model_cn_state": cn_map[:n],
                   "model_rep_state": rep_map[:n],
                   "model_p_rep": p_rep[:n]}
        if want_entropy:
            per_bin["model_cn_entropy"] = cn_ent_host[:n]
            qc_collect.update({k: np.asarray(v) for k, v in qc_host.items()})
            qc_collect["tau"] = np.asarray(tau)
            # the full-shape MAP planes, for the PPC pass downstream
            # (build_cell_qc) — already fetched, no re-decode needed
            qc_collect["cn_map"] = np.asarray(cn_map)
            qc_collect["rep_map"] = np.asarray(rep_map)
        out = attach_dense_columns(
            cn_long, cell_ids, data.loci, cols,
            per_bin=per_bin,
            per_cell={"model_tau": tau[:n], "model_u": u[:n]},
            per_locus={"model_rho": rho},
        )

    supp = [
        pd.DataFrame({"param": ["model_lambda"], "level": ["all"],
                      "value": [float(lamb)]}),
        pd.DataFrame({"param": ["model_a"], "level": ["all"],
                      "value": [float(np.asarray(a_c).reshape(-1)[0])]}),
        pd.DataFrame({"param": ["loss_g"] * len(losses_g),
                      "level": np.arange(len(losses_g)),
                      "value": np.asarray(losses_g, np.float64)}),
        pd.DataFrame({"param": ["loss_s"] * len(losses_s),
                      "level": np.arange(len(losses_s)),
                      "value": np.asarray(losses_s, np.float64)}),
    ]
    if mirror_rescue_stats is not None:
        # audit trail in the user-facing output, not just logs: how many
        # boundary-tau cells the rescue examined and how many it kept
        supp.append(pd.DataFrame({
            "param": [f"mirror_rescue_{k}" for k in mirror_rescue_stats],
            "level": ["all"] * len(mirror_rescue_stats),
            "value": [float(v) for v in mirror_rescue_stats.values()],
        }))
    return out, pd.concat(supp, ignore_index=True)
