"""Checkpointing of fitted parameter pytrees + optimizer state.

The reference has no checkpoint/resume at all — learned state crosses the
three SVI steps only in-memory (reference: pert_model.py:772-787, 836-851).
The TPU runner persists, after each step (and periodically DURING a
controller-chunked fit — see ``PertConfig.checkpoint_every``), the fitted
(unconstrained) parameter dict, the Adam optimiser state, the loss history
and a small meta record (iterations run, converged flag) as a flat
``.npz``.

Durability contract (this is restart-critical state, so every write is
paranoid):

* **atomic commit** — the npz is written to a temp file in the same
  directory and ``os.replace``d into place, so a preemption mid-write
  can never leave a torn file under the canonical name;
* **integrity footer** — 48 trailing bytes (magic + payload length +
  sha256 of the payload) appended after the zip payload (the zip EOCD
  scan tolerates trailing data).  ``load_step`` verifies length and
  digest before unpickling anything, so truncation/corruption surfaces
  as a typed :class:`CheckpointCorrupt` naming the file instead of an
  opaque zipfile/unpickling error;
* **bounded retention** — each save rotates the previous good file to
  ``pert_<step>.prev.npz`` first; a corrupt newest checkpoint falls
  back to that predecessor (one extra fit segment re-run beats a dead
  resume).

Resume semantics (see ``runner.PertInference._fit``):

* a COMPLETED step (converged, NaN-aborted, or out of budget) is restored
  as-is and not refit;
* a PARTIAL step (stopped early, killed mid-budget, or a periodic
  in-fit checkpoint) resumes optimisation from the saved iteration with
  Adam moments — and, for controller-chunked fits, the controller's
  own state (best-loss checkpoint, budget ledger, diagnostics ring) —
  intact, so the resumed trajectory is bit-identical to an
  uninterrupted run (the compiled loop is deterministic given params +
  opt state + loss history).
"""

from __future__ import annotations

import hashlib
import os
import struct
from typing import Optional

import numpy as np

from scdna_replication_tools_tpu.infer.manifest import atomic_write_bytes
from scdna_replication_tools_tpu.utils.profiling import logger

# Format history (the pi_logits layout contract lives in layout.py):
#   v3  integrity footer appended; optional ctrl.* / best.* extras
#       (controller resume state) — fully readable by the v2 loader
#       layout-wise, so no layout bump
#   v2  pi_logits stored STATE-MAJOR (P, cells, loci)
#   v1  (never stamped) pi_logits cells-major — round <= 3 checkpoints;
#       round-4 snapshots confusingly wrote state-major WITHOUT a stamp,
#       so an unstamped 3-D pi_logits is AMBIGUOUS and load_step refuses
#       it rather than guessing (a wrong guess trains on a transposed
#       tensor); delete the stale .npz and refit.
CHECKPOINT_FORMAT_VERSION = 3

# integrity footer: magic(8) + little-endian payload length(8) + sha256(32)
_FOOTER_MAGIC = b"PERTCK01"
_FOOTER_LEN = len(_FOOTER_MAGIC) + 8 + 32


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file failed integrity verification or parsing.

    Carries the offending ``path`` so operators (and the RunLog event
    the runner emits) can name the artifact to delete or investigate.
    """

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint {path}: {reason}")
        self.path = path
        self.reason = reason


def _step_path(checkpoint_dir: str, step: str) -> str:
    return os.path.join(checkpoint_dir, f"pert_{step}.npz")


def _prev_path(path: str) -> str:
    root, ext = os.path.splitext(path)
    return f"{root}.prev{ext}"


def save_step(checkpoint_dir: str, step: str, params: dict,
              losses: np.ndarray, extra: Optional[dict] = None,
              opt_state=None, num_iters: Optional[int] = None,
              converged: bool = True, nan_abort: bool = False) -> str:
    os.makedirs(checkpoint_dir, exist_ok=True)
    path = _step_path(checkpoint_dir, step)
    flat = {f"param.{k}": np.asarray(v) for k, v in params.items()}
    flat["losses"] = np.asarray(losses)
    # v3 = state-major pi_logits (see layout.py) + integrity footer
    flat["meta.format_version"] = np.asarray(CHECKPOINT_FORMAT_VERSION)
    flat["meta.num_iters"] = np.asarray(
        num_iters if num_iters is not None else len(losses))
    flat["meta.converged"] = np.asarray(bool(converged))
    flat["meta.nan_abort"] = np.asarray(bool(nan_abort))
    if opt_state is not None:
        # flatten generically; the reader rebuilds the treedef from a
        # fresh optax init over the restored params (same structure).
        # Dtype-aware (optimizer_state_dtype='bfloat16'): numpy's npz
        # container cannot round-trip ml_dtypes.bfloat16 (it reloads as
        # a void dtype), so bfloat16 leaves are stored as uint16 BIT
        # VIEWS with a per-leaf ``optdtype.N`` sidecar that the loader
        # uses to view them back — bit-exact both ways.  The summary
        # ``meta.opt_moment_dtype`` is what the runner's resume gate
        # compares against the configured dtype.
        import jax
        leaves = jax.tree_util.tree_leaves(opt_state)
        moment_dtype = "float32"
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            if arr.dtype.name == "bfloat16":
                flat[f"opt.{i}"] = arr.view(np.uint16)
                flat[f"optdtype.{i}"] = np.asarray("bfloat16")
                moment_dtype = "bfloat16"
            else:
                flat[f"opt.{i}"] = arr
        flat["meta.opt_moment_dtype"] = np.asarray(moment_dtype)
    for k, v in (extra or {}).items():
        flat[f"extra.{k}"] = np.asarray(v)

    # serialize to memory so the integrity footer hashes exactly the
    # bytes that land on disk, then commit atomically with retention:
    # rotate the previous good file aside BEFORE replacing it, so a
    # corrupt new file (partial write + crash, or the injected
    # corruption fault) always leaves a fallback
    import io

    buf = io.BytesIO()
    np.savez(buf, **flat)
    payload = buf.getvalue()
    footer = (_FOOTER_MAGIC + struct.pack("<Q", len(payload))
              + hashlib.sha256(payload).digest())
    if os.path.exists(path):
        try:
            os.replace(path, _prev_path(path))
        except OSError as exc:
            logger.warning("checkpoint retention: could not rotate %s "
                           "(%s)", path, exc)
    atomic_write_bytes(path, payload + footer)

    from scdna_replication_tools_tpu.utils import faults as _faults

    if _faults.point(f"{step}/save") == "corrupt":
        _faults.corrupt_file(path)
    return path


def quarantine_stale(checkpoint_dir: str) -> int:
    """Rename every ``pert_*.npz`` (and retained ``.prev``) aside to
    ``*.stale`` — called when the resume ledger is voided (fingerprint
    mismatch under ``resume='auto'``, or ``resume='off'``).  Resetting
    the ledger alone is not enough: the files would survive, and once
    the NEW identity lands in the manifest a later run would
    fingerprint-verify and silently restore params fitted to OTHER
    data.  Renaming (not deleting) keeps the forensic artifact while
    guaranteeing no loader ever reads it; returns the count moved."""
    moved = 0
    try:
        import glob

        for path in glob.glob(os.path.join(checkpoint_dir, "pert_*.npz")):
            try:
                os.replace(path, path + ".stale")
                moved += 1
            except OSError as exc:
                logger.warning("could not quarantine stale checkpoint "
                               "%s (%s)", path, exc)
    except OSError as exc:
        logger.warning("stale-checkpoint quarantine failed in %s (%s)",
                       checkpoint_dir, exc)
    if moved:
        logger.warning("quarantined %d stale checkpoint file(s) in %s "
                       "(renamed to *.stale)", moved, checkpoint_dir)
    return moved


def _verify_and_read(path: str):
    """Verify the integrity footer and parse the npz; raises
    :class:`CheckpointCorrupt` on any failure.  Pre-v3 files (no
    footer) parse unverified — refusing every historical checkpoint
    would turn an integrity upgrade into a fleet-wide refit."""
    try:
        with open(path, "rb") as fh:
            blob = fh.read()
    except OSError as exc:
        raise CheckpointCorrupt(path, f"unreadable ({exc})")
    if len(blob) >= _FOOTER_LEN \
            and blob[-_FOOTER_LEN:-_FOOTER_LEN + len(_FOOTER_MAGIC)] \
            == _FOOTER_MAGIC:
        footer = blob[-_FOOTER_LEN:]
        (length,) = struct.unpack(
            "<Q", footer[len(_FOOTER_MAGIC):len(_FOOTER_MAGIC) + 8])
        payload = blob[:-_FOOTER_LEN]
        if len(payload) != length:
            raise CheckpointCorrupt(
                path, f"truncated: footer records {length} payload "
                      f"bytes, file has {len(payload)}")
        if hashlib.sha256(payload).digest() != footer[-32:]:
            raise CheckpointCorrupt(path, "sha256 mismatch (bit rot or "
                                          "partial overwrite)")
    else:
        payload = blob   # pre-v3: no footer to verify
    import io

    try:
        return np.load(io.BytesIO(payload))
    except Exception as exc:  # zipfile/ValueError/pickle zoo — the
        # typed error IS this except block's purpose
        raise CheckpointCorrupt(
            path, f"unparseable npz ({type(exc).__name__}: {exc})")


def load_step(checkpoint_dir: str, step: str):
    """Returns (params, losses, extra), or None if no checkpoint exists.

    ``extra`` carries the ``meta.*`` record, any ``opt.N`` optimiser
    leaves (rebuild the pytree with :func:`restore_opt_state`) and any
    ``ctrl.*``/``best.*`` controller resume state.  A corrupt newest
    file falls back to the retained ``.prev`` checkpoint (with a
    warning); when no fallback survives verification either, raises
    :class:`CheckpointCorrupt` for the NEWEST file — the caller decides
    whether a fresh refit is acceptable.
    """
    path = _step_path(checkpoint_dir, step)
    if not os.path.exists(path):
        prev = _prev_path(path)
        if os.path.exists(prev):
            # rotate-then-write crash window: the canonical file was
            # rotated aside but the replacement never committed — the
            # retained predecessor is the newest durable state
            logger.warning(
                "checkpoint %s is missing but its retained predecessor "
                "exists (crash between rotation and commit?) — "
                "restoring %s", path, prev)
            data = _verify_and_read(prev)
            return _unpack(prev, data)
        return None
    try:
        data = _verify_and_read(path)
    except CheckpointCorrupt as exc:
        prev = _prev_path(path)
        if os.path.exists(prev):
            logger.warning(
                "%s — falling back to the retained previous checkpoint "
                "%s", exc, prev)
            try:
                data = _verify_and_read(prev)
            except CheckpointCorrupt:
                raise exc from None   # report the NEWEST file
        else:
            raise
    return _unpack(path, data)


def _unpack(path: str, data):
    """(params, losses, extra) from a verified npz archive."""
    params = {k[len("param."):]: data[k] for k in data.files
              if k.startswith("param.")}
    extra = {k[len("extra."):]: data[k] for k in data.files
             if k.startswith("extra.")}
    for k in data.files:
        if k.startswith("meta.") or k.startswith("opt."):
            extra[k] = data[k]
    # bfloat16 moments round-trip: uint16 bit views back to bfloat16
    # (see save_step) — readers downstream never see the storage trick
    for k in data.files:
        if k.startswith("optdtype."):
            leaf_key = "opt." + k[len("optdtype."):]
            if str(data[k]) == "bfloat16" and leaf_key in extra:
                import ml_dtypes

                extra[leaf_key] = extra[leaf_key].view(ml_dtypes.bfloat16)
    version = int(extra.get("meta.format_version", 1))
    if version < 2 and "pi_logits" in params and params["pi_logits"].ndim == 3:
        raise ValueError(
            f"{path} has no format_version stamp: its pi_logits layout is "
            "ambiguous (pre-v2 checkpoints exist in BOTH cells-major and "
            "state-major orientations) and restoring a transposed tensor "
            "would silently corrupt training — delete the stale "
            "checkpoint file and refit")
    return params, data["losses"], extra


def restore_opt_state(extra: dict, params: dict, learning_rate: float,
                      b1: float, b2: float):
    """Rebuild the optax state pytree from flat ``opt.N`` leaves, or None
    when the checkpoint predates optimiser-state persistence."""
    opt_keys = sorted((k for k in extra if k.startswith("opt.")),
                      key=lambda k: int(k.split(".", 1)[1]))
    if not opt_keys:
        return None
    import jax
    from scdna_replication_tools_tpu.infer.svi import make_opt_state

    template = make_opt_state(params, learning_rate, b1, b2)
    treedef = jax.tree_util.tree_structure(template)
    leaves = [extra[k] for k in opt_keys]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def restore_controller_state(extra: dict) -> Optional[dict]:
    """Rebuild the chunked-fit controller's resume state from a
    checkpoint's ``ctrl.*`` / ``best.*`` extras, or None when the
    checkpoint predates in-fit checkpointing (``infer/svi.py``'s
    ``resume_state`` contract — the fields that make a mid-fit resume
    reproduce the uninterrupted decision trail bit-exactly)."""
    if "ctrl.format" not in extra:
        return None
    state = {
        "reseeds": int(extra["ctrl.reseeds"]),
        "extra_granted": int(extra["ctrl.extra_granted"]),
        "nan_retries": int(extra["ctrl.nan_retries"]),
        "lr": float(extra["ctrl.lr"]),
        "budget": int(extra["ctrl.budget"]),
        "stagnation_anchor": int(extra["ctrl.stagnation_anchor"]),
        "prev_verdict": str(extra["ctrl.prev_verdict"]) or None,
        "best_loss": float(extra["ctrl.best_loss"]),
        "best_it": int(extra["ctrl.best_it"]),
        "diag": np.asarray(extra["ctrl.diag"]),
        "diag_i0": int(extra["ctrl.diag_i0"]),
    }
    best = {k[len("best."):]: np.asarray(v) for k, v in extra.items()
            if k.startswith("best.")}
    if best:
        state["best_params"] = best
    else:
        # an inexact (mid-chunk emergency) save may have lost the
        # best-loss params; a finite best_loss without its params would
        # make the early-stop restore hand back the WRONG state — drop
        # the record and let the resumed segment re-establish its best
        state["best_loss"] = float("inf")
        state["best_it"] = 0
    return state


def pack_controller_state(state: dict) -> dict:
    """Flatten an ``infer/svi.py`` controller state dict into the
    ``extra`` keys :func:`restore_controller_state` reads back."""
    out = {
        "ctrl.format": 1,
        "ctrl.reseeds": int(state["reseeds"]),
        "ctrl.extra_granted": int(state["extra_granted"]),
        "ctrl.nan_retries": int(state["nan_retries"]),
        "ctrl.lr": float(state["lr"]),
        "ctrl.budget": int(state["budget"]),
        "ctrl.stagnation_anchor": int(state["stagnation_anchor"]),
        "ctrl.prev_verdict": state.get("prev_verdict") or "",
        "ctrl.best_loss": float(state["best_loss"]),
        "ctrl.best_it": int(state["best_it"]),
        "ctrl.diag": np.asarray(state["diag"]),
        "ctrl.diag_i0": int(state["diag_i0"]),
    }
    for k, v in (state.get("best_params") or {}).items():
        out[f"best.{k}"] = np.asarray(v)
    return out
