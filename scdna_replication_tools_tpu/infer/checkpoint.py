"""Step-boundary checkpointing of fitted parameter pytrees.

The reference has no checkpoint/resume at all — learned state crosses the
three SVI steps only in-memory (reference: pert_model.py:772-787, 836-851).
Step boundaries are natural checkpoints, so the TPU runner persists the
fitted (unconstrained) parameter dict, loss history and RNG-free metadata
after each step as a flat ``.npz``; a rerun resumes from the last
completed step.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


def save_step(checkpoint_dir: str, step: str, params: dict,
              losses: np.ndarray, extra: Optional[dict] = None) -> str:
    os.makedirs(checkpoint_dir, exist_ok=True)
    path = os.path.join(checkpoint_dir, f"pert_{step}.npz")
    flat = {f"param.{k}": np.asarray(v) for k, v in params.items()}
    flat["losses"] = np.asarray(losses)
    for k, v in (extra or {}).items():
        flat[f"extra.{k}"] = np.asarray(v)
    np.savez(path, **flat)
    return path


def load_step(checkpoint_dir: str, step: str):
    """Returns (params, losses, extra) or None if the checkpoint is absent."""
    path = os.path.join(checkpoint_dir, f"pert_{step}.npz")
    if not os.path.exists(path):
        return None
    data = np.load(path)
    params = {k[len("param."):]: data[k] for k in data.files
              if k.startswith("param.")}
    extra = {k[len("extra."):]: data[k] for k in data.files
             if k.startswith("extra.")}
    return params, data["losses"], extra
