"""Checkpointing of fitted parameter pytrees + optimizer state.

The reference has no checkpoint/resume at all — learned state crosses the
three SVI steps only in-memory (reference: pert_model.py:772-787, 836-851).
The TPU runner persists, after each step (and periodically DURING a
controller-chunked fit — see ``PertConfig.checkpoint_every``), the fitted
(unconstrained) parameter dict, the Adam optimiser state, the loss history
and a small meta record (iterations run, converged flag) as a flat
``.npz``.

Durability contract (this is restart-critical state, so every write is
paranoid):

* **atomic commit** — the npz is written to a temp file in the same
  directory and ``os.replace``d into place, so a preemption mid-write
  can never leave a torn file under the canonical name;
* **integrity footer** — 48 trailing bytes (magic + payload length +
  sha256 of the payload) appended after the zip payload (the zip EOCD
  scan tolerates trailing data).  ``load_step`` verifies length and
  digest before unpickling anything, so truncation/corruption surfaces
  as a typed :class:`CheckpointCorrupt` naming the file instead of an
  opaque zipfile/unpickling error;
* **bounded retention** — each save rotates the previous good file to
  ``pert_<step>.prev.npz`` first; a corrupt newest checkpoint falls
  back to that predecessor (one extra fit segment re-run beats a dead
  resume).

Resume semantics (see ``runner.PertInference._fit``):

* a COMPLETED step (converged, NaN-aborted, or out of budget) is restored
  as-is and not refit;
* a PARTIAL step (stopped early, killed mid-budget, or a periodic
  in-fit checkpoint) resumes optimisation from the saved iteration with
  Adam moments — and, for controller-chunked fits, the controller's
  own state (best-loss checkpoint, budget ledger, diagnostics ring) —
  intact, so the resumed trajectory is bit-identical to an
  uninterrupted run (the compiled loop is deterministic given params +
  opt state + loss history).
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
from typing import Optional

import numpy as np

from scdna_replication_tools_tpu.infer.manifest import atomic_write_bytes
from scdna_replication_tools_tpu.utils.profiling import logger

# Format history (the pi_logits layout contract lives in layout.py):
#   v4  topology stamp (meta.topology: mesh axes/extents, process
#       count/index, device kind, per-leaf PartitionSpecs from
#       layout.param_layouts) embedded in every save; multi-process
#       saves write one HOST-LOCAL shard file per process plus a
#       process-0 commit pointer (two-phase commit — see save_step);
#       per-leaf `range.`/`gshape.` sidecars record each block's
#       global box so a checkpoint written on ANY topology reassembles
#       on any other.  v3 files load unchanged (no stamp = legacy
#       single-device topology).
#   v3  integrity footer appended; optional ctrl.* / best.* extras
#       (controller resume state) — fully readable by the v2 loader
#       layout-wise, so no layout bump
#   v2  pi_logits stored STATE-MAJOR (P, cells, loci)
#   v1  (never stamped) pi_logits cells-major — round <= 3 checkpoints;
#       round-4 snapshots confusingly wrote state-major WITHOUT a stamp,
#       so an unstamped 3-D pi_logits is AMBIGUOUS and load_step refuses
#       it rather than guessing (a wrong guess trains on a transposed
#       tensor); delete the stale .npz and refit.
CHECKPOINT_FORMAT_VERSION = 4

# integrity footer: magic(8) + little-endian payload length(8) + sha256(32)
_FOOTER_MAGIC = b"PERTCK01"
_FOOTER_LEN = len(_FOOTER_MAGIC) + 8 + 32


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file failed integrity verification or parsing.

    Carries the offending ``path`` so operators (and the RunLog event
    the runner emits) can name the artifact to delete or investigate.
    """

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint {path}: {reason}")
        self.path = path
        self.reason = reason


def _step_path(checkpoint_dir: str, step: str) -> str:
    return os.path.join(checkpoint_dir, f"pert_{step}.npz")


def _prev_path(path: str) -> str:
    root, ext = os.path.splitext(path)
    return f"{root}.prev{ext}"


def _commit_path(checkpoint_dir: str, step: str) -> str:
    return os.path.join(checkpoint_dir, f"pert_{step}.commit.json")


def _shard_path(checkpoint_dir: str, step: str, seq: int, k: int,
                n: int) -> str:
    return os.path.join(checkpoint_dir,
                        f"pert_{step}.s{seq}.p{k}of{n}.npz")


# ---------------------------------------------------------------------------
# topology stamp + host-local views
# ---------------------------------------------------------------------------


def topology_stamp(mesh=None) -> dict:
    """JSON-able record of the save-time execution topology.

    Embedded in every checkpoint (``meta.topology``) so a resuming
    process can tell bit-exact same-geometry restores apart from
    cross-topology (resharding) resumes: mesh axis names/extents,
    process count/index, device count/kind, and the PartitionSpec +
    cells-axis of every parameter leaf from ``layout.param_layouts``
    (the same table the DP006/DP007 contract checker enumerates).
    """
    from scdna_replication_tools_tpu import layout
    from scdna_replication_tools_tpu.parallel.distributed import (
        process_topology,
    )
    from scdna_replication_tools_tpu.parallel.mesh import loci_axis

    stamp = {"format": 1}
    stamp.update(process_topology(mesh))
    lx = loci_axis(mesh) if mesh is not None else None
    stamp["param_layouts"] = layout.param_layouts(lx)
    return stamp


def host_view(tree):
    """Host-transferable view of a pytree for the checkpoint writer.

    Fully-addressable leaves (single-process, or replicated on one
    host's devices) become numpy; multi-host global jax.Arrays pass
    through UNCHANGED — :func:`save_step` gathers their addressable
    shards into this host's block.  Call sites that used to
    ``tree_map(np.asarray, ...)`` route through this instead, because
    ``np.asarray`` on a non-fully-addressable array raises.
    """
    import jax

    def one(leaf):
        if leaf is None:
            return None
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return leaf
        return np.asarray(leaf)

    return jax.tree_util.tree_map(one, tree)


def _host_block(leaf):
    """(host-local numpy block, global box or None) of one leaf.

    For plain numpy / fully-addressable arrays the block IS the whole
    array (box None).  For a multi-host global array, the addressable
    shards are assembled into the bounding box of this host's region —
    per-host contiguous by the ``HostShard`` tiling contract — and the
    box ``((lo0, hi0), ...)`` records where the block sits in the
    global array, which is all the loader needs to reassemble on ANY
    topology.
    """
    import jax

    if not isinstance(leaf, jax.Array) or leaf.is_fully_addressable:
        return np.asarray(leaf), None
    shards = list(leaf.addressable_shards)
    shape = leaf.shape
    ndim = len(shape)
    los = list(shape)
    his = [0] * ndim
    boxes = []
    for s in shards:
        box = []
        for d, sl in enumerate(s.index):
            start = 0 if sl.start is None else int(sl.start)
            stop = shape[d] if sl.stop is None else int(sl.stop)
            box.append((start, stop))
            los[d] = min(los[d], start)
            his[d] = max(his[d], stop)
        boxes.append(tuple(box))
    block = np.zeros([hi - lo for lo, hi in zip(los, his)],
                     np.asarray(shards[0].data).dtype)
    for s, box in zip(shards, boxes):
        target = tuple(slice(b[0] - lo, b[1] - lo)
                       for b, lo in zip(box, los))
        block[target] = np.asarray(s.data)
    if all(lo == 0 and hi == dim
           for lo, hi, dim in zip(los, his, shape)):
        return block, None   # this host sees the whole array
    return block, tuple((int(lo), int(hi)) for lo, hi in zip(los, his))


def _flat_add(flat: dict, key: str, leaf, multiproc: bool) -> None:
    """Record one leaf under ``key``, with ``range.``/``gshape.``
    sidecars when only this host's block is stored.  bfloat16 leaves
    are stored as uint16 bit views with an ``optdtype.``-style sidecar
    (npz cannot round-trip ml_dtypes) — the loader views them back."""
    import jax

    gshape = tuple(getattr(leaf, "shape", np.shape(leaf)))
    if multiproc and isinstance(leaf, jax.Array) \
            and not leaf.is_fully_addressable:
        block, box = _host_block(leaf)
    else:
        block, box = np.asarray(leaf), None
    if block.dtype.name == "bfloat16":
        flat[key] = block.view(np.uint16)
        flat[f"leafdtype.{key}"] = np.asarray("bfloat16")
    else:
        flat[key] = block
    if box is not None:
        flat[f"range.{key}"] = np.asarray(box, np.int64)
        flat[f"gshape.{key}"] = np.asarray(gshape, np.int64)


def _encode_payload(flat: dict) -> bytes:
    """npz bytes + integrity footer: serialized in memory so the footer
    hashes exactly the bytes that land on disk."""
    import io

    buf = io.BytesIO()
    np.savez(buf, **flat)
    payload = buf.getvalue()
    footer = (_FOOTER_MAGIC + struct.pack("<Q", len(payload))
              + hashlib.sha256(payload).digest())
    return payload + footer


def save_step(checkpoint_dir: str, step: str, params: dict,
              losses: np.ndarray, extra: Optional[dict] = None,
              opt_state=None, num_iters: Optional[int] = None,
              converged: bool = True, nan_abort: bool = False,
              mesh=None, coordinate: bool = True) -> str:
    """Persist one step's state; sharding- and topology-aware.

    Single-process: one atomic ``pert_<step>.npz`` exactly as before
    (rotate-previous retention, integrity footer), now carrying the
    topology stamp.  Multi-process: every host writes ITS cells-rows
    (gathered from addressable shards — the global tensor is never
    materialised anywhere) to a per-host shard file, then a barrier,
    then process 0 atomically commits the generation pointer
    (``pert_<step>.commit.json``) — the **two-phase commit**.  A
    preemption anywhere in the window leaves the previous COMPLETE
    generation visible: shard files without a commit pointing at them
    do not exist as far as ``load_step`` is concerned, so ``--resume
    auto`` can never see a mixed-step or partially-written checkpoint.

    ``params``/``opt_state``/``extra`` leaves may be numpy, host
    jax.Arrays, or multi-host global jax.Arrays (see
    :func:`host_view`); ``mesh`` (optional) enriches the topology
    stamp with the mesh axes the leaves were placed on.

    ``coordinate=False`` (the EMERGENCY path — a dying process saving
    on the way out of an escaping exception) writes only phase 1 of a
    multi-process save: this host's shard file, no barrier, no commit.
    A process that is going away cannot ask its peers to rendezvous —
    they may be mid-chunk, or already dead — so the generation stays
    uncommitted and invisible; resume falls back to the last COMMITTED
    generation, which is precisely the two-phase visibility contract.
    Single-process saves ignore the flag (one atomic file either way).
    """
    os.makedirs(checkpoint_dir, exist_ok=True)
    from scdna_replication_tools_tpu.parallel.distributed import (
        process_rank_and_count,
    )

    kproc, nproc = process_rank_and_count()
    multiproc = nproc > 1

    flat: dict = {}
    for k, v in params.items():
        _flat_add(flat, f"param.{k}", v, multiproc)
    flat["losses"] = np.asarray(losses)
    flat["meta.format_version"] = np.asarray(CHECKPOINT_FORMAT_VERSION)
    flat["meta.num_iters"] = np.asarray(
        num_iters if num_iters is not None else len(losses))
    flat["meta.converged"] = np.asarray(bool(converged))
    flat["meta.nan_abort"] = np.asarray(bool(nan_abort))
    flat["meta.topology"] = np.asarray(json.dumps(topology_stamp(mesh)))
    if opt_state is not None:
        # flatten generically; the reader rebuilds the treedef from a
        # fresh optax init over the restored params (same structure).
        # Dtype-aware (optimizer_state_dtype='bfloat16'): npz cannot
        # round-trip ml_dtypes.bfloat16, so bfloat16 leaves are stored
        # as uint16 BIT VIEWS with a per-leaf sidecar the loader uses
        # to view them back — bit-exact both ways (_flat_add).  The
        # summary ``meta.opt_moment_dtype`` is what the runner's
        # resume gate compares against the configured dtype.
        import jax

        leaves = jax.tree_util.tree_leaves(opt_state)
        moment_dtype = "float32"
        for i, leaf in enumerate(leaves):
            _flat_add(flat, f"opt.{i}", leaf, multiproc)
            if f"leafdtype.opt.{i}" in flat:
                moment_dtype = "bfloat16"
        flat["meta.opt_moment_dtype"] = np.asarray(moment_dtype)
    for k, v in (extra or {}).items():
        _flat_add(flat, f"extra.{k}", v, multiproc)

    if multiproc:
        return _save_step_multiprocess(checkpoint_dir, step, flat,
                                       nproc, kproc, mesh,
                                       coordinate=coordinate)

    # single-process: atomic commit with retention — rotate the
    # previous good file aside BEFORE replacing it, so a corrupt new
    # file (partial write + crash, or the injected corruption fault)
    # always leaves a fallback
    path = _step_path(checkpoint_dir, step)
    blob = _encode_payload(flat)
    if os.path.exists(path):
        try:
            os.replace(path, _prev_path(path))
        except OSError as exc:
            logger.warning("checkpoint retention: could not rotate %s "
                           "(%s)", path, exc)
    atomic_write_bytes(path, blob)
    # a fresh single-file save supersedes any sharded generation this
    # step accumulated under a previous (multi-host) topology: retire
    # the commit POINTER atomically (the shard files become invisible
    # with it; kept on disk as forensics until the next save's
    # retention pass)
    commit = _commit_path(checkpoint_dir, step)
    if os.path.exists(commit):
        try:
            os.replace(commit, commit + ".superseded")
        except OSError as exc:
            logger.warning("could not retire superseded sharded "
                           "checkpoint commit %s (%s)", commit, exc)

    from scdna_replication_tools_tpu.utils import faults as _faults

    if _faults.point(f"{step}/save") == "corrupt":
        _faults.corrupt_file(path)
    return path


def _read_commit(checkpoint_dir: str, step: str) -> Optional[dict]:
    """Parse the step's sharded-generation commit pointer, or None."""
    path = _commit_path(checkpoint_dir, step)
    try:
        with open(path) as fh:
            doc = json.load(fh)
        if not isinstance(doc, dict) or "files" not in doc:
            raise ValueError("not a checkpoint commit document")
        return doc
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as exc:
        logger.warning("checkpoint commit %s is unreadable (%s) — the "
                       "sharded generation it pointed at is not "
                       "loadable", path, exc)
        return None


def _save_step_multiprocess(checkpoint_dir: str, step: str, flat: dict,
                            nproc: int, kproc: int, mesh,
                            coordinate: bool = True) -> str:
    """Phase 1: every host atomically writes + fsyncs its shard file.
    Barrier.  Phase 2: process 0 atomically commits the generation
    pointer.  See :func:`save_step` for the visibility contract (and
    for ``coordinate=False`` — phase 1 only, no rendezvous)."""
    from scdna_replication_tools_tpu.parallel.distributed import barrier
    from scdna_replication_tools_tpu.utils import faults as _faults

    prev_doc = _read_commit(checkpoint_dir, step)
    seq = int(prev_doc["seq"]) + 1 if prev_doc else 1
    path = _shard_path(checkpoint_dir, step, seq, kproc, nproc)
    atomic_write_bytes(path, _encode_payload(flat))
    if _faults.point(f"{step}/save") == "corrupt":
        _faults.corrupt_file(path)
    if not coordinate:
        logger.warning(
            "emergency (uncoordinated) checkpoint save for %s: wrote "
            "this host's shard %s but did NOT commit — the generation "
            "stays invisible; resume uses the last committed one",
            step, os.path.basename(path))
        return path
    barrier(f"pert-ckpt/{step}/s{seq}/written")
    if kproc == 0:
        doc = {
            "format": 1,
            "seq": seq,
            "process_count": nproc,
            "files": [os.path.basename(
                _shard_path(checkpoint_dir, step, seq, j, nproc))
                for j in range(nproc)],
            "topology": topology_stamp(mesh),
        }
        if prev_doc:
            doc["prev"] = {"seq": int(prev_doc["seq"]),
                           "files": list(prev_doc["files"])}
        atomic_write_bytes(_commit_path(checkpoint_dir, step),
                           json.dumps(doc, indent=1).encode())
        # mirror of the single-file save's commit-pointer retirement:
        # a stale pert_<step>.npz from a previous (single-process)
        # attempt must not out-mtime-tiebreak the generation just
        # committed
        stale_single = _step_path(checkpoint_dir, step)
        if os.path.exists(stale_single):
            try:
                os.replace(stale_single, stale_single + ".superseded")
            except OSError as exc:
                logger.warning("could not retire superseded single-"
                               "file checkpoint %s (%s)", stale_single,
                               exc)
        # bounded retention: generations older than `prev` are dead
        keep = {seq} | ({int(prev_doc["seq"])} if prev_doc else set())
        import glob as _glob
        import re as _re

        for old in _glob.glob(os.path.join(
                checkpoint_dir, f"pert_{step}.s*.p*of*.npz")):
            m = _re.search(r"\.s(\d+)\.p\d+of\d+\.npz$", old)
            if m and int(m.group(1)) not in keep:
                try:
                    os.unlink(old)
                except OSError:
                    pass
    # every host waits for the commit before returning: a caller that
    # immediately saves again must see THIS generation's seq
    barrier(f"pert-ckpt/{step}/s{seq}/committed")
    return path


def quarantine_stale(checkpoint_dir: str) -> int:
    """Rename every ``pert_*.npz`` (and retained ``.prev``) aside to
    ``*.stale`` — called when the resume ledger is voided (fingerprint
    mismatch under ``resume='auto'``, or ``resume='off'``).  Resetting
    the ledger alone is not enough: the files would survive, and once
    the NEW identity lands in the manifest a later run would
    fingerprint-verify and silently restore params fitted to OTHER
    data.  Renaming (not deleting) keeps the forensic artifact while
    guaranteeing no loader ever reads it; returns the count moved."""
    moved = 0
    try:
        import glob

        # shard files (pert_<step>.sN.pKofM.npz) match the same glob;
        # the commit pointers must be retired WITH them or a later
        # multi-host run would chase dangling generation references
        stale = glob.glob(os.path.join(checkpoint_dir, "pert_*.npz")) \
            + glob.glob(os.path.join(checkpoint_dir,
                                     "pert_*.commit.json"))
        for path in stale:
            try:
                os.replace(path, path + ".stale")
                moved += 1
            except OSError as exc:
                logger.warning("could not quarantine stale checkpoint "
                               "%s (%s)", path, exc)
    except OSError as exc:
        logger.warning("stale-checkpoint quarantine failed in %s (%s)",
                       checkpoint_dir, exc)
    if moved:
        logger.warning("quarantined %d stale checkpoint file(s) in %s "
                       "(renamed to *.stale)", moved, checkpoint_dir)
    return moved


def _verify_and_read(path: str):
    """Verify the integrity footer and parse the npz; raises
    :class:`CheckpointCorrupt` on any failure.  Pre-v3 files (no
    footer) parse unverified — refusing every historical checkpoint
    would turn an integrity upgrade into a fleet-wide refit."""
    try:
        with open(path, "rb") as fh:
            blob = fh.read()
    except OSError as exc:
        raise CheckpointCorrupt(path, f"unreadable ({exc})")
    if len(blob) >= _FOOTER_LEN \
            and blob[-_FOOTER_LEN:-_FOOTER_LEN + len(_FOOTER_MAGIC)] \
            == _FOOTER_MAGIC:
        footer = blob[-_FOOTER_LEN:]
        (length,) = struct.unpack(
            "<Q", footer[len(_FOOTER_MAGIC):len(_FOOTER_MAGIC) + 8])
        payload = blob[:-_FOOTER_LEN]
        if len(payload) != length:
            raise CheckpointCorrupt(
                path, f"truncated: footer records {length} payload "
                      f"bytes, file has {len(payload)}")
        if hashlib.sha256(payload).digest() != footer[-32:]:
            raise CheckpointCorrupt(path, "sha256 mismatch (bit rot or "
                                          "partial overwrite)")
    else:
        payload = blob   # pre-v3: no footer to verify
    import io

    try:
        return np.load(io.BytesIO(payload))
    except Exception as exc:  # zipfile/ValueError/pickle zoo — the
        # typed error IS this except block's purpose
        raise CheckpointCorrupt(
            path, f"unparseable npz ({type(exc).__name__}: {exc})")


def _npz_dict(data) -> dict:
    """Materialise a verified npz archive into a plain dict."""
    return {k: data[k] for k in data.files}


def _merge_generation(flats: list) -> dict:
    """Reassemble one flat checkpoint mapping from per-host shard files.

    Leaves without a ``range.`` sidecar are host-identical (replicated
    or whole-array) — the first file's copy wins.  Sliced leaves are
    placed back into a zero-initialised global array at their recorded
    boxes; the per-host tiling is contiguous and even (HostShard), so
    the boxes exactly tile the global extent.
    """
    merged: dict = {}
    keys = list(dict.fromkeys(k for flat in flats for k in flat))
    for key in keys:
        if key.startswith("range.") or key.startswith("gshape."):
            continue
        range_key = f"range.{key}"
        if not any(range_key in flat for flat in flats):
            for flat in flats:
                if key in flat:
                    merged[key] = flat[key]
                    break
            continue
        out = None
        for flat in flats:
            if key not in flat:
                continue
            block = flat[key]
            if range_key not in flat:
                # a host that saw the whole array (e.g. after a shrink
                # to fewer hosts than the commit's writer set expected)
                out = np.array(block)
                break
            box = np.asarray(flat[range_key])
            if out is None:
                gshape = tuple(int(v) for v in flat[f"gshape.{key}"])
                out = np.zeros(gshape, block.dtype)
            out[tuple(slice(int(lo), int(hi)) for lo, hi in box)] = block
        merged[key] = out
    return merged


def _load_sharded(checkpoint_dir: str, step: str, doc: dict):
    """Load + merge one committed sharded generation, falling back to
    the retained previous generation when the committed one fails
    verification (the multi-file analog of the ``.prev`` fallback)."""

    def read_gen(files):
        flats = []
        for name in files:
            path = os.path.join(checkpoint_dir, name)
            flats.append(_npz_dict(_verify_and_read(path)))
        return flats

    try:
        flats = read_gen(doc["files"])
    except CheckpointCorrupt as exc:
        prev = doc.get("prev")
        if not prev:
            raise
        logger.warning("%s — falling back to the retained previous "
                       "sharded generation (seq %s)", exc,
                       prev.get("seq"))
        try:
            flats = read_gen(prev["files"])
        except CheckpointCorrupt:
            raise exc from None   # report the NEWEST generation
    return _unpack(f"{checkpoint_dir}/pert_{step}.commit.json",
                   _merge_generation(flats))


def load_step(checkpoint_dir: str, step: str):
    """Returns (params, losses, extra), or None if no checkpoint exists.

    ``extra`` carries the ``meta.*`` record (including the parsed
    ``meta.topology`` stamp for v4+ files), any ``opt.N`` optimiser
    leaves (rebuild the pytree with :func:`restore_opt_state`) and any
    ``ctrl.*``/``best.*`` controller resume state.  A corrupt newest
    file falls back to the retained ``.prev`` checkpoint (with a
    warning); when no fallback survives verification either, raises
    :class:`CheckpointCorrupt` for the NEWEST file — the caller decides
    whether a fresh refit is acceptable.

    Topology-portable: a step saved as a multi-host sharded generation
    (commit pointer + per-host shard files) is reassembled into full
    global arrays regardless of the CURRENT topology — the caller
    re-places them onto whatever mesh it runs (resharding resume).
    When both a sharded generation and a single file exist (a resumed
    run changed process count mid-history), the newer artifact wins.
    """
    path = _step_path(checkpoint_dir, step)
    doc = _read_commit(checkpoint_dir, step)
    if doc is not None:
        if os.path.exists(path):
            # both formats present: the newest save wins — each save
            # path retires the OTHER format's artifact after
            # committing its own, so coexistence is a crash window
            # between commit and retirement.  On an mtime TIE (coarse
            # filesystems) the single file wins: the only same-second
            # window is the single-process save's (npz written, crash
            # before the commit pointer retired — the npz is the newer
            # progress); a fresh sharded generation's stale-npz window
            # closes against an npz from a PREVIOUS attempt, minutes
            # older.
            try:
                commit_mtime = os.path.getmtime(
                    _commit_path(checkpoint_dir, step))
                if os.path.getmtime(path) >= commit_mtime:
                    doc = None
            except OSError:
                doc = None
        if doc is not None:
            return _load_sharded(checkpoint_dir, step, doc)
    if not os.path.exists(path):
        prev = _prev_path(path)
        if os.path.exists(prev):
            # rotate-then-write crash window: the canonical file was
            # rotated aside but the replacement never committed — the
            # retained predecessor is the newest durable state
            logger.warning(
                "checkpoint %s is missing but its retained predecessor "
                "exists (crash between rotation and commit?) — "
                "restoring %s", path, prev)
            data = _verify_and_read(prev)
            return _unpack(prev, _npz_dict(data))
        return None
    try:
        data = _verify_and_read(path)
    except CheckpointCorrupt as exc:
        prev = _prev_path(path)
        if os.path.exists(prev):
            logger.warning(
                "%s — falling back to the retained previous checkpoint "
                "%s", exc, prev)
            try:
                data = _verify_and_read(prev)
            except CheckpointCorrupt:
                raise exc from None   # report the NEWEST file
        else:
            raise
    return _unpack(path, _npz_dict(data))


def _unpack(path: str, data: dict):
    """(params, losses, extra) from a verified flat mapping."""
    params = {k[len("param."):]: data[k] for k in data
              if k.startswith("param.")}
    extra = {k[len("extra."):]: data[k] for k in data
             if k.startswith("extra.")}
    for k in data:
        if k.startswith("meta.") or k.startswith("opt."):
            extra[k] = data[k]
    # bfloat16 leaves round-trip: uint16 bit views back to bfloat16
    # (see _flat_add; `optdtype.` is the pre-v4 spelling of the same
    # sidecar) — readers downstream never see the storage trick
    for k in data:
        if k.startswith("optdtype.") or k.startswith("leafdtype."):
            if k.startswith("optdtype."):
                target = "opt." + k[len("optdtype."):]
            else:
                target = k[len("leafdtype."):]
            if str(data[k]) != "bfloat16":
                continue
            import ml_dtypes

            if target.startswith("param."):
                name = target[len("param."):]
                if name in params:
                    params[name] = params[name].view(ml_dtypes.bfloat16)
            elif target.startswith("extra."):
                name = target[len("extra."):]
                if name in extra:
                    extra[name] = extra[name].view(ml_dtypes.bfloat16)
            elif target in extra:
                extra[target] = extra[target].view(ml_dtypes.bfloat16)
    if "meta.topology" in extra:
        try:
            extra["meta.topology"] = json.loads(str(extra["meta.topology"]))
        except (TypeError, ValueError):
            extra["meta.topology"] = None
    version = int(extra.get("meta.format_version", 1))
    if version < 2 and "pi_logits" in params and params["pi_logits"].ndim == 3:
        raise ValueError(
            f"{path} has no format_version stamp: its pi_logits layout is "
            "ambiguous (pre-v2 checkpoints exist in BOTH cells-major and "
            "state-major orientations) and restoring a transposed tensor "
            "would silently corrupt training — delete the stale "
            "checkpoint file and refit")
    return params, data["losses"], extra


def restore_opt_state(extra: dict, params: dict, learning_rate: float,
                      b1: float, b2: float):
    """Rebuild the optax state pytree from flat ``opt.N`` leaves, or None
    when the checkpoint predates optimiser-state persistence."""
    opt_keys = sorted((k for k in extra if k.startswith("opt.")),
                      key=lambda k: int(k.split(".", 1)[1]))
    if not opt_keys:
        return None
    import jax
    from scdna_replication_tools_tpu.infer.svi import make_opt_state

    template = make_opt_state(params, learning_rate, b1, b2)
    treedef = jax.tree_util.tree_structure(template)
    leaves = [extra[k] for k in opt_keys]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def restore_controller_state(extra: dict) -> Optional[dict]:
    """Rebuild the chunked-fit controller's resume state from a
    checkpoint's ``ctrl.*`` / ``best.*`` extras, or None when the
    checkpoint predates in-fit checkpointing (``infer/svi.py``'s
    ``resume_state`` contract — the fields that make a mid-fit resume
    reproduce the uninterrupted decision trail bit-exactly)."""
    if "ctrl.format" not in extra:
        return None
    state = {
        "reseeds": int(extra["ctrl.reseeds"]),
        "extra_granted": int(extra["ctrl.extra_granted"]),
        "nan_retries": int(extra["ctrl.nan_retries"]),
        "lr": float(extra["ctrl.lr"]),
        "budget": int(extra["ctrl.budget"]),
        "stagnation_anchor": int(extra["ctrl.stagnation_anchor"]),
        "prev_verdict": str(extra["ctrl.prev_verdict"]) or None,
        "best_loss": float(extra["ctrl.best_loss"]),
        "best_it": int(extra["ctrl.best_it"]),
        "diag": np.asarray(extra["ctrl.diag"]),
        "diag_i0": int(extra["ctrl.diag_i0"]),
    }
    best = {k[len("best."):]: np.asarray(v) for k, v in extra.items()
            if k.startswith("best.")}
    if best:
        state["best_params"] = best
    else:
        # an inexact (mid-chunk emergency) save may have lost the
        # best-loss params; a finite best_loss without its params would
        # make the early-stop restore hand back the WRONG state — drop
        # the record and let the resumed segment re-establish its best
        state["best_loss"] = float("inf")
        state["best_it"] = 0
    return state


def pack_controller_state(state: dict) -> dict:
    """Flatten an ``infer/svi.py`` controller state dict into the
    ``extra`` keys :func:`restore_controller_state` reads back.

    Leaves go through :func:`host_view`: multi-host global arrays
    (the best-loss params of a sharded fit, the replicated diag ring)
    pass through for :func:`save_step` to gather per host."""
    out = {
        "ctrl.format": 1,
        "ctrl.reseeds": int(state["reseeds"]),
        "ctrl.extra_granted": int(state["extra_granted"]),
        "ctrl.nan_retries": int(state["nan_retries"]),
        "ctrl.lr": float(state["lr"]),
        "ctrl.budget": int(state["budget"]),
        "ctrl.stagnation_anchor": int(state["stagnation_anchor"]),
        "ctrl.prev_verdict": state.get("prev_verdict") or "",
        "ctrl.best_loss": float(state["best_loss"]),
        "ctrl.best_it": int(state["best_it"]),
        "ctrl.diag": host_view(state["diag"]),
        "ctrl.diag_i0": int(state["diag_i0"]),
    }
    for k, v in (state.get("best_params") or {}).items():
        out[f"best.{k}"] = host_view(v)
    return out
