"""Checkpointing of fitted parameter pytrees + optimizer state.

The reference has no checkpoint/resume at all — learned state crosses the
three SVI steps only in-memory (reference: pert_model.py:772-787, 836-851).
The TPU runner persists, after each step, the fitted (unconstrained)
parameter dict, the Adam optimiser state, the loss history and a small
meta record (iterations run, converged flag) as a flat ``.npz``.

Resume semantics (see ``runner.PertInference._fit``):

* a COMPLETED step (converged, NaN-aborted, or out of budget) is restored
  as-is and not refit;
* a PARTIAL step (stopped early, e.g. a smaller ``max_iter`` budget or a
  killed run whose latest boundary file was partial) resumes optimisation
  from the saved iteration with Adam moments intact — the resumed
  trajectory is bit-identical to an uninterrupted run because the
  compiled loop is deterministic given params + opt state + loss history.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

# Format history (the pi_logits layout contract lives in layout.py):
#   v2  pi_logits stored STATE-MAJOR (P, cells, loci)
#   v1  (never stamped) pi_logits cells-major — round <= 3 checkpoints;
#       round-4 snapshots confusingly wrote state-major WITHOUT a stamp,
#       so an unstamped 3-D pi_logits is AMBIGUOUS and load_step refuses
#       it rather than guessing (a wrong guess trains on a transposed
#       tensor); delete the stale .npz and refit.
CHECKPOINT_FORMAT_VERSION = 2


def save_step(checkpoint_dir: str, step: str, params: dict,
              losses: np.ndarray, extra: Optional[dict] = None,
              opt_state=None, num_iters: Optional[int] = None,
              converged: bool = True, nan_abort: bool = False) -> str:
    os.makedirs(checkpoint_dir, exist_ok=True)
    path = os.path.join(checkpoint_dir, f"pert_{step}.npz")
    flat = {f"param.{k}": np.asarray(v) for k, v in params.items()}
    flat["losses"] = np.asarray(losses)
    # v2 = pi_logits stored state-major (P, cells, loci); see layout.py
    flat["meta.format_version"] = np.asarray(CHECKPOINT_FORMAT_VERSION)
    flat["meta.num_iters"] = np.asarray(
        num_iters if num_iters is not None else len(losses))
    flat["meta.converged"] = np.asarray(bool(converged))
    flat["meta.nan_abort"] = np.asarray(bool(nan_abort))
    if opt_state is not None:
        # flatten generically; the reader rebuilds the treedef from a
        # fresh optax init over the restored params (same structure)
        import jax
        leaves = jax.tree_util.tree_leaves(opt_state)
        for i, leaf in enumerate(leaves):
            flat[f"opt.{i}"] = np.asarray(leaf)
    for k, v in (extra or {}).items():
        flat[f"extra.{k}"] = np.asarray(v)
    np.savez(path, **flat)
    return path


def load_step(checkpoint_dir: str, step: str):
    """Returns (params, losses, extra) or None if the checkpoint is absent.

    ``extra`` carries the ``meta.*`` record and any ``opt.N`` optimiser
    leaves (rebuild the pytree with :func:`restore_opt_state`).
    """
    path = os.path.join(checkpoint_dir, f"pert_{step}.npz")
    if not os.path.exists(path):
        return None
    data = np.load(path)
    params = {k[len("param."):]: data[k] for k in data.files
              if k.startswith("param.")}
    extra = {k[len("extra."):]: data[k] for k in data.files
             if k.startswith("extra.")}
    for k in data.files:
        if k.startswith("meta.") or k.startswith("opt."):
            extra[k] = data[k]
    version = int(extra.get("meta.format_version", 1))
    if version < 2 and "pi_logits" in params and params["pi_logits"].ndim == 3:
        raise ValueError(
            f"{path} has no format_version stamp: its pi_logits layout is "
            "ambiguous (pre-v2 checkpoints exist in BOTH cells-major and "
            "state-major orientations) and restoring a transposed tensor "
            "would silently corrupt training — delete the stale "
            "checkpoint file and refit")
    return params, data["losses"], extra


def restore_opt_state(extra: dict, params: dict, learning_rate: float,
                      b1: float, b2: float):
    """Rebuild the optax state pytree from flat ``opt.N`` leaves, or None
    when the checkpoint predates optimiser-state persistence."""
    opt_keys = sorted((k for k in extra if k.startswith("opt.")),
                      key=lambda k: int(k.split(".", 1)[1]))
    if not opt_keys:
        return None
    import jax
    from scdna_replication_tools_tpu.infer.svi import make_opt_state

    template = make_opt_state(params, learning_rate, b1, b2)
    treedef = jax.tree_util.tree_structure(template)
    leaves = [extra[k] for k in opt_keys]
    return jax.tree_util.tree_unflatten(treedef, leaves)
