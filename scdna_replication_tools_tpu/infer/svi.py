"""Compiled MAP-SVI driver: one XLA program per fit, loop on device.

The reference drives Pyro SVI with a Python ``for`` loop calling
``svi.step`` per iteration with host-side convergence checks
(reference: pert_model.py:742-758).  Here the entire optimisation —
Adam updates, loss history, plateau test, NaN abort — is a single
``lax.while_loop`` compiled once and dispatched once, so iteration cost is
pure device time with no host round-trips.

Convergence semantics mirror the reference exactly
(reference: pert_model.py:748-758):

* after recording loss_i, if i >= min_iter the window
  ``|max(losses[i-9:i]) - min(losses[i-9:i])| / |losses[0] - losses[i]|``
  is compared against rel_tol;
* a NaN loss aborts the fit (the numerical-sanitisation analog of the
  reference's NaN guard).

Optimiser: Adam(lr, betas=(0.8, 0.99)) as in reference: pert_model.py:734.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from scdna_replication_tools_tpu.obs import doctor as _doctor
from scdna_replication_tools_tpu.obs import runlog as _runlog

# fixed slot count of the in-fit diagnostics ring buffer: large enough
# that a converged fit's whole sampled trajectory usually survives, small
# enough that the carry cost is invisible (64 x 3 f32 = 768 bytes)
DIAG_RING = 64


@dataclasses.dataclass
class FitResult:
    params: dict            # fitted unconstrained params (device pytree)
    losses: np.ndarray      # (num_iters,) float per-iteration losses
    num_iters: int
    converged: bool
    nan_abort: bool
    opt_state: object = None  # final optax state (device pytree) — persist
                              # it to make a partial fit exactly resumable
    timings: dict = dataclasses.field(default_factory=dict)
    # wall-clock split of this fit's host-side cost: {"trace", "compile",
    # "fit"} seconds plus "program_cache" ("hit" when the in-process AOT
    # cache served the compiled program — trace and compile are then 0)
    diagnostics: Optional[dict] = None
    # on-device fit-health samples (``fit_map(diag_every=K)``): arrays
    # "iter"/"loss"/"grad_norm"/"param_norm" for the last <=DIAG_RING
    # iterations sampled every K, recorded INSIDE the while_loop carry
    # (no host sync) and fetched once post-fit; None when disabled
    verdict: Optional[str] = None
    # convergence-doctor class of this fit's loss tail (obs/doctor.py):
    # converged / plateaued / oscillating / diverging / unknown
    health: Optional[dict] = None
    # the full doctor report behind ``verdict``: reason, relative tail
    # drift/variance, gradient-norm decay — the ``fit_health`` telemetry
    # event's payload (infer/runner.py emits it)


def _window_stat(losses, i, win_size):
    """max-min over losses[i-9:i] (the reference's losses[-10:-1])."""
    start = jnp.maximum(i - win_size, 0)
    win = jax.lax.dynamic_slice(losses, (start,), (win_size,))
    # guard: when i < win_size the slice contains unwritten tail values;
    # the caller only consults this once i >= min_iter (>= 9 in practice)
    return jnp.max(win) - jnp.min(win)


# params0 / opt_state0 / losses0 / diag0 are initial-value pytrees, dead
# the moment the loop consumes them — donating them lets XLA reuse their
# buffers for the loop carry instead of copying on entry (at the
# 10k-cell scale pi_logits alone is ~2.8 GB; without donation every fit
# pays that copy in HBM churn and transient footprint).  Checkpoint
# resume stays bit-exact: donation recycles buffers, it never changes
# values, and every caller builds these pytrees fresh per fit (pinned by
# tests/test_donation.py).
@functools.partial(jax.jit, static_argnames=("loss_fn", "max_iter", "min_iter",
                                             "lr", "b1", "b2", "diag_every"),
                   donate_argnames=("params0", "opt_state0", "losses0",
                                    "diag0"))
def _run_fit(loss_fn: Callable, params0: dict, opt_state0, losses0, diag0,
             i0, loss_args: tuple,
             max_iter: int, min_iter: int, rel_tol: float,
             lr: float, b1: float, b2: float, diag_every: int):
    tx = optax.adam(learning_rate=lr, b1=b1, b2=b2)

    value_and_grad = jax.value_and_grad(loss_fn)

    def cond(carry):
        i, _, _, _, _, done, _, _ = carry
        return jnp.logical_and(i < max_iter, jnp.logical_not(done))

    def body(carry):
        # named_scope: groups this region's device time under one label
        # in jax.profiler traces (tools/trace_summary.py aggregates by
        # these pipeline-phase scopes)
        with jax.named_scope("pert/fit_step"):
            return _body(carry)

    def _body(carry):
        i, params, opt_state, losses, diag, _, _, _ = carry
        loss, grads = value_and_grad(params, *loss_args)

        if diag_every:
            # fit-health ring buffer, fully on device: loss + global
            # grad/param norms every diag_every iterations.  lax.cond (a
            # true runtime branch — the loop is not vmapped) keeps the
            # norm reductions off the non-sampled iterations, so the
            # steady-state step cost is untouched.
            def _record(d):
                row = jnp.stack([
                    loss.astype(jnp.float32),
                    optax.global_norm(grads).astype(jnp.float32),
                    optax.global_norm(params).astype(jnp.float32),
                ])
                slot = (i // diag_every) % DIAG_RING
                return jax.lax.dynamic_update_slice(d, row[None, :],
                                                    (slot, 0))

            diag = jax.lax.cond(i % diag_every == 0, _record,
                                lambda d: d, diag)

        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        losses = losses.at[i].set(loss)

        is_nan = jnp.isnan(loss)
        denom = jnp.abs(losses[0] - loss)
        # window clamped so tiny smoke-test budgets (max_iter < 9) compile
        loss_diff = _window_stat(losses, i, min(9, max_iter)) / denom
        converged = jnp.logical_and(i >= min_iter, loss_diff < rel_tol)
        done = jnp.logical_or(is_nan, converged)
        return (i + 1, params, opt_state, losses, diag, done, converged,
                is_nan)

    init = (jnp.asarray(i0), params0, opt_state0, losses0, diag0,
            jnp.asarray(False), jnp.asarray(False), jnp.asarray(False))
    (i, params, opt_state, losses, diag, _, converged,
     is_nan) = jax.lax.while_loop(cond, body, init)
    return i, params, opt_state, losses, diag, converged, is_nan


def make_opt_state(params: dict, learning_rate: float = 0.05,
                   b1: float = 0.8, b2: float = 0.99):
    """Fresh Adam state for ``params`` — also the treedef donor when
    restoring a checkpointed state from flat leaves."""
    return optax.adam(learning_rate=learning_rate, b1=b1, b2=b2).init(params)


# ---------------------------------------------------------------------------
# AOT program cache: dedupe trace+compile across fits
# ---------------------------------------------------------------------------
#
# jax.jit's own cache keys on the loss callable's *identity*, so two fits
# whose programs are identical (same spec, same shapes/dtypes/shardings)
# still retrace and recompile when the caller builds a fresh loss closure
# each time.  The runner now passes value-hashable loss callables
# (runner._PertLossFn) and this cache keys on (loss value, optimiser
# statics, abstract signature of every dynamic argument) — equal programs
# are compiled ONCE per process, and the explicit lower()/compile() split
# also yields the trace/compile phase timings the orchestration layer
# reports.  With the persistent compilation cache enabled (see
# utils.profiling.enable_persistent_compile_cache), the compile() half is
# served from disk across processes too.

_PROGRAM_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_PROGRAM_CACHE_MAX = 32


def _leaf_sig(leaf):
    return (getattr(leaf, "shape", None), str(getattr(leaf, "dtype", None)),
            getattr(leaf, "weak_type", None), getattr(leaf, "sharding", None))


def _abstract_sig(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef, tuple(_leaf_sig(leaf) for leaf in leaves))


def clear_program_cache() -> None:
    """Drop the in-process compiled-program cache (test seam)."""
    _PROGRAM_CACHE.clear()


def _key_hash(key) -> str:
    """Stable-in-process content hash of a program-cache key, for the
    telemetry ``compile`` events (reprs of specs/treedefs/shardings are
    deterministic within a process — good enough to correlate events of
    one run; NOT comparable across processes)."""
    import hashlib

    return hashlib.sha256(repr(key).encode()).hexdigest()[:12]


def _get_compiled(loss_fn, dynamic_args, rel_tol, statics, timings: dict):
    """Compiled _run_fit program for this signature, timed on miss.

    ``rel_tol`` is a DYNAMIC scalar (passed by keyword at lowering time,
    so the compiled program is reusable across tolerance values); the
    caller must invoke the result as ``compiled(*dynamic_args,
    rel_tol=...)`` to match the lowered pytree.

    Every resolution emits a telemetry ``compile`` event to the active
    RunLog (no-op outside a session): content hash, hit/miss,
    trace/compile seconds, plus the program's cost_analysis FLOPs and
    memory_analysis footprint (cached alongside the program so warm runs
    still report their memory high-water)."""
    try:
        key = (loss_fn, statics, _abstract_sig(dynamic_args))
        hash(key)
    except TypeError:
        _runlog.current().emit("compile", key_hash="unhashable",
                               label=type(loss_fn).__name__,
                               cache="uncacheable")
        return None  # unhashable loss callable/sharding: fall back
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        _PROGRAM_CACHE.move_to_end(key)
        timings["program_cache"] = "hit"
        compiled, stats = cached
        _runlog.current().emit("compile", key_hash=_key_hash(key),
                               label=type(loss_fn).__name__, cache="hit",
                               trace_seconds=0.0, compile_seconds=0.0,
                               **stats)
        return compiled
    max_iter, min_iter, lr, b1, b2, diag_every = statics
    t0 = time.perf_counter()
    lowered = _run_fit.lower(loss_fn, *dynamic_args,
                             max_iter=max_iter, min_iter=min_iter,
                             rel_tol=rel_tol, lr=lr, b1=b1, b2=b2,
                             diag_every=diag_every)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    timings["trace"] = t1 - t0
    timings["compile"] = t2 - t1
    timings["program_cache"] = "miss"
    stats = _runlog.compiled_program_stats(compiled)
    _runlog.current().emit("compile", key_hash=_key_hash(key),
                           label=type(loss_fn).__name__, cache="miss",
                           trace_seconds=round(t1 - t0, 4),
                           compile_seconds=round(t2 - t1, 4), **stats)
    _PROGRAM_CACHE[key] = (compiled, stats)
    while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
        _PROGRAM_CACHE.popitem(last=False)
    return compiled


def fit_map(loss_fn: Callable, params0: dict, loss_args: tuple = (),
            max_iter: int = 2000, min_iter: int = 100, rel_tol: float = 1e-6,
            learning_rate: float = 0.05, b1: float = 0.8, b2: float = 0.99,
            opt_state0=None, losses_prefix: Optional[np.ndarray] = None,
            diag_every: int = 0,
            doctor_thresholds: Optional[dict] = None,
            ) -> FitResult:
    """Fit ``params`` by MAP ascent of ``-loss_fn`` with reference semantics.

    ``loss_fn(params, *loss_args) -> scalar loss`` must be jit-traceable.
    When ``loss_fn`` is hashable by VALUE (e.g. a frozen dataclass), fits
    with identical programs share one trace+compile via the AOT program
    cache; closures/lambdas still work but only dedupe by identity.

    The ``params0``/``opt_state0`` pytrees (and the internal loss buffer)
    are DONATED to the compiled program — do not reuse those exact arrays
    after calling; ``FitResult.params``/``opt_state`` are the live
    outputs.  Exception: on the resume path (``opt_state0`` given) the
    inputs are defensively copied first, so a prior FitResult stays
    usable after resuming from it.

    Resume: pass the ``opt_state`` of a previous partial FitResult plus
    its ``losses`` as ``losses_prefix`` — optimisation continues from
    iteration ``len(losses_prefix)`` with Adam moments intact, so an
    interrupted fit reproduces the uninterrupted trajectory exactly (the
    loop is deterministic given params + opt state + loss history).

    ``diag_every=K`` (0 = off) samples loss + global grad/param norms
    every K iterations into an on-device ring buffer of ``DIAG_RING``
    slots — no host sync during the loop, fetched once post-fit and
    surfaced as ``FitResult.diagnostics`` (the last <=DIAG_RING samples
    of the run).  The extra reductions run only on sampled iterations
    (a compiled conditional), so the steady-state iteration cost is
    unchanged; K is a static of the compiled program.

    Every fit is also run through the convergence doctor
    (obs/doctor.py): ``FitResult.verdict`` classifies the loss tail
    (converged / plateaued / oscillating / diverging / unknown) with the
    full report on ``FitResult.health``; ``doctor_thresholds`` overrides
    the doctor's window/slope_tol/var_tol/grad_ratio defaults (the
    runner passes ``PertConfig``'s).  Host-side on the already-fetched
    loss history — adds no device work.
    """
    if opt_state0 is None:
        params0 = jax.tree_util.tree_map(jnp.asarray, params0)
        opt_state0 = make_opt_state(params0, learning_rate, b1, b2)
    else:
        # resume path: the caller is handing over a previous FitResult's
        # LIVE params/opt_state.  jnp.asarray would alias them, donation
        # would then delete the caller's buffers, and any reuse (retry
        # after a transient failure, checkpointing the partial fit)
        # would hit "Array has been deleted" — copy instead.  Resumes
        # are rare (checkpoint restarts), so the one extra copy does not
        # erode the donation win on the hot fresh-fit path.
        copy = functools.partial(jnp.array, copy=True)
        params0 = jax.tree_util.tree_map(copy, params0)
        opt_state0 = jax.tree_util.tree_map(copy, opt_state0)
    i0 = 0
    losses0 = jnp.zeros((max_iter,), jnp.float32)
    if losses_prefix is not None and len(losses_prefix) > 0:
        i0 = min(int(len(losses_prefix)), int(max_iter))
        losses0 = losses0.at[:i0].set(
            jnp.asarray(losses_prefix[:i0], jnp.float32))
    i0_host = int(i0)
    i0 = jnp.asarray(i0, jnp.int32)

    diag_every = int(diag_every)
    # shape (0, 3) when disabled: the carry keeps one uniform pytree
    # structure and the static diag_every branch removes every diag op
    diag0 = jnp.zeros((DIAG_RING if diag_every else 0, 3), jnp.float32)

    rel_tol = float(rel_tol)
    statics = (int(max_iter), int(min_iter),
               float(learning_rate), float(b1), float(b2), diag_every)
    dynamic_args = (params0, opt_state0, losses0, diag0, i0, loss_args)
    timings: dict = {"trace": 0.0, "compile": 0.0}
    compiled = _get_compiled(loss_fn, dynamic_args, rel_tol, statics,
                             timings)

    t0 = time.perf_counter()
    if compiled is not None:
        out = compiled(*dynamic_args, rel_tol=rel_tol)
    else:
        timings["program_cache"] = "uncacheable"
        out = _run_fit(loss_fn, *dynamic_args,
                       max_iter=statics[0], min_iter=statics[1],
                       rel_tol=rel_tol, lr=statics[2], b1=statics[3],
                       b2=statics[4], diag_every=diag_every)
    i, params, opt_state, losses, diag, converged, is_nan = out
    n = int(i)
    losses_host = np.asarray(losses)[:n]
    diagnostics = None
    if diag_every:
        diagnostics = _decode_diag(np.asarray(diag), n, i0_host, diag_every)
    timings["fit"] = time.perf_counter() - t0
    health = _diagnose(losses_host, bool(converged), bool(is_nan),
                       diagnostics, doctor_thresholds)
    return FitResult(
        params=params,
        losses=losses_host,
        num_iters=n,
        converged=bool(converged),
        nan_abort=bool(is_nan),
        opt_state=opt_state,
        timings=timings,
        diagnostics=diagnostics,
        verdict=health["verdict"],
        health=health,
    )


def _diagnose(losses: np.ndarray, converged: bool, nan_abort: bool,
              diagnostics: Optional[dict],
              thresholds: Optional[dict]) -> dict:
    """Convergence-doctor report for one completed fit (host-side)."""
    kwargs = dict(thresholds or {})
    grad = diagnostics["grad_norm"] if diagnostics is not None \
        and len(diagnostics.get("grad_norm", ())) else None
    return _doctor.diagnose_fit(
        losses, converged=converged, nan_abort=nan_abort,
        grad_norm_first=float(grad[0]) if grad is not None else None,
        grad_norm_last=float(grad[-1]) if grad is not None else None,
        **kwargs)


def _decode_diag(diag: np.ndarray, num_iters: int, i0: int,
                 diag_every: int) -> dict:
    """Map ring-buffer slots back to the iterations they sampled.

    Sampled iterations are the multiples of ``diag_every`` in
    ``[i0, num_iters)`` (a resumed fit samples only its own segment);
    slot ``(iter // diag_every) % DIAG_RING`` holds each — the last
    ``DIAG_RING`` samples are distinct slots, older ones were
    overwritten.
    """
    first = -(-i0 // diag_every) * diag_every  # ceil to a multiple
    sampled = list(range(first, num_iters, diag_every))
    kept = sampled[-DIAG_RING:]
    rows = [(it // diag_every) % DIAG_RING for it in kept]
    return {
        "every": diag_every,
        "iter": np.asarray(kept, np.int64),
        "loss": diag[rows, 0] if kept else np.zeros(0, np.float32),
        "grad_norm": diag[rows, 1] if kept else np.zeros(0, np.float32),
        "param_norm": diag[rows, 2] if kept else np.zeros(0, np.float32),
    }
