"""Compiled MAP-SVI driver: one XLA program per fit, loop on device.

The reference drives Pyro SVI with a Python ``for`` loop calling
``svi.step`` per iteration with host-side convergence checks
(reference: pert_model.py:742-758).  Here the entire optimisation —
Adam updates, loss history, plateau test, NaN abort — is a single
``lax.while_loop`` compiled once and dispatched once, so iteration cost is
pure device time with no host round-trips.

Convergence semantics mirror the reference exactly
(reference: pert_model.py:748-758):

* after recording loss_i, if i >= min_iter the window
  ``|max(losses[i-9:i]) - min(losses[i-9:i])| / |losses[0] - losses[i]|``
  is compared against rel_tol;
* a NaN loss aborts the fit (the numerical-sanitisation analog of the
  reference's NaN guard).

Optimiser: Adam(lr, betas=(0.8, 0.99)) as in reference: pert_model.py:734.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax


@dataclasses.dataclass
class FitResult:
    params: dict            # fitted unconstrained params (device pytree)
    losses: np.ndarray      # (num_iters,) float per-iteration losses
    num_iters: int
    converged: bool
    nan_abort: bool
    opt_state: object = None  # final optax state (device pytree) — persist
                              # it to make a partial fit exactly resumable


def _window_stat(losses, i, win_size):
    """max-min over losses[i-9:i] (the reference's losses[-10:-1])."""
    start = jnp.maximum(i - win_size, 0)
    win = jax.lax.dynamic_slice(losses, (start,), (win_size,))
    # guard: when i < win_size the slice contains unwritten tail values;
    # the caller only consults this once i >= min_iter (>= 9 in practice)
    return jnp.max(win) - jnp.min(win)


@functools.partial(jax.jit, static_argnames=("loss_fn", "max_iter", "min_iter",
                                             "lr", "b1", "b2"))
def _run_fit(loss_fn: Callable, params0: dict, opt_state0, losses0,
             i0, loss_args: tuple,
             max_iter: int, min_iter: int, rel_tol: float,
             lr: float, b1: float, b2: float):
    tx = optax.adam(learning_rate=lr, b1=b1, b2=b2)

    value_and_grad = jax.value_and_grad(loss_fn)

    def cond(carry):
        i, _, _, _, done, _, _ = carry
        return jnp.logical_and(i < max_iter, jnp.logical_not(done))

    def body(carry):
        i, params, opt_state, losses, _, _, _ = carry
        loss, grads = value_and_grad(params, *loss_args)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        losses = losses.at[i].set(loss)

        is_nan = jnp.isnan(loss)
        denom = jnp.abs(losses[0] - loss)
        # window clamped so tiny smoke-test budgets (max_iter < 9) compile
        loss_diff = _window_stat(losses, i, min(9, max_iter)) / denom
        converged = jnp.logical_and(i >= min_iter, loss_diff < rel_tol)
        done = jnp.logical_or(is_nan, converged)
        return (i + 1, params, opt_state, losses, done, converged, is_nan)

    init = (jnp.asarray(i0), params0, opt_state0, losses0,
            jnp.asarray(False), jnp.asarray(False), jnp.asarray(False))
    i, params, opt_state, losses, _, converged, is_nan = jax.lax.while_loop(
        cond, body, init)
    return i, params, opt_state, losses, converged, is_nan


def make_opt_state(params: dict, learning_rate: float = 0.05,
                   b1: float = 0.8, b2: float = 0.99):
    """Fresh Adam state for ``params`` — also the treedef donor when
    restoring a checkpointed state from flat leaves."""
    return optax.adam(learning_rate=learning_rate, b1=b1, b2=b2).init(params)


def fit_map(loss_fn: Callable, params0: dict, loss_args: tuple = (),
            max_iter: int = 2000, min_iter: int = 100, rel_tol: float = 1e-6,
            learning_rate: float = 0.05, b1: float = 0.8, b2: float = 0.99,
            opt_state0=None, losses_prefix: Optional[np.ndarray] = None,
            ) -> FitResult:
    """Fit ``params`` by MAP ascent of ``-loss_fn`` with reference semantics.

    ``loss_fn(params, *loss_args) -> scalar loss`` must be jit-traceable.

    Resume: pass the ``opt_state`` of a previous partial FitResult plus
    its ``losses`` as ``losses_prefix`` — optimisation continues from
    iteration ``len(losses_prefix)`` with Adam moments intact, so an
    interrupted fit reproduces the uninterrupted trajectory exactly (the
    loop is deterministic given params + opt state + loss history).
    """
    if opt_state0 is None:
        opt_state0 = make_opt_state(params0, learning_rate, b1, b2)
    i0 = 0
    losses0 = jnp.zeros((max_iter,), jnp.float32)
    if losses_prefix is not None and len(losses_prefix) > 0:
        i0 = min(int(len(losses_prefix)), int(max_iter))
        losses0 = losses0.at[:i0].set(
            jnp.asarray(losses_prefix[:i0], jnp.float32))
    i, params, opt_state, losses, converged, is_nan = _run_fit(
        loss_fn, params0, opt_state0, losses0, i0, loss_args,
        int(max_iter), int(min_iter),
        float(rel_tol), float(learning_rate), float(b1), float(b2))
    n = int(i)
    return FitResult(
        params=params,
        losses=np.asarray(losses)[:n],
        num_iters=n,
        converged=bool(converged),
        nan_abort=bool(is_nan),
        opt_state=opt_state,
    )
