"""Compiled MAP-SVI driver: one XLA program per fit, loop on device.

The reference drives Pyro SVI with a Python ``for`` loop calling
``svi.step`` per iteration with host-side convergence checks
(reference: pert_model.py:742-758).  Here the entire optimisation —
Adam updates, loss history, plateau test, NaN abort — is a single
``lax.while_loop`` compiled once and dispatched once, so iteration cost is
pure device time with no host round-trips.

Convergence semantics mirror the reference exactly
(reference: pert_model.py:748-758):

* after recording loss_i, if i >= min_iter the window
  ``|max(losses[i-9:i]) - min(losses[i-9:i])| / |losses[0] - losses[i]|``
  is compared against rel_tol;
* a NaN loss aborts the fit (the numerical-sanitisation analog of the
  reference's NaN guard).

Optimiser: Adam(lr, betas=(0.8, 0.99)) as in reference: pert_model.py:734.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import threading
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from scdna_replication_tools_tpu.infer import aotcache as _aotcache
from scdna_replication_tools_tpu.obs import doctor as _doctor
from scdna_replication_tools_tpu.obs import heartbeat as _heartbeat
from scdna_replication_tools_tpu.obs import meter as _meter
from scdna_replication_tools_tpu.obs import runlog as _runlog
from scdna_replication_tools_tpu.ops import adam_kernel as _adam_kernel
from scdna_replication_tools_tpu.utils import faults as _faults

# fixed slot count of the in-fit diagnostics ring buffer: large enough
# that a converged fit's whole sampled trajectory usually survives, small
# enough that the carry cost is invisible (64 x 3 f32 = 768 bytes)
DIAG_RING = 64


@dataclasses.dataclass
class FitResult:
    params: dict            # fitted unconstrained params (device pytree)
    losses: np.ndarray      # (num_iters,) float per-iteration losses
    num_iters: int
    converged: bool
    nan_abort: bool
    opt_state: object = None  # final optax state (device pytree) — persist
                              # it to make a partial fit exactly resumable
    timings: dict = dataclasses.field(default_factory=dict)
    # wall-clock split of this fit's host-side cost: {"trace", "compile",
    # "fit"} seconds plus "program_cache" ("hit" when the in-process AOT
    # cache served the compiled program — trace and compile are then 0)
    diagnostics: Optional[dict] = None
    # on-device fit-health samples (``fit_map(diag_every=K)``): arrays
    # "iter"/"loss"/"grad_norm"/"param_norm" for the last <=DIAG_RING
    # iterations sampled every K, recorded INSIDE the while_loop carry
    # (no host sync) and fetched once post-fit; None when disabled
    verdict: Optional[str] = None
    # convergence-doctor class of this fit's loss tail (obs/doctor.py):
    # converged / plateaued / oscillating / diverging / unknown
    health: Optional[dict] = None
    # the full doctor report behind ``verdict``: reason, relative tail
    # drift/variance, gradient-norm decay — the ``fit_health`` telemetry
    # event's payload (infer/runner.py emits it)
    decisions: list = dataclasses.field(default_factory=list)
    # the adaptive controller's audit trail for this fit (empty when the
    # controller is off or never acted): one dict per decision, emitted
    # verbatim as ``control_decision`` RunLog events by the runner
    budget: Optional[int] = None
    # the FINAL iteration budget the fit ran under (== the configured
    # max_iter unless the controller granted extensions)


def _window_stat(losses, i, win_size):
    """max-min over losses[i-9:i] (the reference's losses[-10:-1])."""
    start = jnp.maximum(i - win_size, 0)
    win = jax.lax.dynamic_slice(losses, (start,), (win_size,))
    # guard: when i < win_size the slice contains unwritten tail values;
    # the caller only consults this once i >= min_iter (>= 9 in practice)
    return jnp.max(win) - jnp.min(win)


def _pi_param_name(params: dict) -> Optional[str]:
    """The (planes, cells, loci) pi parameter's key: 'pi_bin_logits'
    under the independent-binary CN encoding, 'pi_logits' under the
    categorical one, None for pytrees that carry neither (generic
    fit_map callers)."""
    for name in ("pi_bin_logits", "pi_logits"):
        if name in params:
            return name
    return None


def _effective_fused_adam(fused_adam: str, moment_dtype: str) -> str:
    """bfloat16 moments REQUIRE the custom update (the stock optax chain
    would widen them back to float32 mid-loop and break the while-loop
    carry dtype contract) — promote 'off' to the XLA implementation."""
    if fused_adam == "off" and moment_dtype != "float32":
        return "xla"
    return fused_adam


def _fused_adam_apply(params: dict, grads: dict, opt_state, lr, b1, b2,
                      impl: str, moment_dtype: str):
    """One optimizer step through the fused-Adam path, preserving the
    optax.adam state PYTREE (ScaleByAdamState + the scale stage's empty
    state) so checkpoints, resume and ``make_opt_state``'s treedef-donor
    role are untouched — only how the leaves are computed changes.  The
    big (planes, cells, loci) pi parameter goes through the selected
    kernel (and the configured moment dtype); every other leaf takes
    the same single-sweep math as plain XLA ops (they are O(cells) /
    O(loci) — noise either way)."""
    inner = opt_state[0]
    rest = tuple(opt_state[1:])
    count = optax.safe_int32_increment(inner.count)
    pi_name = _pi_param_name(params)
    new_params: dict = {}
    new_mu: dict = {}
    new_nu: dict = {}
    for k in params:
        is_pi = k == pi_name
        p2, m2, v2 = _adam_kernel.adam_plane_update(
            params[k], grads[k], inner.mu[k], inner.nu[k], lr, b1, b2,
            count, impl=impl if is_pi else "xla",
            moment_dtype=moment_dtype if is_pi else "float32")
        new_params[k], new_mu[k], new_nu[k] = p2, m2, v2
    return new_params, (inner._replace(count=count, mu=new_mu,
                                       nu=new_nu),) + rest


def _fit_loop(loss_fn: Callable, lr, b1: float, b2: float,
              loss_args: tuple, diag_every: int, conv_window: int,
              bound, min_iter, rel_tol, init,
              fused_adam: str = "off", moment_dtype: str = "float32"):
    """The shared per-iteration fit loop of :func:`_run_fit` and
    :func:`_run_fit_chunk` — ONE copy of the iteration math, so the
    fixed and chunked paths cannot drift apart.  ``bound`` / ``min_iter``
    / ``rel_tol`` / ``lr`` may be Python scalars (fixed path: baked into
    the program) or traced device scalars (chunk path: one program
    serves every chunk of every budget); ``conv_window`` is always
    static (it sizes a dynamic_slice).

    ``fused_adam`` (static) selects the optimizer-update path:
    ``'off'`` keeps the stock optax chain bit-exactly; ``'xla'`` /
    ``'pallas'`` / ``'pallas_interpret'`` route the big pi parameter
    through the single-sweep fused update (ops/adam_kernel.py) with its
    stored moments in ``moment_dtype``."""
    fused_adam = _effective_fused_adam(fused_adam, moment_dtype)
    tx = optax.adam(learning_rate=lr, b1=b1, b2=b2)

    value_and_grad = jax.value_and_grad(loss_fn)

    def cond(carry):
        i, _, _, _, _, done, _, _ = carry
        return jnp.logical_and(i < bound, jnp.logical_not(done))

    def body(carry):
        # named_scope: groups this region's device time under one label
        # in jax.profiler traces (tools/trace_summary.py aggregates by
        # these pipeline-phase scopes)
        with jax.named_scope("pert/fit_step"):
            return _body(carry)

    def _body(carry):
        i, params, opt_state, losses, diag, _, _, _ = carry
        loss, grads = value_and_grad(params, *loss_args)

        if diag_every:
            # fit-health ring buffer, fully on device: loss + global
            # grad/param norms every diag_every iterations.  lax.cond (a
            # true runtime branch — the loop is not vmapped) keeps the
            # norm reductions off the non-sampled iterations, so the
            # steady-state step cost is untouched.
            def _record(d):
                row = jnp.stack([
                    loss.astype(jnp.float32),
                    optax.global_norm(grads).astype(jnp.float32),
                    optax.global_norm(params).astype(jnp.float32),
                ])
                slot = (i // diag_every) % DIAG_RING
                return jax.lax.dynamic_update_slice(d, row[None, :],
                                                    (slot, 0))

            diag = jax.lax.cond(i % diag_every == 0, _record,
                                lambda d: d, diag)

        if fused_adam != "off":
            params, opt_state = _fused_adam_apply(
                params, grads, opt_state, lr, b1, b2, fused_adam,
                moment_dtype)
        else:
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        losses = losses.at[i].set(loss)

        is_nan = jnp.isnan(loss)
        denom = jnp.abs(losses[0] - loss)
        loss_diff = _window_stat(losses, i, conv_window) / denom
        converged = jnp.logical_and(i >= min_iter, loss_diff < rel_tol)
        done = jnp.logical_or(is_nan, converged)
        return (i + 1, params, opt_state, losses, diag, done, converged,
                is_nan)

    (i, params, opt_state, losses, diag, _, converged,
     is_nan) = jax.lax.while_loop(cond, body, init)
    return i, params, opt_state, losses, diag, converged, is_nan


# Declared jit contracts of the two fit programs, in ONE place: the
# decorators below consume these tuples and the deep static-analysis
# layer (tools/pertlint/deep) reads the SAME tuples to audit the lowered
# programs — `donate_argnames` that fail to produce a real
# input_output_alias in the lowered module are exactly the PR-4
# mirror-rescue aliasing bug class, and a drifted copy of this list in
# the lint layer would make that audit lie.
FIT_STATIC_ARGNAMES = ("loss_fn", "max_iter", "min_iter", "lr", "b1", "b2",
                       "diag_every", "fused_adam", "moment_dtype")
FIT_DONATE_ARGNAMES = ("params0", "opt_state0", "losses0", "diag0")
CHUNK_STATIC_ARGNAMES = ("loss_fn", "conv_window", "b1", "b2", "diag_every",
                         "fused_adam", "moment_dtype")
CHUNK_DONATE_ARGNAMES = ("opt_state0", "losses0", "diag0")


# params0 / opt_state0 / losses0 / diag0 are initial-value pytrees, dead
# the moment the loop consumes them — donating them lets XLA reuse their
# buffers for the loop carry instead of copying on entry (at the
# 10k-cell scale pi_logits alone is ~2.8 GB; without donation every fit
# pays that copy in HBM churn and transient footprint).  Checkpoint
# resume stays bit-exact: donation recycles buffers, it never changes
# values, and every caller builds these pytrees fresh per fit (pinned by
# tests/test_donation.py).
@functools.partial(jax.jit, static_argnames=FIT_STATIC_ARGNAMES,
                   donate_argnames=FIT_DONATE_ARGNAMES)
def _run_fit(loss_fn: Callable, params0: dict, opt_state0, losses0, diag0,
             i0, loss_args: tuple,
             max_iter: int, min_iter: int, rel_tol: float,
             lr: float, b1: float, b2: float, diag_every: int,
             fused_adam: str = "off", moment_dtype: str = "float32"):
    init = (jnp.asarray(i0), params0, opt_state0, losses0, diag0,
            jnp.asarray(False), jnp.asarray(False), jnp.asarray(False))
    # window clamped so tiny smoke-test budgets (max_iter < 9) compile
    return _fit_loop(loss_fn, lr, b1, b2, loss_args, diag_every,
                     min(9, max_iter), max_iter, min_iter, rel_tol, init,
                     fused_adam=fused_adam, moment_dtype=moment_dtype)


# Chunked twin of ``_run_fit`` for the adaptive controller
# (obs/controller.py): identical per-iteration math (the shared
# ``_fit_loop``), but the loop bound ``stop`` — and min_iter / rel_tol /
# the learning rate — are DYNAMIC scalars, so ONE compiled program
# serves every chunk of every budget (including controller-granted
# extensions and the reduced-LR NaN retry); compile cost is unchanged
# versus the whole-budget program.  ``conv_window`` is the SAME
# ``min(9, max_iter)`` clamp the fixed path bakes in (it sizes a
# dynamic_slice, so it must stay static).  ``params0`` is deliberately
# NOT donated: the host driver keeps the chunk-entry params alive as the
# best-loss checkpoint the re-seed and NaN-escalation actions restart
# from (one extra live params copy — documented in PERF_NOTES).  The
# consumed-on-entry carries (opt/losses/diag) are still donated.
@functools.partial(jax.jit, static_argnames=CHUNK_STATIC_ARGNAMES,
                   donate_argnames=CHUNK_DONATE_ARGNAMES)
def _run_fit_chunk(loss_fn: Callable, params0: dict, opt_state0, losses0,
                   diag0, i0, stop, min_iter, rel_tol, lr,
                   loss_args: tuple,
                   conv_window: int, b1: float, b2: float,
                   diag_every: int,
                   fused_adam: str = "off", moment_dtype: str = "float32"):
    init = (i0, params0, opt_state0, losses0, diag0,
            jnp.asarray(False), jnp.asarray(False), jnp.asarray(False))
    return _fit_loop(loss_fn, lr, b1, b2, loss_args, diag_every,
                     conv_window, stop, min_iter, rel_tol, init,
                     fused_adam=fused_adam, moment_dtype=moment_dtype)


# ---------------------------------------------------------------------------
# Slab twin of the chunk program: continuous batching for serving
# ---------------------------------------------------------------------------
#
# ``_run_fit_chunk_slab`` maps the chunk program over a leading BLOCK
# axis: W same-shaped requests (the serving bucket ladder guarantees
# equal shapes within a rung) advance one chunk in ONE dispatch, each
# block carrying its own params/opt-state/loss-buffer and its own
# dynamic ``i0``/``stop``/``min_iter``/``rel_tol``/``lr`` scalars.  The
# per-chunk controller verdicts come back PER BLOCK (converged/is_nan
# vectors), which is what lets the serving slab retire a converged
# request at a chunk boundary and refill its block with a fresh one —
# the way vectorized-MCMC ensembles retire converged chains
# (arXiv:2503.17405) without stalling the rest.
#
# Retirement/vacancy convention: a block whose ``stop`` equals its
# ``i0`` has an immediately-false loop condition — its carry passes
# through UNTOUCHED (vmap-of-while_loop masks the lane), so parked
# blocks cost only the masked lane's share of each fused step.
#
# ``fused_adam='pallas'`` is not supported under the slab (the Pallas
# kernel's batching rule is unvalidated here); 'off' and 'xla' are.
SLAB_STATIC_ARGNAMES = CHUNK_STATIC_ARGNAMES
SLAB_DONATE_ARGNAMES = CHUNK_DONATE_ARGNAMES


@functools.partial(jax.jit, static_argnames=SLAB_STATIC_ARGNAMES,
                   donate_argnames=SLAB_DONATE_ARGNAMES)
def _run_fit_chunk_slab(loss_fn: Callable, params0: dict, opt_state0,
                        losses0, diag0, i0, stop, min_iter, rel_tol, lr,
                        loss_args: tuple,
                        conv_window: int, b1: float, b2: float,
                        diag_every: int,
                        fused_adam: str = "off",
                        moment_dtype: str = "float32"):
    if fused_adam.startswith("pallas"):
        raise ValueError(
            "fused_adam='pallas*' is not supported in the slab program; "
            "use 'off' or 'xla'")

    def _block(params0_b, opt_state0_b, losses0_b, diag0_b, i0_b,
               stop_b, min_iter_b, rel_tol_b, lr_b, loss_args_b):
        init = (i0_b, params0_b, opt_state0_b, losses0_b, diag0_b,
                jnp.asarray(False), jnp.asarray(False),
                jnp.asarray(False))
        return _fit_loop(loss_fn, lr_b, b1, b2, loss_args_b, diag_every,
                         conv_window, stop_b, min_iter_b, rel_tol_b,
                         init, fused_adam=fused_adam,
                         moment_dtype=moment_dtype)

    return jax.vmap(_block)(params0, opt_state0, losses0, diag0,
                            jnp.asarray(i0), jnp.asarray(stop),
                            jnp.asarray(min_iter), jnp.asarray(rel_tol),
                            jnp.asarray(lr), loss_args)


def slab_pack(blocks):
    """Stack per-block pytrees (equal treedefs/shapes) along a new
    leading block axis — the host-side packer for the slab program."""
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves),
                                  *blocks)


def slab_block(slab, index: int):
    """Extract block ``index`` from a slab pytree (drops the block
    axis) — the retirement path hands this back to the per-request
    decode."""
    return jax.tree_util.tree_map(lambda leaf: leaf[index], slab)


def slab_fill(slab, index: int, block):
    """Functionally replace block ``index`` of ``slab`` — the refill
    path, when a freshly admitted request takes over a vacated block.

    Returns the new slab; the input slab's buffers are NOT donated here
    (refill happens on the host between chunk dispatches, where the old
    slab may still back a retiring block's decode)."""
    return jax.tree_util.tree_map(
        lambda leaf, b: leaf.at[index].set(b), slab, block)


# ---------------------------------------------------------------------------
# Pluggable chunk dispatcher: the seam continuous batching hooks
# ---------------------------------------------------------------------------
#
# The chunked fit driver (``_fit_map_controlled``) dispatches every chunk
# through ONE seam: when a per-thread dispatcher is installed
# (``set_chunk_dispatcher``), each chunk is handed over as a
# :class:`ChunkCall` instead of being dispatched solo.  The batched
# serving worker (serve/slab.SlabFitCoordinator) uses this to rendezvous
# concurrent same-signature chunks from K request threads and advance
# them in one ``_run_fit_chunk_slab`` dispatch — the continuous-batching
# slab.  The seam is thread-local on purpose: the worker's block threads
# opt in individually, and everything else (serial mode, notebooks,
# tests) never sees a dispatcher.
#
# Numerics contract (pinned by tests/test_slab.py): a PACKED lane runs
# the vectorized slab program, whose fused update chain may differ from
# the solo program by ~1 ulp per step on some backends (value-dependent
# instruction selection — e.g. XLA:CPU picks different vector widths for
# (W, N) and (N,) layouts).  Lanes never exchange values — per-lane
# results are independent of WHO shares the slab — but bit-identity with
# the solo program holds only for dispatch groups of one, which the
# coordinator routes through ``ChunkCall.solo``.

_CHUNK_DISPATCHER_TLS = threading.local()


def set_chunk_dispatcher(dispatcher) -> None:
    """Install (``None`` clears) this thread's chunk dispatcher.

    The dispatcher must provide ``dispatch(call: ChunkCall)`` returning a
    ``_run_fit_chunk``-shaped output tuple, plus ``fit_begin()`` /
    ``fit_end()`` bracketing calls the chunked driver emits around each
    fit so the dispatcher knows how many threads are actively fitting."""
    _CHUNK_DISPATCHER_TLS.dispatcher = dispatcher


def get_chunk_dispatcher():
    """This thread's chunk dispatcher, or None (the default)."""
    return getattr(_CHUNK_DISPATCHER_TLS, "dispatcher", None)


@dataclasses.dataclass
class ChunkCall:
    """One chunk dispatch, reified for a dispatcher.

    ``args`` is the full ``_run_fit_chunk`` dynamic-argument tuple
    ``(params, opt_state, losses, diag, i0, stop, min_iter, rel_tol, lr,
    loss_args)``; ``solo`` dispatches it through the caller's (possibly
    AOT-compiled) solo program.  ``signature()`` is the pack-compatibility
    key: calls pack into one slab only when loss_fn, statics and every
    abstract leaf signature agree."""

    loss_fn: Callable
    args: tuple
    static_kwargs: dict
    solo: Callable
    # cost-attribution handle: (CostLedger, ctx snapshot) captured on
    # the lane's own thread at dispatch time, so the slab LEADER can
    # book each lane's share of the dispatch into the right request's
    # ledger with the lane's own step/bucket/pad_frac attribution.
    # None = unmetered (no ledger on the lane's RunLog).
    meter: Optional[tuple] = None

    def signature(self):
        try:
            lf = hash(self.loss_fn)
        except TypeError:
            lf = id(self.loss_fn)
        return (lf, tuple(sorted(self.static_kwargs.items())),
                _abstract_sig(self.args))


def dispatch_chunk_slab(calls, width: int, timings: Optional[dict] = None):
    """Advance every call's block in ONE ``_run_fit_chunk_slab``
    dispatch; returns one ``_run_fit_chunk``-shaped output tuple per
    call, in order.

    The slab is dispatched at the nearest POWER-OF-TWO width rung at or
    above the live lane count (2, 4, 8, ...; ``width`` is only a floor
    for the rung ladder's cap semantics at the caller): vacancies
    within a rung are padded with parked copies of the lead lane
    (``stop == i0`` — frozen passthrough, results discarded).  The
    rung ladder keeps the compile ledger bounded — at most log2(K)
    programs per signature, each warm after its first use across
    retire/refill churn — while a pair of live lanes costs a 2-wide
    program, not a K-wide one (on a SIMD-saturated host, padded lanes
    are not free).  Callers must pre-group by ``ChunkCall.signature()``;
    mixed-signature packs are a usage error (jnp.stack would throw on
    shape mismatch)."""
    W = 2
    while W < len(calls):
        W *= 2
    cols = list(zip(*[c.args for c in calls]))
    pad = W - len(calls)
    if pad:
        lead = calls[0].args
        for _ in range(pad):
            for j in range(len(cols)):
                # parked lane: lead's buffers with stop pinned to i0
                cols[j] = cols[j] + (lead[4] if j == 5 else lead[j],)
    packed = [slab_pack(list(col)) for col in cols]
    lead_call = calls[0]
    static_kwargs = dict(lead_call.static_kwargs)
    _timings: dict = timings if timings is not None else {}
    compiled = _resolve_program(_run_fit_chunk_slab, f"slab{W}",
                                lead_call.loss_fn, tuple(packed), {},
                                static_kwargs, _timings)
    if compiled is not None:
        out = compiled(*packed)
    else:
        out = _run_fit_chunk_slab(lead_call.loss_fn, *packed,
                                  **static_kwargs)
    i_o, params_o, opt_o, losses_o, diag_o, conv_o, nan_o = out
    return [(i_o[b], slab_block(params_o, b), slab_block(opt_o, b),
             losses_o[b], diag_o[b], conv_o[b], nan_o[b])
            for b in range(len(calls))]


def make_opt_state(params: dict, learning_rate: float = 0.05,
                   b1: float = 0.8, b2: float = 0.99,
                   moment_dtype: str = "float32"):
    """Fresh Adam state for ``params`` — also the treedef donor when
    restoring a checkpointed state from flat leaves (the treedef is
    dtype-independent, so the donor role never needs the dtype).

    ``moment_dtype='bfloat16'`` stores the big pi parameter's m/v
    moments in bfloat16 (PertConfig.optimizer_state_dtype): half the
    optimizer-state HBM traffic and residency for the one parameter
    that dominates both.  The arithmetic stays float32 — see
    ops/adam_kernel.py."""
    state = optax.adam(learning_rate=learning_rate, b1=b1,
                       b2=b2).init(params)
    if moment_dtype != "float32":
        dt = _adam_kernel.moment_jnp_dtype(moment_dtype)
        pi = _pi_param_name(params) if isinstance(params, dict) else None
        if pi is not None:
            inner = state[0]
            mu = dict(inner.mu)
            nu = dict(inner.nu)
            mu[pi] = mu[pi].astype(dt)
            nu[pi] = nu[pi].astype(dt)
            state = (inner._replace(mu=mu, nu=nu),) + tuple(state[1:])
    return state


# ---------------------------------------------------------------------------
# AOT program cache: dedupe trace+compile across fits
# ---------------------------------------------------------------------------
#
# jax.jit's own cache keys on the loss callable's *identity*, so two fits
# whose programs are identical (same spec, same shapes/dtypes/shardings)
# still retrace and recompile when the caller builds a fresh loss closure
# each time.  The runner now passes value-hashable loss callables
# (runner._PertLossFn) and this cache keys on (loss value, optimiser
# statics, abstract signature of every dynamic argument) — equal programs
# are compiled ONCE per process, and the explicit lower()/compile() split
# also yields the trace/compile phase timings the orchestration layer
# reports.  With the persistent compilation cache enabled (see
# utils.profiling.enable_persistent_compile_cache), the compile() half is
# served from disk across processes too; with the persistent EXECUTABLE
# store activated (infer/aotcache.py, PertConfig.executable_cache_dir) a
# cold process skips trace+lower+compile entirely and deserializes the
# finished executable — the ``cache="disk_hit"`` telemetry arm.

_PROGRAM_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_PROGRAM_CACHE_MAX = 32
# dict ops only (get/move_to_end/insert/evict); compilation runs
# unlocked but DEDUPED: a cold miss registers a per-key in-flight event
# in _PROGRAM_INFLIGHT under this lock, concurrent same-key misses wait
# on it and re-read the cache instead of racing XLA (the batched
# serving worker dispatches fits from concurrent block threads — the
# old both-compile race wasted a full compile AND would write the disk
# artifact twice).  A failed leader wakes followers with no cache
# entry; each retries as leader itself.
_PROGRAM_CACHE_LOCK = threading.Lock()
_PROGRAM_INFLIGHT: dict = {}


def _leaf_sig(leaf):
    return (getattr(leaf, "shape", None), str(getattr(leaf, "dtype", None)),
            getattr(leaf, "weak_type", None), getattr(leaf, "sharding", None))


def _abstract_sig(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef, tuple(_leaf_sig(leaf) for leaf in leaves))


def clear_program_cache() -> None:
    """Drop the in-process compiled-program cache (test seam)."""
    _PROGRAM_CACHE.clear()


def _key_hash(key) -> str:
    """Cross-process-comparable content hash of a program-cache key,
    for the telemetry ``compile`` events: hashed over the SAME
    canonical serialization the disk store digests (memory addresses
    scrubbed), so compile events from different workers/hosts
    correlate in pert_trace waterfalls."""
    import hashlib

    text = _aotcache.canonical_key_text(key)
    return hashlib.sha256(text.encode()).hexdigest()[:12]


def _resolve_program(target, tag: str, loss_fn, dynamic_args,
                     dynamic_kwargs: dict, static_kwargs: dict,
                     timings: dict, compile_deadline=None):
    """Compiled program of ``target`` for this signature, timed on miss.

    Shared by the whole-budget program (``_run_fit``) and the
    controller's chunk program (``_run_fit_chunk``); ``tag`` keeps their
    cache keys apart.  Entries in ``dynamic_kwargs`` are DYNAMIC scalars
    passed by keyword at lowering time — the compiled program is
    reusable across their values, and the caller must invoke the result
    as ``compiled(*dynamic_args, **dynamic_kwargs)`` to match the
    lowered pytree.

    Every resolution emits a telemetry ``compile`` event to the active
    RunLog (no-op outside a session): content hash, hit/miss/disk_hit,
    trace/compile (or deserialize) seconds, plus the program's
    cost_analysis FLOPs and memory_analysis footprint (cached alongside
    the program so warm runs still report their memory high-water).

    Cold misses are deduped per key: the first thread to miss becomes
    the compile leader, concurrent same-key misses wait and then read
    the cache (one XLA invocation, one disk artifact).  Before XLA the
    leader probes the persistent executable store (infer/aotcache.py,
    when activated) — a disk hit deserializes instead of compiling."""
    try:
        key = (tag, loss_fn, tuple(sorted(static_kwargs.items())),
               _abstract_sig((dynamic_args, dynamic_kwargs)))
        hash(key)
    except TypeError:
        _runlog.current().emit("compile", key_hash="unhashable",
                               label=type(loss_fn).__name__, tag=tag,
                               cache="uncacheable")
        return None  # unhashable loss callable/sharding: fall back
    while True:
        with _PROGRAM_CACHE_LOCK:
            cached = _PROGRAM_CACHE.get(key)
            if cached is not None:
                _PROGRAM_CACHE.move_to_end(key)
                inflight, leader = None, False
            else:
                inflight = _PROGRAM_INFLIGHT.get(key)
                leader = inflight is None
                if leader:
                    inflight = threading.Event()
                    _PROGRAM_INFLIGHT[key] = inflight
        if cached is not None:
            timings["program_cache"] = "hit"
            compiled, stats = cached
            if stats.get("flops"):
                timings["flops"] = stats["flops"]
            _runlog.current().emit("compile", key_hash=_key_hash(key),
                                   label=type(loss_fn).__name__, tag=tag,
                                   cache="hit",
                                   trace_seconds=0.0, compile_seconds=0.0,
                                   **stats)
            return compiled
        if leader:
            break
        # follower: the leader is compiling this exact key — wait, then
        # re-read the cache (a dead leader leaves no entry; retry as
        # leader ourselves)
        inflight.wait()
    try:
        store = _aotcache.active_store()
        ktext = digest = None
        if store is not None:
            ktext = _aotcache.canonical_key_text(key)
            digest = _aotcache.key_digest(ktext)
            loaded = store.load(digest)
            if loaded is not None:
                compiled, stats, deser = loaded
                timings["program_cache"] = "disk_hit"
                timings["deserialize"] = deser
                if stats.get("flops"):
                    timings["flops"] = stats["flops"]
                ledger = _meter.ledger_of(_runlog.current())
                if ledger is not None:
                    ledger.book_compile(seconds=deser, deserialize=True)
                _runlog.current().emit(
                    "compile", key_hash=_key_hash(key),
                    label=type(loss_fn).__name__, tag=tag,
                    cache="disk_hit",
                    deserialize_seconds=round(deser, 4),
                    trace_seconds=0.0, compile_seconds=0.0, **stats)
                with _PROGRAM_CACHE_LOCK:
                    _PROGRAM_CACHE[key] = (compiled, stats)
                    while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
                        _PROGRAM_CACHE.popitem(last=False)
                return compiled
        t0 = time.perf_counter()
        lowered = target.lower(loss_fn, *dynamic_args, **dynamic_kwargs,
                               **static_kwargs)
        t1 = time.perf_counter()

        # per-phase watchdog: an XLA compile over a dead TPU tunnel
        # blocks forever with ~0 CPU (the BENCH_r05 rc=124 failure
        # mode); the deadline converts that into a typed,
        # checkpointable abort.  The fault-injection site sits INSIDE
        # the deadline so a simulated `hang@compile` exercises the real
        # watchdog path.
        def _do_compile():
            _faults.point("compile")
            return lowered.compile()

        compiled = _faults.run_with_deadline(
            _do_compile, compile_deadline, f"compile:{tag}")
        t2 = time.perf_counter()
        timings["trace"] = t1 - t0
        timings["compile"] = t2 - t1
        timings["program_cache"] = "miss"
        stats = _runlog.compiled_program_stats(compiled)
        if stats.get("flops"):
            timings["flops"] = stats["flops"]
        ledger = _meter.ledger_of(_runlog.current())
        if ledger is not None:
            ledger.book_compile(seconds=t2 - t0)
        extra = {"aot_disk": "miss"} if store is not None else {}
        _runlog.current().emit("compile", key_hash=_key_hash(key),
                               label=type(loss_fn).__name__, tag=tag,
                               cache="miss",
                               trace_seconds=round(t1 - t0, 4),
                               compile_seconds=round(t2 - t1, 4),
                               **extra, **stats)
        with _PROGRAM_CACHE_LOCK:
            _PROGRAM_CACHE[key] = (compiled, stats)
            while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
                _PROGRAM_CACHE.popitem(last=False)
        if store is not None:
            meta = {"tag": tag,
                    "label": type(loss_fn).__name__,
                    "key_hash": _key_hash(key),
                    "shapes": _aotcache.signature_shapes(key)}
            landed, why = store.save(digest, ktext, compiled, stats,
                                     meta=meta)
            if not landed and why == "unloadable":
                # The executable XLA revived from its persistent
                # COMPILATION cache does not survive serialize/
                # deserialize (dangling fusion symbols on XLA:CPU) —
                # recompile once to get a payload that round-trips,
                # and keep serving from the original.  Two layers of
                # reuse must be sidestepped: jax memoizes its
                # cache-enabled decision per process (is_cache_used),
                # so the ``enable_compilation_cache`` config toggle is
                # inert after the first compile — the memo itself is
                # flipped (under jax's own mutex) so the retry goes
                # straight to backend_compile, no cache read OR write;
                # and a bare re-``compile()`` would return the SAME
                # revived executable from jax's in-memory layer, so
                # the retry passes an explicitly-default compiler
                # option (a codegen no-op that changes the in-memory
                # key).  Best-effort: any failure — including these
                # private attrs moving in a future jax — just leaves
                # this program un-stored.
                try:
                    from jax._src import compilation_cache as _jcc
                    with _jcc._cache_initialized_mutex:
                        prev = (_jcc._cache_checked, _jcc._cache_used)
                        _jcc._cache_checked, _jcc._cache_used = True, False
                    try:
                        fresh = lowered.compile(compiler_options={
                            "xla_embed_ir_in_executable": False})
                    finally:
                        with _jcc._cache_initialized_mutex:
                            _jcc._cache_checked, _jcc._cache_used = prev
                    store.save(digest, ktext, fresh, stats, meta=meta)
                except Exception as exc:  # noqa: BLE001
                    _aotcache.logger.debug(
                        "aotcache: cache-bypassed recompile for %s "
                        "failed (program stays un-stored): %s",
                        digest, exc)
        return compiled
    finally:
        with _PROGRAM_CACHE_LOCK:
            _PROGRAM_INFLIGHT.pop(key, None)
        inflight.set()


def resolve_jit_program(target, tag: str, head, dynamic_args,
                        static_kwargs: Optional[dict] = None):
    """AOT-resolve an auxiliary jitted entry point (decode/PPC slabs)
    through the same machinery as the fit programs: in-process LRU,
    in-flight compile dedup, the persistent executable store, and the
    telemetry ``compile`` event stream.

    ``head`` is the entry point's leading (static) argument — the model
    spec for the slab programs, playing the role ``loss_fn`` plays for
    the fit programs: part of the cache key, first operand of
    ``target.lower``.  Returns the compiled program — invoke it as
    ``compiled(*dynamic_args)`` (static args are bound at lowering
    time) — or None when the key is unhashable; callers fall back to
    the plain jit call, which behaves identically minus the store.

    Without this, only the fit programs survived a process restart:
    the restarted serve worker's first request paid ZERO fit compiles
    but still multi-second traces for decode/PPC — the long pole of
    the cold-start A/B (``bench.py --serve-ab --restart``)."""
    return _resolve_program(target, tag, head, tuple(dynamic_args),
                            {}, dict(static_kwargs or {}), {})


def fit_map(loss_fn: Callable, params0: dict, loss_args: tuple = (),
            max_iter: int = 2000, min_iter: int = 100, rel_tol: float = 1e-6,
            learning_rate: float = 0.05, b1: float = 0.8, b2: float = 0.99,
            opt_state0=None, losses_prefix: Optional[np.ndarray] = None,
            diag_every: int = 0,
            doctor_thresholds: Optional[dict] = None,
            controller=None, escalate_dir: Optional[str] = None,
            escalate_tag: str = "fit",
            checkpoint_every: int = 0, checkpoint_cb=None,
            resume_state: Optional[dict] = None,
            compile_deadline: Optional[float] = None,
            chunk_deadline: Optional[float] = None,
            fused_adam: str = "off", moment_dtype: str = "float32",
            ) -> FitResult:
    """Fit ``params`` by MAP ascent of ``-loss_fn`` with reference semantics.

    ``loss_fn(params, *loss_args) -> scalar loss`` must be jit-traceable.
    When ``loss_fn`` is hashable by VALUE (e.g. a frozen dataclass), fits
    with identical programs share one trace+compile via the AOT program
    cache; closures/lambdas still work but only dedupe by identity.

    The ``params0``/``opt_state0`` pytrees (and the internal loss buffer)
    are DONATED to the compiled program — do not reuse those exact arrays
    after calling; ``FitResult.params``/``opt_state`` are the live
    outputs.  Exception: on the resume path (``opt_state0`` given) the
    inputs are defensively copied first, so a prior FitResult stays
    usable after resuming from it.

    Resume: pass the ``opt_state`` of a previous partial FitResult plus
    its ``losses`` as ``losses_prefix`` — optimisation continues from
    iteration ``len(losses_prefix)`` with Adam moments intact, so an
    interrupted fit reproduces the uninterrupted trajectory exactly (the
    loop is deterministic given params + opt state + loss history).

    ``diag_every=K`` (0 = off) samples loss + global grad/param norms
    every K iterations into an on-device ring buffer of ``DIAG_RING``
    slots — no host sync during the loop, fetched once post-fit and
    surfaced as ``FitResult.diagnostics`` (the last <=DIAG_RING samples
    of the run).  The extra reductions run only on sampled iterations
    (a compiled conditional), so the steady-state iteration cost is
    unchanged; K is a static of the compiled program.

    Every fit is also run through the convergence doctor
    (obs/doctor.py): ``FitResult.verdict`` classifies the loss tail
    (converged / plateaued / oscillating / diverging / unknown) with the
    full report on ``FitResult.health``; ``doctor_thresholds`` overrides
    the doctor's window/slope_tol/var_tol/grad_ratio defaults (the
    runner passes ``PertConfig``'s).  Host-side on the already-fetched
    loss history — adds no device work.

    ``controller`` (an ``obs.controller.ControllerPolicy``; requires
    ``diag_every > 0``) switches the single whole-budget
    ``lax.while_loop`` for an outer host loop over jit-compiled
    fixed-size chunks of ``diag_every`` iterations — ONE compiled
    program reused for every chunk — and between chunks evaluates the
    flight-recorder signals: a doctor-``converged`` partial tail
    early-stops the fit (reclaiming the remaining budget), a
    ``plateaued`` fit at exhaustion is granted extra iterations, an
    ``oscillating`` one is re-seeded from the best-loss checkpoint, and
    a NaN-poisoned chunk escalates through a checkpoint save
    (``escalate_dir``/``escalate_tag``) plus one reduced-LR retry before
    aborting.  The audit trail lands on ``FitResult.decisions``.
    ``controller=None`` (the default) keeps the original single-program
    path bit-exactly.

    Durability (chunked path only — see OBSERVABILITY.md "Durable
    runs"): ``checkpoint_every=N`` calls ``checkpoint_cb`` every N
    completed chunks (and, best-effort, on any exception escaping the
    loop) with the host state needed for an exact mid-fit resume —
    params, opt state, loss prefix and the controller's own ledger;
    ``resume_state`` (from ``checkpoint.restore_controller_state``)
    restores that ledger so a resumed fit reproduces the uninterrupted
    decision trail bit-exactly.  ``compile_deadline``/``chunk_deadline``
    arm the per-phase watchdog (``utils.faults.run_with_deadline``),
    turning hangs into typed, checkpointed aborts.

    ``fused_adam`` (already resolved: 'off'/'xla'/'pallas'/
    'pallas_interpret') routes the big pi parameter's Adam update
    through the single-sweep fused kernel (ops/adam_kernel.py) instead
    of the stock optax chain; ``moment_dtype`` ('float32'/'bfloat16')
    selects the STORED dtype of that parameter's m/v moments (bfloat16
    implies at least the XLA fused update — optax would widen the
    carry).  'off' + 'float32' (the defaults) reproduce the previous
    optax path bit-exactly.
    """
    fused_adam = _effective_fused_adam(str(fused_adam), str(moment_dtype))
    if controller is not None and diag_every:
        return _fit_map_controlled(
            loss_fn, params0, loss_args, max_iter=max_iter,
            min_iter=min_iter, rel_tol=rel_tol,
            learning_rate=learning_rate, b1=b1, b2=b2,
            opt_state0=opt_state0, losses_prefix=losses_prefix,
            diag_every=diag_every, doctor_thresholds=doctor_thresholds,
            policy=controller, escalate_dir=escalate_dir,
            escalate_tag=escalate_tag,
            checkpoint_every=checkpoint_every, checkpoint_cb=checkpoint_cb,
            resume_state=resume_state, compile_deadline=compile_deadline,
            chunk_deadline=chunk_deadline,
            fused_adam=fused_adam, moment_dtype=moment_dtype)
    if opt_state0 is None:
        params0 = jax.tree_util.tree_map(jnp.asarray, params0)
        opt_state0 = make_opt_state(params0, learning_rate, b1, b2,
                                    moment_dtype=moment_dtype)
    else:
        # resume path: the caller is handing over a previous FitResult's
        # LIVE params/opt_state.  jnp.asarray would alias them, donation
        # would then delete the caller's buffers, and any reuse (retry
        # after a transient failure, checkpointing the partial fit)
        # would hit "Array has been deleted" — copy instead.  Resumes
        # are rare (checkpoint restarts), so the one extra copy does not
        # erode the donation win on the hot fresh-fit path.
        copy = functools.partial(jnp.array, copy=True)
        params0 = jax.tree_util.tree_map(copy, params0)
        opt_state0 = jax.tree_util.tree_map(copy, opt_state0)
    i0 = 0
    losses0 = jnp.zeros((max_iter,), jnp.float32)
    if losses_prefix is not None and len(losses_prefix) > 0:
        i0 = min(int(len(losses_prefix)), int(max_iter))
        losses0 = losses0.at[:i0].set(
            jnp.asarray(losses_prefix[:i0], jnp.float32))
    i0_host = int(i0)
    i0 = jnp.asarray(i0, jnp.int32)

    diag_every = int(diag_every)
    # shape (0, 3) when disabled: the carry keeps one uniform pytree
    # structure and the static diag_every branch removes every diag op
    diag0 = jnp.zeros((DIAG_RING if diag_every else 0, 3), jnp.float32)

    rel_tol = float(rel_tol)
    static_kwargs = dict(max_iter=int(max_iter), min_iter=int(min_iter),
                         lr=float(learning_rate), b1=float(b1),
                         b2=float(b2), diag_every=diag_every,
                         fused_adam=fused_adam, moment_dtype=moment_dtype)
    dynamic_args = (params0, opt_state0, losses0, diag0, i0, loss_args)
    timings: dict = {"trace": 0.0, "compile": 0.0}
    compiled = _resolve_program(_run_fit, "fit", loss_fn, dynamic_args,
                                {"rel_tol": rel_tol}, static_kwargs,
                                timings, compile_deadline=compile_deadline)

    t0 = time.perf_counter()
    if compiled is not None:
        out = compiled(*dynamic_args, rel_tol=rel_tol)
    else:
        timings["program_cache"] = "uncacheable"
        out = _run_fit(loss_fn, *dynamic_args, rel_tol=rel_tol,
                       **static_kwargs)
    i, params, opt_state, losses, diag, converged, is_nan = out
    n = int(i)
    losses_host = np.asarray(losses)[:n]
    diagnostics = None
    if diag_every:
        diagnostics = _decode_diag(np.asarray(diag), n, i0_host, diag_every)
    timings["fit"] = time.perf_counter() - t0
    ledger = _meter.ledger_of(_runlog.current())
    if ledger is not None:
        ledger.book_chunk(entry_it=i0_host, end_it=n,
                          wall_seconds=timings["fit"],
                          flops=float(timings.get("flops") or 0.0))
    health = _diagnose(losses_host, bool(converged), bool(is_nan),
                       diagnostics, doctor_thresholds)
    return FitResult(
        params=params,
        losses=losses_host,
        num_iters=n,
        converged=bool(converged),
        nan_abort=bool(is_nan),
        opt_state=opt_state,
        timings=timings,
        diagnostics=diagnostics,
        verdict=health["verdict"],
        health=health,
        budget=int(max_iter),
    )


def _perturb_params(params: dict, scale: float, seed: int, salt: int):
    """Deterministic re-seed perturbation around a checkpointed pytree.

    Per-leaf relative scale (``scale * (std(leaf) + 1e-3)``) so flat and
    wide leaves both move; keyed by (seed, salt) so the same run always
    re-seeds identically — the decision trail must be reproducible.
    On-device ops, so sharded params stay sharded.
    """
    key = jax.random.fold_in(jax.random.PRNGKey(int(seed)), int(salt))
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(key, max(len(leaves), 1))
    out = []
    for leaf, k in zip(leaves, keys):
        leaf = jnp.asarray(leaf)
        sigma = scale * (jnp.std(leaf) + 1e-3)
        out.append(leaf + sigma * jax.random.normal(k, leaf.shape,
                                                    leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


def _fit_map_controlled(loss_fn: Callable, params0: dict, loss_args: tuple,
                        max_iter: int, min_iter: int, rel_tol: float,
                        learning_rate: float, b1: float, b2: float,
                        opt_state0, losses_prefix, diag_every: int,
                        doctor_thresholds: Optional[dict], policy,
                        escalate_dir: Optional[str],
                        escalate_tag: str,
                        checkpoint_every: int = 0, checkpoint_cb=None,
                        resume_state: Optional[dict] = None,
                        compile_deadline: Optional[float] = None,
                        chunk_deadline: Optional[float] = None,
                        fused_adam: str = "off",
                        moment_dtype: str = "float32"
                        ) -> FitResult:
    """Adaptive (chunked) twin of :func:`fit_map` — see its docstring.

    The outer loop runs on the host; each chunk is one dispatch of the
    single compiled ``_run_fit_chunk`` program (``diag_every``
    iterations, or fewer at a budget edge).  Between chunks the
    controller policy (obs/controller.py) reads the fetched loss
    trajectory + the diagnostics ring-buffer tail and issues decisions;
    this function applies them to the device state and records the
    audit trail on ``FitResult.decisions``.

    The chunk boundaries are also the durability points: the
    fault-injection site ``{escalate_tag}/chunk`` fires at the top of
    every iteration of the host loop (where params, opt state and the
    loss buffer are all live chunk OUTPUTS — a graceful save there is
    exact), ``checkpoint_cb`` runs every ``checkpoint_every`` completed
    chunks and best-effort on the way out of any escaping exception,
    and ``chunk_deadline`` bounds each dispatch+fetch.
    """
    from scdna_replication_tools_tpu.obs import controller as _controller

    max_iter = int(max_iter)
    min_iter = int(min_iter)
    diag_every = int(diag_every)
    resume_state = dict(resume_state or {})
    # the loss buffer must hold the larger of the configured and any
    # resumed (already-extended) budget PLUS the full extension
    # headroom — the controller may still grant up to max_extra_iters
    # beyond whichever budget the resumed fit continues under
    buf_len = max(max_iter, int(resume_state.get("budget", 0))) \
        + max(int(policy.max_extra_iters), 0)

    if opt_state0 is None:
        params0 = jax.tree_util.tree_map(jnp.asarray, params0)
        opt_state0 = make_opt_state(params0, learning_rate, b1, b2,
                                    moment_dtype=moment_dtype)
    else:
        # resume path: copy before the chunk program donates (see
        # fit_map's fixed path — same contract)
        copy = functools.partial(jnp.array, copy=True)
        params0 = jax.tree_util.tree_map(copy, params0)
        opt_state0 = jax.tree_util.tree_map(copy, opt_state0)
    i0_host = 0
    losses = jnp.zeros((buf_len,), jnp.float32)
    if losses_prefix is not None and len(losses_prefix) > 0:
        i0_host = min(int(len(losses_prefix)), buf_len)
        losses = losses.at[:i0_host].set(
            jnp.asarray(losses_prefix[:i0_host], jnp.float32))
    diag = jnp.zeros((DIAG_RING, 3), jnp.float32)
    # diag_i0 anchors the ring's slot->iteration mapping: 0 for a fresh
    # fit, and for a resumed fit WITH a restored ring still the original
    # fit's start — the restored slots cover the pre-resume samples, so
    # the doctor reads the same window an uninterrupted run would
    diag_i0 = i0_host
    if resume_state.get("diag") is not None:
        diag = jnp.asarray(np.asarray(resume_state["diag"], np.float32))
        diag_i0 = int(resume_state.get("diag_i0", 0))

    static_kwargs = dict(conv_window=min(9, max_iter), b1=float(b1),
                         b2=float(b2), diag_every=diag_every,
                         fused_adam=fused_adam, moment_dtype=moment_dtype)
    # dynamic scalars with pinned dtypes so every chunk hits the same
    # compiled program
    as_i32 = functools.partial(jnp.asarray, dtype=jnp.int32)
    as_f32 = functools.partial(jnp.asarray, dtype=jnp.float32)
    rel_tol_arr = as_f32(float(rel_tol))
    min_iter_arr = as_i32(min_iter)
    lr_now = float(resume_state.get("lr", learning_rate))

    timings: dict = {"trace": 0.0, "compile": 0.0}
    probe_args = (params0, opt_state0, losses, diag, as_i32(i0_host),
                  as_i32(min(i0_host + diag_every, max_iter)),
                  min_iter_arr, rel_tol_arr, as_f32(lr_now), loss_args)
    compiled = _resolve_program(_run_fit_chunk, "chunk", loss_fn,
                                probe_args, {}, static_kwargs, timings,
                                compile_deadline=compile_deadline)

    def run_solo(args):
        if compiled is not None:
            return compiled(*args)
        return _run_fit_chunk(loss_fn, *args, **static_kwargs)

    # captured ONCE per fit: the dispatcher seam is thread-local and the
    # chunk loop must not change engines mid-fit.  Same for the cost
    # ledger (it rides the thread-local RunLog).
    dispatcher = get_chunk_dispatcher()
    ledger = _meter.ledger_of(_runlog.current())
    chunk_flops = float(timings.get("flops") or 0.0)

    def run_chunk(params, opt_state, losses, diag, i_host, stop_host,
                  lr_val):
        args = (params, opt_state, losses, diag, as_i32(i_host),
                as_i32(stop_host), min_iter_arr, rel_tol_arr,
                as_f32(lr_val), loss_args)
        if dispatcher is not None:
            meter = (ledger, ledger.ctx_snapshot()) \
                if ledger is not None else None
            return dispatcher.dispatch(ChunkCall(
                loss_fn=loss_fn, args=args, static_kwargs=static_kwargs,
                solo=run_solo, meter=meter))
        return run_solo(args)

    # solo mode books its own chunks from inside the loop; in slab mode
    # the coordinator books instead (the lane's wall includes rendezvous
    # wait — only the leader sees the true dispatch wall and each
    # lane's 1/W share of it)
    book_chunk = None
    if dispatcher is None and ledger is not None:
        def book_chunk(entry_it, end_it, wall_seconds):
            ledger.book_chunk(entry_it=int(entry_it), end_it=int(end_it),
                              wall_seconds=float(wall_seconds),
                              flops=chunk_flops)

    params, opt_state = params0, opt_state0
    i_host = i0_host
    budget = int(resume_state.get("budget", max_iter))
    decisions: list = []
    reseeds = int(resume_state.get("reseeds", 0))
    extra_granted = int(resume_state.get("extra_granted", 0))
    nan_retries = int(resume_state.get("nan_retries", 0))
    converged_flag = nan_flag = False
    best_loss = float(resume_state.get("best_loss", float("inf")))
    best_it = int(resume_state.get("best_it", i0_host))
    best_params = params0
    if resume_state.get("best_params") is not None:
        best_params = jax.tree_util.tree_map(
            jnp.asarray, dict(resume_state["best_params"]))
    prev_verdict = resume_state.get("prev_verdict") or None
    # iteration the current trajectory regime began at: 0 for a fresh
    # or resumed fit (a resume continues the same trajectory), bumped
    # by reseed / NaN retry so the stagnation stop measures the
    # restarted segment on its own terms
    stagnation_anchor = int(resume_state.get("stagnation_anchor", 0))

    fault_site = f"{escalate_tag}/chunk"
    # live-state snapshot for the emergency save: seeded with the entry
    # state so even a first-chunk abort leaves resumable state — for a
    # RESUMED fit that includes the restored loss prefix (an abort
    # before the first fetch must not supersede the iteration-N
    # checkpoint with a zero-iteration one)
    entry_losses = None
    if losses_prefix is not None and i0_host:
        entry_losses = np.asarray(losses_prefix[:i0_host], np.float32)
    snap: dict = dict(
        params=params, opt_state=opt_state, losses_np=entry_losses,
        i_host=i_host, best_params=best_params, best_it=best_it,
        best_loss=best_loss, diag=diag, diag_i0=diag_i0,
        reseeds=reseeds, extra_granted=extra_granted,
        nan_retries=nan_retries, lr=lr_now, budget=budget,
        stagnation_anchor=stagnation_anchor, prev_verdict=prev_verdict)

    t0 = time.perf_counter()
    # bracket the whole chunk loop so the dispatcher's barrier knows how
    # many threads are actively fitting (vs in host-side pipeline work)
    if dispatcher is not None:
        dispatcher.fit_begin()
    try:
        (i_host, params, opt_state, losses, diag, losses_np,
         converged_flag, nan_flag, budget, decisions, best_loss,
         best_params, best_it, lr_now, reseeds, extra_granted,
         nan_retries, prev_verdict, stagnation_anchor) = _chunk_loop(
            run_chunk=run_chunk, params=params, opt_state=opt_state,
            losses=losses, diag=diag, i_host=i_host, budget=budget,
            lr_now=lr_now, policy=policy, min_iter=min_iter,
            diag_every=diag_every, diag_i0=diag_i0, b1=b1, b2=b2,
            escalate_dir=escalate_dir, escalate_tag=escalate_tag,
            fault_site=fault_site, chunk_deadline=chunk_deadline,
            checkpoint_every=checkpoint_every,
            checkpoint_cb=checkpoint_cb, moment_dtype=moment_dtype,
            decisions=decisions, best_loss=best_loss,
            best_params=best_params, best_it=best_it, reseeds=reseeds,
            extra_granted=extra_granted, nan_retries=nan_retries,
            prev_verdict=prev_verdict,
            stagnation_anchor=stagnation_anchor, snap=snap,
            book_chunk=book_chunk)
    except BaseException:
        _emergency_save(checkpoint_cb, snap)
        raise
    finally:
        if dispatcher is not None:
            dispatcher.fit_end()

    n = i_host
    losses_host = losses_np[:n] if losses_np is not None \
        else np.asarray(losses)[:n]
    diagnostics = _decode_diag(np.asarray(diag), n, diag_i0, diag_every)
    timings["fit"] = time.perf_counter() - t0
    health = _diagnose(losses_host, converged_flag, nan_flag,
                       diagnostics, doctor_thresholds)
    return FitResult(
        params=params,
        losses=losses_host,
        num_iters=n,
        converged=converged_flag,
        nan_abort=nan_flag,
        opt_state=opt_state,
        timings=timings,
        diagnostics=diagnostics,
        verdict=health["verdict"],
        health=health,
        decisions=decisions,
        budget=int(budget),
    )


def _np_tree_or_none(tree):
    """Host-transferable view of a pytree, or None when any leaf was
    donated away (mid-chunk failure: the consumed carries are gone).
    Multi-host global arrays pass through as jax.Arrays for the
    checkpoint writer to gather per host (checkpoint.host_view)."""
    if tree is None:
        return None
    from scdna_replication_tools_tpu.infer import checkpoint as _ckpt

    try:
        return _ckpt.host_view(tree)
    except Exception:  # pertlint: disable=PL011 — this IS the
        # deleted-buffer probe: None is the answer ("donated away"),
        # which the caller reports via the inexact_checkpoint event
        return None


def _emergency_save(checkpoint_cb, snap: dict) -> None:
    """Best-effort resumable save on the way out of an escaping
    exception, from the chunk loop's live-state snapshot.  At the top
    of the host loop every carry is a live chunk OUTPUT, so a graceful
    preemption saves exact state; after a mid-chunk failure the donated
    carries are gone and the save degrades to params + loss prefix
    (resume restarts the Adam moments there — the documented rescue
    tolerance, reported via ``exact=False``)."""
    if checkpoint_cb is None or not snap:
        return
    try:
        p_np = _np_tree_or_none(snap.get("params"))
        o_np = _np_tree_or_none(snap.get("opt_state"))
        i_host = int(snap.get("i_host", 0))
        best_it = int(snap.get("best_it", 0))
        l = snap.get("losses_np")
        l_np = np.asarray(l[:i_host]) if l is not None \
            else np.zeros(0, np.float32)
        if p_np is None:
            p_np, o_np = _np_tree_or_none(snap.get("best_params")), None
            l_np = l_np[:best_it]
        if p_np is None:
            return
        diag_np = _np_tree_or_none(snap.get("diag"))
        state = {
            "reseeds": int(snap.get("reseeds", 0)),
            "extra_granted": int(snap.get("extra_granted", 0)),
            "nan_retries": int(snap.get("nan_retries", 0)),
            "lr": float(snap.get("lr", 0.0)),
            "budget": int(snap.get("budget", 0)),
            "stagnation_anchor": int(snap.get("stagnation_anchor", 0)),
            "prev_verdict": snap.get("prev_verdict"),
            "best_loss": float(snap.get("best_loss", float("inf"))),
            "best_it": best_it,
            "best_params": _np_tree_or_none(snap.get("best_params")),
            # a donated ring degrades to an empty one anchored at the
            # resume point: the doctor then reads only the new segment
            "diag": diag_np if diag_np is not None
            else np.zeros((DIAG_RING, 3), np.float32),
            "diag_i0": int(snap.get("diag_i0", 0))
            if diag_np is not None else int(len(l_np)),
        }
        # coordinated=False: a dying process must not enter the
        # two-phase commit's barriers (its peers may be mid-chunk or
        # already gone) — multi-process emergency saves write only this
        # host's phase-1 shard; single-process saves are unaffected
        checkpoint_cb(params=p_np, opt_state=o_np, losses=l_np,
                      num_iters=int(len(l_np)), state=state,
                      exact=o_np is not None, coordinated=False)
    except Exception as exc:  # noqa: BLE001 — the original abort must
        # surface, not a failed rescue save
        from scdna_replication_tools_tpu.utils.profiling import logger

        logger.warning("emergency checkpoint save failed: %s", exc)


def _chunk_loop(*, run_chunk, params, opt_state, losses, diag, i_host,
                budget, lr_now, policy, min_iter, diag_every, diag_i0,
                b1, b2, escalate_dir, escalate_tag, fault_site,
                chunk_deadline, checkpoint_every, checkpoint_cb,
                decisions, best_loss, best_params,
                best_it, reseeds, extra_granted, nan_retries,
                prev_verdict, stagnation_anchor, snap: dict,
                moment_dtype: str = "float32", book_chunk=None):
    """The host-side chunk loop of :func:`_fit_map_controlled`.

    ``snap`` is the caller-owned live-state snapshot: refreshed with
    plain reference assignments at the top of every pass (so it is
    always current and costs nothing), consumed by
    :func:`_emergency_save` when an exception escapes this loop.
    Returns the full loop state so the caller packages the FitResult.
    """
    from scdna_replication_tools_tpu.obs import controller as _controller

    losses_np = None
    chunks_done = 0
    converged_flag = nan_flag = False

    # causal span per dispatched chunk (obs/spans.py): the tracer rides
    # the active RunLog — the same seam the compile events use — so the
    # chunk loop needs no plumbing; None (tracing off) costs one
    # attribute read per fit
    tracer = getattr(_runlog.current(), "tracer", None)
    chunk_t0 = chunk_t1 = 0.0

    def _chunk_span(entry_it, i_now, action, verdict=None):
        """One completed fit/chunk span carrying the controller's
        verdict for the pass; everything but the wall-clock interval is
        deterministic content.  Every chunk outcome path calls this
        exactly once, so it is also the heartbeat pump site: progress,
        the ms/iter EWMA sample and the verdict trail ride the
        process-global seam (a no-op when heartbeats are off), on EVERY
        rank — unlike the RunLog, which rank 0 alone writes."""
        _heartbeat.note_chunk(
            step=escalate_tag, chunk=chunks_done, iteration=int(i_now),
            budget=int(budget), wall_seconds=chunk_t1 - chunk_t0,
            iters=int(i_now) - int(entry_it), action=str(action),
            verdict=verdict)
        if book_chunk is not None:
            # solo-mode cost booking rides the same once-per-outcome
            # site as the heartbeat; a NaN rewind passes i_now < the
            # step's high-water, which the ledger books as retry_refit
            book_chunk(entry_it, i_now, chunk_t1 - chunk_t0)
        if tracer is None:
            return
        attrs = dict(chunk=chunks_done, iter_start=int(entry_it),
                     iter_end=int(i_now), action=str(action))
        if verdict:
            attrs["verdict"] = str(verdict)
        tracer.record_span("fit/chunk", chunk_t0, chunk_t1, **attrs)

    while i_host < budget:
        snap.update(
            params=params, opt_state=opt_state, losses_np=losses_np,
            i_host=i_host, best_params=best_params, best_it=best_it,
            best_loss=best_loss, diag=diag, diag_i0=diag_i0,
            reseeds=reseeds, extra_granted=extra_granted,
            nan_retries=nan_retries, lr=lr_now, budget=budget,
            stagnation_anchor=stagnation_anchor,
            prev_verdict=prev_verdict)
        # periodic durability point, at the TOP of the loop pass: here
        # every carry is a live chunk output AND every controller
        # evaluation for the completed chunks has already been applied,
        # so a resume from this snapshot replays the uninterrupted
        # run's remaining chunk grid and decision trail bit-exactly.
        # (Saving before the evaluation would make the resumed run skip
        # one evaluation and diverge.)  Cadence counts dispatched
        # chunks — a non-trajectory quantity, so saving never perturbs
        # the fit it snapshots.
        if checkpoint_cb is not None and checkpoint_every \
                and chunks_done and losses_np is not None \
                and chunks_done % int(checkpoint_every) == 0:
            from scdna_replication_tools_tpu.infer import (
                checkpoint as _ckpt,
            )

            checkpoint_cb(
                params=_ckpt.host_view(params),
                opt_state=_ckpt.host_view(opt_state),
                losses=losses_np[:i_host], num_iters=i_host,
                state={
                    "reseeds": reseeds, "extra_granted": extra_granted,
                    "nan_retries": nan_retries, "lr": lr_now,
                    "budget": budget,
                    "stagnation_anchor": stagnation_anchor,
                    "prev_verdict": prev_verdict,
                    "best_loss": best_loss, "best_it": best_it,
                    "best_params": _np_tree_or_none(best_params),
                    "diag": _ckpt.host_view(diag), "diag_i0": diag_i0,
                }, exact=True)

        # injection site at the top of the loop: every carry is a live
        # chunk output here, so a simulated preemption aborts with
        # exactly-resumable state (the emergency hook in the caller)
        poison = _faults.point(fault_site) == "nan"

        chunk_entry_params, chunk_entry_it = params, i_host

        def _dispatch():
            out = run_chunk(params, opt_state, losses, diag, i_host,
                            min(i_host + diag_every, budget), lr_now)
            # the blocking host fetch happens INSIDE the deadline: a
            # stalled device-to-host transfer is precisely the hang
            # the chunk watchdog exists to catch
            return out, int(out[0]), np.asarray(out[3])

        chunk_t0 = time.time()
        out, i_host, losses_np = _faults.run_with_deadline(
            _dispatch, chunk_deadline, f"{escalate_tag} fit chunk")
        chunk_t1 = time.time()
        (_, params, opt_state, losses, diag, converged, is_nan) = out
        chunks_done += 1
        if poison:
            # the injected-NaN fault: poison the chunk's last recorded
            # loss so everything DOWNSTREAM of detection — escalation
            # decision, diagnosable checkpoint, reduced-LR retry,
            # rewind-and-overwrite — is the real machinery, not a mock
            # (np.asarray of a device buffer is read-only; copy first)
            losses_np = np.array(losses_np)
            losses_np[max(i_host - 1, 0)] = np.nan
            is_nan = True
        traj = losses_np[:i_host]
        # best-loss checkpoint at chunk granularity: the params that
        # ENTERED this chunk scored losses[entry_it] (computed inside
        # the chunk from exactly those params)
        if chunk_entry_it < i_host \
                and np.isfinite(losses_np[chunk_entry_it]) \
                and float(losses_np[chunk_entry_it]) < best_loss:
            best_loss = float(losses_np[chunk_entry_it])
            best_params, best_it = chunk_entry_params, chunk_entry_it
        converged_flag = bool(converged)
        nan_flag = bool(is_nan)

        if nan_flag:
            decision = _controller.decide(
                policy, losses=traj, it=i_host, budget=budget,
                min_iter=min_iter, nan=True,
                nan_retries_done=nan_retries)
            decision = dict(decision)
            prev_verdict = None  # the retry restarts the trajectory
            # the artifact must be self-consistent: best_params belong
            # to iteration best_it, so the checkpoint records THAT
            # prefix — the poisoned tail lives on FitResult.losses and
            # the nan_abort event, not inside the restartable state
            ckpt_path = _save_escalation_checkpoint(
                escalate_dir, escalate_tag, best_params,
                traj[:best_it], num_iters=best_it)
            if ckpt_path:
                decision["detail"] = (decision.get("detail", "")
                                      + f"; checkpoint saved to "
                                        f"{ckpt_path}")
            decisions.append(decision)
            _chunk_span(chunk_entry_it, i_host,
                        decision.get("action", "escalate"),
                        verdict="nan")
            if decision.get("outcome") != "retry":
                break
            nan_retries += 1
            lr_now = lr_now * float(policy.nan_lr_factor)
            params = best_params
            opt_state = make_opt_state(best_params, lr_now, b1, b2,
                                       moment_dtype=moment_dtype)
            # redo from the checkpointed iteration: every poisoned
            # losses/diag entry beyond it is overwritten as the retry
            # re-runs those iterations
            i_host = best_it
            stagnation_anchor = best_it
            nan_flag = False
            continue

        if converged_flag:
            _chunk_span(chunk_entry_it, i_host, "converged")
            break  # the reference's own rel-tol criterion fired

        d = _decode_diag(np.asarray(diag), i_host, diag_i0, diag_every)
        grad = d["grad_norm"] if len(d["iter"]) else None
        decision, prev_verdict = _controller.evaluate(
            policy, losses=traj, it=i_host, budget=budget,
            min_iter=min_iter,
            grad_norm_first=float(grad[0]) if grad is not None else None,
            grad_norm_last=float(grad[-1]) if grad is not None else None,
            exhausted=i_host >= budget, reseeds_done=reseeds,
            extra_granted=extra_granted, prev_verdict=prev_verdict,
            stagnation_start=stagnation_anchor)
        if decision is None:
            _chunk_span(chunk_entry_it, i_host, "continue",
                        verdict=prev_verdict)
            continue
        action = decision["action"]
        _chunk_span(chunk_entry_it, i_host, action,
                    verdict=(decision.get("trigger") or {}).get("verdict"))
        if action == "early_stop":
            # hand back the BEST state seen, not whatever the last
            # chunk left: the noisy tails this stop fires on carry
            # intermittent loss spikes, and stopping right after one
            # must not cost accuracy.  Audited on the decision itself.
            if best_loss < float(traj[-1]):
                params = best_params
                decision["detail"] = (
                    f"restored the best-loss checkpoint (iter {best_it}"
                    f", loss {best_loss:.6g}) — the final state was "
                    f"worse (loss {float(traj[-1]):.6g})")
            # an early stop IS the adaptive path's convergence
            # criterion firing — mark the fit TERMINAL, so a saved
            # checkpoint restores as completed instead of resuming a
            # deliberately-finished fit (which would re-burn the
            # reclaimed budget, pairing the restored best-loss params
            # with final-iteration Adam moments and a loss prefix that
            # matches neither)
            converged_flag = True
            decisions.append(decision)
            break
        decisions.append(decision)
        if action == "extend":
            grant = int(decision["iters_granted"])
            budget += grant
            extra_granted += grant
        elif action == "reseed":
            reseeds += 1
            params = _perturb_params(best_params, policy.reseed_scale,
                                     policy.seed, reseeds)
            opt_state = make_opt_state(params, lr_now, b1, b2,
                                       moment_dtype=moment_dtype)
            prev_verdict = None  # the perturbed trajectory is a new
            # regime — instability must re-prove persistence, and the
            # stagnation stop must not cancel the restart against the
            # pre-reseed global best
            stagnation_anchor = i_host

    return (i_host, params, opt_state, losses, diag, losses_np,
            converged_flag, nan_flag, budget, decisions, best_loss,
            best_params, best_it, lr_now, reseeds, extra_granted,
            nan_retries, prev_verdict, stagnation_anchor)


def _save_escalation_checkpoint(escalate_dir, tag, params, losses,
                                num_iters: int) -> Optional[str]:
    """Persist the best-loss state of a NaN-escalated fit (diagnosable
    artifact for the post-mortem); best-effort — a failed save must not
    mask the escalation itself."""
    if not escalate_dir:
        return None
    try:
        from scdna_replication_tools_tpu.infer import checkpoint as ckpt

        params_np = ckpt.host_view(params)
        return ckpt.save_step(str(escalate_dir), f"{tag}_nan", params_np,
                              np.asarray(losses), num_iters=num_iters,
                              converged=False, nan_abort=True)
    except Exception as exc:  # noqa: BLE001 — telemetry-adjacent path
        from scdna_replication_tools_tpu.utils.profiling import logger

        logger.warning("NaN-escalation checkpoint save failed: %s", exc)
        return None


def _diagnose(losses: np.ndarray, converged: bool, nan_abort: bool,
              diagnostics: Optional[dict],
              thresholds: Optional[dict]) -> dict:
    """Convergence-doctor report for one completed fit (host-side)."""
    kwargs = dict(thresholds or {})
    grad = diagnostics["grad_norm"] if diagnostics is not None \
        and len(diagnostics.get("grad_norm", ())) else None
    return _doctor.diagnose_fit(
        losses, converged=converged, nan_abort=nan_abort,
        grad_norm_first=float(grad[0]) if grad is not None else None,
        grad_norm_last=float(grad[-1]) if grad is not None else None,
        **kwargs)


def _decode_diag(diag: np.ndarray, num_iters: int, i0: int,
                 diag_every: int) -> dict:
    """Map ring-buffer slots back to the iterations they sampled.

    Sampled iterations are the multiples of ``diag_every`` in
    ``[i0, num_iters)`` (a resumed fit samples only its own segment);
    slot ``(iter // diag_every) % DIAG_RING`` holds each — the last
    ``DIAG_RING`` samples are distinct slots, older ones were
    overwritten.
    """
    first = -(-i0 // diag_every) * diag_every  # ceil to a multiple
    sampled = list(range(first, num_iters, diag_every))
    kept = sampled[-DIAG_RING:]
    rows = [(it // diag_every) % DIAG_RING for it in kept]
    return {
        "every": diag_every,
        "iter": np.asarray(kept, np.int64),
        "loss": diag[rows, 0] if kept else np.zeros(0, np.float32),
        "grad_norm": diag[rows, 1] if kept else np.zeros(0, np.float32),
        "param_norm": diag[rows, 2] if kept else np.zeros(0, np.float32),
    }
