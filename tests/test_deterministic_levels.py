"""Integration tests for the deterministic (non-SVI) inference levels.

``scRT.infer(level='cell' | 'clone' | 'bulk')`` runs the pre-PERT
heuristic pipeline (clustering -> clone assignment -> GC correction ->
normalisation -> Manhattan binarisation; reference:
infer_scRT.py:171-276).  Round 1 wired these but never exercised them
end to end; here each level runs on the simulated fixture and must
produce the reference's output columns with sane values — and the
heuristic replication calls must beat chance against simulator truth.
"""

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.api import scRT
from scdna_replication_tools_tpu.models.simulator import pert_simulator


@pytest.fixture(scope="module")
def sim_data(synthetic_frames):
    df_s, df_g = synthetic_frames
    sim_s, sim_g = pert_simulator(
        df_s, df_g, num_reads=50_000, rt_cols=["rt_A", "rt_B"],
        clones=["A", "B"], lamb=0.75, betas=[0.5, 0.0], a=10.0, seed=5)
    for df in (sim_s, sim_g):
        df["reads"] = df["true_reads_norm"]
        df["state"] = df["true_somatic_cn"].astype(int)
        df["copy"] = df["true_somatic_cn"].astype(float)
    return sim_s, sim_g


def _run_level(sim_data, level, clone_col="clone_id"):
    sim_s, sim_g = sim_data
    scrt = scRT(sim_s.copy(), sim_g.copy(), input_col="reads",
                clone_col=clone_col, assign_col="copy", rt_prior_col=None)
    cn_s_out, supp_s, cn_g1_out, supp_g1 = scrt.infer(level=level)
    return scrt, cn_s_out


EXPECTED_COLS = ["rt_value", "rt_state", "frac_rt", "binary_thresh"]


@pytest.mark.parametrize("level", ["cell", "clone", "bulk"])
def test_level_output_contract(sim_data, level):
    """Every deterministic level adds the rt_value/rt_state/frac_rt/
    binary_thresh columns (reference: infer_scRT.py:199-202, 237-240,
    270-274 via binarize_rt_profiles)."""
    _, out = _run_level(sim_data, level)
    for col in EXPECTED_COLS:
        assert col in out.columns, f"{level}: missing {col}"
    # binary rt_state
    assert set(np.unique(out["rt_state"])) <= {0.0, 1.0}
    # per-cell frac_rt consistent with rt_state
    frac = out.groupby("cell_id").agg(
        f=("frac_rt", "first"), m=("rt_state", "mean"))
    np.testing.assert_allclose(frac["f"], frac["m"], atol=1e-6)
    # rt_value is the continuous normalised profile; finite
    assert np.isfinite(out["rt_value"]).all()


@pytest.mark.parametrize("level", ["cell", "clone"])
def test_level_changepoint_and_norm_columns(sim_data, level):
    """cell/clone levels carry the intermediate normalisation columns
    (GC-corrected rpm; cell level also the changepoint segments,
    reference: normalize_by_cell.py:216-267)."""
    _, out = _run_level(sim_data, level)
    assert "rpm_gc_norm" in out.columns
    if level == "cell":
        assert "changepoint_segments" in out.columns
        # segments are small non-negative integers per cell
        segs = out["changepoint_segments"]
        assert (segs >= 0).all()


@pytest.mark.parametrize("level", ["cell", "clone", "bulk"])
def test_level_recovers_replication_better_than_chance(sim_data, level):
    """The heuristic levels are baselines, not PERT — but on clean
    simulated data their binary calls must still track true_rep."""
    _, out = _run_level(sim_data, level)
    acc = (out["rt_state"] == out["true_rep"]).mean()
    assert acc > 0.65, f"{level}: rep accuracy {acc:.3f}"


def test_cell_level_clusters_when_no_clones(sim_data):
    """clone_col=None triggers kmeans/BIC clustering of the G1 cells
    (reference: infer_scRT.py:173-176)."""
    scrt, out = _run_level(sim_data, "clone", clone_col=None)
    assert scrt.clone_col == "cluster_id"
    for col in EXPECTED_COLS:
        assert col in out.columns


def test_clone_level_clusters_umap_hdbscan(sim_data):
    """clustering_method='umap_hdbscan' wires the reference's optional
    cncluster.py:10-46 path into clone discovery; hyperparameters are
    tuned down for the 24-cell fixture via clustering_kwargs."""
    sim_s, sim_g = sim_data
    scrt = scRT(sim_s.copy(), sim_g.copy(), input_col="reads",
                clone_col=None, assign_col="copy", rt_prior_col=None,
                clustering_method="umap_hdbscan",
                clustering_kwargs={"min_cluster_size": 8, "min_samples": 4,
                                   "n_neighbors": 8})
    out = scrt.infer(level="clone")[0]
    assert scrt.clone_col == "cluster_id"
    for col in EXPECTED_COLS:
        assert col in out.columns
    # S cells were assigned to the discovered clusters; the two
    # simulated clones must be separated into >= 2 of them
    assert out["cluster_id"].nunique() >= 2


def test_invalid_clustering_method_raises(sim_data):
    sim_s, sim_g = sim_data
    with pytest.raises(ValueError, match="clustering_method"):
        scRT(sim_s, sim_g, clustering_method="umap")


def test_pseudobulk_and_twidth_downstream(sim_data):
    """Downstream RT analysis runs off a deterministic level's output
    (reference: infer_scRT.py:279-290)."""
    scrt, out = _run_level(sim_data, "clone")
    pb = scrt.compute_pseudobulk_rt_profiles()
    assert "pseudobulk_hours" in pb.columns
    tw, right_t, left_t, popt, time_bins, pct_reps = scrt.calculate_twidth()
    assert np.isfinite(tw)
    assert 0.0 < tw < 20.0
    # %-replicated curve spans the transition the sigmoid fits
    assert len(time_bins) == len(pct_reps)
    assert np.nanmax(pct_reps) > 0.6 and np.nanmin(pct_reps) < 0.4
