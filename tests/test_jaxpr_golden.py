"""Golden-jaxpr regression: the hot programs' traced structure is pinned.

The decode slab and the fit chunk are the two programs whose semantic
drift costs the most (the decode feeds every QC/packaging surface; the
chunk program is ONE compiled body reused for every controller chunk of
every fit).  This test snapshots each program's **primitive multiset**
and **dtype census** — order-free, so a legitimate reordering or
re-fusion of the same math never trips it, while a new host callback, a
dtype promotion, an extra transpose or a lost while-loop fails loudly.

The snapshot records the jax version it was generated under; a
different installed jax (CI's floating pin) skips rather than chasing
upstream lowering details.  Regenerate after an INTENDED change with:

    PERT_UPDATE_GOLDEN=1 python -m pytest tests/test_jaxpr_golden.py
"""

import json
import os
import pathlib
import sys

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

import jax  # noqa: E402

from tools.pertlint.deep import entrypoints, trace  # noqa: E402

GOLDEN = pathlib.Path(__file__).parent / "golden" / "jaxpr_census.json"
# the binary-encoding twins pin the PR-11 programs: the Kb-plane chunk
# fit (fused kernel + single-sweep Adam) and the binary decode slab
PROGRAMS = ("decode_slab", "fit_chunk", "decode_slab_binary",
            "fit_chunk_binary")


def _census(name: str) -> dict:
    prog = entrypoints.REGISTRY[name]()
    ctx = trace.build_program_context(prog)
    dtypes: dict = {}
    for aval in ctx.var_avals:
        dtypes[aval.dtype] = dtypes.get(aval.dtype, 0) + 1
    return {
        "primitives": {p.name: p.count for p in ctx.primitives},
        "dtypes": dtypes,
        "num_consts": len(ctx.consts),
        "num_outputs": len(ctx.out_avals),
    }


def _current() -> dict:
    return {"jax_version": jax.__version__,
            "programs": {name: _census(name) for name in PROGRAMS}}


def test_golden_jaxpr_census():
    current = _current()
    if os.environ.get("PERT_UPDATE_GOLDEN"):
        GOLDEN.parent.mkdir(parents=True, exist_ok=True)
        GOLDEN.write_text(json.dumps(current, indent=1, sort_keys=True)
                          + "\n")
        pytest.skip(f"golden snapshot regenerated at {GOLDEN}")
    assert GOLDEN.is_file(), \
        f"no golden snapshot — run PERT_UPDATE_GOLDEN=1 pytest {__file__}"
    golden = json.loads(GOLDEN.read_text())
    if golden["jax_version"] != jax.__version__:
        pytest.skip(f"snapshot from jax {golden['jax_version']}, running "
                    f"{jax.__version__} — lowering details differ across "
                    f"versions; regenerate to re-pin")
    for name in PROGRAMS:
        want, got = golden["programs"][name], current["programs"][name]
        # compare per-key so the failure names the drifted primitive
        # instead of dumping two 60-entry dicts
        assert set(want["primitives"]) == set(got["primitives"]), (
            name, "primitive set drift",
            set(want["primitives"]) ^ set(got["primitives"]))
        diffs = {p: (c, got["primitives"][p])
                 for p, c in want["primitives"].items()
                 if got["primitives"][p] != c}
        assert not diffs, (name, "primitive count drift", diffs)
        assert want["dtypes"] == got["dtypes"], (name, "dtype census drift")
        assert want["num_consts"] == got["num_consts"], (name, "consts")
        assert want["num_outputs"] == got["num_outputs"], (name, "outputs")
