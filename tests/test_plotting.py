"""Plotting smoke tests (Agg backend) — figures render without scgenome."""

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt
import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.plotting import (
    get_clone_cmap,
    get_cn_cmap,
    get_rt_cmap,
    plot_cell_cn_profile,
    plot_clustered_cell_cn_matrix,
    plot_model_results,
)


@pytest.fixture(scope="module")
def plot_frame():
    rng = np.random.default_rng(0)
    rows = []
    for clone, cells in [("A", 6), ("B", 6)]:
        for i in range(cells):
            for chrom, n in [("1", 40), ("2", 30)]:
                starts = np.arange(n) * 500_000
                rows.append(pd.DataFrame({
                    "cell_id": f"{clone}{i}",
                    "chr": chrom,
                    "start": starts,
                    "end": starts + 500_000,
                    "clone_id": clone,
                    "state": 2 + (clone == "B") * (np.arange(n) < 10),
                    "model_cn_state": 2,
                    "model_rep_state": rng.integers(0, 2, n),
                    "model_tau": (i + 1) / (cells + 1),
                    "rpm": rng.poisson(50, n).astype(float),
                }))
    return pd.concat(rows, ignore_index=True)


def test_cmaps():
    assert get_cn_cmap(np.array([0, 5])).N == 6
    assert get_rt_cmap().N == 2
    assert "A" in get_clone_cmap()


def test_genome_profile_axis(plot_frame):
    fig, ax = plt.subplots()
    one_cell = plot_frame[plot_frame.cell_id == "A0"]
    plot_cell_cn_profile(ax, one_cell, "rpm", cn_field_name="state",
                         rawy=True)
    assert ax.get_xlabel() == "chromosome"
    plt.close(fig)


def test_clustered_matrix_shapes(plot_frame):
    fig, ax = plt.subplots()
    mat = plot_clustered_cell_cn_matrix(ax, plot_frame, "state",
                                        cluster_field_name="clone_id")
    assert mat.shape == (70, 12)  # 70 loci x 12 cells
    plt.close(fig)


def test_plot_model_results_renders(plot_frame):
    fig = plot_model_results(plot_frame, plot_frame)
    assert len(fig.axes) >= 8
    plt.close(fig)
