"""Parity tests for the sparse one-hot Dirichlet-prior path.

The production CN priors (hmmcopy / diploid / g1_cells / g1_clones,
reference: pert_model.py:272-296) concentrate on ONE state per bin, so
the dense (cells, loci, P) etas tensor is ~P x its information content.
``priors.sparsify_etas`` compacts it to (eta_idx, eta_w) planes and the
fused kernel streams those instead (ops/enum_kernel.py).  These tests pin
that the sparse encoding computes the IDENTICAL objective and gradients
as the dense path at every level: kernel, full model loss, and the
runner's end-to-end fit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scdna_replication_tools_tpu.layout import state_major
from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    init_params,
    pert_loss,
)
from scdna_replication_tools_tpu.models.priors import sparsify_etas
from scdna_replication_tools_tpu.ops.enum_kernel import (
    enum_loglik_fused,
    enum_loglik_fused_sparse,
)
from scdna_replication_tools_tpu.ops.gc import gc_features

P = 13


def test_sparsify_etas_detects_one_hot():
    rng = np.random.default_rng(0)
    etas = np.ones((4, 10, P), np.float32)
    states = rng.integers(0, P, (4, 10))
    np.put_along_axis(etas, states[..., None], 1e6 + 1.0, axis=-1)
    # a few uniform bins (all ones) must also be representable
    etas[0, :3, :] = 1.0
    sp = sparsify_etas(etas)
    assert sp is not None
    idx, w = sp
    assert idx.shape == w.shape == (4, 10)
    assert np.all(w[0, :3] == 0.0)
    np.testing.assert_array_equal(idx[1:], states[1:])
    np.testing.assert_allclose(w[1:], 1e6, rtol=1e-6)


def test_sparsify_etas_rejects_multi_state_and_sub_unit():
    etas = np.ones((2, 5, P), np.float32)
    etas[..., 2] = 50.0
    etas[..., 3] = 50.0   # composite-style two-state bin
    assert sparsify_etas(etas) is None
    etas = np.ones((2, 5, P), np.float32)
    etas[..., 2] = 0.5    # sub-unit concentration
    assert sparsify_etas(etas) is None


def _problem(C=8, L=96, seed=7, weight=1e5):
    rng = np.random.default_rng(seed)
    reads = jnp.asarray(rng.poisson(40, (C, L)).astype(np.float32))
    mu = jnp.asarray(rng.uniform(2, 30, (C, L)).astype(np.float32))
    logits = jnp.asarray(rng.normal(0, 2, (C, L, P)).astype(np.float32))
    phi = jnp.asarray(rng.uniform(0.01, 0.99, (C, L)).astype(np.float32))
    etas = np.ones((C, L, P), np.float32)
    states = rng.integers(0, P, (C, L))
    np.put_along_axis(etas, states[..., None], weight, axis=-1)
    idx, w = sparsify_etas(etas)
    return (reads, mu, logits, phi, jnp.asarray(etas),
            jnp.asarray(idx), jnp.asarray(w), jnp.float32(0.75))


def test_sparse_kernel_matches_dense_kernel():
    """enum_loglik_fused_sparse must equal enum_loglik_fused (value AND
    all gradients) on a one-hot prior — same math, compact encoding."""
    reads, mu, logits, phi, etas, idx, w, lamb = _problem()
    rng = np.random.default_rng(3)
    ct = jnp.asarray(rng.normal(0, 1, reads.shape), jnp.float32)

    def dense(mu, logits, phi):
        return jnp.sum(enum_loglik_fused(
            reads, mu, state_major(logits), phi, state_major(etas), lamb,
            True) * ct)

    def sparse(mu, logits, phi):
        return jnp.sum(enum_loglik_fused_sparse(
            reads, mu, state_major(logits), phi, idx, w, lamb, True) * ct)

    vd, gd = jax.value_and_grad(dense, (0, 1, 2))(mu, logits, phi)
    vs, gs = jax.value_and_grad(sparse, (0, 1, 2))(mu, logits, phi)
    assert abs(float(vd - vs)) / abs(float(vd)) < 1e-5
    for name, a, b in zip(("dmu", "dpi", "dphi"), gd, gs):
        rel = jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(a)) + 1e-30)
        assert float(rel) < 1e-4, (name, float(rel))


def test_sparse_kernel_rejects_bad_shapes():
    reads, mu, logits, phi, etas, idx, w, lamb = _problem()
    with pytest.raises(ValueError, match="STATE-MAJOR"):
        enum_loglik_fused_sparse(reads, mu, logits, phi, idx, w, lamb, True)


def _model_problem(weight):
    rng = np.random.default_rng(5)
    C, L = 12, 200
    reads = rng.poisson(40, (C, L)).astype(np.float32)
    gammas = rng.uniform(0.35, 0.6, L).astype(np.float32)
    etas = np.ones((C, L, P), np.float32)
    states = rng.integers(1, 5, (C, L))
    np.put_along_axis(etas, states[..., None], weight, axis=-1)
    idx, w = sparsify_etas(etas)
    common = dict(
        reads=jnp.asarray(reads), libs=jnp.zeros((C,), jnp.int32),
        gamma_feats=gc_features(jnp.asarray(gammas), 4),
        mask=jnp.ones((C,), jnp.float32))
    dense_batch = PertBatch(etas=jnp.asarray(etas), **common)
    sparse_batch = PertBatch(eta_idx=jnp.asarray(idx),
                             eta_w=jnp.asarray(w), **common)
    fixed = {"beta_means": jnp.zeros((1, 5), jnp.float32),
             "lamb": jnp.asarray(0.75, jnp.float32)}
    t_init = np.full(C, 0.4, np.float32)
    return dense_batch, sparse_batch, fixed, t_init


@pytest.mark.parametrize("impl", ["xla", "pallas_interpret"])
def test_pert_loss_sparse_matches_dense(impl):
    """Full model loss + gradients: sparse_etas encoding vs dense etas.

    weight=1e3 keeps the dense path's float32 gammaln normaliser accurate
    enough for a tight value comparison (at 1e6 the DENSE path carries
    ~1-per-bin f32 cancellation noise in the parameter-free constant —
    the sparse analytic form is the more accurate of the two)."""
    dense_batch, sparse_batch, fixed, t_init = _model_problem(weight=1e3)

    out = {}
    for name, batch, sparse in (("dense", dense_batch, False),
                                ("sparse", sparse_batch, True)):
        spec = PertModelSpec(P=P, K=4, L=1, tau_mode="param",
                             cond_beta_means=True, fixed_lamb=True,
                             sparse_etas=sparse, enum_impl=impl)
        params = init_params(spec, batch, fixed, t_init=t_init)
        out[name] = jax.value_and_grad(
            lambda p: pert_loss(spec, p, fixed, batch))(params)

    (vd, gd), (vs, gs) = out["dense"], out["sparse"]
    assert abs(float(vd - vs)) / abs(float(vd)) < 1e-5, (float(vd), float(vs))
    for k in gd:
        denom = float(jnp.max(jnp.abs(gd[k]))) + 1e-20
        rel = float(jnp.max(jnp.abs(gd[k] - gs[k]))) / denom
        assert rel < 2e-2, (k, rel)


def test_init_params_sparse_matches_dense():
    dense_batch, sparse_batch, fixed, t_init = _model_problem(weight=1e3)
    spec_d = PertModelSpec(P=P, K=4, L=1, cond_beta_means=True,
                           fixed_lamb=True)
    spec_s = PertModelSpec(P=P, K=4, L=1, cond_beta_means=True,
                           fixed_lamb=True, sparse_etas=True)
    pd_ = init_params(spec_d, dense_batch, fixed, t_init=t_init)
    ps_ = init_params(spec_s, sparse_batch, fixed, t_init=t_init)
    # identical pi init (up to float op order) and identical u init
    # (same ploidy guess from the compact encoding)
    np.testing.assert_allclose(np.asarray(pd_["pi_logits"]),
                               np.asarray(ps_["pi_logits"]), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(pd_["u"]), np.asarray(ps_["u"]),
                               rtol=1e-6)


def test_runner_auto_sparse_matches_dense_fit(synthetic_frames):
    """End-to-end: the runner's auto-detected sparse path must reproduce
    the dense fit's loss trajectory (g1_clones is one-hot structured).
    cn_prior_weight=1e6 here — the parameter-free Dirichlet constant
    differs between the two encodings by dense-path f32 gammaln noise,
    so trajectories are compared after subtracting the iteration-0
    offset (gradients, and hence the fit, are identical)."""
    from conftest import dense_inputs_from_frames
    from scdna_replication_tools_tpu.config import PertConfig
    from scdna_replication_tools_tpu.infer.runner import PertInference

    s, g1, clone_idx = dense_inputs_from_frames(synthetic_frames)

    def run(sparse):
        config = PertConfig(cn_prior_method="g1_clones", max_iter=25,
                            min_iter=12, run_step3=False,
                            sparse_etas=sparse,
                            enum_impl="pallas_interpret")
        inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                            clone_idx_g1=clone_idx, num_clones=2)
        _, step2, _ = inf.run()
        assert step2.spec.sparse_etas == sparse
        return np.asarray(step2.fit.losses, np.float64)

    dense = run(False)
    sparse = run(True)
    assert sparse.shape == dense.shape
    # constant offset = the differently-computed Dirichlet normaliser
    np.testing.assert_allclose(sparse - sparse[0], dense - dense[0],
                               rtol=5e-4, atol=2.0)
