"""pertlint-deep: the jaxpr/sharding analysis layer.

Three strata:

* pure-unit — DP005/DP006/DP007 verdicts on hand-built contexts (no
  tracing), one test per sharding-contract failure mode;
* traced-unit — each jaxpr rule catching a deliberately-broken synthetic
  program (the DP003 case is shaped like the PR-4 mirror-rescue
  aliasing bug: a donated buffer the lowering could not alias);
* the gate — the real registry traces every entry point and
  ``python -m tools.pertlint --deep`` exits 0 on HEAD with zero
  unbaselined findings, every baselined deep finding carrying a
  rationale.
"""

import functools
import json
import pathlib
import subprocess
import sys
import warnings

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tools.pertlint.deep import entrypoints, trace  # noqa: E402
from tools.pertlint.deep.engine import deep_lint, run_deep_rules  # noqa: E402
from tools.pertlint.deep.rules_jaxpr import (  # noqa: E402
    ConstantBloat,
    DonationAudit,
    DtypePromotionAudit,
    HostCallbackInProgram,
    WhileCarryConsistency,
)
from tools.pertlint.deep.rules_sharding import (  # noqa: E402
    INDIVISIBLE,
    RANK,
    REUSE,
    UNKNOWN,
    ShardingContract,
    ShardingDivisibility,
    check_spec_against_shape,
)

BASELINE = REPO_ROOT / "tools" / "pertlint" / "baseline.json"

S = jax.ShapeDtypeStruct
f32 = jnp.float32


def _ctx_for(fn, dynamic, declared_donate=(), name="synthetic",
             kwargs=None):
    """ProgramContext of a synthetic jitted fn (args all dynamic)."""
    prog = entrypoints.EntryProgram(
        name=name, anchor=fn, jit_fn=fn,
        args=tuple(v for _, v in dynamic), kwargs=kwargs or {},
        dynamic_args=list(dynamic), declared_donate=tuple(declared_donate))
    with warnings.catch_warnings():
        # a deliberately-unusable donation warns; that IS the test
        warnings.simplefilter("ignore")
        return trace.build_program_context(prog)


# ---------------------------------------------------------------------------
# traced-unit: each jaxpr rule catches its seeded defect
# ---------------------------------------------------------------------------

def test_dp003_catches_broken_donation():
    """The PR-4 bug shape: donate_argnames declared, but no output
    matches the donated buffer, so the lowered module carries NO
    input_output_alias for it — the donation silently does nothing."""
    @functools.partial(jax.jit, donate_argnames=("params0",))
    def broken(params0, x):
        # params0 is consumed but no output matches its (8, 8) f32 aval,
        # so the lowering cannot realise the declared donation
        return (jnp.sum(params0) + x,)

    ctx = _ctx_for(broken, [("params0", S((8, 8), f32)),
                            ("x", S((4,), f32))],
                   declared_donate=("params0",))
    findings = list(DonationAudit().check(ctx))
    assert any("NO input_output_alias" in f.message for f in findings), \
        [f.message for f in findings]


def test_dp003_count_fallback_on_unused_donated_arg():
    """A donated arg so dead it is pruned from the lowered signature:
    leaf attribution degrades, but the audit still fails via the
    declared-vs-realised count comparison."""
    @functools.partial(jax.jit, donate_argnames=("params0",))
    def broken(params0, x):
        return (x * 2.0,)

    ctx = _ctx_for(broken, [("params0", S((8, 8), f32)),
                            ("x", S((4,), f32))],
                   declared_donate=("params0",))
    findings = list(DonationAudit().check(ctx))
    assert any("input_output_alias" in f.message for f in findings)


def test_dp003_clean_on_healthy_donation():
    @functools.partial(jax.jit, donate_argnames=("params0",))
    def healthy(params0, x):
        return params0 + x, x

    ctx = _ctx_for(healthy, [("params0", S((8,), f32)),
                             ("x", S((8,), f32))],
                   declared_donate=("params0",))
    assert list(DonationAudit().check(ctx)) == []


def test_dp003_flags_undonated_init_buffer():
    def plain(params0, x):
        return params0 + x

    fn = jax.jit(plain)
    ctx = _ctx_for(fn, [("params0", S((8,), f32)), ("x", S((8,), f32))])
    findings = list(DonationAudit().check(ctx))
    assert any("not donated" in f.message for f in findings)


def test_dp003_flags_donation_typo():
    def plain(params0, x):
        return params0 + x

    ctx = _ctx_for(jax.jit(plain),
                   [("params0", S((8,), f32)), ("x", S((8,), f32))],
                   declared_donate=("params0", "opt_stat0"))  # typo'd name
    findings = list(DonationAudit().check(ctx))
    assert any("no such dynamic argument" in f.message for f in findings)


def test_dp001_catches_f64_leak():
    def leaky(x):
        return x * 2.0

    with jax.experimental.enable_x64():
        ctx = _ctx_for(jax.jit(leaky), [("x", S((4,), jnp.float64))])
    findings = list(DtypePromotionAudit().check(ctx))
    assert any("float64" in f.message for f in findings)


def test_dp001_catches_f32_to_bf16_narrowing():
    def narrowing(x):
        return (x.astype(jnp.bfloat16) * 2).astype(f32)

    ctx = _ctx_for(jax.jit(narrowing), [("x", S((4,), f32))])
    findings = list(DtypePromotionAudit().check(ctx))
    assert any("f32->bf16" in f.message for f in findings)


def test_dp002_catches_debug_print():
    def chatty(x):
        jax.debug.print("x = {x}", x=x)
        return x * 2

    ctx = _ctx_for(jax.jit(chatty), [("x", S((4,), f32))])
    findings = list(HostCallbackInProgram().check(ctx))
    assert any("debug_callback" in f.message for f in findings)


def test_dp004_catches_constant_bloat():
    big = np.ones((600, 600), np.float32)  # 1.44 MB > the 1 MiB threshold

    def bloated(x):
        return x + jnp.asarray(big)[0, :4]

    ctx = _ctx_for(jax.jit(bloated), [("x", S((4,), f32))])
    findings = list(ConstantBloat().check(ctx))
    assert any("closed-over constant" in f.message for f in findings)
    assert "(600, 600)" in findings[0].message


def test_dp005_catches_weak_typed_carry():
    def loopy(x):
        # the 0 literal leaks a weak int32 into the carry
        return jax.lax.while_loop(lambda c: c[0] < 3,
                                  lambda c: (c[0] + 1, c[1] * 2.0),
                                  (0, x))

    ctx = _ctx_for(jax.jit(loopy), [("x", S((), f32))])
    findings = list(WhileCarryConsistency().check(ctx))
    assert any("weakly typed" in f.message for f in findings)


def test_dp005_mismatched_carry_unit():
    """Init-vs-body aval disagreement (not constructible through jax's
    own trace-time checks) via a hand-built context."""
    entry = trace.WhileCarryEntry(
        position=3,
        init=trace.AvalInfo(shape=(8,), dtype="float32"),
        body_out=trace.AvalInfo(shape=(8,), dtype="bfloat16"))
    ctx = trace.ProgramContext(
        name="unit", path="x.py", line=1, primitives=[], out_avals=[],
        var_avals=[], converts=[], consts=[], leaves=[],
        declared_donate=(), dynamic_arg_names=(), while_carries=[entry],
        alias_count=0, donated_leaf_count=0)
    findings = list(WhileCarryConsistency().check(ctx))
    assert len(findings) == 1 and "slot 3" in findings[0].message


# ---------------------------------------------------------------------------
# pure-unit: the sharding contract checker, one test per failure mode
# ---------------------------------------------------------------------------

EXTENTS = {"cells": 4, "loci": 2}


def _codes(spec, rank, shape):
    return [c for c, _ in check_spec_against_shape(spec, rank, shape,
                                                   EXTENTS)]


def test_contract_clean_spec_passes():
    assert _codes((("cells",), ("loci",)), 2, (8, 16)) == []


def test_contract_unknown_axis():
    assert _codes((("cells",), ("model",)), 2, (8, 16)) == [UNKNOWN]


def test_contract_rank_overflow():
    # trailing None dims count: the factory believes the tensor is 3-D
    assert _codes((("cells",), (), ()), 3, (8, 16)) == [RANK]


def test_contract_axis_reuse():
    assert _codes((("cells",), ("cells",)), 2, (8, 16)) == [REUSE]


def test_contract_indivisible_shape():
    # 9 cells over 4 shards does not divide
    assert _codes((("cells",), ("loci",)), 2, (9, 16)) == [INDIVISIBLE]


def test_contract_multi_axis_dim_extent():
    # ('cells','loci') on one dim shards it 8-ways: 16 % 8 == 0 passes,
    # 12 % 8 fails
    spec = (("cells", "loci"), ())
    assert _codes(spec, 2, (16, 3)) == []
    assert _codes(spec, 2, (12, 3)) == [INDIVISIBLE]


def test_contract_on_head_is_clean():
    """The real layout.py contract against the canonical 4x2 mesh and
    shapes: zero findings — the machine-checked form of the 'single
    owner of the tensor-layout contract' docstring."""
    ctx = trace.build_contract_context(entrypoints.CANONICAL_DIMS,
                                       entrypoints.MESH_EXTENTS)
    assert len(ctx.rows) >= 20  # batch + params + 3 shard_map factories
    findings = list(ShardingContract().check(ctx)) \
        + list(ShardingDivisibility().check(ctx))
    assert findings == [], [f.message for f in findings]


def test_contract_catches_seeded_bad_rows():
    ctx = trace.build_contract_context(entrypoints.CANONICAL_DIMS,
                                       entrypoints.MESH_EXTENTS)
    ctx.rows.append(trace.ContractRow(
        tensor="seeded.bad", factory="batch_specs",
        spec=(("cells",), ("rows",)), spec_rank=3, shape=(8, 16), line=1))
    c6 = list(ShardingContract().check(ctx))
    assert {m for f in c6 for m in [f.message] if "seeded.bad" in m}
    assert any("unknown" in f.message or "rows" in f.message for f in c6)
    assert any("rank" in f.message for f in c6)


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def test_registry_traces_all_entry_points():
    """Acceptance: >= 6 registered entry points trace on CPU, covering
    the fit chunk, loss, decode slab, PPC and the sharded placements."""
    findings, stats = run_deep_rules()
    assert len(stats.entrypoints) >= 6, stats
    assert {"fit_chunk", "loss", "decode_slab", "ppc"} \
        <= set(stats.entrypoints)
    assert {"sharded_batch", "sharded_params"} <= set(stats.entrypoints) \
        or stats.skipped  # skipped only when the backend lacks devices
    assert stats.contract_rows >= 20


def test_deep_gate_is_clean_on_head():
    """THE gate: zero unbaselined deep findings against the shipped
    baseline, in-process (fast path for iteration)."""
    result, stats, _ = deep_lint(baseline_path=BASELINE)
    assert result.new == [], [f.render() for f in result.new]
    assert stats.unrationalized == []


def test_deep_cli_gate_subprocess():
    """Exactly as CI runs it: ``python -m tools.pertlint --deep``."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.pertlint", "--deep",
         "--baseline", str(BASELINE)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "entry points traced" in proc.stdout


def test_baselined_deep_findings_carry_rationale():
    """Acceptance: every baselined deep (DP) finding has a one-line
    rationale — semantic debt without a recorded WHY does not ship."""
    entries = json.loads(BASELINE.read_text())["findings"]
    dp = [e for e in entries if e["rule"].startswith("DP")]
    for e in dp:
        assert e.get("rationale"), f"DP entry without rationale: {e}"


def test_svi_donation_sites_all_alias():
    """Acceptance: every donate_argnames site in infer/svi.py produces
    real input_output_aliases — the fit program end to end, and the
    chunk program for each of its declared donations."""
    for build in (entrypoints.build_fit, entrypoints.build_fit_chunk):
        prog = build()
        ctx = trace.build_program_context(prog)
        donated = [l for l in ctx.leaves if l.donated]
        assert donated, prog.name
        assert all(l.aliased for l in donated), \
            (prog.name, [(l.arg, l.keypath) for l in donated
                         if not l.aliased])
