"""Console entry points, end to end over TSV files.

The reference declares ``infer_scRT``/``infer_SPF`` console scripts whose
argument parsing is broken (infer_scRT.py:16-22, :303 — get_args never
returns, main unpacks 2 of 4 values); these tests pin that OUR CLIs
actually run the simulate -> infer -> analyse loop from files on disk,
the way a shell user would drive them.
"""

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.cli import (
    infer_scrt_main,
    infer_spf_main,
    simulator_main,
)


@pytest.fixture(scope="module")
def cli_dir(tmp_path_factory, synthetic_frames):
    """Input TSVs + simulator-CLI outputs shared across the CLI tests."""
    d = tmp_path_factory.mktemp("cli")
    df_s, df_g = synthetic_frames
    df_s.to_csv(d / "in_s.tsv", sep="\t", index=False)
    df_g.to_csv(d / "in_g.tsv", sep="\t", index=False)

    simulator_main(["-si", str(d / "in_s.tsv"), "-gi", str(d / "in_g.tsv"),
                    "-n", "50000", "-l", "0.75", "-a", "10",
                    "-b", "0.5", "0.0", "-rt", "rt_A", "rt_B",
                    "-c", "A", "B",
                    "-so", str(d / "sim_s.tsv"), "-go", str(d / "sim_g.tsv")])

    for name in ("sim_s", "sim_g"):
        df = pd.read_csv(d / f"{name}.tsv", sep="\t", dtype={"chr": str})
        df["reads"] = df["true_reads_norm"]
        df["state"] = df["true_somatic_cn"].astype(int)
        df["copy"] = df["true_somatic_cn"].astype(float)
        df.to_csv(d / f"pert_{name}.tsv", sep="\t", index=False)
    return d


def test_simulator_cli_outputs(cli_dir):
    sim_s = pd.read_csv(cli_dir / "sim_s.tsv", sep="\t")
    sim_g = pd.read_csv(cli_dir / "sim_g.tsv", sep="\t")
    for col in ("true_reads_norm", "true_rep", "true_t", "true_somatic_cn"):
        assert col in sim_s.columns
    assert (sim_g["true_rep"] == 0).all()      # G1 cells are unreplicated
    assert sim_s["true_rep"].mean() > 0.05     # S cells replicate


def test_infer_scrt_cli_pert_level(cli_dir):
    out, supp = cli_dir / "out.tsv", cli_dir / "supp.tsv"
    infer_scrt_main([str(cli_dir / "pert_sim_s.tsv"),
                     str(cli_dir / "pert_sim_g.tsv"),
                     str(out), str(supp),
                     "--max-iter", "150", "--cn-prior-method", "g1_clones"])
    res = pd.read_csv(out, sep="\t")
    for col in ("model_cn_state", "model_rep_state", "model_tau",
                "model_u", "model_rho"):
        assert col in res.columns
    acc = (res["model_rep_state"] == res["true_rep"]).mean()
    assert acc > 0.9, f"CLI pert rep accuracy {acc:.3f}"
    losses = pd.read_csv(supp, sep="\t").query("param == 'loss_s'")["value"]
    assert len(losses) and losses.iloc[-1] < losses.iloc[0]


def test_infer_scrt_cli_deterministic_level(cli_dir):
    out, supp = cli_dir / "out_clone.tsv", cli_dir / "supp_clone.tsv"
    infer_scrt_main([str(cli_dir / "pert_sim_s.tsv"),
                     str(cli_dir / "pert_sim_g.tsv"),
                     str(out), str(supp), "--level", "clone"])
    res = pd.read_csv(out, sep="\t")
    for col in ("rt_value", "rt_state", "frac_rt", "binary_thresh"):
        assert col in res.columns
    assert set(np.unique(res["rt_state"])) <= {0.0, 1.0}


def test_infer_spf_cli(cli_dir):
    out_s, out_spf = cli_dir / "spf_s.tsv", cli_dir / "spf.tsv"
    infer_spf_main([str(cli_dir / "pert_sim_s.tsv"),
                    str(cli_dir / "pert_sim_g.tsv"),
                    str(out_s), str(out_spf)])
    spf = pd.read_csv(out_spf, sep="\t")
    for col in ("clone_id", "SPF", "SPF_std", "num_s", "num_g"):
        assert col in spf.columns
    # S cells are reassigned to clones by read-profile correlation
    # (reference semantics), so per-clone S counts can shift; the pool
    # totals and the SPF identity are the invariants
    assert np.isfinite(spf["SPF"]).all()
    assert spf["num_s"].sum() == 24 and spf["num_g"].sum() == 24
    np.testing.assert_allclose(
        spf["SPF"], spf["num_s"] / (spf["num_s"] + spf["num_g"]))
    assert (spf["SPF_std"] > 0).all()


def test_infer_scrt_cli_clone_discovery(cli_dir):
    """--clone-col none triggers G1 clustering before the clone level."""
    out, supp = cli_dir / "out_disc.tsv", cli_dir / "supp_disc.tsv"
    infer_scrt_main([str(cli_dir / "pert_sim_s.tsv"),
                     str(cli_dir / "pert_sim_g.tsv"),
                     str(out), str(supp), "--level", "clone",
                     "--clone-col", "none",
                     "--clustering-method", "kmeans"])
    res = pd.read_csv(out, sep="\t")
    assert "cluster_id" in res.columns
    assert res["cluster_id"].nunique() >= 2


def test_infer_spf_cli_without_s_clone_column(cli_dir, tmp_path):
    """SPF's own job is assigning S cells to clones: cn_s without a
    clone column is canonical input and must run (cn_g1 carries it)."""
    s = pd.read_csv(cli_dir / "pert_sim_s.tsv", sep="\t") \
        .drop(columns=["clone_id"])
    s_path = tmp_path / "s_noclone.tsv"
    s.to_csv(s_path, sep="\t", index=False)
    out_s, out_spf = tmp_path / "s_out.tsv", tmp_path / "spf_out.tsv"
    infer_spf_main([str(s_path), str(cli_dir / "pert_sim_g.tsv"),
                    str(out_s), str(out_spf)])
    spf = pd.read_csv(out_spf, sep="\t")
    assert spf["num_s"].sum() == 24


def test_infer_spf_cli_validation_error(cli_dir, tmp_path):
    """A frame missing the input column fails fast with a named message."""
    bad = pd.read_csv(cli_dir / "pert_sim_s.tsv", sep="\t") \
        .drop(columns=["reads"])
    bad_path = tmp_path / "bad_s.tsv"
    bad.to_csv(bad_path, sep="\t", index=False)
    with pytest.raises(ValueError, match=r"cn_s is missing column\(s\).*reads"):
        infer_spf_main([str(bad_path), str(cli_dir / "pert_sim_g.tsv"),
                        str(tmp_path / "o1.tsv"), str(tmp_path / "o2.tsv")])
