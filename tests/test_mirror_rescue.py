"""Mirror-basin rescue (PertConfig.mirror_rescue) — beyond-reference.

PERT's step-2 objective is mirror-degenerate at the S-phase extremes: a
nearly-fully-replicated cell at read rate u is likelihood-equivalent to
an unreplicated cell at rate ~2u, and the u prior's mean tracks the
fitted tau (reference: pert_model.py:597-600), so both basins are
self-consistent for the reference's prior-free ``expose_tau`` param
(reference: pert_model.py:583).  The rescue re-fits boundary-tau cells
from the mirrored initialisation and keeps, per cell, whichever fit
scores the higher per-cell log-joint.

These tests drive the mechanism deterministically: corrupt a fitted
step-2 state into the mirrored basin for chosen late-S cells and assert
the rescue (a) detects them, (b) restores a high-tau fit, (c) strictly
improves the total objective; and that on an uncorrupted state the pass
never degrades the objective.
"""

import dataclasses

import numpy as np
import pytest

from scdna_replication_tools_tpu.config import ColumnConfig, PertConfig
from scdna_replication_tools_tpu.data.loader import build_pert_inputs
from scdna_replication_tools_tpu.infer.runner import PertInference
from scdna_replication_tools_tpu.models.pert import (
    constrained,
    from_unit_interval,
    pert_loss,
)

# 24 S cells: indices 0-5 late S (the mirror-prone regime), rest spread
TAUS = np.concatenate([
    np.linspace(0.90, 0.96, 6),
    np.linspace(0.15, 0.80, 18),
])


def _workload(synthetic_frames):
    """PERT-generative reads over the conftest frames with engineered
    per-cell taus (the conftest Poisson draws carry no replication
    structure, so tau would be unidentifiable)."""
    df_s, df_g = (df.copy() for df in synthetic_frames)
    rng = np.random.default_rng(5)
    lamb, a_true, u_true = 0.75, 10.0, 12.0

    def fill(df, s_phase):
        reads = np.empty(len(df), float)
        tau_map = {}
        for i, cid in enumerate(df["cell_id"].drop_duplicates()):
            m = (df["cell_id"] == cid).to_numpy()
            sub = df[m]
            clone = sub["clone_id"].iloc[0]
            rt = sub["rt_A" if clone == "A" else "rt_B"].to_numpy()
            cn = sub["true_somatic_cn"].to_numpy()
            gc = sub["gc"].to_numpy()
            tau = float(TAUS[i]) if s_phase else 0.0
            if s_phase:
                phi = 1.0 / (1.0 + np.exp(-a_true * (tau - (1.0 - rt))))
                rep = (rng.random(rt.size) < phi).astype(float)
            else:
                rep = np.zeros(rt.size)
            theta = u_true * cn * (1.0 + rep) * np.exp(0.5 * gc)
            delta = np.maximum(theta * (1.0 - lamb) / lamb, 1.0)
            reads[m] = rng.negative_binomial(delta, 1.0 - lamb)
            tau_map[cid] = tau
        df["reads"] = reads
        df["state"] = df["true_somatic_cn"].astype(int)
        return tau_map

    tau_map = fill(df_s, True)
    fill(df_g, False)
    s, g1 = build_pert_inputs(df_s, df_g, ColumnConfig())
    true_t = np.array([tau_map[c] for c in s.cell_ids])
    clone_of = df_s.drop_duplicates("cell_id").set_index("cell_id")[
        "clone_id"]
    clone_idx = np.array([0 if clone_of[c] == "A" else 1
                          for c in s.cell_ids], np.int32)
    return s, g1, true_t, clone_idx


def _fit_pipeline(synthetic_frames, **cfg_overrides):
    """steps 1-2 on the engineered-tau workload; kwargs override config."""
    s, g1, true_t, clone_idx = _workload(synthetic_frames)
    cfg_kwargs = dict(max_iter=250, min_iter=60, max_iter_step1=100,
                      min_iter_step1=30, run_step3=False,
                      cn_prior_method="g1_clones", enum_impl="xla",
                      mirror_max_iter=300, mirror_min_iter=50)
    cfg_kwargs.update(cfg_overrides)
    inf = PertInference(s, g1, PertConfig(**cfg_kwargs),
                        clone_idx_s=clone_idx, clone_idx_g1=clone_idx,
                        num_clones=2)
    step1 = inf.run_step1()
    etas = inf.build_etas()
    step2 = inf.run_step2(step1, etas)
    return inf, step2, true_t


@pytest.fixture(scope="module")
def fitted(synthetic_frames):
    return _fit_pipeline(synthetic_frames)


def _corrupt_to_mirror(step2, cells):
    """Move the given cells' params into the mirrored basin: tau -> 0.01
    with u scaled to keep the expected read rate (the degeneracy's other
    self-consistent solution)."""
    params = {k: np.array(v) for k, v in step2.fit.params.items()}
    c = constrained(step2.spec, step2.fit.params, step2.fixed)
    tau_fit = np.asarray(c["tau"])
    for i in cells:
        params["tau_raw"][i] = from_unit_interval(0.01)
        params["u"][i] = params["u"][i] * (1.0 + tau_fit[i]) / 1.01
    import jax.numpy as jnp
    new_params = {k: jnp.asarray(v) for k, v in params.items()}
    return dataclasses.replace(
        step2, fit=dataclasses.replace(step2.fit, params=new_params))


def test_rescue_restores_mirrored_cells(fitted):
    inf, step2, true_t = fitted
    # pick by TRUTH, not position: the loader orders cells
    # lexicographically, so TAUS' positional order is not preserved
    late = list(np.flatnonzero(true_t > 0.85))[:3]
    assert len(late) == 3
    corrupted = _corrupt_to_mirror(step2, late)

    loss_before = float(pert_loss(corrupted.spec, corrupted.fit.params,
                                  corrupted.fixed, corrupted.batch))
    rescued = inf._mirror_rescue(corrupted, corrupted.batch)

    assert inf.mirror_rescue_stats["candidates"] >= len(late)
    assert inf.mirror_rescue_stats["accepted"] >= len(late)

    c = constrained(rescued.spec, rescued.fit.params, rescued.fixed)
    tau = np.asarray(c["tau"])
    for i in late:
        assert tau[i] > 0.5, (
            f"cell {i} stayed mirrored: tau={tau[i]:.3f} "
            f"(true {true_t[i]:.2f})")

    loss_after = float(pert_loss(rescued.spec, rescued.fit.params,
                                 rescued.fixed, rescued.batch))
    assert loss_after < loss_before, (loss_after, loss_before)


def test_rescue_candidate_cap(fitted):
    """mirror_max_cells bounds the sub-fit, most boundary-extreme first."""
    inf, step2, true_t = fitted
    late = list(np.flatnonzero(true_t > 0.85))[:3]
    corrupted = _corrupt_to_mirror(step2, late)
    old_cfg = inf.config
    try:
        inf.config = dataclasses.replace(old_cfg, mirror_max_cells=1)
        rescued = inf._mirror_rescue(corrupted, corrupted.batch)
    finally:
        inf.config = old_cfg
    assert inf.mirror_rescue_stats["candidates"] >= len(late)
    assert inf.mirror_rescue_stats["capped_to"] == 1
    assert inf.mirror_rescue_stats["accepted"] <= 1
    # the one rescued cell is one of the corrupted (most extreme) ones
    c = constrained(rescued.spec, rescued.fit.params, rescued.fixed)
    tau = np.asarray(c["tau"])
    assert sum(tau[i] > 0.5 for i in late) == \
        inf.mirror_rescue_stats["accepted"]


def test_per_cell_objective_decomposes_log_joint(fitted):
    """sum(per_cell_objective) + global priors == log_joint — the
    numerical foundation of the rescue acceptance rule (accepted swaps
    can only increase the total objective)."""
    from scdna_replication_tools_tpu.models.pert import (
        _global_log_prior,
        log_joint,
        per_cell_objective,
    )

    inf, step2, _ = fitted
    spec, params, fixed, batch = (step2.spec, step2.fit.params,
                                  step2.fixed, step2.batch)
    total = float(log_joint(spec, params, fixed, batch))
    per_cell = np.asarray(per_cell_objective(spec, params, fixed, batch))
    glob = float(_global_log_prior(spec, constrained(spec, params, fixed)))
    # log_joint masks per-cell terms; per_cell_objective does not — apply
    # the mask here so the identity also holds for padded batches
    recon = float((per_cell * np.asarray(batch.mask)).sum()) + glob
    assert abs(recon - total) <= abs(total) * 1e-5, (recon, total)


def test_rescue_on_sharded_step2(synthetic_frames):
    """The rescue must work when step 2 ran on a device mesh: sharded
    params/batch materialise host-side for the candidate scan and the
    splice, and the sub-fit runs single-device."""
    inf, step2, true_t = _fit_pipeline(
        synthetic_frames, max_iter=150, min_iter=40, max_iter_step1=60,
        min_iter_step1=20, num_shards=2, mirror_max_iter=200,
        mirror_min_iter=40)
    assert not step2.batch.reads.sharding.is_fully_replicated

    late = [int(np.flatnonzero(true_t > 0.85)[0])]
    corrupted = _corrupt_to_mirror(step2, late)
    rescued = inf._mirror_rescue(corrupted, corrupted.batch)
    assert inf.mirror_rescue_stats["accepted"] >= 1
    c = constrained(rescued.spec, rescued.fit.params, rescued.fixed)
    assert float(np.asarray(c["tau"])[late[0]]) > 0.5


def test_rescue_never_degrades_clean_fit(fitted):
    inf, step2, _ = fitted
    loss_before = float(pert_loss(step2.spec, step2.fit.params,
                                  step2.fixed, step2.batch))
    rescued = inf._mirror_rescue(step2, step2.batch)
    loss_after = float(pert_loss(rescued.spec, rescued.fit.params,
                                 rescued.fixed, rescued.batch))
    # per-cell acceptance: only objective-improving swaps are taken, so
    # the total can only go down (equal when nothing is accepted); allow
    # float32 evaluation noise
    assert loss_after <= loss_before + abs(loss_before) * 1e-6

    # non-candidate cells' params are untouched
    c0 = constrained(step2.spec, step2.fit.params, step2.fixed)
    c1 = constrained(rescued.spec, rescued.fit.params, rescued.fixed)
    tau0, tau1 = np.asarray(c0["tau"]), np.asarray(c1["tau"])
    cfg = inf.config
    non_cand = (tau0 >= cfg.mirror_tau_lo) & (tau0 <= cfg.mirror_tau_hi)
    np.testing.assert_array_equal(tau0[non_cand], tau1[non_cand])
