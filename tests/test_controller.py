"""Adaptive fit controller: policy, chunked driver, audit determinism.

The controller (obs/controller.py + the chunked driver in
infer/svi.py::_fit_map_controlled) closes the observability → control
loop: fits run as jit-compiled fixed-size chunks and between chunks the
flight-recorder signals may early-stop / extend / re-seed / escalate.
These tests pin the contracts that make that safe to ship default-ON:

* the POLICY maps synthetic signal sets to the documented actions, with
  the documented bounds (extension cap, reseed budget, NaN retry
  budget) and never acts on thin evidence;
* the chunked loop is a bit-exact twin of the single whole-budget
  ``lax.while_loop`` when the controller never acts — the restructure
  itself introduces no numeric drift — and ``controller=None`` is
  literally the untouched fixed path (no decisions, same budget);
* DETERMINISM: same seed + same config → byte-identical
  ``control_decision`` sequences (the audit trail is reproducible);
* NaN escalation end-to-end on a toy loss that genuinely poisons
  itself: checkpoint artifact saved, reduced-LR retry, bounded aborts;
* the action vocabulary is a single source of truth: ``ACTIONS`` ==
  the schema enum (pertlint PL010 cross-checks emit sites against it).
"""

import json

import numpy as np
import jax.numpy as jnp
import pytest

from scdna_replication_tools_tpu.infer.runner import _PertLossFn
from scdna_replication_tools_tpu.infer.svi import fit_map
from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    init_params,
)
from scdna_replication_tools_tpu.obs import ACTIONS, ControllerPolicy, decide
from scdna_replication_tools_tpu.obs.schema import load_schema
from scdna_replication_tools_tpu.ops.gc import gc_features

SPEC = PertModelSpec(P=5, K=2, L=1, tau_mode="param")


def _problem(seed=0, num_cells=8, num_loci=30):
    rng = np.random.default_rng(seed)
    reads = rng.poisson(40, (num_cells, num_loci)).astype(np.float32)
    gammas = rng.uniform(0.35, 0.6, num_loci).astype(np.float32)
    etas = np.ones((num_cells, num_loci, SPEC.P), np.float32)
    etas[:, :, 2] = 100.0
    batch = PertBatch(
        reads=jnp.asarray(reads),
        libs=jnp.zeros(num_cells, jnp.int32),
        gamma_feats=gc_features(jnp.asarray(gammas), SPEC.K),
        mask=jnp.ones((num_cells,), jnp.float32),
        etas=jnp.asarray(etas),
    )
    params0 = init_params(SPEC, batch, {},
                          t_init=np.full(num_cells, 0.4, np.float32))
    return params0, batch


# ---------------------------------------------------------------------------
# policy: synthetic signals -> documented actions
# ---------------------------------------------------------------------------

POLICY = ControllerPolicy(max_extra_iters=60, extend_step=50,
                          stop_patience=50, stop_ftol=1e-3, window=16)


def _floor_tail(n_descent=100, n_flat=100, noise=0.02, seed=0):
    """Smooth descent to a floor, then a noisy-but-stagnant tail."""
    rng = np.random.default_rng(seed)
    return (list(np.linspace(100.0, 10.0, n_descent))
            + list(10.0 + noise * rng.standard_normal(n_flat)))


def test_policy_no_decision_while_descending():
    losses = list(np.linspace(100.0, 10.0, 100))
    assert decide(POLICY, losses=losses, it=100, budget=200,
                  min_iter=60) is None


def test_policy_no_decision_on_thin_evidence():
    # below min_iter, and below a full doctor window: never act
    assert decide(POLICY, losses=[5.0, 4.0], it=2, budget=200,
                  min_iter=60) is None
    assert decide(POLICY, losses=_floor_tail(), it=200, budget=400,
                  min_iter=300) is None


def test_policy_stagnant_floor_early_stops_with_ledger():
    losses = _floor_tail()
    d = decide(POLICY, losses=losses, it=200, budget=400, min_iter=60)
    assert d["action"] == "early_stop"
    assert d["iters_saved"] == 200
    assert d["thresholds"]["stop_patience"] == 50
    # the trigger snapshot must let an auditor re-derive the verdict
    assert d["trigger"]["verdict"] == "converged"
    assert d["trigger"]["reason"]


def test_policy_stagnation_is_spike_robust():
    """A transient loss spike inside the patience window must not block
    the stop (the best-loss series is monotone), and a spike must not
    CAUSE a stop while the fit is still genuinely improving."""
    losses = _floor_tail(noise=0.02)
    # catastrophic transient OUTSIDE the doctor window (so the tail
    # reads clean) but inside the 50-iter patience horizon: a plain
    # "no loss improvement" test would see 80.0 and refuse to stop;
    # the monotone best-loss series does not care
    losses[-30] = 80.0
    d = decide(POLICY, losses=losses, it=200, budget=400, min_iter=60)
    assert d is not None and d["action"] == "early_stop"

    improving = list(np.linspace(100.0, 10.0, 200))
    improving[-30] = 80.0
    assert decide(POLICY, losses=improving, it=200, budget=400,
                  min_iter=60) is None


def test_policy_stagnation_anchor_gives_restart_runway():
    """A reseed/NaN-retry restart begins a new trajectory regime: the
    stagnation stop must measure only within it (stagnation_start),
    not cancel the restart against the pre-restart global best it has
    not yet beaten."""
    # regime 1: descent to a 10.0 floor; regime 2 (restart at iter
    # 200): fresh descent from the perturbed state, still above the
    # old best — genuinely improving, but min(losses) is unchanged
    losses = _floor_tail() + list(np.linspace(60.0, 12.0, 100))
    # unanchored, the pre-restart best reads as 100 iters of zero
    # improvement and stops the restarted fit
    d = decide(POLICY, losses=losses, it=300, budget=400, min_iter=60)
    assert d is not None and d["action"] == "early_stop"
    # anchored at the restart, the new regime gets its full patience
    assert decide(POLICY, losses=losses, it=300, budget=400,
                  min_iter=60, stagnation_start=200) is None


def test_policy_in_window_spike_neither_stops_nor_reseeds():
    """A spike INSIDE the doctor window reads oscillating: the stop
    triggers hold off (never stop into very-recent instability) and a
    FIRST unstable read never re-seeds (the persistence gate) — the
    transient costs at most one chunk of deferral."""
    losses = _floor_tail(noise=0.02)
    losses[-10] = 80.0
    assert decide(POLICY, losses=losses, it=200, budget=400,
                  min_iter=60) is None


def test_policy_extend_only_at_exhaustion_and_capped():
    losses = list(np.linspace(100.0, 10.0, 200))
    kw = dict(losses=losses, it=200, budget=200, min_iter=60,
              exhausted=True, grad_norm_first=5.0, grad_norm_last=4.0)
    d = decide(POLICY, **kw)
    assert d["action"] == "extend" and d["iters_granted"] == 50
    # the grant is clipped by the remaining headroom...
    d = decide(POLICY, extra_granted=POLICY.max_extra_iters - 10, **kw)
    assert d["iters_granted"] == 10
    # ...and a spent cap grants nothing
    assert decide(POLICY, extra_granted=POLICY.max_extra_iters,
                  **kw) is None


def test_policy_no_extend_when_best_loss_is_stagnant():
    """At exhaustion, a 'plateaued' tail whose BEST loss went nowhere
    over the patience horizon is churn, not progress — no grant."""
    losses = _floor_tail(n_descent=100, n_flat=100, noise=0.0)
    assert decide(POLICY, losses=losses, it=200, budget=200,
                  min_iter=60, exhausted=True, grad_norm_first=5.0,
                  grad_norm_last=4.0) is None


def test_policy_oscillation_reseeds_only_when_persistent():
    """Re-seed needs oscillation on two CONSECUTIVE evaluations: the
    first unstable read only parks the verdict (no action); the second
    fires, and the reseed budget bounds it."""
    from scdna_replication_tools_tpu.obs import evaluate

    rng = np.random.default_rng(3)
    base = list(np.linspace(100.0, 60.0, 100))
    osc = base + list(60.0 + 15.0 * (-1.0) ** np.arange(60)
                      + rng.standard_normal(60))
    d, verdict = evaluate(POLICY, losses=osc, it=160, budget=400,
                          min_iter=60)
    assert d is None and verdict == "oscillating"
    d, _ = evaluate(POLICY, losses=osc, it=160, budget=400, min_iter=60,
                    prev_verdict=verdict)
    assert d is not None and d["action"] == "reseed"
    assert "consecutive" in d["detail"]
    d, _ = evaluate(POLICY, losses=osc, it=160, budget=400, min_iter=60,
                    prev_verdict=verdict,
                    reseeds_done=POLICY.max_reseeds)
    assert d is None


def test_policy_nan_escalates_then_aborts():
    d = decide(POLICY, losses=[1.0, float("nan")], it=2, budget=200,
               min_iter=60, nan=True)
    assert d["action"] == "escalate" and d["outcome"] == "retry"
    d = decide(POLICY, losses=[1.0, float("nan")], it=2, budget=200,
               min_iter=60, nan=True,
               nan_retries_done=POLICY.max_nan_retries)
    assert d["outcome"] == "abort"


def test_actions_vocabulary_matches_schema_enum():
    schema = load_schema()
    enum = schema["definitions"]["control_decision"]["properties"][
        "action"]["enum"]
    assert set(ACTIONS) == set(enum)


# ---------------------------------------------------------------------------
# chunked driver: parity, determinism, audit trail
# ---------------------------------------------------------------------------

# a policy that can never act: no stagnation rule, a doctor window no
# partial tail will ever fill, no extension headroom
INERT = ControllerPolicy(max_extra_iters=0, stop_patience=0,
                         window=10**6)


def test_inert_controller_reproduces_fixed_path_bit_exactly():
    """The chunked outer loop is a numeric no-op: with a controller
    that never acts, trajectory AND params must equal the single
    whole-budget ``lax.while_loop`` bit for bit."""
    loss = _PertLossFn(spec=SPEC)
    params_a, batch_a = _problem(seed=2)
    fixed = fit_map(loss, params_a, ({}, batch_a), max_iter=40,
                    min_iter=40, diag_every=10)
    params_b, batch_b = _problem(seed=2)
    chunked = fit_map(loss, params_b, ({}, batch_b), max_iter=40,
                      min_iter=40, diag_every=10, controller=INERT)
    assert chunked.decisions == []
    assert chunked.budget == fixed.budget == 40
    np.testing.assert_array_equal(fixed.losses, chunked.losses)
    for k in fixed.params:
        np.testing.assert_array_equal(np.asarray(fixed.params[k]),
                                      np.asarray(chunked.params[k]))
    # the ring buffer sampled the same iterations with the same values
    np.testing.assert_array_equal(fixed.diagnostics["loss"],
                                  chunked.diagnostics["loss"])


def test_controller_none_is_the_fixed_path():
    params0, batch = _problem(seed=4)
    fit = fit_map(_PertLossFn(spec=SPEC), params0, ({}, batch),
                  max_iter=10, min_iter=10, diag_every=5)
    assert fit.decisions == []
    assert fit.budget == 10


def _eager_stop_fit(seed=5):
    """A controlled fit configured so the stagnation stop genuinely
    fires inside the budget (loose ftol, short patience)."""
    policy = ControllerPolicy(max_extra_iters=0, stop_patience=10,
                              stop_ftol=0.02, window=16)
    params0, batch = _problem(seed=seed)
    return fit_map(_PertLossFn(spec=SPEC), params0, ({}, batch),
                   max_iter=120, min_iter=20, diag_every=10,
                   controller=policy)


def test_early_stop_reclaims_budget_and_audits():
    fit = _eager_stop_fit()
    assert fit.decisions, "stagnation stop never fired on this fixture"
    last = fit.decisions[-1]
    assert last["action"] == "early_stop"
    assert fit.num_iters < 120
    assert last["iters_saved"] == 120 - fit.num_iters
    assert last["iter"] == fit.num_iters
    # trajectory is truncated at the stop, all real samples
    assert len(fit.losses) == fit.num_iters
    assert np.isfinite(fit.losses).all()


def test_decision_trail_is_byte_identical_across_reruns():
    """Same seed + same config → the audit trail serialises to the
    SAME bytes (the reproducibility contract of adaptive fits)."""
    a, b = _eager_stop_fit(seed=6), _eager_stop_fit(seed=6)
    assert json.dumps(a.decisions, sort_keys=True) \
        == json.dumps(b.decisions, sort_keys=True)
    np.testing.assert_array_equal(a.losses, b.losses)
    for k in a.params:
        np.testing.assert_array_equal(np.asarray(a.params[k]),
                                      np.asarray(b.params[k]))


# ---------------------------------------------------------------------------
# NaN escalation end-to-end (toy self-poisoning loss)
# ---------------------------------------------------------------------------


def _poison_loss(params, ceiling):
    # smooth descent toward x=10 that walks off a sqrt cliff at
    # x=ceiling: past it the loss is NaN, exactly the mid-fit poisoning
    # the escalation path exists for
    x = params["x"]
    return jnp.sum((x - 10.0) ** 2) + jnp.sum(jnp.sqrt(ceiling - x))


def test_nan_escalation_checkpoints_retries_and_bounds(tmp_path):
    params0 = {"x": jnp.zeros((4,), jnp.float32)}
    fit = fit_map(_poison_loss, params0, (4.0,), max_iter=400,
                  min_iter=1, learning_rate=0.5, diag_every=10,
                  controller=ControllerPolicy(max_extra_iters=0,
                                              stop_patience=0,
                                              window=10**6),
                  escalate_dir=str(tmp_path), escalate_tag="toy")
    escalations = [d for d in fit.decisions if d["action"] == "escalate"]
    assert escalations, "the poisoned fit never escalated"
    assert escalations[0]["outcome"] == "retry"
    assert "lr x 0.1" in escalations[0]["detail"]
    # the diagnosable artifact exists and carries a finite best state
    ckpt = tmp_path / "pert_toy_nan.npz"
    assert ckpt.exists()
    assert str(ckpt) in escalations[0]["detail"]
    saved = np.load(ckpt)
    assert np.isfinite(saved["param.x"]).all()
    # retries are bounded: at most max_nan_retries retry outcomes, and
    # a second escalation (if any) aborts
    outcomes = [d["outcome"] for d in escalations]
    assert outcomes.count("retry") <= 1
    if len(escalations) > 1:
        assert outcomes[-1] == "abort"
        assert fit.nan_abort


def test_nan_escalation_is_deterministic(tmp_path):
    runs = []
    for sub in ("a", "b"):
        params0 = {"x": jnp.zeros((4,), jnp.float32)}
        fit = fit_map(_poison_loss, params0, (4.0,), max_iter=400,
                      min_iter=1, learning_rate=0.5, diag_every=10,
                      controller=ControllerPolicy(max_extra_iters=0,
                                                  stop_patience=0,
                                                  window=10**6),
                      escalate_dir=str(tmp_path / sub),
                      escalate_tag="toy")
        # strip the checkpoint path (varies with tmp dir by design)
        trail = [{k: v for k, v in d.items() if k != "detail"}
                 for d in fit.decisions]
        runs.append(json.dumps(trail, sort_keys=True))
    assert runs[0] == runs[1]


# ---------------------------------------------------------------------------
# reseed mechanism (driver level)
# ---------------------------------------------------------------------------


def test_perturb_params_is_deterministic_and_small():
    from scdna_replication_tools_tpu.infer.svi import _perturb_params

    params = {"a": jnp.ones((8,), jnp.float32),
              "b": jnp.linspace(-2.0, 2.0, 16).astype(jnp.float32)}
    p1 = _perturb_params(params, 0.02, seed=7, salt=1)
    p2 = _perturb_params(params, 0.02, seed=7, salt=1)
    for k in params:
        np.testing.assert_array_equal(np.asarray(p1[k]),
                                      np.asarray(p2[k]))
        # perturbed, but on the scale of the leaf spread, not beyond
        assert not np.array_equal(np.asarray(p1[k]),
                                  np.asarray(params[k]))
        assert np.max(np.abs(np.asarray(p1[k]) - np.asarray(params[k]))) \
            < 1.0
    p3 = _perturb_params(params, 0.02, seed=7, salt=2)
    assert not np.array_equal(np.asarray(p3["a"]), np.asarray(p1["a"]))


def test_controller_requires_diag_cadence():
    """controller without a flight recorder (diag_every=0) falls back
    to the fixed path rather than acting blind."""
    params0, batch = _problem(seed=8)
    fit = fit_map(_PertLossFn(spec=SPEC), params0, ({}, batch),
                  max_iter=10, min_iter=10, diag_every=0,
                  controller=POLICY)
    assert fit.decisions == []
    assert fit.num_iters == 10
