"""Causal span tracing (obs/spans.py, schema v8) + the satellites:
Perfetto export/stitching (tools/pert_trace.py), the serve queue-wait
span, the worker status surface, fleet/trace_summary JSON formats.

The module-scoped ``traced_pair`` fixture runs the SAME tiny chunked
fit twice (same seed) under a tracer, so the determinism, schema,
export and report tests all read from two cheap runs that share one
compiled program.
"""

import json
import os
import pathlib
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from scdna_replication_tools_tpu.infer import svi
from scdna_replication_tools_tpu.infer.runner import _PertLossFn
from scdna_replication_tools_tpu.infer.svi import fit_map
from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    init_params,
)
from scdna_replication_tools_tpu.obs import spans as spans_mod
from scdna_replication_tools_tpu.obs.controller import ControllerPolicy
from scdna_replication_tools_tpu.obs.runlog import RunLog
from scdna_replication_tools_tpu.obs.schema import validate_run
from scdna_replication_tools_tpu.obs.summary import summarize_run
from scdna_replication_tools_tpu.ops.gc import gc_features
from scdna_replication_tools_tpu.serve import ServeWorker, SpoolQueue
from scdna_replication_tools_tpu.utils.profiling import PhaseTimer

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from tools import pert_trace  # noqa: E402

SPEC = PertModelSpec(P=5, K=2, L=1, tau_mode="param")

# the two span-payload fields that legitimately differ across reruns;
# everything else is the determinism contract
UNSTABLE_SPAN_FIELDS = ("start_unix", "duration_seconds")


def _problem(num_cells=16, num_loci=64, seed=0):
    rng = np.random.default_rng(seed)
    reads = rng.poisson(40, (num_cells, num_loci)).astype(np.float32)
    gammas = rng.uniform(0.35, 0.6, num_loci).astype(np.float32)
    etas = np.ones((num_cells, num_loci, SPEC.P), np.float32)
    etas[:, :, 2] = 100.0
    batch = PertBatch(
        reads=jnp.asarray(reads),
        libs=jnp.zeros(num_cells, jnp.int32),
        gamma_feats=gc_features(jnp.asarray(gammas), SPEC.K),
        mask=jnp.ones((num_cells,), jnp.float32),
        etas=jnp.asarray(etas),
    )
    params0 = init_params(SPEC, batch, {},
                          t_init=np.full(num_cells, 0.4, np.float32))
    return params0, ({}, batch)


def _traced_fit(path, seed=0, tracer=None, iters=75):
    """One chunked fit under a RunLog session with span tracing: the
    root 'run' span, phase spans through the on_add chain, and
    fit/chunk spans through the runlog.current() seam."""
    log = RunLog(str(path) if path else None)
    if tracer is not None:
        spans_mod.attach_tracer(log, tracer)
    timer = PhaseTimer()
    spans_mod.attach_phase_sink(timer, tracer)
    params0, loss_args = _problem(seed=seed)
    policy = ControllerPolicy(max_extra_iters=0)
    with log.session(config={"seed": seed}, timer=timer):
        with timer.phase("step2/build"):
            pass
        fit = fit_map(_PertLossFn(spec=SPEC), params0, loss_args,
                      max_iter=iters, min_iter=iters, diag_every=25,
                      controller=policy)
        timer.add("step2/fit", fit.timings["fit"])
        log.emit("fit_end", step="step2", iters=int(fit.num_iters),
                 converged=bool(fit.converged),
                 nan_abort=bool(fit.nan_abort),
                 wall_seconds=round(fit.timings["fit"], 4))
    return fit


@pytest.fixture(scope="module")
def traced_pair(tmp_path_factory):
    root = tmp_path_factory.mktemp("spans")
    paths = []
    for i in range(2):
        p = root / f"run_{i}.jsonl"
        tracer = spans_mod.SpanTracer(
            trace_id=spans_mod.derive_trace_id("same-seed"))
        _traced_fit(p, seed=0, tracer=tracer)
        paths.append(p)
    return paths


def _events(path):
    return [json.loads(line) for line in
            pathlib.Path(path).read_text().splitlines()]


def _span_events(path):
    return [e for e in _events(path) if e["event"] == "span_end"]


# ---------------------------------------------------------------------------
# determinism + schema
# ---------------------------------------------------------------------------


def test_span_tree_deterministic_across_same_seed_reruns(traced_pair):
    """The span TREE — names, ids, parentage, attrs, order — is
    byte-identical across same-seed reruns; only the wall-clock fields
    differ.  The byte-stability analog of the metrics-snapshot pin."""
    def stable_tree(path):
        out = []
        for ev in _span_events(path):
            row = {k: v for k, v in ev.items()
                   if k not in UNSTABLE_SPAN_FIELDS + ("t",)}
            out.append(row)
        return json.dumps(out, sort_keys=True)

    a, b = traced_pair
    assert _span_events(a), "traced run produced no spans"
    assert stable_tree(a) == stable_tree(b)


def test_traced_runs_are_schema_v8_valid(traced_pair):
    for path in traced_pair:
        assert validate_run(path) == []


def test_chunk_spans_carry_controller_verdicts(traced_pair):
    chunks = [e for e in _span_events(traced_pair[0])
              if e["name"] == "fit/chunk"]
    assert len(chunks) == 3  # 75 iters / 25-iter chunks
    for i, ev in enumerate(chunks, start=1):
        attrs = ev["attrs"]
        assert attrs["chunk"] == i
        assert attrs["iter_end"] - attrs["iter_start"] == 25
        assert attrs["action"] in ("continue", "early_stop", "extend",
                                   "reseed", "converged", "escalate")
    # every chunk parents under the root 'run' span
    root = next(e for e in _span_events(traced_pair[0])
                if e["name"] == "run")
    assert all(c["parent_id"] == root["span_id"] for c in chunks)


def test_events_carry_span_envelope_while_span_open(traced_pair):
    events = _events(traced_pair[0])
    run_start = events[0]
    assert run_start["event"] == "run_start"
    assert run_start["trace_id"] == spans_mod.derive_trace_id(
        "same-seed")
    phases = [e for e in events if e["event"] == "phase"]
    assert phases and all("span" in e for e in phases)
    # run_end is emitted AFTER the root span closed: no envelope
    assert "span" not in events[-1] and events[-1]["event"] == "run_end"


def test_tracing_off_log_carries_no_span_bytes(tmp_path):
    """The v8 gating contract: without a tracer the stream has no
    span_end events, no span envelopes and no trace_id — nothing a
    pre-v8 consumer would not recognise."""
    path = tmp_path / "untraced.jsonl"
    _traced_fit(path, seed=0, tracer=None)
    events = _events(path)
    assert events and events[0]["event"] == "run_start"
    assert "trace_id" not in events[0]
    assert not any(e["event"] == "span_end" for e in events)
    assert not any("span" in e for e in events)
    assert validate_run(path) == []


def test_pre_v8_artifact_still_validates_and_summarizes():
    """Backward tolerance: a committed pre-v8 log validates against the
    current schema and summarizes with an empty spans section."""
    path = REPO_ROOT / "artifacts" / "RUNLOG_r09_metrics_cpu.jsonl"
    assert validate_run(path) == []
    summary = summarize_run(path)
    assert summary["spans"] == {"count": 0, "by_name": {},
                                "trace_ids": []}
    assert summary["trace_id"] is None


# ---------------------------------------------------------------------------
# Perfetto export + stitching
# ---------------------------------------------------------------------------


def test_perfetto_export_parses_validates_and_round_trips(traced_pair,
                                                          tmp_path):
    out = tmp_path / "trace.json"
    rc = pert_trace.main(["export", "--perfetto",
                          str(traced_pair[0]), "--out", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert pert_trace.validate_trace(doc) == []
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(slices) == len(_span_events(traced_pair[0]))
    # round-trip: re-serialising the parsed document is stable
    assert json.loads(json.dumps(doc)) == doc
    # the CLI validator agrees
    assert pert_trace.main(["validate", str(out)]) == 0


def test_perfetto_validator_rejects_malformed(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"traceEvents": [
        {"name": "x"},                          # no ph
        {"ph": "X", "name": "y", "ts": 0},      # no dur/pid/tid
    ]}))
    assert pert_trace.main(["validate", str(bad)]) == 1
    errors = pert_trace.validate_trace(json.loads(bad.read_text()))
    assert any("missing ph" in e for e in errors)
    assert any("dur" in e for e in errors)
    # a NON-NUMERIC dur must be reported, not crash the comparison
    errors = pert_trace.validate_trace({"traceEvents": [
        {"ph": "X", "name": "a", "ts": 0, "dur": "abc",
         "pid": 1, "tid": 1}]})
    assert errors == ["traceEvents[0]: X event lacks numeric dur"]


def test_multiprocess_merge_stitches_two_logs_into_one_trace(tmp_path):
    """Two per-process RunLogs of one trace (same trace id, different
    process_index — the multi-host shape) merge into ONE timeline:
    shared lane, per-process pid rows."""
    trace_id = spans_mod.derive_trace_id("mh-run")
    paths = []
    for proc in (0, 1):
        p = tmp_path / f"proc{proc}.jsonl"
        log = RunLog(str(p))
        tracer = spans_mod.SpanTracer(trace_id=trace_id,
                                      process_index=proc)
        spans_mod.attach_tracer(log, tracer)
        with log.session(config={"seed": 0}):
            with tracer.span("fit/chunk", chunk=1, iter_start=0,
                             iter_end=25, action="continue"):
                time.sleep(0.01)
        paths.append(p)
    out = tmp_path / "merged.json"
    assert pert_trace.main(["export", str(paths[0]), str(paths[1]),
                            "--out", str(out)]) == 0
    doc = json.loads(out.read_text())
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in slices} == {0, 1}
    assert len({e["tid"] for e in slices}) == 1  # one stitched lane
    # both logs stamped the shared trace id into run_start
    for p in paths:
        assert _events(p)[0]["trace_id"] == trace_id


def test_export_survives_same_instant_same_name_spans():
    """Two spans tying on (start, dur, name, pid, lane) — e.g. two
    zero-second phases in the same clock tick — must not make the
    sort fall through to comparing the args dicts (TypeError)."""
    def span(sid, i):
        return {"name": "a", "trace_id": "t", "span_id": sid,
                "parent_id": None, "start_unix": 5.0,
                "duration_seconds": 0.0, "process_index": 0,
                "attrs": {"i": i}}

    log = {"path": "x.jsonl", "trace_id": "t", "request_id": None,
           "process_index": 0, "spans": [span("1", 1), span("2", 2)],
           "phases": []}
    doc = pert_trace.build_trace([log])
    assert pert_trace.validate_trace(doc) == []
    assert sum(1 for e in doc["traceEvents"] if e["ph"] == "X") == 2


def _write_jsonl(path, events):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(e) + "\n" for e in events))


def test_waterfall_pools_spans_from_every_worker_log(tmp_path, capsys):
    """Multi-worker spool: a request's spool-side spans live in
    whichever worker served it — the waterfall must read ALL worker
    logs, not just the newest (which would silently zero the other
    workers' queue_wait/admission components)."""
    spool = tmp_path / "spool"

    def worker_log(name, rid):
        _write_jsonl(spool / name, [
            {"event": "run_start", "seq": 0, "t": 0.0,
             "schema_version": 8, "run_name": "pert_serve", "pid": 1,
             "started_unix": 100.0},
            {"event": "span_end", "seq": 1, "t": 0.1, "name": "request",
             "trace_id": rid, "span_id": "1", "parent_id": None,
             "start_unix": 100.0, "duration_seconds": 2.0,
             "process_index": 0, "attrs": {"request_id": rid}},
            {"event": "span_end", "seq": 2, "t": 0.2,
             "name": "queue_wait", "trace_id": rid, "span_id": "2",
             "parent_id": "1", "start_unix": 99.0,
             "duration_seconds": 1.0, "process_index": 0,
             "attrs": {"request_id": rid}},
        ])

    worker_log("worker_a.jsonl", "r1")
    worker_log("worker_b.jsonl", "r2")
    for rid in ("r1", "r2"):
        _write_jsonl(spool / "results" / rid / "run.jsonl", [
            {"event": "run_start", "seq": 0, "t": 0.0,
             "schema_version": 8, "run_name": "pert", "pid": 1,
             "started_unix": 100.5, "request_id": rid},
            {"event": "span_end", "seq": 1, "t": 0.5,
             "name": "step2/fit", "trace_id": rid, "span_id": "1",
             "parent_id": None, "start_unix": 100.5,
             "duration_seconds": 0.5, "process_index": 0,
             "attrs": {"kind": "phase"}},
        ])
    capsys.readouterr()
    assert pert_trace.main(["waterfall", "--spool", str(spool)]) == 0
    doc = json.loads(capsys.readouterr().out)
    for rid in ("r1", "r2"):
        wf = doc["requests"][rid]
        assert wf["queue_wait"] == 1.0, (rid, wf)
        assert wf["fit"] == 0.5
        assert wf["total_seconds"] == 2.0


def test_request_waterfall_has_full_component_vocabulary(traced_pair):
    wf = pert_trace.request_waterfall(None, traced_pair[0])
    for comp in pert_trace.WATERFALL_COMPONENTS:
        assert comp in wf
    assert wf["fit"] > 0
    assert wf["queue_wait"] == 0.0  # no worker log: honest zero


def test_report_renders_where_the_time_went(traced_pair):
    from tools.pert_report import render_report

    report = render_report(traced_pair[0])
    assert "## Where the time went (spans)" in report
    assert "| fit |" in report
    assert "`fit/chunk`" in report
    # an untraced/pre-v8 log renders the placeholder instead
    old = render_report(REPO_ROOT / "artifacts"
                        / "RUNLOG_r09_metrics_cpu.jsonl")
    assert "pre-v8 run log" in old


# ---------------------------------------------------------------------------
# the serve queue-wait span + worker status surface
# ---------------------------------------------------------------------------


def _submit_bad_request(queue, rid, mtime=None):
    queue.submit("/nonexistent/s.tsv", "/nonexistent/g1.tsv",
                 request_id=rid)
    if mtime is not None:
        os.utime(queue.root / "pending" / f"{rid}.json",
                 (mtime, mtime))


def test_queue_wait_span_matches_ticket_timestamps(tmp_path):
    """The queue-crossing span is measured from the pending ticket's
    mtime (the atomic-commit instant) to the claim — and request_start's
    queue_wait_seconds + the pert_serve_queue_wait_seconds histogram
    carry the same quantity."""
    q = SpoolQueue(tmp_path / "spool")
    pinned = time.time() - 7.5
    _submit_bad_request(q, "waits", mtime=pinned)
    worker = ServeWorker(q, max_requests=1, exit_when_idle=True)
    stats = worker.run()
    events = _events(stats["worker_log"])
    qw_span = next(e for e in events if e["event"] == "span_end"
                   and e["name"] == "queue_wait")
    start = next(e for e in events if e["event"] == "request_start")
    assert abs(qw_span["start_unix"] - pinned) < 0.5
    assert qw_span["duration_seconds"] >= 7.0
    assert abs(start["queue_wait_seconds"]
               - qw_span["duration_seconds"]) < 0.5
    # the worker registry's histogram observed it (satellite: the
    # queue-wait metric fed from the queue-crossing span)
    text = worker.registry.to_prometheus_text()
    assert "pert_serve_queue_wait_seconds_count 1" in text
    assert validate_run(stats["worker_log"]) == []


def test_worker_log_span_lifecycle_per_request(tmp_path):
    """Every request opens a 'request' root span whose trace id is the
    ticket's; queue_wait and admission nest under it; the tracer is
    detached between requests."""
    q = SpoolQueue(tmp_path / "spool")
    _submit_bad_request(q, "r_a")
    _submit_bad_request(q, "r_b")
    worker = ServeWorker(q, max_requests=2, exit_when_idle=True)
    stats = worker.run()
    spans = _span_events(stats["worker_log"])
    requests = [e for e in spans if e["name"] == "request"]
    assert [e["attrs"]["request_id"] for e in requests] == ["r_a", "r_b"]
    assert {e["trace_id"] for e in requests} == {
        spans_mod.derive_trace_id("r_a"),
        spans_mod.derive_trace_id("r_b")}
    for req in requests:
        children = [e for e in spans
                    if e["parent_id"] == req["span_id"]
                    and e["trace_id"] == req["trace_id"]]
        assert {e["name"] for e in children} == {"queue_wait",
                                                "admission"}
    assert worker.worker_log.tracer is None  # detached after drain


def test_worker_status_json_atomic_and_heartbeat_fresh(tmp_path):
    """status.json: always a complete JSON document (atomic replace),
    heartbeat-fresh while the worker idles, terminal state on exit."""
    q = SpoolQueue(tmp_path / "spool")
    worker = ServeWorker(q, poll_interval=0.1)
    result = {}

    def _run():
        result["stats"] = worker.run()

    thread = threading.Thread(target=_run, daemon=True)
    thread.start()
    try:
        deadline = time.monotonic() + 30
        seen = []
        while time.monotonic() < deadline and len(seen) < 2:
            try:
                doc = json.loads(q.status_path.read_text())
            except (OSError, ValueError):
                time.sleep(0.05)
                continue
            # every read parses completely — the atomicity contract
            assert doc["kind"] == "pert_serve_status"
            if not seen or doc["updated_unix"] > seen[-1]:
                seen.append(doc["updated_unix"])
            time.sleep(0.15)
        assert len(seen) >= 2, "heartbeat never advanced updated_unix"
    finally:
        worker.request_drain()
        thread.join(timeout=30)
    assert not thread.is_alive()
    final = json.loads(q.status_path.read_text())
    assert final["state"] == "stopped"
    assert final["queue_depth"] == 0
    assert final["in_flight"] is None
    assert final["processed"]["total"] == 0
    assert "buckets_served" in final and "recent" in final


def test_worker_status_records_outcomes_and_queue(tmp_path):
    q = SpoolQueue(tmp_path / "spool")
    _submit_bad_request(q, "r_fail")
    worker = ServeWorker(q, max_requests=1, exit_when_idle=True)
    worker.run()
    doc = json.loads(q.status_path.read_text())
    assert doc["by_status"] == {"failed": 1}
    assert doc["processed"]["total"] == 1
    assert [o["request_id"] for o in doc["recent"]] == ["r_fail"]
    assert doc["state"] == "stopped"


def test_span_ids_unique_across_stitched_tracers():
    """Several tracers share one trace id (the worker's request tracer
    + the request run's handoff tracer; every host of a multi-process
    run) — their namespaced counters must not collide, or the
    parent_id→span_id join across stitched logs turns cyclic (a 'run'
    span that is its own parent)."""
    worker = spans_mod.SpanTracer(trace_id="shared")
    req = worker.begin("request", request_id="r")
    handoff = spans_mod.SpanTracer.from_trace_parent(
        worker.trace_parent(req))
    assert handoff.trace_id == "shared"
    run_span = handoff.begin("run")
    assert run_span.parent_id == req.span_id
    assert run_span.span_id != req.span_id
    # multi-host: same trace id on another process, disjoint ids
    peer = spans_mod.SpanTracer(trace_id="shared", process_index=1)
    assert peer.begin("run").span_id != req.span_id
    # and the namespacing is deterministic (rerun -> same ids)
    handoff2 = spans_mod.SpanTracer.from_trace_parent(
        worker.trace_parent(req))
    assert handoff2.begin("run").span_id == run_span.span_id


def test_last_closed_span_is_the_mid_fit_progress_note():
    """The status heartbeat's "needle": the most recently completed
    span in the process, updated on every span close — the signal
    that keeps moving while the worker thread is inside a fit."""
    tracer = spans_mod.SpanTracer(trace_id="note")
    with tracer.span("fit/chunk", chunk=1, iter_start=0, iter_end=25,
                     action="continue"):
        pass
    note = spans_mod.last_closed_span()
    assert note["name"] == "fit/chunk" and note["trace_id"] == "note"
    assert isinstance(note["end_unix"], float)


def test_serve_status_cli_renders_worker_surface(tmp_path, capsys):
    from scdna_replication_tools_tpu.serve.cli import main as serve_main

    q = SpoolQueue(tmp_path / "spool")
    _submit_bad_request(q, "r_cli")
    ServeWorker(q, max_requests=1, exit_when_idle=True).run()
    capsys.readouterr()
    assert serve_main(["status", "--spool", str(q.root)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["worker"]["kind"] == "pert_serve_status"
    assert doc["worker"]["state"] == "stopped"
    assert isinstance(doc["worker"]["age_seconds"], float)
    assert [r["request_id"] for r in doc["requests"]] == ["r_cli"]
    # a spool no worker ever ran on reports worker=null, not an error
    q2 = SpoolQueue(tmp_path / "spool2")
    q2.ensure_dirs()
    capsys.readouterr()
    assert serve_main(["status", "--spool", str(q2.root)]) == 0
    assert json.loads(capsys.readouterr().out)["worker"] is None


def test_worker_no_trace_spans_mutes_span_material(tmp_path):
    q = SpoolQueue(tmp_path / "spool")
    _submit_bad_request(q, "r_mute")
    worker = ServeWorker(q, max_requests=1, exit_when_idle=True,
                         trace_spans=False)
    stats = worker.run()
    events = _events(stats["worker_log"])
    assert not any(e["event"] == "span_end" for e in events)
    assert not any("span" in e for e in events)
    # queue-wait is still measured (ticket timestamps, no span needed)
    start = next(e for e in events if e["event"] == "request_start")
    assert start["queue_wait_seconds"] is not None


# ---------------------------------------------------------------------------
# satellites: fleet --format json, trace_summary --json + full paths
# ---------------------------------------------------------------------------


def test_fleet_query_and_trend_format_json(traced_pair, tmp_path,
                                           capsys):
    from tools import pert_fleet

    index = tmp_path / "index.json"
    assert pert_fleet.main(["index", "--roots", str(traced_pair[0]),
                            str(traced_pair[1]),
                            "--out", str(index)]) == 0
    capsys.readouterr()
    assert pert_fleet.main(["query", "--index", str(index),
                            "--format", "json"]) == 0
    records = json.loads(capsys.readouterr().out)
    assert len(records) == 2 and records[0]["run_name"] == "pert"

    out = tmp_path / "trend.json"
    assert pert_fleet.main(["trend", "--index", str(index),
                            "--format", "json", "--metric",
                            "pert_fit_iters_total",
                            "--out", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert doc["kind"] == "pert_fleet_trend"
    assert doc["num_runs"] == 2
    series = doc["metrics"]["pert_fit_iters_total"]
    assert series["values"] == [75, 75]
    assert [r["file"] for r in series["runs"]] == ["run_0.jsonl",
                                                   "run_1.jsonl"]


def _write_fake_trace(profile_dir: pathlib.Path, events):
    run = profile_dir / "plugins" / "profile" / "run1"
    run.mkdir(parents=True)
    (run / "host.trace.json").write_text(json.dumps(
        {"traceEvents": events}))


def test_trace_summary_keys_scopes_by_full_path(tmp_path, capsys):
    """The collision fix: two same-leaf scopes under DIFFERENT parents
    stay distinct rows (they used to merge silently into one
    innermost-leaf key)."""
    from tools.trace_summary import main as ts_main
    from tools.trace_summary import scope_totals

    _write_fake_trace(tmp_path, [
        {"ph": "X", "name": "pert/decode/pert/fetch/mul", "dur": 1000},
        {"ph": "X", "name": "pert/qc_entropy/pert/fetch/add",
         "dur": 2000},
        {"ph": "X", "name": "pert/fit_step/fusion", "dur": 4000},
        {"ph": "M", "name": "meta"},
    ])
    totals = scope_totals(str(tmp_path))
    assert totals == {"pert/decode/pert/fetch": 0.001,
                      "pert/qc_entropy/pert/fetch": 0.002,
                      "pert/fit_step": 0.004}
    # --json: the machine-readable twin
    capsys.readouterr()
    ts_main([str(tmp_path), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["scope_seconds"]["pert/decode/pert/fetch"] == 0.001
    assert len(doc["scope_seconds"]) == 3


def test_span_registry_covers_every_literal_code_site():
    """The registry and the code agree: every name the PL014 fixture
    relies on exists, and the names the package opens are registered
    (the lint gate enforces this; the test documents the contract)."""
    names = spans_mod.registry_span_names()
    assert {"run", "request", "queue_wait", "admission",
            "stream_back", "fit/chunk"} <= names


# ---------------------------------------------------------------------------
# overhead guard
# ---------------------------------------------------------------------------


def test_span_tracing_overhead_below_2_percent(tmp_path):
    """The acceptance bar: tracing-on adds <2% to the chunked fit wall
    at the smoke shape.  Same methodology as the PR-4/5/9 guards: both
    configurations pre-compiled, alternating timed runs, best-of-N,
    and BOTH arms pay the same RunLog session (the delta under test is
    the tracer alone).  The tracer does NO in-loop device work (one
    record_span per chunk + one JSONL line), so the true delta is
    noise; the absolute slack absorbs scheduler jitter on a contended
    box."""
    svi.clear_program_cache()

    def one_fit(traced, seed):
        tracer = spans_mod.SpanTracer(
            trace_id=spans_mod.derive_trace_id("ovh")) if traced \
            else None
        path = tmp_path / ("traced.jsonl" if traced else "base.jsonl")
        return _traced_fit(path, seed=seed, tracer=tracer,
                           iters=60).timings["fit"]

    one_fit(False, seed=0)   # compile outside the timed region
    one_fit(True, seed=0)
    base, traced = [], []
    for rep in range(1, 8):
        base.append(one_fit(False, seed=rep))
        traced.append(one_fit(True, seed=rep))
    base_wall, traced_wall = min(base), min(traced)
    assert traced_wall <= base_wall * 1.02 + 0.05, \
        (f"span tracing costs {(traced_wall / base_wall - 1):.1%} of "
         f"the fit wall (base {base_wall:.3f}s vs traced "
         f"{traced_wall:.3f}s)")
