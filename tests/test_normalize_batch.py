"""Batched normalize-by-cell engine: parity, unbounded rounds, scale.

Round-2 verdict item 1: the batched C++ changepoint kernel
(native/segment.cpp) must actually drive ``normalize_by_cell`` — all S
cells advance through the flattening rounds in lock step, one
``find_breakpoints_batch`` call per round — and must agree bit-for-bit
with the per-cell reference-shaped loop (kept as ``engine='loop'``).

Also covers round-2 verdict item 9: the flattening loops are unbounded
by default, exactly like the reference's ``while True``
(reference: normalize_by_cell.py:44, 72) — a profile with >20 real CNA
segments must get all of them nominated, not stop at an arbitrary cap.
"""

import os

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.pipeline.normalize import (
    identify_changepoint_segs,
    normalize_by_cell,
    remove_cell_specific_CNAs_batch,
)
from scdna_replication_tools_tpu.pipeline.segment import (
    find_breakpoints,
    find_breakpoints_batch,
)


def _expected_pair(y, n_bkps):
    bkps = find_breakpoints(y, n_bkps)
    if n_bkps == 2:
        return list(bkps[:2]) if len(bkps) == 3 else [-1, -1]
    return [bkps[0], -1] if len(bkps) == 2 else [-1, -1]


@pytest.mark.parametrize("n_bkps", [1, 2])
def test_batch_kernel_matches_python_oracle(n_bkps):
    """The C++ kernel must return the Python oracle's breakpoints on
    random, structured, clipped, degenerate, and ragged rows — including
    the oracle's first-minimum tie-breaking on exactly tied costs."""
    rng = np.random.default_rng(42)
    rows = []
    # random
    rows += [rng.normal(0, 1, 400) for _ in range(10)]
    # CN-step structure
    for _ in range(10):
        cn = np.full(400, 2.0)
        a = rng.integers(30, 300)
        cn[a:a + rng.integers(20, 80)] = rng.choice([1.0, 3.0, 4.0])
        rows.append(cn + rng.normal(0, 0.15, 400))
    # percentile-clipped plateaus (repeated values, near-tie prone)
    for _ in range(5):
        r = rng.normal(0, 1, 400)
        rows.append(np.clip(r, np.percentile(r, 5), np.percentile(r, 95)))
    # exact-tie degenerates: all-zero, all-constant
    rows.append(np.zeros(400))
    rows.append(np.full(400, 5.0))
    # ragged short rows
    lens = [len(r) for r in rows] + [3, 7]
    rows += [rng.normal(0, 1, 3), rng.normal(0, 1, 7)]

    max_len = max(lens)
    Y = np.zeros((len(rows), max_len))
    for i, r in enumerate(rows):
        Y[i, :len(r)] = r
    got = find_breakpoints_batch(Y, n_bkps, row_len=np.array(lens))
    for i, r in enumerate(rows):
        assert list(got[i]) == _expected_pair(r, n_bkps), f"row {i}"


def _cna_frames(n_s=60, n_g1=30, num_loci=200, seed=3):
    """Long-form S/G1 frames across chr {1,2,X} with random cell-specific
    CNAs so the changepoint gates actually fire."""
    rng = np.random.default_rng(seed)
    chroms = np.array(["1"] * 80 + ["2"] * 80 + ["X"] * 40)
    starts = np.concatenate(
        [np.arange((chroms == c).sum()) * 500_000 for c in ["1", "2", "X"]])

    def make(prefix, n, clone, base_cn):
        frames = []
        for i in range(n):
            cn = base_cn.copy()
            if rng.random() < 0.6:
                a = rng.integers(10, 150)
                cn[a:a + rng.integers(10, 40)] *= rng.choice([0.5, 1.5, 2.0])
            frames.append(pd.DataFrame({
                "cell_id": f"{prefix}_{clone}_{i}", "chr": chroms,
                "start": starts,
                "rpm_gc_norm": rng.poisson(50 * cn).astype(float),
                "clone_id": clone, "state": np.round(base_cn).astype(int),
            }))
        return frames

    base_a = np.full(num_loci, 2.0)
    base_a[100:130] = 4.0
    base_b = np.full(num_loci, 2.0)
    base_b[30:60] = 3.0
    half_s, half_g = n_s // 2, n_g1 // 2
    cn_s = pd.concat(make("s", half_s, "A", base_a)
                     + make("s", half_s, "B", base_b), ignore_index=True)
    cn_g1 = pd.concat(make("g", half_g, "A", base_a)
                      + make("g", half_g, "B", base_b), ignore_index=True)
    return cn_s, cn_g1


def test_normalize_engines_bit_identical():
    """engine='batch' (default, C++ kernel) and engine='loop' (per-cell
    reference shape) must produce bit-identical DataFrames on >=50 cells
    with real changepoint activity."""
    cn_s, cn_g1 = _cna_frames(n_s=60)
    out_loop = normalize_by_cell(cn_s, cn_g1, engine="loop")
    out_batch = normalize_by_cell(cn_s, cn_g1, engine="batch")
    # real activity, not a trivially-empty comparison
    assert (out_loop["changepoint_segments"] > 0).sum() > 100
    assert out_loop["cell_id"].nunique() == 60
    pd.testing.assert_frame_equal(out_loop, out_batch)


def test_normalize_engines_agree_on_noncanonical_contigs():
    """Contigs outside CHR_ORDER (e.g. 'MT') become NaN under the loop
    engine's categorical cast; the batch engine must gate and merge the
    same way rather than comparing raw labels."""
    cn_s, cn_g1 = _cna_frames(n_s=10, n_g1=8)
    for df in (cn_s, cn_g1):
        df.loc[df["start"] >= df["start"].max() - 2_000_000, "chr"] = "MT"
    out_loop = normalize_by_cell(cn_s, cn_g1, engine="loop")
    out_batch = normalize_by_cell(cn_s, cn_g1, engine="batch")
    pd.testing.assert_frame_equal(out_loop, out_batch)


def test_normalize_default_engine_is_batch():
    cn_s, cn_g1 = _cna_frames(n_s=10, n_g1=8)
    out_default = normalize_by_cell(cn_s, cn_g1)
    out_batch = normalize_by_cell(cn_s, cn_g1, engine="batch")
    pd.testing.assert_frame_equal(out_default, out_batch)
    with pytest.raises(ValueError):
        normalize_by_cell(cn_s, cn_g1, engine="nope")


def test_batch_core_tolerates_empty_rows():
    """A cell with zero valid loci must not abort the whole batch."""
    rng = np.random.default_rng(0)
    Y = np.zeros((3, 50))
    Y[0] = rng.normal(0, 1, 50)
    Y[2] = rng.normal(0, 1, 50)
    chroms = np.array(["1"] * 50)
    rt, chng = remove_cell_specific_CNAs_batch(
        Y, np.array([50, 0, 50]), [chroms, chroms[:0], chroms])
    assert np.isfinite(rt[0]).all() and np.isfinite(rt[2]).all()
    assert (rt[1] == 0).all() and (chng[1] == 0).all()


def _many_segment_profile():
    """24 short, sparse, equal-amplitude CNA blocks: the 2-breakpoint
    optimum isolates them one per round, so full flattening takes 24
    rounds — past the old arbitrary cap of 20."""
    rng = np.random.default_rng(0)
    n = 2000
    y = 10.0 + rng.normal(0, 0.05, n)
    pos = np.linspace(40, n - 60, 24).astype(int)
    for p in pos:
        y[p:p + 8] *= 2.0
    return y, np.array(["7"] * n)


def test_unbounded_rounds_nominate_all_segments():
    """The reference's flattening loop is unbounded (while True,
    normalize_by_cell.py:44); >20 real segments must all be nominated."""
    y, chroms = _many_segment_profile()
    _, chng = identify_changepoint_segs(y, chroms)
    assert len(np.unique(chng[chng > 0])) == 24
    assert chng.max() == 24.0
    # the explicit bound still works for adversarial inputs
    _, chng20 = identify_changepoint_segs(y, chroms, max_rounds=20)
    assert chng20.max() == 20.0


def test_batch_core_matches_single_on_many_segments():
    """The lock-step batch core must track the single-profile path
    through all 24 rounds, not just the first few.  The batch core
    trims tails first (like remove_cell_specific_CNAs), so the single
    side gets the same trim."""
    from scdna_replication_tools_tpu.pipeline.normalize import _trim_tails

    y, chroms = _many_segment_profile()
    _, chng_single = identify_changepoint_segs(_trim_tails(y), chroms)
    Y = np.stack([y, y[::-1].copy()])
    # reversed row keeps the batch genuinely heterogeneous
    rt, chng = remove_cell_specific_CNAs_batch(
        Y, np.array([len(y), len(y)]), [chroms, chroms])
    np.testing.assert_array_equal(chng[0], chng_single)


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("SCRT_SKIP_SLOW") == "1",
                    reason="SCRT_SKIP_SLOW=1")
def test_batch_cna_pass_10k_cells_genome_wide():
    """Round-2 verdict bar: 10k cells x 5,451 loci through the batched
    CNA pass without the per-cell Python cliff.  Measured ~374 CPU-s
    (vs ~2h extrapolated for the per-cell loop).  The bound is on
    PROCESS CPU TIME, not wall-clock: total CPU work is what the
    Python-cliff bar actually measures, it is invariant to how many
    cores the threaded kernel spreads across, and — unlike wall time —
    it does not flake when an unrelated process contends for the
    machine (this test failed twice from exactly that)."""
    import time

    from scdna_replication_tools_tpu.native.build import native_available

    if not native_available():
        pytest.skip("native kernel unavailable; the pure-Python fallback "
                    "would run this scale test for hours before failing "
                    "the cpu-time bound")

    rng = np.random.default_rng(1)
    S, n = 10_000, 5451
    Y = rng.normal(0, 1, (S, n))
    for i in np.nonzero(rng.random(S) < 0.25)[0]:
        a = rng.integers(100, n - 600)
        Y[i, a:a + rng.integers(50, 400)] += rng.choice([-1.5, 1.5, 2.5])
    chroms = np.array(["1"] * 2000 + ["7"] * 1500 + ["13"] * 1000
                      + ["X"] * 951, dtype=object)
    row_len = np.full(S, n, np.int64)
    t0 = time.process_time()
    rt, chng = remove_cell_specific_CNAs_batch(Y, row_len, [chroms] * S)
    cpu_s = time.process_time() - t0
    assert cpu_s < 600.0, f"{cpu_s:.0f} CPU-s (bound 600)"
    assert np.isfinite(rt[:, :n]).all()
    assert (chng.max(axis=1) > 0).sum() > 5_000  # the gates really fired
