"""The pert-watch run-health plane: heartbeat writer atomicity and
sequence discipline (obs/heartbeat.py), the freshness ladder and
multi-host aggregation (straggler spread, desync, presumed-lost),
the declarative alert engine (obs/alerts.py + obs/alert_rules.json),
and the ``pert_watch`` CLI exit-code / textfile contract.

Everything here runs on synthesized heartbeat trees — the live
two-process end-to-end loop (heartbeats pumped from a real fit, a
preempted rank flagged presumed-lost before the survivor's collective
dies) is ``tools/watch_smoke.py``, exercised by the CI watch-smoke
step.
"""

import json
import pathlib
import sys
import time

import pytest

from scdna_replication_tools_tpu.obs import alerts as alerts_mod
from scdna_replication_tools_tpu.obs import heartbeat as hb
from scdna_replication_tools_tpu.obs import metrics as metrics_mod
from scdna_replication_tools_tpu.utils.profiling import PhaseTimer

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from tools import pert_watch  # noqa: E402


def _doc(rank, *, state="running", step="step2", chunk=3, iteration=60,
         budget=100, interval=10.0, age=0.0, now=None, count=2,
         eta=4.0, metrics=None):
    """One synthetic heartbeat document, ``age`` seconds old."""
    now = time.time() if now is None else now
    return {
        "kind": hb.HEARTBEAT_KIND, "version": hb.HEARTBEAT_VERSION,
        "process_index": rank, "process_count": count, "state": state,
        "interval_seconds": interval, "step": step, "chunk": chunk,
        "iteration": iteration, "budget": budget,
        "ms_per_iter_ewma": 12.0, "eta_seconds": eta,
        "written_unix": now - age, "seq": 7,
        "metrics": metrics or {},
    }


def _tree(tmp_path, docs):
    """Write raw heartbeat docs into a health/ dir, bypassing
    HeartbeatFile so tests control seq/written_unix exactly."""
    health = tmp_path / "health"
    health.mkdir(parents=True, exist_ok=True)
    for doc in docs:
        hb.host_path(health, doc["process_index"]).write_text(
            json.dumps(doc))
    return health


# ---------------------------------------------------------------------------
# HeartbeatFile: atomicity + sequence discipline
# ---------------------------------------------------------------------------


def test_heartbeat_file_seq_monotonic_and_resumes(tmp_path):
    path = tmp_path / "host_0.json"
    f = hb.HeartbeatFile(path)
    assert f.write({"a": 1}) == 1
    assert f.write({"a": 2}) == 2
    doc = json.loads(path.read_text())
    assert doc["seq"] == 2 and doc["a"] == 2
    assert doc["written_unix"] > 0
    # a restarted writer resumes the sequence — it never moves backwards
    f2 = hb.HeartbeatFile(path)
    assert f2.write({"a": 3}) == 3
    assert json.loads(path.read_text())["seq"] == 3


def test_heartbeat_file_write_is_atomic_no_temp_litter(tmp_path):
    """Every committed state is complete JSON and the directory never
    accumulates temp files (atomic_write_bytes contract)."""
    path = tmp_path / "host_0.json"
    f = hb.HeartbeatFile(path)
    for i in range(25):
        f.write({"payload": "x" * (i * 40), "i": i})
        doc = json.loads(path.read_text())  # parse must never fail
        assert doc["i"] == i
    assert [p.name for p in tmp_path.iterdir()] == ["host_0.json"]


def test_heartbeat_file_never_raises_on_unwritable_path(tmp_path):
    (tmp_path / "blocker").write_text("a file where a dir must go")
    f = hb.HeartbeatFile(tmp_path / "blocker" / "host_0.json")
    assert f.write({"a": 1}) is None  # swallowed, not raised


def test_scan_health_skips_torn_and_foreign_files(tmp_path):
    health = _tree(tmp_path, [_doc(0), _doc(1)])
    (health / "host_2.json").write_text('{"kind": "pert_hear')  # torn
    (health / "notes.txt").write_text("not a heartbeat")
    rows = hb.scan_health(health)
    assert [r["rank"] for r in rows] == [0, 1]


# ---------------------------------------------------------------------------
# freshness ladder
# ---------------------------------------------------------------------------


def test_freshness_ladder_from_writers_own_interval():
    now = time.time()
    for age, want in ((5.0, "fresh"), (29.0, "fresh"),
                      (31.0, "lagging"), (99.0, "lagging"),
                      (101.0, "stale"), (299.0, "stale"),
                      (301.0, "presumed_lost")):
        doc = _doc(0, interval=10.0, age=age, now=now)
        assert hb.freshness(doc, now) == want, (age, want)


def test_freshness_terminal_states_are_final_never_stale():
    now = time.time()
    for state in sorted(hb.TERMINAL_STATES):
        doc = _doc(0, state=state, age=1e6, now=now)
        assert hb.freshness(doc, now) == "final"


def test_freshness_scales_with_declared_cadence():
    """The same 60s age is fresh for a 30s writer, presumed-lost for a
    sub-second writer — thresholds come from the document, not the
    reader's config."""
    now = time.time()
    assert hb.freshness(_doc(0, interval=30.0, age=60.0, now=now),
                        now) == "fresh"
    assert hb.freshness(_doc(0, interval=0.5, age=60.0, now=now),
                        now) == "presumed_lost"


# ---------------------------------------------------------------------------
# aggregation: stragglers, desync, missing ranks, seq stalls
# ---------------------------------------------------------------------------


def test_aggregate_straggler_spread_same_step(tmp_path):
    now = time.time()
    health = _tree(tmp_path, [
        _doc(0, chunk=5, iteration=90, now=now),
        _doc(1, chunk=2, iteration=40, now=now),
    ])
    agg = hb.aggregate_health(health, now=now)
    assert agg["straggler_spread_chunks"] == 3
    assert agg["straggler_spread_iters"] == 50
    assert agg["desync"] is False
    assert agg["missing_ranks"] == []
    assert agg["worst_freshness"] == "fresh"


def test_aggregate_desync_and_cross_step_spread_excluded(tmp_path):
    """Running hosts in different steps is desync; chunk counters do
    not compare across steps, so spread is computed within the modal
    step only."""
    now = time.time()
    health = _tree(tmp_path, [
        _doc(0, step="step3", chunk=1, iteration=5, now=now, count=3),
        _doc(1, step="step2", chunk=9, iteration=95, now=now, count=3),
        _doc(2, step="step2", chunk=9, iteration=95, now=now, count=3),
    ])
    agg = hb.aggregate_health(health, now=now)
    assert agg["desync"] is True
    assert agg["steps"] == ["step2", "step3"]
    assert agg["straggler_spread_chunks"] == 0  # modal step2 group only


def test_aggregate_missing_rank_and_presumed_lost(tmp_path):
    now = time.time()
    health = _tree(tmp_path, [
        _doc(0, now=now, count=3),
        _doc(1, interval=0.5, age=120.0, now=now, count=3),  # lost
    ])
    agg = hb.aggregate_health(health, now=now)
    assert agg["process_count"] == 3
    assert agg["missing_ranks"] == [2]
    assert agg["worst_freshness"] == "presumed_lost"
    assert agg["hosts"][1]["freshness"] == "presumed_lost"
    assert agg["max_lag_seconds"] >= 119.0


def test_aggregate_final_hosts_exempt_from_lag(tmp_path):
    """A finished run left overnight: terminal docs are final, do not
    drive max_lag, and never trip the staleness alarm."""
    now = time.time()
    health = _tree(tmp_path, [
        _doc(0, state="done", age=7200.0, now=now),
        _doc(1, state="done", age=7200.0, now=now),
    ])
    agg = hb.aggregate_health(health, now=now)
    assert agg["worst_freshness"] == "final"
    assert agg["max_lag_seconds"] == 0.0
    assert agg["states"] == {"done": 2}


# ---------------------------------------------------------------------------
# RunHeartbeat writer: progress, EWMA/ETA sanity, lifecycle
# ---------------------------------------------------------------------------


def test_run_heartbeat_announces_immediately(tmp_path):
    rh = hb.RunHeartbeat(tmp_path, interval_seconds=60.0,
                         process_index=1, process_count=2)
    doc = hb.read_heartbeat(hb.host_path(tmp_path, 1))
    assert doc["state"] == "running" and doc["seq"] == 1
    assert doc["process_count"] == 2
    assert doc["interval_seconds"] == 60.0
    rh.close("done")
    assert hb.read_heartbeat(hb.host_path(tmp_path, 1))["state"] == "done"


def test_run_heartbeat_eta_projection_sane(tmp_path):
    rh = hb.RunHeartbeat(tmp_path, interval_seconds=0.0)
    rh.note_chunk(step="step2", chunk=1, iteration=25, budget=100,
                  wall_seconds=0.5, iters=25, action="continue",
                  verdict="improving")
    rh.pump(force=True)  # beat the write throttle for the assertion
    doc1 = hb.read_heartbeat(hb.host_path(tmp_path, 0))
    # 20 ms/iter x 75 remaining = 1.5s
    assert doc1["ms_per_iter_ewma"] == pytest.approx(20.0)
    assert doc1["eta_seconds"] == pytest.approx(1.5)
    rh.note_chunk(step="step2", chunk=2, iteration=75, budget=100,
                  wall_seconds=1.0, iters=50, action="continue",
                  verdict="improving")
    rh.pump(force=True)
    doc2 = hb.read_heartbeat(hb.host_path(tmp_path, 0))
    # ETA shrinks as iteration approaches budget; trail records verdicts
    assert 0.0 < doc2["eta_seconds"] < doc1["eta_seconds"]
    assert doc2["trail"][-1] == "it75:continue/improving"
    rh.note_chunk(iteration=100, budget=100)
    rh.pump(force=True)
    assert hb.read_heartbeat(
        hb.host_path(tmp_path, 0))["eta_seconds"] == 0.0


def test_run_heartbeat_throttle_and_fault_event_force(tmp_path):
    rh = hb.RunHeartbeat(tmp_path, interval_seconds=3600.0)
    seq0 = hb.read_heartbeat(hb.host_path(tmp_path, 0))["seq"]
    rh.note_chunk(step="step2", chunk=1, iteration=5, budget=10)
    assert hb.read_heartbeat(
        hb.host_path(tmp_path, 0))["seq"] == seq0  # throttled
    rh.observe_event("retry", {})  # fault-ladder event forces a write
    doc = hb.read_heartbeat(hb.host_path(tmp_path, 0))
    assert doc["seq"] == seq0 + 1
    assert doc["faults"] == {"retry": 1}
    assert doc["iteration"] == 5  # the throttled note rode along
    rh.observe_event("fit_summary", {})  # non-fault events do not
    assert hb.read_heartbeat(hb.host_path(tmp_path, 0))["seq"] == seq0 + 1


def test_run_heartbeat_samples_installed_registry(tmp_path):
    reg = metrics_mod.MetricsRegistry()
    metrics_mod.install(reg)
    try:
        reg.gauge("pert_device_hbm_peak_bytes").set(123.0)
        reg.counter("pert_retries_total").inc(2)
        reg.counter("pert_fit_iters_total").inc(50)  # not sampled
        rh = hb.RunHeartbeat(tmp_path, interval_seconds=0.0)
        rh.pump(force=True)
        doc = hb.read_heartbeat(hb.host_path(tmp_path, 0))
        assert doc["metrics"]["pert_device_hbm_peak_bytes"] == 123.0
        assert doc["metrics"]["pert_retries_total"] == 2
        assert "pert_fit_iters_total" not in doc["metrics"]
        # the ETA gauge is pushed back into the registry on pump
        rh.note_chunk(step="s", chunk=1, iteration=50, budget=100,
                      wall_seconds=1.0, iters=50)
        rh.pump(force=True)
        snap = reg.snapshot(stable_only=False)
        assert snap["pert_run_eta_seconds"]["value"] == pytest.approx(1.0)
    finally:
        metrics_mod.uninstall(reg)


def test_module_seam_and_phase_sink_chain(tmp_path):
    rh = hb.RunHeartbeat(tmp_path, interval_seconds=0.0)
    hb.install(rh)
    try:
        assert hb.current() is rh
        hb.note_chunk(step="step2", chunk=2, iteration=9, budget=10)
        rh.pump(force=True)
        assert hb.read_heartbeat(
            hb.host_path(tmp_path, 0))["iteration"] == 9
        timer = PhaseTimer()
        calls = []
        timer.on_add = lambda n, s: calls.append(n)
        hb.attach_phase_sink(timer)
        hb.attach_phase_sink(timer)  # re-attach is a no-op, no stacking
        timer.on_add("load", 0.1)
        assert calls == ["load"]  # prior sink still chained
        rh.pump(force=True)
        assert hb.read_heartbeat(
            hb.host_path(tmp_path, 0))["phase"] == "load"
    finally:
        hb.uninstall(rh)
    hb.note_chunk(step="x")  # no-op once uninstalled
    assert hb.current() is None


def test_resolve_dir_auto_requires_checkpoint_dir(tmp_path):
    assert hb.resolve_dir("auto", None) is None
    assert hb.resolve_dir("auto", str(tmp_path)) == str(
        tmp_path / "health")
    assert hb.resolve_dir(None, str(tmp_path)) is None
    assert hb.resolve_dir("off", str(tmp_path)) is None
    assert hb.resolve_dir(str(tmp_path / "h"), None) == str(
        tmp_path / "h")


# ---------------------------------------------------------------------------
# alert engine
# ---------------------------------------------------------------------------


def _rules(*rules):
    return alerts_mod.validate_rules({"rules": list(rules)})


def test_checked_in_rule_file_validates():
    rules = alerts_mod.load_rules()
    names = [r["name"] for r in rules]
    assert "host-presumed-lost" in names
    assert "hosts-desynced" in names


def test_rule_validation_rejects_unknown_metric_and_field():
    with pytest.raises(alerts_mod.AlertRuleError, match="unknown metric"):
        _rules({"name": "r", "kind": "threshold", "severity": "error",
                "metric": "pert_no_such_metric", "op": ">", "value": 0})
    with pytest.raises(alerts_mod.AlertRuleError, match="unknown field"):
        _rules({"name": "r", "kind": "threshold", "severity": "error",
                "field": "no_such_field", "op": ">", "value": 0})


def test_rule_validation_rejects_bad_grammar():
    with pytest.raises(alerts_mod.AlertRuleError, match="unknown kind"):
        _rules({"name": "r", "kind": "vibes", "severity": "error"})
    with pytest.raises(alerts_mod.AlertRuleError, match="duplicate"):
        _rules({"name": "r", "kind": "desync", "severity": "error"},
               {"name": "r", "kind": "desync", "severity": "warning"})
    with pytest.raises(alerts_mod.AlertRuleError, match="unknown keys"):
        _rules({"name": "r", "kind": "desync", "severity": "error",
                "op": ">"})
    with pytest.raises(alerts_mod.AlertRuleError,
                       match="exactly one of"):
        _rules({"name": "r", "kind": "threshold", "severity": "error",
                "op": ">", "value": 1})
    with pytest.raises(alerts_mod.AlertRuleError, match="max_level"):
        _rules({"name": "r", "kind": "staleness", "severity": "error",
                "max_level": "presumed_lost"})
    with pytest.raises(alerts_mod.AlertRuleError, match="number"):
        _rules({"name": "r", "kind": "threshold", "severity": "error",
                "field": "eta_seconds", "op": ">", "value": True})


def test_alert_staleness_fires_on_presumed_lost_only(tmp_path):
    now = time.time()
    health = _tree(tmp_path, [
        _doc(0, now=now),
        _doc(1, interval=0.5, age=120.0, now=now),
    ])
    agg = hb.aggregate_health(health, now=now)
    verdicts = alerts_mod.evaluate(alerts_mod.load_rules(), agg)
    fired = {v["name"]: v for v in verdicts if v["fired"]}
    assert "host-presumed-lost" in fired
    assert "host1" in fired["host-presumed-lost"]["detail"]
    failing = alerts_mod.failing(verdicts)
    assert [v["name"] for v in failing] == ["host-presumed-lost"]


def test_alert_desync_absence_and_metric_threshold(tmp_path):
    now = time.time()
    health = _tree(tmp_path, [
        _doc(0, step="step3", now=now, count=3,
             metrics={"pert_nan_aborts_total": 2}),
        _doc(1, step="step2", now=now, count=3),
    ])
    agg = hb.aggregate_health(health, now=now)
    fired = {v["name"]: v for v in alerts_mod.evaluate(
        alerts_mod.load_rules(), agg) if v["fired"]}
    assert "hosts-desynced" in fired
    assert "missing-heartbeats" in fired  # rank 2 never wrote
    assert "nan-aborts" in fired
    assert fired["nan-aborts"]["severity"] == "warning"


def test_alert_healthy_and_finished_trees_are_quiet(tmp_path):
    now = time.time()
    rules = alerts_mod.load_rules()
    health = _tree(tmp_path, [_doc(0, now=now), _doc(1, now=now)])
    assert alerts_mod.failing(alerts_mod.evaluate(
        rules, hb.aggregate_health(health, now=now))) == []
    done = _tree(tmp_path / "d", [
        _doc(0, state="done", age=9000.0, now=now),
        _doc(1, state="done", age=9000.0, now=now)])
    assert alerts_mod.failing(alerts_mod.evaluate(
        rules, hb.aggregate_health(done, now=now))) == []


# ---------------------------------------------------------------------------
# pert_watch CLI: exit codes, textfile gauges, report rendering
# ---------------------------------------------------------------------------


def test_watch_check_exit_codes_and_textfile(tmp_path, capsys):
    _tree(tmp_path, [_doc(0), _doc(1)])
    prom = tmp_path / "watch.prom"
    rc = pert_watch.main(["check", str(tmp_path),
                          "--metrics-textfile", str(prom)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["kind"] == "pert_watch_check"
    assert out["failing"] == []
    text = prom.read_text()
    for name in ("pert_heartbeat_lag_seconds",
                 "pert_straggler_spread_chunks",
                 "pert_run_eta_seconds"):
        assert name in text

    stale = tmp_path / "stale"
    _tree(stale, [_doc(0), _doc(1, interval=0.5, age=300.0)])
    rc = pert_watch.main(["check", str(stale)])
    captured = capsys.readouterr()
    assert rc == 1
    assert "host-presumed-lost" in captured.err
    assert json.loads(captured.out)["failing"] == ["host-presumed-lost"]


def test_watch_check_empty_dir_fails_absence(tmp_path, capsys):
    (tmp_path / "health").mkdir()
    rc = pert_watch.main(["check", str(tmp_path)])
    captured = capsys.readouterr()
    assert rc == 1
    assert "missing-heartbeats" in captured.err


def test_watch_once_renders_mission_control(tmp_path, capsys):
    _tree(tmp_path, [_doc(0, chunk=5, iteration=90),
                     _doc(1, chunk=2, iteration=40)])
    rc = pert_watch.main(["watch", str(tmp_path), "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "host0" in out and "host1" in out
    assert "spread 3 chunks / 50 iters" in out
    assert "ETA" in out


def test_watch_report_markdown_and_pert_report_embed(tmp_path, capsys):
    from tools.pert_report import _run_health_section

    _tree(tmp_path, [_doc(0), _doc(1, interval=0.5, age=300.0)])
    rc = pert_watch.main(["report", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "## Run health" in out
    assert "presumed_lost" in out
    assert "host-presumed-lost" in out  # the alert bullet names the rule
    # pert_report embeds the same renderer, resolving health/ next to
    # the run log; placeholder when no heartbeats exist
    lines = _run_health_section(tmp_path / "run.jsonl")
    assert any("presumed_lost" in ln for ln in lines)
    empty = _run_health_section(tmp_path / "nowhere" / "run.jsonl")
    assert any("no heartbeats" in ln for ln in empty)


def test_resolve_health_dir_accepts_run_dir_or_health_dir(tmp_path):
    health = _tree(tmp_path, [_doc(0)])
    assert pert_watch.resolve_health_dir(str(tmp_path)) == health
    assert pert_watch.resolve_health_dir(str(health)) == health
