"""Long-genome (small-bin) regime: the reference's stated scaling pain
point (reference: README.md:55-57 — 20kb bins mean ~25x more loci than
500kb, with runtime/NaN warnings and no mitigation).

The TPU design handles the scale by sharding the loci axis (2-D
cells x loci mesh; the likelihood has no cross-locus coupling) with
masked padding to shard evenly, and the sparse one-hot prior encoding
keeps the device-resident prior at 2 planes.  This test runs the
COMPLETE pipeline at 15k loci (a large chromosome at 20kb density) over
a 2x4 virtual-device mesh with simulator-generated reads and pins what
the machinery guarantees at a CI-feasible 200-iteration budget:
finiteness, monotone loss, the sparse+sharded production configuration,
and better-than-noise tau/rep recovery.

Recovery QUALITY at this scale is budget-bound, not machinery-bound:
the same configuration reaches pooled tau r=0.64 at 400 iters (measured
while writing this test) and the reference's own guidance is >1000
iterations — the D1-geometry suite (tests/test_d1_shape.py) pins
high-accuracy recovery at the 280-loci scale where the budget converges.
The genome-wide 154,770-bin artifact is recorded by
``tools/full_pipeline_bench.py --bin-size 20000``
(artifacts/FULL_PIPELINE_r05_20kb_cpu.json).
"""

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.config import PertConfig
from scdna_replication_tools_tpu.data.loader import build_pert_inputs
from scdna_replication_tools_tpu.infer.runner import PertInference
from scdna_replication_tools_tpu.models.pert import constrained
from scdna_replication_tools_tpu.models.simulator import pert_simulator


@pytest.mark.slow
def test_20kb_density_pipeline_on_2d_mesh():
    rng = np.random.default_rng(42)
    num_loci, n_per = 15_000, 12
    starts = (np.arange(num_loci) * 20_000).astype(np.int64)
    gc = np.clip(0.45 + 0.08 * np.sin(np.arange(num_loci) / 900.0)
                 + rng.normal(0, 0.02, num_loci), 0.3, 0.65)
    rt = 0.5 + 0.45 * np.sin(np.arange(num_loci) / 1500.0 + 1.0)
    meta = pd.DataFrame({"chr": "1", "start": starts,
                         "end": starts + 20_000, "gc": gc, "mcf7rt": rt,
                         "rt_A": rt})
    cn = np.full(num_loci, 2.0)
    cn[4000:6000] = 3.0

    def mk(prefix):
        out = []
        for i in range(n_per):
            df = meta.copy()
            df["cell_id"] = f"{prefix}_A_{i}"
            df["library_id"] = "LIB0"
            df["clone_id"] = "A"
            df["true_somatic_cn"] = cn
            out.append(df)
        return out

    df_s = pd.concat(mk("s"), ignore_index=True)
    df_g = pd.concat(mk("g"), ignore_index=True)
    sim_s, sim_g = pert_simulator(
        df_s, df_g, num_reads=600_000, rt_cols=["rt_A"], clones=["A"],
        lamb=0.75, betas=[0.5, 0.0], a=10.0, seed=5)
    for d in (sim_s, sim_g):
        d["reads"] = d["true_reads_norm"]
        d["state"] = d["true_somatic_cn"].astype(int)
        d["copy"] = d["true_somatic_cn"]

    s, g1 = build_pert_inputs(sim_s, sim_g)
    clone_idx = np.zeros(n_per, np.int32)
    config = PertConfig(cn_prior_method="g1_clones", max_iter=200,
                        min_iter=100, run_step3=False,
                        rho_from_rt_prior=True,
                        num_shards=2, loci_shards=4)
    inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=1)
    step1, step2, _ = inf.run()

    # machinery guarantees at scale
    assert not step2.fit.nan_abort
    assert np.isfinite(step2.fit.losses).all()
    assert step2.fit.losses[-1] < step2.fit.losses[0]
    assert step2.spec.sparse_etas, "one-hot prior must auto-sparsify"
    assert not step2.fit.params["tau_raw"].sharding.is_fully_replicated, \
        "per-cell params must stay sharded over the mesh"

    # better-than-noise recovery at the 200-iter CI budget (see module
    # docstring for why the bar is not the D1-scale 0.9)
    truth = sim_s.drop_duplicates("cell_id").set_index("cell_id")["true_t"]
    c = constrained(step2.spec, step2.fit.params, step2.fixed)
    tau_fit = np.asarray(c["tau"])[:n_per]
    # pivot_matrix orders cells lexicographically (s_A_0, s_A_1, s_A_10,
    # ...) — index truth by the model's own cell order, not numerically
    tt = truth.loc[list(s.cell_ids)[:n_per]].to_numpy()
    r = np.corrcoef(tau_fit, tt)[0, 1]
    assert r > 0.25, f"tau correlation {r:.3f} at 20kb density"
