"""Observability: PertConfig(profile_dir=...) must produce a real
jax.profiler trace artifact, and log_step_summary must emit its line.

The reference's observability is a DEBUG log stream around each SVI step
(reference: pert_model.py:25-33, 746); the TPU framework's equivalent is
the per-step summary plus XLA-level traces — this pins that the trace
context actually writes TensorBoard/Perfetto dumps (round-4 VERDICT noted
the hook existed but had never demonstrably produced an artifact).
"""

import glob
import logging
import os

import numpy as np

from scdna_replication_tools_tpu.config import PertConfig
from scdna_replication_tools_tpu.infer.runner import PertInference
from scdna_replication_tools_tpu.utils import profiling

from conftest import dense_inputs_from_frames as _dense_inputs  # noqa: E402


def test_profile_dir_writes_trace(tmp_path, synthetic_frames):
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    config = PertConfig(cn_prior_method="g1_clones", max_iter=8, min_iter=4,
                        run_step3=False, profile_dir=str(tmp_path))
    inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    step1, step2, _ = inf.run()
    assert np.isfinite(step2.fit.losses).all()
    # jax.profiler.trace writes plugins/profile/<run>/<host>.xplane.pb
    xplanes = glob.glob(os.path.join(str(tmp_path), "**", "*.xplane.pb"),
                        recursive=True)
    assert xplanes, (
        f"no xplane trace written under {tmp_path}: "
        f"{list(glob.glob(str(tmp_path) + '/**', recursive=True))}")


def test_huge_enum_tensor_warning(caplog, synthetic_frames):
    """The XLA-path OOM advisory fires from the size estimate alone (no
    giant allocation needed: fake the read matrix shape via a spec/batch
    pair passed straight to the checker)."""
    from scdna_replication_tools_tpu.models.pert import (
        PertBatch,
        PertModelSpec,
    )

    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    inf = PertInference(s, g1, PertConfig(run_step3=False),
                        clone_idx_s=clone_idx, clone_idx_g1=clone_idx,
                        num_clones=2)
    spec = PertModelSpec(P=13, enum_impl="xla")

    class FakeBatch:
        class reads:
            shape = (20_000, 5_451)

    with caplog.at_level(logging.WARNING, "scdna_replication_tools_tpu"):
        inf._warn_if_enum_tensor_huge(spec, FakeBatch())
    assert any("enumeration tensor" in r.message for r in caplog.records)


def test_phase_timer_warns_once_on_overlapping_phases(caplog):
    """Overlapping phase() contexts double-count wall and break the
    >=95%-coverage invariant — the timer must flag them (once: a hot
    loop with a mis-nested phase must not spam a warning per call)."""
    timer = profiling.PhaseTimer()
    with caplog.at_level(logging.WARNING, "scdna_replication_tools_tpu"):
        with timer.phase("outer"):
            with timer.phase("inner"):
                pass
            with timer.phase("inner2"):  # second overlap: not re-reported
                pass
    overlap = [r for r in caplog.records
               if "overlapping phases" in r.message]
    assert len(overlap) == 1
    # both phases still accumulated (warn, don't drop data)
    assert set(timer.phases) == {"outer", "inner", "inner2"}


def test_phase_timer_sequential_phases_do_not_warn(caplog):
    timer = profiling.PhaseTimer()
    with caplog.at_level(logging.WARNING, "scdna_replication_tools_tpu"):
        with timer.phase("a"):
            pass
        with timer.phase("a"):  # re-entering a NAME accumulates, legal
            pass
        with timer.phase("b"):
            pass
    assert not [r for r in caplog.records
                if "overlapping phases" in r.message]
    assert timer.phases["a"] >= 0.0


def test_phase_timer_on_add_sink_observes_every_accumulation():
    timer = profiling.PhaseTimer()
    seen = []
    timer.on_add = lambda name, secs: seen.append((name, secs))
    with timer.phase("x"):
        pass
    timer.add("y", 1.5)
    assert [name for name, _ in seen] == ["x", "y"]
    assert seen[1][1] == 1.5


def test_compile_cache_tmp_fallback_is_user_stable(monkeypatch):
    """The tmp-dir fallback must be portable (os.getuid does not exist
    on Windows — getpass.getuser is the cross-platform spelling) and
    STABLE across processes: a pid-derived component would give every
    run a cold cache, defeating the persistent cache entirely."""
    # force the repo-local candidate to be unwritable
    real_mkdir = os.makedirs

    def deny(path, *a, **k):
        raise OSError("read-only checkout")

    monkeypatch.setattr("pathlib.Path.mkdir",
                        lambda self, *a, **k: deny(self))
    path1 = profiling.resolve_compile_cache_dir("auto")
    path2 = profiling.resolve_compile_cache_dir("auto")
    assert path1 == path2, "fallback cache dir must be stable across calls"
    assert str(os.getpid()) not in os.path.basename(path1)
    import getpass

    try:
        user = getpass.getuser()
    except (KeyError, OSError):
        user = os.environ.get("USER") or "user"
    assert path1.endswith(f"scdna_rt_tpu_jax_cache_{user}")
    assert real_mkdir is os.makedirs  # monkeypatch scope sanity


def test_log_step_summary_line(caplog):
    class Fit:
        num_iters = 10
        losses = np.array([5.0, 4.0], np.float32)
        converged = True
        nan_abort = False

    with caplog.at_level(logging.INFO, "scdna_replication_tools_tpu"):
        profiling.log_step_summary("step2", Fit(), wall_time=2.0,
                                   num_cells=100)
    assert any("step2: 10 iters" in r.message for r in caplog.records)
