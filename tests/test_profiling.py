"""Observability: PertConfig(profile_dir=...) must produce a real
jax.profiler trace artifact, and log_step_summary must emit its line.

The reference's observability is a DEBUG log stream around each SVI step
(reference: pert_model.py:25-33, 746); the TPU framework's equivalent is
the per-step summary plus XLA-level traces — this pins that the trace
context actually writes TensorBoard/Perfetto dumps (round-4 VERDICT noted
the hook existed but had never demonstrably produced an artifact).
"""

import glob
import logging
import os

import numpy as np

from scdna_replication_tools_tpu.config import PertConfig
from scdna_replication_tools_tpu.infer.runner import PertInference
from scdna_replication_tools_tpu.utils import profiling

from conftest import dense_inputs_from_frames as _dense_inputs  # noqa: E402


def test_profile_dir_writes_trace(tmp_path, synthetic_frames):
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    config = PertConfig(cn_prior_method="g1_clones", max_iter=8, min_iter=4,
                        run_step3=False, profile_dir=str(tmp_path))
    inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    step1, step2, _ = inf.run()
    assert np.isfinite(step2.fit.losses).all()
    # jax.profiler.trace writes plugins/profile/<run>/<host>.xplane.pb
    xplanes = glob.glob(os.path.join(str(tmp_path), "**", "*.xplane.pb"),
                        recursive=True)
    assert xplanes, (
        f"no xplane trace written under {tmp_path}: "
        f"{list(glob.glob(str(tmp_path) + '/**', recursive=True))}")


def test_huge_enum_tensor_warning(caplog, synthetic_frames):
    """The XLA-path OOM advisory fires from the size estimate alone (no
    giant allocation needed: fake the read matrix shape via a spec/batch
    pair passed straight to the checker)."""
    from scdna_replication_tools_tpu.models.pert import (
        PertBatch,
        PertModelSpec,
    )

    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    inf = PertInference(s, g1, PertConfig(run_step3=False),
                        clone_idx_s=clone_idx, clone_idx_g1=clone_idx,
                        num_clones=2)
    spec = PertModelSpec(P=13, enum_impl="xla")

    class FakeBatch:
        class reads:
            shape = (20_000, 5_451)

    with caplog.at_level(logging.WARNING, "scdna_replication_tools_tpu"):
        inf._warn_if_enum_tensor_huge(spec, FakeBatch())
    assert any("enumeration tensor" in r.message for r in caplog.records)


def test_log_step_summary_line(caplog):
    class Fit:
        num_iters = 10
        losses = np.array([5.0, 4.0], np.float32)
        converged = True
        nan_abort = False

    with caplog.at_level(logging.INFO, "scdna_replication_tools_tpu"):
        profiling.log_step_summary("step2", Fit(), wall_time=2.0,
                                   num_cells=100)
    assert any("step2: 10 iters" in r.message for r in caplog.records)
