"""Metrics registry + fleet index: the acceptance surface of the
unified-metrics PR.

* ``metrics_snapshot`` events are schema-v5-valid and BYTE-IDENTICAL
  across two same-seed CPU runs (the manifest's ``stable`` gate +
  fixed-bucket histograms are what make that possible);
* histogram bucket edges come from the checked-in manifest, never from
  code;
* the Prometheus textfile is written atomically and parses against the
  text exposition grammar;
* ``pert_fleet`` index/query/trend/regress work end to end, including
  the seeded-regression nonzero exit (a synthetic +20% fit-wall
  regression trips the manifest's 15% threshold) and the
  unknown-metric warning;
* ``memory_stats``-less backends (CPU) degrade to absent gauges;
* metrics ON adds <2% to the step-2 fit wall (same alternating-timed
  harness as the PR-4/PR-5 overhead guards).
"""

import json
import re

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.api import scRT
from scdna_replication_tools_tpu.infer import svi
from scdna_replication_tools_tpu.infer.svi import fit_map
from scdna_replication_tools_tpu.infer.runner import _PertLossFn
from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    init_params,
)
from scdna_replication_tools_tpu.obs import metrics as metrics_mod
from scdna_replication_tools_tpu.obs.metrics import (
    MetricsRegistry,
    attach_phase_sink,
    manifest_metrics,
)
from scdna_replication_tools_tpu.obs.runlog import RunLog
from scdna_replication_tools_tpu.obs.schema import validate_run
from scdna_replication_tools_tpu.obs.summary import (
    flat_metrics,
    summarize_run,
)
from scdna_replication_tools_tpu.ops.gc import gc_features
from scdna_replication_tools_tpu.utils.profiling import PhaseTimer

import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from tools import pert_fleet  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_registry():
    """Every test starts with no process-global registry installed."""
    metrics_mod.install(None)
    yield
    metrics_mod.install(None)


@pytest.fixture(autouse=True, scope="module")
def _cold_program_cache_for_later_modules():
    """This module's pipeline runs use the SAME tiny workload/config as
    test_runlog's telemetry fixture; leaving their programs in the
    in-process AOT cache would hand that fixture a near-zero-wall warm
    run, where the >=95% phase-coverage invariant's fixed
    few-millisecond inter-phase overhead no longer amortises.  Restore
    the cache state later modules saw before this module existed."""
    yield
    svi.clear_program_cache()


def _pipeline_frames(synthetic_frames):
    df_s, df_g = synthetic_frames
    df_s = df_s.assign(reads=np.random.default_rng(0)
                       .poisson(40, len(df_s)).astype(float),
                       state=df_s.true_somatic_cn.astype(int),
                       copy=df_s.true_somatic_cn)
    df_g = df_g.assign(reads=np.random.default_rng(1)
                       .poisson(40, len(df_g)).astype(float),
                       state=df_g.true_somatic_cn.astype(int),
                       copy=df_g.true_somatic_cn)
    return df_s, df_g


def _run_once(synthetic_frames, log_path, textfile=None):
    # the in-process AOT program cache must start cold for BOTH runs,
    # or run 2's compile events flip from miss to hit and the (stable)
    # cache counters legitimately differ.  The budgets deliberately
    # DIFFER from test_runlog's telemetry fixture (12/6 vs 10/5,
    # diag_every 3 vs 2): same-config programs left warm in the
    # process/disk caches would collapse that fixture's wall and break
    # its >=95% phase-coverage invariant's amortisation
    svi.clear_program_cache()
    df_s, df_g = _pipeline_frames(synthetic_frames)
    scrt = scRT(df_s, df_g, clone_col="clone_id",
                cn_prior_method="g1_clones", max_iter=12, min_iter=6,
                run_step3=True, telemetry_path=str(log_path),
                metrics_textfile=str(textfile) if textfile else None,
                fit_diag_every=3)
    scrt.infer(level="pert")
    return scrt


@pytest.fixture(scope="module")
def same_seed_pair(synthetic_frames, tmp_path_factory):
    """Two identical same-seed CPU pipeline runs with telemetry +
    metrics, each from a cold program cache."""
    root = tmp_path_factory.mktemp("metrics_pair")
    metrics_mod.install(None)
    a = _run_once(synthetic_frames, root / "a.jsonl",
                  textfile=root / "a.prom")
    b = _run_once(synthetic_frames, root / "b.jsonl",
                  textfile=root / "b.prom")
    metrics_mod.install(None)
    return root, a, b


def _events(path):
    return [json.loads(line)
            for line in path.read_text().splitlines() if line.strip()]


def _snapshots(path):
    return [ev for ev in _events(path)
            if ev["event"] == "metrics_snapshot"]


# ---------------------------------------------------------------------------
# snapshots: schema validity + byte determinism
# ---------------------------------------------------------------------------


def test_runs_are_schema_v5_valid(same_seed_pair):
    root, _, _ = same_seed_pair
    assert validate_run(root / "a.jsonl") == []
    assert validate_run(root / "b.jsonl") == []


def test_snapshot_emitted_at_step_boundaries_and_run_end(same_seed_pair):
    root, _, _ = same_seed_pair
    phases = [s["phase"] for s in _snapshots(root / "a.jsonl")]
    assert phases == ["step1/end", "step2/end", "step3/end", "run_end"]


def test_snapshots_byte_identical_across_same_seed_runs(same_seed_pair):
    """THE acceptance bar: two same-seed CPU runs produce byte-identical
    metrics_snapshot events.  Only the envelope's wall-clock ``t`` may
    differ — seq, phase and the whole metrics payload must serialize
    identically."""
    root, _, _ = same_seed_pair
    snaps_a = _snapshots(root / "a.jsonl")
    snaps_b = _snapshots(root / "b.jsonl")
    assert len(snaps_a) == len(snaps_b) == 4
    for ev_a, ev_b in zip(snaps_a, snaps_b):
        stripped_a = {k: v for k, v in ev_a.items() if k != "t"}
        stripped_b = {k: v for k, v in ev_b.items() if k != "t"}
        assert json.dumps(stripped_a, sort_keys=False) == \
            json.dumps(stripped_b, sort_keys=False), ev_a.get("phase")


def test_final_snapshot_carries_the_stable_catalogue(same_seed_pair):
    root, _, _ = same_seed_pair
    final = _snapshots(root / "a.jsonl")[-1]["metrics"]
    # the deterministic core: per-step iteration counters, the fit-iters
    # histogram, cache counters, the events counter
    assert final['pert_fit_iters_total{step="step2"}']["value"] == 12
    assert final["pert_fit_iters"]["count"] == 3
    assert final["pert_compile_cache_misses_total"]["value"] >= 1
    assert final["pert_runlog_events_total"]["value"] > 10
    # wall-clock metrics are textfile-only: unstable by manifest
    assert not any(k.startswith("pert_fit_wall_seconds")
                   for k in final)
    assert not any(k.startswith("pert_phase_seconds_total")
                   for k in final)
    # snapshot keys are sorted (byte-stability needs one canonical order)
    assert list(final) == sorted(final)


def test_stable_only_gate(same_seed_pair):
    _, scrt, _ = same_seed_pair
    reg = scrt.metrics_registry
    full = reg.snapshot(stable_only=False)
    stable = reg.snapshot()
    assert set(stable) <= set(full)
    assert any(k.startswith("pert_fit_wall_seconds") for k in full)
    assert not any(k.startswith("pert_fit_wall_seconds") for k in stable)


def test_snapshot_always_metrics_ride_the_event_despite_instability():
    """XLA scope-time gauges exist only on explicitly-profiled runs;
    the manifest's `"snapshot": "always"` opts them into the (default,
    stable-only) snapshot anyway — the satellite contract that scope
    time appears in metrics_snapshot."""
    reg = MetricsRegistry.create()
    reg.gauge("pert_xla_scope_seconds",
              labels={"scope": "pert/fit_step"}).set(1.25)
    snap = reg.snapshot()
    assert snap['pert_xla_scope_seconds{scope="pert/fit_step"}'][
        "value"] == 1.25


# ---------------------------------------------------------------------------
# histograms + manifest
# ---------------------------------------------------------------------------


def test_histogram_bucket_edges_come_from_the_manifest():
    spec = manifest_metrics()["pert_fit_iters"]
    reg = MetricsRegistry.create()
    hist = reg.histogram("pert_fit_iters")
    assert hist.buckets == tuple(float(b) for b in spec["buckets"])
    for v in (10, 70, 3000):
        hist.observe(v)
    snap = reg.snapshot()["pert_fit_iters"]
    # one count per declared edge + the overflow bin
    assert len(snap["buckets"]) == len(spec["buckets"]) + 1
    assert snap["count"] == 3
    assert snap["buckets"][0] == 1          # 10 <= 25
    assert snap["buckets"][2] == 1          # 50 < 70 <= 100
    assert snap["buckets"][-1] == 1         # 3000 > 2500 -> overflow
    assert snap["sum"] == 3080


def test_every_manifest_histogram_declares_buckets():
    for name, spec in manifest_metrics().items():
        if spec.get("type") == "histogram":
            assert spec.get("buckets"), \
                f"{name}: histogram without pinned bucket edges"


def test_unknown_metric_warns_once_and_still_records(caplog):
    reg = MetricsRegistry.create()
    with caplog.at_level("WARNING",
                         logger="scdna_replication_tools_tpu"):
        reg.counter("pert_not_in_manifest_total").inc()
        reg.counter("pert_not_in_manifest_total").inc()
    warnings = [r for r in caplog.records
                if "pert_not_in_manifest_total" in r.getMessage()]
    assert len(warnings) == 1
    # recorded (textfile) but excluded from the stable snapshot
    assert "pert_not_in_manifest_total 2" in reg.to_prometheus_text()
    assert "pert_not_in_manifest_total" not in reg.snapshot()


def test_type_mismatch_against_manifest_warns(caplog):
    reg = MetricsRegistry.create()
    with caplog.at_level("WARNING",
                         logger="scdna_replication_tools_tpu"):
        reg.gauge("pert_fit_iters_total").set(3)  # declared counter
    assert any("declared" in r.getMessage() for r in caplog.records)


# ---------------------------------------------------------------------------
# textfile: atomicity + exposition grammar
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})? '
    r'[-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|Inf|NaN)$')


def test_textfile_is_valid_prometheus_exposition(same_seed_pair):
    root, _, _ = same_seed_pair
    text = (root / "a.prom").read_text()
    assert text.endswith("\n")
    names_with_type = set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram"), line
            names_with_type.add(name)
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
        base = line.split("{")[0].split(" ")[0]
        stripped = re.sub(r"_(bucket|sum|count)$", "", base)
        assert base in names_with_type or stripped in names_with_type
    # histograms expose the cumulative-bucket triplet
    assert "pert_trace_seconds_bucket{le=\"+Inf\"}" in text
    assert "pert_trace_seconds_sum" in text
    assert "pert_trace_seconds_count" in text
    # wall-clock metrics ARE here (the non-snapshot surface)
    assert "pert_fit_wall_seconds" in text


def test_textfile_write_is_atomic(tmp_path):
    """write-temp + os.replace: the destination is either the old or
    the new complete file, and no temp files are left behind."""
    reg = MetricsRegistry.create(textfile_path=str(tmp_path / "m.prom"))
    reg.counter("pert_retries_total").inc()
    assert reg.write_textfile() == str(tmp_path / "m.prom")
    first = (tmp_path / "m.prom").read_text()
    reg.counter("pert_retries_total").inc()
    reg.write_textfile()
    second = (tmp_path / "m.prom").read_text()
    assert first != second and "pert_retries_total 2" in second
    leftovers = [p for p in tmp_path.iterdir() if p.name != "m.prom"]
    assert leftovers == [], "temp files leaked next to the textfile"


def test_textfile_unwritable_location_degrades(tmp_path, caplog):
    target = tmp_path / "file_not_dir"
    target.write_text("occupied")
    reg = MetricsRegistry.create(
        textfile_path=str(target / "m.prom"))  # parent is a FILE
    reg.counter("pert_retries_total").inc()
    with caplog.at_level("WARNING",
                         logger="scdna_replication_tools_tpu"):
        assert reg.write_textfile() is None
        assert reg.write_textfile() is None  # warns once, stays quiet


# ---------------------------------------------------------------------------
# instrumentation seams
# ---------------------------------------------------------------------------


def test_runlog_emit_feeds_registry_even_when_log_disabled():
    reg = MetricsRegistry.create()
    metrics_mod.install(reg)
    log = RunLog(None)  # disabled instance — still instrumented
    log.emit("retry", label="x", attempt=1)
    log.emit("degrade", action="drop_ppc")
    log.emit("fault_injected", site="s", kind="oom")
    assert reg.counter("pert_retries_total").value == 1
    assert reg.counter("pert_degrades_total",
                       labels={"action": "drop_ppc"}).value == 1
    assert reg.counter("pert_faults_injected_total",
                       labels={"kind": "oom"}).value == 1


def test_phase_sink_chains_with_runlog_session(tmp_path):
    reg = MetricsRegistry.create()
    metrics_mod.install(reg)
    timer = PhaseTimer()
    attach_phase_sink(timer)
    attach_phase_sink(timer)  # idempotent
    log = RunLog(str(tmp_path / "chain.jsonl"))
    with log.session(config={}, timer=timer):
        with timer.phase("stage/x"):
            pass
    # both consumers saw the phase: the log as an event, the registry
    # as the per-phase seconds counter
    events = [json.loads(line) for line
              in (tmp_path / "chain.jsonl").read_text().splitlines()]
    assert any(ev["event"] == "phase" and ev.get("name") == "stage/x"
               for ev in events)
    series = reg.counter("pert_phase_seconds_total",
                         labels={"phase": "stage/x"})
    assert series.value is not None and series.value >= 0.0


def test_interleaved_logs_feed_their_own_registries(tmp_path):
    """Two logs active in ONE process (the serving worker's shape:
    worker-level log + per-request logs) must never cross-feed — the
    emit seam resolves the LOG-OWNED registry first and falls back to
    the process-global seam only for registry-less logs."""
    reg_a = MetricsRegistry.create()
    reg_b = MetricsRegistry.create()
    reg_global = MetricsRegistry.create()
    metrics_mod.install(reg_global)
    log_a = RunLog(str(tmp_path / "a.jsonl"))
    log_a.metrics_registry = reg_a
    log_b = RunLog(str(tmp_path / "b.jsonl"))
    log_b.metrics_registry = reg_b
    log_bare = RunLog(None)  # no owned registry: global fallback
    log_a.open_run()
    log_b.open_run()
    # interleaved emission, the two-requests-in-one-worker pattern
    log_a.emit("fit_end", step="step2", iters=100, converged=True,
               nan_abort=False, wall_seconds=1.0)
    log_b.emit("fit_end", step="step2", iters=7, converged=False,
               nan_abort=False, wall_seconds=1.0)
    log_a.emit("retry", label="x", attempt=1)
    log_b.emit("degrade", action="drop_ppc")
    log_bare.emit("retry", label="y", attempt=1)
    log_a.close_run()
    log_b.close_run()

    a_iters = reg_a.counter("pert_fit_iters_total",
                            labels={"step": "step2"}).value
    b_iters = reg_b.counter("pert_fit_iters_total",
                            labels={"step": "step2"}).value
    assert (a_iters, b_iters) == (100, 7)
    assert reg_a.counter("pert_retries_total").value == 1
    assert reg_b.counter("pert_retries_total").value in (None, 0)
    assert reg_a.counter("pert_degrades_total",
                         labels={"action": "drop_ppc"}).value \
        in (None, 0)
    assert reg_b.counter("pert_degrades_total",
                         labels={"action": "drop_ppc"}).value == 1
    # the registry-less log fed the global seam, and ONLY it
    assert reg_global.counter("pert_retries_total").value == 1
    assert reg_global.counter("pert_fit_iters_total",
                              labels={"step": "step2"}).value \
        in (None, 0)


def test_phase_sink_pinned_registry_does_not_cross_feed():
    """attach_phase_sink(timer, registry=...) routes that timer's
    phases into exactly that registry, regardless of what the
    process-global seam points at — and re-attaching with a different
    registry REPLACES the metrics sink instead of stacking a second
    one (two chained sinks would double-feed two registries)."""
    reg_a = MetricsRegistry.create()
    reg_b = MetricsRegistry.create()
    metrics_mod.install(reg_b)  # the global seam points elsewhere
    timer_a = PhaseTimer()
    attach_phase_sink(timer_a, registry=reg_a)
    attach_phase_sink(timer_a, registry=reg_a)  # idempotent per pair
    timer_a.add("stage/a", 1.0)
    key_a = ("pert_phase_seconds_total", (("phase", "stage/a"),))
    assert reg_a.counter("pert_phase_seconds_total",
                         labels={"phase": "stage/a"}).value == 1.0
    assert key_a not in reg_b._series

    # re-scope the SAME timer to reg_b: reg_a must stop receiving
    attach_phase_sink(timer_a, registry=reg_b)
    timer_a.add("stage/a", 2.0)
    assert reg_a.counter("pert_phase_seconds_total",
                         labels={"phase": "stage/a"}).value == 1.0
    assert reg_b.counter("pert_phase_seconds_total",
                         labels={"phase": "stage/a"}).value == 2.0


def test_phase_sink_rescopes_under_an_open_session(tmp_path):
    """Re-attaching while a RunLog session has chained its own sink on
    TOP must re-scope the buried metrics sink in place — an
    outermost-only replacement would stack a second sink and
    double-feed both registries (and lose the new one when the session
    restores the outer chain on exit)."""
    reg_a = MetricsRegistry.create()
    reg_b = MetricsRegistry.create()
    timer = PhaseTimer()
    attach_phase_sink(timer, registry=reg_a)
    log = RunLog(str(tmp_path / "scoped.jsonl"))
    key = ("pert_phase_seconds_total", (("phase", "stage/x"),))
    with log.session(config={}, timer=timer):
        # the session's sink now wraps the metrics sink
        attach_phase_sink(timer, registry=reg_b)
        timer.add("stage/x", 1.0)
        assert key not in reg_a._series          # no double-feed
        assert reg_b.counter("pert_phase_seconds_total",
                             labels={"phase": "stage/x"}).value == 1.0
    # the re-scoped sink survives the session's chain restoration
    timer.add("stage/x", 2.0)
    assert reg_b.counter("pert_phase_seconds_total",
                         labels={"phase": "stage/x"}).value == 3.0
    assert key not in reg_a._series


def test_memory_stats_absent_backend_is_a_noop(monkeypatch):
    """A backend whose devices lack usable memory_stats (CPU returns
    None; others raise NotImplementedError) yields no device gauges and
    no exception."""
    reg = MetricsRegistry.create()

    class _NoStats:
        id = 0

        def memory_stats(self):
            raise NotImplementedError("no stats on this backend")

    class _NoneStats:
        id = 1

        def memory_stats(self):
            return None

    import jax
    monkeypatch.setattr(jax, "local_devices",
                        lambda: [_NoStats(), _NoneStats()])
    reg.sample_device_memory()
    assert not any(k.startswith("pert_device_hbm")
                   for k in reg.snapshot(stable_only=False))


def test_memory_stats_present_sets_high_water(monkeypatch):
    reg = MetricsRegistry.create()

    class _Dev:
        def __init__(self, id_, peak):
            self.id = id_
            self._peak = peak

        def memory_stats(self):
            return {"peak_bytes_in_use": self._peak,
                    "bytes_in_use": self._peak // 2}

    import jax
    dev = _Dev(0, 1 << 30)
    monkeypatch.setattr(jax, "local_devices", lambda: [dev])
    reg.sample_device_memory()
    dev._peak = 1 << 20  # a LOWER later sample must not erode the max
    reg.sample_device_memory()
    snap = reg.snapshot()
    assert snap['pert_device_hbm_peak_bytes{device="0"}']["value"] \
        == 1 << 30


# ---------------------------------------------------------------------------
# fleet: index / query / trend / regress
# ---------------------------------------------------------------------------


def test_fleet_index_and_query(same_seed_pair, tmp_path, capsys):
    root, _, _ = same_seed_pair
    out = tmp_path / "index.json"
    assert pert_fleet.main(["index", "--roots", str(root),
                            "--out", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert doc["kind"] == "pert_fleet_index" and doc["num_runs"] == 2
    from scdna_replication_tools_tpu.obs import SCHEMA_VERSION

    for record in doc["runs"]:
        assert record["schema_version"] == SCHEMA_VERSION
        assert record["metrics"]["pert_fit_iters_total"] == 24
        assert record["workload"]["num_cells"] is not None
    # query by the (shared) config hash finds both; a bogus hash none
    capsys.readouterr()
    assert pert_fleet.main(["query", "--index", str(out),
                            "--config-hash",
                            doc["runs"][0]["config_hash"]]) == 0

    def _rows(text):
        return [ln for ln in text.splitlines() if ln.startswith("| `")]

    assert len(_rows(capsys.readouterr().out)) == 2
    assert pert_fleet.main(["query", "--index", str(out),
                            "--config-hash", "nope"]) == 0
    assert len(_rows(capsys.readouterr().out)) == 0


def test_fleet_trend_renders_sparkline(same_seed_pair, tmp_path):
    root, _, _ = same_seed_pair
    out = tmp_path / "trend.md"
    assert pert_fleet.main(["trend", "--roots", str(root),
                            "--index", str(tmp_path / "absent.json"),
                            "--metric", "pert_fit_wall_seconds",
                            "pert_fit_iters_total",
                            "--out", str(out)]) == 0
    text = out.read_text()
    assert "## `pert_fit_iters_total`" in text
    assert any(ch in text for ch in "▁▂▃▄▅▆▇█")


def test_fleet_regress_clean_on_identical_run(same_seed_pair, tmp_path):
    root, _, _ = same_seed_pair
    run = str(root / "a.jsonl")
    base = tmp_path / "base.json"
    # the documented refresh workflow needs no --baseline
    assert pert_fleet.main(["regress", "--run", run,
                            "--write-baseline", str(base)]) == 0
    assert base.is_file()
    assert pert_fleet.main(["regress", "--run", run,
                            "--baseline", str(base)]) == 0
    # comparing without a baseline is a usage error, not a crash
    with pytest.raises(SystemExit):
        pert_fleet.main(["regress", "--run", run])


def test_fleet_regress_seeded_20pct_fit_wall_regression_exits_nonzero(
        same_seed_pair, tmp_path, capsys):
    """The acceptance pin: a synthetic +20% fit-wall regression against
    the baseline trips the manifest's 15% threshold -> nonzero exit."""
    root, _, _ = same_seed_pair
    run = str(root / "a.jsonl")
    record = pert_fleet.run_record(run)
    baseline = pert_fleet.write_baseline(record,
                                         tmp_path / "base.json")
    doc = json.loads((tmp_path / "base.json").read_text())
    # the run is exactly the baseline, so shrink the BASELINE's fit
    # wall: the run then reads as +20% — an injected regression
    doc["metrics"]["pert_fit_wall_seconds"] /= 1.20
    (tmp_path / "base.json").write_text(json.dumps(doc))
    rc = pert_fleet.main(["regress", "--run", run, "--baseline",
                          str(tmp_path / "base.json")])
    assert rc == 1
    err = capsys.readouterr()
    assert "REGRESSION GATE FAILED" in err.err
    assert "pert_fit_wall_seconds" in err.err
    assert baseline["kind"] == "pert_fleet_baseline"


def test_fleet_regress_direction_aware(same_seed_pair, tmp_path):
    """An IMPROVEMENT past the threshold must not fail the gate."""
    root, _, _ = same_seed_pair
    run = str(root / "a.jsonl")
    record = pert_fleet.run_record(run)
    pert_fleet.write_baseline(record, tmp_path / "base.json")
    doc = json.loads((tmp_path / "base.json").read_text())
    doc["metrics"]["pert_fit_wall_seconds"] *= 1.5   # run is 33% faster
    (tmp_path / "base.json").write_text(json.dumps(doc))
    assert pert_fleet.main(["regress", "--run", run, "--baseline",
                            str(tmp_path / "base.json")]) == 0


def test_fleet_regress_tolerance_scale_widens_thresholds(
        same_seed_pair, tmp_path):
    root, _, _ = same_seed_pair
    run = str(root / "a.jsonl")
    record = pert_fleet.run_record(run)
    pert_fleet.write_baseline(record, tmp_path / "base.json")
    doc = json.loads((tmp_path / "base.json").read_text())
    doc["metrics"]["pert_fit_wall_seconds"] /= 1.20
    (tmp_path / "base.json").write_text(json.dumps(doc))
    assert pert_fleet.main(["regress", "--run", run, "--baseline",
                            str(tmp_path / "base.json"),
                            "--tolerance-scale", "4"]) == 0


def test_fleet_regress_zero_baseline_is_incomparable_not_gated(
        same_seed_pair, tmp_path, capsys):
    """A gated metric whose baseline is 0 has an undefined relative
    delta (+inf beats any tolerance scale) — it must warn and be marked
    incomparable, never hard-fail the gate (a warm-cache baseline with
    0 compile misses would otherwise wedge CI forever)."""
    root, _, _ = same_seed_pair
    run = str(root / "a.jsonl")
    record = pert_fleet.run_record(run)
    assert record["metrics"]["pert_compile_cache_misses_total"] > 0
    pert_fleet.write_baseline(record, tmp_path / "base.json")
    doc = json.loads((tmp_path / "base.json").read_text())
    doc["metrics"]["pert_compile_cache_misses_total"] = 0  # gated metric
    (tmp_path / "base.json").write_text(json.dumps(doc))
    assert pert_fleet.main(["regress", "--run", run, "--baseline",
                            str(tmp_path / "base.json")]) == 0
    captured = capsys.readouterr()
    assert "incomparable" in captured.out
    assert "zero base" in captured.err + captured.out


def test_regress_verdict_higher_direction_is_satisfiable():
    """The 'higher is better' gate must be able to fire: a non-negative
    metric can drop at most 100% (bad saturates at 1.0), so effective
    thresholds are capped below that — a throughput collapse REGRESSES
    even under a large --tolerance-scale, and a total cache-hit loss
    trips the 0.5 manifest threshold."""
    from scdna_replication_tools_tpu.obs.metrics import regress_verdict

    spec = {"regress": {"threshold": 0.3, "direction": "higher"}}
    # collapse 100 -> 1 iters/s under scale 4 (0.3*4=1.2 capped to .95)
    _, thr, verdict = regress_verdict(spec, 100.0, 1.0,
                                      tolerance_scale=4.0)
    assert thr < 1.0 and verdict == "REGRESSED"
    hits = manifest_metrics()["pert_compile_cache_hits_total"]
    assert regress_verdict(hits, 8, 0)[2] == "REGRESSED"   # all hits lost
    assert regress_verdict(hits, 8, 6)[2] == "ok"          # within 50%
    # a zero-base IMPROVEMENT on a 'higher' metric is not incomparable
    assert regress_verdict(hits, 0, 4)[2] == "improved"


def test_report_compare_and_fleet_regress_share_one_judgement(
        same_seed_pair, tmp_path):
    """The --compare table and the fleet gate must agree: both consume
    obs.metrics.regress_verdict (pinned here via the same doctored
    pair used in the compare test)."""
    from scdna_replication_tools_tpu.obs.metrics import regress_verdict

    root, _, _ = same_seed_pair
    record = pert_fleet.run_record(str(root / "a.jsonl"))
    base = pert_fleet.write_baseline(record, tmp_path / "b.json")
    result = pert_fleet.compare_to_baseline(base, record)
    for row in result["rows"]:
        if row["verdict"] in ("missing",):
            continue
        spec = manifest_metrics().get(
            pert_fleet.metric_base_name(row["metric"]))
        assert row["verdict"] == regress_verdict(
            spec, row["baseline"], row["run"])[2]


def test_fleet_regress_unknown_metric_warns_not_gates(
        same_seed_pair, tmp_path, capsys):
    root, _, _ = same_seed_pair
    run = str(root / "a.jsonl")
    record = pert_fleet.run_record(run)
    pert_fleet.write_baseline(record, tmp_path / "base.json")
    doc = json.loads((tmp_path / "base.json").read_text())
    doc["metrics"]["pert_metric_from_the_future"] = 42
    (tmp_path / "base.json").write_text(json.dumps(doc))
    assert pert_fleet.main(["regress", "--run", run, "--baseline",
                            str(tmp_path / "base.json")]) == 0
    assert "pert_metric_from_the_future" in capsys.readouterr().err


def test_fleet_derives_metrics_from_pre_v5_logs():
    """The committed r08 (schema v3) artifact must still index with its
    event-derived metrics — the fleet trends history, not just new
    runs."""
    record = pert_fleet.run_record(
        REPO_ROOT / "artifacts" / "RUNLOG_r08_controller_cpu.jsonl")
    assert record is not None
    assert record["metrics"]["pert_fit_wall_seconds"] > 0
    assert record["metrics"]["pert_fit_iters_total"] > 0


def test_committed_fleet_baseline_is_well_formed():
    """The CI gate's baseline artifact: parses, declares only
    manifest-known gated metrics, and matches the controller-A/B
    workload the CI job regresses against it."""
    path = REPO_ROOT / "artifacts" / "FLEET_BASELINE_cpu.json"
    doc = json.loads(path.read_text())
    assert doc["kind"] == "pert_fleet_baseline"
    assert doc["platform"] == "cpu"
    known = manifest_metrics()
    gated = [k for k in doc["metrics"]
             if (known.get(pert_fleet._metric_base_name(k)) or {})
             .get("regress")]
    assert "pert_fit_wall_seconds" in gated
    assert "pert_fit_iters_total" in gated


def test_flat_metrics_merges_snapshot_over_derived(same_seed_pair):
    root, _, _ = same_seed_pair
    summary = summarize_run(root / "a.jsonl")
    flat = flat_metrics(summary)
    # derived-only (wall-clock) and snapshot-only (labelled counters)
    # coexist in one vector
    assert "pert_fit_wall_seconds" in flat
    assert 'pert_fit_iters_total{step="step2"}' in flat
    assert flat['pert_fit_iters_total{step="step2"}'] == 12


# ---------------------------------------------------------------------------
# report integration
# ---------------------------------------------------------------------------


def test_report_metrics_section_on_v5_run(same_seed_pair):
    from tools.pert_report import render_report

    root, _, _ = same_seed_pair
    report = render_report(root / "a.jsonl")
    assert "## Metrics" in report
    assert "pert_fit_iters_total" in report


def test_report_metrics_section_pinned_on_committed_artifact():
    """The committed r09 (schema v5) run log renders a real Metrics
    section — the satellite's committed-artifact pin."""
    from tools.pert_report import render_report

    report = render_report(
        REPO_ROOT / "artifacts" / "RUNLOG_r09_metrics_cpu.jsonl")
    assert "## Metrics" in report
    assert 'pert_fit_iters_total{step="step2"}' in report
    assert "pre-v5" not in report


def test_report_metrics_placeholder_on_pre_v5_artifact():
    from tools.pert_report import render_report

    report = render_report(
        REPO_ROOT / "artifacts" / "RUNLOG_r08_controller_cpu.jsonl")
    assert "## Metrics" in report
    assert "pre-v5 run log" in report


def test_report_compare_applies_regression_thresholds(same_seed_pair,
                                                      tmp_path):
    from tools.pert_report import render_compare

    root, _, _ = same_seed_pair
    # a doctored copy with +50% fit wall: the compare table must mark
    # the gated metric over threshold
    events = _events(root / "a.jsonl")
    for ev in events:
        if ev["event"] == "fit_end":
            ev["wall_seconds"] = round(ev["wall_seconds"] * 1.5, 4)
    doctored = tmp_path / "slow.jsonl"
    doctored.write_text("\n".join(json.dumps(ev) for ev in events)
                        + "\n")
    report = render_compare(root / "a.jsonl", doctored)
    assert "## Metrics (B - A)" in report
    row = next(line for line in report.splitlines()
               if line.startswith("| `pert_fit_wall_seconds`"))
    assert "over threshold" in row


# ---------------------------------------------------------------------------
# overhead guard
# ---------------------------------------------------------------------------

SPEC = PertModelSpec(P=5, K=2, L=1, tau_mode="param")


def _problem(num_cells=64, num_loci=256, seed=0):
    # same shape/constitution as the PR-4 diagnostics guard
    # (tests/test_runlog.py::_problem at its overhead size)
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    reads = rng.poisson(40, (num_cells, num_loci)).astype(np.float32)
    gammas = rng.uniform(0.35, 0.6, num_loci).astype(np.float32)
    etas = np.ones((num_cells, num_loci, SPEC.P), np.float32)
    etas[:, :, 2] = 100.0
    batch = PertBatch(
        reads=jnp.asarray(reads),
        libs=jnp.zeros(num_cells, jnp.int32),
        gamma_feats=gc_features(jnp.asarray(gammas), SPEC.K),
        mask=jnp.ones((num_cells,), jnp.float32),
        etas=jnp.asarray(etas),
    )
    params0 = init_params(SPEC, batch, {},
                          t_init=np.full(num_cells, 0.4, np.float32))
    return params0, ({}, batch)


def test_metrics_overhead_below_2_percent():
    """Bench guard for the acceptance bar: the metrics registry must add
    <2% wall to the step-2 fit at the smoke shape.  Same methodology as
    the PR-4 diagnostics and PR-5 QC guards: both configurations
    pre-compiled, then alternating timed dispatches, best-of-N.  The
    registry does NO in-fit work (it rides event emission and phase
    exits — see PERF_NOTES "Metrics-registry overhead"), so the true
    delta is zero; the absolute slack absorbs scheduler jitter at the
    ~2 s smoke wall, where per-dispatch noise alone exceeds 2% on a
    contended CI box."""
    svi.clear_program_cache()
    iters = 60

    def one_fit(with_metrics, seed):
        if with_metrics:
            reg = MetricsRegistry.create()
            metrics_mod.install(reg)
        else:
            metrics_mod.install(None)
        try:
            params0, loss_args = _problem(seed=seed)
            fit = fit_map(_PertLossFn(spec=SPEC), params0, loss_args,
                          max_iter=iters, min_iter=iters,
                          diag_every=25)
            assert fit.num_iters == iters
            return fit.timings["fit"]
        finally:
            metrics_mod.install(None)

    one_fit(False, seed=0)   # compile both paths outside the
    one_fit(True, seed=0)    # timed region
    base, metered = [], []
    for rep in range(1, 8):
        base.append(one_fit(False, seed=rep))
        metered.append(one_fit(True, seed=rep))
    base_wall, metered_wall = min(base), min(metered)
    assert metered_wall <= base_wall * 1.02 + 0.05, \
        (f"metrics registry costs "
         f"{(metered_wall / base_wall - 1):.1%} of the fit wall "
         f"(base {base_wall:.3f}s vs metered {metered_wall:.3f}s)")


# ---------------------------------------------------------------------------
# registry hygiene
# ---------------------------------------------------------------------------


def test_registry_uninstalled_after_facade_run(synthetic_frames,
                                               tmp_path):
    """The facade retires its registry from the process-global seam at
    run end — a later bare RunLog must see no snapshot injection."""
    scrt = _run_once(synthetic_frames, tmp_path / "z.jsonl")
    assert metrics_mod.current() is metrics_mod._NULL
    # ...while the registry object stays inspectable on the facade
    assert scrt.metrics_registry.snapshot()


def test_uninstall_respects_newer_install():
    a, b = MetricsRegistry.create(), MetricsRegistry.create()
    metrics_mod.install(a)
    metrics_mod.install(b)
    metrics_mod.uninstall(a)     # stale cleanup must not clobber b
    assert metrics_mod.current() is b
    metrics_mod.uninstall(b)
    assert metrics_mod.current() is metrics_mod._NULL


def test_null_registry_swallows_everything():
    null = metrics_mod.current()
    null.counter("pert_whatever").inc()
    null.observe("pert_whatever", 3)
    null.observe_phase("x", 1.0)
    null.record_event("fit_end", {})
    null.sample_device_memory()
    assert null.snapshot() == {} and null.write_textfile() is None
