"""Pipeline-stage tests: segment, lowess, consensus, assignment, SPF,
phase calling, pseudobulk, twidth, deterministic levels."""

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.pipeline.segment import find_breakpoints
from scdna_replication_tools_tpu.pipeline.gc_correction import (
    bulk_g1_gc_correction,
    lowess,
)
from scdna_replication_tools_tpu.pipeline.pseudobulk import (
    compute_pseudobulk_rt_profiles,
)
from scdna_replication_tools_tpu.pipeline.twidth import (
    calculate_twidth,
    compute_time_from_scheduled_column,
)
from scdna_replication_tools_tpu.pipeline.phase import predict_cycle_phase
from scdna_replication_tools_tpu.api import SPF


def test_find_breakpoints_single():
    y = np.concatenate([np.zeros(50), np.ones(50) * 3.0])
    bkps = find_breakpoints(y, n_bkps=1)
    assert bkps == [50, 100]


def test_find_breakpoints_double():
    y = np.concatenate([np.zeros(40), np.ones(30) * 3.0, np.zeros(40)])
    bkps = find_breakpoints(y, n_bkps=2)
    assert bkps == [40, 70, 110]


def test_lowess_recovers_smooth_trend():
    rng = np.random.default_rng(0)
    x = np.linspace(0, 1, 300)
    y = np.sin(2 * x) + rng.normal(0, 0.05, 300)
    xv = np.linspace(0.1, 0.9, 20)
    pred = lowess(y, x, xv, frac=0.3)
    np.testing.assert_allclose(pred, np.sin(2 * xv), atol=0.08)


def test_bulk_gc_correction_flattens_gc_trend(synthetic_frames):
    df_s, df_g = synthetic_frames
    rng = np.random.default_rng(2)
    for df in (df_s, df_g):
        # reads strongly driven by GC
        df["reads"] = rng.poisson(100 * np.exp(2.0 * df["gc"]))
    cn_s, cn_g1 = bulk_g1_gc_correction(df_s.copy(), df_g.copy())
    # after correction, correlation of normalised reads with GC ~ 0
    r_before = np.corrcoef(cn_g1["reads"], cn_g1["gc"])[0, 1]
    r_after = np.corrcoef(cn_g1["rpm_gc_norm"], cn_g1["gc"])[0, 1]
    assert abs(r_after) < 0.1 < abs(r_before)


def test_spf_fractions(synthetic_frames):
    df_s, df_g = synthetic_frames
    rng = np.random.default_rng(3)
    for df in (df_s, df_g):
        df["reads"] = rng.poisson(
            40 * df["true_somatic_cn"].to_numpy()).astype(float)
    spf = SPF(df_s.copy(), df_g.copy(), input_col="reads",
              clone_col="clone_id")
    _, out = spf.infer()
    assert set(out.columns) == {"clone_id", "SPF", "SPF_std", "num_s",
                                "num_g"}
    # both clones have 12 S and 12 G cells -> SPF 0.5 each
    np.testing.assert_allclose(out["SPF"], 0.5)
    assert (out["SPF_std"] > 0).all()


def test_binarize_without_chr_column():
    """Regression: chr-less input must binarise, not silently empty out."""
    from scdna_replication_tools_tpu.pipeline.binarize import (
        binarize_profiles,
    )
    rng = np.random.default_rng(0)
    df = pd.DataFrame({
        "cell_id": np.repeat([f"c{i}" for i in range(4)], 50),
        "start": np.tile(np.arange(50), 4),
        "rt_value": rng.normal(0, 1, 200),
    })
    out, manhattan = binarize_profiles(df, "rt_value")
    assert len(out) == 200
    assert set(out["rt_state"].unique()) <= {0.0, 1.0}
    assert len(manhattan) == 400  # 4 cells x 100 thresholds


def _phase_input():
    rng = np.random.default_rng(4)
    rows = []
    for i in range(6):
        n = 200
        if i < 3:  # replicating cells
            rep = (rng.random(n) < 0.5).astype(float)
        else:      # non-replicating
            rep = np.zeros(n)
        rows.append(pd.DataFrame({
            "cell_id": f"c{i}",
            "chr": "1",
            "start": np.arange(n),
            "model_rep_state": rep,
            "model_cn_state": 2,
            "rpm": rng.poisson(50, n).astype(float),
        }))
    return pd.concat(rows, ignore_index=True)


def test_predict_cycle_phase_splits_cells():
    cn = _phase_input()
    cn_s, cn_g, cn_lq = predict_cycle_phase(cn)
    s_cells = set(cn_s["cell_id"].unique())
    g_cells = set(cn_g["cell_id"].unique())
    assert {"c0", "c1", "c2"} <= s_cells | set(cn_lq["cell_id"].unique())
    assert {"c3", "c4", "c5"} <= g_cells
    assert (cn_g["PERT_phase"] == "G1/2").all()


def test_pseudobulk_and_twidth():
    rng = np.random.default_rng(5)
    n_loci, n_cells = 150, 30
    rho = np.linspace(0.9, 0.1, n_loci)  # early -> late gradient
    rows = []
    for i in range(n_cells):
        tau = (i + 1) / (n_cells + 1)
        rep = (rng.random(n_loci) < 1 / (1 + np.exp(-8 * (tau - rho)))
               ).astype(float)
        rows.append(pd.DataFrame({
            "cell_id": f"c{i}", "chr": "1", "start": np.arange(n_loci),
            "clone_id": "A", "rt_state": rep, "frac_rt": rep.mean(),
        }))
    cn = pd.concat(rows, ignore_index=True)

    bulk = compute_pseudobulk_rt_profiles(cn, "rt_state")
    assert "pseudobulk_rt_state" in bulk.columns
    assert "pseudobulk_hours" in bulk.columns
    assert bulk["pseudobulk_hours"].max() == pytest.approx(10.0)
    # early loci (high mean rep) -> small hours
    r = np.corrcoef(bulk["pseudobulk_rt_state"], bulk["pseudobulk_hours"])[0, 1]
    assert r < -0.9

    cn = pd.merge(cn, bulk)
    cn = compute_time_from_scheduled_column(
        cn, pseudobulk_col="pseudobulk_hours", frac_rt_col="frac_rt")
    t_width, right, left, popt, tb, pr = calculate_twidth(cn)
    assert np.isfinite(t_width)
    # %-replicated decreases with time-from-scheduled, so the 25% point
    # lies right of the 75% point and t_width is positive
    assert 0 < t_width < 20
