"""Distribution log-prob parity vs torch.distributions (the reference's
numerical ground truth, reference: pert_model.py:4-14)."""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from scdna_replication_tools_tpu.ops import dists


def test_nb_log_prob_matches_torch():
    rng = np.random.default_rng(0)
    k = rng.integers(0, 200, size=50).astype(np.float32)
    delta = rng.uniform(1.0, 80.0, size=50).astype(np.float32)
    lamb = 0.75
    ours = dists.nb_log_prob(jnp.asarray(k), jnp.asarray(delta),
                             np.log(lamb), np.log1p(-lamb))
    ref = torch.distributions.NegativeBinomial(
        total_count=torch.tensor(delta), probs=torch.tensor(lamb)
    ).log_prob(torch.tensor(k)).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-4, atol=1e-4)


def test_gamma_log_prob_matches_torch():
    x = np.asarray([0.5, 2.0, 10.0, 40.0], np.float32)
    ours = dists.gamma_log_prob(jnp.asarray(x), 2.0, 0.2)
    ref = torch.distributions.Gamma(2.0, 0.2).log_prob(torch.tensor(x)).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-5, atol=1e-5)


def test_beta_log_prob_matches_torch():
    x = np.asarray([0.1, 0.5, 0.9], np.float32)
    ours = dists.beta_log_prob(jnp.asarray(x), 1.5, 1.5)
    ref = torch.distributions.Beta(1.5, 1.5).log_prob(torch.tensor(x)).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-5, atol=1e-5)


def test_normal_log_prob_matches_torch():
    x = np.asarray([-1.0, 0.0, 2.5], np.float32)
    ours = dists.normal_log_prob(jnp.asarray(x), 1.0, 2.0)
    ref = torch.distributions.Normal(1.0, 2.0).log_prob(torch.tensor(x)).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-5, atol=1e-5)


def test_dirichlet_log_prob_matches_torch():
    conc = np.asarray([[1.0, 2.0, 3.0], [5.0, 1.0, 1.0]], np.float32)
    p = np.asarray([[0.2, 0.3, 0.5], [0.7, 0.1, 0.2]], np.float32)
    ours = dists.dirichlet_log_prob(jnp.asarray(p), jnp.asarray(conc))
    ref = torch.distributions.Dirichlet(torch.tensor(conc)).log_prob(
        torch.tensor(p)).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-5, atol=1e-5)


def test_bernoulli_log_prob_matches_torch():
    p = np.asarray([0.1, 0.5, 0.999], np.float32)
    for v in (0.0, 1.0):
        x = np.full(3, v, np.float32)
        ours = dists.bernoulli_log_prob(jnp.asarray(x), jnp.asarray(p))
        ref = torch.distributions.Bernoulli(torch.tensor(p)).log_prob(
            torch.tensor(x)).numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-4, atol=1e-5)
