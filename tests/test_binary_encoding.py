"""Parity tests for the independent-binary CN encoding + fused Adam.

The binary encoding (``enum_impl='binary'``, arXiv 2206.00093)
reparameterises the P-way categorical ``pi_logits`` as Kb = ceil(log2 P)
independent binary logit planes masked to the valid states, shrinking
every O(P) per-iteration HBM stream (pi in, dpi out, Adam state) to
O(log P) — see PERF_NOTES' planes table (146 -> 56 at P = 13).  It is a
DIFFERENT variational family, so parity is gated the way sparse etas
was, at three levels:

* kernel: the fused binary Pallas kernels against an XLA transcription
  of the same masked-softmax objective (value + every gradient), and
  the sparse-vs-dense binary variants against each other;
* model loss: ``binary_interpret`` (the kernel) against ``binary_xla``
  (the fallback) — same encoding, different backend, tight agreement;
* runner: a full simulate-and-recover run under ``binary_xla`` must
  match the dense arm's accuracy (tau truth-correlation, CN accuracy,
  qc_pass counts) within tolerance.

The fused single-sweep Adam path (ops/adam_kernel.py) and the bfloat16
moment storage ride along: the XLA implementation must reproduce the
optax trajectory BIT-exactly at float32, the Pallas kernel to rounding,
bfloat16 moments within a bounded divergence, and the dtype-aware
checkpoint contract must round-trip bfloat16 bit-exactly while REFUSING
a mid-budget resume across moment dtypes.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from scdna_replication_tools_tpu.layout import state_major
from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    binary_log_pi,
    init_params,
    pert_loss,
)
from scdna_replication_tools_tpu.models.priors import sparsify_etas
from scdna_replication_tools_tpu.ops.enum_kernel import (
    binary_code_matrix,
    binary_code_width,
    enum_loglik_fused_binary,
    enum_loglik_fused_sparse_binary,
    planes_per_iter,
    resolve_enum_impl,
)
from scdna_replication_tools_tpu.ops.gc import gc_features

P = 13


# ---------------------------------------------------------------------------
# encoding basics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("P_", [2, 3, 7, 13, 16, 20])
def test_binary_code_matrix_is_injective(P_):
    """Every valid state must map to a distinct bit code of width
    ceil(log2 P) — the masked softmax is over exactly these rows."""
    B = binary_code_matrix(P_)
    Kb = binary_code_width(P_)
    assert B.shape == (P_, Kb)
    codes = {tuple(row) for row in B.astype(int).tolist()}
    assert len(codes) == P_
    # row s IS the binary expansion of s
    for s in range(P_):
        assert int(sum(B[s, k] * 2 ** k for k in range(Kb))) == s


def test_resolve_enum_impl_binary_values():
    assert resolve_enum_impl("binary_xla") == "binary_xla"
    assert resolve_enum_impl("binary") in ("binary_xla", "binary_pallas")
    with pytest.raises(ValueError, match="enum_impl"):
        resolve_enum_impl("binary_nope")


def test_planes_model_matches_perf_notes_table():
    """The analytic traffic model is the PERF_NOTES table as code: the
    committed accounting numbers must never drift from the gauge the
    fleet regression gate holds."""
    assert planes_per_iter(13, binary=False, sparse_etas=True) == 146
    assert planes_per_iter(13, binary=True, sparse_etas=True) == 56
    assert planes_per_iter(13, binary=True, sparse_etas=True,
                           moment_dtype="bfloat16") == 48
    # the pre-sparse-etas historical figure: kernel 77 + adam 91
    assert planes_per_iter(13, binary=False, sparse_etas=False) == 168


# ---------------------------------------------------------------------------
# kernel-level parity
# ---------------------------------------------------------------------------

def _problem(C=8, L=96, seed=7, weight=1e5):
    rng = np.random.default_rng(seed)
    Kb = binary_code_width(P)
    reads = jnp.asarray(rng.poisson(40, (C, L)).astype(np.float32))
    mu = jnp.asarray(rng.uniform(2, 30, (C, L)).astype(np.float32))
    z = jnp.asarray(rng.normal(0, 1.5, (C, L, Kb)).astype(np.float32))
    phi = jnp.asarray(rng.uniform(0.01, 0.99, (C, L)).astype(np.float32))
    etas = np.ones((C, L, P), np.float32)
    states = rng.integers(0, P, (C, L))
    np.put_along_axis(etas, states[..., None], weight, axis=-1)
    idx, w = sparsify_etas(etas)
    ct = jnp.asarray(rng.normal(0, 1, (C, L)), jnp.float32)
    return (reads, mu, z, phi, jnp.asarray(etas), jnp.asarray(idx),
            jnp.asarray(w), jnp.float32(0.75), ct)


def _binary_xla_oracle(reads, mu, z, phi, etas, lamb):
    """XLA transcription of the fused binary objective: expand the Kb
    planes through the bit matrix, masked log-softmax over the P valid
    states, dense enumerated NB likelihood + Dirichlet data term."""
    from jax.scipy.special import gammaln, logsumexp

    B = jnp.asarray(binary_code_matrix(P))
    log_pi = jax.nn.log_softmax(jnp.einsum("clk,pk->clp", z, B), -1)
    chi = jnp.arange(P, dtype=jnp.float32)[:, None] * \
        (1.0 + jnp.arange(2, dtype=jnp.float32))[None, :]
    delta = jnp.maximum(mu[..., None, None] * chi * (1 - lamb) / lamb, 1.0)
    nb = (gammaln(reads[..., None, None] + delta) - gammaln(delta)
          - gammaln(reads[..., None, None] + 1.0)
          + delta * jnp.log1p(-lamb)
          + reads[..., None, None] * jnp.log(lamb))
    bern = jnp.stack([jnp.log1p(-phi), jnp.log(phi)], -1)
    joint = log_pi[..., :, None] + bern[..., None, :] + nb
    return logsumexp(joint, axis=(-2, -1)) \
        + jnp.sum((etas - 1.0) * log_pi, axis=-1)


@pytest.mark.parametrize("etas_kind", ["random_small", "concentrated_1e5"])
def test_binary_kernel_matches_xla_oracle(etas_kind):
    """Value + all three gradients of the fused binary kernel against
    jax.grad through the XLA oracle — including the chained
    softmax-Jacobian + bit-expansion backward (dz)."""
    reads, mu, z, phi, etas, _, _, lamb, ct = _problem()
    if etas_kind == "random_small":
        rng = np.random.default_rng(11)
        etas = jnp.asarray(rng.uniform(0.3, 5.0, etas.shape)
                           .astype(np.float32))

    def oracle(mu, z, phi):
        return jnp.sum(_binary_xla_oracle(reads, mu, z, phi, etas, lamb)
                       * ct)

    def kernel(mu, z, phi):
        return jnp.sum(enum_loglik_fused_binary(
            reads, mu, state_major(z), phi, state_major(etas), lamb, P,
            True) * ct)

    v_ref, g_ref = jax.value_and_grad(oracle, (0, 1, 2))(mu, z, phi)
    v_pal, g_pal = jax.value_and_grad(kernel, (0, 1, 2))(mu, z, phi)
    assert abs(float(v_ref - v_pal)) / (abs(float(v_ref)) + 1e-30) < 1e-4
    for name, a, b in zip(("dmu", "dz", "dphi"), g_ref, g_pal):
        rel = float(jnp.max(jnp.abs(a - b))
                    / (jnp.max(jnp.abs(a)) + 1e-30))
        assert rel < 2e-2, (name, rel)


def test_sparse_binary_kernel_matches_dense_binary_kernel():
    """The sparse-etas binary variant must equal the dense binary one
    (value AND gradients) on a one-hot prior — same math, compact
    Dirichlet encoding (mirrors test_sparse_etas's kernel gate)."""
    reads, mu, z, phi, etas, idx, w, lamb, ct = _problem()

    def dense(z):
        return jnp.sum(enum_loglik_fused_binary(
            reads, mu, state_major(z), phi, state_major(etas), lamb, P,
            True) * ct)

    def sparse(z):
        return jnp.sum(enum_loglik_fused_sparse_binary(
            reads, mu, state_major(z), phi, idx, w, lamb, P, True) * ct)

    vd, gd = jax.value_and_grad(dense)(z)
    vs, gs = jax.value_and_grad(sparse)(z)
    assert abs(float(vd - vs)) / abs(float(vd)) < 1e-5
    rel = float(jnp.max(jnp.abs(gd - gs)) / (jnp.max(jnp.abs(gd)) + 1e-30))
    assert rel < 1e-4, rel


def test_binary_kernel_rejects_bad_shapes():
    reads, mu, z, phi, etas, idx, w, lamb, _ = _problem()
    with pytest.raises(ValueError, match="Kb"):
        # cells-major z (the layout bug class the categorical kernels
        # also reject loudly)
        enum_loglik_fused_binary(reads, mu, z, phi, state_major(etas),
                                 lamb, P, True)
    with pytest.raises(ValueError, match="Kb"):
        enum_loglik_fused_sparse_binary(reads, mu, z, phi, idx, w, lamb,
                                        P, True)


# ---------------------------------------------------------------------------
# model-loss-level parity
# ---------------------------------------------------------------------------

def _model_problem(weight=1e5):
    rng = np.random.default_rng(5)
    C, L = 12, 200
    reads = rng.poisson(40, (C, L)).astype(np.float32)
    gammas = rng.uniform(0.35, 0.6, L).astype(np.float32)
    etas = np.ones((C, L, P), np.float32)
    states = rng.integers(1, 5, (C, L))
    np.put_along_axis(etas, states[..., None], weight, axis=-1)
    idx, w = sparsify_etas(etas)
    common = dict(
        reads=jnp.asarray(reads), libs=jnp.zeros((C,), jnp.int32),
        gamma_feats=gc_features(jnp.asarray(gammas), 4),
        mask=jnp.ones((C,), jnp.float32))
    sparse_batch = PertBatch(eta_idx=jnp.asarray(idx),
                             eta_w=jnp.asarray(w), **common)
    fixed = {"beta_means": jnp.zeros((1, 5), jnp.float32),
             "lamb": jnp.asarray(0.75, jnp.float32)}
    return sparse_batch, fixed, np.full(C, 0.4, np.float32), states


def test_pert_loss_binary_kernel_matches_binary_xla():
    """Full model loss + gradients: the binary kernel backend vs the
    binary XLA fallback — SAME encoding (identical pi_bin_logits
    parameterisation), so agreement is at kernel-accuracy level."""
    batch, fixed, t_init, _ = _model_problem()
    out = {}
    for impl in ("binary_xla", "binary_interpret"):
        spec = PertModelSpec(P=P, K=4, L=1, tau_mode="param",
                             cond_beta_means=True, fixed_lamb=True,
                             sparse_etas=True, enum_impl=impl)
        params = init_params(spec, batch, fixed, t_init=t_init)
        assert "pi_bin_logits" in params and "pi_logits" not in params
        assert params["pi_bin_logits"].shape == \
            (binary_code_width(P),) + batch.reads.shape
        out[impl] = jax.value_and_grad(
            lambda p: pert_loss(spec, p, fixed, batch))(params)
    (va, ga), (vb, gb) = out["binary_xla"], out["binary_interpret"]
    assert abs(float(va - vb)) / abs(float(va)) < 5e-4
    for k in ga:
        denom = float(jnp.max(jnp.abs(ga[k]))) + 1e-20
        assert float(jnp.max(jnp.abs(ga[k] - gb[k]))) / denom < 2e-2, k


def test_binary_init_targets_the_prior_mode():
    """The one-hot-prior init must put each bin's masked-softmax argmax
    at the prior state (the binary family cannot represent the dense
    init's exact simplex point; the MODE is the contract)."""
    batch, fixed, t_init, states = _model_problem()
    spec = PertModelSpec(P=P, K=4, L=1, tau_mode="param",
                         cond_beta_means=True, fixed_lamb=True,
                         sparse_etas=True, enum_impl="binary_xla")
    params = init_params(spec, batch, fixed, t_init=t_init)
    log_pi = binary_log_pi(spec, params["pi_bin_logits"])
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(log_pi, -1)), states)
    # and the mode carries essentially all the mass under the 1e5 prior
    assert float(jnp.exp(jnp.max(log_pi, -1)).min()) > 0.99


# ---------------------------------------------------------------------------
# fused Adam + bfloat16 moments
# ---------------------------------------------------------------------------

def _fit_problem():
    rng = np.random.default_rng(0)
    pi = rng.normal(0, 1, (13, 24, 300)).astype(np.float32)
    tau = rng.normal(0, 1, (24,)).astype(np.float32)

    def fresh():
        return {"pi_logits": jnp.asarray(pi), "tau_raw": jnp.asarray(tau)}

    def loss(p):
        return jnp.sum(jnp.sin(p["pi_logits"])) * 1e-3 \
            + jnp.sum(p["tau_raw"] ** 2)

    return fresh, loss


def test_fused_adam_xla_reproduces_optax_bit_exactly():
    """The fused single-sweep update (XLA impl) replicates
    optax.scale_by_adam + scale(-lr) in operation order — the full
    compiled-fit trajectory must be BIT-identical, which is what lets
    'auto' ship without perturbing any reference-parity test."""
    from scdna_replication_tools_tpu.infer.svi import fit_map

    fresh, loss = _fit_problem()
    kw = dict(max_iter=25, min_iter=25, rel_tol=0.0, diag_every=0)
    base = fit_map(loss, fresh(), **kw)
    fused = fit_map(loss, fresh(), fused_adam="xla", **kw)
    np.testing.assert_array_equal(base.losses, fused.losses)
    for k in base.params:
        np.testing.assert_array_equal(np.asarray(base.params[k]),
                                      np.asarray(fused.params[k]))


def test_fused_adam_pallas_matches_xla():
    """The Pallas Adam kernel (interpret mode on CPU: identical body)
    agrees with the XLA implementation to float32 rounding."""
    from scdna_replication_tools_tpu.infer.svi import fit_map

    fresh, loss = _fit_problem()
    kw = dict(max_iter=25, min_iter=25, rel_tol=0.0, diag_every=0)
    x = fit_map(loss, fresh(), fused_adam="xla", **kw)
    p = fit_map(loss, fresh(), fused_adam="pallas_interpret", **kw)
    np.testing.assert_allclose(x.losses, p.losses, rtol=1e-5, atol=1e-5)


def test_bf16_moments_trajectory_divergence_is_bounded():
    """bfloat16 moment storage changes the trajectory (that is the
    documented trade) but must stay CLOSE to the float32 one over a
    real optimisation segment — a blow-up here would mean the
    arithmetic (not just the storage) lost precision."""
    from scdna_replication_tools_tpu.infer.svi import fit_map

    fresh, loss = _fit_problem()
    kw = dict(max_iter=60, min_iter=60, rel_tol=0.0, diag_every=0)
    f32 = fit_map(loss, fresh(), fused_adam="xla", **kw)
    bf16 = fit_map(loss, fresh(), fused_adam="xla",
                   moment_dtype="bfloat16", **kw)
    assert bf16.opt_state[0].mu["pi_logits"].dtype == jnp.bfloat16
    # small params (not the pi plane) keep float32 moments
    assert bf16.opt_state[0].mu["tau_raw"].dtype == jnp.float32
    denom = abs(float(f32.losses[0] - f32.losses[-1])) + 1e-30
    rel = np.max(np.abs(f32.losses - bf16.losses)) / denom
    assert rel < 0.05, rel


def test_bf16_moments_resume_is_bit_exact():
    """A bfloat16-moment fit interrupted mid-budget and resumed from
    its (params, opt_state, loss prefix) must reproduce the
    uninterrupted trajectory bit-exactly — the same contract the f32
    path pins in test_donation."""
    from scdna_replication_tools_tpu.infer.svi import fit_map

    fresh, loss = _fit_problem()
    kw = dict(rel_tol=0.0, diag_every=0, fused_adam="xla",
              moment_dtype="bfloat16")
    full = fit_map(loss, fresh(), max_iter=40, min_iter=40, **kw)
    part = fit_map(loss, fresh(), max_iter=20, min_iter=20, **kw)
    resumed = fit_map(loss, part.params, max_iter=40, min_iter=40,
                      opt_state0=part.opt_state,
                      losses_prefix=part.losses, **kw)
    np.testing.assert_array_equal(full.losses, resumed.losses)
    for k in full.params:
        np.testing.assert_array_equal(np.asarray(full.params[k]),
                                      np.asarray(resumed.params[k]))


def test_checkpoint_round_trips_bf16_moments_bit_exactly(tmp_path):
    """save -> load of a bfloat16-moment optimizer state preserves the
    exact bits (uint16-view storage; npz cannot hold ml_dtypes
    natively) and records the moment dtype in the meta block."""
    from scdna_replication_tools_tpu.infer import checkpoint as ckpt
    from scdna_replication_tools_tpu.infer.svi import fit_map

    fresh, loss = _fit_problem()
    fit = fit_map(loss, fresh(), max_iter=10, min_iter=10, rel_tol=0.0,
                  diag_every=0, fused_adam="xla",
                  moment_dtype="bfloat16")
    params_np = jax.tree_util.tree_map(np.asarray, fit.params)
    opt_np = jax.tree_util.tree_map(np.asarray, fit.opt_state)
    ckpt.save_step(str(tmp_path), "step2", params_np, fit.losses,
                   opt_state=opt_np, num_iters=fit.num_iters,
                   converged=False)
    params, losses, extra = ckpt.load_step(str(tmp_path), "step2")
    assert str(extra["meta.opt_moment_dtype"]) == "bfloat16"
    restored = ckpt.restore_opt_state(extra, params, 0.05, 0.8, 0.99)
    ref_leaves = jax.tree_util.tree_leaves(opt_np)
    got_leaves = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, restored))
    assert len(ref_leaves) == len(got_leaves)
    for a, b in zip(ref_leaves, got_leaves):
        assert a.dtype == b.dtype, (a.dtype, b.dtype)
        np.testing.assert_array_equal(
            a.view(np.uint16) if a.dtype.name == "bfloat16" else a,
            b.view(np.uint16) if b.dtype.name == "bfloat16" else b)


def test_resume_refuses_moment_dtype_mismatch(tmp_path, synthetic_frames):
    """A PARTIAL float32-moment checkpoint must refuse to resume under
    optimizer_state_dtype='bfloat16' (the continuation cannot be
    bit-exact) — loudly, not by silent divergence."""
    from conftest import dense_inputs_from_frames
    from scdna_replication_tools_tpu.config import PertConfig
    from scdna_replication_tools_tpu.infer import checkpoint as ckpt
    from scdna_replication_tools_tpu.infer.runner import PertInference
    from scdna_replication_tools_tpu.infer.svi import fit_map

    fresh, loss = _fit_problem()
    fit = fit_map(loss, fresh(), max_iter=10, min_iter=10, rel_tol=0.0,
                  diag_every=0)
    ckpt.save_step(str(tmp_path), "step2",
                   jax.tree_util.tree_map(np.asarray, fit.params),
                   fit.losses,
                   opt_state=jax.tree_util.tree_map(np.asarray,
                                                    fit.opt_state),
                   num_iters=fit.num_iters, converged=False)

    s, g1, clone_idx = dense_inputs_from_frames(synthetic_frames)
    config = PertConfig(checkpoint_dir=str(tmp_path), resume="force",
                        max_iter=100, min_iter=10,
                        optimizer_state_dtype="bfloat16",
                        telemetry_path=None)
    inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    with pytest.raises(ValueError, match="optimizer_state_dtype"):
        inf._load_resumable("step2", 100, None, None, None)


# ---------------------------------------------------------------------------
# runner-level parity (simulate and recover)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def binary_vs_dense_runs(synthetic_frames):
    """Two full scRT runs on the same simulated workload — the sole
    delta is enum_impl ('auto' -> categorical XLA on CPU vs
    'binary_xla').  Module-scoped: the two pipelines are the expensive
    part of this suite."""
    from scdna_replication_tools_tpu.api import scRT
    from scdna_replication_tools_tpu.models.simulator import pert_simulator

    df_s, df_g = synthetic_frames
    sim_s, sim_g = pert_simulator(
        df_s, df_g, num_reads=50_000, rt_cols=["rt_A", "rt_B"],
        clones=["A", "B"], lamb=0.75, betas=[0.5, 0.0], a=10.0, seed=11)
    for df in (sim_s, sim_g):
        df["reads"] = df["true_reads_norm"]
        df["state"] = df["true_somatic_cn"].astype(int)
        df["copy"] = df["true_somatic_cn"].astype(float)

    out = {}
    for name, impl in (("dense", "auto"), ("binary", "binary_xla")):
        scrt = scRT(sim_s.copy(), sim_g.copy(), input_col="reads",
                    clone_col="clone_id", assign_col="copy",
                    cn_prior_method="g1_clones", max_iter=300,
                    min_iter=100, rt_prior_col=None, run_step3=False,
                    enum_impl=impl, seed=0)
        cn_s_out, _, _, _ = scrt.infer(level="pert")
        qc = scrt.cell_qc()
        out[name] = (cn_s_out, qc)
    return out


def test_runner_binary_matches_dense_tau_accuracy(binary_vs_dense_runs):
    """ISSUE 11 acceptance: binary-arm tau truth-correlation >= 0.99 of
    the dense arm's value on the simulator workload."""
    corr = {}
    for name, (cn_out, _) in binary_vs_dense_runs.items():
        per_cell = cn_out.groupby("cell_id").agg(
            tau=("model_tau", "first"), true_t=("true_t", "first"))
        corr[name] = float(np.corrcoef(per_cell["tau"],
                                       per_cell["true_t"])[0, 1])
    assert corr["dense"] > 0.8, corr
    assert corr["binary"] >= 0.99 * corr["dense"], corr


def test_runner_binary_matches_dense_cn_accuracy(binary_vs_dense_runs):
    acc = {}
    for name, (cn_out, _) in binary_vs_dense_runs.items():
        acc[name] = float((cn_out["model_cn_state"]
                           == cn_out["true_somatic_cn"]).mean())
    assert acc["dense"] > 0.9, acc
    assert acc["binary"] >= acc["dense"] - 0.02, acc


def test_runner_binary_matches_dense_qc_pass_counts(binary_vs_dense_runs):
    """Identical qc_pass counts within tolerance: the encoding change
    must not shift cells across the model-health QC gates."""
    counts = {name: int(qc["qc_pass"].sum())
              for name, (_, qc) in binary_vs_dense_runs.items()}
    n = len(binary_vs_dense_runs["dense"][1])
    assert abs(counts["binary"] - counts["dense"]) <= max(1, n // 12), \
        counts
