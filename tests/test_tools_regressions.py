"""Regression tests for the repo tooling (ADVICE.md round 5 + BENCH_r05).

* ``tools/accuracy_sweep.py`` — the end-of-run summary used to crash
  with TypeError when any config's metric was None (min() over Nones),
  losing the summary line AFTER all the compute was spent.
* ``bench.py`` — the bare harness invocation timed out (BENCH_r05
  rc=124, nothing parsed); the --budget preset layer keeps the default
  fast while ``--budget full`` preserves the production-shaped problem.

Both modules are import-light at top level (no jax/torch until main()),
so these tests stay in the fast tier.
"""

import importlib.util
import json
import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]


def _load(name, relpath):
    spec = importlib.util.spec_from_file_location(name, REPO_ROOT / relpath)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_sweep_summary_filters_none_metrics():
    sweep = _load("accuracy_sweep_under_test", "tools/accuracy_sweep.py")
    results = [
        {"rep_accuracy": 0.93}, {"rep_accuracy": None},
        {"rep_accuracy": 0.88},
    ]
    s = sweep.summarize(results)
    assert s == {"configs_run": 3, "min_rep_accuracy": 0.88,
                 "configs_without_accuracy": 1}


def test_sweep_summary_all_none_is_well_defined():
    sweep = _load("accuracy_sweep_under_test", "tools/accuracy_sweep.py")
    s = sweep.summarize([{"rep_accuracy": None}])
    assert s["min_rep_accuracy"] is None
    assert s["configs_without_accuracy"] == 1
    assert s["configs_run"] == 1


def test_sweep_summary_empty_results():
    sweep = _load("accuracy_sweep_under_test", "tools/accuracy_sweep.py")
    s = sweep.summarize([])
    assert s["configs_run"] == 0 and s["min_rep_accuracy"] is None


def test_bench_default_budget_is_fast():
    bench = _load("bench_under_test", "bench.py")
    args = bench._parse_args([])
    assert args.budget == "fast"
    assert (args.cells, args.loci, args.iters) == (256, 1024, 50)
    assert args.baseline_iters == 5 and args.probe_timeout == 60


def test_bench_full_budget_restores_production_shape():
    bench = _load("bench_under_test", "bench.py")
    args = bench._parse_args(["--budget", "full"])
    assert (args.cells, args.loci, args.iters) == (1000, 5451, 100)
    assert args.baseline_iters == 20 and args.probe_timeout == 150


def test_bench_explicit_args_beat_the_preset():
    bench = _load("bench_under_test", "bench.py")
    args = bench._parse_args(["--cells", "77", "--probe-timeout", "5"])
    assert args.cells == 77 and args.probe_timeout == 5
    assert args.loci == 1024        # unspecified -> fast preset still fills


def test_bench_presets_cover_every_sentinel_arg():
    """Every None-defaulted size arg must be filled by BOTH presets, or a
    bare run would crash on a None size."""
    bench = _load("bench_under_test", "bench.py")
    for budget in bench.BUDGETS:
        args = bench._parse_args(["--budget", budget])
        for name in ("cells", "loci", "iters", "baseline_iters",
                     "probe_timeout"):
            assert getattr(args, name) is not None, (budget, name)


def test_bench_baseline_cache_roundtrip(tmp_path):
    """write_baseline_cache -> load_cached_baseline must roundtrip by
    shape key, replace same-shape entries, and miss on other shapes —
    the mechanism that keeps the CPU-fallback path off the ~20-minute
    torch-twin measurement (VERDICT r5 next-round #1)."""
    bench = _load("bench_under_test", "bench.py")
    path = tmp_path / "baseline.json"
    args = bench._parse_args(["--budget", "fast"])
    assert bench.load_cached_baseline(args, path=path) is None
    bench.write_baseline_cache(args, 1.234, -42.0, path=path)
    entry = bench.load_cached_baseline(args, path=path)
    assert entry is not None and entry["sec_per_iter"] == 1.234
    # replacement, not duplication
    bench.write_baseline_cache(args, 2.0, -41.0, path=path)
    data = json.loads(path.read_text())
    assert len(data["entries"]) == 1
    assert bench.load_cached_baseline(args, path=path)["sec_per_iter"] == 2.0
    # a different shape misses
    other = bench._parse_args(["--budget", "full"])
    assert bench.load_cached_baseline(other, path=path) is None


def test_bench_committed_baseline_covers_both_budgets():
    """The committed artifact must hit for the budget presets — the
    exact shapes the driver and the window runner invoke — so a dead
    tunnel never re-pays the twin measurement."""
    bench = _load("bench_under_test", "bench.py")
    for budget in bench.BUDGETS:
        args = bench._parse_args(["--budget", budget])
        entry = bench.load_cached_baseline(args)
        assert entry is not None, (
            f"artifacts/BENCH_BASELINE_torch_twin.json has no entry for "
            f"the {budget!r} preset shape "
            f"({args.cells}x{args.loci}) — regenerate with "
            f"--write-baseline-cache")
        assert entry["sec_per_iter"] > 0


def test_pert_report_renders_committed_r07_artifacts(tmp_path):
    """The committed cold/warm telemetry pair must stay renderable —
    single-run report AND --compare — since they are the documented
    entry point for the run-report workflow (OBSERVABILITY.md)."""
    report_tool = _load("pert_report_under_test", "tools/pert_report.py")
    cold = REPO_ROOT / "artifacts" / "RUNLOG_r07_cold_cpu.jsonl"
    warm = REPO_ROOT / "artifacts" / "RUNLOG_r07_warm_cpu.jsonl"
    assert cold.exists() and warm.exists()

    single = report_tool.render_report(cold)
    assert "# PERT run report" in single
    assert "## Phase waterfall" in single
    assert "## SVI fits" in single
    assert "step2" in single
    assert "## Compiled programs" in single
    assert "## Mirror rescue" in single
    # pre-v4 artifact: the Resilience section renders a placeholder,
    # never pretends the durability trail was clean
    assert "## Resilience" in single
    assert "pre-v4 run log" in single

    out = tmp_path / "cmp.md"
    report_tool.main(["--compare", str(cold), str(warm),
                      "--out", str(out)])
    compare = out.read_text()
    assert "# PERT run comparison" in compare
    assert "## Phases (B - A)" in compare
    assert "## Fits (B - A)" in compare
    # the pair is the SAME experiment with only the log path moved
    assert "**configs**: identical" in compare


def test_pert_report_renders_nan_abort_diagnostics(tmp_path):
    """A diverged fit stores its non-finite grad/param norms as null in
    the JSONL (RFC 8259 has no NaN); the fit table must render that run
    — it is exactly the post-mortem the report exists for."""
    import json

    report_tool = _load("pert_report_nan_case", "tools/pert_report.py")
    events = [
        {"event": "run_start", "seq": 0, "t": 0.0, "schema_version": 1,
         "run_name": "pert", "pid": 1},
        {"event": "fit_end", "seq": 1, "t": 1.0, "step": "step2",
         "iters": 40, "final_loss": None, "converged": False,
         "nan_abort": True, "wall_seconds": 1.0,
         "diagnostics": {"every": 25, "samples": 2,
                         "window_start_iter": 0, "window_end_iter": 25,
                         "grad_norm_first": 12.5, "grad_norm_last": None,
                         "grad_norm_max": None, "param_norm_last": None}},
        {"event": "nan_abort", "seq": 2, "t": 1.1, "step": "step2",
         "iters": 40, "loss_tail": [1.0, None]},
        {"event": "run_end", "seq": 3, "t": 1.2, "status": "ok",
         "wall_seconds": 1.2, "events_emitted": 4},
    ]
    path = tmp_path / "nan_run.jsonl"
    path.write_text("".join(json.dumps(e) + "\n" for e in events))

    from scdna_replication_tools_tpu.obs import validate_run
    assert validate_run(path) == []
    report = report_tool.render_report(path)
    assert "12.5@i0 → nan@i25" in report


def test_committed_r07_runlogs_are_schema_valid():
    from scdna_replication_tools_tpu.obs import validate_run

    for name in ("RUNLOG_r07_cold_cpu.jsonl", "RUNLOG_r07_warm_cpu.jsonl"):
        errors = validate_run(REPO_ROOT / "artifacts" / name)
        assert errors == [], f"{name}: {errors[:5]}"


def test_full_pipeline_bench_json_r07_obs_fields():
    """The r07 artifacts carry the telemetry roll-up fields the BENCH
    rounds consume (peak HBM + program-cache counts)."""
    for name in ("FULL_PIPELINE_r07_obs_cold_cpu.json",
                 "FULL_PIPELINE_r07_obs_warm_cpu.json"):
        data = json.loads(
            (REPO_ROOT / "artifacts" / name).read_text())
        assert data["peak_hbm_bytes"] > 0
        assert data["compile_cache_misses"] >= 0
        assert data["compile_cache_hits"] >= 0
        assert data["run_log"].endswith(".jsonl")


def _write_trace(profile_dir, run="2026_01_01_00_00_00",
                 fname="host.trace.json", gz=False):
    """A minimal Chrome-trace dump in the jax.profiler layout: two XLA
    ops inside ``pert/*`` named scopes (one via the event name, one via
    args metadata — both placements occur across backends) plus one
    unscoped op."""
    events = [
        {"ph": "X", "name": "pert/fit_step/fusion.1", "dur": 3000},
        {"ph": "X", "name": "loop_convert_fusion",
         "args": {"long_name": "broadcast(pert/ppc/gamma.2)"},
         "dur": 2000},
        # nested named_scope: the innermost scope must win, not fold
        # into the enclosing pert/decode
        {"ph": "X", "name": "pert/decode/pert/qc_entropy/reduce.4",
         "dur": 1500},
        {"ph": "X", "name": "copy.3", "dur": 1000},
        {"ph": "M", "name": "process_name"},
    ]
    run_dir = profile_dir / "plugins" / "profile" / run
    run_dir.mkdir(parents=True, exist_ok=True)
    payload = json.dumps({"traceEvents": events})
    if gz:
        import gzip
        (run_dir / (fname + ".gz")).write_bytes(
            gzip.compress(payload.encode()))
    else:
        (run_dir / fname).write_text(payload)


def test_trace_summary_reads_uncompressed_and_groups_scopes(tmp_path):
    """Satellite contract: plain *.trace.json dumps (some jax
    versions/backends skip the gzip) are summarised too, and device
    time is grouped per pert/* named scope whether the scope lands in
    the event name or in the args metadata."""
    ts = _load("trace_summary_under_test", "tools/trace_summary.py")
    _write_trace(tmp_path, gz=False)
    report = ts.summarise(str(tmp_path))
    assert "named_scope groups" in report
    assert "pert/fit_step" in report and "pert/ppc" in report
    # nested scope attributed to the innermost region
    assert "pert/qc_entropy" in report
    # gz and plain dumps coexist without double-listing either run
    _write_trace(tmp_path, run="2026_01_01_00_00_01", gz=True)
    report = ts.summarise(str(tmp_path))
    assert report.count("named_scope groups") == 2
    # the SAME dump in both forms (gunzip -k) must not double-count
    _write_trace(tmp_path, run="2026_01_01_00_00_01", gz=False)
    report = ts.summarise(str(tmp_path))
    assert report.count("named_scope groups") == 2


def test_trace_summary_empty_dir_names_expected_layout(tmp_path):
    ts = _load("trace_summary_under_test", "tools/trace_summary.py")
    try:
        ts.summarise(str(tmp_path))
    except SystemExit as exc:
        msg = str(exc)
        assert "plugins/profile" in msg and "trace.json" in msg
    else:
        raise AssertionError("empty profile dir must SystemExit")


if __name__ == "__main__":
    sys.exit(0)
