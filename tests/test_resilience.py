"""Durable runs: fault injection, kill-and-resume parity, degradation.

The chaos suite behind ISSUE 9's acceptance bar: for every injection
point (phase boundaries + mid-fit chunks), killing the pipeline and
rerunning with ``resume='auto'`` must reproduce the uninterrupted
golden run's trajectory bit-exactly — with the decision + resume trail
reproducible from the RunLog.  Plus units for the pieces: the fault
plan's deterministic schedule, the exception taxonomy, retry backoff,
the watchdog, checkpoint integrity (footer, fallback, typed errors),
the manifest's fingerprint gate, and the OOM degradation ladder.

Fast subset runs in tier-1; the full kill-site matrix is ``slow``.
"""

import json
import os
import pathlib

import numpy as np
import pytest

from scdna_replication_tools_tpu.config import PertConfig
from scdna_replication_tools_tpu.infer import checkpoint as ckpt
from scdna_replication_tools_tpu.infer import manifest as manifest_mod
from scdna_replication_tools_tpu.infer.runner import (
    PertInference,
    _decode_with_degradation,
)
from scdna_replication_tools_tpu.obs.schema import validate_run
from scdna_replication_tools_tpu.utils import faults as faults_mod

from conftest import dense_inputs_from_frames as _dense_inputs  # noqa: E402


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    """No fault plan may leak across tests (the runner installs them
    process-globally by design)."""
    yield
    faults_mod.install(None)


# controller ON with a non-pinned budget so the chunked (durable) fit
# path runs; rel_tol=0 keeps budgets deterministic; extensions bounded
# so the suite stays fast
BASE = dict(cn_prior_method="g1_clones", rel_tol=0.0, run_step3=False,
            max_iter=100, min_iter=25, max_iter_step1=40,
            min_iter_step1=20, fit_diag_every=25,
            controller_max_extra_iters=50, telemetry_path=None)


@pytest.fixture(scope="module")
def golden(synthetic_frames):
    """The uninterrupted reference run every chaos case compares to."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    inf = PertInference(s, g1, PertConfig(**BASE), clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    step1, step2, _ = inf.run()
    return inf, step1, step2


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------


def test_fault_spec_parsing_and_determinism():
    plan = faults_mod.FaultPlan.from_spec(
        "preempt@step2/chunk#3,nan@step2/chunk#5,hang@compile#2:0.01,"
        "oom@pkg/decode#1-2,corrupt@step2/save#*")
    # hit counting is per-site, 1-based, deterministic
    assert plan.check("step2/chunk") is None           # hit 1
    assert plan.check("step2/chunk") is None           # hit 2
    assert plan.check("step2/chunk").kind == "preempt"  # hit 3
    assert plan.check("step2/chunk") is None           # hit 4
    assert plan.check("step2/chunk").kind == "nan"     # hit 5
    assert plan.check("compile") is None
    assert plan.check("compile").kind == "hang"
    assert plan.check("pkg/decode").kind == "oom"      # range 1-2
    assert plan.check("pkg/decode").kind == "oom"
    assert plan.check("pkg/decode") is None
    for _ in range(5):
        assert plan.check("step2/save").kind == "corrupt"   # '*'
    assert len(plan.fired) == 10


def test_fault_spec_rejects_garbage():
    with pytest.raises(ValueError):
        faults_mod.FaultPlan.from_spec("explode@somewhere")
    with pytest.raises(ValueError):
        faults_mod.FaultPlan.from_spec("preempt-no-site")


def test_point_is_inert_without_a_plan():
    faults_mod.install(None)
    assert faults_mod.point("anything") is None


def test_resolve_plan_env_fallback(monkeypatch):
    monkeypatch.setenv(faults_mod.ENV_VAR, "preempt@x")
    plan = faults_mod.resolve_plan(None)
    assert plan is not None and plan.rules[0].site == "x"
    assert faults_mod.resolve_plan("off") is None
    monkeypatch.delenv(faults_mod.ENV_VAR)
    assert faults_mod.resolve_plan(None) is None


# ---------------------------------------------------------------------------
# exception taxonomy + retry + watchdog
# ---------------------------------------------------------------------------


def test_classify_exception_taxonomy():
    cls = faults_mod.classify_exception
    assert cls(faults_mod.SimulatedPreemption("s", 1)) == "preemption"
    assert cls(KeyboardInterrupt()) == "preemption"
    assert cls(faults_mod.WatchdogTimeout("fit", 1.0)) == "hang"
    assert cls(faults_mod.SimulatedResourceExhausted("s", 1)) == "oom"
    assert cls(RuntimeError("RESOURCE_EXHAUSTED: out of memory "
                            "allocating 2.8G")) == "oom"
    assert cls(MemoryError()) == "oom"
    assert cls(RuntimeError("UNAVAILABLE: connection to TPU worker "
                            "lost")) == "transient"
    assert cls(ConnectionResetError("peer")) == "transient"
    assert cls(TimeoutError()) == "transient"
    # a dying TPU worker's status in its surviving peers is hostloss,
    # not transient: retrying on the same mesh cannot succeed — the
    # elastic rung rebuilds a smaller one instead
    assert cls(RuntimeError("DATA_LOSS: checkpoint shard gone")) \
        == "hostloss"
    assert cls(RuntimeError("device lost: the system has halted")) \
        == "hostloss"
    # the default is deterministic: retrying unknown errors hides bugs
    assert cls(ValueError("bad shape")) == "deterministic"
    assert cls(RuntimeError("some internal invariant")) == "deterministic"


def test_retry_call_retries_transient_with_backoff():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TimeoutError("transient blip")
        return "ok"

    out = faults_mod.retry_call(flaky, label="t", max_attempts=3,
                                base_delay=0.25, sleep=sleeps.append)
    assert out == "ok" and calls["n"] == 3
    assert sleeps == [0.25, 0.5]   # deterministic exponential ladder


def test_retry_call_never_retries_deterministic_errors():
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise ValueError("a real bug")

    with pytest.raises(ValueError):
        faults_mod.retry_call(broken, label="t", max_attempts=5,
                              sleep=lambda _: None)
    assert calls["n"] == 1


def test_retry_call_bounded():
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise TimeoutError("forever")

    with pytest.raises(TimeoutError):
        faults_mod.retry_call(always, label="t", max_attempts=2,
                              sleep=lambda _: None)
    assert calls["n"] == 3   # 1 call + 2 retries


def test_run_with_deadline():
    import time as _time

    assert faults_mod.run_with_deadline(lambda: 42, None, "x") == 42
    assert faults_mod.run_with_deadline(lambda: 42, 5.0, "x") == 42
    with pytest.raises(faults_mod.WatchdogTimeout, match="hung"):
        faults_mod.run_with_deadline(lambda: _time.sleep(2.0), 0.05, "x")

    def boom():
        raise ValueError("inner")

    with pytest.raises(ValueError, match="inner"):
        faults_mod.run_with_deadline(boom, 5.0, "x")


# ---------------------------------------------------------------------------
# checkpoint integrity
# ---------------------------------------------------------------------------


def _save_dummy(tmp_path, tag="step2", value=1.0):
    params = {"tau_raw": np.full(8, value, np.float32)}
    return ckpt.save_step(str(tmp_path), tag, params,
                          np.array([3.0, 2.0, float(value)], np.float32))


def test_checkpoint_footer_roundtrip(tmp_path):
    _save_dummy(tmp_path)
    params, losses, extra = ckpt.load_step(str(tmp_path), "step2")
    assert float(params["tau_raw"][0]) == 1.0
    assert int(extra["meta.format_version"]) >= 3


def test_truncated_checkpoint_raises_typed_error(tmp_path):
    path = _save_dummy(tmp_path)
    with open(path, "r+b") as fh:
        fh.truncate(os.path.getsize(path) // 2)
    with pytest.raises(ckpt.CheckpointCorrupt) as exc_info:
        ckpt.load_step(str(tmp_path), "step2")
    assert path in str(exc_info.value)


def test_bitflip_checkpoint_raises_typed_error(tmp_path):
    path = _save_dummy(tmp_path)
    blob = bytearray(pathlib.Path(path).read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    pathlib.Path(path).write_bytes(bytes(blob))
    with pytest.raises(ckpt.CheckpointCorrupt, match="sha256|truncated"):
        ckpt.load_step(str(tmp_path), "step2")


def test_corrupt_checkpoint_falls_back_to_retained_previous(tmp_path):
    _save_dummy(tmp_path, value=1.0)   # becomes .prev on the next save
    path = _save_dummy(tmp_path, value=2.0)
    faults_mod.corrupt_file(path)
    params, _, _ = ckpt.load_step(str(tmp_path), "step2")
    assert float(params["tau_raw"][0]) == 1.0   # the retained previous


def test_missing_canonical_falls_back_to_retained_previous(tmp_path):
    """Crash between rotate and commit: the canonical file is gone but
    the retained predecessor must be restored, not ignored."""
    _save_dummy(tmp_path, value=1.0)
    path = _save_dummy(tmp_path, value=2.0)
    os.unlink(path)   # the new file never committed
    params, _, _ = ckpt.load_step(str(tmp_path), "step2")
    assert float(params["tau_raw"][0]) == 1.0


def test_footerless_legacy_checkpoint_still_loads(tmp_path):
    path = _save_dummy(tmp_path)
    blob = pathlib.Path(path).read_bytes()
    pathlib.Path(path).write_bytes(blob[:-48])   # strip the footer
    params, _, _ = ckpt.load_step(str(tmp_path), "step2")
    assert float(params["tau_raw"][0]) == 1.0


def test_single_process_emergency_save_is_a_normal_atomic_save(tmp_path):
    """coordinate=False (the dying-process emergency path) only changes
    MULTI-process behaviour (shard file, no commit — see
    tests/test_topology_resume.py); single-process it must stay the
    same atomic, footered, immediately-loadable file as ever."""
    params = {"tau_raw": np.full(8, 7.0, np.float32)}
    path = ckpt.save_step(str(tmp_path), "step2", params,
                          np.array([3.0], np.float32), coordinate=False)
    assert os.path.basename(path) == "pert_step2.npz"
    loaded, _, extra = ckpt.load_step(str(tmp_path), "step2")
    assert float(loaded["tau_raw"][0]) == 7.0
    assert int(extra["meta.format_version"]) >= 4
    # the topology stamp rides every save, emergency or not
    assert isinstance(extra.get("meta.topology"), dict)


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def test_manifest_fingerprint_and_atomic_roundtrip(tmp_path):
    a = np.arange(100, dtype=np.float32).reshape(10, 10)
    fp = manifest_mod.data_fingerprint(a)
    assert fp == manifest_mod.data_fingerprint(a.copy())
    b = a.copy()
    b[3, 3] += 1.0
    assert fp != manifest_mod.data_fingerprint(b)
    assert fp != manifest_mod.data_fingerprint(a.astype(np.float64))

    m = manifest_mod.RunManifest(tmp_path)
    m.begin_run("cfg123", fp, run_log_path="run.jsonl")
    m.update_step("step1", "complete", num_iters=40)
    m2 = manifest_mod.RunManifest.load(tmp_path)
    ok, reason = m2.match("cfg123", fp)
    assert ok and "verified" in reason
    assert m2.step("step1")["status"] == "complete"
    # data mismatch blocks; config mismatch only annotates
    ok, reason = m2.match("cfg123", "deadbeef")
    assert not ok and "mismatch" in reason
    ok, reason = m2.match("other-config", fp)
    assert ok and "config hash differs" in reason


def test_manifest_corrupt_file_degrades_to_empty(tmp_path):
    (tmp_path / manifest_mod.MANIFEST_NAME).write_text("{not json")
    m = manifest_mod.RunManifest.load(tmp_path)
    assert m.match("x", "y")[0] is False


# ---------------------------------------------------------------------------
# chaos: kill-and-resume parity
# ---------------------------------------------------------------------------

FAST_KILL_SITES = ["step2/chunk#3", "step2/start"]
SLOW_KILL_SITES = ["step1/start", "step1/chunk#2", "step2/end"]


def _run_pipeline(synthetic_frames, config):
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    return inf, inf.run()


@pytest.mark.parametrize(
    "site",
    FAST_KILL_SITES + [pytest.param(s, marks=pytest.mark.slow)
                       for s in SLOW_KILL_SITES])
def test_kill_and_resume_parity(site, golden, synthetic_frames, tmp_path):
    """Preempt at a phase boundary or mid-fit chunk, rerun with
    resume='auto': the final trajectory and params must be bit-exact
    against the uninterrupted golden run, and both RunLogs must
    validate against schema v4."""
    _, g_step1, g_step2 = golden
    durable = dict(checkpoint_dir=str(tmp_path / "ck"),
                   checkpoint_every=2)

    cfg_kill = PertConfig(**{**BASE, **durable,
                             "faults": f"preempt@{site}",
                             "telemetry_path":
                                 str(tmp_path / "killed.jsonl")})
    with pytest.raises(faults_mod.SimulatedPreemption):
        _run_pipeline(synthetic_frames, cfg_kill)
    # what the kill left behind decides whether the rerun can resume
    # at all (a preempt before the first checkpoint leaves nothing)
    had_durable = bool(list((tmp_path / "ck").glob("pert_*.npz")))

    cfg_resume = PertConfig(**{**BASE, **durable,
                               "telemetry_path":
                                   str(tmp_path / "resumed.jsonl")})
    _, (r1, r2, _) = _run_pipeline(synthetic_frames, cfg_resume)

    np.testing.assert_array_equal(r2.fit.losses, g_step2.fit.losses)
    np.testing.assert_array_equal(
        np.asarray(r2.fit.params["tau_raw"]),
        np.asarray(g_step2.fit.params["tau_raw"]))
    np.testing.assert_array_equal(r1.fit.losses, g_step1.fit.losses)
    # the resumed fit re-makes exactly the decisions the golden run
    # made AFTER the resume point (a suffix of the golden trail; the
    # pre-kill prefix lives in the killed run's own log)
    g_trail = [(d["action"], d["iter"]) for d in g_step2.fit.decisions]
    r_trail = [(d["action"], d["iter"]) for d in r2.fit.decisions]
    assert r_trail == g_trail[len(g_trail) - len(r_trail):]

    # both artifacts validate against schema v4, and the resumed log
    # carries the resume trail
    for name in ("killed.jsonl", "resumed.jsonl"):
        path = tmp_path / name
        if path.exists():
            assert validate_run(path) == [], name
    resumed_events = [json.loads(line) for line in
                      (tmp_path / "resumed.jsonl").read_text()
                      .splitlines()]
    # the resume trail appears whenever the kill left anything durable
    # behind (a kill before the first checkpoint is a genuinely fresh
    # rerun — e.g. preempt@step1/start)
    if had_durable:
        assert any(ev["event"] == "resume" for ev in resumed_events)
    killed_events = [json.loads(line) for line in
                     (tmp_path / "killed.jsonl").read_text().splitlines()]
    assert any(ev["event"] == "fault_injected" for ev in killed_events)
    assert killed_events[-1]["event"] == "run_end" \
        and killed_events[-1]["status"] == "error"


def test_injected_transient_failure_retries_and_resumes(golden,
                                                        synthetic_frames,
                                                        tmp_path):
    """A transient fault mid-fit must be retried (bounded backoff) and
    the retry must RESUME from the emergency checkpoint — landing on
    the golden trajectory, with the retry audited in the run log."""
    _, _, g_step2 = golden
    cfg = PertConfig(**{**BASE, "checkpoint_dir": str(tmp_path / "ck"),
                        "checkpoint_every": 2,
                        "retry_backoff_seconds": 0.01,
                        "faults": "transient@step2/chunk#3",
                        "telemetry_path": str(tmp_path / "t.jsonl")})
    _, (_, r2, _) = _run_pipeline(synthetic_frames, cfg)
    np.testing.assert_array_equal(r2.fit.losses, g_step2.fit.losses)
    np.testing.assert_array_equal(
        np.asarray(r2.fit.params["tau_raw"]),
        np.asarray(g_step2.fit.params["tau_raw"]))
    events = [json.loads(line) for line in
              (tmp_path / "t.jsonl").read_text().splitlines()]
    assert any(ev["event"] == "retry" and ev["label"] == "step2/fit"
               for ev in events)
    assert any(ev["event"] == "resume" and ev["action"] == "resumed"
               for ev in events)
    assert validate_run(tmp_path / "t.jsonl") == []


def test_injected_nan_drives_real_escalation_machinery(synthetic_frames,
                                                       tmp_path):
    """A nan fault poisons one chunk: the controller must escalate
    through the diagnosable checkpoint + reduced-LR retry and finish."""
    cfg = PertConfig(checkpoint_dir=str(tmp_path), checkpoint_every=0,
                     faults="nan@step2/chunk#2", **BASE)
    _, (s1, s2, _) = _run_pipeline(synthetic_frames, cfg)
    actions = [d["action"] for d in s2.fit.decisions]
    assert "escalate" in actions
    esc = next(d for d in s2.fit.decisions if d["action"] == "escalate")
    assert esc["outcome"] == "retry"
    assert not s2.fit.nan_abort          # the retry recovered
    assert (tmp_path / "pert_step2_nan.npz").exists()


def test_corrupted_saves_degrade_to_refit(golden, synthetic_frames,
                                          tmp_path):
    """Every step2 checkpoint write corrupted: the resume run must
    detect it (typed, audited) and refit from scratch — landing on the
    golden trajectory, not crashing on an unpickling error."""
    _, _, g_step2 = golden
    cfg_a = PertConfig(checkpoint_dir=str(tmp_path),
                       faults="corrupt@step2/save#*", **BASE)
    _run_pipeline(synthetic_frames, cfg_a)
    cfg_b = PertConfig(**{**BASE, "checkpoint_dir": str(tmp_path),
                          "telemetry_path": str(tmp_path / "r.jsonl")})
    _, (_, r2, _) = _run_pipeline(synthetic_frames, cfg_b)
    np.testing.assert_array_equal(r2.fit.losses, g_step2.fit.losses)
    events = [json.loads(line) for line in
              (tmp_path / "r.jsonl").read_text().splitlines()]
    assert any(ev["event"] == "degrade"
               and ev["action"] == "checkpoint_discarded"
               for ev in events)


def test_fingerprint_mismatch_blocks_resume(synthetic_frames, tmp_path):
    """Checkpoints fitted to OTHER data must not be restored under
    resume='auto' — that would be silent corruption, not a resume."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    cfg = PertConfig(checkpoint_dir=str(tmp_path), **BASE)
    inf = PertInference(s, g1, cfg, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    inf.run()

    s2, g12, clone_idx2 = _dense_inputs(synthetic_frames)
    s2.reads[0, :] += 7.0   # different data, same shapes
    inf2 = PertInference(s2, g12, cfg, clone_idx_s=clone_idx2,
                         clone_idx_g1=clone_idx2, num_clones=2)
    assert not inf2._resume_ok
    step1, step2, _ = inf2.run()
    assert step1.wall_time > 0 and step2.wall_time > 0   # refit, not
    # restored


def test_retry_can_resume_checkpoints_written_this_run(synthetic_frames,
                                                       tmp_path):
    """Fresh checkpoint dir: the directory identity is unverifiable at
    construction (_resume_ok False), but a transient retry inside the
    SAME run must still resume the checkpoints this run wrote — they
    carry the current identity by construction."""
    from scdna_replication_tools_tpu.infer.runner import StepOutput

    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    cfg = PertConfig(checkpoint_dir=str(tmp_path), **BASE)
    inf = PertInference(s, g1, cfg, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    assert not inf._resume_ok   # fresh dir, nothing to verify
    step1, _, _ = inf.run()
    assert "step1" in inf._steps_written
    loaded = inf._load_resumable("step1", step1.fit.budget, step1.spec,
                                 step1.fixed, step1.batch)
    assert isinstance(loaded, StepOutput)   # the retry path restores


def test_resume_with_grown_budget_continues_the_fit(synthetic_frames,
                                                    tmp_path):
    """The documented budget-growth workflow: a fit that exhausted a
    small budget un-converged must RESUME and run the extra iterations
    under a larger max_iter — not restore as complete because the saved
    controller budget was smaller."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    base = {**BASE, "controller_max_extra_iters": 0,
            "controller_stop_patience": 0}
    cfg_small = PertConfig(checkpoint_dir=str(tmp_path),
                           **{**base, "max_iter": 75})
    inf_a = PertInference(s, g1, cfg_small, clone_idx_s=clone_idx,
                          clone_idx_g1=clone_idx, num_clones=2)
    _, a2, _ = inf_a.run()
    assert a2.fit.num_iters == 75 and not a2.fit.converged

    cfg_big = PertConfig(checkpoint_dir=str(tmp_path),
                         **{**base, "max_iter": 125})
    inf_b = PertInference(s, g1, cfg_big, clone_idx_s=clone_idx,
                          clone_idx_g1=clone_idx, num_clones=2)
    _, b2, _ = inf_b.run()
    assert b2.fit.num_iters == 125   # resumed AND ran the growth
    np.testing.assert_array_equal(b2.fit.losses[:75], a2.fit.losses)


def test_invalid_resume_value_rejected_before_manifest_mutation(
        synthetic_frames, tmp_path):
    """A typo'd resume value must raise BEFORE the manifest is touched
    — a config error cannot cost durable resume state."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    cfg = PertConfig(checkpoint_dir=str(tmp_path), **BASE)
    inf = PertInference(s, g1, cfg, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    inf.run()
    manifest_before = (tmp_path / "manifest.json").read_text()
    with pytest.raises(ValueError, match="resume"):
        PertInference(s, g1,
                      PertConfig(checkpoint_dir=str(tmp_path),
                                 resume="no", **BASE),
                      clone_idx_s=clone_idx, clone_idx_g1=clone_idx,
                      num_clones=2)
    assert (tmp_path / "manifest.json").read_text() == manifest_before


def test_resume_off_refits(synthetic_frames, tmp_path):
    cfg = PertConfig(checkpoint_dir=str(tmp_path), **BASE)
    _run_pipeline(synthetic_frames, cfg)
    cfg_off = PertConfig(checkpoint_dir=str(tmp_path), resume="off",
                         **BASE)
    _, (r1, r2, _) = _run_pipeline(synthetic_frames, cfg_off)
    assert r1.wall_time > 0 and r2.wall_time > 0


def test_watchdog_converts_compile_hang_into_typed_abort(synthetic_frames,
                                                         tmp_path):
    """A hang injected inside the compile path + an armed compile
    deadline must abort with WatchdogTimeout (classified 'hang'), not
    sit forever — the rc=124 conversion."""
    from scdna_replication_tools_tpu.infer import svi

    svi.clear_program_cache()   # force a real compile resolution
    try:
        cfg = PertConfig(checkpoint_dir=str(tmp_path),
                         faults="hang@compile#1:1.5",
                         watchdog_compile_seconds=0.2, **BASE)
        with pytest.raises(faults_mod.WatchdogTimeout):
            _run_pipeline(synthetic_frames, cfg)
    finally:
        svi.clear_program_cache()


# ---------------------------------------------------------------------------
# degradation ladder
# ---------------------------------------------------------------------------


def test_decode_ladder_halves_slab_on_oom(golden):
    inf, _, step2 = golden
    faults_mod.install(faults_mod.FaultPlan.from_spec(
        "oom@pkg/decode#1"))
    decoded, ent, want = _decode_with_degradation(
        step2.spec, step2.fit.params, step2.fixed, step2.batch,
        inf._step2_data, None, True, "pkg")
    assert want is True and ent is not None
    assert len(decoded) == 3
    assert faults_mod.active().fired[0]["kind"] == "oom"


def test_decode_ladder_drops_qc_surfaces_when_halving_fails(golden):
    inf, _, step2 = golden
    faults_mod.install(faults_mod.FaultPlan.from_spec(
        "oom@pkg/decode#1-4"))
    decoded, ent, want = _decode_with_degradation(
        step2.spec, step2.fit.params, step2.fixed, step2.batch,
        inf._step2_data, None, True, "pkg")
    assert want is False and ent is None
    assert len(decoded) == 3


def test_decode_ladder_exhausted_reraises(golden):
    inf, _, step2 = golden
    faults_mod.install(faults_mod.FaultPlan.from_spec(
        "oom@pkg/decode#*"))
    with pytest.raises(faults_mod.SimulatedResourceExhausted):
        _decode_with_degradation(
            step2.spec, step2.fit.params, step2.fixed, step2.batch,
            inf._step2_data, None, True, "pkg")


def test_decode_ladder_propagates_deterministic_errors(golden):
    """Non-OOM errors must escape the ladder untouched from the first
    attempt — no silent slab-halving around real bugs."""
    inf, _, step2 = golden
    bad_params = dict(step2.fit.params)
    bad_params.pop("tau_raw")
    with pytest.raises(Exception) as exc_info:
        _decode_with_degradation(
            step2.spec, bad_params, step2.fixed, step2.batch,
            inf._step2_data, None, False, "pkg")
    assert faults_mod.classify_exception(exc_info.value) \
        == "deterministic"


def test_ppc_oom_degrades_to_nan_columns(golden):
    inf, _, step2 = golden
    frac_low = np.zeros(inf._step2_data.num_cells, np.float32)
    qc_stats = {
        "tau": np.full(step2.batch.reads.shape[0], 0.5, np.float32),
        "mean_cn_entropy": frac_low + 0.1,
        "max_cn_entropy": frac_low + 0.2,
        "frac_low_conf": frac_low,
        "mean_rep_entropy": frac_low + 0.1,
    }
    faults_mod.install(faults_mod.FaultPlan.from_spec("oom@qc/ppc#1"))
    df = inf.build_cell_qc(step2, inf._step2_data, qc_stats)
    assert df["ppc_z"].isna().all()
    assert not df["qc_flags"].str.contains("ppc_outlier").any()
    # the PPC drop must not poison the non_finite flag
    assert not df["qc_flags"].str.contains("non_finite").any()


# ---------------------------------------------------------------------------
# inertness + overhead guards
# ---------------------------------------------------------------------------

_V4_KINDS = {"fault_injected", "retry", "degrade", "resume"}


def test_disabled_harness_is_inert(synthetic_frames, tmp_path):
    """faults=None + no checkpoint_dir: the run log must carry ZERO
    durability events — the whole layer reduces to inert checks."""
    cfg = PertConfig(**{**BASE,
                        "telemetry_path": str(tmp_path / "clean.jsonl")})
    _run_pipeline(synthetic_frames, cfg)
    assert validate_run(tmp_path / "clean.jsonl") == []
    events = [json.loads(line) for line in
              (tmp_path / "clean.jsonl").read_text().splitlines()]
    assert not [ev for ev in events if ev["event"] in _V4_KINDS]
    from scdna_replication_tools_tpu.obs import SCHEMA_VERSION
    assert events[0]["schema_version"] == SCHEMA_VERSION >= 4


def test_periodic_checkpoint_overhead_is_bounded(synthetic_frames,
                                                 tmp_path):
    """Coarse tier-1 guard at the smoke shape: periodic checkpointing
    (every 2 chunks) must not blow up the step-2 fit wall.  The bound
    is deliberately loose — at this tiny shape the fixed npz-write cost
    is a far larger fraction of the fit than at the flagship shape
    PERF_NOTES pins (<2%); this guard catches pathological regressions
    (a sync or save per iteration), not basis points."""
    import time

    s, g1, clone_idx = _dense_inputs(synthetic_frames)

    def fit_wall(**extra):
        cfg = PertConfig(**{**BASE, **extra})
        inf = PertInference(s, g1, cfg, clone_idx_s=clone_idx,
                            clone_idx_g1=clone_idx, num_clones=2)
        t0 = time.perf_counter()
        inf.run()
        return time.perf_counter() - t0

    fit_wall()   # warm the compile caches for both arms
    walls_off = []
    walls_on = []
    for trial in range(3):   # interleaved: drift-robust (PERF_NOTES)
        walls_off.append(fit_wall())
        walls_on.append(fit_wall(
            checkpoint_dir=str(tmp_path / f"ck{trial}"),
            checkpoint_every=2))
    assert np.median(walls_on) < np.median(walls_off) * 2.0 + 0.5
