"""rt_prior -> rho conditioning (PertConfig.rho_from_rt_prior).

The reference loads and rescales the RT-prior column
(reference: pert_model.py:182-187, 254-257) and defines a conditioning
branch in the model (rho0, reference: pert_model.py:568-570), but never
connects the two — run_pert_model never passes rho0.  Our opt-in flag
wires that capability: step 2 fixes rho to the rescaled prior instead of
learning it.  Default-off preserves reference behaviour.
"""

import numpy as np
import pytest

from conftest import dense_inputs_from_frames
from scdna_replication_tools_tpu.config import PertConfig
from scdna_replication_tools_tpu.infer.runner import PertInference
from scdna_replication_tools_tpu.models.pert import constrained


def _dense_inputs(synthetic_frames, rt_prior_col):
    return dense_inputs_from_frames(synthetic_frames,
                                    rt_prior_col=rt_prior_col)


def _run_step2(s, g1, clone_idx, **cfg_kwargs):
    cfg = PertConfig(max_iter=10, min_iter=2, max_iter_step1=6,
                     min_iter_step1=2, run_step3=False,
                     cn_prior_method="hmmcopy", enum_impl="xla",
                     **cfg_kwargs)
    inf = PertInference(s, g1, cfg, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    step1 = inf.run_step1()
    etas = inf.build_etas()
    return inf.run_step2(step1, etas)


def test_rho_conditioned_on_rt_prior(synthetic_frames):
    s, g1, clone_idx = _dense_inputs(synthetic_frames, "mcf7rt")
    assert s.rt_prior is not None
    step2 = _run_step2(s, g1, clone_idx, rho_from_rt_prior=True)

    # rho is fixed to the loader's rescaled prior, not learned
    assert "rho_raw" not in step2.fit.params
    c2 = constrained(step2.spec, step2.fit.params, step2.fixed)
    np.testing.assert_allclose(np.asarray(c2["rho"]), s.rt_prior, atol=1e-6)
    assert step2.fit.num_iters > 0
    assert np.isfinite(step2.fit.losses).all()


def test_rho_learned_by_default(synthetic_frames):
    s, g1, clone_idx = _dense_inputs(synthetic_frames, "mcf7rt")
    step2 = _run_step2(s, g1, clone_idx)
    assert "rho_raw" in step2.fit.params
    c2 = constrained(step2.spec, step2.fit.params, step2.fixed)
    # the learned profile moves away from the prior (it is not conditioned)
    assert not np.allclose(np.asarray(c2["rho"]), s.rt_prior, atol=1e-6)


def test_missing_rt_prior_raises(synthetic_frames):
    s, g1, clone_idx = _dense_inputs(synthetic_frames, None)
    assert s.rt_prior is None
    with pytest.raises(ValueError, match="rho_from_rt_prior"):
        _run_step2(s, g1, clone_idx, rho_from_rt_prior=True)
