"""Model-core tests: loss consistency, chunking, decode, SVI driver."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from scdna_replication_tools_tpu.infer.svi import fit_map
from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    decode_discrete,
    init_params,
    log_joint,
    pert_loss,
)
from scdna_replication_tools_tpu.ops.gc import gc_features


def _toy_batch(rng, num_cells=8, num_loci=30, P=5, step1=False):
    reads = rng.poisson(40, (num_cells, num_loci)).astype(np.float32)
    libs = np.zeros(num_cells, np.int32)
    gammas = rng.uniform(0.35, 0.6, num_loci).astype(np.float32)
    if step1:
        etas = None
    else:
        # concentrate the CN prior at state 2 (degenerate all-ones etas
        # make the ploidy guess 0, which NaNs the u prior — same as the
        # reference's argmax(etas) branch, pert_model.py:592-593)
        etas = np.ones((num_cells, num_loci, P), np.float32)
        etas[:, :, 2] = 100.0
    cn_obs = rep_obs = None
    if step1:
        cn_obs = np.full((num_cells, num_loci), 2.0, np.float32)
        rep_obs = np.zeros((num_cells, num_loci), np.float32)
    return PertBatch(
        reads=jnp.asarray(reads),
        libs=jnp.asarray(libs),
        gamma_feats=gc_features(jnp.asarray(gammas), 2),
        mask=jnp.ones((num_cells,), jnp.float32),
        etas=None if etas is None else jnp.asarray(etas),
        cn_obs=None if cn_obs is None else jnp.asarray(cn_obs),
        rep_obs=None if rep_obs is None else jnp.asarray(rep_obs),
    )


def test_loss_finite_enumerated():
    rng = np.random.default_rng(0)
    spec = PertModelSpec(P=5, K=2, L=1, tau_mode="param")
    batch = _toy_batch(rng, P=5)
    params = init_params(spec, batch, {}, t_init=np.full(8, 0.4, np.float32))
    loss = pert_loss(spec, params, {}, batch)
    assert np.isfinite(float(loss))


def test_loss_finite_step1():
    rng = np.random.default_rng(1)
    spec = PertModelSpec(P=5, K=2, L=1, tau_mode="beta_default", step1=True)
    batch = _toy_batch(rng, P=5, step1=True)
    params = init_params(spec, batch, {})
    loss = pert_loss(spec, params, {}, batch)
    assert np.isfinite(float(loss))


def test_chunked_loss_matches_full():
    rng = np.random.default_rng(2)
    batch = _toy_batch(rng, P=5)
    t_init = np.full(8, 0.4, np.float32)
    spec_full = PertModelSpec(P=5, K=2, L=1, tau_mode="param")
    spec_chunk = PertModelSpec(P=5, K=2, L=1, tau_mode="param", cell_chunk=4)
    params = init_params(spec_full, batch, {}, t_init=t_init)
    l_full = float(pert_loss(spec_full, params, {}, batch))
    l_chunk = float(pert_loss(spec_chunk, params, {}, batch))
    assert np.isclose(l_full, l_chunk, rtol=1e-5)


def test_mask_excludes_padded_cells():
    rng = np.random.default_rng(3)
    spec = PertModelSpec(P=5, K=2, L=1, tau_mode="param")
    batch = _toy_batch(rng, num_cells=8, P=5)
    params = init_params(spec, batch, {}, t_init=np.full(8, 0.4, np.float32))
    l_all = float(log_joint(spec, params, {}, batch))

    # zero out the last 4 cells via the mask: the per-cell contribution of
    # the survivors must be what a 4-cell batch would produce
    mask_half = jnp.asarray([1, 1, 1, 1, 0, 0, 0, 0], jnp.float32)
    batch_half = PertBatch(batch.reads, batch.libs, batch.gamma_feats,
                           mask_half, batch.etas)
    l_half = float(log_joint(spec, params, {}, batch_half))
    assert l_half != l_all
    assert np.isfinite(l_half)


def test_decode_shapes_and_determinism():
    rng = np.random.default_rng(4)
    spec = PertModelSpec(P=5, K=2, L=1, tau_mode="param")
    batch = _toy_batch(rng, P=5)
    params = init_params(spec, batch, {}, t_init=np.full(8, 0.4, np.float32))
    cn, rep, p_rep = decode_discrete(spec, params, {}, batch)
    assert cn.shape == (8, 30) and rep.shape == (8, 30)
    assert int(jnp.max(cn)) < 5
    assert set(np.unique(np.asarray(rep))) <= {0, 1}
    assert np.all((np.asarray(p_rep) >= 0) & (np.asarray(p_rep) <= 1))


def test_decode_cell_slabs_are_exact():
    """The slabbed decode (OOM guard for genome-scale packaging) must be
    bit-identical to the single-pass decode — every term is per-cell
    independent.  Exercises a slab size that does not divide the cell
    count (8 cells, slabs of 3)."""
    rng = np.random.default_rng(4)
    spec = PertModelSpec(P=5, K=2, L=1, tau_mode="param")
    batch = _toy_batch(rng, P=5)
    params = init_params(spec, batch, {}, t_init=np.full(8, 0.4, np.float32))
    whole = decode_discrete(spec, params, {}, batch)
    slabbed = decode_discrete(spec, params, {}, batch, cell_chunk=3)
    for a, b in zip(whole, slabbed):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fit_map_reduces_loss_and_early_stops():
    rng = np.random.default_rng(5)
    spec = PertModelSpec(P=5, K=2, L=1, tau_mode="param")
    batch = _toy_batch(rng, P=5)
    params0 = init_params(spec, batch, {}, t_init=np.full(8, 0.4, np.float32))

    def loss_fn(params, batch):
        return pert_loss(spec, params, {}, batch)

    fit = fit_map(loss_fn, params0, (batch,), max_iter=400, min_iter=30,
                  rel_tol=1e-4)
    assert fit.losses[-1] < fit.losses[0]
    assert not fit.nan_abort
    # plateau tolerance loose enough that it should stop before max_iter
    assert fit.num_iters <= 400
