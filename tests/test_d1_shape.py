"""Full-pipeline run at the reference's canonical D1.0 workload shape.

The reference's only real-data test constructs (but never infers on) an
scRT object over D1.0: 400 S + 400 G1 cells x 271 loci x 3 chromosomes
(reference: test_with_pytest.py:94-98; the data files themselves are
absent from the snapshot, .MISSING_LARGE_BLOBS:1-4).  This module runs
the COMPLETE pipeline — simulator -> scRT.infer('pert') -> phase
prediction — at that shape (3 chromosomes, 280 loci, 56+56 cells; cell
count reduced from 400/phase to keep CPU CI in minutes while preserving
the multi-chromosome, >=271-loci geometry) and asserts quantitative
recovery including the per-clone tau correlation the smaller
single-chromosome suite cannot measure representatively.
"""

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.api import scRT
from scdna_replication_tools_tpu.models.simulator import pert_simulator

CHROMS = {"1": 120, "2": 96, "3": 64}          # 280 loci over 3 chromosomes
N_PER_CLONE = 14                               # x 2 clones x 2 phases = 56+56


@pytest.fixture(scope="module")
def d1_frames():
    """Synthetic frames at the D1.0 geometry (multi-chromosome CNAs,
    2 clones, distinct per-clone RT profiles)."""
    rng = np.random.default_rng(42)
    frames_meta = []
    offset = 0.0
    for chrom, n in CHROMS.items():
        starts = (np.arange(n) * 500_000).astype(np.int64)
        gc = np.clip(0.45 + 0.08 * np.sin(np.arange(n) / 9.0 + offset)
                     + rng.normal(0, 0.02, n), 0.3, 0.65)
        rt = 0.5 + 0.45 * np.sin(np.arange(n) / 15.0 + 1.0 + offset)
        rt_b = 0.5 + 0.45 * np.sin(np.arange(n) / 15.0 + 2.2 + offset)
        frames_meta.append(pd.DataFrame({
            "chr": chrom, "start": starts, "end": starts + 500_000,
            "gc": gc, "mcf7rt": rt, "rt_A": rt, "rt_B": rt_b}))
        offset += 1.7
    meta = pd.concat(frames_meta, ignore_index=True)
    num_loci = len(meta)
    assert num_loci == 280

    # clone CN profiles with CNAs on different chromosomes (D1.0 is
    # near-diploid with clone-distinguishing segments)
    cn_a = np.full(num_loci, 2.0)
    cn_a[40:90] = 3.0          # chr1 gain
    cn_a[200:230] = 1.0        # chr2/3 loss
    cn_b = np.full(num_loci, 2.0)
    cn_b[130:170] = 4.0        # chr2 amplification

    def make_cells(prefix, clone, cn_profile):
        out = []
        for i in range(N_PER_CLONE):
            df = meta.copy()
            df["cell_id"] = f"{prefix}_{clone}_{i}"
            df["library_id"] = "LIB0"
            df["clone_id"] = clone
            df["true_somatic_cn"] = cn_profile
            out.append(df)
        return out

    df_s = pd.concat(make_cells("s", "A", cn_a) + make_cells("s", "B", cn_b),
                     ignore_index=True)
    df_g = pd.concat(make_cells("g", "A", cn_a) + make_cells("g", "B", cn_b),
                     ignore_index=True)
    return df_s, df_g


@pytest.fixture(scope="module")
def d1_output(d1_frames):
    df_s, df_g = d1_frames
    sim_s, sim_g = pert_simulator(
        df_s, df_g, num_reads=100_000, rt_cols=["rt_A", "rt_B"],
        clones=["A", "B"], lamb=0.75, betas=[0.5, 0.0], a=10.0, seed=5)
    for df in (sim_s, sim_g):
        df["reads"] = df["true_reads_norm"]
        df["state"] = df["true_somatic_cn"].astype(int)
        df["copy"] = df["true_somatic_cn"].astype(float)
    scrt = scRT(sim_s.copy(), sim_g.copy(), input_col="reads",
                clone_col="clone_id", assign_col="copy",
                cn_prior_method="g1_clones", max_iter=400, min_iter=100,
                rt_prior_col=None, run_step3=True)
    out = scrt.infer(level="pert")
    return out, sim_s


@pytest.mark.slow
def test_d1_shape_geometry(d1_output):
    (cn_s_out, supp_s, cn_g1_out, _), _ = d1_output
    assert cn_s_out["chr"].nunique() == 3
    assert cn_s_out.groupby(["chr", "start"]).ngroups == 280
    assert cn_s_out["cell_id"].nunique() == 2 * N_PER_CLONE
    assert cn_g1_out["cell_id"].nunique() == 2 * N_PER_CLONE
    loss_s = supp_s.query("param == 'loss_s'")["value"].to_numpy()
    assert loss_s[-1] < loss_s[0]


# deliberately NOT @slow: the flagship-geometry recovery must run in the
# default gate (round-4 regression shipped because the only tests pinning
# it were deselected); the sibling tests reuse this module-scoped fixture,
# so -m slow adds no second fit
def test_d1_recovery(d1_output):
    (cn_s_out, *_), _ = d1_output
    rep_acc = (cn_s_out["model_rep_state"] == cn_s_out["true_rep"]).mean()
    cn_acc = (cn_s_out["model_cn_state"]
              == cn_s_out["true_somatic_cn"]).mean()
    assert rep_acc > 0.80, f"rep-state accuracy {rep_acc:.3f}"
    assert cn_acc > 0.90, f"CN accuracy {cn_acc:.3f}"


@pytest.mark.slow
def test_d1_per_clone_tau_correlation(d1_output):
    """tau must be recovered WITHIN each clone, not only pooled — a
    pooled correlation can ride clone-level offsets; the per-clone
    statistic is the one the VERDICT asked this fixture to pin."""
    (cn_s_out, *_), _ = d1_output
    per_cell = cn_s_out.groupby("cell_id").agg(
        tau=("model_tau", "first"), true_t=("true_t", "first"),
        clone=("clone_id", "first"))
    for clone, grp in per_cell.groupby("clone"):
        r = np.corrcoef(grp["tau"], grp["true_t"])[0, 1]
        assert r > 0.8, f"clone {clone} tau correlation {r:.3f}"


@pytest.mark.slow
def test_d1_phase_prediction(d1_output):
    """predict_cycle_phase over the combined S+G1 output labels most
    true-S cells S and most G1 cells G1/2 or LQ (reference:
    predict_cycle_phase.py:99-117)."""
    from scdna_replication_tools_tpu.pipeline.phase import (
        predict_cycle_phase,
    )
    (cn_s_out, _, cn_g1_out, _), _ = d1_output
    cn = pd.concat([cn_s_out, cn_g1_out], ignore_index=True)
    # rpm is a required input column (reference: predict_cycle_phase.py:54)
    cn["rpm"] = cn["reads"] / cn.groupby("cell_id")["reads"] \
        .transform("sum") * 1e6
    phased_s, phased_g, phased_lq = predict_cycle_phase(cn)
    phases = pd.concat([phased_s, phased_g, phased_lq],
                       ignore_index=True).groupby("cell_id")["PERT_phase"] \
        .first()
    s_cells = phases[phases.index.str.startswith("s_")]
    assert (s_cells == "S").mean() > 0.7, (s_cells.value_counts().to_dict())
