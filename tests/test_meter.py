"""The cost plane (obs/meter.py + tools/pert_meter.py): conservation,
waste attribution, tenant accounting.

The ledger's contract is a single invariant — every booked record,
every rollup slot and every cross-ledger merge satisfies
``billed == effective + sum(waste)`` — plus the attribution semantics
riding on it: padding waste from the bucket contract's ``pad_frac``,
``retry_refit`` from the per-step iteration high-water mark (a
fault-ladder re-entry re-fits iterations the trajectory already had),
``retired_lane`` from slab occupancy, ``queue_idle`` from serve claim
gaps, and the per-tenant rollup keyed on the worker's SANITIZED tenant
label (the spool is a filesystem drop-box; a forged ticket string is
never echoed raw).
"""

import json
import pathlib
import sys

import pytest

from scdna_replication_tools_tpu.obs import heartbeat as heartbeat_mod
from scdna_replication_tools_tpu.obs.meter import (
    WASTE_CATEGORIES,
    CostLedger,
    conservation_gap,
    ledger_of,
)
from scdna_replication_tools_tpu.obs.runlog import RunLog
from scdna_replication_tools_tpu.obs.schema import validate_run

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]
                       / "tools"))


def _assert_conserves(meter_dict):
    assert conservation_gap(meter_dict) < 1e-6, meter_dict


# ---------------------------------------------------------------------------
# the conservation invariant
# ---------------------------------------------------------------------------


def test_conservation_across_all_booking_kinds():
    """Every typed booking entry point produces conserving records, and
    the per-step / per-bucket / total rollups conserve too."""
    led = CostLedger(scope={"run": "t"}, devices=1)
    with led.context(step="step2", bucket="c32xl64", cells=24,
                     pad_frac=0.25):
        led.book_chunk(entry_it=0, end_it=100, wall_seconds=2.0)
        # a rewound re-fit of iterations 50..100: retry_refit waste
        led.book_chunk(entry_it=50, end_it=100, wall_seconds=0.5)
        led.book_compile(seconds=1.5)
        led.book_compile(seconds=0.3, deserialize=True)
        led.book_exec(kind="decode", seconds=0.8)
    with led.context(step="step3", bucket="c64xl64", cells=48):
        led.book_chunk(entry_it=0, end_it=50, wall_seconds=1.0)
    led.book_retired(seconds=2.0, device_share=0.25)
    led.book_queue_idle(seconds=0.7)

    summary = led.summary()
    _assert_conserves(summary)
    for slot in list(summary["by_step"].values()) \
            + list(summary["by_bucket"].values()):
        _assert_conserves(slot)
    # every waste category the taxonomy names actually landed
    assert set(summary["waste_seconds"]) == set(WASTE_CATEGORIES)
    # and the waste names stay inside the closed taxonomy
    assert all(k in WASTE_CATEGORIES for k in summary["waste_seconds"])
    # billed = 2.0 + 0.5 + 1.5 + 0.3 + 0.8 + 1.0 + 0.5 + 0.7
    assert summary["billed_device_seconds"] == pytest.approx(7.3)
    # goodput counts fit progress only: 24 * 100 + 48 * 50
    assert summary["cell_iters"] == pytest.approx(4800.0)


def test_overbooked_waste_is_clamped_to_billed():
    """Waste can never exceed billed (conservation by construction):
    an overbooked record scales its categories proportionally."""
    led = CostLedger(devices=1)
    rec = led.book(kind="x", wall_seconds=1.0,
                   waste={"compile": 3.0, "padding": 1.0})
    assert rec["billed_device_seconds"] == pytest.approx(1.0)
    assert sum(rec["waste"].values()) == pytest.approx(1.0)
    # proportions preserved: 3:1
    assert rec["waste"]["compile"] == pytest.approx(0.75)
    assert rec["effective_device_seconds"] == pytest.approx(0.0)
    _assert_conserves(led.totals())


def test_device_count_multiplies_billed_time():
    led = CostLedger(devices=4)
    rec = led.book(kind="x", wall_seconds=2.0)
    assert rec["billed_device_seconds"] == pytest.approx(8.0)
    _assert_conserves(led.totals())


# ---------------------------------------------------------------------------
# retry_refit: the fault-ladder re-entry accounting
# ---------------------------------------------------------------------------


def test_retry_refit_on_rewound_iterations():
    """A NaN rewind (or resume overlap) re-runs iterations below the
    step's high-water mark: they bill, but as retry_refit waste, and
    credit no fresh cell-iterations."""
    led = CostLedger(devices=1)
    with led.context(step="step2", cells=10):
        first = led.book_chunk(entry_it=0, end_it=100, wall_seconds=1.0)
        assert first["waste"] == {}
        assert first["cell_iters"] == pytest.approx(1000.0)
        # fault-ladder re-entry: rewound to iteration 40, re-fit to 100
        redo = led.book_chunk(entry_it=40, end_it=100, wall_seconds=0.6)
        assert redo["waste"]["retry_refit"] == pytest.approx(0.6)
        assert redo["cell_iters"] == 0.0
        # past the high-water mark again: fresh work, no refit waste
        cont = led.book_chunk(entry_it=100, end_it=150,
                              wall_seconds=0.5)
        assert cont["waste"] == {}
        assert cont["cell_iters"] == pytest.approx(500.0)
    step = led.summary()["by_step"]["step2"]
    _assert_conserves(step)
    assert step["waste_seconds"]["retry_refit"] == pytest.approx(0.6)


def test_retry_refit_composes_with_padding():
    """Padding takes its pad_frac share first; retry_refit decomposes
    the remaining (non-padding) time by the refitted iteration share —
    the two categories never double-bill the same device-second."""
    led = CostLedger(devices=1)
    with led.context(step="s", cells=8, pad_frac=0.5):
        led.book_chunk(entry_it=0, end_it=100, wall_seconds=1.0)
        redo = led.book_chunk(entry_it=50, end_it=100, wall_seconds=0.5)
    assert redo["waste"]["padding"] == pytest.approx(0.25)
    assert redo["waste"]["retry_refit"] == pytest.approx(0.25)
    assert redo["effective_device_seconds"] == pytest.approx(0.0)
    _assert_conserves(led.totals())


def test_iter_high_water_is_per_step():
    led = CostLedger(devices=1)
    with led.context(step="step2", cells=1):
        led.book_chunk(entry_it=0, end_it=100, wall_seconds=1.0)
    with led.context(step="step3", cells=1):
        # a different step starts its own high-water: no refit waste
        rec = led.book_chunk(entry_it=0, end_it=100, wall_seconds=1.0)
    assert rec["waste"] == {}


# ---------------------------------------------------------------------------
# slab occupancy: retired-lane waste
# ---------------------------------------------------------------------------


def test_slab_booking_matches_pinned_occupancy():
    """A W=4 rung carrying 3 live lanes: each lane bills wall/W into
    its own ledger, the parked (W-n)/W books as retired_lane on the
    worker ledger — total attributed time equals wall x devices, and
    retired time equals (1 - occupancy) x wall exactly."""
    from types import SimpleNamespace

    from scdna_replication_tools_tpu.serve.slab import (
        SlabFitCoordinator,
    )

    wall = 2.0
    lanes = [CostLedger(scope={"request": f"r{i}"}, devices=1)
             for i in range(3)]
    worker_led = CostLedger(scope={"worker": "w"}, devices=1)
    group = []
    for i, led in enumerate(lanes):
        ctx = {"step": "step2", "bucket": "c32xl64", "cells": 10,
               "pad_frac": 0.0}
        call = SimpleNamespace(meter=(led, ctx),
                               args=(None, None, None, None, 0))
        group.append(SimpleNamespace(call=call))
    outs = [(40,), (40,), (40,)]
    coord = SimpleNamespace(meter_ledger=worker_led)
    SlabFitCoordinator._book_slab(coord, group, outs, wall,
                                  {"flops": 400.0})

    per_lane = [led.totals() for led in lanes]
    for t in per_lane:
        _assert_conserves(t)
        assert t["billed_device_seconds"] == pytest.approx(wall / 4)
        assert t["flops"] == pytest.approx(100.0)
    retired = worker_led.totals()
    _assert_conserves(retired)
    occupancy = 3 / 4
    assert retired["waste_seconds"]["retired_lane"] == pytest.approx(
        (1 - occupancy) * wall)
    total_attributed = sum(t["billed_device_seconds"]
                           for t in per_lane) \
        + retired["billed_device_seconds"]
    assert total_attributed == pytest.approx(wall)
    # the vacancy is attributed to the rung for the by_bucket rollup
    assert "c32xl64" in worker_led.summary()["by_bucket"]


# ---------------------------------------------------------------------------
# live surfaces: heartbeat freshness, RunLog embedding
# ---------------------------------------------------------------------------


def test_heartbeat_goodput_tracks_bookings(tmp_path):
    """Every booking refreshes the live heartbeat's goodput/waste_frac
    fields — the pert-watch plane shows cost efficiency mid-fit, not
    only at run_end."""
    hb = heartbeat_mod.RunHeartbeat(tmp_path / "health",
                                    interval_seconds=0.05)
    heartbeat_mod.install(hb)
    try:
        led = CostLedger(devices=1)
        with led.context(step="s", cells=10):
            led.book_chunk(entry_it=0, end_it=100, wall_seconds=1.0)
        assert hb._fields["goodput"] == pytest.approx(1000.0)
        assert hb._fields["waste_frac"] == pytest.approx(0.0)
        led.book_compile(seconds=1.0)
        assert hb._fields["goodput"] == pytest.approx(500.0)
        assert hb._fields["waste_frac"] == pytest.approx(0.5)
    finally:
        heartbeat_mod.install(None)


def test_runlog_carries_meter_on_run_end(tmp_path):
    """The ledger rides the RunLog seam: ``run_log.meter_ledger`` is
    discoverable via ledger_of(), and close_run embeds the summary in
    run_end (schema v9) — which still validates."""
    path = tmp_path / "run.jsonl"
    log = RunLog(path)
    led = CostLedger(scope={"run": "t"}, devices=1)
    log.meter_ledger = led
    assert ledger_of(log) is led
    with log.session(config={}, run_name="meter_test"):
        with led.context(step="s", cells=5, pad_frac=0.2):
            led.book_chunk(entry_it=0, end_it=10, wall_seconds=1.0)
    events = [json.loads(line) for line in path.read_text().splitlines()]
    end = next(e for e in events if e["event"] == "run_end")
    meter = end["meter"]
    _assert_conserves(meter)
    assert meter["waste_seconds"]["padding"] == pytest.approx(0.2)
    assert meter["by_step"]["s"]["records"] == 1
    validate_run(path)


# ---------------------------------------------------------------------------
# tenant accounting: sanitization + the per-tenant rollup
# ---------------------------------------------------------------------------


def test_tenant_sanitization_pins():
    from scdna_replication_tools_tpu.serve.worker import ServeWorker

    clean = ServeWorker._sanitize_tenant
    assert clean(None) is None
    assert clean("") is None
    assert clean("team-a.prod_1") == "team-a.prod_1"
    # a forged path-traversal label is squashed, never echoed raw
    assert clean("../../etc/passwd") == ".._.._etc_passwd"
    assert clean("evil tenant\n$(rm -rf)") == "evil_tenant___rm_-rf_"
    # overlong labels truncate to 64
    assert clean("x" * 200) == "x" * 64
    assert clean("!!!") == "___"


def test_worker_rolls_up_sanitized_tenants(tmp_path):
    """End-to-end over a real (admission-failing, so fast) worker
    session: the ticket's tenant rides submit -> spool -> worker, the
    worker sanitizes it before trusting it anywhere — request events,
    status.json processed.by_tenant, run() stats — and the worker log
    still validates against the schema."""
    from scdna_replication_tools_tpu.serve import (
        ServeWorker,
        SpoolQueue,
    )

    q = SpoolQueue(tmp_path / "spool")
    q.submit("/nonexistent/s.tsv", "/nonexistent/g1.tsv",
             request_id="r_forged", tenant="../../etc/passwd")
    q.submit("/nonexistent/s.tsv", "/nonexistent/g1.tsv",
             request_id="r_plain", tenant="team-a")
    q.submit("/nonexistent/s.tsv", "/nonexistent/g1.tsv",
             request_id="r_anon")
    worker = ServeWorker(q, max_requests=3, exit_when_idle=True)
    stats = worker.run()

    assert stats["processed"] == 3
    assert stats["by_tenant"] == {".._.._etc_passwd": 1, "team-a": 1}
    events = [json.loads(line) for line
              in open(stats["worker_log"]).read().splitlines()]
    by_rid = {e["request_id"]: e for e in events
              if e.get("event") == "request_end"}
    assert by_rid["r_forged"]["tenant"] == ".._.._etc_passwd"
    assert by_rid["r_plain"]["tenant"] == "team-a"
    assert by_rid["r_anon"]["tenant"] is None
    # the raw forged string appears NOWHERE in the worker log
    assert "../../etc/passwd" not in pathlib.Path(
        stats["worker_log"]).read_text()
    status = json.loads(q.status_path.read_text())
    assert status["processed"]["total"] == 3
    assert status["processed"]["by_tenant"] == stats["by_tenant"]
    # the worker-session cost digest rides the same surface (the three
    # claim gaps are queue_idle waste, so billed is non-zero)
    assert status["meter"]["billed_device_seconds"] >= 0.0
    _assert_conserves(stats["meter"])
    validate_run(stats["worker_log"])


# ---------------------------------------------------------------------------
# the CLI: report / attribution / ab
# ---------------------------------------------------------------------------


def _mk_meter(step="step2", bucket="c32xl64", cells=10, pad_frac=0.25,
              iters=100, wall=2.0, compile_s=0.5):
    led = CostLedger(scope={"request": "x"}, devices=1)
    with led.context(step=step, bucket=bucket, cells=cells,
                     pad_frac=pad_frac):
        led.book_compile(seconds=compile_s)
        led.book_chunk(entry_it=0, end_it=iters, wall_seconds=wall)
    return led.summary()


@pytest.fixture()
def fake_spool(tmp_path):
    """A synthetic spool: one worker log (two finished requests with
    tenants + a worker-session run_end meter) and each request's own
    run log carrying its meter — the exact join surface
    ``pert_meter attribution`` walks."""
    spool = tmp_path / "spool"
    (spool / "results" / "r1").mkdir(parents=True)
    (spool / "results" / "r2").mkdir(parents=True)
    run_logs = {}
    for rid, tenant in (("r1", "team-a"), ("r2", "team-b")):
        meter = _mk_meter()
        log = spool / "results" / rid / "run.jsonl"
        log.write_text(json.dumps(
            {"event": "run_end", "status": "ok", "meter": meter}) + "\n")
        run_logs[rid] = str(log)
    worker_led = CostLedger(scope={"worker": "w"}, devices=1)
    worker_led.book_queue_idle(seconds=1.0)
    events = [
        {"event": "request_end", "request_id": "r1", "status": "ok",
         "tenant": "team-a", "bucket": {"name": "c32xl64"},
         "wall_seconds": 3.0, "run_log": run_logs["r1"]},
        {"event": "request_end", "request_id": "r2", "status": "ok",
         "tenant": "team-b", "bucket": {"name": "c32xl64"},
         "wall_seconds": 3.1, "run_log": run_logs["r2"]},
        {"event": "run_end", "status": "ok",
         "meter": worker_led.summary()},
    ]
    (spool / "worker_1.jsonl").write_text(
        "".join(json.dumps(e) + "\n" for e in events))
    return spool


def test_cli_report_on_run_log_and_spool(fake_spool, capsys):
    from tools import pert_meter

    run_log = fake_spool / "results" / "r1" / "run.jsonl"
    rc = pert_meter.main(["report", str(run_log), "--json", "--check"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["conservation_ok"] is True
    # billed = 0.5 compile + 2.0 chunk
    assert doc["meter"]["billed_device_seconds"] == pytest.approx(2.5)
    assert doc["meter"]["waste_seconds"]["padding"] == pytest.approx(0.5)

    rc = pert_meter.main(["report", str(fake_spool), "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    # worker idle + both requests
    assert doc["meter"]["billed_device_seconds"] == pytest.approx(6.0)
    assert {r["request_id"] for r in doc["requests"]} == {"r1", "r2"}

    # the markdown waterfall renders too (no --json)
    rc = pert_meter.main(["report", str(fake_spool)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "waste: `padding`" in out and "**effective**" in out


def test_cli_attribution_rolls_up_tenants(fake_spool, capsys):
    from tools import pert_meter

    rc = pert_meter.main(["attribution", str(fake_spool), "--json",
                          "--check"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["conservation_ok"] is True
    assert set(doc["by_tenant"]) == {"team-a", "team-b"}
    assert doc["by_tenant"]["team-a"]["requests"] == 1
    assert doc["by_tenant"]["team-a"]["billed_device_seconds"] \
        == pytest.approx(2.5)
    assert doc["by_bucket"]["c32xl64"]["requests"] == 2
    # rollup = worker (1.0 idle) + 2 x 2.5
    assert doc["meter"]["billed_device_seconds"] == pytest.approx(6.0)


def test_cli_attribution_check_fails_on_violation(tmp_path, capsys):
    """--check is a real gate: a ledger that does not conserve exits 1."""
    from tools import pert_meter

    spool = tmp_path / "spool"
    spool.mkdir()
    broken = {"billed_device_seconds": 10.0,
              "effective_device_seconds": 1.0,
              "waste_seconds": {"padding": 1.0}, "cell_iters": 0.0,
              "records": 1}
    (spool / "worker_1.jsonl").write_text(
        json.dumps({"event": "run_end", "meter": broken}) + "\n")
    assert pert_meter.main(["attribution", str(spool), "--json",
                            "--check"]) == 1
    capsys.readouterr()


def test_cli_ab_compares_arms(fake_spool, tmp_path, capsys):
    from tools import pert_meter

    other = tmp_path / "other.jsonl"
    other.write_text(json.dumps(
        {"event": "run_end",
         "meter": _mk_meter(pad_frac=0.0, wall=1.0, compile_s=0.0)})
        + "\n")
    rc = pert_meter.main(["ab", str(fake_spool), str(other), "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["a"]["meter"]["billed_device_seconds"] \
        == pytest.approx(6.0)
    assert doc["b"]["meter"]["billed_device_seconds"] \
        == pytest.approx(1.0)
    assert doc["deltas"]["billed_device_seconds_ratio"] \
        == pytest.approx(1.0 / 6.0, rel=1e-3)
    # arm B wastes nothing; A carries padding + compile + idle
    assert doc["deltas"]["waste_frac_delta"] < 0.0


def test_merge_meters_conserves():
    from tools.pert_meter import merge_meters

    merged = merge_meters([_mk_meter(), _mk_meter(pad_frac=0.5),
                           None, {}])
    _assert_conserves(merged)
    assert merged["records"] == 4  # 2 x (compile + chunk)
    assert merged["billed_device_seconds"] == pytest.approx(5.0)
