"""Checkpoint/resume at step boundaries (infer/checkpoint.py).

The reference has no checkpointing — learned state crosses its three SVI
steps only in memory (reference: pert_model.py:772-787, 836-851).  The
TPU runner persists each step's fitted params + loss history and resumes
a rerun from the last completed step; these tests pin that behaviour.
"""

import numpy as np
import pandas as pd

from scdna_replication_tools_tpu.config import ColumnConfig, PertConfig
from scdna_replication_tools_tpu.data.loader import build_pert_inputs
from scdna_replication_tools_tpu.infer import checkpoint as ckpt
from scdna_replication_tools_tpu.infer.runner import PertInference


from conftest import dense_inputs_from_frames as _dense_inputs  # noqa: E402


def test_save_load_roundtrip(tmp_path):
    params = {"a_raw": np.float32(1.5), "tau_raw": np.arange(4, dtype=np.float32)}
    losses = np.array([10.0, 5.0, 2.0], np.float32)
    ckpt.save_step(str(tmp_path), "step2", params, losses,
                   extra={"seed": np.int64(7)})
    got_params, got_losses, extra = ckpt.load_step(str(tmp_path), "step2")
    np.testing.assert_array_equal(got_params["tau_raw"], params["tau_raw"])
    np.testing.assert_array_equal(got_losses, losses)
    assert int(extra["seed"]) == 7
    assert ckpt.load_step(str(tmp_path), "step3") is None


def test_unstamped_pi_logits_checkpoint_is_refused(tmp_path):
    """Pre-v2 checkpoints carry no format_version; their pi_logits layout
    is ambiguous (cells-major in rounds <=3, state-major in round-4
    snapshots) — load_step must refuse rather than guess and silently
    train a transposed tensor."""
    import pytest

    params = {"pi_logits": np.zeros((13, 8, 32), np.float32)}
    path = ckpt.save_step(str(tmp_path), "step2", params,
                          np.array([1.0], np.float32))
    # strip the stamp to fabricate a legacy file
    data = dict(np.load(path))
    del data["meta.format_version"]
    np.savez(path, **data)
    with pytest.raises(ValueError, match="format_version"):
        ckpt.load_step(str(tmp_path), "step2")


def test_partial_fit_resume_is_exact(tmp_path, synthetic_frames):
    """A step-2 fit killed mid-budget must, on resume, land on exactly the
    uninterrupted run's trajectory: Adam moments + loss history + params
    are persisted, and the compiled loop is deterministic.

    Emulates the kill by running with half the iteration budget (the
    checkpoint records converged=False), then rerunning with the full
    budget against the same checkpoint_dir.
    """
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    full, half = 120, 60
    # rel_tol=0 so neither run plateau-converges before its budget;
    # step-1 budget pinned so every config fits the SAME step-1 (the
    # default derives it from max_iter, which differs between runs)
    base = dict(cn_prior_method="g1_clones", rel_tol=0.0, run_step3=False,
                max_iter_step1=40, min_iter_step1=40)

    # uninterrupted reference run (no checkpointing)
    inf_a = PertInference(s, g1,
                          PertConfig(max_iter=full, min_iter=full, **base),
                          clone_idx_s=clone_idx, clone_idx_g1=clone_idx,
                          num_clones=2)
    a1, a2, _ = inf_a.run()

    # interrupted: half budget with checkpoints, then full-budget rerun
    inf_b = PertInference(s, g1,
                          PertConfig(max_iter=half, min_iter=half,
                                     checkpoint_dir=str(tmp_path), **base),
                          clone_idx_s=clone_idx, clone_idx_g1=clone_idx,
                          num_clones=2)
    b1_half, b2_half, _ = inf_b.run()
    assert b2_half.fit.num_iters == half and not b2_half.fit.converged

    inf_c = PertInference(s, g1,
                          PertConfig(max_iter=full, min_iter=full,
                                     checkpoint_dir=str(tmp_path), **base),
                          clone_idx_s=clone_idx, clone_idx_g1=clone_idx,
                          num_clones=2)
    c1, c2, _ = inf_c.run()

    # resumed step 2 ran only the remaining iterations...
    assert c2.fit.num_iters == full
    # ...and reproduces the uninterrupted loss trajectory and parameters
    np.testing.assert_allclose(c2.fit.losses, a2.fit.losses, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(c2.fit.params["tau_raw"]),
                               np.asarray(a2.fit.params["tau_raw"]),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(float(c2.fit.losses[-1]),
                               float(a2.fit.losses[-1]), rtol=1e-6)


def test_sharded_partial_fit_resume_is_exact(tmp_path, synthetic_frames):
    """Checkpoint/resume under the 8-device sharded production path.

    Same invariant as test_partial_fit_resume_is_exact but with the cells
    axis sharded over the virtual mesh and the interpreted Pallas kernel:
    the checkpoint round-trips numpy-host copies of sharded arrays, and a
    resumed fit must re-shard them and land on the uninterrupted sharded
    trajectory.
    """
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    # budgets are wall-budget-trimmed, not accuracy-tuned: the invariant
    # (bit-exact sharded resume) is budget-independent, and the
    # interpreted kernel makes every sharded iteration expensive on CPU
    # (trimmed again 40/20 -> 16/8 when the serve suite landed: three
    # pipelines x ~1 s/interpreted-iteration made this single test
    # ~2 min of the 870 s tier-1 budget; 8 fitted + 8 resumed
    # iterations still cross a real mid-budget boundary)
    full, half = 16, 8
    base = dict(cn_prior_method="g1_clones", rel_tol=0.0, run_step3=False,
                max_iter_step1=10, min_iter_step1=10, num_shards=8,
                enum_impl="pallas_interpret")

    inf_a = PertInference(s, g1,
                          PertConfig(max_iter=full, min_iter=full, **base),
                          clone_idx_s=clone_idx, clone_idx_g1=clone_idx,
                          num_clones=2)
    _, a2, _ = inf_a.run()

    inf_b = PertInference(s, g1,
                          PertConfig(max_iter=half, min_iter=half,
                                     checkpoint_dir=str(tmp_path), **base),
                          clone_idx_s=clone_idx, clone_idx_g1=clone_idx,
                          num_clones=2)
    _, b2_half, _ = inf_b.run()
    assert b2_half.fit.num_iters == half and not b2_half.fit.converged

    inf_c = PertInference(s, g1,
                          PertConfig(max_iter=full, min_iter=full,
                                     checkpoint_dir=str(tmp_path), **base),
                          clone_idx_s=clone_idx, clone_idx_g1=clone_idx,
                          num_clones=2)
    _, c2, _ = inf_c.run()

    assert c2.fit.num_iters == full
    np.testing.assert_allclose(c2.fit.losses, a2.fit.losses, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(c2.fit.params["tau_raw"]),
                               np.asarray(a2.fit.params["tau_raw"]),
                               rtol=1e-5, atol=1e-7)
    # the resumed fit keeps the production sharding
    assert not c2.fit.params["tau_raw"].sharding.is_fully_replicated


def test_resume_skips_completed_steps(tmp_path, synthetic_frames):
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    config = PertConfig(cn_prior_method="g1_clones", max_iter=30,
                        min_iter=15, run_step3=False,
                        checkpoint_dir=str(tmp_path))
    inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    step1, step2, _ = inf.run()
    assert step1.wall_time > 0 and step2.wall_time > 0

    # a fresh runner with the same checkpoint_dir must restore, not refit:
    # restored StepOutputs carry wall_time == 0 and identical losses
    inf2 = PertInference(s, g1, config, clone_idx_s=clone_idx,
                         clone_idx_g1=clone_idx, num_clones=2)
    r1, r2, _ = inf2.run()
    assert r1.wall_time == 0.0 and r2.wall_time == 0.0
    np.testing.assert_allclose(r2.fit.losses, step2.fit.losses)
    np.testing.assert_allclose(
        np.asarray(r2.fit.params["tau_raw"]),
        np.asarray(step2.fit.params["tau_raw"]), rtol=1e-6)
