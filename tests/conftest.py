"""Test environment: 8 virtual CPU devices for multi-chip sharding tests.

Must run before the first ``import jax`` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell presets axon (TPU)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# jax is pre-imported by the environment's sitecustomize before conftest
# runs, so the env var alone is not enough — override the live config too
# (the backend itself is still uninitialised at this point).
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pandas as pd
import pytest


@pytest.fixture(scope="session")
def synthetic_frames():
    """Small synthetic 2-clone dataset in the reference's long-form contract.

    Mirrors the shape of the reference's simulator test fixture
    (reference: test_with_pytest.py:22-58) but with generated GC/RT
    profiles instead of the bundled mcfrt.csv.
    """
    rng = np.random.default_rng(7)
    num_loci = 120
    chrom = "1"
    starts = (np.arange(num_loci) * 500_000).astype(np.int64)
    gc = np.clip(0.45 + 0.08 * np.sin(np.arange(num_loci) / 9.0)
                 + rng.normal(0, 0.02, num_loci), 0.3, 0.65)
    # smooth replication-timing profile (early ~ high values)
    rt = 0.5 + 0.45 * np.sin(np.arange(num_loci) / 15.0 + 1.0)
    rt_b = 0.5 + 0.45 * np.sin(np.arange(num_loci) / 15.0 + 2.2)

    def make_cells(prefix, n, clone, cn_profile):
        frames = []
        for i in range(n):
            frames.append(pd.DataFrame({
                "cell_id": f"{prefix}_{clone}_{i}",
                "chr": chrom,
                "start": starts,
                "end": starts + 500_000,
                "gc": gc,
                "mcf7rt": rt,
                "rt_A": rt,
                "rt_B": rt_b,
                "library_id": "LIB0",
                "clone_id": clone,
                "true_somatic_cn": cn_profile,
            }))
        return frames

    cn_a = np.full(num_loci, 2.0)
    cn_a[80:100] = 4.0  # clone A carries an amplification
    cn_b = np.full(num_loci, 2.0)
    cn_b[20:50] = 3.0   # clone B carries a gain

    n_per_clone = 12
    df_s = pd.concat(
        make_cells("s", n_per_clone, "A", cn_a)
        + make_cells("s", n_per_clone, "B", cn_b),
        ignore_index=True)
    df_g = pd.concat(
        make_cells("g", n_per_clone, "A", cn_a)
        + make_cells("g", n_per_clone, "B", cn_b),
        ignore_index=True)
    return df_s, df_g


def dense_inputs_from_frames(synthetic_frames, rt_prior_col=None):
    """Dense PertData inputs + clone indices from the synthetic frames.

    Shared by the padding/sharding, checkpoint and rho-prior test modules.
    """
    from scdna_replication_tools_tpu.config import ColumnConfig
    from scdna_replication_tools_tpu.data.loader import build_pert_inputs

    df_s, df_g = (df.copy() for df in synthetic_frames)
    rng = np.random.default_rng(0)
    for df in (df_s, df_g):
        df["reads"] = rng.poisson(
            40 * df["true_somatic_cn"].to_numpy()).astype(float)
        df["state"] = df["true_somatic_cn"].astype(int)
    cols = ColumnConfig(rt_prior_col=rt_prior_col)
    s, g1 = build_pert_inputs(df_s, df_g, cols)
    clone_idx = np.array([0] * 12 + [1] * 12, np.int32)
    return s, g1, clone_idx
