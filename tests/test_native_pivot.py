"""Tests for the native scatter-pivot and its loader integration."""

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.config import ColumnConfig
from scdna_replication_tools_tpu.data.loader import pivot_matrix
from scdna_replication_tools_tpu.native import native_available
from scdna_replication_tools_tpu.native.pivot import gather_melt, scatter_pivot


def _long_frame(num_cells=7, num_loci=50, seed=0, shuffle=True):
    rng = np.random.default_rng(seed)
    cells = [f"c{i:03d}" for i in range(num_cells)]
    rows = []
    for c in cells:
        rows.append(pd.DataFrame({
            "cell_id": c,
            "chr": ["1"] * (num_loci // 2) + ["X"] * (num_loci - num_loci // 2),
            "start": np.r_[np.arange(num_loci // 2),
                           np.arange(num_loci - num_loci // 2)] * 500_000,
            "reads": rng.poisson(40, num_loci).astype(float),
        }))
    df = pd.concat(rows, ignore_index=True)
    if shuffle:
        df = df.sample(frac=1.0, random_state=1).reset_index(drop=True)
    return df


def test_scatter_pivot_matches_numpy_fallback():
    rng = np.random.default_rng(2)
    n_cells, n_loci, n = 11, 37, 300
    cc = rng.integers(0, n_cells, n).astype(np.int32)
    lc = rng.integers(0, n_loci, n).astype(np.int32)
    # dedupe keys (contract: one row per key)
    _, keep = np.unique(cc.astype(np.int64) * n_loci + lc, return_index=True)
    cc, lc = cc[keep], lc[keep]
    vals = rng.normal(0, 10, len(cc))

    a = scatter_pivot(cc, lc, vals, n_cells, n_loci, use_native=False)
    b = scatter_pivot(cc, lc, vals, n_cells, n_loci)
    np.testing.assert_array_equal(np.isnan(a), np.isnan(b))
    np.testing.assert_allclose(np.nan_to_num(a), np.nan_to_num(b))

    got = gather_melt(np.nan_to_num(a), cc, lc)
    np.testing.assert_allclose(got, vals.astype(np.float32))


def test_native_library_builds_here():
    """The image ships g++, so the native path must actually build."""
    assert native_available()


def test_pivot_matrix_matches_pandas_pivot_table():
    df = _long_frame()
    cols = ColumnConfig()
    got = pivot_matrix(df, "reads", cols)

    from scdna_replication_tools_tpu.utils.chrom import as_chr_categorical
    ref_df = df.copy()
    ref_df["chr"] = as_chr_categorical(ref_df["chr"])
    want = ref_df.pivot_table(index="cell_id", columns=["chr", "start"],
                              values="reads", observed=True).sort_index(axis=1)
    np.testing.assert_allclose(got.to_numpy(), want.to_numpy())
    assert list(got.index) == list(want.index)
    assert [tuple(map(str, t)) for t in got.columns] == \
        [tuple(map(str, t)) for t in want.columns]


def test_pivot_matrix_drops_unknown_chromosomes():
    df = _long_frame(num_cells=3, num_loci=10)
    weird = df.iloc[:5].copy()
    weird["chr"] = "chrUn_gl000220"
    got = pivot_matrix(pd.concat([df, weird], ignore_index=True), "reads")
    want = pivot_matrix(df, "reads")
    np.testing.assert_allclose(got.to_numpy(), want.to_numpy())


def test_pivot_matrix_duplicate_keys_fall_back_to_mean():
    df = _long_frame(num_cells=2, num_loci=6, shuffle=False)
    dup = df.iloc[[0]].copy()
    dup["reads"] = df.iloc[0]["reads"] + 10.0
    got = pivot_matrix(pd.concat([df, dup], ignore_index=True), "reads")
    assert got.iloc[0, 0] == df.iloc[0]["reads"] + 5.0  # pivot_table mean
