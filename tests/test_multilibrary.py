"""Multi-library (L=2) inference: per-library GC betas must be recovered.

Step 1 exists to fit per-library GC polynomials — ``beta_means[libs]`` /
``beta_stds[libs]`` index cells into their library's coefficients
(reference: pert_model.py:560-562, 603).  Round 1 never ran these paths
with L>=2; here two libraries get OPPOSITE-sign GC slopes and inference
must recover both, end to end through the default ``g1_composite`` prior
(reference: pert_model.py:41 — the previously untested shipped default).

Reads are drawn by an independent NumPy NB generator (not the package's
simulator), so generation and inference share no code.
"""

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.config import ColumnConfig, PertConfig
from scdna_replication_tools_tpu.data.loader import build_pert_inputs
from scdna_replication_tools_tpu.infer.runner import PertInference
from scdna_replication_tools_tpu.models.pert import constrained

LAMB = 0.75
# true per-library GC curves, degree-1: [slope, intercept]
TRUE_BETAS = np.array([[0.8, 0.0],
                       [-0.6, 0.1]])


def _nb_draw(rng, theta, lamb):
    """NB with torch parameterisation mean = delta*lamb/(1-lamb) = theta."""
    delta = np.maximum(theta * (1 - lamb) / lamb, 1.0)
    return rng.negative_binomial(delta, 1 - lamb).astype(np.float32)


@pytest.fixture(scope="module")
def two_library_problem():
    rng = np.random.default_rng(3)
    num_loci = 100
    n_per_lib_g1, n_per_lib_s = 8, 6
    starts = (np.arange(num_loci) * 500_000).astype(np.int64)
    gc = np.clip(0.45 + 0.1 * np.sin(np.arange(num_loci) / 7.0)
                 + rng.normal(0, 0.02, num_loci), 0.3, 0.65)
    rho = 0.5 + 0.45 * np.sin(np.arange(num_loci) / 15.0 + 1.0)

    cn = np.full(num_loci, 2.0)
    cn[60:80] = 3.0

    def omega(lib):
        return np.exp(TRUE_BETAS[lib, 0] * gc + TRUE_BETAS[lib, 1])

    frames_g1, frames_s = [], []
    truth_rep = {}
    for lib in (0, 1):
        for i in range(n_per_lib_g1):
            u = rng.uniform(8, 12)
            reads = _nb_draw(rng, u * cn * omega(lib), LAMB)
            frames_g1.append(pd.DataFrame({
                "cell_id": f"g_l{lib}_{i}", "chr": "1", "start": starts,
                "end": starts + 500_000, "gc": gc,
                "library_id": f"LIB{lib}", "clone_id": "A",
                "reads": reads, "state": cn.astype(int),
                "copy": cn}))
        for i in range(n_per_lib_s):
            u = rng.uniform(8, 12)
            tau = rng.uniform(0.15, 0.85)
            phi = np.clip(1 / (1 + np.exp(-10.0 * (tau - rho))),
                          0.001, 0.999)
            rep = (rng.random(num_loci) < phi).astype(np.float32)
            theta = u * cn * (1.0 + rep) * omega(lib)
            cell = f"s_l{lib}_{i}"
            truth_rep[cell] = rep
            frames_s.append(pd.DataFrame({
                "cell_id": cell, "chr": "1", "start": starts,
                "end": starts + 500_000, "gc": gc,
                "library_id": f"LIB{lib}", "clone_id": "A",
                "reads": _nb_draw(rng, theta, LAMB),
                "state": cn.astype(int), "copy": cn}))

    df_s = pd.concat(frames_s, ignore_index=True)
    df_g1 = pd.concat(frames_g1, ignore_index=True)
    cols = ColumnConfig(rt_prior_col=None)
    s, g1 = build_pert_inputs(df_s, df_g1, cols)
    return dict(s=s, g1=g1, gc=gc, truth_rep=truth_rep)


@pytest.fixture(scope="module")
def fitted(two_library_problem):
    p = two_library_problem
    n_s = p["s"].num_cells
    n_g1 = p["g1"].num_cells
    config = PertConfig(P=6, K=1, cn_prior_method="g1_composite",
                        max_iter=400, min_iter=100, run_step3=False,
                        enum_impl="xla")
    inf = PertInference(
        p["s"], p["g1"], config,
        clone_idx_s=np.zeros(n_s, np.int64),
        clone_idx_g1=np.zeros(n_g1, np.int64),
        num_clones=1)
    step1 = inf.run_step1()
    etas = inf.build_etas()
    step2 = inf.run_step2(step1, etas)
    return inf, step1, step2


def test_library_index_has_two_libraries(two_library_problem):
    p = two_library_problem
    assert p["s"].num_libraries == 2
    assert p["g1"].num_libraries == 2
    assert set(np.unique(p["s"].libs)) == {0, 1}


def test_step1_recovers_per_library_gc_slopes(two_library_problem, fitted):
    """Fitted beta_means must reproduce each library's GC curve — and not
    the other library's (the slopes have opposite signs)."""
    p = two_library_problem
    _, step1, _ = fitted
    c1 = constrained(step1.spec, step1.fit.params, step1.fixed)
    beta_means = np.asarray(c1["beta_means"])        # (2, K+1)
    gc = p["gc"]

    for lib in (0, 1):
        fit_curve = beta_means[lib, 0] * gc          # slope * gc (K=1)
        true_curve = TRUE_BETAS[lib, 0] * gc
        r = np.corrcoef(fit_curve, true_curve)[0, 1]
        assert r > 0.95, f"lib {lib}: GC curve corr {r:.3f}"
        # slope signs are opposite by construction; the fit must preserve
        # the sign per library
        assert np.sign(beta_means[lib, 0]) == np.sign(TRUE_BETAS[lib, 0]), (
            f"lib {lib}: slope {beta_means[lib, 0]:.3f} "
            f"vs true {TRUE_BETAS[lib, 0]:.3f}")
    assert beta_means[0, 0] > 0 > beta_means[1, 0]


def test_step2_default_prior_recovers_rep_states(two_library_problem, fitted):
    """End-to-end through the default g1_composite prior: decode accuracy
    on the independently generated truth."""
    from scdna_replication_tools_tpu.models.pert import decode_discrete

    p = two_library_problem
    inf, _, step2 = fitted
    cn_map, rep_map, _ = decode_discrete(
        step2.spec, step2.fit.params, step2.fixed, step2.batch)
    rep_map = np.asarray(rep_map)[: p["s"].num_cells]
    cn_map = np.asarray(cn_map)[: p["s"].num_cells]

    truth = np.stack([p["truth_rep"][c] for c in p["s"].cell_ids])
    rep_acc = (rep_map == truth).mean()
    assert rep_acc > 0.85, f"rep accuracy {rep_acc:.3f}"

    cn_true = np.full_like(cn_map, 2)
    cn_true[:, 60:80] = 3
    cn_acc = (cn_map == cn_true).mean()
    assert cn_acc > 0.90, f"CN accuracy {cn_acc:.3f}"


def test_step2_loss_decreased(fitted):
    _, _, step2 = fitted
    losses = np.asarray(step2.fit.losses)
    losses = losses[np.isfinite(losses)]
    assert losses[-1] < losses[0]
