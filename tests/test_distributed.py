"""Multi-host plumbing (parallel/distributed.py), single-process case.

A real pod cannot run in CI; what CAN be pinned is that the multi-host
entry points are exact aliases of the single-host path when
process_count == 1 (the degenerate case the module documents), so model
code driven through them produces identical results — the multi-host
path then differs only in how jax.Arrays are assembled
(make_array_from_process_local_data), which JAX owns.
"""

import numpy as np

from scdna_replication_tools_tpu.infer.svi import fit_map
from scdna_replication_tools_tpu.models.pert import pert_loss
from scdna_replication_tools_tpu.parallel.distributed import (
    HostShard,
    global_mesh,
    init_distributed,
    shard_batch_multihost,
    shard_params_multihost,
)
from scdna_replication_tools_tpu.parallel.mesh import shard_batch, shard_params

from __graft_entry__ import _toy_problem


def test_init_distributed_single_process_noop():
    assert init_distributed() == 1


def test_host_shard_bounds():
    shard = HostShard.for_this_process(32)
    assert (shard.lo, shard.hi) == (0, 32)


def test_multihost_placement_matches_single_host_fit():
    import jax.numpy as jnp

    spec, params, fixed, batch = _toy_problem(num_cells=16, num_loci=64,
                                              enum_impl="pallas_interpret",
                                              sparse=True)
    mesh = global_mesh(4, loci_shards=2)
    shard = HostShard.for_this_process(16)

    def fresh_params():
        # fit_map DONATES params0, and jax.device_put of an
        # already-committed array can return the SAME zero-copy buffer
        # (the PR-4 aliasing class) — so placing the one `params` dict
        # twice would hand the second run deleted buffers.  Each run
        # places its own fresh copies, per fit_map's documented
        # donation contract.
        return {k: jnp.array(v, copy=True) for k, v in params.items()}

    def run(b, p):
        def loss_fn(p_, fixed_, b_):
            return pert_loss(spec, p_, fixed_, b_, mesh=mesh)
        fit = fit_map(loss_fn, p, (fixed, b), max_iter=4, min_iter=4,
                      learning_rate=5e-2)
        return np.asarray(fit.losses, np.float64)

    ref = run(shard_batch(mesh, batch), shard_params(mesh, fresh_params()))
    got = run(shard_batch_multihost(mesh, batch, shard),
              shard_params_multihost(mesh, fresh_params(), shard))
    np.testing.assert_allclose(got, ref, rtol=1e-6)
