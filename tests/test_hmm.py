"""Tests for the genome-smoothed Viterbi CN decode (models/hmm.py)."""

import itertools

import jax.numpy as jnp
import numpy as np

from scdna_replication_tools_tpu.models.hmm import (
    hmm_decode,
    transition_log_probs,
    viterbi_paths,
)


def _brute_force_path(emissions, restart, log_trans):
    """Exact MAP path by exhaustive enumeration (small problems only)."""
    L, P = emissions.shape
    best_score, best_path = -np.inf, None
    for path in itertools.product(range(P), repeat=L):
        score = emissions[0, path[0]]
        for t in range(1, L):
            score += emissions[t, path[t]]
            if not restart[t]:
                score += log_trans[path[t - 1], path[t]]
        if score > best_score:
            best_score, best_path = score, path
    return np.array(best_path)


def test_viterbi_matches_brute_force():
    rng = np.random.default_rng(0)
    P, L = 4, 7
    emissions = rng.normal(0, 2, (L, P)).astype(np.float32)
    restart = np.zeros(L, np.float32)
    restart[0] = restart[4] = 1.0  # chromosome break mid-sequence
    log_trans = np.asarray(transition_log_probs(P, 0.9))

    got = np.asarray(viterbi_paths(
        jnp.asarray(emissions)[None], jnp.asarray(restart),
        jnp.asarray(log_trans)))[0]
    want = _brute_force_path(emissions, restart, log_trans)
    np.testing.assert_array_equal(got, want)


def test_viterbi_smooths_single_bin_flicker():
    """A lone weak outlier bin inside a long CN=2 segment is smoothed,
    while a strongly-supported multi-bin segment is kept."""
    P, L = 5, 40
    emissions = np.full((L, P), -10.0, np.float32)
    emissions[:, 2] = -1.0                 # CN 2 everywhere
    emissions[15, 2] = -2.0                # one flicker bin weakly
    emissions[15, 4] = -1.5                # ... prefers CN 4
    # real 5-bin CN 3 segment: per-bin gain 3.0 x 5 bins = 15 beats the
    # two switch penalties (2 x log(0.0025/0.99) ~ -12)
    emissions[25:30, 3] = 2.0
    restart = np.zeros(L, np.float32)
    restart[0] = 1.0

    log_trans = transition_log_probs(P, 0.99)
    path = np.asarray(viterbi_paths(
        jnp.asarray(emissions)[None], jnp.asarray(restart), log_trans))[0]

    assert path[15] == 2, "flicker should be smoothed to the segment CN"
    assert (path[25:30] == 3).all(), "supported segment must survive"
    assert (np.delete(path, np.r_[15, 25:30]) == 2).all()


def test_restart_decouples_chromosomes():
    """With an extreme self-prob the path is constant per chromosome but
    free to jump at the boundary."""
    P, L = 3, 10
    emissions = np.zeros((L, P), np.float32)
    emissions[:5, 0] = 2.0   # chr1 favours state 0
    emissions[5:, 2] = 2.0   # chr2 favours state 2
    restart = np.zeros(L, np.float32)
    restart[0] = restart[5] = 1.0

    log_trans = transition_log_probs(P, 0.9999)
    path = np.asarray(viterbi_paths(
        jnp.asarray(emissions)[None], jnp.asarray(restart), log_trans))[0]
    assert (path[:5] == 0).all() and (path[5:] == 2).all()


def test_hmm_decode_shapes_and_rep_consistency():
    rng = np.random.default_rng(1)
    C, L, P = 3, 20, 6
    joint = jnp.asarray(rng.normal(0, 1, (C, L, P, 2)).astype(np.float32))
    restart = jnp.asarray(np.r_[1.0, np.zeros(L - 1)].astype(np.float32))
    cn, rep, p_rep = hmm_decode(joint, restart, 0.95)
    assert cn.shape == rep.shape == p_rep.shape == (C, L)
    # rep must be the argmax over the rep axis at the decoded CN
    at_cn = np.take_along_axis(np.asarray(joint),
                               np.asarray(cn)[..., None, None], axis=-2)
    np.testing.assert_array_equal(np.asarray(rep), at_cn[..., 0, :].argmax(-1))
    assert ((0.0 <= np.asarray(p_rep)) & (np.asarray(p_rep) <= 1.0)).all()


def test_hmm_decode_cell_slabs_are_exact():
    """decode_discrete_hmm's cell-slabbed path (OOM guard for
    genome-scale packaging) must be bit-identical to one pass: the
    Viterbi couples loci, not cells.  Slab of 3 over 8 cells exercises
    the non-dividing remainder."""
    from scdna_replication_tools_tpu.models.pert import (
        PertModelSpec,
        decode_discrete_hmm,
        init_params,
    )
    from tests.test_model_core import _toy_batch

    rng = np.random.default_rng(7)
    spec = PertModelSpec(P=5, K=2, L=1, tau_mode="param")
    batch = _toy_batch(rng, P=5)
    params = init_params(spec, batch, {},
                         t_init=np.full(8, 0.4, np.float32))
    restart = jnp.asarray(
        np.r_[1.0, np.zeros(batch.reads.shape[1] - 1)].astype(np.float32))
    whole = decode_discrete_hmm(spec, params, {}, batch, restart, 0.9)
    slab = decode_discrete_hmm(spec, params, {}, batch, restart, 0.9,
                               cell_chunk=3)
    for a, b in zip(whole, slab):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
