"""pertserve: shape buckets, the file-queue spool, and the worker.

The module-scoped ``served`` fixture runs ONE worker session over
three queued requests — clean (cold), chaos-faulted, clean (warm) —
plus two direct golden runs, and every behavioural test reads from it:

* bucket padding: the warm request must be a 100% AOT program-cache
  hit (zero compile misses in its own RunLog);
* per-request fault isolation: the injected ``oom@step2/fit#1``
  aborts request 2's manifest only — the worker survives and request
  3 lands bit-identical to its golden direct run;
* padded-vs-direct parity: bucket padding changes shapes, not
  answers (CN decode identical, tau within float tolerance of the
  unpadded trajectory).

Compile cost note: the three serve requests and the padded golden run
share one program set (that is the point of the bucket), so this
module pays roughly two compiles total — the bucket-shaped one and
the unpadded-parity one.
"""

import pathlib
import signal
import sys
import threading
import time

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.obs import metrics as metrics_mod
from scdna_replication_tools_tpu.obs.schema import validate_run
from scdna_replication_tools_tpu.obs.summary import summarize_run
from scdna_replication_tools_tpu.serve import (
    Bucket,
    BucketRefusal,
    BucketSet,
    ServeWorker,
    SpoolQueue,
)

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]
                       / "tools"))

REQUEST_OPTIONS = {
    "max_iter": 120, "min_iter": 40, "run_step3": False,
    # rescue off: its sub-fit program is candidate-count-shaped, which
    # would make the zero-miss warm assertion depend on cohort noise
    # (the documented bucket-contract caveat)
    "mirror_rescue": False, "seed": 0, "cn_prior_method": "g1_clones",
}


def _frames(num_loci=48, cells_per_clone=3, seed=3):
    from accuracy_sweep import _tutorial

    tut = _tutorial()
    df_s, df_g = tut.make_input_frames(num_loci=num_loci,
                                       cells_per_clone=cells_per_clone,
                                       seed=seed)
    return tut.simulate_pert_frames(df_s, df_g, num_reads=8000,
                                    lamb=0.75, a=10.0, seed=seed + 1)


# ---------------------------------------------------------------------------
# bucket units
# ---------------------------------------------------------------------------


def test_bucket_selects_smallest_fitting():
    bs = BucketSet(cells=(8, 16, 32), loci=(64, 128, 256))
    assert bs.select(5, 64) == Bucket(8, 64)
    assert bs.select(8, 64) == Bucket(8, 64)       # exact fit
    assert bs.select(9, 65) == Bucket(16, 128)
    assert bs.select(32, 256) == Bucket(32, 256)   # largest, admitted


def test_bucket_refusal_above_largest():
    bs = BucketSet(cells=(8, 16), loci=(64,))
    with pytest.raises(BucketRefusal):
        bs.select(17, 64)
    with pytest.raises(BucketRefusal):
        bs.select(8, 65)
    # the refusal names the offending shape and the ceiling
    try:
        bs.select(17, 400)
    except BucketRefusal as exc:
        assert "17 cells x 400 loci" in str(exc)
        assert "16 x 64" in str(exc)


def test_bucket_pad_frac_bounds_on_doubling_ladder():
    """Powers-of-two ladders bound padding analytically for requests
    at least HALF the smallest rung per axis: each axis then pads by
    < 2x, so the padded area is < 4x and pad_frac < 0.75.  Smaller
    requests still admit — into the smallest bucket, padding more."""
    bs = BucketSet()  # the default doubling ladders
    rng = np.random.default_rng(0)
    for _ in range(200):
        cells = int(rng.integers(bs.cells[0] // 2, bs.cells[-1] + 1))
        loci = int(rng.integers(bs.loci[0] // 2, bs.loci[-1] + 1))
        bucket = bs.select(cells, loci)
        frac = bucket.pad_frac(cells, loci)
        assert 0.0 <= frac < 0.75, (cells, loci, bucket, frac)
    # exact fits pad nothing
    assert BucketSet().select(256, 2048).pad_frac(256, 2048) == 0.0
    # below the floor the bound honestly does NOT hold: a tiny cohort
    # admits into the smallest bucket with a higher pad fraction
    tiny = bs.select(2, 16)
    assert tiny == Bucket(bs.cells[0], bs.loci[0])
    assert 0.75 < tiny.pad_frac(2, 16) < 1.0


def test_bucketset_validation_and_parsing():
    with pytest.raises(ValueError):
        BucketSet(cells=(16, 8), loci=(64,))   # not ascending
    with pytest.raises(ValueError):
        BucketSet(cells=(), loci=(64,))        # empty
    with pytest.raises(ValueError):
        BucketSet(cells=(0,), loci=(64,))      # non-positive
    with pytest.raises(ValueError):
        BucketSet().select(0, 64)              # degenerate request
    bs = BucketSet.from_specs("8, 16,32", None)
    assert bs.cells == (8, 16, 32)
    assert bs.loci == BucketSet().loci
    assert BucketSet.from_specs(None, "64").loci == (64,)


# ---------------------------------------------------------------------------
# spool-queue units (no jax, no pipeline)
# ---------------------------------------------------------------------------


def _tiny_frame():
    return pd.DataFrame({"cell_id": ["c0"], "chr": ["1"], "start": [0],
                         "reads": [1.0]})


def test_queue_submit_claim_finish_roundtrip(tmp_path):
    q = SpoolQueue(tmp_path / "spool")
    df = _tiny_frame()
    first = q.submit_frames(df, df, options={"max_iter": 7})
    second = q.submit_frames(df, df)
    assert q.depth() == 2

    t = q.claim()
    assert t.request_id == first            # FIFO by id
    assert t.options == {"max_iter": 7}
    assert pathlib.Path(t.s_path).exists()
    assert (q.root / "active" / f"{first}.json").exists()

    q.finish(t, "ok", results_dir=q.results_dir(first))
    assert q.status(first)["state"] == "done"
    assert not (q.root / "active" / f"{first}.json").exists()
    # the results tree carries a copy of the terminal ticket
    assert (q.results_dir(first) / "request.json").exists()

    t2 = q.claim()
    assert t2.request_id == second
    q.finish(t2, "failed", error="boom")
    assert q.status(second)["state"] == "failed"
    assert q.status(second)["error"] == "boom"
    assert q.claim() is None
    states = {d["request_id"]: d["state"] for d in q.list_requests()}
    assert states == {first: "done", second: "failed"}


def test_queue_ignores_partial_and_malformed_tickets(tmp_path):
    q = SpoolQueue(tmp_path / "spool")
    q.ensure_dirs()
    # a torn atomic-write temp file must be invisible to the scan
    (q.root / "pending" / "x.json.abc.tmp").write_text("{")
    assert q.pending() == []
    # a malformed ticket is parked as failed, not a queue wedge
    (q.root / "pending" / "bad.json").write_text("{not json")
    assert q.claim() is None
    assert q.status("bad")["state"] == "failed"
    assert "unreadable ticket" in q.status("bad")["error"]


def test_queue_fifo_is_submission_order_not_id_order(tmp_path):
    """A caller-supplied lexically-small --request-id must not jump
    ahead of earlier tickets: FIFO is submission time, id only breaks
    same-instant ties."""
    import os

    q = SpoolQueue(tmp_path / "spool")
    df = _tiny_frame()
    first = q.submit_frames(df, df, request_id="zzz_first_submitted")
    second = q.submit_frames(df, df, request_id="aaa_but_later")
    # pin distinct mtimes explicitly (same-second submissions tie-break
    # by id, which is exactly what this test must not rely on)
    os.utime(q.root / "pending" / f"{first}.json", (1000, 1000))
    os.utime(q.root / "pending" / f"{second}.json", (2000, 2000))
    assert q.claim().request_id == first
    assert q.claim().request_id == second


def test_worker_rejects_reserved_default_options(tmp_path):
    """Operator-level default options fail FAST at startup: a reserved
    key (paths/padding the worker itself owns) would otherwise
    TypeError inside scRT on every single request."""
    q = SpoolQueue(tmp_path / "spool")
    with pytest.raises(ValueError, match="telemetry_path"):
        ServeWorker(q, default_options={"telemetry_path": "/tmp/x",
                                        "max_iter": 10})
    # whitelisted defaults are fine
    ServeWorker(q, default_options={"max_iter": 10})


def test_admission_failure_still_emits_lifecycle_pair(tmp_path):
    """A request whose inputs cannot even be read fails at admission —
    but the worker log's one-start-one-end-per-request contract must
    hold (no orphan request_end for latency/attribution joins)."""
    q = SpoolQueue(tmp_path / "spool")
    rid = q.submit("/nonexistent/s.tsv", "/nonexistent/g1.tsv",
                   request_id="bad_paths")
    worker = ServeWorker(q, max_requests=1, exit_when_idle=True)
    stats = worker.run()
    assert stats["by_status"] == {"failed": 1}
    assert q.status(rid)["state"] == "failed"
    import json as _json

    events = [_json.loads(line) for line in
              open(stats["worker_log"]).read().splitlines()]
    starts = [e for e in events if e["event"] == "request_start"]
    ends = [e for e in events if e["event"] == "request_end"]
    assert [e["request_id"] for e in starts] == [rid]
    assert [e["request_id"] for e in ends] == [rid]
    assert starts[0]["detail"] == "failed at admission"
    assert validate_run(stats["worker_log"]) == []


def test_queue_rejects_duplicate_request_id(tmp_path):
    q = SpoolQueue(tmp_path / "spool")
    df = _tiny_frame()
    q.submit_frames(df, df, request_id="dup")
    with pytest.raises(ValueError, match="dup"):
        q.submit_frames(df, df, request_id="dup")


# ---------------------------------------------------------------------------
# the worker session: cold / faulted / warm + goldens
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    from scdna_replication_tools_tpu.api import scRT

    root = tmp_path_factory.mktemp("pert_serve")
    queue = SpoolQueue(root / "spool")
    buckets = BucketSet(cells=(8, 16), loci=(64, 128))

    sim_a = _frames(seed=3)
    sim_b = _frames(seed=11)

    r1 = queue.submit_frames(*sim_a, options=REQUEST_OPTIONS,
                             request_id="r1_cold")
    r2 = queue.submit_frames(
        *sim_a, options={**REQUEST_OPTIONS,
                         "faults": "oom@step2/fit#1"},
        request_id="r2_faulted")
    r3 = queue.submit_frames(*sim_b, options=REQUEST_OPTIONS,
                             request_id="r3_warm")

    worker = ServeWorker(queue, buckets=buckets, max_requests=3,
                         exit_when_idle=True,
                         metrics_textfile=str(root / "serve.prom"))
    stats = worker.run()

    by_id = {o.request_id: o for o in worker.outcomes}
    bucket = by_id[r3].bucket

    # golden: the same frames through a DIRECT run under the same
    # bucket padding — the serve path must be bit-identical to it
    scrt = scRT(sim_b[0].copy(), sim_b[1].copy(),
                telemetry_path=str(root / "golden.jsonl"),
                pad_cells_to=bucket["cells"],
                pad_loci_to=bucket["loci"], **REQUEST_OPTIONS)
    golden_out, _, _, _ = scrt.infer(level="pert")

    # direct UNPADDED run of the same frames: the padded-parity anchor
    scrt_direct = scRT(sim_b[0].copy(), sim_b[1].copy(),
                       telemetry_path=str(root / "direct.jsonl"),
                       **REQUEST_OPTIONS)
    direct_out, _, _, _ = scrt_direct.infer(level="pert")

    return {
        "queue": queue, "stats": stats, "worker": worker,
        "ids": (r1, r2, r3), "by_id": by_id, "bucket": bucket,
        "golden_out": golden_out, "direct_out": direct_out,
        "registry": worker.registry, "sim_b": sim_b,
    }


def _served_frame(served, rid):
    return pd.read_csv(
        served["queue"].results_dir(rid) / "output.tsv", sep="\t",
        dtype={"chr": str}).sort_values(["cell_id", "chr", "start"]) \
        .reset_index(drop=True)


def test_worker_processes_all_and_isolates_fault(served):
    r1, r2, r3 = served["ids"]
    by_id = served["by_id"]
    assert served["stats"]["processed"] == 3
    assert by_id[r1].status == "ok"
    assert by_id[r2].status == "failed"
    assert "RESOURCE_EXHAUSTED" in by_id[r2].error
    # the worker SURVIVED the faulted request and served the next one
    assert by_id[r3].status == "ok"
    assert served["queue"].status(r2)["state"] == "failed"
    assert served["queue"].status(r3)["state"] == "done"
    # the faulted request's own artifacts carry the audit: the
    # injected fault and the abort_resumable degrade rung
    r2_summary = summarize_run(by_id[r2].run_log)
    resil = r2_summary["resilience"]
    assert any(f.get("kind") == "oom" for f in resil["faults"])
    assert any(d.get("action") == "abort_resumable"
               for d in resil["degrades"])


def test_warm_request_is_full_program_cache_hit(served):
    r1, _, r3 = served["ids"]
    cold = served["by_id"][r1].compile_cache
    warm = served["by_id"][r3].compile_cache
    assert cold["cache_misses"] > 0          # the cold request compiled
    assert warm["cache_misses"] == 0         # the warm one never did
    assert warm["cache_hits"] > 0
    assert warm["hit_rate"] == 1.0


def test_served_output_bit_identical_to_golden(served):
    """A request through the worker == a direct run with the same
    bucket padding, bit-for-bit at the output's float32 precision —
    including AFTER a faulted neighbour request (the acceptance
    criterion's isolation + parity bar)."""
    _, _, r3 = served["ids"]
    s = _served_frame(served, r3)
    g = served["golden_out"].sort_values(["cell_id", "chr", "start"]) \
        .reset_index(drop=True)
    assert len(s) == len(g) > 0
    assert (s["model_cn_state"].to_numpy()
            == g["model_cn_state"].to_numpy()).all()
    assert (s["model_tau"].to_numpy(np.float32)
            == g["model_tau"].to_numpy(np.float32)).all()
    assert (s["model_rep_state"].to_numpy()
            == g["model_rep_state"].to_numpy()).all()


def test_bucket_padding_parity_vs_direct_run(served):
    """Bucket padding changes shapes (and so float reduction order),
    not answers: the CN/rep decode matches the unpadded run exactly
    and tau agrees to float tolerance."""
    g = served["golden_out"].sort_values(["cell_id", "chr", "start"]) \
        .reset_index(drop=True)
    d = served["direct_out"].sort_values(["cell_id", "chr", "start"]) \
        .reset_index(drop=True)
    assert len(g) == len(d) > 0
    cn_match = (g["model_cn_state"].to_numpy()
                == d["model_cn_state"].to_numpy()).mean()
    assert cn_match >= 0.99, f"CN decode drifted: match {cn_match:.4f}"
    np.testing.assert_allclose(
        g["model_tau"].to_numpy(np.float64),
        d["model_tau"].to_numpy(np.float64), atol=5e-3, rtol=0.0)
    # pad rows never leak into the long output (inner-join semantics)
    assert not g["cell_id"].astype(str).str.startswith("__pad").any()


def test_request_results_streamed_back(served):
    _, _, r3 = served["ids"]
    results = served["queue"].results_dir(r3)
    for name in ("output.tsv", "supp.tsv", "cell_qc.tsv", "run.jsonl",
                 "request.json"):
        assert (results / name).exists(), name
    qc = pd.read_csv(results / "cell_qc.tsv", sep="\t")
    assert {"cell_id", "model_tau", "qc_pass"} <= set(qc.columns)
    # per-request durable-run artifacts live under the results tree
    assert (results / "ckpt" / "manifest.json").exists()


def test_worker_and_request_logs_schema_valid(served):
    assert validate_run(served["stats"]["worker_log"]) == []
    _, r2, r3 = served["ids"]
    assert validate_run(served["by_id"][r3].run_log) == []
    # the faulted request's log ends with run_end status=error — still
    # schema-valid (the session wrapper guarantees the envelope)
    assert validate_run(served["by_id"][r2].run_log) == []


def test_worker_log_carries_request_lifecycle(served):
    summary = summarize_run(served["stats"]["worker_log"])
    requests = {r["request_id"]: r for r in summary["requests"]}
    r1, r2, r3 = served["ids"]
    assert requests[r1]["status"] == "ok"
    assert requests[r2]["status"] == "failed"
    assert requests[r2]["error_class"] == "oom"
    assert requests[r3]["status"] == "ok"
    assert requests[r3]["compile_cache"]["cache_misses"] == 0
    assert requests[r3]["bucket"]["name"] == \
        f"c{served['bucket']['cells']}xl{served['bucket']['loci']}"


def test_worker_gauges_scoped_to_worker_registry(served):
    """The worker registry carries the serve gauges; the per-request
    fit counters stay in the request registries — the interleaved-log
    cross-feed the log-scoped seam (satellite: obs/metrics.py) fixes."""
    text = served["registry"].to_prometheus_text()
    assert 'pert_serve_requests_total{status="ok"} 2' in text
    assert 'pert_serve_requests_total{status="failed"} 1' in text
    assert "pert_serve_queue_depth" in text
    assert "pert_serve_bucket_pad_frac" in text
    # no cross-feed: the requests' fit/compile counters must NOT have
    # leaked into the worker's registry
    assert "pert_fit_iters_total" not in text
    assert "pert_compile_cache" not in text
    # and the textfile scrape surface was written
    snap = served["registry"].snapshot()
    assert any(k.startswith("pert_serve_requests_total") for k in snap)


def test_fleet_groups_serve_traffic_by_request(served):
    from tools import pert_fleet

    r1, r2, r3 = served["ids"]
    spool_root = served["queue"].root
    runs = pert_fleet.build_index([spool_root])["runs"]
    by_request = {r.get("request_id"): r for r in runs
                  if r.get("request_id")}
    assert set(by_request) == {r1, r2, r3}

    class _Args:
        config_hash = run_name = status = since = until = None
        request = r3

    only_r3 = pert_fleet.filter_runs(runs, _Args())
    assert [r["request_id"] for r in only_r3] == [r3]
    _Args.request = "*"
    assert len(pert_fleet.filter_runs(runs, _Args())) == 3
    table = pert_fleet.render_query(only_r3)
    assert r3 in table


def test_refused_request_never_reaches_the_runner(served, tmp_path):
    """A shape above the largest bucket is refused at admission — no
    compile, a terminal 'refused' ticket, a request_end audit."""
    queue = SpoolQueue(tmp_path / "spool")
    big = _frames(num_loci=256, cells_per_clone=3, seed=5)
    rid = queue.submit_frames(*big, options=REQUEST_OPTIONS)
    worker = ServeWorker(queue, buckets=BucketSet(cells=(8,),
                                                  loci=(64, 128)),
                         max_requests=1, exit_when_idle=True)
    stats = worker.run()
    assert stats["by_status"] == {"refused": 1}
    doc = queue.status(rid)
    assert doc["state"] == "failed" and doc["status"] == "refused"
    assert "exceeds the largest bucket" in doc["error"]
    summary = summarize_run(stats["worker_log"])
    assert summary["requests"][0]["status"] == "refused"


def test_graceful_drain_on_shutdown_signal(served, tmp_path):
    """SIGTERM mid-session: the in-flight request finishes, pending
    tickets stay queued, the worker log closes cleanly."""
    queue = SpoolQueue(tmp_path / "spool")
    rid1 = queue.submit_frames(*served["sim_b"],
                               options=REQUEST_OPTIONS)
    worker = ServeWorker(queue,
                         buckets=BucketSet(cells=(8, 16),
                                           loci=(64, 128)),
                         poll_interval=0.1)
    prev_term = signal.getsignal(signal.SIGTERM)
    prev_int = signal.getsignal(signal.SIGINT)
    worker.install_signal_handlers()
    result = {}

    def _run():
        result["stats"] = worker.run()

    thread = threading.Thread(target=_run, daemon=True)
    try:
        thread.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            doc = queue.status(rid1)
            if doc and doc["state"] == "done":
                break
            time.sleep(0.05)
        else:
            pytest.fail("first request never finished")
        # the shutdown signal FIRST (raise_signal runs the handler
        # synchronously in this thread, so _draining is set before the
        # submit commits), THEN a late request — the worker's loop
        # checks the drain flag before claiming, so ordering the other
        # way would race its 50 ms poll against the submit
        signal.raise_signal(signal.SIGTERM)
        rid2 = queue.submit_frames(*served["sim_b"],
                                   options=REQUEST_OPTIONS)
        thread.join(timeout=30)
        assert not thread.is_alive(), "worker did not drain"
    finally:
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)
    stats = result["stats"]
    assert stats["drained"] is True
    assert stats["processed"] == 1
    # assert on the QUEUE, not the worker's pending_left snapshot: the
    # drained worker may read its stats in the instant before the late
    # submit's atomic rename commits — the durable fact is that the
    # ticket is still pending and untouched after the worker is gone
    assert queue.depth() == 1
    assert queue.status(rid2)["state"] == "pending"
    assert validate_run(stats["worker_log"]) == []
