"""pertlint-flow: the interprocedural SPMD/program-identity layer.

Three strata, mirroring test_pertlint_deep:

* fixture-unit — every FL rule catches its seeded defect in
  tests/pertlint_fixtures/flow_pkg (including the PR-11 verdict-gated
  allgather reconstruction), pinned line-exactly by ``expect:``
  comments, and the negative (``*_ok``) cases stay clean;
* contract — the identity map covers the deep registry exactly, the
  committed ``artifacts/PROGRAM_IDENTITY.json`` is current,
  schema-valid and fully hash-covered, and the NON_HASH_FIELDS
  exclusion contract is single-sourced: statically readable by the
  flow engine AND honoured by the run-log config digest;
* the gate — ``python -m tools.pertlint --flow`` exits 0 on HEAD with
  every baselined flow finding carrying a rationale.
"""

import dataclasses
import json
import pathlib
import re
import subprocess
import sys

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from tools.pertlint.deep import entrypoints  # noqa: E402
from tools.pertlint.flow.engine import (  # noqa: E402
    ENTRY_JIT,
    _SYNTHETIC_ENTRIES,
    build_flow_context,
    flow_lint,
    non_hash_fields_of,
    run_flow_rules,
)
from tools.pertlint.flow.identity import SCHEMA  # noqa: E402

BASELINE = REPO_ROOT / "tools" / "pertlint" / "baseline.json"
ARTIFACT = REPO_ROOT / "artifacts" / "PROGRAM_IDENTITY.json"
FIXTURE_PKG = REPO_ROOT / "tests" / "pertlint_fixtures" / "flow_pkg"

_EXPECT = re.compile(r"expect:\s*((?:PL|DP|FL)\d{3})")


def _expected_findings():
    out = set()
    for f in sorted(FIXTURE_PKG.glob("*.py")):
        for i, line in enumerate(f.read_text().splitlines(), 1):
            m = _EXPECT.search(line)
            if m:
                out.add((f.name, i, m.group(1)))
    return out


@pytest.fixture(scope="module")
def fixture_run():
    ctx = build_flow_context(package_root=FIXTURE_PKG,
                             registry_names=None)
    findings, stats = run_flow_rules(ctx=ctx)
    return ctx, findings, stats


@pytest.fixture(scope="module")
def head_ctx():
    return build_flow_context()


# -- fixture-unit: one seeded defect per FL rule --------------------------

def test_every_fl_rule_catches_its_seeded_defect(fixture_run):
    """Line-exact: the findings are precisely the ``expect:`` set —
    nothing missed (rules fire) and nothing extra (negatives clean)."""
    _, findings, _ = fixture_run
    got = {(pathlib.Path(f.path).name, f.line, f.rule) for f in findings}
    expected = _expected_findings()
    assert expected, "fixture package lost its expect comments"
    assert {r for _, _, r in expected} == \
        {"FL001", "FL002", "FL003", "FL004", "FL005", "FL006"}
    missing = expected - got
    unexpected = got - expected
    assert not missing, f"rules failed to fire: {sorted(missing)}"
    assert not unexpected, f"false positives: {sorted(unexpected)}"


def test_pr11_verdict_gated_allgather_reconstruction(fixture_run):
    """The PR-11 deadlock class specifically: an allgather gated on a
    rank-derived local verdict is caught as FL001."""
    _, findings, _ = fixture_run
    hits = [f for f in findings if f.rule == "FL001"
            and "verdict_gated_allgather" in f.message
            and "process_allgather" in f.message]
    assert len(hits) == 1, [f.message for f in findings
                            if f.rule == "FL001"]


def test_interprocedural_collective_closure(fixture_run):
    """Guarding a CALL that reaches a collective is as divergent as
    guarding the primitive — the closure, not just the roots."""
    _, findings, _ = fixture_run
    hits = [f for f in findings if f.rule == "FL001"
            and "leader_only_barrier" in f.message]
    assert len(hits) == 1


def test_negative_cases_stay_clean(fixture_run):
    """count-uniform guards and provably-single-process branches are
    the soundness edge: flagging them would poison the real gate."""
    _, findings, _ = fixture_run
    bad = [f.message for f in findings
           if "count_guarded_sync_ok" in f.message
           or "count_branch_order_ok" in f.message
           or "fetch_single_world_ok" in f.message]
    assert bad == []


def test_inline_suppression_applies_to_flow_findings():
    """``# pertlint: disable=FL001`` drops the finding in flow_lint,
    exactly like the AST and deep layers."""
    result, _, _ = flow_lint(select={"FL001"}, package_root=FIXTURE_PKG)
    sup = [f for f in result.suppressed if "suppressed_sync" in f.message]
    assert len(sup) == 1
    assert all("suppressed_sync" not in f.message for f in result.new)


def test_fixture_identity_verdicts(fixture_run):
    """The three verdict values, one fixture jit function each."""
    _, _, stats = fixture_run
    assert stats.verdicts["_render"] == "leak"
    assert stats.verdicts["_kernel"] == "incomplete"
    assert stats.verdicts["_stepper"] == "covered"


def test_fixture_non_hash_fields_read_statically(fixture_run):
    ctx, _, _ = fixture_run
    assert ctx.non_hash_fields == ("telemetry_path", "request_id")


# -- contract: identity map, artifact, NON_HASH_FIELDS --------------------

def test_identity_map_covers_registry_exactly():
    """A new deep entry point without an identity mapping fails loudly
    here (and would gate as FL004 via the _unmapped row)."""
    mapped = set(ENTRY_JIT) | set(_SYNTHETIC_ENTRIES)
    assert mapped == set(entrypoints.REGISTRY), \
        (sorted(mapped), sorted(entrypoints.REGISTRY))
    assert not set(ENTRY_JIT) & set(_SYNTHETIC_ENTRIES)


def test_program_identity_artifact_schema_and_roundtrip():
    doc = json.loads(ARTIFACT.read_text())
    assert doc["schema"] == SCHEMA
    assert doc["package"] == "scdna_replication_tools_tpu"
    assert doc["jit_cache_key_includes_jax_version"] is True
    assert [e["name"] for e in doc["entries"]] == \
        list(entrypoints.REGISTRY)
    for e in doc["entries"]:
        assert e["verdict"] in ("covered", "leak", "incomplete")
        assert isinstance(e["line"], int) and e["line"] >= 1
        assert e["identity_inputs"], e["name"]
        for inp in e["identity_inputs"]:
            assert inp["classification"] in ("covered", "leak",
                                             "incomplete")
            assert inp["provenance"] == sorted(inp["provenance"])
    # round-trips bit-exactly through json
    assert json.loads(json.dumps(doc)) == doc


def test_program_identity_artifact_is_current_and_covered(head_ctx):
    """The committed certificate equals a fresh regeneration (no drift)
    and every registered entry point is hash-covered on HEAD — the
    AOT-cache-key soundness claim this PR certifies."""
    committed = json.loads(ARTIFACT.read_text())
    assert committed == head_ctx.identity_report
    assert all(e["verdict"] == "covered" for e in committed["entries"]), \
        {e["name"]: e["verdict"] for e in committed["entries"]}


def test_non_hash_fields_contract_single_sourced(head_ctx):
    """config.NON_HASH_FIELDS is one literal tuple: statically readable
    by the flow engine, real PertConfig fields, and echoed into the
    committed certificate."""
    from scdna_replication_tools_tpu.config import (
        NON_HASH_FIELDS,
        PertConfig,
    )
    assert non_hash_fields_of(head_ctx.graph) == NON_HASH_FIELDS
    field_names = {f.name for f in dataclasses.fields(PertConfig)}
    assert set(NON_HASH_FIELDS) <= field_names
    doc = json.loads(ARTIFACT.read_text())
    assert doc["non_hash_fields"] == sorted(NON_HASH_FIELDS)


def test_config_digest_invariant_to_non_hash_fields():
    """Satellite contract: moving EVERY excluded field leaves the run
    digest unchanged; moving a behavioural field changes it."""
    from scdna_replication_tools_tpu.config import (
        NON_HASH_FIELDS,
        PertConfig,
    )
    from scdna_replication_tools_tpu.obs.runlog import _config_digest

    base = PertConfig()
    moved = dataclasses.replace(
        base, telemetry_path="/elsewhere/run.ndjson",
        metrics_textfile="/elsewhere/metrics.prom",
        request_id="req-42", trace_spans=True, trace_parent="aaaa:bbbb",
        slab_width=4, executable_cache_dir="/elsewhere/exec_cache",
        heartbeat_dir="/elsewhere/health",
        heartbeat_interval_seconds=1.5)
    # the replacement above must exercise EVERY declared excluded field
    changed = {f for f in NON_HASH_FIELDS
               if getattr(moved, f) != getattr(base, f)}
    assert changed == set(NON_HASH_FIELDS)
    assert _config_digest(base) == _config_digest(moved)
    behavioural = dataclasses.replace(base, max_iter=base.max_iter + 1)
    assert _config_digest(base) != _config_digest(behavioural)


def test_head_collective_fabric_is_seen(head_ctx):
    """FL001's clean verdict must mean 'guards are sound', not 'the
    analysis went blind': the barrier/checkpoint/consensus fabric is
    visible as collective sites and a non-trivial reachable set."""
    g = head_ctx.graph
    sites = [s for fn in g.functions.values()
             for s in g.collective_sites(fn)]
    assert len(sites) >= 10, len(sites)
    assert len(g.collective_bearing) >= 5
    assert len(g.multiprocess_reachable) > len(g.collective_bearing)
    assert not g.parse_errors, g.parse_errors


# -- the aot_disk_key certificate row (schema v2) --------------------------

def test_aot_disk_key_row_present_and_covered(head_ctx):
    """The persistent executable store's digest contract is certified:
    every declared KEY_COMPONENT has covered provenance, and the
    committed artifact carries the row."""
    from scdna_replication_tools_tpu.infer import aotcache

    row = head_ctx.identity_report.get("aot_disk_key")
    assert row is not None
    assert row["verdict"] == "covered", row
    assert row["components"] == list(aotcache.KEY_COMPONENTS)
    assert {i["name"] for i in row["identity_inputs"]} == \
        set(aotcache.KEY_COMPONENTS)
    committed = json.loads(ARTIFACT.read_text())
    assert committed["aot_disk_key"] == row
    # the store location itself must be hash-excluded (the digest
    # embeds the config hash — hashing the location would
    # self-invalidate a relocated store)
    from scdna_replication_tools_tpu.config import NON_HASH_FIELDS
    assert "executable_cache_dir" in NON_HASH_FIELDS


def test_aot_disk_key_drift_gates_as_fl004():
    """Two-way drift detection: a certified component missing from the
    declared KEY_COMPONENTS (or vice versa) degrades to ``unknown:``
    provenance and fires FL004 on the aot row."""
    from tools.pertlint.flow import engine as eng
    from tools.pertlint.flow import rules_flow

    ctx = build_flow_context()
    row = ctx.identity_report["aot_disk_key"]
    # simulate a component the store stopped digesting
    broken = dict(row)
    broken["components"] = [c for c in row["components"]
                            if c != "device-kind"]
    broken["identity_inputs"] = [
        i for i in row["identity_inputs"] if i["name"] != "device-kind"
    ] + [{"name": "device-kind",
          "provenance": ["unknown:certified component 'device-kind' is "
                         "missing from infer/aotcache.py KEY_COMPONENTS"],
          "classification": "incomplete"}]
    ctx.identity_report["aot_disk_key"] = broken
    rule = next(r for r in eng._flow_rules() if r.id == "FL004")
    hits = [f for f in rule.check(ctx) if "[aot_disk_key]" in f.message]
    assert len(hits) == 1, [f.message for f in rule.check(ctx)]
    assert "device-kind" in hits[0].message
    # engine-side: the provenance map itself cross-checks the literal
    assert set(eng._AOT_KEY_PROVENANCE) == set(row["components"])
    assert rules_flow._certified_rows(ctx.identity_report)[-1] is broken


def test_aot_disk_key_slab_width_never_in_provenance(head_ctx):
    """The slab<W> tag's width is covered by the abstract signature,
    not by the hash-excluded config:slab_width placement field — a
    config:slab_width atom would classify as a FL003 leak."""
    row = head_ctx.identity_report["aot_disk_key"]
    atoms = {a for i in row["identity_inputs"] for a in i["provenance"]}
    assert "config:slab_width" not in atoms
    assert "config:executable_cache_dir" not in atoms


# -- the gate -------------------------------------------------------------

def test_flow_gate_is_clean_on_head():
    """THE gate, in-process: zero unbaselined flow findings, every
    baselined one rationalized, every entry point hash-covered."""
    result, stats, _ = flow_lint(baseline_path=BASELINE)
    assert result.new == [], [f.render() for f in result.new]
    assert stats.unrationalized == []
    assert set(stats.verdicts.values()) == {"covered"}, stats.verdicts


def test_flow_cli_gate_subprocess(tmp_path):
    """Exactly as CI runs it: ``python -m tools.pertlint --flow``."""
    out = tmp_path / "PROGRAM_IDENTITY.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.pertlint", "--flow",
         "--baseline", str(BASELINE), "--identity-out", str(out)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "entry points certified" in proc.stdout
    doc = json.loads(out.read_text())
    assert doc["schema"] == SCHEMA


def test_baselined_flow_findings_carry_rationale():
    """Zero unexplained flow entries; error-severity FL findings must
    be FIXED, not baselined — only the FL006 warning inventory (the
    ROADMAP multi-host work list) may be grandfathered."""
    entries = json.loads(BASELINE.read_text())["findings"]
    fl = [e for e in entries if e["rule"].startswith("FL")]
    assert fl, "expected the FL006 host-fetch inventory in the baseline"
    assert {e["rule"] for e in fl} == {"FL006"}
    for e in fl:
        assert e.get("rationale"), f"FL entry without rationale: {e}"
