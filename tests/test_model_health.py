"""Inference-health observability: convergence doctor, posterior
confidence, posterior-predictive QC, and their telemetry surface.

The acceptance surface of the model-health PR:

* the convergence doctor classifies synthetic loss tails correctly
  (flat / oscillating / diverging / budget-exhausted / NaN);
* posterior entropy maps are exact at the two analytic corners
  (uniform posterior -> 1, certain posterior -> 0) and ride the decode
  slabs without changing the MAP planes;
* a pipeline run with QC enabled emits schema-valid ``fit_health`` and
  ``cell_qc_summary`` events, renders a "Model health" report section,
  and flags a deliberately pathological cell (reads scrambled across
  bins) while leaving clean cells unflagged;
* the schema file and the SCHEMA_VERSION constant cannot drift apart,
  and the summary aggregation tolerates unknown future event kinds;
* all diagnostics together add <5% wall to the step-2 fit (bench
  guard, same pattern as the PR-4 ring-buffer guard).
"""

import json
import time

import numpy as np
import jax
import jax.numpy as jnp
import pandas as pd
import pytest

from scdna_replication_tools_tpu.api import scRT
from scdna_replication_tools_tpu.infer import svi
from scdna_replication_tools_tpu.infer.runner import _PertLossFn
from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    decode_discrete,
    entropy_from_joint,
    init_params,
    posterior_entropy,
    ppc_discrepancy,
)
from scdna_replication_tools_tpu.obs import (
    SCHEMA_VERSION,
    VERDICTS,
    classify_loss_tail,
    diagnose_fit,
    summarize_events,
    validate_run,
)
from scdna_replication_tools_tpu.obs.doctor import tail_stats
from scdna_replication_tools_tpu.obs.schema import load_schema
from scdna_replication_tools_tpu.ops.gc import gc_features


# ---------------------------------------------------------------------------
# convergence doctor
# ---------------------------------------------------------------------------

def _descent(n=50, hi=2000.0, lo=1000.0):
    return np.linspace(hi, lo, n)


def test_doctor_flat_tail_is_converged():
    losses = np.r_[_descent(), np.full(30, 1000.0)]
    verdict, stats = classify_loss_tail(losses)
    assert verdict == "converged"
    assert abs(stats["drift"]) < 1e-6


def test_doctor_oscillating_tail():
    tail = 1000.0 + 50.0 * (-1.0) ** np.arange(30)
    verdict, stats = classify_loss_tail(np.r_[_descent(), tail])
    assert verdict == "oscillating"
    assert stats["rel_var"] > 0.01


def test_doctor_oscillation_verdict_is_phase_invariant():
    """A pure alternation fits a small least-squares slope whose SIGN
    depends only on window parity; neither phase may read as
    'diverging' (wrong remediation: post-mortem instead of lower LR)."""
    for phase in (0, 1):
        tail = 1000.0 + 50.0 * (-1.0) ** (np.arange(30) + phase)
        verdict, _ = classify_loss_tail(np.r_[_descent(), tail])
        assert verdict == "oscillating", (phase, verdict)


def test_doctor_rising_tail_is_diverging():
    losses = np.r_[_descent(), np.linspace(1000.0, 1500.0, 16)]
    verdict, stats = classify_loss_tail(losses)
    assert verdict == "diverging"
    assert stats["drift"] > 0.1


def test_doctor_budget_exhausted_descent_is_plateaued():
    """A tail still steeply descending at the stop: the iteration budget
    ended the fit, not the objective."""
    verdict, stats = classify_loss_tail(_descent(n=80))
    assert verdict == "plateaued"
    assert stats["drift"] < -0.01


def test_doctor_nan_tail_is_diverging():
    losses = np.r_[_descent(), [np.nan]]
    verdict, stats = classify_loss_tail(losses)
    assert verdict == "diverging"
    assert stats["finite"] is False


def test_doctor_too_few_samples_is_unknown():
    assert classify_loss_tail([1.0, 2.0])[0] == "unknown"
    report = diagnose_fit([1.0])
    assert report["verdict"] == "unknown"
    assert "too few" in report["reason"]


@pytest.mark.parametrize("n", [0, 1])
def test_doctor_degenerate_tails_are_unknown(n):
    """The adaptive controller feeds IN-FLIGHT partial trajectories to
    the doctor; the empty and single-sample tails must read unknown,
    never index out of range or divide by zero (sxx is 0 at n=1)."""
    losses = [1000.0] * n
    assert tail_stats(losses) is None
    assert classify_loss_tail(losses)[0] == "unknown"
    report = diagnose_fit(losses)
    assert report["verdict"] == "unknown"
    # and the same with gradient evidence present — grad health alone
    # must not invent a verdict out of a signal-free tail
    report = diagnose_fit(losses, grad_norm_first=100.0,
                          grad_norm_last=1.0)
    assert report["verdict"] == "unknown"


def test_doctor_min_samples_raises_the_evidence_bar():
    """K-1 samples under a demanded min_samples=K read unknown; the
    full K flip to a real verdict (the controller passes its window
    length so it never acts on a part-filled window)."""
    K = 16
    flat = [1000.0] * (K - 1)
    assert tail_stats(flat, window=K, min_samples=K) is None
    assert classify_loss_tail(flat, window=K, min_samples=K)[0] \
        == "unknown"
    assert diagnose_fit(flat, window=K, min_samples=K)["verdict"] \
        == "unknown"
    full = np.r_[np.linspace(2000.0, 1000.0, 40), [1000.0] * K]
    assert diagnose_fit(full, window=K, min_samples=K)["verdict"] \
        == "converged"
    # min_samples below the absolute floor is clamped, not honoured
    assert tail_stats([1.0, 2.0], min_samples=0) is None


def test_doctor_grad_norm_demotes_flat_to_plateaued():
    """Flat loss + undecayed gradient = stalled optimisation, not rest;
    a decayed gradient keeps the converged verdict."""
    losses = np.r_[_descent(), np.full(30, 1000.0)]
    stuck = diagnose_fit(losses, converged=False,
                         grad_norm_first=100.0, grad_norm_last=90.0)
    assert stuck["verdict"] == "plateaued"
    assert stuck["grad_decay"] == pytest.approx(0.9)
    rested = diagnose_fit(losses, converged=False,
                          grad_norm_first=100.0, grad_norm_last=1.0)
    assert rested["verdict"] == "converged"
    # the fit loop's own criterion firing always reads converged
    flagged = diagnose_fit(losses, converged=True,
                           grad_norm_first=100.0, grad_norm_last=90.0)
    assert flagged["verdict"] == "converged"


def test_doctor_nan_abort_flag_overrides():
    report = diagnose_fit(np.full(40, 1000.0), nan_abort=True)
    assert report["verdict"] == "diverging"
    assert "NaN" in report["reason"]


# ---------------------------------------------------------------------------
# schema-consistency + forward-compat guards
# ---------------------------------------------------------------------------

def test_schema_file_version_matches_constant():
    """The checked-in schema document and the SCHEMA_VERSION constant
    stamped into run_start must be the same number — a bump in one
    without the other would mislabel every artifact."""
    assert load_schema()["schema_version"] == SCHEMA_VERSION


def test_schema_knows_model_health_events_and_verdicts():
    schema = load_schema()
    kinds = set(schema["properties"]["event"]["enum"])
    assert {"fit_health", "cell_qc_summary"} <= kinds
    verdict_enum = set(
        schema["definitions"]["fit_health"]["properties"]["verdict"]["enum"])
    assert verdict_enum == set(VERDICTS)


def test_summarize_events_tolerates_unknown_kinds():
    """Forward compat: a v3 log with event kinds this build has never
    heard of must still summarise — unknown kinds are ignored, not a
    reason to drop the whole summary."""
    events = [
        {"event": "run_start", "seq": 0, "t": 0.0, "schema_version": 99,
         "run_name": "future", "pid": 1},
        {"event": "quantum_flux_report", "seq": 1, "t": 0.1, "flux": 42},
        {"event": "fit_end", "seq": 2, "t": 0.2, "step": "step2",
         "iters": 5, "converged": True, "nan_abort": False,
         "wall_seconds": 0.5},
        {"event": "run_end", "seq": 3, "t": 0.3, "status": "ok",
         "wall_seconds": 0.3, "events_emitted": 3},
    ]
    summary = summarize_events(events)
    assert summary["status"] == "ok"
    assert [f["step"] for f in summary["fits"]] == ["step2"]
    assert summary["num_events"] == 4
    assert summary["fit_health"] == [] and summary["cell_qc"] == []


# ---------------------------------------------------------------------------
# posterior-confidence maps
# ---------------------------------------------------------------------------

def test_entropy_uniform_and_certain_corners():
    joint = jnp.zeros((2, 3, 5, 2))          # uniform posterior
    cn_ent, rep_ent = entropy_from_joint(joint)
    np.testing.assert_allclose(np.asarray(cn_ent), 1.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(rep_ent), 1.0, atol=1e-6)
    peaked = joint.at[..., 2, 1].set(80.0)   # one state takes all mass
    cn_ent, rep_ent = entropy_from_joint(peaked)
    np.testing.assert_allclose(np.asarray(cn_ent), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(rep_ent), 0.0, atol=1e-5)


def test_entropy_handles_hard_minus_inf_logits():
    """A state with exactly zero probability (logit -inf) contributes 0
    to the entropy, not NaN."""
    joint = jnp.full((1, 1, 3, 2), -jnp.inf).at[0, 0, :2, 0].set(0.0)
    cn_ent, rep_ent = entropy_from_joint(joint)
    assert np.isfinite(np.asarray(cn_ent)).all()
    # two equally likely CN states out of 3: H = log2/log3
    np.testing.assert_allclose(np.asarray(cn_ent)[0, 0],
                               np.log(2) / np.log(3), atol=1e-6)
    np.testing.assert_allclose(np.asarray(rep_ent)[0, 0], 0.0, atol=1e-6)


SPEC = PertModelSpec(P=5, K=2, L=1, tau_mode="param", fixed_lamb=True)


def _problem(num_cells=8, num_loci=30, seed=0):
    rng = np.random.default_rng(seed)
    reads = rng.poisson(40, (num_cells, num_loci)).astype(np.float32)
    gammas = rng.uniform(0.35, 0.6, num_loci).astype(np.float32)
    etas = np.ones((num_cells, num_loci, SPEC.P), np.float32)
    etas[:, :, 2] = 100.0
    batch = PertBatch(
        reads=jnp.asarray(reads),
        libs=jnp.zeros(num_cells, jnp.int32),
        gamma_feats=gc_features(jnp.asarray(gammas), SPEC.K),
        mask=jnp.ones((num_cells,), jnp.float32),
        etas=jnp.asarray(etas),
    )
    fixed = {"lamb": jnp.asarray(0.3, jnp.float32)}
    params0 = init_params(SPEC, batch, fixed,
                          t_init=np.full(num_cells, 0.4, np.float32))
    return params0, fixed, batch


def test_decode_with_entropy_extends_not_changes_the_planes():
    params0, fixed, batch = _problem()
    base = decode_discrete(SPEC, params0, fixed, batch)
    extended = decode_discrete(SPEC, params0, fixed, batch,
                               want_entropy=True)
    assert len(base) == 3 and len(extended) == 5
    for a, b in zip(base, extended[:3]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    cn_ent, rep_ent = (np.asarray(extended[3]), np.asarray(extended[4]))
    assert cn_ent.shape == batch.reads.shape == rep_ent.shape
    assert ((cn_ent >= 0) & (cn_ent <= 1)).all()
    assert ((rep_ent >= 0) & (rep_ent <= 1)).all()
    pe = posterior_entropy(SPEC, params0, fixed, batch)
    np.testing.assert_array_equal(np.asarray(pe[0]), cn_ent)


def test_hmm_decode_entropy_matches_independent_decode():
    """The Viterbi decode's entropy side-channel (computed from its own
    per-slab joint, no second enumeration) must equal the confidence
    maps of the independent decode — entropy is a property of the
    posterior, not of the decoding rule."""
    from scdna_replication_tools_tpu.models.pert import decode_discrete_hmm

    params0, fixed, batch = _problem()
    restart = jnp.ones((batch.reads.shape[1],), jnp.float32)
    out = decode_discrete_hmm(SPEC, params0, fixed, batch, restart,
                              self_prob=0.9, want_entropy=True)
    assert len(out) == 5
    # jitted slab vs eager joint: f32 fusion rounding, ~4e-7 absolute
    pe = posterior_entropy(SPEC, params0, fixed, batch)
    np.testing.assert_allclose(np.asarray(out[3]), np.asarray(pe[0]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out[4]), np.asarray(pe[1]),
                               rtol=1e-5, atol=1e-6)


def test_decode_entropy_slabbed_matches_single_pass():
    params0, fixed, batch = _problem(num_cells=9)
    one = decode_discrete(SPEC, params0, fixed, batch, want_entropy=True)
    slabbed = decode_discrete(SPEC, params0, fixed, batch, cell_chunk=4,
                              want_entropy=True)
    for a, b in zip(one, slabbed):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# posterior-predictive check
# ---------------------------------------------------------------------------

def test_ppc_scrambled_cell_scores_extreme():
    """A cell whose reads are randomly permuted across bins no longer
    tracks its own fitted GC/CN structure: its observed deviance must
    sit far above the replicate distribution while intact cells stay
    within it."""
    params0, fixed, batch = _problem(num_cells=6, num_loci=80)
    # make reads structured (so a permutation destroys real signal):
    # strong GC-correlated rate via the model's own omega at params0
    rng = np.random.default_rng(3)
    loci_rate = 20.0 + 60.0 * np.linspace(0, 1, 80)
    reads = rng.poisson(loci_rate, (6, 80)).astype(np.float32)
    reads[0] = rng.permutation(reads[0])
    batch = PertBatch(
        reads=jnp.asarray(reads), libs=batch.libs,
        gamma_feats=batch.gamma_feats, mask=batch.mask, etas=batch.etas,
    )
    fit = svi.fit_map(_PertLossFn(spec=SPEC), params0, (fixed, batch),
                      max_iter=150, min_iter=50)
    _, z = ppc_discrepancy(SPEC, fit.params, fixed, batch,
                           jax.random.PRNGKey(0), num_replicates=8)
    z = np.asarray(z)
    assert np.argmax(z) == 0, f"scrambled cell not the PPC extreme: {z}"
    assert z[0] > 3.0


def test_ppc_slabbed_deviance_matches_single_pass():
    params0, fixed, batch = _problem(num_cells=9)
    dev_one, _ = ppc_discrepancy(SPEC, params0, fixed, batch,
                                 jax.random.PRNGKey(1), num_replicates=4)
    dev_slab, _ = ppc_discrepancy(SPEC, params0, fixed, batch,
                                  jax.random.PRNGKey(1), num_replicates=4,
                                  cell_chunk=4)
    # the OBSERVED deviance is draw-independent — it must agree exactly
    # across slabbings (z differs: slabs fold the key differently)
    np.testing.assert_allclose(np.asarray(dev_one), np.asarray(dev_slab),
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# fit verdict wiring
# ---------------------------------------------------------------------------

def test_fit_map_surfaces_verdict_and_health():
    params0, fixed, batch = _problem()
    fit = svi.fit_map(_PertLossFn(spec=SPEC), params0, (fixed, batch),
                      max_iter=30, min_iter=10, diag_every=5)
    assert fit.verdict in VERDICTS
    assert fit.health["verdict"] == fit.verdict
    assert fit.health["reason"]
    assert fit.health["grad_decay"] is not None  # ring buffer sampled


# ---------------------------------------------------------------------------
# end-to-end: pathological cell flagged, events + report rendered
# ---------------------------------------------------------------------------

BAD_CELL = "s_A_0"


@pytest.fixture(scope="module")
def qc_run(synthetic_frames, tmp_path_factory):
    """One pipeline run with QC on and one deliberately pathological
    S cell: its reads are scrambled across bins, destroying the
    GC/CN-correlated structure every other cell carries."""
    df_s, df_g = (df.copy() for df in synthetic_frames)
    rng = np.random.default_rng(0)
    for df in (df_s, df_g):
        df["reads"] = rng.poisson(
            20 * df["true_somatic_cn"].to_numpy()).astype(float)
        df["state"] = df["true_somatic_cn"].astype(int)
        df["copy"] = df["true_somatic_cn"]
    mask = df_s.cell_id == BAD_CELL
    df_s.loc[mask, "reads"] = rng.permutation(
        df_s.loc[mask, "reads"].to_numpy())

    log_path = tmp_path_factory.mktemp("qc") / "qc_run.jsonl"
    scrt = scRT(df_s, df_g, clone_col="clone_id",
                cn_prior_method="g1_clones", max_iter=60, min_iter=20,
                run_step3=True, telemetry_path=str(log_path),
                fit_diag_every=5)
    out, supp, _, _ = scrt.infer(level="pert")
    return scrt, out, log_path


def test_pathological_cell_flagged_clean_cells_not(qc_run):
    scrt, _, _ = qc_run
    qc = scrt.cell_qc()
    assert isinstance(qc, pd.DataFrame)
    assert len(qc) == 24
    bad = qc.loc[qc.cell_id == BAD_CELL].iloc[0]
    assert not bad.qc_pass
    assert "ppc_outlier" in bad.qc_flags
    # the scrambled cell is the PPC extreme by a wide margin
    assert bad.ppc_z == qc.ppc_z.max()
    clean = qc.loc[qc.cell_id != BAD_CELL]
    # no intact cell reads as a PPC outlier...
    assert not clean.qc_flags.str.contains("ppc_outlier").any()
    # ...and the cohort is not blanket-flagged (boundary-tau flags on a
    # few genuinely extreme-tau cells are legitimate)
    assert clean.qc_pass.mean() > 0.75


def test_qc_table_columns_and_ranges(qc_run):
    scrt, out, _ = qc_run
    qc = scrt.cell_qc()
    for col in ("cell_id", "model_tau", "mean_cn_entropy",
                "max_cn_entropy", "frac_low_conf", "mean_rep_entropy",
                "ppc_deviance", "ppc_z", "rescue_candidate",
                "rescue_accepted", "qc_flags", "qc_pass"):
        assert col in qc.columns, col
    assert ((qc.mean_cn_entropy >= 0) & (qc.mean_cn_entropy <= 1)).all()
    assert ((qc.frac_low_conf >= 0) & (qc.frac_low_conf <= 1)).all()
    # the long output carries the per-bin posterior-confidence map
    assert "model_cn_entropy" in out.columns
    assert out.model_cn_entropy.between(0, 1).all()


def test_qc_run_emits_schema_valid_health_events(qc_run):
    _, _, log_path = qc_run
    assert validate_run(log_path) == []
    events = [json.loads(line)
              for line in log_path.read_text().splitlines() if line.strip()]
    assert events[0]["schema_version"] == SCHEMA_VERSION
    health = [ev for ev in events if ev["event"] == "fit_health"]
    assert {ev["step"] for ev in health} == {"step1", "step2", "step3"}
    assert all(ev["verdict"] in VERDICTS for ev in health)
    qc_events = [ev for ev in events if ev["event"] == "cell_qc_summary"]
    assert len(qc_events) == 1
    ev = qc_events[0]
    assert ev["num_cells"] == 24
    assert ev["num_flagged"] >= 1
    flagged_ids = {c["cell_id"] for c in ev["flagged_cells"]}
    assert BAD_CELL in flagged_ids
    assert sum(ev["entropy_hist"]) == 24
    assert "ppc_outlier" in ev["flag_counts"]


def test_pert_report_renders_model_health_section(qc_run, tmp_path):
    _, _, log_path = qc_run
    import pathlib
    import subprocess
    import sys
    repo = pathlib.Path(__file__).resolve().parents[1]
    out_md = tmp_path / "report.md"
    proc = subprocess.run(
        [sys.executable, str(repo / "tools" / "pert_report.py"),
         str(log_path), "--out", str(out_md)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    report = out_md.read_text()
    assert "## Model health" in report
    assert BAD_CELL in report          # flagged-cell table
    assert "ppc_outlier" in report
    assert "entropy histogram" in report


def test_qc_off_restores_bare_pipeline(synthetic_frames, tmp_path):
    """qc=False: no QC phases, no health events, no entropy column, and
    cell_qc() explains itself instead of returning stale data."""
    df_s, df_g = (df.copy() for df in synthetic_frames)
    rng = np.random.default_rng(1)
    for df in (df_s, df_g):
        df["reads"] = rng.poisson(40, len(df)).astype(float)
        df["state"] = df["true_somatic_cn"].astype(int)
        df["copy"] = df["true_somatic_cn"]
    log_path = tmp_path / "noqc.jsonl"
    scrt = scRT(df_s, df_g, clone_col="clone_id",
                cn_prior_method="g1_clones", max_iter=6, min_iter=3,
                run_step3=False, telemetry_path=str(log_path), qc=False)
    out, _, _, _ = scrt.infer(level="pert")
    assert "model_cn_entropy" not in out.columns
    events = [json.loads(line)
              for line in log_path.read_text().splitlines() if line.strip()]
    kinds = {ev["event"] for ev in events}
    assert "fit_health" not in kinds and "cell_qc_summary" not in kinds
    with pytest.raises(RuntimeError, match="qc"):
        scrt.cell_qc()


def test_cell_qc_before_infer_raises():
    scrt = scRT(pd.DataFrame({}), pd.DataFrame({}))
    with pytest.raises(RuntimeError, match="infer"):
        scrt.cell_qc()


# ---------------------------------------------------------------------------
# bench guard: all diagnostics on <5% step-2 fit overhead
# ---------------------------------------------------------------------------

def test_all_diagnostics_overhead_below_5_percent():
    """Acceptance bar: the full diagnostics stack (ring buffer sampling
    + post-fit decode + convergence doctor) must add <5% to the
    step-2-shaped fit wall.  Measures the WHOLE fit_map call (not just
    the device dispatch) so the doctor's host-side cost is included.
    Methodology as PR 4's ring-buffer guard: both programs pre-compiled,
    alternating timed calls, best-of-N, small absolute slack for timer
    jitter at sub-second walls."""
    svi.clear_program_cache()
    iters = 60

    def one_fit(diag_every, seed):
        params0, fixed, batch = _problem(num_cells=64, num_loci=256,
                                         seed=seed)
        t0 = time.perf_counter()
        fit = svi.fit_map(_PertLossFn(spec=SPEC), params0, (fixed, batch),
                          max_iter=iters, min_iter=iters,
                          diag_every=diag_every)
        wall = time.perf_counter() - t0
        assert fit.num_iters == iters
        assert fit.verdict in VERDICTS
        return wall

    one_fit(0, seed=0)   # compile both programs outside the
    one_fit(25, seed=0)  # timed region
    base, diag = [], []
    for rep in range(1, 6):
        base.append(one_fit(0, seed=rep))
        diag.append(one_fit(25, seed=rep))
    base_wall, diag_wall = min(base), min(diag)
    assert diag_wall <= base_wall * 1.05 + 0.015, \
        (f"full diagnostics stack costs "
         f"{(diag_wall / base_wall - 1):.1%} of the fit wall "
         f"(base {base_wall:.3f}s vs diag {diag_wall:.3f}s)")
