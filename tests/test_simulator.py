"""Simulator tests — the analog of the reference's de-facto integration
test (reference: test_with_pytest.py:11-78)."""

import numpy as np
import pandas as pd

from scdna_replication_tools_tpu.models.simulator import (
    convert_rt_units,
    pert_simulator,
)


def test_convert_rt_units():
    rt = np.array([0.0, 5.0, 10.0])
    out = convert_rt_units(rt)
    # largest raw values (latest in source units) map to 0
    np.testing.assert_allclose(out, [1.0, 0.5, 0.0])


def test_pert_simulator_output_columns(synthetic_frames):
    df_s, df_g = synthetic_frames
    sim_s, sim_g = pert_simulator(
        df_s, df_g, num_reads=50_000, rt_cols=["rt_A", "rt_B"],
        clones=["A", "B"], lamb=0.75, betas=[0.5, 0.0], a=10.0)

    for col in ["true_reads_norm", "true_reads_raw", "true_rep",
                "true_p_rep", "true_t", "true_total_cn"]:
        assert col in sim_s.columns, col
        assert col in sim_g.columns, col

    # G1 cells must be fully unreplicated (reference: test_with_pytest.py:69-78)
    assert (sim_g["true_rep"] == 0).all()
    assert (sim_g["true_t"] == 0).all()

    # every S cell's replication fraction in [0, 1]; taus spread over (0,1)
    fracs = sim_s.groupby("cell_id")["true_rep"].mean()
    assert fracs.between(0, 1).all()
    taus = sim_s.groupby("cell_id")["true_t"].first()
    assert taus.between(0, 1).all()
    assert taus.std() > 0.05

    # total CN doubles where replicated
    rep_rows = sim_s[sim_s["true_rep"] == 1]
    np.testing.assert_allclose(rep_rows["true_total_cn"],
                               rep_rows["true_somatic_cn"] * 2)

    # read counts roughly normalised to num_reads per cell
    per_cell = sim_s.groupby("cell_id")["true_reads_norm"].sum()
    assert (np.abs(per_cell - 50_000) < 500).all()


def test_simulator_replication_follows_tau(synthetic_frames):
    """Cells late in S phase (large tau) must have more replicated bins."""
    df_s, df_g = synthetic_frames
    sim_s, _ = pert_simulator(
        df_s, df_g, num_reads=50_000, rt_cols=["rt_A", "rt_B"],
        clones=["A", "B"], lamb=0.75, betas=[0.5, 0.0], a=10.0, seed=3)
    per_cell = sim_s.groupby("cell_id").agg(
        frac=("true_rep", "mean"), tau=("true_t", "first"))
    r = np.corrcoef(per_cell["frac"], per_cell["tau"])[0, 1]
    assert r > 0.9
