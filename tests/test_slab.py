"""Device-level slab program (`infer/svi._run_fit_chunk_slab`): the
vmapped twin of the chunk fit program that continuous batching packs
same-bucket requests into.

The contract under test, on a toy quadratic loss (the real loss would
only slow the pins down without changing the vmap semantics):

* **bit parity** — a slab of W blocks advances each block EXACTLY as W
  solo `_run_fit_chunk` dispatches would: params, losses and verdicts
  bit-identical per block;
* **frozen lanes** — a block whose ``stop == i0`` (retired/vacant) has
  an immediately-false loop condition: its carry passes through
  untouched, so a parked block costs nothing semantically;
* **refill** — ``slab_fill`` functionally replaces one block
  (``slab_pack``/``slab_block`` round-trip), and the refilled slab's
  next dispatch advances the fresh block from ITS state while the
  veterans continue from theirs;
* **pallas refusal** — ``fused_adam='pallas*'`` raises at trace time
  (the Pallas kernel's batching rule is unvalidated under vmap);
* **coordinator** — serve/slab.SlabFitCoordinator rendezvous-packs
  concurrent chunk dispatches: >= 2 same-signature calls advance on the
  vectorized program, a lone call stays bit-identical with serial via
  its solo program, and a slab-level failure degrades lane-by-lane.

Numerics caveat (the documented serving contract, see
OBSERVABILITY.md "Serving"): the vectorized program's fused update
chain may differ from the solo program by ~1 ulp per step —
value-dependent vector-width instruction selection on XLA:CPU — so
packed-lane assertions pin tight ``allclose`` tolerances, not bitwise
equality.  The bitwise pins below are the cases the system actually
guarantees bit-exact: parked-lane passthrough and solo dispatch.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scdna_replication_tools_tpu.infer.svi import (
    DIAG_RING,
    ChunkCall,
    _run_fit_chunk,
    _run_fit_chunk_slab,
    make_opt_state,
    slab_block,
    slab_fill,
    slab_pack,
)
from scdna_replication_tools_tpu.serve.slab import SlabFitCoordinator

MAX_ITER = 32
CONV_WINDOW = 8
N = 4  # toy parameter size


def _toy_loss(params, target):
    return jnp.sum((params["x"] - target) ** 2)


def _block_state(seed):
    """One block's full chunk-call state, deterministically from a
    seed — rebuildable at will because the chunk programs DONATE
    opt_state/losses/diag buffers."""
    rng = np.random.RandomState(seed)
    params = {"x": jnp.asarray(rng.randn(N), jnp.float32)}
    opt_state = make_opt_state(params, learning_rate=0.05)
    losses = jnp.zeros((MAX_ITER,), jnp.float32)
    diag = jnp.zeros((DIAG_RING, 3), jnp.float32)
    target = jnp.asarray(rng.randn(N), jnp.float32)
    return params, opt_state, losses, diag, target


def _solo_chunk(seed, i0, stop, min_iter=4):
    params, opt_state, losses, diag, target = _block_state(seed)
    return _run_fit_chunk(
        _toy_loss, params, opt_state, losses, diag,
        jnp.asarray(i0), jnp.asarray(stop), jnp.asarray(min_iter),
        jnp.asarray(1e-9), jnp.asarray(0.05), (target,),
        conv_window=CONV_WINDOW, b1=0.8, b2=0.99, diag_every=0)


def _slab_chunk(seeds, i0s, stops, min_iters=None):
    states = [_block_state(s) for s in seeds]
    params = slab_pack([st[0] for st in states])
    opt_state = slab_pack([st[1] for st in states])
    losses = slab_pack([st[2] for st in states])
    diag = slab_pack([st[3] for st in states])
    targets = slab_pack([(st[4],) for st in states])
    min_iters = min_iters or [4] * len(seeds)
    return _run_fit_chunk_slab(
        _toy_loss, params, opt_state, losses, diag,
        list(i0s), list(stops), list(min_iters),
        [1e-9] * len(seeds), [0.05] * len(seeds), targets,
        conv_window=CONV_WINDOW, b1=0.8, b2=0.99, diag_every=0)


def test_slab_blocks_match_solo_chunks():
    seeds = (3, 11, 29)
    out = _slab_chunk(seeds, i0s=[0, 0, 0], stops=[16, 16, 16])
    i_s, params_s, _, losses_s, _, conv_s, nan_s = out
    for b, seed in enumerate(seeds):
        i, params, _, losses, _, conv, is_nan = _solo_chunk(seed, 0, 16)
        assert int(i_s[b]) == int(i)
        # packed lanes: ulp tolerance (module docstring), not bitwise
        np.testing.assert_allclose(np.asarray(params_s["x"][b]),
                                   np.asarray(params["x"]),
                                   rtol=0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(losses_s[b]),
                                   np.asarray(losses),
                                   rtol=1e-5, atol=1e-5)
        assert bool(conv_s[b]) == bool(conv)
        assert bool(nan_s[b]) == bool(is_nan)


def test_slab_parks_retired_lane_untouched():
    # lane 1 retired: stop == i0 -> its cond is immediately false and
    # the carry must come back bit-identical while lanes 0/2 advance
    seeds = (3, 11, 29)
    out = _slab_chunk(seeds, i0s=[0, 5, 0], stops=[16, 5, 16])
    _, params_s, _, losses_s, _, _, _ = out
    parked_params, _, parked_losses, _, _ = _block_state(11)
    np.testing.assert_array_equal(np.asarray(params_s["x"][1]),
                                  np.asarray(parked_params["x"]))
    np.testing.assert_array_equal(np.asarray(losses_s[1]),
                                  np.asarray(parked_losses))
    # live lanes still match their solo runs (packed-lane tolerance)
    for b, seed in ((0, 3), (2, 29)):
        _, params, _, losses, _, _, _ = _solo_chunk(seed, 0, 16)
        np.testing.assert_allclose(np.asarray(params_s["x"][b]),
                                   np.asarray(params["x"]),
                                   rtol=0, atol=1e-6)


def test_slab_refill_advances_fresh_block_from_its_own_state():
    # chunk 1: blocks (3, 11); block 1 then retires and is refilled
    # with request 29's fresh state; chunk 2 must advance block 0 from
    # its chunk-1 carry and block 1 exactly as 29's first solo chunk
    states = [_block_state(3), _block_state(11)]
    params = slab_pack([st[0] for st in states])
    opt_state = slab_pack([st[1] for st in states])
    losses = slab_pack([st[2] for st in states])
    diag = slab_pack([st[3] for st in states])
    targets = slab_pack([(st[4],) for st in states])
    i_s, params, opt_state, losses, diag, _, _ = _run_fit_chunk_slab(
        _toy_loss, params, opt_state, losses, diag,
        [0, 0], [8, 8], [4, 4], [1e-9, 1e-9], [0.05, 0.05], targets,
        conv_window=CONV_WINDOW, b1=0.8, b2=0.99, diag_every=0)
    assert [int(v) for v in i_s] == [8, 8]

    fresh_params, fresh_opt, fresh_losses, fresh_diag, fresh_target = \
        _block_state(29)
    params = slab_fill(params, 1, fresh_params)
    opt_state = slab_fill(opt_state, 1, fresh_opt)
    losses = slab_fill(losses, 1, fresh_losses)
    diag = slab_fill(diag, 1, fresh_diag)
    targets = slab_fill(targets, 1, (fresh_target,))
    i_s, params2, _, losses2, _, _, _ = _run_fit_chunk_slab(
        _toy_loss, params, opt_state, losses, diag,
        [8, 0], [16, 8], [4, 4], [1e-9, 1e-9], [0.05, 0.05], targets,
        conv_window=CONV_WINDOW, b1=0.8, b2=0.99, diag_every=0)
    assert [int(v) for v in i_s] == [16, 8]

    # veteran block 0 == solo run straight to 16 (packed tolerance)
    _, solo_params, _, solo_losses, _, _, _ = _solo_chunk(3, 0, 16)
    np.testing.assert_allclose(np.asarray(params2["x"][0]),
                               np.asarray(solo_params["x"]),
                               rtol=0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(losses2[0]),
                               np.asarray(solo_losses),
                               rtol=1e-5, atol=1e-5)
    # refilled block 1 == request 29's own first chunk
    _, solo_params, _, solo_losses, _, _, _ = _solo_chunk(29, 0, 8)
    np.testing.assert_allclose(
        np.asarray(slab_block(params2, 1)["x"]),
        np.asarray(solo_params["x"]), rtol=0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(losses2[1]),
                               np.asarray(solo_losses),
                               rtol=1e-5, atol=1e-5)


# -- SlabFitCoordinator: the cross-thread rendezvous -----------------------

_SK = dict(conv_window=CONV_WINDOW, b1=0.8, b2=0.99, diag_every=0)


def _chunk_call(seed, i0=0, stop=16, min_iter=4):
    params, opt_state, losses, diag, target = _block_state(seed)
    args = (params, opt_state, losses, diag,
            jnp.asarray(i0), jnp.asarray(stop), jnp.asarray(min_iter),
            jnp.asarray(1e-9), jnp.asarray(0.05), (target,))
    return ChunkCall(
        loss_fn=_toy_loss, args=args, static_kwargs=dict(_SK),
        solo=lambda a: _run_fit_chunk(_toy_loss, *a, **_SK))


def _dispatch_in_thread(coord, call, box, key):
    try:
        box[key] = coord.dispatch(call)
    except BaseException as exc:  # surfaced by the test body
        box[key] = exc


def _rendezvous(coord, calls):
    """Register every fitter BEFORE any dispatch (in the worker each
    block thread brackets a whole multi-chunk fit, so peers are
    registered long before the next chunk; here each thread has exactly
    one chunk and would otherwise race past the barrier)."""
    box = {}
    for _ in calls:
        coord.fit_begin()
    threads = [
        threading.Thread(target=_dispatch_in_thread,
                         args=(coord, call, box, key))
        for key, call in calls.items()
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    for _ in calls:
        coord.fit_end()
    return box


def test_coordinator_packs_concurrent_dispatches():
    coord = SlabFitCoordinator(2, window_seconds=2.0)
    box = _rendezvous(coord, {seed: _chunk_call(seed)
                              for seed in (3, 11)})
    assert coord.packed_dispatches == 1
    assert coord.packed_lanes == 2
    for seed in (3, 11):
        out = box[seed]
        assert not isinstance(out, BaseException), out
        i, params, _, losses, _, conv, is_nan = out
        si, sp, _, sl, _, sconv, snan = _solo_chunk(seed, 0, 16)
        assert int(i) == int(si)
        np.testing.assert_allclose(np.asarray(params["x"]),
                                   np.asarray(sp["x"]),
                                   rtol=0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(losses), np.asarray(sl),
                                   rtol=1e-5, atol=1e-5)
        assert bool(conv) == bool(sconv) and bool(is_nan) == bool(snan)


def test_coordinator_singleton_stays_bit_exact():
    # a lone fitter's chunk must go through its solo program — the
    # occupancy-1 bit-identity guarantee — and never the slab program
    coord = SlabFitCoordinator(2, window_seconds=0.05)
    coord.fit_begin()
    box = {}
    _dispatch_in_thread(coord, _chunk_call(3), box, 3)
    coord.fit_end()
    assert coord.packed_dispatches == 0
    assert coord.dispatches == 1
    out = box[3]
    assert not isinstance(out, BaseException), out
    _, params, _, losses, _, _, _ = out
    _, sp, _, sl, _, _, _ = _solo_chunk(3, 0, 16)
    np.testing.assert_array_equal(np.asarray(params["x"]),
                                  np.asarray(sp["x"]))
    np.testing.assert_array_equal(np.asarray(losses), np.asarray(sl))


def test_coordinator_slab_failure_degrades_lane_by_lane():
    # poison the slab as a unit: pallas fused_adam raises at slab trace
    # time, so the leader must fall back to per-lane solo dispatches —
    # and only the lane whose own solo ALSO fails surfaces an error
    coord = SlabFitCoordinator(2, window_seconds=2.0)

    def poison_solo(a):
        raise RuntimeError("lane poison")

    # both lanes share a slab-refused static (pallas) so they group
    # together AND the packed dispatch raises as a unit; their solo
    # paths drop the static, so the fallback exercises real isolation
    good = _chunk_call(3)
    bad = _chunk_call(11)
    good.static_kwargs["fused_adam"] = "pallas"
    bad.static_kwargs["fused_adam"] = "pallas"
    bad.solo = poison_solo

    box = _rendezvous(coord, {"good": good, "bad": bad})
    assert coord.packed_dispatches == 0  # slab refused, nothing packed
    assert isinstance(box["bad"], RuntimeError)
    assert "lane poison" in str(box["bad"])
    out = box["good"]
    assert not isinstance(out, BaseException), out
    _, params, _, losses, _, _, _ = out
    _, sp, _, sl, _, _, _ = _solo_chunk(3, 0, 16)
    np.testing.assert_array_equal(np.asarray(params["x"]),
                                  np.asarray(sp["x"]))


def test_slab_refuses_pallas_fused_adam():
    states = [_block_state(3), _block_state(11)]
    params = slab_pack([st[0] for st in states])
    opt_state = slab_pack([st[1] for st in states])
    losses = slab_pack([st[2] for st in states])
    diag = slab_pack([st[3] for st in states])
    targets = slab_pack([(st[4],) for st in states])
    with pytest.raises(ValueError, match="pallas"):
        _run_fit_chunk_slab(
            _toy_loss, params, opt_state, losses, diag,
            [0, 0], [8, 8], [4, 4], [1e-9, 1e-9], [0.05, 0.05],
            targets, conv_window=CONV_WINDOW, b1=0.8, b2=0.99,
            diag_every=0, fused_adam="pallas")
