"""Anchor the JAX objective to the reference's Pyro semantics.

Pyro itself is not installable in this image, so the anchor is the next
strongest thing: an INDEPENDENT float64 transcription of the reference's
``model_s`` (reference: pert_model.py:541-646) built on
``torch.distributions`` — the exact distribution objects Pyro evaluates
under the hood — with the TraceEnum_ELBO + AutoDelta semantics applied
by hand:

* AutoDelta guide => ELBO = log-joint density at the point estimates
  (every Delta's entropy/log-q term is 0);
* ``config_enumerate`` + TraceEnum_ELBO => the two discrete sites are
  marginalised exactly, with Pyro's enumeration broadcast layout (cn in
  dim -3, rep in dim -4 beyond the (loci, cells) plates,
  reference: pert_model.py:613, 626);
* pyro.param sites (lambda, beta_stds, tau-with-t_init) contribute no
  prior term; conditioned sample sites still contribute their log-prob
  (poutine.condition semantics, reference: pert_model.py:724-729).

Unlike bench.py's torch twin (which mirrors the builder's own math and
could cancel a shared bug), this oracle is derived line by line from the
reference file, keeps the reference's (loci, cells) layout, and uses
torch.distributions for every density — so a parameterisation mistake in
ops/dists.py or a dropped term in models/pert.py shows up as a value
mismatch here.
"""

import numpy as np
import pytest
import torch
import torch.distributions as D

import jax.numpy as jnp

from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    constrained,
    decode_discrete,
    init_params,
    pert_loss,
)
from scdna_replication_tools_tpu.ops.gc import gc_features

torch.set_default_dtype(torch.float64)


def _t(x):
    return torch.as_tensor(np.asarray(x), dtype=torch.float64)


def reference_elbo_oracle(values, data_lc, gammas, libs, etas_lc,
                          P, K, L, *, lamb_is_param, tau_is_param,
                          t_alpha=None, t_beta=None,
                          cn_obs_lc=None, rep_obs_lc=None):
    """-loss of one SVI step of the reference's model_s, float64.

    ``values`` holds the constrained point estimates; all (loci, cells)
    layout like the reference ('_lc' suffixed args).  Returns a python
    float: the ELBO (= log-joint at the point estimates, discretes
    marginalised).
    """
    num_loci, num_cells = data_lc.shape
    a = _t(values["a"]).reshape(1)
    lamb = _t(values["lamb"]).reshape(())
    beta_means = _t(values["beta_means"])          # (L, K+1)
    beta_stds = _t(values["beta_stds"])            # (L, K+1)
    rho = _t(values["rho"])                        # (loci,)
    tau = _t(values["tau"])                        # (cells,)
    u = _t(values["u"])                            # (cells,)
    betas = _t(values["betas"])                    # (cells, K+1)
    data = _t(data_lc)
    gammas = _t(gammas)
    libs = torch.as_tensor(np.asarray(libs), dtype=torch.long)

    elbo = torch.zeros(())

    # a ~ Gamma(2, 0.2)                            pert_model.py:553
    elbo = elbo + D.Gamma(torch.tensor([2.0]),
                          torch.tensor([0.2])).log_prob(a).sum()
    # lambda: pyro.param => no prior term          pert_model.py:556-557
    assert lamb_is_param
    # beta_means ~ Normal(0,1).expand([L, K+1])    pert_model.py:560
    elbo = elbo + D.Normal(0.0, 1.0).log_prob(beta_means).sum()
    # beta_stds: pyro.param => no prior term       pert_model.py:561-562
    # rho ~ Beta(1, 1) per locus                   pert_model.py:574
    elbo = elbo + D.Beta(torch.tensor([1.0]),
                         torch.tensor([1.0])).log_prob(rho).sum()

    # tau                                          pert_model.py:580-585
    if not tau_is_param:
        if t_alpha is not None:
            elbo = elbo + D.Beta(_t(t_alpha), _t(t_beta)).log_prob(tau).sum()
        else:
            elbo = elbo + D.Beta(torch.tensor(1.5),
                                 torch.tensor(1.5)).log_prob(tau).sum()

    # cell ploidies feed the u prior (pert_model.py:589-600).  The cn0
    # branch (:589-590) is simulator-only — run_pert_model never passes
    # cn0, and step 1 passes neither cn0 nor etas (:743), so step 1 uses
    # the default ploidy 2.0 even though its cn site is conditioned.
    if etas_lc is not None:
        cell_ploidies = _t(np.argmax(etas_lc, axis=2)).mean(dim=0)
    else:
        cell_ploidies = torch.ones(num_cells) * 2.0
    u_guess = data.mean(dim=0) / ((1 + tau) * cell_ploidies)
    u_stdev = u_guess / 10.0
    elbo = elbo + D.Normal(u_guess, u_stdev).log_prob(u).sum()

    # betas ~ Normal(beta_means[libs], beta_stds[libs])  pert_model.py:603
    elbo = elbo + D.Normal(beta_means[libs],
                           beta_stds[libs]).log_prob(betas).sum()

    # phi = clamp(sigmoid(a (tau - rho)))          pert_model.py:616-623
    t_diff = tau.reshape(-1, num_cells) - rho.reshape(num_loci, -1)
    phi = torch.sigmoid(a.reshape(()) * t_diff)
    phi = torch.clamp(phi, 0.001, 0.999)

    # omega = exp(betas . gc_features(gamma))      pert_model.py:632-633
    feats = torch.stack([gammas ** i for i in range(K, 0, -1)]
                        + [torch.ones_like(gammas)], dim=1)
    gc_feats = feats.reshape(num_loci, 1, K + 1)
    omega = torch.exp(torch.sum(betas * gc_feats, 2))   # (loci, cells)

    def nb_log_prob(chi):
        """NB observation term for a given total CN (broadcasts over the
        enumeration dims), reference: pert_model.py:636-646."""
        theta = u * chi * omega
        delta = theta * (1 - lamb) / lamb
        delta = torch.clamp(delta, min=1.0)
        return D.NegativeBinomial(total_count=delta,
                                  probs=lamb).log_prob(data)

    if cn_obs_lc is not None:
        # step 1: cn and rep observed via poutine.condition — their
        # log-probs still enter the loss              pert_model.py:724-729
        cn_o = _t(cn_obs_lc)
        rep_o = _t(rep_obs_lc)
        if etas_lc is None:
            etas = torch.ones(num_loci, num_cells, P)
        else:
            etas = _t(etas_lc)
        pi = _t(values["pi"])
        elbo = elbo + D.Dirichlet(etas).log_prob(pi).sum()
        elbo = elbo + D.Categorical(probs=pi).log_prob(cn_o.long()).sum()
        elbo = elbo + D.Bernoulli(probs=phi).log_prob(rep_o).sum()
        elbo = elbo + nb_log_prob(cn_o * (1.0 + rep_o)).sum()
        return float(elbo)

    # step 2/3: pi ~ Dirichlet(etas); cn, rep enumerated in parallel
    if etas_lc is None:
        etas = torch.ones(num_loci, num_cells, P)
    else:
        etas = _t(etas_lc)
    pi = _t(values["pi"])                          # (loci, cells, P)
    elbo = elbo + D.Dirichlet(etas).log_prob(pi).sum()

    # Pyro's parallel-enumeration layout: cn occupies dim -3, rep dim -4
    # (the first dims beyond max_plate_nesting=2)    pert_model.py:611-626
    cn_enum = torch.arange(P, dtype=torch.float64).reshape(P, 1, 1)
    rep_enum = torch.arange(2, dtype=torch.float64).reshape(2, 1, 1, 1)
    lp_cn = D.Categorical(probs=pi).log_prob(cn_enum)        # (P, l, c)
    lp_rep = D.Bernoulli(probs=phi).log_prob(rep_enum)       # (2, 1, l, c)
    chi = cn_enum * (1.0 + rep_enum)                          # (2, P, 1, 1)
    lp_nb = nb_log_prob(chi)                                  # (2, P, l, c)
    joint = lp_cn.unsqueeze(0) + lp_rep + lp_nb               # (2, P, l, c)
    marg = torch.logsumexp(joint.reshape(2 * P, num_loci, num_cells), dim=0)
    elbo = elbo + marg.sum()
    return float(elbo)


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def _problem(rng, num_cells=10, num_loci=40, P=6, K=3, L=1,
             eta_conc=50.0, step1=False):
    reads = rng.poisson(40, (num_cells, num_loci)).astype(np.float32)
    gammas = rng.uniform(0.35, 0.6, num_loci).astype(np.float32)
    libs = (np.arange(num_cells) % L).astype(np.int32)
    etas = np.ones((num_cells, num_loci, P), np.float32)
    states = rng.integers(1, 4, (num_cells, num_loci))
    np.put_along_axis(etas, states[..., None], eta_conc, axis=-1)
    cn_obs = rep_obs = None
    if step1:
        cn_obs = states.astype(np.float32)
        rep_obs = (np.arange(num_cells) % 2)[:, None] * \
            np.ones((1, num_loci), np.float32)
    return reads, gammas, libs, etas, cn_obs, rep_obs


def _randomized_params(rng, spec, batch, fixed, t_init):
    """init_params + random perturbation so no term sits at a special
    point (0 logits, prior means) where a dropped factor could hide."""
    params = init_params(spec, batch, fixed, t_init=t_init)
    leaves, treedef = __import__("jax").tree_util.tree_flatten(params)
    leaves = [jnp.asarray(
        np.asarray(x) + rng.normal(0, 0.05, np.shape(x)).astype(np.float32))
        for x in leaves]
    return treedef.unflatten(leaves)


def _oracle_values(spec, params, fixed, reads_shape):
    """Constrained site values as numpy, plus the (loci, cells[, P])
    transposes the oracle expects."""
    c = constrained(spec, params, fixed)
    vals = {k: np.asarray(v, np.float64) for k, v in c.items()
            if k not in ("log_pi", "pi")}
    vals["pi"] = np.transpose(np.asarray(c["pi"], np.float64), (1, 0, 2))
    # renormalise in float64: the oracle's Dirichlet.log_prob validates
    # the simplex at float64 precision
    vals["pi"] /= vals["pi"].sum(axis=-1, keepdims=True)
    return vals


def _compare(spec, batch, fixed, t_init, rng, **oracle_kwargs):
    params = _randomized_params(rng, spec, batch, fixed, t_init)
    jax_elbo = -float(pert_loss(spec, params, fixed, batch))
    vals = _oracle_values(spec, params, fixed, batch.reads.shape)
    etas_lc = None if batch.etas is None else \
        np.transpose(np.asarray(batch.etas, np.float64), (1, 0, 2))
    cn_lc = None if batch.cn_obs is None else np.asarray(batch.cn_obs).T
    rep_lc = None if batch.rep_obs is None else np.asarray(batch.rep_obs).T
    ref_elbo = reference_elbo_oracle(
        vals, np.asarray(batch.reads, np.float64).T,
        np.asarray(batch.gamma_feats)[:, -2],  # linear column == gamma
        np.asarray(batch.libs), etas_lc, spec.P, spec.K, spec.L,
        cn_obs_lc=cn_lc, rep_obs_lc=rep_lc, **oracle_kwargs)
    # float32 forward pass vs float64 oracle: tolerance scales with the
    # magnitude of the largest accumulated term
    scale = max(abs(ref_elbo), 1.0)
    assert abs(jax_elbo - ref_elbo) < 3e-5 * scale, (
        f"jax={jax_elbo:.3f} oracle={ref_elbo:.3f} "
        f"diff={jax_elbo - ref_elbo:.5f}")
    return params


def _batch_from(spec, reads, gammas, libs, etas, cn_obs=None, rep_obs=None,
                t_alpha=None, t_beta=None):
    return PertBatch(
        reads=jnp.asarray(reads),
        libs=jnp.asarray(libs),
        gamma_feats=gc_features(jnp.asarray(gammas), spec.K),
        mask=jnp.ones((reads.shape[0],), jnp.float32),
        etas=None if etas is None else jnp.asarray(etas),
        cn_obs=None if cn_obs is None else jnp.asarray(cn_obs),
        rep_obs=None if rep_obs is None else jnp.asarray(rep_obs),
        t_alpha=None if t_alpha is None else jnp.asarray(t_alpha),
        t_beta=None if t_beta is None else jnp.asarray(t_beta),
    )


# ---------------------------------------------------------------------------
# tests
# ---------------------------------------------------------------------------

def test_step2_production_config_matches_reference():
    """Step 2 as run_pert_model runs it: beta_means conditioned, lambda
    fixed, tau a param from guess_times (reference: pert_model.py:777-816)."""
    rng = np.random.default_rng(0)
    reads, gammas, libs, etas, _, _ = _problem(rng)
    spec = PertModelSpec(P=6, K=3, L=1, tau_mode="param",
                         cond_beta_means=True, fixed_lamb=True)
    batch = _batch_from(spec, reads, gammas, libs, etas)
    fixed = {"beta_means": jnp.asarray(
                 rng.normal(0, 0.3, (1, 4)).astype(np.float32)),
             "lamb": jnp.asarray(0.75, jnp.float32)}
    _compare(spec, batch, fixed, rng.uniform(0.2, 0.8, 10).astype(np.float32),
             rng, lamb_is_param=True, tau_is_param=True)


def test_step2_free_sites_match_reference():
    """All sample sites free: beta_means sampled, tau ~ Beta(1.5, 1.5)
    (reference: pert_model.py:560, 585)."""
    rng = np.random.default_rng(1)
    reads, gammas, libs, etas, _, _ = _problem(rng)
    spec = PertModelSpec(P=6, K=3, L=1, tau_mode="beta_default",
                         fixed_lamb=True)
    batch = _batch_from(spec, reads, gammas, libs, etas)
    fixed = {"lamb": jnp.asarray(0.6, jnp.float32)}
    _compare(spec, batch, fixed, None, rng,
             lamb_is_param=True, tau_is_param=False)


def test_step2_beta_prior_tau_matches_reference():
    """tau ~ Beta(t_alpha, t_beta) branch (reference: pert_model.py:580-581,
    used by step 3 via guess_times posteriors)."""
    rng = np.random.default_rng(2)
    reads, gammas, libs, etas, _, _ = _problem(rng)
    t_alpha = rng.uniform(1.0, 3.0, 10).astype(np.float32)
    t_beta = rng.uniform(1.0, 3.0, 10).astype(np.float32)
    spec = PertModelSpec(P=6, K=3, L=1, tau_mode="beta_prior",
                         cond_beta_means=True, fixed_lamb=True)
    batch = _batch_from(spec, reads, gammas, libs, etas,
                        t_alpha=t_alpha, t_beta=t_beta)
    fixed = {"beta_means": jnp.zeros((1, 4), jnp.float32),
             "lamb": jnp.asarray(0.75, jnp.float32)}
    _compare(spec, batch, fixed, None, rng,
             lamb_is_param=True, tau_is_param=False,
             t_alpha=t_alpha, t_beta=t_beta)


def test_step1_observed_discretes_match_reference():
    """Step 1: cn/rep conditioned to the G1/G2-doubled training data,
    etas NOT passed (uniform Dirichlet, ploidy 2.0) — exactly how
    run_pert_model invokes it (reference: pert_model.py:718-743)."""
    rng = np.random.default_rng(3)
    reads, gammas, libs, _, cn_obs, rep_obs = _problem(rng, step1=True)
    # lambda as a live param (interval-transformed), as step 1 fits it
    # (reference: pert_model.py:556-557)
    spec = PertModelSpec(P=6, K=3, L=1, tau_mode="beta_default", step1=True,
                         fixed_lamb=False)
    batch = _batch_from(spec, reads, gammas, libs, None, cn_obs, rep_obs)
    _compare(spec, batch, {}, None, rng,
             lamb_is_param=True, tau_is_param=False)


def test_multilibrary_matches_reference():
    """L=2 libraries: betas indexed per cell through beta_means[libs] /
    beta_stds[libs] (reference: pert_model.py:560-562, 603)."""
    rng = np.random.default_rng(4)
    reads, gammas, libs, etas, _, _ = _problem(rng, num_cells=12, L=2)
    spec = PertModelSpec(P=6, K=3, L=2, tau_mode="param",
                         cond_beta_means=True, fixed_lamb=True)
    batch = _batch_from(spec, reads, gammas, libs, etas)
    fixed = {"beta_means": jnp.asarray(
                 rng.normal(0, 0.3, (2, 4)).astype(np.float32)),
             "lamb": jnp.asarray(0.75, jnp.float32)}
    _compare(spec, batch, fixed,
             rng.uniform(0.2, 0.8, 12).astype(np.float32), rng,
             lamb_is_param=True, tau_is_param=True)


def test_high_concentration_loss_differences_match():
    """At the production eta concentration (1e6, pert_model.py:41) the
    Dirichlet normaliser dwarfs float32 absolute precision, so compare
    LOSS DIFFERENCES between two parameter points (constants cancel) —
    the part SVI gradients actually see."""
    rng = np.random.default_rng(5)
    reads, gammas, libs, etas, _, _ = _problem(rng, eta_conc=1e6)
    spec = PertModelSpec(P=6, K=3, L=1, tau_mode="param",
                         cond_beta_means=True, fixed_lamb=True)
    batch = _batch_from(spec, reads, gammas, libs, etas)
    fixed = {"beta_means": jnp.zeros((1, 4), jnp.float32),
             "lamb": jnp.asarray(0.75, jnp.float32)}
    t_init = rng.uniform(0.2, 0.8, 10).astype(np.float32)

    jax_vals, ref_vals = [], []
    for seed in (10, 11):
        prng = np.random.default_rng(seed)
        params = _randomized_params(prng, spec, batch, fixed, t_init)
        jax_vals.append(-float(pert_loss(spec, params, fixed, batch)))
        vals = _oracle_values(spec, params, fixed, batch.reads.shape)
        ref_vals.append(reference_elbo_oracle(
            vals, np.asarray(batch.reads, np.float64).T,
            gammas, libs,
            np.transpose(np.asarray(etas, np.float64), (1, 0, 2)),
            spec.P, spec.K, spec.L, lamb_is_param=True, tau_is_param=True))
    d_jax = jax_vals[0] - jax_vals[1]
    d_ref = ref_vals[0] - ref_vals[1]
    # (etas-1)*log_pi carries 1e6-scale coefficients, so float32
    # log_softmax noise (~1e-7 relative) leaves ~0.1% error on the
    # parameter-dependent difference — the bound is precision, not
    # semantics (the exact-value tests above pin those at eta=50)
    assert abs(d_jax - d_ref) < 3e-3 * max(abs(d_ref), 1.0), (
        f"jax diff={d_jax:.3f} oracle diff={d_ref:.3f}")


def test_decode_agrees_with_oracle_argmax():
    """infer_discrete(temperature=0) equivalence: the (cn, rep) argmax of
    the oracle's enumerated joint must match decode_discrete
    (reference: pert_model.py:824-827)."""
    rng = np.random.default_rng(6)
    reads, gammas, libs, etas, _, _ = _problem(rng)
    spec = PertModelSpec(P=6, K=3, L=1, tau_mode="param",
                         cond_beta_means=True, fixed_lamb=True)
    batch = _batch_from(spec, reads, gammas, libs, etas)
    fixed = {"beta_means": jnp.zeros((1, 4), jnp.float32),
             "lamb": jnp.asarray(0.75, jnp.float32)}
    params = _randomized_params(
        rng, spec, batch, fixed, rng.uniform(0.2, 0.8, 10).astype(np.float32))

    cn_map, rep_map, _ = decode_discrete(spec, params, fixed, batch)

    # oracle joint, float64, reference layout
    vals = _oracle_values(spec, params, fixed, batch.reads.shape)
    pi = torch.as_tensor(vals["pi"])                       # (l, c, P)
    a = torch.as_tensor(vals["a"]).reshape(())
    tau = torch.as_tensor(vals["tau"])
    rho = torch.as_tensor(vals["rho"])
    u = torch.as_tensor(vals["u"])
    betas = torch.as_tensor(vals["betas"])
    lamb = torch.as_tensor(vals["lamb"]).reshape(())
    g = torch.as_tensor(np.asarray(gammas, np.float64))
    num_loci, num_cells = pi.shape[0], pi.shape[1]
    phi = torch.clamp(torch.sigmoid(a * (tau.reshape(1, -1)
                                         - rho.reshape(-1, 1))), 0.001, 0.999)
    feats = torch.stack([g ** i for i in range(spec.K, 0, -1)]
                        + [torch.ones_like(g)], dim=1)
    omega = torch.exp(torch.sum(betas * feats.reshape(num_loci, 1, -1), 2))
    cn_enum = torch.arange(spec.P, dtype=torch.float64).reshape(spec.P, 1, 1)
    rep_enum = torch.arange(2, dtype=torch.float64).reshape(2, 1, 1, 1)
    chi = cn_enum * (1.0 + rep_enum)
    theta = u * chi * omega
    delta = torch.clamp(theta * (1 - lamb) / lamb, min=1.0)
    lp_nb = D.NegativeBinomial(total_count=delta, probs=lamb).log_prob(
        torch.as_tensor(np.asarray(batch.reads, np.float64).T))
    joint = (torch.log(pi).permute(2, 0, 1).unsqueeze(0)
             + D.Bernoulli(probs=phi).log_prob(rep_enum) + lp_nb)
    flat = joint.reshape(2 * spec.P, num_loci, num_cells)
    best = torch.argmax(flat, dim=0)          # index = rep * P + cn
    oracle_cn = (best % spec.P).numpy().T
    oracle_rep = (best // spec.P).numpy().T

    agree = np.mean((np.asarray(cn_map) == oracle_cn)
                    & (np.asarray(rep_map) == oracle_rep))
    assert agree > 0.99, f"decode agreement {agree:.4f}"
