"""Clone-discovery clustering: kmeans/BIC and the umap_hdbscan path.

The umap_hdbscan_cluster parity target is the reference's
cncluster.py:10-46 (umap embedding -> hdbscan labels -> cell_id/
cluster_id/umap1/umap2 frame); here the embedding is the deterministic
kNN-graph spectral layout (see pipeline/clustering.py docstrings).
"""

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.pipeline.clustering import (
    kmeans_cluster,
    spectral_embed,
    umap_hdbscan_cluster,
)


def _blob_frame(n_per_blob=40, n_loci=60, seed=0):
    """(loci x cells) matrix frame of 3 well-separated CN blobs."""
    rng = np.random.default_rng(seed)
    blobs = []
    for b, base in enumerate([2.0, 4.0, 6.0]):
        centers = np.full(n_loci, base)
        centers[b * 10:(b + 1) * 10] += 2.0   # blob-specific CNA
        blobs.append(centers[None, :]
                     + 0.1 * rng.standard_normal((n_per_blob, n_loci)))
    X = np.concatenate(blobs, axis=0)          # cells x loci
    cells = [f"c{b}_{i}" for b in range(3) for i in range(n_per_blob)]
    truth = np.repeat(np.arange(3), n_per_blob)
    frame = pd.DataFrame(X.T, columns=cells)   # loci x cells
    return frame, truth


def test_spectral_embed_shape_and_determinism():
    frame, _ = _blob_frame()
    X = frame.T.values
    e1 = spectral_embed(X, n_components=2, n_neighbors=10)
    e2 = spectral_embed(X, n_components=2, n_neighbors=10)
    assert e1.shape == (X.shape[0], 2)
    assert np.array_equal(e1, e2)
    assert np.all(np.isfinite(e1))


def test_umap_hdbscan_recovers_blobs():
    frame, truth = _blob_frame()
    out = umap_hdbscan_cluster(frame, n_neighbors=10)
    assert list(out.columns) == ["cell_id", "cluster_id", "umap1", "umap2"]
    assert len(out) == frame.shape[1]
    labeled = out["cluster_id"].to_numpy()
    # reference hyperparameters (min_cluster_size=30) on 3 x 40-cell
    # blobs: expect the 3 blobs found with little noise
    assert (labeled >= 0).mean() > 0.9
    # majority label of each true blob must be distinct and dominant
    majorities = []
    for b in range(3):
        lab = labeled[truth == b]
        lab = lab[lab >= 0]
        vals, counts = np.unique(lab, return_counts=True)
        assert counts.max() / (truth == b).sum() > 0.8
        majorities.append(vals[np.argmax(counts)])
    assert len(set(majorities)) == 3


def test_umap_hdbscan_small_data_is_noise():
    """Below min_cluster_size everything is noise (-1), like hdbscan."""
    frame, _ = _blob_frame(n_per_blob=8, n_loci=20)
    out = umap_hdbscan_cluster(frame, n_neighbors=5)
    assert (out["cluster_id"] == -1).all()


def test_spectral_embed_sparse_branch_matches_blob_structure():
    """n > 2048 rides the ARPACK shift-invert path; blob separation
    must survive the solver switch."""
    frame, truth = _blob_frame(n_per_blob=720, n_loci=30, seed=1)
    X = frame.T.values                         # 2160 cells > 2048
    emb = spectral_embed(X, n_components=2, n_neighbors=10)
    assert emb.shape == (X.shape[0], 2)
    assert np.all(np.isfinite(emb))
    # blob centroids in embedding space must be mutually separated
    # relative to within-blob spread
    cents = np.stack([emb[truth == b].mean(0) for b in range(3)])
    spread = max(emb[truth == b].std(0).max() for b in range(3))
    for a in range(3):
        for b in range(a + 1, 3):
            assert np.linalg.norm(cents[a] - cents[b]) > 2.0 * spread


def test_cluster_g1_cells_error_paths():
    from scdna_replication_tools_tpu.pipeline.clustering import (
        cluster_g1_cells,
    )
    frame, _ = _blob_frame(n_per_blob=6, n_loci=20)
    with pytest.raises(ValueError, match="kmeans"):
        cluster_g1_cells(frame, method="umap")
    # all-noise (min_cluster_size far above the cell count) raises with
    # guidance instead of returning an empty clone table
    with pytest.raises(ValueError, match="noise"):
        cluster_g1_cells(frame, method="umap_hdbscan", n_neighbors=5)


def test_discover_clones_custom_cell_col():
    """The long-form preamble honors a non-default cell column."""
    from scdna_replication_tools_tpu.pipeline.clustering import (
        discover_clones,
    )
    frame, truth = _blob_frame()
    long = (frame.reset_index(names="start")
            .melt(id_vars="start", var_name="barcode", value_name="copy"))
    long["chr"] = "1"
    out, clone_col = discover_clones(long, "copy", cell_col="barcode",
                                     method="kmeans", min_k=2, max_k=4)
    assert clone_col == "cluster_id"
    assert "cluster_id" in out.columns and "barcode" in out.columns
    per_cell = out.drop_duplicates("barcode").set_index("barcode")
    tr = pd.Series(truth, index=frame.columns)
    purity = (per_cell.join(tr.rename("truth")).groupby("truth")
              ["cluster_id"].agg(lambda s: s.value_counts(normalize=True)
                                 .iloc[0]))
    assert (purity > 0.9).all()


def test_discover_clones_overwrites_preexisting_cluster_id():
    """Re-running inference on a previous run's output (which already
    carries cluster_id) must overwrite it, not suffix to _x/_y and
    KeyError downstream (ADVICE.md round 5)."""
    from scdna_replication_tools_tpu.pipeline.clustering import (
        discover_clones,
    )
    frame, _ = _blob_frame()
    long = (frame.reset_index(names="start")
            .melt(id_vars="start", var_name="cell_id", value_name="copy"))
    long["chr"] = "1"
    long["cluster_id"] = 99            # stale labels from a previous run
    out, clone_col = discover_clones(long, "copy", method="kmeans",
                                     min_k=2, max_k=4)
    assert clone_col == "cluster_id"
    assert "cluster_id" in out.columns
    assert not any(c.startswith("cluster_id_") for c in out.columns)
    assert (out["cluster_id"] != 99).any()   # fresh labels, not the stale 99
    assert len(out) == len(long)


def test_spectral_embed_sparse_path_on_disconnected_graph():
    """The ARPACK shift-invert path (forced via dense_cutoff) must handle
    a kNN graph with multiple components — the normalized Laplacian then
    has a multiplicity->1 zero eigenvalue, which the old sigma=0.0
    shift-invert handed to SuperLU as an exactly singular factorization
    (ADVICE.md round 5)."""
    frame, truth = _blob_frame(n_per_blob=50, n_loci=30, seed=3)
    X = frame.T.values
    # n_neighbors small vs the blob size: the symmetrised kNN graph of
    # three well-separated blobs disconnects into 3 components
    emb = spectral_embed(X, n_components=2, n_neighbors=5, dense_cutoff=16)
    assert emb.shape == (X.shape[0], 2)
    assert np.all(np.isfinite(emb))


def test_spectral_embed_dense_fallback_on_solver_failure(monkeypatch):
    """When ARPACK/SuperLU still fails, the dense-eigh fallback keeps
    clone discovery alive (and produces the same embedding family)."""
    import scipy.sparse.linalg

    def boom(*args, **kwargs):
        raise RuntimeError("Factor is exactly singular")

    monkeypatch.setattr(scipy.sparse.linalg, "eigsh", boom)
    frame, _ = _blob_frame(n_per_blob=30, n_loci=30, seed=4)
    X = frame.T.values
    emb = spectral_embed(X, n_components=2, n_neighbors=8, dense_cutoff=16)
    assert emb.shape == (X.shape[0], 2)
    assert np.all(np.isfinite(emb))
    # the fallback must agree with the small-n dense path bit-for-bit
    # (same Laplacian, same solver)
    dense = spectral_embed(X, n_components=2, n_neighbors=8)
    assert np.array_equal(emb, dense)


def test_kmeans_cluster_still_recovers_blobs():
    frame, truth = _blob_frame()
    out = kmeans_cluster(frame, min_k=2, max_k=5)
    merged = out.assign(truth=truth)
    purity = (merged.groupby("truth")["cluster_id"]
              .agg(lambda s: s.value_counts().iloc[0] / len(s)))
    assert (purity > 0.9).all()
