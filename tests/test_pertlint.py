"""pertlint: detection, suppression, baseline workflow, and the CI gate.

Pure stdlib + tools.pertlint — no jax/numpy/pandas imports — so the CI
lint job can run this module with a bare interpreter.

Fixture convention (tests/pertlint_fixtures/): each rule has one fixture
module, parsed but never imported.  A line ending in ``# expect: PLnnn``
must produce exactly that finding; a line carrying
``# pertlint: disable=PLnnn`` must land in the suppressed list.  The
fixtures double as living documentation of each rule's exemptions.
"""

import json
import pathlib
import re
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from tools.pertlint import lint_paths, lint_source  # noqa: E402
from tools.pertlint.cli import main as cli_main  # noqa: E402
from tools.pertlint.core import all_rules  # noqa: E402
from tools.pertlint.engine import snapshot_baseline  # noqa: E402

FIXTURE_DIR = REPO_ROOT / "tests" / "pertlint_fixtures"
PACKAGE = REPO_ROOT / "scdna_replication_tools_tpu"
BASELINE = REPO_ROOT / "tools" / "pertlint" / "baseline.json"

_EXPECT = re.compile(r"#\s*expect:\s*(PL\d{3})")

FIXTURES = {
    "PL001": FIXTURE_DIR / "pl001_host_sync.py",
    "PL002": FIXTURE_DIR / "pl002_tracer_branch.py",
    "PL003": FIXTURE_DIR / "pl003_partition_spec.py",
    "PL004": FIXTURE_DIR / "ops" / "pl004_dtype_drift.py",
    "PL005": FIXTURE_DIR / "pl005_rng.py",
    "PL006": FIXTURE_DIR / "pl006_jit_in_loop.py",
    "PL007": FIXTURE_DIR / "pl007_donate.py",
    "PL008": FIXTURE_DIR / "pl008_print.py",
    "PL009": FIXTURE_DIR / "pl009_event_kinds.py",
    "PL010": FIXTURE_DIR / "pl010_control_actions.py",
    "PL011": FIXTURE_DIR / "pl011_swallowed.py",
    "PL012": FIXTURE_DIR / "pl012_metric_names.py",
    "PL013": FIXTURE_DIR / "pl013_raw_writes.py",
    "PL014": FIXTURE_DIR / "pl014_span_names.py",
}


def _lint_fixture(path):
    source = path.read_text()
    findings, suppressed = lint_source(source, path=path.as_posix())
    return source, findings, suppressed


def test_every_rule_has_a_fixture():
    assert set(FIXTURES) == {r.id for r in all_rules()}


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_detections_match_expect_markers(rule_id):
    """Findings == the fixture's ``# expect:`` markers, line-exact."""
    source, findings, suppressed = _lint_fixture(FIXTURES[rule_id])
    expected = {i for i, line in enumerate(source.splitlines(), start=1)
                if (m := _EXPECT.search(line)) and m.group(1) == rule_id}
    assert expected, "fixture must seed at least one violation"
    actual = {f.line for f in findings if f.rule == rule_id}
    assert actual == expected
    # no OTHER rule may fire on this fixture's expect lines (isolation)
    cross = {f.rule for f in findings} - {rule_id}
    assert not cross, f"unexpected cross-rule findings: {cross}"


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_suppression_lines_are_suppressed(rule_id):
    """Each fixture's inline-disable line produces a suppressed finding —
    proving the violation was detected AND the comment ate it."""
    source, findings, suppressed = _lint_fixture(FIXTURES[rule_id])
    disable_lines = {i for i, line in enumerate(source.splitlines(), 1)
                     if f"pertlint: disable={rule_id}" in line}
    assert disable_lines, "fixture must carry a suppressed case"
    assert disable_lines == {s.line for s in suppressed if s.rule == rule_id}
    assert not ({f.line for f in findings} & disable_lines)


def test_suppression_marker_in_string_literal_is_inert():
    src = textwrap.dedent("""\
        import numpy as np
        MSG = "# pertlint: disable=PL005"
        def f(n):
            return np.random.rand(n), MSG
        """)
    findings, suppressed = lint_source(src)
    assert [f.rule for f in findings] == ["PL005"]
    assert not suppressed


def test_malformed_suppression_markers_fail_closed():
    """A typo'd keyword or an invalid rule list must suppress NOTHING —
    widening to all rules would turn a typo into a disabled gate."""
    body = "import numpy as np\ndef f(n):\n    return np.random.rand(n)"
    for marker in ("# pertlint: disable-files=PL005",   # keyword typo
                   "# pertlint: disable=bogus",          # no valid rule id
                   "# pertlint: disabled=PL005"):        # keyword typo
        src = body.replace("np.random.rand(n)",
                           f"np.random.rand(n)  {marker}")
        findings, suppressed = lint_source(src)
        assert [f.rule for f in findings] == ["PL005"], marker
        assert not suppressed, marker


def test_suppression_rule_ids_are_case_normalised():
    src = ("import numpy as np\ndef f(n):\n"
           "    return np.random.rand(n)  # pertlint: disable=pl005\n")
    findings, suppressed = lint_source(src)
    assert not findings
    assert [s.rule for s in suppressed] == ["PL005"]


def test_local_assignment_does_not_taint_same_named_helper():
    """A Store-context name inside a jitted function must not mark a
    same-named module-level host helper as traced (PL001 false
    positive)."""
    src = textwrap.dedent("""\
        import jax
        import numpy as np

        def report(x):
            return float(np.asarray(x).mean())   # host-only: legal

        @jax.jit
        def step(x):
            report = x * 2.0                     # local, shadows nothing
            return report
        """)
    findings, _ = lint_source(src)
    assert findings == []


def test_file_wide_suppression():
    src = textwrap.dedent("""\
        # pertlint: disable-file=PL005 — fixture-wide opt-out
        import numpy as np
        def f(n):
            return np.random.rand(n) + np.random.randn(n)
        """)
    findings, suppressed = lint_source(src)
    assert not findings
    assert {s.rule for s in suppressed} == {"PL005"}


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_baseline_roundtrip(rule_id, tmp_path):
    """Baseline workflow per rule: snapshot grandfathers every finding;
    a freshly added violation still gates."""
    fixture = FIXTURES[rule_id]
    work = tmp_path / ("ops" if rule_id == "PL004" else "lib")
    work.mkdir()
    target = work / fixture.name
    target.write_text(fixture.read_text())
    baseline = tmp_path / "baseline.json"

    n = snapshot_baseline([str(work)], baseline)
    assert n > 0
    clean = lint_paths([str(work)], baseline_path=baseline)
    assert clean.new == [] and len(clean.baselined) == n

    with target.open("a") as fh:
        fh.write(_seed_violation(rule_id))
    dirty = lint_paths([str(work)], baseline_path=baseline)
    assert [f.rule for f in dirty.new] == [rule_id]
    assert len(dirty.baselined) == n


def _seed_violation(rule_id):
    return {
        "PL001": "\n@jax.jit\ndef seeded(x):\n    return float(x)\n",
        "PL002": ("\n@jax.jit\ndef seeded(x):\n"
                  "    if jnp.isnan(x).any():\n        x = x * 0\n"
                  "    return x\n"),
        "PL003": "\ndef seeded():\n    return P('cells')\n",
        "PL004": "\ndef seeded(n):\n    return jnp.zeros((n,))\n",
        "PL005": "\ndef seeded(n):\n    return np.random.rand(n)\n",
        "PL006": ("\ndef seeded(fns):\n    for f in fns:\n"
                  "        g = jax.jit(f)\n    return g\n"),
        "PL007": ("\n@jax.jit\ndef seeded(params0):\n"
                  "    return params0\n"),
        "PL008": "\ndef seeded(x):\n    print(x)\n    return x\n",
        "PL009": ("\ndef seeded(run_log):\n"
                  "    run_log.emit('bogus_event_kind')\n"),
        "PL010": ("\ndef seeded(run_log):\n"
                  "    run_log.emit('control_decision', "
                  "action='bogus_action', iter=1)\n"),
        "PL011": ("\ndef seeded(fn):\n    try:\n        return fn()\n"
                  "    except Exception:\n        return None\n"),
        "PL012": ("\ndef seeded(metrics):\n"
                  "    metrics.counter('pert_bogus_total').inc()\n"),
        "PL013": ("\ndef seeded(path, arr):\n"
                  "    np.savez(path, arr=arr)\n"),
        "PL014": ("\ndef seeded(tracer):\n"
                  "    tracer.span('request')\n"),
    }[rule_id]


def test_baseline_fingerprint_survives_line_shift(tmp_path):
    """Inserting unrelated lines above a baselined finding must not
    resurrect it (fingerprints are content-addressed, not line-keyed)."""
    target = tmp_path / "mod.py"
    target.write_text("import numpy as np\n"
                      "def f(n):\n    return np.random.rand(n)\n")
    baseline = tmp_path / "baseline.json"
    snapshot_baseline([str(target)], baseline)
    target.write_text("import numpy as np\n\n# a comment\n\n"
                      "def g():\n    return 1\n\n"
                      "def f(n):\n    return np.random.rand(n)\n")
    result = lint_paths([str(target)], baseline_path=baseline)
    assert result.new == [] and len(result.baselined) == 1


def test_partial_snapshot_retains_out_of_scope_entries(tmp_path):
    """--write-baseline over a path subset must keep the grandfathered
    entries of every other path (no silent baseline data loss)."""
    a, b = tmp_path / "a", tmp_path / "b"
    a.mkdir(); b.mkdir()
    (a / "m.py").write_text("import numpy as np\n"
                            "def f(n):\n    return np.random.rand(n)\n")
    (b / "m.py").write_text("import numpy as np\n"
                            "def g(n):\n    return np.random.randn(n)\n")
    baseline = tmp_path / "baseline.json"
    assert snapshot_baseline([str(a), str(b)], baseline) == 2
    # re-snapshot ONLY a/ — b/'s entry must survive, and the full-tree
    # lint must still be clean against the rewritten baseline
    assert snapshot_baseline([str(a)], baseline) == 2
    result = lint_paths([str(a), str(b)], baseline_path=baseline)
    assert result.new == [] and len(result.baselined) == 2
    # pruning still works within the snapshot scope: fix a/ and re-write
    (a / "m.py").write_text("def f(n):\n    return n\n")
    assert snapshot_baseline([str(a)], baseline) == 1


def test_write_baseline_with_select_is_refused(tmp_path, capsys):
    target = tmp_path / "m.py"
    target.write_text("def f():\n    return 1\n")
    rc = cli_main([str(target), "--write-baseline", "--select", "PL005",
                   "--baseline", str(tmp_path / "b.json")])
    assert rc == 2
    assert "--select" in capsys.readouterr().err


def test_stale_baseline_entries_reported(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text("import numpy as np\n"
                      "def f(n):\n    return np.random.rand(n)\n")
    baseline = tmp_path / "baseline.json"
    snapshot_baseline([str(target)], baseline)
    target.write_text("import numpy as np\n"
                      "def f(n, rng):\n    return rng.random(n)\n")
    result = lint_paths([str(target)], baseline_path=baseline)
    assert result.new == [] and len(result.stale_baseline) == 1


def test_cli_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n")
    assert cli_main([str(clean), "--no-baseline"]) == 0

    dirty = tmp_path / "dirty.py"
    dirty.write_text("import numpy as np\n"
                     "def f(n):\n    return np.random.rand(n)\n")
    assert cli_main([str(dirty), "--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "PL005" in out and "dirty.py:3" in out

    assert cli_main([]) == 2                       # no paths
    assert cli_main([str(clean), "--select", "PL999"]) == 2
    assert cli_main(["--list-rules"]) == 0
    assert "PL001" in capsys.readouterr().out


def test_cli_select_and_json_format(tmp_path, capsys):
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import numpy as np\n"
                     "def f(n):\n    return np.random.rand(n)\n")
    # selecting an unrelated rule: the PL005 violation is not even run
    assert cli_main([str(dirty), "--no-baseline", "--select", "PL006"]) == 0
    capsys.readouterr()
    assert cli_main([str(dirty), "--no-baseline", "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["new"][0]["rule"] == "PL005"
    assert payload["files_checked"] == 1


def test_cli_github_format(tmp_path, capsys):
    """--format=github renders findings as workflow annotations (and
    only changes the rendering — the exit code still gates)."""
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import numpy as np\n"
                     "def f(n):\n    return np.random.rand(n)\n")
    assert cli_main([str(dirty), "--no-baseline", "--format",
                     "github"]) == 1
    out = capsys.readouterr().out
    assert f"::error file={dirty.as_posix()},line=3,col=12," in out
    assert "title=pertlint PL005::" in out


def test_cli_list_rules_includes_deep_layer(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "PL001" in out and "DP003" in out and "DP006" in out


def test_update_baseline_prunes_stale_and_dead_entries(tmp_path, capsys):
    """--update-baseline drops entries whose finding is gone (stale) or
    whose file is gone (dead), keeps live ones, and NEVER grandfathers
    a new violation."""
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    body = "import numpy as np\ndef f(n):\n    return np.random.rand(n)\n"
    a.write_text(body)
    b.write_text(body.replace("f(", "g("))
    baseline = tmp_path / "baseline.json"
    assert snapshot_baseline([str(a), str(b)], baseline) == 2

    a.write_text("def f(n):\n    return n\n")   # fixed: entry goes stale
    b.unlink()                                   # deleted: entry goes dead
    rc = cli_main([str(a), "--baseline", str(baseline),
                   "--update-baseline"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "2 stale/dead entries pruned" in out
    assert json.loads(baseline.read_text())["findings"] == []

    # prune-only: a fresh violation still gates after an update
    a.write_text(body)
    assert cli_main([str(a), "--baseline", str(baseline),
                     "--update-baseline"]) == 0
    assert cli_main([str(a), "--baseline", str(baseline)]) == 1


def test_cli_warns_on_stale_and_missing_baseline_entries(tmp_path, capsys):
    target = tmp_path / "mod.py"
    target.write_text("import numpy as np\n"
                      "def f(n):\n    return np.random.rand(n)\n")
    baseline = tmp_path / "baseline.json"
    snapshot_baseline([str(target)], baseline)
    target.write_text("def f(n):\n    return n\n")
    assert cli_main([str(target), "--baseline", str(baseline)]) == 0
    err = capsys.readouterr().err
    assert "warning" in err and "stale" in err

    # point the entry at a path that no longer exists
    data = json.loads(baseline.read_text())
    data["findings"][0]["path"] = str(tmp_path / "gone.py")
    baseline.write_text(json.dumps(data))
    assert cli_main([str(target), "--baseline", str(baseline)]) == 0
    err = capsys.readouterr().err
    assert "missing file" in err


def test_update_baseline_prunes_program_scoped_deep_entries(tmp_path):
    """Deep (DP) entries are program-scoped, not path-scoped: when the
    deep rules ran and no longer produce an entry's fingerprint, it is
    pruned even with no lint paths given (``--deep --update-baseline``)
    — while a still-produced one survives, rationale intact."""
    from tools.pertlint.engine import update_baseline

    target = tmp_path / "svi.py"
    target.write_text("def fit():\n    return 1\n")
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"version": 1, "findings": [
        {"rule": "DP003", "path": str(target), "line": 1,
         "fingerprint": "feedfacefeedface", "message": "gone"},
        {"rule": "DP003", "path": str(target), "line": 1,
         "fingerprint": "cafef00dcafef00d", "message": "alive",
         "rationale": "deliberate"},
    ]}))
    kept, pruned = update_baseline(
        [], baseline, extra_produced={"cafef00dcafef00d"},
        extra_rule_ids={"DP003"})
    assert (kept, pruned) == (1, 1)
    entry = json.loads(baseline.read_text())["findings"][0]
    assert entry["fingerprint"] == "cafef00dcafef00d"
    assert entry["rationale"] == "deliberate"


def test_write_baseline_preserves_rationales(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text("import numpy as np\n"
                      "def f(n):\n    return np.random.rand(n)\n")
    baseline = tmp_path / "baseline.json"
    snapshot_baseline([str(target)], baseline)
    data = json.loads(baseline.read_text())
    data["findings"][0]["rationale"] = "legacy RNG, scheduled for PR 9"
    baseline.write_text(json.dumps(data))
    snapshot_baseline([str(target)], baseline)  # regenerate
    entry = json.loads(baseline.read_text())["findings"][0]
    assert entry["rationale"] == "legacy RNG, scheduled for PR 9"


def test_cli_parse_error_exits_2(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(:\n")
    assert cli_main([str(bad), "--no-baseline"]) == 2


def test_package_gate_is_clean():
    """THE gate: the shipped tree + shipped baseline lints clean.  Run
    exactly as CI does — ``python -m tools.pertlint <package>``."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.pertlint",
         "scdna_replication_tools_tpu", "--baseline",
         str(BASELINE)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_seeded_violation_fails_the_gate(tmp_path):
    """Acceptance criterion: introducing a violation (a float() on a
    traced value inside a jitted helper, and a PartitionSpec outside
    layout.py) flips the module CLI to a non-zero exit."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "seeded.py").write_text(textwrap.dedent("""\
        import jax
        from jax.sharding import PartitionSpec

        @jax.jit
        def step(x):
            return float(x)

        SPEC = PartitionSpec("cells")
        """))
    proc = subprocess.run(
        [sys.executable, "-m", "tools.pertlint", str(pkg), "--no-baseline"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "PL001" in proc.stdout and "PL003" in proc.stdout
