"""Donated fit buffers + the AOT program cache (infer/svi.py).

The ``_run_fit`` entry donates its initial-value pytrees
(params0/opt_state0/losses0) so XLA reuses their buffers for the loop
carry instead of copying on entry (at 10k cells pi_logits alone is
~2.8 GB of entry-copy HBM churn without it).  These tests pin:

* donation actually happens (the entry buffers are deleted after the
  call) and never changes results;
* checkpoint-style resume (opt_state + losses_prefix) stays bit-exact
  under donation — the acceptance bar of the donation change;
* equal-program fits share one trace+compile through the AOT program
  cache when the loss callable hashes by value (runner._PertLossFn),
  and the cache is transparent (identical results on hit).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from scdna_replication_tools_tpu.infer import svi
from scdna_replication_tools_tpu.infer.runner import _PertLossFn
from scdna_replication_tools_tpu.infer.svi import fit_map
from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    init_params,
)
from scdna_replication_tools_tpu.ops.gc import gc_features

SPEC = PertModelSpec(P=5, K=2, L=1, tau_mode="param")


def _problem(seed=0, num_cells=8, num_loci=30):
    rng = np.random.default_rng(seed)
    reads = rng.poisson(40, (num_cells, num_loci)).astype(np.float32)
    gammas = rng.uniform(0.35, 0.6, num_loci).astype(np.float32)
    etas = np.ones((num_cells, num_loci, SPEC.P), np.float32)
    etas[:, :, 2] = 100.0
    batch = PertBatch(
        reads=jnp.asarray(reads),
        libs=jnp.zeros(num_cells, jnp.int32),
        gamma_feats=gc_features(jnp.asarray(gammas), SPEC.K),
        mask=jnp.ones((num_cells,), jnp.float32),
        etas=jnp.asarray(etas),
    )
    params0 = init_params(SPEC, batch, {},
                          t_init=np.full(num_cells, 0.4, np.float32))
    return params0, batch


def _supports_donation():
    """XLA backends without donation support silently ignore it (jax
    warns); skip the buffer-deletion assertions there rather than
    encoding a platform list."""
    x = jnp.ones((4,))
    jax.jit(lambda v: v + 1, donate_argnums=0)(x)
    return x.is_deleted()


def test_fit_map_donates_entry_buffers():
    params0, batch = _problem()
    entry_leaves = list(params0.values())
    fit = fit_map(_PertLossFn(spec=SPEC), params0, ({}, batch),
                  max_iter=6, min_iter=3)
    assert np.isfinite(fit.losses).all()
    if not _supports_donation():
        pytest.skip("backend ignores donation")
    assert all(leaf.is_deleted() for leaf in entry_leaves), \
        "params0 buffers survived the fit — donation is not wired"
    # outputs are live, fresh buffers
    assert not any(v.is_deleted() for v in fit.params.values())


def test_program_cache_hits_for_equal_programs():
    svi.clear_program_cache()
    params_a, batch = _problem(seed=1)
    fit_a = fit_map(_PertLossFn(spec=SPEC), params_a, ({}, batch),
                    max_iter=6, min_iter=3)
    assert fit_a.timings["program_cache"] == "miss"
    assert fit_a.timings["compile"] > 0.0

    # fresh loss instance + fresh buffers, same program by value
    params_b, batch_b = _problem(seed=1)
    fit_b = fit_map(_PertLossFn(spec=SPEC), params_b, ({}, batch_b),
                    max_iter=6, min_iter=3)
    assert fit_b.timings["program_cache"] == "hit"
    assert fit_b.timings["trace"] == 0.0
    assert fit_b.timings["compile"] == 0.0
    # the cache is transparent: identical inputs -> identical trajectory
    np.testing.assert_array_equal(fit_a.losses, fit_b.losses)


def test_resume_is_bit_exact_under_donation():
    """Stop at iteration k, resume with Adam moments + loss prefix: the
    stitched trajectory must equal the uninterrupted one bit for bit
    (the checkpoint contract donation must not break)."""
    loss = _PertLossFn(spec=SPEC)

    params_full, batch = _problem(seed=2)
    full = fit_map(loss, params_full, ({}, batch), max_iter=10,
                   min_iter=10)

    params_part, batch_p = _problem(seed=2)
    part = fit_map(loss, params_part, ({}, batch_p), max_iter=4,
                   min_iter=4)
    resumed = fit_map(loss, part.params, ({}, batch_p), max_iter=10,
                      min_iter=10, opt_state0=part.opt_state,
                      losses_prefix=part.losses)

    np.testing.assert_array_equal(full.losses, resumed.losses)
    for k in full.params:
        np.testing.assert_array_equal(np.asarray(full.params[k]),
                                      np.asarray(resumed.params[k]))
    # the resume path copies its inputs before donating: the partial
    # FitResult must stay usable (retry / checkpoint after resume)
    assert not any(v.is_deleted() for v in part.params.values())
    assert not any(leaf.is_deleted()
                   for leaf in jax.tree_util.tree_leaves(part.opt_state))


def test_unhashable_loss_falls_back_cleanly():
    """A lambda loss (identity hash) must still fit correctly through
    the cache (keyed by identity) or the direct-jit fallback."""
    params0, batch = _problem(seed=3)

    fit = fit_map(lambda p, f, b: _PertLossFn(spec=SPEC)(p, f, b),
                  params0, ({}, batch), max_iter=6, min_iter=3)
    assert np.isfinite(fit.losses).all()
    assert fit.num_iters == 6
