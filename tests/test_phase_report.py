"""Phase-report schema: the end-to-end wall must be accounted for.

The perf story of the orchestration layer rests on the ``phases`` dict
(`scRT.phase_report`, passed through to the bench JSON artifacts): every
stage of the pipeline (clone prep, load, per-step build/h2d/trace/
compile/fit, decode, packaging) is a named, measured phase.  This smoke
pins the schema — required keys present, phases non-negative and
non-overlapping enough to sum to >=95% of the measured wall — so the
JSON surface cannot silently rot.
"""

import time

import numpy as np
import pytest

from scdna_replication_tools_tpu.api import scRT

REQUIRED_PHASES = [
    "clone_prep", "load",
    "step1/build", "step1/h2d", "step1/fit",
    "step2/prior", "step2/build", "step2/h2d", "step2/fit",
    "step3/build", "step3/h2d", "step3/fit",
    "package_s/decode", "package_s/fetch", "package_s/package",
    "package_g1/decode", "package_g1/fetch", "package_g1/package",
]


@pytest.fixture(scope="module")
def phase_run(synthetic_frames):
    df_s, df_g = synthetic_frames
    df_s = df_s.assign(reads=np.random.default_rng(0)
                       .poisson(40, len(df_s)).astype(float),
                       state=df_s.true_somatic_cn.astype(int),
                       copy=df_s.true_somatic_cn)
    df_g = df_g.assign(reads=np.random.default_rng(1)
                       .poisson(40, len(df_g)).astype(float),
                       state=df_g.true_somatic_cn.astype(int),
                       copy=df_g.true_somatic_cn)
    scrt = scRT(df_s, df_g, clone_col="clone_id",
                cn_prior_method="g1_clones", max_iter=10, min_iter=5,
                run_step3=True)
    t0 = time.perf_counter()
    scrt.infer(level="pert")
    wall = time.perf_counter() - t0
    return scrt, wall


def test_phase_report_schema(phase_run):
    scrt, _ = phase_run
    report = scrt.phase_report
    assert report is not None
    missing = [k for k in REQUIRED_PHASES if k not in report]
    assert not missing, f"phase report lost keys: {missing}"
    # trace/compile keys exist per step (0.0 on a program-cache hit)
    for step in ("step1", "step2", "step3"):
        assert f"{step}/trace" in report
        assert f"{step}/compile" in report
    assert all(v >= 0.0 for v in report.values())


def test_phases_cover_95_percent_of_wall(phase_run):
    scrt, wall = phase_run
    report = scrt.phase_report
    accounted = report["total_accounted"]
    assert accounted <= wall * 1.02, \
        "phases overlap: accounted exceeds the measured wall"
    assert accounted >= 0.95 * wall, \
        (f"phases cover only {accounted / wall:.1%} of the wall "
         f"({accounted:.2f}s of {wall:.2f}s) — a stage went unaccounted")


@pytest.mark.slow
def test_full_pipeline_bench_json_carries_phases(tmp_path):
    """The bench artifact surface: tiny genome workload end to end
    through tools/full_pipeline_bench.run, asserting the JSON contract
    the committed artifacts (and tpu_window_runner) rely on."""
    import json
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]
                           / "tools"))
    import full_pipeline_bench as fpb

    out_path = tmp_path / "bench.json"
    fpb.main(["--cells", "6", "--g1-cells", "3",
              "--bin-size", "20000000", "--max-iter", "6",
              "--min-iter", "3", "--run-step3",
              "--compile-cache", str(tmp_path / "cache"),
              "--out", str(out_path)])
    out = json.loads(out_path.read_text())
    assert "phases" in out and out["phases"], "bench JSON lost its phases"
    assert out["phase_coverage_of_wall"] >= 0.95
    assert out["non_fit_wall_seconds"] >= 0.0
    assert "step2/fit" in out["phases"]
