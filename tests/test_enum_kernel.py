"""Parity tests for the fused Pallas enumerated-likelihood kernel.

The kernel (ops/enum_kernel.py) must agree with the XLA broadcast path
(models/pert._enum_bin_loglik) — the parity oracle — in both the forward
value and all three gradients.  On CPU the kernel runs through the Pallas
interpreter (``interpret=True``), which executes the identical kernel
body, so these tests validate the TPU code path's math end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.special import digamma as sp_digamma
from scipy.special import gammaln as sp_gammaln

from scdna_replication_tools_tpu.layout import state_major
from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    init_params,
    pert_loss,
)
from scdna_replication_tools_tpu.ops.enum_kernel import (
    _chi_slots,
    _digamma_ge1,
    _lgamma_ge1,
    enum_loglik,
    enum_loglik_fused,
)
from scdna_replication_tools_tpu.ops.gc import gc_features

P = 13


def _problem(C=24, L=300, seed=0):
    # L=300 deliberately not a multiple of the 512 lane tile: exercises
    # the wrapper's padding path
    rng = np.random.default_rng(seed)
    reads = jnp.asarray(rng.poisson(40, (C, L)).astype(np.float32))
    mu = jnp.asarray(rng.uniform(2, 30, (C, L)).astype(np.float32))
    logits = jnp.asarray(rng.normal(0, 2, (C, L, P)).astype(np.float32))
    phi = jnp.asarray(rng.uniform(0.01, 0.99, (C, L)).astype(np.float32))
    return reads, mu, logits, phi, jnp.float32(0.75)


def _xla_oracle(reads, mu, log_pi, phi, lamb, P=P):
    from jax.scipy.special import gammaln, logsumexp
    chi = jnp.arange(P, dtype=jnp.float32)[:, None] * \
        (1.0 + jnp.arange(2, dtype=jnp.float32))[None, :]
    delta = jnp.maximum(mu[..., None, None] * chi * (1 - lamb) / lamb, 1.0)
    nb = (gammaln(reads[..., None, None] + delta) - gammaln(delta)
          - gammaln(reads[..., None, None] + 1.0)
          + delta * jnp.log1p(-lamb) + reads[..., None, None] * jnp.log(lamb))
    bern = jnp.stack([jnp.log1p(-phi), jnp.log(phi)], -1)
    joint = log_pi[..., :, None] + bern[..., None, :] + nb
    return logsumexp(joint, axis=(-2, -1))


def test_lgamma_digamma_approximations():
    z = np.random.default_rng(1).uniform(1.0, 5e4, 50000).astype(np.float32)
    lg = np.asarray(_lgamma_ge1(jnp.asarray(z)), np.float64)
    dg = np.asarray(_digamma_ge1(jnp.asarray(z)), np.float64)
    rel = np.abs(lg - sp_gammaln(z)) / np.maximum(np.abs(sp_gammaln(z)), 1.0)
    assert rel.max() < 1e-5
    assert np.abs(dg - sp_digamma(z)).max() < 1e-4


def test_fused_lgamma_digamma_matches_separate_helpers():
    """The backward kernels' fused evaluation must be bit-identical to the
    separate helpers it replaced (same ops, same order per output)."""
    from scdna_replication_tools_tpu.ops.enum_kernel import (
        _lgamma_digamma_ge1,
    )

    z = jnp.asarray(np.random.default_rng(2)
                    .uniform(1.0, 5e4, 20000).astype(np.float32))
    lg_f, dg_f = _lgamma_digamma_ge1(z)
    np.testing.assert_array_equal(np.asarray(lg_f),
                                  np.asarray(_lgamma_ge1(z)))
    np.testing.assert_array_equal(np.asarray(dg_f),
                                  np.asarray(_digamma_ge1(z)))


@pytest.mark.parametrize("P_", [1, 2, 3, 7, 13, 16])
def test_chi_slots_cover_every_state_rep_pair_once(P_):
    """The chi-dedup table must enumerate each (state, rep) pair exactly
    once with the correct chi = s * (1 + r), for ANY P (P is a config
    knob, not a constant)."""
    seen = {}
    for chi, pairs in _chi_slots(P_):
        for s, r in pairs:
            assert (s, r) not in seen, (s, r)
            seen[(s, r)] = chi
            assert chi == float(s * (1 + r)), (s, r, chi)
    assert len(seen) == 2 * P_
    # the dedup must actually dedup: distinct chi count < pair count
    # whenever a collision exists (P >= 3 has s=2, r=0 vs s=1, r=1)
    if P_ >= 3:
        assert len(_chi_slots(P_)) < 2 * P_


@pytest.mark.parametrize("P_", [3, 7])
def test_forward_parity_at_nondefault_P(P_):
    """Kernel parity at P values other than 13 pins _chi_slots + the
    unrolled loops' generality (P is PertConfig-settable)."""
    rng = np.random.default_rng(17)
    C, L = 8, 96
    reads = jnp.asarray(rng.poisson(30, (C, L)).astype(np.float32))
    mu = jnp.asarray(rng.uniform(2, 20, (C, L)).astype(np.float32))
    logits = jnp.asarray(rng.normal(0, 2, (C, L, P_)).astype(np.float32))
    phi = jnp.asarray(rng.uniform(0.05, 0.95, (C, L)).astype(np.float32))
    lamb = jnp.float32(0.7)
    log_pi = jax.nn.log_softmax(logits, -1)

    ll_ref = _xla_oracle(reads, mu, log_pi, phi, lamb, P=P_)
    ll_pal = enum_loglik(reads, mu, log_pi, phi, lamb, True)
    rel = jnp.max(jnp.abs(ll_ref - ll_pal) / (jnp.abs(ll_ref) + 1.0))
    assert float(rel) < 1e-3, float(rel)


def test_forward_parity_with_xla_oracle():
    reads, mu, logits, phi, lamb = _problem()
    log_pi = jax.nn.log_softmax(logits, -1)
    ll_ref = _xla_oracle(reads, mu, log_pi, phi, lamb)
    ll_pal = enum_loglik(reads, mu, log_pi, phi, lamb, True)
    err = jnp.max(jnp.abs(ll_ref - ll_pal))
    assert float(err) < 5e-2, float(err)


def test_gradient_parity_with_xla_oracle():
    reads, mu, logits, phi, lamb = _problem(C=8, L=96)
    w = jnp.asarray(np.random.default_rng(2).normal(0, 1, reads.shape),
                    jnp.float32)

    def loss(fn, mu, logits, phi):
        return jnp.sum(fn(reads, mu, jax.nn.log_softmax(logits, -1),
                          phi, lamb) * w)

    g_ref = jax.grad(lambda *a: loss(_xla_oracle, *a), (0, 1, 2))(
        mu, logits, phi)
    g_pal = jax.grad(
        lambda *a: loss(lambda *b: enum_loglik(*b, True), *a), (0, 1, 2))(
        mu, logits, phi)
    for a, b in zip(g_ref, g_pal):
        rel = jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(a)) + 1e-30)
        assert float(rel) < 2e-2, float(rel)


def _fused_xla_oracle(reads, mu, pi_logits, phi, etas, lamb):
    """XLA transcription of the fused objective: enumerated likelihood
    plus the Dirichlet data term sum_s (etas_s - 1) * log_softmax(pi)_s."""
    log_pi = jax.nn.log_softmax(pi_logits, -1)
    return _xla_oracle(reads, mu, log_pi, phi, lamb) \
        + jnp.sum((etas - 1.0) * log_pi, axis=-1)


@pytest.mark.parametrize("etas_kind", ["random_small", "concentrated_1e6"])
def test_fused_gradient_parity_with_xla_oracle(etas_kind):
    """Direct gradient test of enum_loglik_fused with RANDOM etas and
    random cotangents.

    The fused backward applies the softmax Jacobian itself — dpi_s =
    dlog_pi_s - softmax_s * tot, where tot accumulates BOTH the posterior
    weights and the g*(etas-1) Dirichlet term (ops/enum_kernel.py, the
    `tot` carry of _fused_bwd_kernel).  The near-one-hot etas of the full
    -loss parity tests barely exercise that correction; random etas and
    cotangents pin it against jax.grad through the XLA oracle.
    """
    reads, mu, logits, phi, lamb = _problem(C=8, L=96, seed=7)
    rng = np.random.default_rng(11)
    if etas_kind == "random_small":
        etas = jnp.asarray(rng.uniform(0.3, 5.0, logits.shape)
                           .astype(np.float32))
    else:
        # the production regime: one state per bin carries the 1e6
        # prior concentration (cn_prior_weight), the rest stay at 1
        etas_np = np.ones(logits.shape, np.float32)
        states = rng.integers(0, P, reads.shape)
        np.put_along_axis(etas_np, states[..., None], 1e6, axis=-1)
        etas = jnp.asarray(etas_np)
    w = jnp.asarray(rng.normal(0, 1, reads.shape), jnp.float32)

    def loss(fn, mu, logits, phi):
        return jnp.sum(fn(reads, mu, logits, phi, etas, lamb) * w)

    def fused_cm(reads, mu, logits, phi, etas, lamb):
        # the kernel's contract is STATE-MAJOR (P, C, L); the oracle stays
        # cells-major, so transpose inside the differentiated function —
        # jax maps the dpi cotangent back through the transpose for us
        return enum_loglik_fused(reads, mu, state_major(logits), phi,
                                 state_major(etas), lamb, True)

    g_ref = jax.grad(lambda *a: loss(_fused_xla_oracle, *a), (0, 1, 2))(
        mu, logits, phi)
    g_pal = jax.grad(lambda *a: loss(fused_cm, *a), (0, 1, 2))(
        mu, logits, phi)

    out_ref = _fused_xla_oracle(reads, mu, logits, phi, etas, lamb)
    out_pal = fused_cm(reads, mu, logits, phi, etas, lamb)
    fwd_rel = jnp.max(jnp.abs(out_ref - out_pal)) \
        / (jnp.max(jnp.abs(out_ref)) + 1e-30)
    assert float(fwd_rel) < 1e-4, float(fwd_rel)

    for name, a, b in zip(("dmu", "dpi_logits", "dphi"), g_ref, g_pal):
        rel = jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(a)) + 1e-30)
        assert float(rel) < 2e-2, (name, float(rel))


def test_extreme_values_stay_finite_and_match_oracle():
    """Kernel robustness at the data extremes the clamps exist for:
    zero-read bins, near-zero and huge rates, phi at its clamp bounds.
    The padded-region sentinels guard padding; this pins the REAL-bin
    extremes against the XLA oracle."""
    C, L = 8, 128
    rng = np.random.default_rng(13)
    reads = rng.poisson(40, (C, L)).astype(np.float32)
    reads[0, :] = 0.0                      # empty cell
    reads[:, 0] = 0.0                      # empty locus
    reads[1, 1] = 5e4                      # read pileup
    mu = rng.uniform(2, 30, (C, L)).astype(np.float32)
    mu[2, :] = 1e-6                        # ~zero rate
    mu[3, :] = 1e4                         # huge rate
    phi = np.clip(rng.uniform(0, 1, (C, L)), 0.001, 0.999).astype(np.float32)
    phi[4, :] = 0.001                      # clamp floor (pert.py PHI_LO)
    phi[5, :] = 0.999                      # clamp ceil
    logits = rng.normal(0, 2, (C, L, P)).astype(np.float32)
    logits[6, :, 0] = 40.0                 # near-one-hot simplex
    reads, mu, phi, logits = map(jnp.asarray, (reads, mu, phi, logits))
    lamb = jnp.float32(0.75)

    log_pi = jax.nn.log_softmax(logits, -1)
    ll_ref = _xla_oracle(reads, mu, log_pi, phi, lamb)
    ll_pal = enum_loglik(reads, mu, log_pi, phi, lamb, True)
    assert bool(jnp.isfinite(ll_pal).all())
    # per-bin RELATIVE bound: the 5e4-read bin has |ll| in the thousands
    # where both the kernel's Stirling lgamma and the oracle's f32
    # gammaln carry O(0.01) absolute rounding — relative is the honest
    # metric across 5 orders of magnitude of ll
    rel = jnp.max(jnp.abs(ll_ref - ll_pal) / (jnp.abs(ll_ref) + 1.0))
    assert float(rel) < 1e-3, float(rel)

    # gradients at the extremes must also be finite
    g = jax.grad(lambda m: jnp.sum(enum_loglik(reads, m, log_pi, phi,
                                               lamb, True)))(mu)
    assert bool(jnp.isfinite(g).all())


def test_layout_contract_raises_on_cells_major_input():
    """Feeding the fused kernel the old cells-major layout (round 4's
    regression: silent NaN garbage) must raise, not compute."""
    reads, mu, logits, phi, lamb = _problem(C=8, L=96)
    etas = jnp.ones_like(logits)
    with pytest.raises(ValueError, match="STATE-MAJOR"):
        enum_loglik_fused(reads, mu, logits, phi, etas, lamb, True)
    # and the unfused kernel rejects state-major input symmetrically
    with pytest.raises(ValueError, match="CELLS-MAJOR"):
        enum_loglik(reads, mu, state_major(logits), phi, lamb, True)


def test_pert_loss_parity_between_impls():
    """Full model loss must match between the XLA and kernel paths.

    Tolerance rationale (this test failed for several rounds at a 1e-5
    loss bound — root cause, established by measurement): the kernel's
    Stirling ``_lgamma_ge1`` carries up to ~3e-6 relative error vs the
    true log-Gamma, and that error is SYSTEMATIC in sign (a truncated
    asymptotic series, not rounding noise), so summing ~2,400 bins
    accumulates it linearly instead of averaging it out — the summed
    loss inherits the kernel's ~1e-4 PER-BIN relative accuracy (at this
    problem: |diff| ~ 29 on a ~2.9e5-magnitude loss = 9.8e-5, i.e.
    ~0.012 per bin on per-bin terms of ~-120).  A 1e-5 bound on the
    TOTAL therefore demanded more accuracy than the kernel's own
    documented per-bin contract (the forward-parity tests above bound
    per-bin relative error at 1e-3); 5e-4 is the honest bound.
    Gradients are ratio-based (posterior weights normalise inside the
    logsumexp), so the systematic lgamma offset largely cancels there —
    their bound stays tight.
    """
    rng = np.random.default_rng(3)
    C, L = 12, 200
    reads = rng.poisson(40, (C, L)).astype(np.float32)
    gammas = rng.uniform(0.35, 0.6, L).astype(np.float32)
    etas = np.ones((C, L, P), np.float32)
    etas[:, :, 2] = 1e5

    batch = PertBatch(
        reads=jnp.asarray(reads), libs=jnp.zeros((C,), jnp.int32),
        gamma_feats=gc_features(jnp.asarray(gammas), 4),
        mask=jnp.ones((C,), jnp.float32), etas=jnp.asarray(etas))
    fixed = {"beta_means": jnp.zeros((1, 5), jnp.float32),
             "lamb": jnp.asarray(0.75, jnp.float32)}

    losses = {}
    grads = {}
    for impl in ("xla", "pallas_interpret"):
        spec = PertModelSpec(P=P, K=4, L=1, tau_mode="param",
                             cond_beta_means=True, fixed_lamb=True,
                             enum_impl=impl)
        params = init_params(spec, batch, fixed,
                             t_init=np.full(C, 0.4, np.float32))
        losses[impl], grads[impl] = jax.value_and_grad(
            lambda p: pert_loss(spec, p, fixed, batch))(params)

    rel = abs(float(losses["xla"]) - float(losses["pallas_interpret"])) \
        / abs(float(losses["xla"]))
    assert rel < 5e-4, rel
    for k in grads["xla"]:
        a, b = grads["xla"][k], grads["pallas_interpret"][k]
        denom = float(jnp.max(jnp.abs(a))) + 1e-20
        assert float(jnp.max(jnp.abs(a - b))) / denom < 2e-2, k
