"""Topology-portable durable runs (ISSUE 13).

The chaos matrix behind the tentpole: checkpoints stamped with their
save-time topology (mesh axes, process count, per-leaf PartitionSpecs),
two-phase-committed sharded generations whose crash windows can never
expose a mixed or partial step to ``--resume auto``, reshard resume
(any mesh restores onto any mesh — bit-exact when the reduction
geometry is unchanged, parity-gated when it is not), the elastic
mesh-shrink rung of the recovery ladder, process-scoped fault rules,
and the multi-host manifest identity (per-host fingerprints + the
deduplicated fingerprint-of-fingerprints).

Fast shapes run in tier-1; the wider reshard matrix is ``slow``.
"""

import json
import shutil

import numpy as np
import pytest

from scdna_replication_tools_tpu.config import PertConfig
from scdna_replication_tools_tpu.infer import checkpoint as ckpt
from scdna_replication_tools_tpu.infer import manifest as manifest_mod
from scdna_replication_tools_tpu.infer.runner import PertInference
from scdna_replication_tools_tpu.obs.schema import validate_run
from scdna_replication_tools_tpu.parallel import mesh as mesh_mod
from scdna_replication_tools_tpu.utils import faults as faults_mod

from conftest import dense_inputs_from_frames as _dense_inputs  # noqa: E402


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    yield
    faults_mod.install(None)


# same budget discipline as test_resilience: controller ON, rel_tol=0,
# bounded extensions — deterministic and CI-cheap (budgets sized to the
# tier-1 wall: 3 chunks of 25, preempt lands at chunk #2)
BASE = dict(cn_prior_method="g1_clones", rel_tol=0.0, run_step3=False,
            max_iter=75, min_iter=25, max_iter_step1=20,
            min_iter_step1=10, fit_diag_every=25,
            controller_max_extra_iters=25, telemetry_path=None)
# the MULTICHIP parity geometry: 4 cell shards x 2 loci shards over the
# conftest-forced 8 host CPU devices
MESH_4x2 = dict(num_shards=4, loci_shards=2)


def _run_pipeline(synthetic_frames, config):
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    return inf, inf.run()


def _events(path):
    return [json.loads(line) for line in path.read_text().splitlines()]


def _tau(fit_result):
    return 1.0 / (1.0 + np.exp(-np.asarray(
        fit_result.params["tau_raw"], np.float64)))


def _assert_tau_parity(g_tau, r_tau, max_boundary_outliers=2):
    """Cross-topology tau parity, honest about the mirror ambiguity.

    tau and 1-tau parameterise the same replication state up to the
    mirror symmetry (PYRO_PARITY.md), and a bistable BOUNDARY cell can
    legitimately land in either basin when the reduction geometry
    changes — the rescue's per-cell objective comparison is a
    knife-edge there, and its sub-fit refits the flipped cell to a
    fresh optimum.  So: every cell must match within 0.05 after
    folding over the mirror, EXCEPT a bounded handful of outliers that
    must each be boundary-extreme (tau < 0.05 or > 0.95) in the golden
    arm — exactly the cells ``cell_qc`` flags as ``boundary_tau``.
    UNfolded bit-equality remains the same-geometry contract."""
    folded = np.minimum(np.abs(g_tau - r_tau),
                        np.abs(g_tau - (1.0 - r_tau)))
    outliers = folded >= 0.05
    assert int(outliers.sum()) <= max_boundary_outliers, folded
    assert np.all((g_tau[outliers] < 0.05) | (g_tau[outliers] > 0.95)), \
        (g_tau[outliers], r_tau[outliers])


# ---------------------------------------------------------------------------
# process-scoped fault rules + hostloss
# ---------------------------------------------------------------------------


def test_fault_rule_process_scope_parsing():
    plan = faults_mod.FaultPlan.from_spec(
        "preempt@step2/chunk#2@proc1,oom@x@proc0,hostloss@y#3@proc*")
    assert plan.rules[0].proc == 1 and plan.rules[0].first == 2
    assert plan.rules[1].proc == 0
    # '@proc*' is the explicit spelling of "every process"
    assert plan.rules[2].proc is None and plan.rules[2].first == 3


def test_fault_rule_bad_process_scope_rejected():
    with pytest.raises(ValueError):
        faults_mod.FaultPlan.from_spec("preempt@site@host1")
    with pytest.raises(ValueError):
        faults_mod.FaultPlan.from_spec("preempt@site@procX")


def test_process_scoped_rule_fires_only_in_its_process():
    spec = "preempt@s#2@proc1"
    # rank 1 sees the fault at hit 2; rank 0 never does — but the hit
    # COUNT advances identically in both (same deterministic schedule)
    plan = faults_mod.FaultPlan.from_spec(spec)
    assert plan.check("s", proc=1) is None
    assert plan.check("s", proc=1).kind == "preempt"
    plan0 = faults_mod.FaultPlan.from_spec(spec)
    assert plan0.check("s", proc=0) is None
    assert plan0.check("s", proc=0) is None
    assert plan0.check("s", proc=0) is None
    # the firing record carries the scope
    assert plan.fired[0]["proc"] == 1


def test_hostloss_kind_raises_and_classifies():
    faults_mod.install(faults_mod.FaultPlan.from_spec("hostloss@z"))
    with pytest.raises(faults_mod.SimulatedHostLoss) as exc_info:
        faults_mod.point("z")
    assert faults_mod.classify_exception(exc_info.value) == "hostloss"
    # the real XLA statuses a dying peer surfaces classify the same way
    assert faults_mod.classify_exception(
        RuntimeError("DATA_LOSS: device lost")) == "hostloss"
    # DATA_LOSS outranks the transient markers: retrying on the same
    # mesh cannot succeed, the elastic rung must get it instead
    assert faults_mod.classify_exception(
        RuntimeError("DATA_LOSS: connection reset by peer")) == "hostloss"


# ---------------------------------------------------------------------------
# topology stamp round-trip
# ---------------------------------------------------------------------------


def test_topology_stamp_roundtrip(tmp_path):
    mesh = mesh_mod.make_mesh(4, loci_shards=2)
    params = {"tau_raw": np.arange(24.0, dtype=np.float32)}
    ckpt.save_step(str(tmp_path), "step2", params,
                   np.zeros(3, np.float32), num_iters=3,
                   converged=False, mesh=mesh)
    _, _, extra = ckpt.load_step(str(tmp_path), "step2")
    topo = extra["meta.topology"]
    assert topo["mesh_axes"] == {"cells": 4, "loci": 2}
    assert topo["process_count"] == 1
    assert topo["num_devices"] >= 8
    # per-leaf layout contract from layout.param_layouts: the big pi
    # tensor is state-major with cells on axis 1
    pi = topo["param_layouts"]["pi_logits"]
    assert pi["cells_axis"] == 1
    assert pi["dims"][pi["cells_axis"]] == "cells"
    assert int(extra["meta.format_version"]) >= 4


def test_unstamped_v3_checkpoint_still_loads(tmp_path):
    """Pre-v4 files carry no stamp: geometry unknown, not an error."""
    import io
    import struct

    flat = {"param.tau_raw": np.ones(4, np.float32),
            "losses": np.zeros(2, np.float32),
            "meta.format_version": np.asarray(3),
            "meta.num_iters": np.asarray(2),
            "meta.converged": np.asarray(False),
            "meta.nan_abort": np.asarray(False)}
    buf = io.BytesIO()
    np.savez(buf, **flat)
    payload = buf.getvalue()
    import hashlib

    footer = (b"PERTCK01" + struct.pack("<Q", len(payload))
              + hashlib.sha256(payload).digest())
    (tmp_path / "pert_step2.npz").write_bytes(payload + footer)
    params, losses, extra = ckpt.load_step(str(tmp_path), "step2")
    assert "meta.topology" not in extra
    np.testing.assert_array_equal(params["tau_raw"], np.ones(4))


# ---------------------------------------------------------------------------
# two-phase commit: crash windows
# ---------------------------------------------------------------------------


def _host_flat(k, full, step="step2", iters=10):
    """One simulated host's flat checkpoint mapping: its half of a
    24-cell tau plus replicated meta — the exact sidecar layout
    ``_flat_add`` emits for a multi-host global array."""
    half = full[k * 12:(k + 1) * 12]
    return {
        f"param.tau_raw": np.asarray(half),
        f"range.param.tau_raw": np.asarray([[k * 12, (k + 1) * 12]],
                                           np.int64),
        f"gshape.param.tau_raw": np.asarray([24], np.int64),
        "losses": np.arange(iters, dtype=np.float32),
        "meta.format_version": np.asarray(ckpt.CHECKPOINT_FORMAT_VERSION),
        "meta.num_iters": np.asarray(iters),
        "meta.converged": np.asarray(False),
        "meta.nan_abort": np.asarray(False),
        "meta.topology": np.asarray(json.dumps(ckpt.topology_stamp(None))),
    }


def _write_generation(ck, full, iters=10):
    """Both hosts write, then host 0 commits (the barrier is a
    single-process no-op here; the serialisation order mirrors the real
    rendezvous: every shard exists before the commit pointer does)."""
    ckpt._save_step_multiprocess(str(ck), "step2",
                                 _host_flat(1, full, iters=iters),
                                 2, 1, None)
    ckpt._save_step_multiprocess(str(ck), "step2",
                                 _host_flat(0, full, iters=iters),
                                 2, 0, None)


def test_sharded_generation_merges_across_hosts(tmp_path):
    full = np.arange(24.0, dtype=np.float32)
    _write_generation(tmp_path, full)
    params, losses, extra = ckpt.load_step(str(tmp_path), "step2")
    np.testing.assert_array_equal(params["tau_raw"], full)
    assert int(extra["meta.num_iters"]) == 10
    doc = json.loads((tmp_path / "pert_step2.commit.json").read_text())
    assert doc["process_count"] == 2 and doc["seq"] == 1


def test_uncommitted_generation_is_invisible(tmp_path):
    """Crash between shard-write and manifest-commit: the new
    generation's shard files exist but no commit points at them — the
    PREVIOUS complete generation is what load_step sees."""
    old = np.arange(24.0, dtype=np.float32)
    _write_generation(tmp_path, old, iters=10)
    # seq 2: host 1 wrote its shard, then the preemption hit before the
    # barrier — no commit, and host 0's shard never landed
    ckpt._save_step_multiprocess(str(tmp_path), "step2",
                                 _host_flat(1, old + 100.0, iters=20),
                                 2, 1, None)
    params, _, extra = ckpt.load_step(str(tmp_path), "step2")
    np.testing.assert_array_equal(params["tau_raw"], old)
    assert int(extra["meta.num_iters"]) == 10


def test_corrupt_committed_generation_falls_back_to_previous(tmp_path):
    old = np.arange(24.0, dtype=np.float32)
    new = old + 7.0
    _write_generation(tmp_path, old, iters=10)
    _write_generation(tmp_path, new, iters=20)
    # the committed seq-2 generation loses a shard to corruption: the
    # multi-file analog of the .prev fallback restores seq 1
    shard = tmp_path / "pert_step2.s2.p1of2.npz"
    shard.write_bytes(shard.read_bytes()[:100])
    params, _, extra = ckpt.load_step(str(tmp_path), "step2")
    np.testing.assert_array_equal(params["tau_raw"], old)
    assert int(extra["meta.num_iters"]) == 10


def test_emergency_save_is_uncoordinated(tmp_path, monkeypatch):
    """A dying process saves phase 1 only: its shard file, no barrier,
    no commit — the generation stays invisible to resume."""
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    ckpt.save_step(str(tmp_path), "step2",
                   {"tau_raw": np.ones(12, np.float32)},
                   np.zeros(2, np.float32), num_iters=2,
                   converged=False, coordinate=False)
    assert (tmp_path / "pert_step2.s1.p1of2.npz").exists()
    assert not (tmp_path / "pert_step2.commit.json").exists()
    assert ckpt.load_step(str(tmp_path), "step2") is None


def test_quarantine_retires_commit_pointers(tmp_path):
    full = np.arange(24.0, dtype=np.float32)
    _write_generation(tmp_path, full)
    moved = ckpt.quarantine_stale(str(tmp_path))
    assert moved >= 3   # 2 shard files + the commit pointer
    assert ckpt.load_step(str(tmp_path), "step2") is None


# ---------------------------------------------------------------------------
# multi-host manifest identity
# ---------------------------------------------------------------------------


def test_combined_fingerprint_dedupes_identical_hosts():
    # the loader bridge: every host digests the same full batch, so the
    # combined identity IS the local one — host-count-portable
    assert manifest_mod.combined_fingerprint({0: "abc", 1: "abc"}) == "abc"
    assert manifest_mod.combined_fingerprint({0: "abc"}) == "abc"
    # genuinely different shards: an ordered fingerprint-of-fingerprints
    combined = manifest_mod.combined_fingerprint({0: "abc", 1: "xyz"})
    assert combined not in ("abc", "xyz") and len(combined) == 16
    assert combined == manifest_mod.combined_fingerprint(
        {1: "xyz", 0: "abc"})   # rank order, not dict order
    assert combined != manifest_mod.combined_fingerprint(
        {0: "xyz", 1: "abc"})


def test_all_host_fingerprints_single_process():
    assert manifest_mod.all_host_fingerprints("fp") == {0: "fp"}


def test_manifest_per_host_fallback(tmp_path, monkeypatch):
    m = manifest_mod.RunManifest.load(tmp_path)
    m.begin_run("cfg", "combined", host_fingerprints={0: "h0", 1: "h1"})
    m2 = manifest_mod.RunManifest.load(tmp_path)
    # the fallback is a SAME-SHAPE instrument: this (1-process) run
    # does not match the recorded 2-host shape, so the drifted combined
    # digest refuses even though rank 1's shard digest matches — the
    # missing rank's recorded data would otherwise go unverified
    ok, _ = m2.match("cfg", "other", host_fingerprint="h1",
                     process_index=1)
    assert not ok
    # same shape (2 live ranks): THIS rank's matching shard verifies
    from scdna_replication_tools_tpu.parallel import distributed

    monkeypatch.setattr(distributed, "process_rank_and_count",
                        lambda: (1, 2))
    ok, reason = m2.match("cfg", "other", host_fingerprint="h1",
                          process_index=1)
    assert ok and "per-host" in reason
    # wrong per-host digest still refuses
    ok, _ = m2.match("cfg", "other", host_fingerprint="nope",
                     process_index=1)
    assert not ok
    # combined match needs no fallback
    assert m2.match("cfg", "combined")[0]


def test_manifest_records_and_clears_host_fingerprints(tmp_path):
    m = manifest_mod.RunManifest.load(tmp_path)
    m.begin_run("cfg", "fp", host_fingerprints={0: "a", 1: "b"})
    assert manifest_mod.RunManifest.load(tmp_path).doc[
        "host_fingerprints"] == {"0": "a", "1": "b"}
    # a later single-host run retires the stale per-host map
    m.begin_run("cfg", "fp", host_fingerprints={0: "fp"})
    assert "host_fingerprints" not in manifest_mod.RunManifest.load(
        tmp_path).doc


# ---------------------------------------------------------------------------
# elastic mesh-shrink ladder (units)
# ---------------------------------------------------------------------------


def test_shrink_mesh_ladder_order():
    rungs = []
    mesh = mesh_mod.make_mesh(4, loci_shards=2)
    while mesh is not None:
        mesh = mesh_mod.shrink_mesh(mesh)
        if mesh is not None:
            rungs.append(dict(mesh.shape))
    # halve cells while the loci extent survives, collapse loci at the
    # bottom, stop at the minimal 1-device mesh (1-D: make_mesh drops
    # the loci axis at extent 1)
    assert rungs == [{"cells": 2, "loci": 2},
                     {"cells": 1, "loci": 2},
                     {"cells": 1}]


def test_shrink_mesh_minimal_is_exhausted():
    assert mesh_mod.shrink_mesh(mesh_mod.make_mesh(1, loci_shards=1)) \
        is None


# ---------------------------------------------------------------------------
# reshard resume matrix + elastic rung (integration, fast shapes)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def sharded_golden(synthetic_frames):
    """Uninterrupted 4x2-mesh reference run."""
    _, (s1, s2, _) = _run_pipeline(
        synthetic_frames, PertConfig(**{**BASE, **MESH_4x2}))
    return s1, s2


@pytest.fixture(scope="module")
def killed_4x2(synthetic_frames, tmp_path_factory):
    """A 4x2 fit preempted mid-step-2, leaving a stamped checkpoint
    directory every reshard-resume case below copies fresh."""
    root = tmp_path_factory.mktemp("killed_4x2")
    cfg = PertConfig(**{**BASE, **MESH_4x2,
                        "checkpoint_dir": str(root / "ck"),
                        "checkpoint_every": 1,
                        "faults": "preempt@step2/chunk#2",
                        "telemetry_path": str(root / "killed.jsonl")})
    with pytest.raises(faults_mod.SimulatedPreemption):
        _run_pipeline(synthetic_frames, cfg)
    faults_mod.install(None)
    assert list((root / "ck").glob("pert_step2*.npz"))
    return root / "ck"


def _resume(synthetic_frames, killed_ck, tmp_path, **mesh_kw):
    ck = tmp_path / "ck"
    shutil.copytree(killed_ck, ck)
    log = tmp_path / "resumed.jsonl"
    cfg = PertConfig(**{**BASE, **mesh_kw, "checkpoint_dir": str(ck),
                        "checkpoint_every": 1,
                        "telemetry_path": str(log)})
    _, (r1, r2, _) = _run_pipeline(synthetic_frames, cfg)
    assert validate_run(log) == []
    return r2, _events(log)


def test_same_mesh_resume_is_bit_exact(sharded_golden, killed_4x2,
                                       synthetic_frames, tmp_path):
    """4x2 -> 4x2: the reduction geometry is unchanged, so the resumed
    trajectory must be BIT-exact against the uninterrupted golden."""
    _, g2 = sharded_golden
    r2, events = _resume(synthetic_frames, killed_4x2, tmp_path,
                         **MESH_4x2)
    np.testing.assert_array_equal(r2.fit.losses, g2.fit.losses)
    np.testing.assert_array_equal(np.asarray(r2.fit.params["tau_raw"]),
                                  np.asarray(g2.fit.params["tau_raw"]))
    resumes = [ev for ev in events if ev["event"] == "resume"
               and ev.get("action") in ("restored", "resumed")]
    assert resumes and all(not ev["resharded"] for ev in resumes)


def test_reshard_resume_4x2_to_single_device(sharded_golden, killed_4x2,
                                             synthetic_frames, tmp_path):
    """4x2 -> single device (mesh None): the checkpoint reassembles and
    re-places on the shrunk topology; the continued trajectory is
    parity-gated (the psum geometry changed — Adam amplifies the
    reassociation epsilon, see test_padding_and_chunking)."""
    _, g2 = sharded_golden
    r2, events = _resume(synthetic_frames, killed_4x2, tmp_path,
                         num_shards=1, loci_shards=1)
    _assert_tau_parity(_tau(g2.fit), _tau(r2.fit))
    # the continued loss trajectory itself stays within the measured
    # cross-geometry envelope (reassociation epsilon through Adam)
    np.testing.assert_allclose(np.asarray(r2.fit.losses),
                               np.asarray(g2.fit.losses), rtol=5e-2)
    resumes = [ev for ev in events if ev["event"] == "resume"
               and ev.get("action") in ("restored", "resumed")]
    assert any(ev["resharded"] for ev in resumes)
    step2 = next(ev for ev in resumes if ev["step"] == "step2")
    assert step2["from_topology"]["mesh_axes"] == {"cells": 4, "loci": 2}
    assert step2["to_topology"]["mesh_axes"] == {}


@pytest.mark.slow
def test_reshard_resume_2x2_to_4x2(synthetic_frames, tmp_path):
    """Growing the mesh is the same contract as shrinking it."""
    ck = tmp_path / "ck"
    cfg_kill = PertConfig(**{**BASE, "num_shards": 2, "loci_shards": 2,
                             "checkpoint_dir": str(ck),
                             "checkpoint_every": 1,
                             "faults": "preempt@step2/chunk#2"})
    with pytest.raises(faults_mod.SimulatedPreemption):
        _run_pipeline(synthetic_frames, cfg_kill)
    faults_mod.install(None)
    log = tmp_path / "resumed.jsonl"
    cfg = PertConfig(**{**BASE, **MESH_4x2, "checkpoint_dir": str(ck),
                        "checkpoint_every": 1,
                        "telemetry_path": str(log)})
    _, (_, r2, _) = _run_pipeline(synthetic_frames, cfg)
    events = _events(log)
    assert any(ev["event"] == "resume" and ev.get("resharded")
               for ev in events)
    assert np.all(np.isfinite(np.asarray(r2.fit.losses)))


def test_hostloss_walks_elastic_rung_to_golden(sharded_golden,
                                               synthetic_frames,
                                               tmp_path):
    """A hostloss mid-sharded-fit must shrink the mesh (audited
    ``degrade mesh_shrink`` with before/after topology), re-place the
    last checkpoint, and still land on golden tau within parity."""
    _, g2 = sharded_golden
    log = tmp_path / "t.jsonl"
    cfg = PertConfig(**{**BASE, **MESH_4x2,
                        "checkpoint_dir": str(tmp_path / "ck"),
                        "checkpoint_every": 1,
                        "faults": "hostloss@step2/chunk#2",
                        "telemetry_path": str(log)})
    _, (_, r2, _) = _run_pipeline(synthetic_frames, cfg)
    events = _events(log)
    shrinks = [ev for ev in events if ev["event"] == "degrade"
               and ev.get("action") == "mesh_shrink"]
    assert len(shrinks) == 1
    assert shrinks[0]["from_topology"]["mesh_axes"] == \
        {"cells": 4, "loci": 2}
    assert shrinks[0]["to_topology"]["mesh_axes"] == \
        {"cells": 2, "loci": 2}
    assert shrinks[0]["error_class"] == "hostloss"
    assert validate_run(log) == []
    _assert_tau_parity(_tau(g2.fit), _tau(r2.fit))
    # the counter behind pert_mesh_shrinks_total rides the same events
    snaps = [ev for ev in events if ev["event"] == "metrics_snapshot"]
    if snaps:
        assert snaps[-1]["metrics"].get(
            "pert_mesh_shrinks_total", {}).get("value", 0) >= 1


def test_first_oom_reenters_same_mesh_before_shrinking(synthetic_frames,
                                                       tmp_path):
    """Shrinking the cells axis RAISES per-device load, so a single
    OOM must not walk the ladder: the first gets one audited same-mesh
    re-entry (resuming the checkpoint), only the REPEAT shrinks."""
    log = tmp_path / "t.jsonl"
    cfg = PertConfig(**{**BASE, **MESH_4x2,
                        "checkpoint_dir": str(tmp_path / "ck"),
                        "checkpoint_every": 1,
                        "faults": "oom@step2/chunk#2-3",
                        "telemetry_path": str(log)})
    _, (_, r2, _) = _run_pipeline(synthetic_frames, cfg)
    events = _events(log)
    retries = [ev for ev in events if ev["event"] == "retry"
               and ev.get("label") == "step2/fit-oom"]
    assert len(retries) == 1 and retries[0]["error_class"] == "oom"
    shrinks = [ev for ev in events if ev["event"] == "degrade"
               and ev.get("action") == "mesh_shrink"]
    assert len(shrinks) == 1
    assert shrinks[0]["error_class"] == "oom"
    assert shrinks[0]["from_topology"]["mesh_axes"] == \
        {"cells": 4, "loci": 2}
    # the retry precedes the shrink: same-mesh first, ladder second
    assert events.index(retries[0]) < events.index(shrinks[0])
    assert np.all(np.isfinite(np.asarray(r2.fit.losses)))
    assert validate_run(log) == []


def test_elastic_rung_disabled_aborts_resumable(synthetic_frames,
                                                tmp_path):
    """``elastic_mesh=False``: the pre-elastic contract — abort with a
    resumable artifact and the ``abort_resumable`` audit."""
    log = tmp_path / "t.jsonl"
    cfg = PertConfig(**{**BASE, **MESH_4x2, "elastic_mesh": False,
                        "checkpoint_dir": str(tmp_path / "ck"),
                        "checkpoint_every": 1,
                        "faults": "hostloss@step2/chunk#2",
                        "telemetry_path": str(log)})
    with pytest.raises(faults_mod.SimulatedHostLoss):
        _run_pipeline(synthetic_frames, cfg)
    events = _events(log)
    assert any(ev["event"] == "degrade"
               and ev.get("action") == "abort_resumable"
               for ev in events)
    # the emergency save left a resumable step-2 artifact behind
    assert list((tmp_path / "ck").glob("pert_step2*.npz"))


def test_shrink_eligibility_units(synthetic_frames, tmp_path):
    """The rung only accepts hostloss/OOM on a shrinkable mesh in a
    single controlling process."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    inf = PertInference(s, g1, PertConfig(**{**BASE, **MESH_4x2}),
                        clone_idx_s=clone_idx, clone_idx_g1=clone_idx,
                        num_clones=2)
    assert inf._shrink_eligible("hostloss")
    assert inf._shrink_eligible("oom")
    assert not inf._shrink_eligible("hang")
    assert not inf._shrink_eligible("preemption")
    inf._mesh = None          # single device: nothing to shrink
    assert not inf._shrink_eligible("hostloss")
