"""Data-core tests: pivots, genome ordering, padding/masking."""

import numpy as np
import pytest
import pandas as pd

from scdna_replication_tools_tpu.config import ColumnConfig
from scdna_replication_tools_tpu.data.loader import (
    build_pert_inputs,
    pad_cells,
)


def _with_reads(df, seed=0):
    rng = np.random.default_rng(seed)
    df = df.copy()
    df["reads"] = rng.integers(10, 100, len(df))
    df["state"] = df["true_somatic_cn"]
    df["copy"] = df["true_somatic_cn"].astype(float)
    return df


def test_build_pert_inputs_shapes(synthetic_frames):
    df_s, df_g = synthetic_frames
    s, g1 = build_pert_inputs(_with_reads(df_s), _with_reads(df_g, 1))
    assert s.reads.shape == (24, 120)
    assert g1.reads.shape == (24, 120)
    assert g1.states.shape == (24, 120)
    assert s.gammas.shape == (120,)
    assert s.rt_prior is not None and s.rt_prior.max() <= 1.0
    assert s.libs.shape == (24,)
    assert list(s.loci.get_level_values(1)) == sorted(
        s.loci.get_level_values(1))


def test_genome_ordering_multichrom():
    # chromosomes must order 1..22,X,Y — not lexicographically
    rows = []
    for chrom in ["10", "2", "1", "X"]:
        for start in [0, 500000]:
            rows.append(dict(cell_id="c0", chr=chrom, start=start,
                             gc=0.4, reads=5, state=2, library_id="L"))
    df = pd.DataFrame(rows)
    cols = ColumnConfig(rt_prior_col=None)
    s, g1 = build_pert_inputs(df, df.copy(), cols)
    chrs = list(s.loci.get_level_values(0).astype(str))
    assert chrs == ["1", "1", "2", "2", "10", "10", "X", "X"]


def test_pad_cells_mask(synthetic_frames):
    df_s, df_g = synthetic_frames
    s, _ = build_pert_inputs(_with_reads(df_s), _with_reads(df_g, 1))
    padded = pad_cells(s, 16)
    assert padded.num_cells == 32
    assert padded.cell_mask.sum() == 24
    assert not padded.cell_mask[-1]
    # original content preserved
    np.testing.assert_array_equal(padded.reads[:24], s.reads)


def test_example_bins_schema():
    from scdna_replication_tools_tpu.data.example_bins import make_example_bins

    bins = make_example_bins(chroms=["1", "2", "X"])
    assert list(bins.columns) == ["chr", "start", "end", "gc", "mcf7rt",
                                  "bin_size"]
    assert set(bins.chr) == {"1", "2", "X"}
    assert (bins.end - bins.start == 500_000).all()
    assert bins.gc.between(0.25, 0.75).all()
    assert bins.mcf7rt.between(0.0, 1.0).all()
    # deterministic given the seed
    again = make_example_bins(chroms=["1", "2", "X"])
    assert bins.equals(again)
    # genome-wide at 500kb lands near the reference's 5451 rows
    full = make_example_bins()
    assert 5000 < len(full) < 6500


def test_validation_names_missing_columns(synthetic_frames):
    df_s, df_g = synthetic_frames
    df_s, df_g = _with_reads(df_s), _with_reads(df_g, 1)
    bad_s = df_s.drop(columns=["reads", "gc"])
    with pytest.raises(ValueError, match=r"cn_s is missing column\(s\).*reads.*gc"):
        build_pert_inputs(bad_s, df_g)
    with pytest.raises(ValueError, match="cn_g1 is empty"):
        build_pert_inputs(df_s, df_g.iloc[0:0])


def test_validation_disjoint_loci(synthetic_frames):
    df_s, df_g = synthetic_frames
    df_s, df_g = _with_reads(df_s), _with_reads(df_g, 1)
    # shift every G1 bin start so no (chr, start) key is shared
    df_g = df_g.assign(start=df_g["start"] + 1)
    with pytest.raises(ValueError, match="no locus is fully observed"):
        build_pert_inputs(df_s, df_g)
