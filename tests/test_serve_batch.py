"""Continuous batching: SLO/priority admission + batched-slab parity.

Two layers under test:

* **spool admission** (no jax): claim order is priority class first
  (``high`` > ``normal`` > ``low``, ticket-borne, default ``normal``),
  oldest-deadline-first within a class, submission FIFO as the final
  key — and a forged/unknown priority class parks the ticket as
  ``failed`` instead of wedging the queue (the PR's pinned bugfix);
* **batched worker parity**: the same three-request burst — clean,
  oom-faulted, clean — through a serial worker (``max_batch=1``) and a
  batched one (``max_batch=2``), asserting the serving contract end to
  end: per-request fault isolation under slab packing, early
  retirement + mid-slab refill observable on ``request_end``, and
  output parity.

Numerics contract (OBSERVABILITY.md "Serving", tests/test_slab.py):
packed slab lanes may differ from serial by accumulated ~1 ulp/step —
value-dependent vector-width instruction selection on XLA:CPU — so
float output columns pin ``allclose`` while every DISCRETE column
(CN state, rep state, clone/phase assignments) must be identical.
"""

import json
import os
import pathlib
import sys

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.obs.schema import validate_run
from scdna_replication_tools_tpu.serve import (
    PRIORITY_CLASSES,
    BucketSet,
    ServeWorker,
    SpoolQueue,
)

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]
                       / "tools"))

from test_serve import REQUEST_OPTIONS, _frames  # noqa: E402


def _tiny_frame():
    return pd.DataFrame({"cell_id": ["c0"], "chr": ["1"], "start": [0],
                         "reads": [1.0]})


def _pin_mtime(q, rid, t):
    os.utime(q.root / "pending" / f"{rid}.json", (t, t))


# ---------------------------------------------------------------------------
# priority admission (queue-level, no jax)
# ---------------------------------------------------------------------------


def test_claim_order_priority_class_then_fifo(tmp_path):
    """high > normal > low; submission order preserved WITHIN a
    class regardless of id or priority of later arrivals."""
    q = SpoolQueue(tmp_path / "spool")
    df = _tiny_frame()
    q.submit_frames(df, df, request_id="n1")                   # normal
    q.submit_frames(df, df, request_id="low1", priority="low")
    q.submit_frames(df, df, request_id="n2", priority="normal")
    q.submit_frames(df, df, request_id="hi1", priority="high")
    q.submit_frames(df, df, request_id="hi2", priority="high")
    for i, rid in enumerate(("n1", "low1", "n2", "hi1", "hi2")):
        _pin_mtime(q, rid, 1000 + i)
    order = [q.claim().request_id for _ in range(5)]
    assert order == ["hi1", "hi2", "n1", "n2", "low1"]
    assert q.claim() is None


def test_claim_order_oldest_deadline_first_within_class(tmp_path):
    """deadline_unix orders within a class: a later-submitted ticket
    with a tighter SLO deadline claims first; deadline-less tickets
    sort after every deadline-bearing peer of their class."""
    q = SpoolQueue(tmp_path / "spool")
    df = _tiny_frame()
    q.submit_frames(df, df, request_id="loose", deadline_unix=9000)
    q.submit_frames(df, df, request_id="none")
    q.submit_frames(df, df, request_id="tight", deadline_unix=5000)
    q.submit_frames(df, df, request_id="hi", priority="high")
    for i, rid in enumerate(("loose", "none", "tight", "hi")):
        _pin_mtime(q, rid, 1000 + i)
    order = [q.claim().request_id for _ in range(4)]
    # class beats deadline; within normal: tight < loose < none
    assert order == ["hi", "tight", "loose", "none"]


def test_submit_rejects_unknown_priority(tmp_path):
    q = SpoolQueue(tmp_path / "spool")
    df = _tiny_frame()
    with pytest.raises(ValueError, match="urgent"):
        q.submit_frames(df, df, priority="urgent")
    assert q.depth() == 0


def test_forged_priority_parks_ticket_as_failed(tmp_path):
    """submit() validates, but tickets are plain spool files — a
    forged/corrupt class must park at claim time as ``failed`` (error
    naming the class), never wedge the queue: the good ticket behind
    it still claims, and a claim PREDICATE must not mask the parking
    (the batched worker filters claims by bucket rung)."""
    q = SpoolQueue(tmp_path / "spool")
    df = _tiny_frame()
    q.submit_frames(df, df, request_id="forged")
    q.submit_frames(df, df, request_id="good")
    _pin_mtime(q, "forged", 1000)
    _pin_mtime(q, "good", 1001)
    path = q.root / "pending" / "forged.json"
    doc = json.loads(path.read_text())
    doc["priority"] = "urgent"
    path.write_text(json.dumps(doc))

    # a rung-filtering predicate that rejects everything: the forged
    # ticket must STILL be parked (it bypasses the predicate)
    assert q.claim(predicate=lambda t: False) is None
    parked = q.status("forged")
    assert parked["state"] == "failed"
    assert "urgent" in parked["error"]
    assert "priority" in parked["error"]

    t = q.claim()
    assert t.request_id == "good"
    assert t.priority == "normal"
    assert q.claim() is None
    assert tuple(PRIORITY_CLASSES) == ("high", "normal", "low")


# ---------------------------------------------------------------------------
# batched-vs-serial worker parity (the tentpole's end-to-end pin)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def burst(tmp_path_factory):
    """The same burst through both worker modes: A (clean), B (oom
    fault injected at step2's first fit), C (clean, different cohort).
    max_requests=3 + exit_when_idle drains exactly the burst.  With
    max_batch=2, A+B pack one slab; B's fault retires its block early
    and C refills the vacancy mid-slab — all three tentpole paths.

    Budgets are half of REQUEST_OPTIONS': iteration counts are DYNAMIC
    args of the chunked fit (no program identity change — the arms
    still ride test_serve's warm ledger in a full-suite run), and
    parity is arm-vs-arm at identical budgets, so the shorter fit
    costs nothing pinned here."""
    root = tmp_path_factory.mktemp("pert_serve_batch")
    buckets = BucketSet(cells=(8, 16), loci=(64, 128))
    options = {**REQUEST_OPTIONS, "max_iter": 60, "min_iter": 20}
    sim_a = _frames(seed=3)
    sim_b = _frames(seed=11)
    submits = [
        ("ba_clean", sim_a, {}),
        ("bb_oom", sim_a, {"faults": "oom@step2/fit#1"}),
        ("bc_refill", sim_b, {}),
    ]

    def run_arm(tag, max_batch):
        q = SpoolQueue(root / tag)
        for rid, sim, extra in submits:
            q.submit_frames(*sim, options={**options, **extra},
                            request_id=rid)
        w = ServeWorker(q, buckets=buckets, max_requests=len(submits),
                        exit_when_idle=True, max_batch=max_batch)
        stats = w.run()
        return {"queue": q, "worker": w, "stats": stats,
                "by_id": {o.request_id: o for o in w.outcomes}}

    return {"serial": run_arm("serial", 1),
            "batched": run_arm("batched", 2)}


def _request_ends(arm):
    ends = []
    with open(arm["stats"]["worker_log"]) as fh:
        for line in fh:
            ev = json.loads(line)
            if ev.get("event") == "request_end":
                ends.append(ev)
    return {e["request_id"]: e for e in ends}


def test_batched_isolates_fault_like_serial(burst):
    """The oom-faulted block fails ALONE in both arms: packing B into
    a slab with A must not poison A or C."""
    for arm in ("serial", "batched"):
        by_id = burst[arm]["by_id"]
        assert burst[arm]["stats"]["by_status"] == \
            {"ok": 2, "failed": 1}, arm
        assert by_id["ba_clean"].status == "ok"
        assert by_id["bc_refill"].status == "ok"
        assert by_id["bb_oom"].status == "failed"
        assert "RESOURCE_EXHAUSTED" in by_id["bb_oom"].error


def test_batched_outputs_match_serial(burst):
    """Discrete output columns identical serial-vs-batched; float
    columns within the documented packed-lane tolerance."""
    for rid in ("ba_clean", "bc_refill"):
        s = pd.read_csv(
            burst["serial"]["queue"].results_dir(rid) / "output.tsv",
            sep="\t", dtype={"chr": str})
        b = pd.read_csv(
            burst["batched"]["queue"].results_dir(rid) / "output.tsv",
            sep="\t", dtype={"chr": str})
        assert list(s.columns) == list(b.columns)
        assert len(s) == len(b) > 0
        for col in s.columns:
            if s[col].dtype.kind == "f":
                assert np.allclose(
                    s[col].to_numpy(), b[col].to_numpy(),
                    rtol=5e-2, atol=1e-3, equal_nan=True), (rid, col)
            else:
                same = (s[col] == b[col]) | (s[col].isna()
                                             & b[col].isna())
                assert same.all(), (rid, col)


def test_batched_retirement_and_refill_observable(burst):
    """request_end in batched mode carries the slab facts: someone
    retired early (a peer kept fitting), occupancy attribution is
    sane, and the serial arm's events stay clean of slab attrs."""
    ends_b = _request_ends(burst["batched"])
    assert set(ends_b) == {"ba_clean", "bb_oom", "bc_refill"}
    for e in ends_b.values():
        assert "retired_early" in e, e["request_id"]
        assert float(e["slab_avg_occupancy"]) >= 1.0
    assert any(e["retired_early"] for e in ends_b.values())
    outcomes = burst["batched"]["by_id"]
    assert any(o.retired_early for o in outcomes.values())

    ends_s = _request_ends(burst["serial"])
    for e in ends_s.values():
        assert "retired_early" not in e
        assert "slab_avg_occupancy" not in e


def test_batched_worker_log_schema_valid_and_attributed(burst):
    assert validate_run(burst["batched"]["stats"]["worker_log"]) == []
    assert validate_run(burst["serial"]["stats"]["worker_log"]) == []
    # run_start context in batched request logs records the slab width
    rid = "ba_clean"
    line = open(burst["batched"]["queue"].results_dir(rid)
                / "run.jsonl").readline()
    start = json.loads(line)
    ctx = start.get("context") or {}
    assert (ctx.get("slab_width") or start.get("slab_width")) == 2


def test_batched_terminal_status_doc(burst):
    doc = json.loads((burst["batched"]["queue"].root
                      / "status.json").read_text())
    slab = doc["slab"]
    assert slab["max_batch"] == 2
    assert slab["occupancy"] == 0 and slab["blocks"] == []
    # the coordinator actually packed fits (the perf win is real, not
    # K threads taking turns on solo programs)
    assert slab["packed_dispatches"] >= 1
    assert slab["packed_lanes"] >= 2
