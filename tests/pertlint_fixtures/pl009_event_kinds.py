"""Fixture for PL009 (unknown-runlog-event-kind).

Parsed by the lint tests, never imported.  Lines ending in the expect
marker must fire; the inline-disable line must land in the suppressed
list.  Known kinds come from the REAL checked-in schema
(obs/runlog_schema.json) — 'fit_end', 'compile', 'note' are in its
enum; 'fit_ended' and 'totally_new_kind' are not.
"""


def known_kinds_are_clean(run_log, _runlog):
    run_log.emit("fit_end", step="step2", iters=10)     # in the enum: ok
    run_log.emit("note", msg="contextual")              # in the enum: ok
    _runlog.current().emit("compile", key_hash="abc",
                           cache="hit")                 # current() seam: ok


def attribute_receiver(self):
    self.run_log.emit("cell_qc_summary", step="step2",
                      num_cells=1, num_flagged=0)       # in the enum: ok


def unknown_kind_fires(run_log):
    run_log.emit("fit_ended", step="step2")  # expect: PL009
    run_log.emit("totally_new_kind", x=1)  # pertlint: disable=PL009


def non_runlog_receivers_are_exempt(radio, signal):
    radio.emit("morse_code")        # not a RunLog: some other emit API
    signal.emit("clicked")          # ditto (Qt-style signal)


def dynamic_kind_is_exempt(run_log, kind):
    run_log.emit(kind, payload=1)   # non-literal: runtime validator's job


class RunLogLike:
    def emit(self, event, **payload):
        return (event, payload)

    def open_run(self):
        # self.emit inside a *Log* class is the canonical lifecycle site
        self.emit("run_start", pid=0)           # in the enum: ok
