# pertlint test fixture: PL006 jit-in-loop.  Parsed, never imported.
import functools

import jax


@jax.jit
def decorated(x):                       # decorator position: exempt
    return x


hoisted = jax.jit(decorated)            # module level, outside loops: ok


def compile_per_item(fns, xs):
    outs = []
    for f in fns:
        outs.append(jax.jit(f))  # expect: PL006
        step = functools.partial(jax.jit, static_argnums=0)  # expect: PL006
        outs.append(step(f))
        sup = jax.jit(f)  # pertlint: disable=PL006
        outs.append(sup)
    comp = [jax.jit(f) for f in fns]  # expect: PL006
    while xs:
        g = jax.jit(fns[0])  # expect: PL006
        xs = xs[:-1]
    return outs, comp, g
