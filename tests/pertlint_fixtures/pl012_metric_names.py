"""Fixture for PL012 (unknown-metric-name).

Parsed by the lint tests, never imported.  Lines ending in the expect
marker must fire; the inline-disable line must land in the suppressed
list.  Known names come from the REAL checked-in manifest
(obs/metrics_manifest.json) — 'pert_fit_iters_total',
'pert_trace_seconds', 'pert_device_hbm_peak_bytes' are in it;
'pert_fit_iterz_total' and 'my_adhoc_metric' are not.
"""


def known_names_are_clean(metrics, registry, metrics_mod):
    metrics.counter("pert_fit_iters_total",
                    labels={"step": "step2"}).inc(10)     # in manifest
    registry.gauge("pert_device_hbm_peak_bytes",
                   labels={"device": "0"}).set(1 << 30)   # in manifest
    metrics_mod.current().observe("pert_trace_seconds", 1.5)  # current()


def unknown_name_fires(metrics):
    metrics.counter("pert_fit_iterz_total").inc()  # expect: PL012
    metrics.histogram("my_adhoc_metric").observe(2)  # pertlint: disable=PL012


def self_receiver_in_metrics_class_fires():
    class FakeMetricsRegistry:
        def counter(self, name, labels=None):
            return self

        def inc(self, amount=1):
            return None

        def record(self):
            self.counter("pert_bogus_series_total").inc()  # expect: PL012


def dynamic_name_is_exempt(metrics, name):
    # non-literal: the runtime warn-once covers it
    metrics.counter(name).inc()


def non_registry_receivers_are_exempt(stream, watchdog):
    # .observe on other APIs is a different vocabulary
    stream.observe("next_value")
    watchdog.observe("heartbeat")
