"""Fixture for PL013 (raw-checkpoint-write) — parsed, never imported."""
import io

import numpy as np


def bad_direct_savez(path, params):
    np.savez(path, **params)  # expect: PL013


def bad_savez_compressed(path, arr):
    np.savez_compressed(path, arr=arr)  # expect: PL013


def bad_np_save(path, arr):
    np.save(path, arr)  # expect: PL013


def bad_binary_open(path, blob):
    with open(path, "wb") as fh:  # expect: PL013
        fh.write(blob)


def bad_binary_append(path, blob):
    fh = open(path, mode="ab")  # expect: PL013
    fh.write(blob)
    fh.close()


def good_serialise_to_memory(params):
    # the sanctioned idiom: serialise in memory, commit atomically
    buf = io.BytesIO()
    np.savez(buf, **params)
    return buf.getvalue()


def good_bytesio_inline(params):
    np.savez(io.BytesIO(), **params)


def good_text_write(path, text):
    # text-mode writes are not durability-bearing artifacts
    with open(path, "w") as fh:
        fh.write(text)


def good_binary_read(path):
    with open(path, "rb") as fh:
        return fh.read()


def good_nonliteral_mode(path, mode, blob):
    # a non-literal mode cannot be judged statically
    with open(path, mode) as fh:
        fh.write(blob)


def deliberate_raw_write(path, blob):
    # e.g. a scratch diagnostic dump that is never resumed from
    with open(path, "wb") as fh:  # pertlint: disable=PL013 — scratch
        # dump, no resume path reads it
        fh.write(blob)
