"""Seeded-defect fixture package for the pertlint FLOW layer.

Parsed by tools/pertlint/flow (pure stdlib ast), NEVER imported — the
``import jax`` / ``multihost_utils`` lines are call-graph anchors, not
runtime dependencies.  Each ``expect: FLnnn`` comment pins one seeded
defect to its exact line; functions named ``*_ok`` are NEGATIVE cases
the rules must leave clean.
"""
