"""Fixture hash-exclusion contract.

The flow engine reads ``<package>.config.NON_HASH_FIELDS`` statically
(a literal tuple of strings), exactly as it does for the real package.
"""

NON_HASH_FIELDS = ("telemetry_path", "request_id")
