"""Seeded program-identity defects: FL003 / FL004 / FL005."""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("tag",))
def _render(x, tag):  # expect: FL003
    return x


def leak_tag(cfg, x):
    # the hash-EXCLUDED telemetry_path becomes a static argname: two
    # configs that hash equal compile different programs
    return _render(x, tag=cfg.telemetry_path)  # expect: FL003


@functools.partial(jax.jit, static_argnames=("mode",))
def _kernel(x, mode):  # expect: FL004
    return x


def run_kernel(x, mode):
    # 'mode' is caller-supplied public API with no in-package binding
    # and no default — the config hash under-determines the program
    return _kernel(x, mode=mode)


@functools.partial(jax.jit, static_argnames=("opts",))
def _stepper(x, opts=None):
    return x


def bad_static_container(x):
    return _stepper(x, opts=[1, 2, 3])  # expect: FL005


def bad_dynamic_scalar():
    return _stepper(0.5)  # expect: FL005
