"""Seeded SPMD-discipline defects: FL001 / FL002 / FL006."""

import jax
import numpy as np
from jax.experimental import multihost_utils


def process_rank_and_count():
    return jax.process_index(), jax.process_count()


# -- FL001: collective under rank-divergent control flow ------------------

def leader_gated_sync(tag):
    if jax.process_index() == 0:
        multihost_utils.sync_global_devices(tag)  # expect: FL001


def early_return_shadow(state):
    # the shadow of a rank-guarded early return: rank != 0 never
    # arrives at the barrier below
    if jax.process_index() != 0:
        return None
    multihost_utils.sync_global_devices("save")  # expect: FL001
    return state


def verdict_gated_allgather(fingerprints):
    # the PR-11 shape: a host-LOCAL verdict (derived from the rank)
    # gates the allgather — ranks that disagree on the verdict hang
    rank, nproc = process_rank_and_count()
    local_ok = _local_verdict(fingerprints, rank)
    if local_ok:
        return multihost_utils.process_allgather(fingerprints)  # expect: FL001
    return None


def _local_verdict(fingerprints, rank):
    return fingerprints[rank] is not None


def rescue_in_except(x):
    try:
        return _compute(x)
    except ValueError:
        multihost_utils.sync_global_devices("rescue")  # expect: FL001
        return None


def _compute(x):
    return x + 1


def _barrier():
    multihost_utils.sync_global_devices("checkpoint")


def leader_only_barrier(x):
    # interprocedural: _barrier REACHES a collective, so guarding the
    # call is as divergent as guarding the primitive
    if jax.process_index() == 0:
        _barrier()  # expect: FL001
    return x


def count_guarded_sync_ok(tag):
    # NEGATIVE: process_count() is SPMD-uniform — every rank takes the
    # same branch, so the guarded collective is sound
    if jax.process_count() > 1:
        multihost_utils.sync_global_devices(tag)


def suppressed_sync(tag):
    # inline suppression must apply to flow findings unchanged
    if jax.process_index() == 0:
        multihost_utils.sync_global_devices(tag)  # pertlint: disable=FL001; raw expect: FL001


# -- FL002: collective order divergence across branches -------------------

def branch_order_divergence(flag, x):
    if flag:  # expect: FL002
        multihost_utils.sync_global_devices("phase")
        multihost_utils.process_allgather(x)
    else:
        multihost_utils.process_allgather(x)
        multihost_utils.sync_global_devices("phase")


def count_branch_order_ok(x):
    # NEGATIVE: the branch condition is the (uniform) process count —
    # every rank agrees on the branch, ordering cannot cross-match
    nproc = jax.process_count()
    if nproc > 1:
        multihost_utils.process_allgather(x)
        multihost_utils.sync_global_devices("multi")
    else:
        multihost_utils.sync_global_devices("multi")


# -- FL006: host fetch on a multi-process-reachable path ------------------

def fetch_after_sync(x):
    multihost_utils.sync_global_devices("gather")
    return np.asarray(x)  # expect: FL006


def fetch_single_world_ok(x):
    # NEGATIVE: the fetch sits on a provably single-process branch
    multihost_utils.sync_global_devices("gather")
    if jax.process_count() <= 1:
        return np.asarray(x)
    return x
