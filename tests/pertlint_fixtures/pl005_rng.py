# pertlint test fixture: PL005 unseeded-rng.  Parsed, never imported.
import numpy as np


def sample(n):
    bad = np.random.rand(n)  # expect: PL005
    np.random.seed(0)  # expect: PL005
    shuffled = np.random.permutation(n)  # expect: PL005
    rng = np.random.default_rng(0)      # explicit generator: exempt
    good = rng.normal(size=n)
    sup = np.random.randn(n)  # pertlint: disable=PL005
    return bad, shuffled, good, sup
