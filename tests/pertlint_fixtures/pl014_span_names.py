"""Fixture for PL014 (span-hygiene).

Parsed by the lint tests, never imported.  Lines ending in the expect
marker must fire; the inline-disable line must land in the suppressed
list.  Known names come from the REAL checked-in registry
(obs/span_registry.json) — 'request', 'queue_wait', 'fit/chunk' are in
it; 'totally_adhoc_span' is not.
"""


def known_names_are_clean(tracer):
    with tracer.span("request"):          # in the registry, with'd
        tracer.record_span("queue_wait", 0.0, 1.0)   # in the registry
    span = tracer.begin("fit/chunk")      # begin/end: non-lexical ok
    tracer.end(span)


def unknown_name_fires(tracer):
    with tracer.span("totally_adhoc_span"):  # expect: PL014
        pass
    with tracer.span("made_up_too"):  # pertlint: disable=PL014
        pass


def dropped_span_fires(tracer):
    tracer.span("request")  # expect: PL014


def never_withed_assignment_fires(tracer):
    cm = tracer.span("request")  # expect: PL014
    return cm is not None


def conditional_cm_then_with_is_clean(tracer, null_cm):
    cm = tracer.span("admission") if tracer is not None else null_cm
    with cm:
        pass


def self_receiver_in_tracer_class_fires():
    class FakeSpanTracer:
        def span(self, name):
            return self

        def helper(self):
            self.span("bogus_internal_span")  # expect: PL014


def dynamic_name_is_exempt(tracer, name):
    # non-literal: cannot be checked statically
    with tracer.span(name):
        pass


def non_tracer_receivers_are_exempt(row, soup):
    # .span on other APIs is a different vocabulary (HTML, layout, ...)
    row.span("two-columns")
    soup.span("highlight")
