"""Fixture for PL010 (unknown-control-decision-action).

Parsed by the lint tests, never imported.  Lines ending in the expect
marker must fire; the inline-disable line must land in the suppressed
list.  Known actions come from the REAL checked-in schema
(obs/runlog_schema.json, definitions.control_decision.action.enum) —
'early_stop', 'extend', 'rescue_skip' are in it; 'early_stopp' and
'panic' are not.
"""


def known_actions_are_clean(run_log, _runlog):
    run_log.emit("control_decision", step="step2",
                 action="early_stop", iter=80)          # in the enum: ok
    run_log.emit("control_decision", step="step2",
                 action="rescue_skip", iter=120)        # in the enum: ok
    _runlog.current().emit("control_decision", step="step1",
                           action="extend", iter=60)    # current(): ok


def unknown_action_fires(run_log):
    run_log.emit("control_decision", step="step2",
                 action="early_stopp", iter=80)  # expect: PL010
    run_log.emit("control_decision", step="step2",
                 action="panic", iter=9)  # pertlint: disable=PL010


def other_event_kinds_are_exempt(run_log):
    # 'action' kwargs of OTHER events are a different vocabulary
    # (checkpoint's save/load enum) — not this rule's business
    run_log.emit("checkpoint", action="save", step="step2")


def dynamic_action_is_exempt(run_log, decision):
    # the runner's pass-through: action arrives inside the decision
    # dict — non-literal, the runtime validator covers it
    run_log.emit("control_decision", step="step2", **decision)
    run_log.emit("control_decision", step="step2",
                 action=decision["action"], iter=1)


def non_runlog_receivers_are_exempt(bus):
    bus.emit("control_decision", action="launch_missiles")
