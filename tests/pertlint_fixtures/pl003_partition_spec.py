# pertlint test fixture: PL003 raw-partitionspec.  Parsed, never imported.
# This file is NOT named layout.py, so every construction is a violation.
import jax.sharding
from jax.sharding import NamedSharding, PartitionSpec as P


def build_specs(mesh):
    a = P("cells", None)  # expect: PL003
    b = jax.sharding.PartitionSpec("cells")  # expect: PL003
    c = P()  # pertlint: disable=PL003 — fixture's sanctioned escape hatch
    # consuming a spec someone else built is fine; only construction gates
    return NamedSharding(mesh, a), b, c
