"""Fixture for PL011 (swallowed-exception-in-library).

Parsed by the lint tests, never imported.  Lines ending in the expect
marker must fire; the inline-disable line must land in the suppressed
list.  The rule targets BROAD handlers (bare ``except:``,
``except Exception:``, ``except BaseException:``) whose body neither
re-raises nor reports (RunLog ``.emit``, a logger call,
``warnings.warn``); narrow handlers and reporting handlers are exempt.
"""

import warnings

from scdna_replication_tools_tpu.utils.profiling import logger


def silent_swallow_fires(fn):
    try:
        return fn()
    except Exception:  # expect: PL011
        return None


def bare_except_fires(fn):
    try:
        return fn()
    except:  # noqa: E722  # expect: PL011
        pass


def base_exception_fires(fn):
    try:
        return fn()
    except BaseException:  # expect: PL011
        return None


def tuple_with_broad_member_fires(fn):
    try:
        return fn()
    except (ValueError, Exception):  # expect: PL011
        return None


def narrow_handler_is_exempt(fn):
    try:
        return fn()
    except OSError:   # a considered decision about one failure mode
        return None


def reraise_is_exempt(fn):
    try:
        return fn()
    except Exception as exc:
        raise RuntimeError("wrapped") from exc


def conditional_reraise_is_exempt(fn, classify):
    try:
        return fn()
    except Exception as exc:
        if classify(exc) != "transient":
            raise
        return None


def logger_call_is_exempt(fn):
    try:
        return fn()
    except Exception as exc:
        logger.warning("best-effort path failed: %s", exc)
        return None


def runlog_emit_is_exempt(fn, run_log):
    try:
        return fn()
    except Exception as exc:
        run_log.emit("note", error=str(exc))
        return None


def warnings_warn_is_exempt(fn):
    try:
        return fn()
    except Exception as exc:
        warnings.warn(f"degraded: {exc}")
        return None


def deliberate_swallow_is_suppressible(fn):
    try:
        return fn()
    except Exception:  # pertlint: disable=PL011 — probe by design
        return None
