# pertlint test fixture: PL004 dtype-drift.  Parsed, never imported.
# Lives under an ops/ directory component so the path-scoped rule fires.
import jax.numpy as jnp


def make_arrays(n):
    a = jnp.zeros((n,))  # expect: PL004
    b = jnp.full((n,), 1.0)  # expect: PL004
    c = jnp.ones((n, 2))  # expect: PL004
    d = jnp.array([1.0, 2.0])  # expect: PL004
    pos = jnp.zeros((n,), jnp.float32)          # positional dtype: ok
    kw = jnp.full((n,), 1.0, dtype=jnp.float32)  # keyword dtype: ok
    conv = jnp.asarray([1.0, 2.0])              # conversion: exempt
    sup = jnp.ones((n,))  # pertlint: disable=PL004
    return a, b, c, d, pos, kw, conv, sup
