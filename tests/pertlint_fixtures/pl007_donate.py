# pertlint test fixture: PL007 undonated-init-buffers.  Parsed, never
# imported.  The rule fires on jit entry points whose signature carries
# initial-value pytree names (params0 / opt_state0 / losses0 / *_init)
# when the jit wrapping has no donate_argnums/donate_argnames.
import functools

import jax


@jax.jit
def bare_decorated(params0, data):  # expect: PL007
    return params0, data


@functools.partial(jax.jit, static_argnames=("n",))
def partial_no_donate(params0, opt_state0, n):  # expect: PL007
    return params0, opt_state0, n


@functools.partial(jax.jit, static_argnames=("n",),
                   donate_argnames=("params0", "opt_state0"))
def partial_donating(params0, opt_state0, n):   # donation present: clean
    return params0, opt_state0, n


@functools.partial(jax.jit, donate_argnums=(0,))
def donating_by_index(state0, data):            # donation present: clean
    return state0, data


@jax.jit
def plain_params_ok(params, batch):   # 'params' is not an init-value name
    return params, batch


@jax.jit
def suppressed(losses0):  # pertlint: disable=PL007
    return losses0


def step_fn(carry0, xs):
    return carry0, xs


wrapped = jax.jit(step_fn)  # expect: PL007
wrapped_ok = jax.jit(step_fn, donate_argnums=(0,))   # donates: clean


def loop_body(state_init):
    return state_init


looped = functools.partial(jax.jit, static_argnums=())(loop_body)  # expect: PL007


def shard_mapped(params0):
    return params0


# shard_map has no donation contract — out of scope for the rule
sharded = jax.shard_map(shard_mapped, mesh=None, in_specs=None,
                        out_specs=None)
