# pertlint test fixture: PL002 tracer-branch.  Parsed, never imported.
import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def entry(x, flag=None):
    if jnp.isnan(x).any():  # expect: PL002
        x = x * 0.0
    while jax.numpy.sum(x) > 0:  # expect: PL002
        x = x - 1.0
    if lax.cumsum(x)[0] > 0:  # expect: PL002
        x = x + 1.0
    if flag is None:                    # static/None test: exempt
        x = x + 2.0
    if isinstance(flag, str):           # host-level type test: exempt
        x = x + 3.0
    if jnp.any(x > 0):  # pertlint: disable=PL002
        x = x * 2.0
    return x


def host_side(x):
    # untraced: Python control flow on jnp results is legal host code
    if jnp.isnan(x).any():
        return 0.0
    return 1.0
