# pertlint test fixture: PL008 print-in-library.  Parsed, never imported.
import logging
import logging as log_mod
from logging import basicConfig

logger = logging.getLogger("scdna_replication_tools_tpu")


def report(result):
    print("fit done:", result)  # expect: PL008
    logger.info("fit done: %s", result)          # package logger: exempt
    return result


def configure():
    logging.basicConfig(level="INFO")  # expect: PL008
    log_mod.basicConfig(level="DEBUG")  # expect: PL008
    basicConfig()  # expect: PL008


def shadowed(print):
    # a locally-bound `print` is the author's own callable, not stdout
    print("routed through an injected sink")
    return print


def emitter(records):
    records.print()                     # attribute call: exempt
    sup = 42
    print("debug dump", sup)  # pertlint: disable=PL008
    return sup
