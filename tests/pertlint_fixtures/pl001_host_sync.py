# pertlint test fixture: PL001 host-sync-in-jit.  Parsed, never imported.
# Violation lines end with an expect-marker comment; suppressed lines
# carry the inline disable comment and must land in the suppressed list.
import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def entry(x):
    bad = float(x)  # expect: PL001
    shape_ok = float(x.shape[0])        # static metadata: exempt
    lit_ok = int(1e6)                   # literal: exempt
    len_ok = int(len(x))                # len(): exempt
    sup = jnp.sum(x).item()  # pertlint: disable=PL001
    pulled = jax.device_get(x)  # expect: PL001
    return helper(bad + sup + shape_ok + lit_ok + len_ok) + pulled


def helper(y):
    # reachable from `entry` (same-module call closure) -> traced
    return np.asarray(y)  # expect: PL001


@functools.partial(jax.jit, static_argnames=("n",))
def with_statics(x, n):
    return x * int(n)                   # static_argnames: exempt


@functools.partial(jax.jit, static_argnums=(1,))
def with_static_nums(x, m):
    # m is positionally static (argnum 1): a host conversion of it is a
    # Python-level operation, exactly like the static_argnames case
    return x * float(m)                 # static_argnums: exempt


def nums_wrapped(x, k, t):
    # call-site wrapping below marks k (argnum 1) static; t stays traced
    return x * int(k) + float(t)  # expect: PL001


nums_entry = jax.jit(nums_wrapped, static_argnums=1)


def host_side(x):
    # not reachable from any jit entry: host code may sync freely
    return float(np.asarray(x).mean())
