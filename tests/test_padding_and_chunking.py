"""Regression tests for padded/chunked execution (the sharded-path NaN
bug: all-ones etas padding made the ploidy guess 0 and NaN'd the loss)."""

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.config import ColumnConfig, PertConfig
from scdna_replication_tools_tpu.data.loader import build_pert_inputs
from scdna_replication_tools_tpu.infer.runner import PertInference, _pad_etas


from conftest import dense_inputs_from_frames as _dense_inputs  # noqa: E402


def test_pad_etas_keeps_ploidy_positive():
    etas = np.ones((3, 10, 5), np.float32)
    etas[:, :, 3] = 50.0
    padded = _pad_etas(etas, 8)
    assert padded.shape == (8, 10, 5)
    # padded rows must argmax to a positive CN state
    assert (np.argmax(padded[3:], axis=-1) > 0).all()


def test_chunked_run_with_padding_stays_finite(synthetic_frames):
    """cell_chunk=16 pads 24 cells -> 32; every step loss must be finite."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    config = PertConfig(cn_prior_method="g1_clones", max_iter=40,
                        min_iter=20, cell_chunk=16, run_step3=True)
    inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    step1, step2, step3 = inf.run()
    for step in (step1, step2, step3):
        assert not step.fit.nan_abort
        assert np.isfinite(step.fit.losses).all()


def test_sharded_run_on_virtual_devices(synthetic_frames):
    """num_shards=8 over the virtual CPU mesh; 24 cells pad to 32."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    config = PertConfig(cn_prior_method="g1_clones", max_iter=30,
                        min_iter=15, num_shards=8, run_step3=False)
    inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    step1, step2, step3 = inf.run()
    assert step3 is None
    for step in (step1, step2):
        assert not step.fit.nan_abort
        assert np.isfinite(step.fit.losses).all()


def test_sharded_pallas_matches_single_device_xla(synthetic_frames):
    """The shard_map'd interpreted kernel on an 8-device mesh must produce
    the same losses as the single-device XLA path (same math, different
    execution): validates the multi-chip Pallas route end to end."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)

    def run(**kw):
        config = PertConfig(cn_prior_method="g1_clones", max_iter=25,
                            min_iter=12, run_step3=False, **kw)
        inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                            clone_idx_g1=clone_idx, num_clones=2)
        _, step2, _ = inf.run()
        return step2.fit.losses

    ref = run(num_shards=1, enum_impl="xla")
    sharded = run(num_shards=8, enum_impl="pallas_interpret")
    assert sharded.shape == ref.shape
    # Tolerances are the MEASURED composition of two documented error
    # sources, not wishful tightness: the interpreted kernel's Stirling
    # lgamma approximation carries a systematic (same-sign, so summed,
    # not averaged-out) per-bin error vs the XLA oracle
    # (test_pert_loss_parity_between_impls, PR 10), and the TOTAL
    # objective partially cancels between its large terms, inflating
    # that bias relative to the total — measured 3.1e-3 at iteration 0
    # at this shape, pinned at 1e-2; across the fitted trajectory Adam
    # chaotically amplifies the per-evaluation bias through the
    # parameter updates (the same regime test_2d_mesh_cells_x_loci
    # documents for psum reassociation), so the trajectory bound is
    # the loose 5e-2.  Sharded-pallas-vs-XLA at the old 2e-4 demanded
    # more than the kernel's own accuracy contract ever promised.
    np.testing.assert_allclose(sharded[0], ref[0], rtol=1e-2)
    np.testing.assert_allclose(sharded, ref, rtol=5e-2)


def test_loci_padding_does_not_change_losses(synthetic_frames):
    """Masked loci padding must be loss-invariant: a fit on 120 loci and a
    fit on the same data padded to 128 masked loci give identical loss
    trajectories (pins the masked reductions in the model)."""
    from scdna_replication_tools_tpu.data.loader import pad_loci

    s, g1, clone_idx = _dense_inputs(synthetic_frames)

    def run(s_in, g1_in):
        config = PertConfig(cn_prior_method="g1_clones", max_iter=25,
                            min_iter=12, run_step3=False)
        inf = PertInference(s_in, g1_in, config, clone_idx_s=clone_idx,
                            clone_idx_g1=clone_idx, num_clones=2)
        step1, step2, _ = inf.run()
        return step1.fit.losses, step2.fit.losses

    l1_ref, l2_ref = run(s, g1)
    l1_pad, l2_pad = run(pad_loci(s, 128), pad_loci(g1, 128))
    np.testing.assert_allclose(l1_pad, l1_ref, rtol=1e-5)
    np.testing.assert_allclose(l2_pad, l2_ref, rtol=1e-5)


def test_2d_mesh_cells_x_loci(synthetic_frames):
    """2x4 (cells x loci) mesh over 8 virtual devices.

    Sharding the loci axis reassociates the loci reductions (psum), so
    gradients differ at float32 epsilon and Adam chaotically amplifies
    that over iterations: iteration 0 must agree tightly (same math),
    the trajectory only loosely."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)

    def run(**kw):
        config = PertConfig(cn_prior_method="g1_clones", max_iter=25,
                            min_iter=12, run_step3=False, **kw)
        inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                            clone_idx_g1=clone_idx, num_clones=2)
        step1, step2, _ = inf.run()
        return step1.fit.losses, step2.fit.losses

    l1_ref, l2_ref = run(num_shards=1)
    l1_sh, l2_sh = run(num_shards=2, loci_shards=4)
    np.testing.assert_allclose(l1_sh[0], l1_ref[0], rtol=1e-5)
    # trajectory bound is loose BY DESIGN (see docstring): float32
    # psum reassociation differs at epsilon per iteration and Adam's
    # early sqrt(v)-normalised steps amplify it chaotically — the
    # measured worst element at this shape is ~3e-2, so 2e-2 was
    # permanently flaky while iteration 0 (the actual same-math pin)
    # holds at 1e-5
    np.testing.assert_allclose(l1_sh, l1_ref, rtol=5e-2)
    np.testing.assert_allclose(l2_sh, l2_ref, rtol=5e-2)


@pytest.mark.slow
def test_2d_mesh_with_loci_padding_and_pallas(synthetic_frames):
    """2x4 mesh where 120 loci pad to a multiple of 4 plus the interpreted
    Pallas kernel under shard_map — the full long-genome configuration.

    ``slow``: this is the COMPOSITION of test_2d_mesh_cells_x_loci (2-D
    mesh, XLA) and test_sharded_pallas_matches_single_device_xla
    (sharded interpreted kernel), both of which stay tier-1; the
    composed case costs ~24 s of interpreted-kernel wall and rides the
    slow matrix instead."""
    from scdna_replication_tools_tpu.data.loader import pad_loci

    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    # make loci count awkward: drop 3 loci so 117 must pad to 120
    import dataclasses as dc
    import pandas as pd

    def trim(d):
        return dc.replace(
            d, reads=d.reads[:, :117],
            states=None if d.states is None else d.states[:, :117],
            gammas=d.gammas[:117],
            rt_prior=None if d.rt_prior is None else d.rt_prior[:117],
            loci=d.loci[:117], loci_mask=d.loci_mask[:117])

    s, g1 = trim(s), trim(g1)

    def run(**kw):
        config = PertConfig(cn_prior_method="g1_clones", max_iter=25,
                            min_iter=12, run_step3=False, **kw)
        inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                            clone_idx_g1=clone_idx, num_clones=2)
        _, step2, _ = inf.run()
        return step2.fit.losses

    ref = run(num_shards=1, enum_impl="xla")
    sharded = run(num_shards=2, loci_shards=4, enum_impl="pallas_interpret")
    # same chaotic-amplification caveat as test_2d_mesh_cells_x_loci,
    # COMPOUNDED by the interpreted kernel's systematic lgamma error
    # vs the XLA reference arm (see
    # test_sharded_pallas_matches_single_device_xla for the measured
    # iteration-0 bias and its cancellation-inflation rationale) —
    # both error sources feed the trajectory here, so the bounds
    # match theirs
    np.testing.assert_allclose(sharded[0], ref[0], rtol=1e-2)
    np.testing.assert_allclose(sharded, ref, rtol=5e-2)
