"""Regression tests for padded/chunked execution (the sharded-path NaN
bug: all-ones etas padding made the ploidy guess 0 and NaN'd the loss)."""

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.config import ColumnConfig, PertConfig
from scdna_replication_tools_tpu.data.loader import build_pert_inputs
from scdna_replication_tools_tpu.infer.runner import PertInference, _pad_etas


def _dense_inputs(synthetic_frames):
    df_s, df_g = synthetic_frames
    rng = np.random.default_rng(0)
    for df in (df_s, df_g):
        df["reads"] = rng.poisson(
            40 * df["true_somatic_cn"].to_numpy()).astype(float)
        df["state"] = df["true_somatic_cn"].astype(int)
    cols = ColumnConfig(rt_prior_col=None)
    s, g1 = build_pert_inputs(df_s, df_g, cols)
    clone_idx = np.array([0] * 12 + [1] * 12, np.int32)
    return s, g1, clone_idx


def test_pad_etas_keeps_ploidy_positive():
    etas = np.ones((3, 10, 5), np.float32)
    etas[:, :, 3] = 50.0
    padded = _pad_etas(etas, 8)
    assert padded.shape == (8, 10, 5)
    # padded rows must argmax to a positive CN state
    assert (np.argmax(padded[3:], axis=-1) > 0).all()


def test_chunked_run_with_padding_stays_finite(synthetic_frames):
    """cell_chunk=16 pads 24 cells -> 32; every step loss must be finite."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    config = PertConfig(cn_prior_method="g1_clones", max_iter=40,
                        min_iter=20, cell_chunk=16, run_step3=True)
    inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    step1, step2, step3 = inf.run()
    for step in (step1, step2, step3):
        assert not step.fit.nan_abort
        assert np.isfinite(step.fit.losses).all()


def test_sharded_run_on_virtual_devices(synthetic_frames):
    """num_shards=8 over the virtual CPU mesh; 24 cells pad to 32."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)
    config = PertConfig(cn_prior_method="g1_clones", max_iter=30,
                        min_iter=15, num_shards=8, run_step3=False)
    inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                        clone_idx_g1=clone_idx, num_clones=2)
    step1, step2, step3 = inf.run()
    assert step3 is None
    for step in (step1, step2):
        assert not step.fit.nan_abort
        assert np.isfinite(step.fit.losses).all()


def test_sharded_pallas_matches_single_device_xla(synthetic_frames):
    """The shard_map'd interpreted kernel on an 8-device mesh must produce
    the same losses as the single-device XLA path (same math, different
    execution): validates the multi-chip Pallas route end to end."""
    s, g1, clone_idx = _dense_inputs(synthetic_frames)

    def run(**kw):
        config = PertConfig(cn_prior_method="g1_clones", max_iter=25,
                            min_iter=12, run_step3=False, **kw)
        inf = PertInference(s, g1, config, clone_idx_s=clone_idx,
                            clone_idx_g1=clone_idx, num_clones=2)
        _, step2, _ = inf.run()
        return step2.fit.losses

    ref = run(num_shards=1, enum_impl="xla")
    sharded = run(num_shards=8, enum_impl="pallas_interpret")
    assert sharded.shape == ref.shape
    np.testing.assert_allclose(sharded, ref, rtol=2e-4)
