"""Telemetry subsystem: schema-valid JSONL runs, guaranteed run_end,
in-fit diagnostics ring buffer + its <5% overhead bench guard.

The acceptance surface of the observability PR:

* a full pipeline run with telemetry enabled emits ONE JSONL whose
  events all validate against the checked-in ``runlog_schema.json`` and
  whose phase events cover >=95% of the measured wall (the PR 2
  invariant, now reproducible from the artifact alone);
* ``run_end`` lands even when the run dies mid-flight (the artifact of
  a crashed run says so, instead of silently truncating);
* the on-device diagnostics ring buffer samples the true trajectory
  without host syncs and without eroding fit throughput.
"""

import json
import os
import time

import numpy as np
import jax.numpy as jnp
import pytest

from scdna_replication_tools_tpu.api import scRT
from scdna_replication_tools_tpu.infer import svi
from scdna_replication_tools_tpu.infer.runner import (
    PertInference,
    _PertLossFn,
)
from scdna_replication_tools_tpu.infer.svi import DIAG_RING, fit_map
from scdna_replication_tools_tpu.models.pert import (
    PertBatch,
    PertModelSpec,
    init_params,
)
from scdna_replication_tools_tpu.obs import (
    RunLog,
    resolve_telemetry_path,
    summarize_run,
    validate_event,
    validate_run,
)
from scdna_replication_tools_tpu.ops.gc import gc_features


def _pipeline_frames(synthetic_frames):
    df_s, df_g = synthetic_frames
    df_s = df_s.assign(reads=np.random.default_rng(0)
                       .poisson(40, len(df_s)).astype(float),
                       state=df_s.true_somatic_cn.astype(int),
                       copy=df_s.true_somatic_cn)
    df_g = df_g.assign(reads=np.random.default_rng(1)
                       .poisson(40, len(df_g)).astype(float),
                       state=df_g.true_somatic_cn.astype(int),
                       copy=df_g.true_somatic_cn)
    return df_s, df_g


@pytest.fixture(scope="module")
def telemetry_run(synthetic_frames, tmp_path_factory):
    """One tiny end-to-end pipeline run with telemetry to a known file."""
    df_s, df_g = _pipeline_frames(synthetic_frames)
    log_path = tmp_path_factory.mktemp("runlog") / "run.jsonl"
    scrt = scRT(df_s, df_g, clone_col="clone_id",
                cn_prior_method="g1_clones", max_iter=10, min_iter=5,
                run_step3=True, telemetry_path=str(log_path),
                fit_diag_every=2)
    t0 = time.perf_counter()
    scrt.infer(level="pert")
    wall = time.perf_counter() - t0
    return scrt, log_path, wall


def _events(path):
    return [json.loads(line)
            for line in path.read_text().splitlines() if line.strip()]


def test_run_emits_single_schema_valid_jsonl(telemetry_run):
    scrt, path, _ = telemetry_run
    assert scrt.run_log_path == str(path)
    errors = validate_run(path)
    assert errors == [], f"schema violations: {errors[:10]}"


def test_run_event_inventory(telemetry_run):
    """The events the report tool relies on are all present."""
    _, path, _ = telemetry_run
    events = _events(path)
    kinds = [ev["event"] for ev in events]
    assert kinds[0] == "run_start"
    assert kinds[-1] == "run_end"
    start = events[0]
    from scdna_replication_tools_tpu.obs import SCHEMA_VERSION
    assert start["schema_version"] == SCHEMA_VERSION
    assert start["config_hash"]
    assert start["config"]["max_iter"] == 10
    assert start["process_index"] == 0
    assert {"step1", "step2", "step3"} == {
        ev["step"] for ev in events if ev["event"] == "fit_end"}
    compiles = [ev for ev in events if ev["event"] == "compile"]
    assert compiles, "no compile events emitted"
    assert all(ev["cache"] in ("hit", "miss", "uncacheable")
               for ev in compiles)
    # mirror_rescue defaults ON -> a rescue event (possibly 0 candidates)
    assert any(ev["event"] == "rescue" for ev in events)
    end = events[-1]
    assert end["status"] == "ok"
    assert end["events_emitted"] == len(events) - 1
    # fit diagnostics summary rides in fit_end
    fit2 = next(ev for ev in events
                if ev["event"] == "fit_end" and ev["step"] == "step2")
    assert fit2["diagnostics"]["every"] == 2
    assert fit2["diagnostics"]["samples"] >= 1


def test_phase_events_cover_95_percent_of_wall(telemetry_run):
    """The PR 2 coverage invariant, reproducible from the artifact
    alone: phase events (plus run_end's authoritative ledger) account
    for >=95% of the measured wall."""
    _, path, wall = telemetry_run
    summary = summarize_run(path)
    accounted = summary["phase_total"]
    assert accounted <= wall * 1.02, \
        "phases overlap: accounted exceeds the measured wall"
    assert accounted >= 0.95 * wall, \
        (f"phase events cover only {accounted / wall:.1%} of the wall "
         f"({accounted:.2f}s of {wall:.2f}s)")
    # the streamed phase events agree with run_end's final ledger
    events = _events(path)
    streamed: dict = {}
    for ev in events:
        if ev["event"] == "phase":
            streamed[ev["name"]] = streamed.get(ev["name"], 0.0) \
                + ev["seconds"]
    ledger = events[-1]["phases"]
    for name, secs in streamed.items():
        assert abs(ledger[name] - secs) < 0.01


def test_run_end_guaranteed_on_midrun_exception(synthetic_frames,
                                                tmp_path, monkeypatch):
    """An injected step-2 failure must still close the log with
    run_end(status=error) carrying the exception — the artifact of a
    crashed run explains itself."""
    df_s, df_g = _pipeline_frames(synthetic_frames)
    log_path = tmp_path / "crash.jsonl"

    def boom(self, *a, **k):
        raise RuntimeError("injected mid-run failure")

    monkeypatch.setattr(PertInference, "run_step2", boom)
    scrt = scRT(df_s, df_g, clone_col="clone_id",
                cn_prior_method="g1_clones", max_iter=6, min_iter=3,
                telemetry_path=str(log_path))
    with pytest.raises(RuntimeError, match="injected"):
        scrt.infer(level="pert")

    errors = validate_run(log_path)
    assert errors == [], f"crashed run log is schema-invalid: {errors[:10]}"
    events = _events(log_path)
    end = events[-1]
    assert end["event"] == "run_end"
    assert end["status"] == "error"
    assert end["error"]["type"] == "RuntimeError"
    assert "injected" in end["error"]["message"]
    # the step-1 fit that completed before the crash is in the artifact
    assert any(ev["event"] == "fit_end" and ev["step"] == "step1"
               for ev in events)


def test_schema_validator_rejects_bad_events():
    assert validate_event({"event": "phase", "seq": 0, "t": 0.0,
                           "name": "x", "seconds": 0.1}) == []
    # missing required payload field
    assert validate_event({"event": "phase", "seq": 0, "t": 0.0,
                           "name": "x"})
    # unknown event kind
    assert validate_event({"event": "wat", "seq": 0, "t": 0.0})
    # wrong type
    assert validate_event({"event": "phase", "seq": 0, "t": 0.0,
                           "name": 3, "seconds": 0.1})
    # bad enum value
    assert validate_event({"event": "compile", "seq": 0, "t": 0.0,
                           "key_hash": "x", "cache": "warmish"})


def test_resolve_telemetry_path_policies(tmp_path):
    assert resolve_telemetry_path(None) is None
    assert resolve_telemetry_path("none") is None
    assert resolve_telemetry_path("off") is None
    explicit = tmp_path / "my_run.jsonl"
    assert resolve_telemetry_path(str(explicit)) == str(explicit)
    into_dir = resolve_telemetry_path(str(tmp_path))
    assert into_dir.startswith(str(tmp_path))
    assert into_dir.endswith(".jsonl")
    auto = resolve_telemetry_path("auto")
    assert auto is not None and auto.endswith(".jsonl")


def test_auto_dir_retention_cap(tmp_path, monkeypatch):
    """The 'auto' directory keeps only the newest AUTO_RETAIN_RUNS logs
    (default-on telemetry must stay bounded); explicit directories are
    the user's and are never pruned."""
    from scdna_replication_tools_tpu.obs import runlog as rl

    monkeypatch.setattr(rl, "AUTO_RETAIN_RUNS", 3)
    auto_dir = tmp_path / "auto_runs"
    auto_dir.mkdir()
    for i in range(5):
        f = auto_dir / f"pert_old_{i}.jsonl"
        f.write_text("{}\n")
        os.utime(f, (1000 + i, 1000 + i))
    rl._prune_auto_dir(auto_dir)
    survivors = sorted(p.name for p in auto_dir.glob("*.jsonl"))
    # cap of 3 = 2 survivors + the about-to-be-written new log
    assert survivors == ["pert_old_3.jsonl", "pert_old_4.jsonl"]

    explicit = resolve_telemetry_path(str(tmp_path) + os.sep)
    assert explicit is not None  # explicit dir path resolves...
    assert (auto_dir / "pert_old_4.jsonl").exists()  # ...and prunes nothing


def test_fit_end_throughput_excludes_restored_iters(tmp_path):
    """A checkpoint-resumed fit reports total iters but rates over the
    resumed segment only — its wall covers just that segment, so
    counting the restored prefix would inflate iters/s by prefix/new."""
    from types import SimpleNamespace

    from scdna_replication_tools_tpu.infer.runner import PertInference
    from scdna_replication_tools_tpu.infer.svi import FitResult

    from scdna_replication_tools_tpu.config import PertConfig

    log = RunLog(str(tmp_path / "resume.jsonl"))
    host = SimpleNamespace(run_log=log, _finite=PertInference._finite,
                           config=PertConfig())
    fit = FitResult(params={}, losses=np.full(1000, -1.0, np.float32),
                    num_iters=1000, converged=True, nan_abort=False)
    with log.session(config={}):
        PertInference._emit_fit_events(host, "step2", fit, wall=2.0,
                                       num_cells=10, prior_iters=900)
    ev = next(e for e in _events(tmp_path / "resume.jsonl")
              if e["event"] == "fit_end")
    assert ev["iters"] == 1000
    assert ev["resumed_from_iter"] == 900
    assert ev["iters_per_second"] == 50.0   # 100 new iters / 2s
    assert ev["cells_per_second"] == 500.0


def test_runlog_nonzero_process_is_noop(tmp_path, monkeypatch):
    """Multi-host contract: only process 0 writes."""
    import jax

    monkeypatch.setattr(jax, "process_index", lambda: 1)
    log = RunLog.create(str(tmp_path / "rank1.jsonl"))
    assert not log.enabled
    with log.session(config=None):
        log.emit("note", msg="should vanish")
    assert not (tmp_path / "rank1.jsonl").exists()


def test_runlog_write_failure_disables_not_raises(tmp_path):
    log = RunLog(str(tmp_path))  # a DIRECTORY: open() will fail
    with log.session(config=None):
        log.emit("note", msg="x")
    assert not log.enabled  # degraded to no-op, no exception
    # a log disabled MID-run must still be fully closed on session exit:
    # no leaked handle, no instance stuck open
    assert log._fh is None and not log._open


def test_unwritable_telemetry_dir_degrades_to_disabled(tmp_path,
                                                       monkeypatch):
    """Telemetry is default-on, so an unwritable location must resolve
    to a disabled log (one warning) — never an exception into the
    inference it was meant to observe."""
    from scdna_replication_tools_tpu.utils import profiling

    monkeypatch.setattr(profiling, "probe_writable_dir", lambda p: False)
    assert resolve_telemetry_path("auto") is None
    assert resolve_telemetry_path(str(tmp_path) + "/") is None
    log = RunLog.create("auto")
    assert not log.enabled
    with log.session(config=None):
        log.emit("note", msg="dropped")  # no-op, no crash


def test_runlog_emit_outside_session_is_dropped(tmp_path):
    """No run_start-less orphan files from directly-driven step methods,
    and no truncation of a completed artifact by a late emit."""
    path = tmp_path / "run.jsonl"
    log = RunLog(str(path))
    log.emit("note", msg="before any session")
    assert not path.exists()          # dropped, not an orphan file
    with log.session(config=None):
        log.emit("note", msg="inside")
    size = path.stat().st_size
    log.emit("note", msg="after close")   # must not reopen/truncate
    assert path.stat().st_size == size
    assert validate_run(path) == []


def test_runlog_explicit_path_replaces_previous_run(tmp_path):
    """One run = one file: re-running against the same explicit path
    must not stack two event streams (validate_run pins seq as the
    line index)."""
    path = tmp_path / "same.jsonl"
    for marker in ("first", "second"):
        log = RunLog(str(path))
        with log.session(config={"marker": marker}):
            log.emit("note", marker=marker)
    assert validate_run(path) == []
    events = _events(path)
    assert [ev["event"] for ev in events] == ["run_start", "note",
                                             "run_end"]
    assert events[1]["marker"] == "second"


def test_runlog_instance_reuse_restarts_seq(tmp_path):
    """The SAME RunLog driven through two sessions (a re-invoked runner
    keeps one instance on self.run_log) must restart seq at 0 with the
    replaced file, or the gap-free 0..n-1 line-index contract breaks."""
    path = tmp_path / "reuse.jsonl"
    log = RunLog(str(path))
    with log.session(config={}):
        log.emit("note", marker="first")
        log.emit("note", marker="again")
    with log.session(config={}):
        log.emit("note", marker="second")
    assert validate_run(path) == []
    events = _events(path)
    assert [ev["seq"] for ev in events] == [0, 1, 2]
    assert events[1]["marker"] == "second"


# ---------------------------------------------------------------------------
# in-fit diagnostics ring buffer
# ---------------------------------------------------------------------------

SPEC = PertModelSpec(P=5, K=2, L=1, tau_mode="param")


def _problem(num_cells=8, num_loci=30, seed=0):
    rng = np.random.default_rng(seed)
    reads = rng.poisson(40, (num_cells, num_loci)).astype(np.float32)
    gammas = rng.uniform(0.35, 0.6, num_loci).astype(np.float32)
    etas = np.ones((num_cells, num_loci, SPEC.P), np.float32)
    etas[:, :, 2] = 100.0
    batch = PertBatch(
        reads=jnp.asarray(reads),
        libs=jnp.zeros(num_cells, jnp.int32),
        gamma_feats=gc_features(jnp.asarray(gammas), SPEC.K),
        mask=jnp.ones((num_cells,), jnp.float32),
        etas=jnp.asarray(etas),
    )
    params0 = init_params(SPEC, batch, {},
                          t_init=np.full(num_cells, 0.4, np.float32))
    return params0, batch


def test_diagnostics_sample_the_true_trajectory():
    params0, batch = _problem()
    fit = fit_map(_PertLossFn(spec=SPEC), params0, ({}, batch),
                  max_iter=20, min_iter=20, diag_every=5)
    d = fit.diagnostics
    assert d is not None and d["every"] == 5
    np.testing.assert_array_equal(d["iter"], [0, 5, 10, 15])
    # the sampled losses are exactly the loss history at those iters —
    # recorded on device inside the while_loop, no re-computation
    np.testing.assert_allclose(d["loss"], fit.losses[d["iter"]],
                               rtol=1e-6)
    assert np.isfinite(d["grad_norm"]).all()
    assert (d["grad_norm"] > 0).all()
    assert np.isfinite(d["param_norm"]).all()
    assert (d["param_norm"] > 0).all()


def test_diagnostics_ring_keeps_last_window():
    """More samples than slots: the ring holds the LAST DIAG_RING."""
    params0, batch = _problem()
    n = DIAG_RING + 20
    fit = fit_map(_PertLossFn(spec=SPEC), params0, ({}, batch),
                  max_iter=n, min_iter=n, diag_every=1)
    d = fit.diagnostics
    assert len(d["iter"]) == DIAG_RING
    np.testing.assert_array_equal(d["iter"], np.arange(20, n))
    np.testing.assert_allclose(d["loss"], fit.losses[20:], rtol=1e-6)


def test_diagnostics_disabled_by_default():
    params0, batch = _problem()
    fit = fit_map(_PertLossFn(spec=SPEC), params0, ({}, batch),
                  max_iter=6, min_iter=3)
    assert fit.diagnostics is None


def test_diagnostics_overhead_below_5_percent():
    """Bench guard for the acceptance bar: the ring buffer must add <5%
    wall to the step-2 fit at the smoke shape.  Methodology: both
    programs pre-compiled (warmup), then alternating timed dispatches,
    best-of-N per config to cut scheduler noise; a small absolute slack
    absorbs timer jitter at sub-second walls."""
    svi.clear_program_cache()
    iters = 60

    def one_fit(diag_every, seed):
        params0, batch = _problem(num_cells=64, num_loci=256, seed=seed)
        fit = fit_map(_PertLossFn(spec=SPEC), params0, ({}, batch),
                      max_iter=iters, min_iter=iters,
                      diag_every=diag_every)
        assert fit.num_iters == iters
        return fit.timings["fit"]

    one_fit(0, seed=0)   # compile both programs outside the
    one_fit(25, seed=0)  # timed region
    base, diag = [], []
    for rep in range(1, 6):
        base.append(one_fit(0, seed=rep))
        diag.append(one_fit(25, seed=rep))
    base_wall, diag_wall = min(base), min(diag)
    assert diag_wall <= base_wall * 1.05 + 0.015, \
        (f"diagnostics ring buffer costs "
         f"{(diag_wall / base_wall - 1):.1%} of the fit wall "
         f"(base {base_wall:.3f}s vs diag {diag_wall:.3f}s)")
