"""Simulate-and-recover: the quantitative acceptance test.

The reference only eyeballs recovery in notebooks (SURVEY.md §4); here it
is automated — PERT inference must recover the simulator's ground truth
(replication states, somatic CN, per-cell S-phase times) from read counts
alone.
"""

import numpy as np
import pandas as pd
import pytest

from scdna_replication_tools_tpu.api import scRT
from scdna_replication_tools_tpu.models.simulator import pert_simulator


@pytest.fixture(scope="module")
def sim_data(synthetic_frames):
    df_s, df_g = synthetic_frames
    sim_s, sim_g = pert_simulator(
        df_s, df_g, num_reads=50_000, rt_cols=["rt_A", "rt_B"],
        clones=["A", "B"], lamb=0.75, betas=[0.5, 0.0], a=10.0, seed=11)
    for df in (sim_s, sim_g):
        df["reads"] = df["true_reads_norm"]
        df["state"] = df["true_somatic_cn"].astype(int)
        df["copy"] = df["true_somatic_cn"].astype(float)
    return sim_s, sim_g


@pytest.fixture(scope="module")
def pert_output(sim_data):
    sim_s, sim_g = sim_data
    scrt = scRT(sim_s.copy(), sim_g.copy(), input_col="reads",
                clone_col="clone_id", assign_col="copy",
                cn_prior_method="g1_clones", max_iter=400, min_iter=100,
                rt_prior_col=None, run_step3=True)
    return scrt.infer(level="pert")


def test_output_contract(pert_output):
    cn_s_out, supp_s, cn_g1_out, supp_g1 = pert_output
    for col in ["model_cn_state", "model_rep_state", "model_tau", "model_u",
                "model_rho"]:
        assert col in cn_s_out.columns, col
        assert col in cn_g1_out.columns, col
    assert {"model_lambda", "model_a", "loss_g", "loss_s"} <= \
        set(supp_s["param"].unique())
    # loss curves decreased
    loss_s = supp_s.query("param == 'loss_s'")["value"].to_numpy()
    assert loss_s[-1] < loss_s[0]


def test_recovers_replication_states(pert_output):
    cn_s_out, *_ = pert_output
    acc = (cn_s_out["model_rep_state"] == cn_s_out["true_rep"]).mean()
    assert acc > 0.80, f"rep-state accuracy {acc:.3f}"


def test_recovers_somatic_cn(pert_output):
    cn_s_out, *_ = pert_output
    acc = (cn_s_out["model_cn_state"] == cn_s_out["true_somatic_cn"]).mean()
    assert acc > 0.90, f"CN accuracy {acc:.3f}"


def test_recovers_tau_ordering(pert_output):
    cn_s_out, *_ = pert_output
    per_cell = cn_s_out.groupby("cell_id").agg(
        tau=("model_tau", "first"), true_t=("true_t", "first"))
    r = np.corrcoef(per_cell["tau"], per_cell["true_t"])[0, 1]
    assert r > 0.8, f"tau correlation {r:.3f}"


def test_recovers_lambda(pert_output):
    _, supp_s, *_ = pert_output
    lamb = supp_s.query("param == 'model_lambda'")["value"].iloc[0]
    assert 0.5 < lamb < 0.95, f"lambda {lamb:.3f} vs true 0.75"


def test_g1_cells_mostly_unreplicated(pert_output):
    _, _, cn_g1_out, _ = pert_output
    # step 3 reruns the S model on G1 cells; their replicated fraction
    # should be extreme (near 0 or 1 is how PERT flags non-replicating)
    frac = cn_g1_out.groupby("cell_id")["model_rep_state"].mean()
    assert ((frac < 0.2) | (frac > 0.8)).mean() > 0.7
