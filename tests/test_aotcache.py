"""Persistent AOT executable store (infer/aotcache.py).

Four satellites of the zero-compile-cold-start contract:

* robustness — a truncated/corrupt entry is quarantined (``*.bad``)
  and the resolver falls back to a clean recompile; a jax-version or
  device-kind mismatch is an honest miss, never a deserialize;
* LRU — the on-disk store is size-capped, evicting
  least-recently-USED (probes touch mtime);
* cross-process — worker B disk-hits worker A's entry (the actual
  fleet-restart story), with the canonical ``_key_hash`` comparable
  across the two processes;
* the double-compile race fix — concurrent same-signature cold misses
  compile ONCE (per-key in-flight leader/followers), and a crashed
  leader's followers retry instead of hanging.
"""

import glob
import os
import pickle
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scdna_replication_tools_tpu.infer import aotcache, svi

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _quad_loss(params, x):
    return jnp.sum((params["w"] - x) ** 2)


@pytest.fixture(autouse=True)
def _isolated_caches():
    svi.clear_program_cache()
    aotcache.deactivate()
    yield
    svi.clear_program_cache()
    aotcache.deactivate()


def _fit(store_dir, n=4):
    aotcache.activate(str(store_dir), config_digest="test-digest")
    return svi.fit_map(_quad_loss, {"w": jnp.zeros(n)}, (jnp.ones(n),),
                       max_iter=40, min_iter=10)


def _store_files(store_dir):
    return sorted(glob.glob(os.path.join(str(store_dir), "*.pertexec")))


# -- roundtrip + robustness ------------------------------------------------

def test_cold_process_miss_becomes_disk_hit(tmp_path):
    r1 = _fit(tmp_path)
    assert r1.timings["program_cache"] == "miss"
    assert len(_store_files(tmp_path)) == 1
    # a fresh process is simulated by clearing the in-process cache:
    # the next resolution probes the disk store instead of XLA
    svi.clear_program_cache()
    r2 = _fit(tmp_path)
    assert r2.timings["program_cache"] == "disk_hit"
    assert r2.timings["deserialize"] > 0.0
    np.testing.assert_allclose(np.asarray(r2.params["w"]),
                               np.asarray(r1.params["w"]))


def test_corrupt_entry_quarantined_then_clean_recompile(tmp_path):
    _fit(tmp_path)
    path = _store_files(tmp_path)[0]
    with open(path, "wb") as fh:
        fh.write(b"torn write, not a pickle")
    svi.clear_program_cache()
    r = _fit(tmp_path)
    assert r.timings["program_cache"] == "miss"   # recompiled cleanly
    bad = glob.glob(os.path.join(str(tmp_path), "*.bad"))
    assert len(bad) == 1                          # quarantined, kept
    # the recompile re-saved a healthy entry under the same digest
    assert len(_store_files(tmp_path)) == 1


def test_truncated_entry_quarantined(tmp_path):
    _fit(tmp_path)
    path = _store_files(tmp_path)[0]
    data = open(path, "rb").read()
    with open(path, "wb") as fh:
        fh.write(data[: len(data) // 2])          # torn mid-payload
    svi.clear_program_cache()
    r = _fit(tmp_path)
    assert r.timings["program_cache"] == "miss"
    assert glob.glob(os.path.join(str(tmp_path), "*.bad"))


def test_save_rejects_payload_that_does_not_load_back(tmp_path,
                                                      monkeypatch):
    # An XLA:CPU executable revived from jax's persistent COMPILATION
    # cache serializes into a payload with dangling fusion symbols
    # (deserialize raises "Symbols not found").  The save-side
    # round-trip gate must refuse to land such an entry.
    import jax.experimental.serialize_executable as se

    real = se.serialize

    def corrupting(compiled):
        payload, in_tree, out_tree = real(compiled)
        return payload[: len(payload) // 2], in_tree, out_tree

    monkeypatch.setattr(se, "serialize", corrupting)
    store = aotcache.ExecutableStore(str(tmp_path))
    compiled = jax.jit(lambda x: x * 2).lower(jnp.zeros(3)).compile()
    landed, why = store.save("dead", "key", compiled, {})
    assert (landed, why) == (False, "unloadable")
    assert not _store_files(tmp_path)             # nothing written
    assert not glob.glob(os.path.join(str(tmp_path), "*.bad"))


def test_unloadable_save_retries_with_compile_cache_bypassed(
        tmp_path, monkeypatch):
    # The resolver's reaction to an "unloadable" save: recompile once
    # with jax's compilation cache bypassed and store THAT payload —
    # the second serialize (of the fresh executable) round-trips.
    import jax.experimental.serialize_executable as se

    real = se.serialize
    calls = {"n": 0}

    def first_call_corrupts(compiled):
        calls["n"] += 1
        payload, in_tree, out_tree = real(compiled)
        if calls["n"] == 1:
            return payload[: len(payload) // 2], in_tree, out_tree
        return payload, in_tree, out_tree

    monkeypatch.setattr(se, "serialize", first_call_corrupts)
    r = _fit(tmp_path)
    assert r.timings["program_cache"] == "miss"
    assert calls["n"] == 2                        # save, then retry
    assert len(_store_files(tmp_path)) == 1       # retry landed it
    svi.clear_program_cache()
    assert _fit(tmp_path).timings["program_cache"] == "disk_hit"


@pytest.mark.parametrize("field,value", [
    ("jax_version", "0.0.0-elsewhere"),
    ("device_kind", "TPU v9000"),
    ("backend", "warp-drive"),
])
def test_env_mismatch_misses_without_deserializing(tmp_path, field, value):
    _fit(tmp_path)
    path = _store_files(tmp_path)[0]
    record = pickle.loads(open(path, "rb").read())
    record["env"][field] = value
    with open(path, "wb") as fh:
        fh.write(pickle.dumps(record))
    store = aotcache.active_store()
    digest = os.path.basename(path)[: -len(".pertexec")]
    assert store._load_from_disk(digest) is None
    # an env mismatch is an honest miss, NOT corruption: no quarantine
    assert not glob.glob(os.path.join(str(tmp_path), "*.bad"))
    assert os.path.exists(path)


# -- LRU / size cap --------------------------------------------------------

def _toy_compiled():
    return jax.jit(lambda x: x + 1).lower(jnp.zeros(3)).compile()


def test_store_evicts_least_recently_used(tmp_path):
    store = aotcache.ExecutableStore(str(tmp_path), max_entries=10)
    compiled = _toy_compiled()
    now = time.time()
    for i in range(4):
        assert store.save(f"d{i:02d}", "key", compiled, {})[0]
        # deterministic recency order regardless of fs mtime resolution
        os.utime(store.path(f"d{i:02d}"), (now + i, now + i))
    store.max_entries = 3
    store._evict()
    left = {os.path.basename(p) for p in _store_files(tmp_path)}
    assert left == {"d01.pertexec", "d02.pertexec", "d03.pertexec"}
    # a probe TOUCHES its entry: d01 becomes most-recent and survives
    # the next insertion round, d02 (now oldest) is evicted
    assert store.load("d01") is not None
    os.utime(store.path("d01"), (now + 9, now + 9))
    store.max_entries = 10          # keep save's own evict pass inert
    store.save("d04", "key", compiled, {})
    os.utime(store.path("d04"), (now + 10, now + 10))
    store.max_entries = 3
    store._evict()
    left = {os.path.basename(p) for p in _store_files(tmp_path)}
    assert left == {"d01.pertexec", "d03.pertexec", "d04.pertexec"}


def test_preload_serves_from_ram(tmp_path):
    _fit(tmp_path)
    store = aotcache.active_store()
    digest = os.path.basename(_store_files(tmp_path)[0])[
        : -len(".pertexec")]
    assert store.preload(digest)
    assert store.preloaded_count() == 1
    os.remove(store.path(digest))                 # disk gone, RAM serves
    assert store.load(digest) is not None
    assert store.preloaded_count() == 0           # consumed once


# -- key canonicalisation --------------------------------------------------

def test_canonical_key_text_scrubs_addresses():
    key = ("fit", object(), (), ())
    text = aotcache.canonical_key_text(key)
    assert "0xADDR" in text
    import re
    assert not re.search(r"0x[0-9a-fA-F]{6,}", text)


def test_key_digest_is_deterministic():
    env = {"jax_version": "1", "backend": "cpu"}
    a = aotcache.key_digest("ktext", env=env, config_digest="cfg")
    b = aotcache.key_digest("ktext", env=env, config_digest="cfg")
    assert a == b
    assert a != aotcache.key_digest("ktext", env=env, config_digest="other")


# -- two-process: worker B hits worker A's entry ---------------------------

_CHILD = """
import sys, json
sys.path.insert(0, {root!r})
import jax.numpy as jnp
from scdna_replication_tools_tpu.infer import aotcache, svi
from scdna_replication_tools_tpu.infer.svi import _key_hash, _abstract_sig

def loss(params, x):
    return jnp.sum((params["w"] - x) ** 2)

aotcache.activate({store!r}, config_digest="shared")
r = svi.fit_map(loss, {{"w": jnp.zeros(4)}}, (jnp.ones(4),),
                max_iter=40, min_iter=10)
key = ("fit", None, (), _abstract_sig(((jnp.ones(4),), {{}})))
print(json.dumps({{"program_cache": r.timings["program_cache"],
                   "key_hash": _key_hash(key)}}))
"""


def test_two_process_disk_hit_and_cross_process_key_hash(tmp_path):
    """Worker A compiles and persists; worker B — a genuinely separate
    process — deserializes instead of compiling, and the canonical
    ``_key_hash`` of an identical logical key matches across the two
    processes (the pert_trace correlation contract)."""
    script = _CHILD.format(root=REPO_ROOT, store=str(tmp_path))
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run():
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stderr
        import json
        return json.loads(proc.stdout.strip().splitlines()[-1])

    a = run()
    assert a["program_cache"] == "miss"
    b = run()
    assert b["program_cache"] == "disk_hit"
    assert a["key_hash"] == b["key_hash"]


# -- the double-compile race fix -------------------------------------------

class _CountingTarget:
    """Stands in for the jitted target: lower()/compile() are slow
    enough that both threads would historically race into XLA."""

    def __init__(self, fail_first=False):
        self.lowers = 0
        self.fail_first = fail_first
        self._lock = threading.Lock()

    def lower(self, loss_fn, *args, **kwargs):
        with self._lock:
            self.lowers += 1
            n = self.lowers
        time.sleep(0.15)
        if self.fail_first and n == 1:
            raise RuntimeError("leader dies mid-compile")
        return self

    def compile(self):
        time.sleep(0.1)
        return lambda *a, **k: None


def _resolve_concurrently(target, n_threads=4):
    results, errors = [], []

    def worker():
        try:
            results.append(svi._resolve_program(
                target, "fit", _quad_loss, (jnp.ones(3),), {}, {}, {}))
        except Exception as exc:  # noqa: BLE001 — asserted below
            errors.append(exc)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    return results, errors


def test_concurrent_cold_misses_compile_once():
    target = _CountingTarget()
    results, errors = _resolve_concurrently(target)
    assert errors == []
    assert len(results) == 4
    assert len({id(r) for r in results}) == 1     # all got THE program
    assert target.lowers == 1                     # one XLA invocation


def test_followers_retry_when_leader_dies():
    target = _CountingTarget(fail_first=True)
    results, errors = _resolve_concurrently(target)
    # exactly one thread (the first leader) saw the failure; a follower
    # took over, compiled, and the rest shared its program
    assert len(errors) == 1
    assert len(results) == 3
    assert len({id(r) for r in results}) == 1
    assert target.lowers == 2
