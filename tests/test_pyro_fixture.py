"""Consume the recorded Pyro head-to-head fixture when it exists.

The build image cannot produce the recorded Pyro run itself: ``pyro-ppl``
is not installed and the image has no network egress, so
``tools/compare_vs_pyro.py`` (and the best-effort ``pyro-parity`` CI job
that runs it and uploads ``pyro_compare.json``) must execute on a
networked machine.  THIS module is the receiving end: the moment a
``pyro_compare.json`` is checked in at the repo root or under ``tools/``,
these assertions activate and pin the framework against the actual
reference execution (reference: pert_model.py:792-830) —

* matched final step-2 loss scale (the north star's matched-ELBO half,
  BASELINE.json);
* >= 95% cn/rep decode agreement;
* tau correlation >= 0.95 between implementations;
* our truth-accuracy within 5 points of Pyro's (the calibration the
  e2e-test bars derive from).

Until then the suite's anchor remains tests/test_reference_oracle.py's
independent float64 transcription, and this module skips with an
explanatory message rather than passing silently.
"""

import json
import pathlib

import pytest

_CANDIDATES = [
    pathlib.Path(__file__).resolve().parent.parent / "pyro_compare.json",
    pathlib.Path(__file__).resolve().parent.parent / "tools"
    / "pyro_compare.json",
]


@pytest.fixture(scope="module")
def pyro_report():
    for p in _CANDIDATES:
        if p.exists():
            with open(p) as fh:
                return json.load(fh)
    pytest.skip(
        "no recorded pyro_compare.json fixture: pyro-ppl is not "
        "installable in this image (no network egress) — produce it with "
        "`python tools/compare_vs_pyro.py` on a networked machine or via "
        "the pyro-parity CI job, then check the JSON in at the repo root")


def test_matched_final_loss_scale(pyro_report):
    jax_loss = pyro_report["jax_final_loss_s"]
    ref_loss = pyro_report["pyro_final_loss_s"]
    rel = abs(jax_loss - ref_loss) / max(abs(ref_loss), 1.0)
    assert rel < 0.05, (
        f"final step-2 loss mismatch: jax {jax_loss} vs pyro {ref_loss} "
        f"(rel {rel:.3f})")


def test_decode_agreement(pyro_report):
    assert pyro_report["rep_agreement"] >= 0.95, pyro_report
    assert pyro_report["cn_agreement"] >= 0.95, pyro_report


def test_tau_correlation(pyro_report):
    assert pyro_report["tau_correlation"] >= 0.95, pyro_report


def test_truth_accuracy_not_worse_than_pyro(pyro_report):
    """The e2e bars (test_end_to_end.py) calibrate from this: our
    accuracy vs simulator truth must sit within 5 points of what the
    Pyro reference achieves on the identical workload."""
    ours = pyro_report["jax_rep_acc_vs_truth"]
    theirs = pyro_report["pyro_rep_acc_vs_truth"]
    assert ours >= theirs - 0.05, (ours, theirs)
