"""CN-prior builders vs direct NumPy transcriptions of the reference.

The vectorised builders in ``models/priors.py`` replace the reference's
Python triple loops (reference: pert_model.py:272-361, 668-716).  Each
oracle here is that loop, transcribed verbatim (reference layout:
(loci, cells)), so any vectorisation mistake — one-hot off-by-one, wrong
tie-breaking, a dropped ploidy filter — shows up as a tensor mismatch.

Covers every ``cn_prior_method``: hmmcopy, diploid, g1_cells, g1_clones,
and the DEFAULT g1_composite (previously the only untested method), plus
the runner-level dispatch and a multi-library step-1 GC-beta recovery
test (reference: pert_model.py:560-562).
"""

import numpy as np
import pytest
from scipy.stats import mode as scipy_mode
from scipy.stats import pearsonr

from scdna_replication_tools_tpu.models import priors


# ---------------------------------------------------------------------------
# reference-loop oracles ((loci, cells) layout like the reference)
# ---------------------------------------------------------------------------

def ref_build_cn_prior(cn_lc, P, weight):
    """pert_model.py:272-282, verbatim loops."""
    num_loci, num_cells = cn_lc.shape
    etas = np.ones((num_loci, num_cells, P), np.float64)
    for i in range(num_loci):
        for n in range(num_cells):
            etas[i, n, int(cn_lc[i, n])] = weight
    return etas


def ref_cell_ploidies(g1_states):
    """add_cell_ploidies: per-cell mode of the state column
    (compute_consensus_clone_profiles.py:30-39)."""
    return np.array([scipy_mode(row, keepdims=True).mode[0]
                     for row in g1_states], np.float64)


def ref_majority_keep(ploidies, clone_idx):
    """filter_ploidies: keep each clone's majority ploidy; pandas
    ``idxmax`` takes the smallest key on ties
    (compute_consensus_clone_profiles.py:17-27)."""
    keep = np.zeros(len(ploidies), bool)
    for c in np.unique(clone_idx):
        sel = clone_idx == c
        vals, counts = np.unique(ploidies[sel], return_counts=True)
        keep |= sel & (ploidies == vals[np.argmax(counts)])
    return keep


def ref_composite_prior(s_reads, s_clone, g1_reads, g1_states, g1_clone,
                        clone_profiles, P, J, weight=1e5):
    """build_composite_cn_prior, verbatim loops (pert_model.py:299-361)."""
    num_cells, num_loci = s_reads.shape

    # J clamp to smallest clone's G1 cell count (:307-310)
    sizes = np.bincount(g1_clone)
    J = min(J, int(sizes[sizes > 0].min()))

    # ploidy filter of the G1 pool (:312-317)
    keep = ref_majority_keep(ref_cell_ploidies(g1_states), g1_clone)

    # documented deviation from the reference: when the ploidy filter
    # shrinks a clone below J, the reference's ``psi_mat.iloc[j]`` would
    # raise IndexError (:349-350); the build clamps J to the filtered
    # pool instead (models/priors.py:143-149), so the oracle does too
    filt_sizes = [max(int(((g1_clone == c) & keep).sum()), 1)
                  for c in np.unique(g1_clone)]
    J = min(J, int(min(filt_sizes)))

    etas = np.ones((num_loci, num_cells, P), np.float64)
    for n in range(num_cells):
        clone = s_clone[n]
        clone_profile = clone_profiles[clone].astype(np.int64)

        # pearson vs every kept G1 cell of the same clone, sorted desc
        # (:335-337 via compute_cell_corrs)
        cands = [g for g in range(len(g1_clone))
                 if g1_clone[g] == clone and keep[g]]
        corrs = [pearsonr(s_reads[n], g1_reads[g])[0] for g in cands]
        order = [cands[k] for k in np.argsort(corrs)[::-1]]

        g1_cell_cns = np.zeros((num_loci, J))
        for j in range(J):
            g1_cell_cns[:, j] = g1_states[order[j]]

        for i in range(num_loci):
            etas[i, n, int(clone_profile[i])] += weight * J * 2   # :352-354
            for j in range(J):
                etas[i, n, int(g1_cell_cns[i, j])] += weight * (J - j)  # :356-359
    return etas


def ref_g1_cells_prior(s_reads, s_clone, g1_reads, g1_states, g1_clone,
                       P, weight):
    """The g1_cells dispatch branch, verbatim (pert_model.py:671-701):
    single best-Pearson G1 cell of the same clone, NO ploidy filter."""
    num_cells, num_loci = s_reads.shape
    cn_prior_input = np.zeros((num_loci, num_cells))
    for n in range(num_cells):
        cands = [g for g in range(len(g1_clone)) if g1_clone[g] == s_clone[n]]
        corrs = [pearsonr(s_reads[n], g1_reads[g])[0] for g in cands]
        best = cands[int(np.argmax(corrs))]
        cn_prior_input[:, n] = g1_states[best]
    return ref_build_cn_prior(cn_prior_input, P, weight)


# ---------------------------------------------------------------------------
# fixture
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def prior_problem():
    rng = np.random.default_rng(42)
    P, J = 8, 5
    num_loci = 60
    n_s, n_g1 = 10, 16     # 8 G1 cells per clone

    g1_clone = np.repeat([0, 1], n_g1 // 2).astype(np.int64)
    s_clone = (np.arange(n_s) % 2).astype(np.int64)

    base = np.full(num_loci, 2)
    prof_a = base.copy()
    prof_a[40:55] = 4
    prof_b = base.copy()
    prof_b[10:30] = 3
    profiles = np.stack([prof_a, prof_b]).astype(np.float64)

    g1_states = profiles[g1_clone].astype(np.int64)
    g1_states += rng.integers(-1, 2, g1_states.shape) * \
        (rng.random(g1_states.shape) < 0.08)
    g1_states = np.clip(g1_states, 0, P - 1)
    # one clone-0 cell is whole-genome tetraploid: the majority-ploidy
    # filter must drop it from the composite's G1 pool
    g1_states[2] = 4

    s_reads = rng.gamma(20, 2, (n_s, num_loci))
    # correlate each S cell with a few same-clone G1 profiles
    g1_reads = np.stack([
        rng.gamma(20, 2, num_loci) + 10 * g1_states[g]
        for g in range(n_g1)])
    s_reads = s_reads + 10 * profiles[s_clone]

    return dict(P=P, J=J, s_reads=s_reads, s_clone=s_clone,
                g1_reads=g1_reads, g1_states=g1_states, g1_clone=g1_clone,
                profiles=profiles)


# ---------------------------------------------------------------------------
# tests: each method vs its loop oracle
# ---------------------------------------------------------------------------

def test_hmmcopy_prior_matches_loops(prior_problem):
    p = prior_problem
    states = p["g1_states"][: 6]
    ours = priors.cn_prior_from_states(states, p["P"], 1e6)
    ref = ref_build_cn_prior(states.T, p["P"], 1e6)
    np.testing.assert_allclose(ours, np.transpose(ref, (1, 0, 2)))


def test_diploid_prior_matches_loops(prior_problem):
    p = prior_problem
    dip = np.full((4, 30), 2.0)
    ours = priors.cn_prior_from_states(dip, p["P"], 1e6)
    ref = ref_build_cn_prior(dip.T, p["P"], 1e6)
    np.testing.assert_allclose(ours, np.transpose(ref, (1, 0, 2)))


def test_clone_prior_matches_loops(prior_problem):
    p = prior_problem
    # non-integral consensus (median can be x.5): int truncation must match
    profiles = p["profiles"] + 0.5
    ours = priors.clone_cn_prior(p["s_clone"], profiles, p["P"], 1e6)
    ref_input = np.zeros((profiles.shape[1], len(p["s_clone"])))
    for n, c in enumerate(p["s_clone"]):
        ref_input[:, n] = profiles[c].astype(np.int64)   # pert_model.py:289
    ref = ref_build_cn_prior(ref_input, p["P"], 1e6)
    np.testing.assert_allclose(ours, np.transpose(ref, (1, 0, 2)))


def test_g1_cells_prior_matches_loops(prior_problem):
    p = prior_problem
    from scdna_replication_tools_tpu.ops.stats import pearson_matrix
    corr = np.asarray(pearson_matrix(p["s_reads"].astype(np.float32),
                                     p["g1_reads"].astype(np.float32)))
    same = p["s_clone"][:, None] == p["g1_clone"][None, :]
    best = np.argmax(np.where(same, corr, -np.inf), axis=1)
    ours = priors.cn_prior_from_states(p["g1_states"][best], p["P"], 1e6)
    ref = ref_g1_cells_prior(p["s_reads"], p["s_clone"], p["g1_reads"],
                             p["g1_states"], p["g1_clone"], p["P"], 1e6)
    np.testing.assert_allclose(ours, np.transpose(ref, (1, 0, 2)))


def test_composite_prior_matches_loops(prior_problem):
    """The DEFAULT cn_prior_method (g1_composite) vs the verbatim loop
    transcription — including the ploidy filter and the J clamp."""
    p = prior_problem
    ours = priors.composite_cn_prior(
        p["s_reads"].astype(np.float32), p["s_clone"],
        p["g1_reads"].astype(np.float32), p["g1_states"], p["g1_clone"],
        p["profiles"], p["P"], J=p["J"])
    ref = ref_composite_prior(
        p["s_reads"], p["s_clone"], p["g1_reads"], p["g1_states"],
        p["g1_clone"], p["profiles"], p["P"], p["J"])
    np.testing.assert_allclose(ours, np.transpose(ref, (1, 0, 2)),
                               rtol=1e-6)


def test_composite_ploidy_filter_excludes_offploidy_cell(prior_problem):
    """The tetraploid clone-0 cell must contribute to NO S cell's top-J
    (it would otherwise rank by correlation like any other)."""
    p = prior_problem
    with_cell = priors.composite_cn_prior(
        p["s_reads"].astype(np.float32), p["s_clone"],
        p["g1_reads"].astype(np.float32), p["g1_states"], p["g1_clone"],
        p["profiles"], p["P"], J=p["J"])
    # remove the tetraploid cell entirely: identical etas ⇒ it was excluded
    keep = np.ones(len(p["g1_clone"]), bool)
    keep[2] = False
    without_cell = priors.composite_cn_prior(
        p["s_reads"][:].astype(np.float32), p["s_clone"],
        p["g1_reads"][keep].astype(np.float32), p["g1_states"][keep],
        p["g1_clone"][keep], p["profiles"], p["P"], J=p["J"])
    np.testing.assert_allclose(with_cell, without_cell)


def test_j_clamped_to_smallest_clone(prior_problem):
    """J larger than the smallest clone's G1 count must clamp, not crash
    (pert_model.py:307-310)."""
    p = prior_problem
    etas = priors.composite_cn_prior(
        p["s_reads"].astype(np.float32), p["s_clone"],
        p["g1_reads"].astype(np.float32), p["g1_states"], p["g1_clone"],
        p["profiles"], p["P"], J=50)
    ref = ref_composite_prior(
        p["s_reads"], p["s_clone"], p["g1_reads"], p["g1_states"],
        p["g1_clone"], p["profiles"], p["P"], J=50)
    np.testing.assert_allclose(etas, np.transpose(ref, (1, 0, 2)), rtol=1e-6)


def test_uniform_prior_shape():
    etas = priors.uniform_prior(3, 7, 5)
    assert etas.shape == (3, 7, 5)
    np.testing.assert_allclose(etas, 0.2)
