"""Device-cost and goodput reporting over meter ledgers
(``obs/meter.py``): the CLI of the cost plane.

Every telemetry-enabled run closes with a ``meter`` section on
``run_end`` (schema v9) — attributed device-seconds decomposed into
effective work and named waste (padding, retired_lane, compile,
compile_deserialize, retry_refit, queue_idle), plus goodput in
cell-iterations per device-second.  This tool renders and cross-checks
those ledgers:

    # one run (or a results dir / a whole serve spool): the
    # efficiency waterfall — billed -> waste rows -> effective
    python -m tools.pert_meter report RUN.jsonl
    python -m tools.pert_meter report /data/pert_spool

    # fleet/tenant accounting over a spool: per-tenant and per-bucket
    # rollups joined from the worker log(s) and every request's own
    # run log, with the conservation invariant checked on each ledger
    python -m tools.pert_meter attribution /data/pert_spool --check

    # two-arm cost comparison (bench artifacts, runs, or spools):
    # device-seconds per request, goodput, waste mix deltas
    python -m tools.pert_meter ab baseline.jsonl candidate.jsonl

``--json`` on every verb emits the machine document instead of
markdown (one JSON object on stdout, bench.py-style).  The
conservation contract — billed == effective + sum(waste) within 1% —
is asserted by ``--check`` (exit 1 on violation); the CI meter smoke
runs exactly that over a real spool.  Event reference:
OBSERVABILITY.md "Cost & goodput: the meter".
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from scdna_replication_tools_tpu.obs.meter import (  # noqa: E402
    WASTE_CATEGORIES,
    conservation_gap,
)

_BAR_WIDTH = 30
#: the 1% conservation tolerance the acceptance contract names
CONSERVATION_TOL = 0.01


# ---------------------------------------------------------------------------
# loading: run logs, results dirs, spools, bench artifacts
# ---------------------------------------------------------------------------

def _iter_events(path):
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue  # torn tail line of a live log


def meter_of_run(path):
    """The ``meter`` section of a run log's ``run_end`` (None when the
    run predates schema v9, metered nothing, or never ended)."""
    meter = None
    for ev in _iter_events(path):
        if ev.get("event") == "run_end" and ev.get("meter"):
            meter = ev["meter"]
    return meter


def _request_rows_of_worker_log(path):
    """request_end joins from one serve worker log: id, tenant, bucket,
    status, wall, and the per-request run log path."""
    rows = []
    for ev in _iter_events(path):
        if ev.get("event") != "request_end":
            continue
        bucket = ev.get("bucket") or {}
        rows.append({
            "request_id": ev.get("request_id"),
            "status": ev.get("status"),
            "tenant": ev.get("tenant"),
            "bucket": bucket.get("name") if isinstance(bucket, dict)
            else bucket,
            "wall_seconds": ev.get("wall_seconds"),
            "run_log": ev.get("run_log"),
        })
    return rows


def collect_spool(spool):
    """Everything the spool knows about cost: the worker session
    ledgers (worker_*.jsonl run_end meters) and one row per request
    (request_end facts + that request's own run-log meter)."""
    spool = pathlib.Path(spool)
    workers = []
    requests = []
    for wlog in sorted(spool.glob("worker_*.jsonl")):
        meter = meter_of_run(wlog)
        if meter:
            workers.append({"path": str(wlog), "meter": meter})
        requests.extend(_request_rows_of_worker_log(wlog))
    for row in requests:
        run_log = row.get("run_log")
        if not run_log:
            # refused/admission-failed requests never opened a run log
            rid = row.get("request_id")
            candidate = spool / "results" / str(rid) / "run.jsonl"
            run_log = str(candidate) if candidate.exists() else None
        if run_log and pathlib.Path(run_log).exists():
            row["meter"] = meter_of_run(run_log)
        else:
            row["meter"] = None
    return {"workers": workers, "requests": requests}


def _meter_like(doc):
    """Find a meter dict inside an arbitrary JSON document (a bench
    artifact arm, a manifest, a bare summary)."""
    if not isinstance(doc, dict):
        return None
    if "billed_device_seconds" in doc:
        return doc
    if isinstance(doc.get("meter"), dict):
        return doc["meter"]
    return None


def load_source(path):
    """Resolve one CLI operand into ``{meters, requests, label}``.

    Accepts a run log (.jsonl), a results directory (contains
    run.jsonl), a spool directory (worker_*.jsonl + results/), or a
    JSON document carrying a ``meter`` block (a durable-run manifest,
    a bench artifact arm).
    """
    p = pathlib.Path(path)
    if p.is_dir():
        if (p / "run.jsonl").exists() and not list(
                p.glob("worker_*.jsonl")):
            meter = meter_of_run(p / "run.jsonl")
            return {"label": p.name, "meters": [meter] if meter else [],
                    "requests": []}
        spooled = collect_spool(p)
        meters = [w["meter"] for w in spooled["workers"]]
        meters += [r["meter"] for r in spooled["requests"]
                   if r.get("meter")]
        return {"label": p.name, "meters": meters,
                "requests": spooled["requests"],
                "workers": spooled["workers"]}
    if str(p).endswith(".jsonl"):
        meter = meter_of_run(p)
        return {"label": p.name, "meters": [meter] if meter else [],
                "requests": _request_rows_of_worker_log(p)}
    with open(p) as fh:
        doc = json.load(fh)
    meter = _meter_like(doc)
    return {"label": p.name, "meters": [meter] if meter else [],
            "requests": []}


# ---------------------------------------------------------------------------
# rollups
# ---------------------------------------------------------------------------

def merge_meters(meters):
    """Sum meter summaries/rollup slots into one conserving rollup
    (billed/effective/waste/cell_iters/flops add; rates recompute)."""
    out = {"billed_device_seconds": 0.0,
           "effective_device_seconds": 0.0,
           "waste_seconds": {}, "cell_iters": 0.0, "flops": 0.0,
           "records": 0}
    for m in meters:
        if not m:
            continue
        out["billed_device_seconds"] += float(
            m.get("billed_device_seconds") or 0.0)
        out["effective_device_seconds"] += float(
            m.get("effective_device_seconds") or 0.0)
        for cat, sec in (m.get("waste_seconds") or {}).items():
            out["waste_seconds"][cat] = \
                out["waste_seconds"].get(cat, 0.0) + float(sec)
        out["cell_iters"] += float(m.get("cell_iters") or 0.0)
        out["flops"] += float(m.get("flops") or 0.0)
        out["records"] += int(m.get("records") or 0)
    billed = out["billed_device_seconds"]
    waste = sum(out["waste_seconds"].values())
    out["waste_frac"] = round(waste / billed, 6) if billed > 0 else 0.0
    if billed > 0:
        out["goodput_cell_iters_per_device_second"] = round(
            out["cell_iters"] / billed, 3)
    for key in ("billed_device_seconds", "effective_device_seconds",
                "cell_iters", "flops"):
        out[key] = round(out[key], 6)
    out["waste_seconds"] = {k: round(v, 6) for k, v
                            in sorted(out["waste_seconds"].items())}
    return out


def rollup_by(rows, key):
    """Group request rows by ``key`` (tenant/bucket) and merge their
    meters; rows without the key land under ``"-"``."""
    groups = {}
    for row in rows:
        label = row.get(key) or "-"
        groups.setdefault(label, []).append(row)
    out = {}
    for label, members in sorted(groups.items()):
        merged = merge_meters([r.get("meter") for r in members])
        merged["requests"] = len(members)
        out[label] = merged
    return out


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def _fmt(v, fmt="{:.2f}"):
    return "-" if v is None else fmt.format(v)


def render_waterfall(meter, title="Cost & efficiency"):
    """The efficiency waterfall of one meter rollup as markdown lines:
    billed device-seconds at the top, one row per waste category, the
    effective remainder, then goodput + the conservation check.
    Shared by ``pert_meter report`` and ``pert_report``."""
    lines = [f"## {title}", ""]
    if not meter:
        return lines + ["_no meter section (pre-v9 run log, or the "
                        "run metered nothing)_", ""]
    billed = float(meter.get("billed_device_seconds") or 0.0)
    effective = float(meter.get("effective_device_seconds") or 0.0)
    waste = meter.get("waste_seconds") or {}
    denom = billed or 1.0
    lines += ["| component | device-seconds | share | |",
              "|---|---:|---:|---|",
              f"| **billed** | {billed:.2f} | 100.0% | |"]
    for cat in WASTE_CATEGORIES:
        sec = float(waste.get(cat) or 0.0)
        if sec == 0.0:
            continue
        share = sec / denom
        bar = "#" * round(share * _BAR_WIDTH)
        lines.append(f"| waste: `{cat}` | {sec:.2f} | {share:.1%} "
                     f"| `{bar}` |")
    for cat in sorted(set(waste) - set(WASTE_CATEGORIES)):
        # forward-compat: categories this tool predates still render
        sec = float(waste.get(cat) or 0.0)
        lines.append(f"| waste: `{cat}` | {sec:.2f} "
                     f"| {sec / denom:.1%} | |")
    eff_bar = "#" * round((effective / denom) * _BAR_WIDTH)
    lines.append(f"| **effective** | {effective:.2f} "
                 f"| {effective / denom:.1%} | `{eff_bar}` |")
    lines.append("")
    goodput = meter.get("goodput_cell_iters_per_device_second")
    if goodput is not None:
        lines.append(f"- **goodput**: {goodput} cell-iterations per "
                     f"device-second ({_fmt(meter.get('cell_iters'), '{:.0f}')} "
                     f"cell-iters total)")
    if meter.get("flops"):
        lines.append(f"- **program FLOPs dispatched**: "
                     f"{meter['flops']:.3g}")
    gap = conservation_gap(meter)
    verdict = "OK" if gap <= CONSERVATION_TOL else "VIOLATED ⚠"
    lines.append(f"- **conservation** (billed = effective + Σwaste): "
                 f"{verdict} (gap {gap:.2e})")
    lines.append("")
    return lines


def _render_request_table(rows):
    if not rows:
        return []
    lines = ["## Per-request cost", "",
             "| request | tenant | bucket | status | billed dev-s | "
             "goodput | waste frac |",
             "|---|---|---|---|---:|---:|---:|"]
    for row in rows:
        m = row.get("meter") or {}
        lines.append(
            f"| {row.get('request_id')} | {row.get('tenant') or '-'} "
            f"| {row.get('bucket') or '-'} | {row.get('status')} "
            f"| {_fmt(m.get('billed_device_seconds'))} "
            f"| {_fmt(m.get('goodput_cell_iters_per_device_second'), '{:.3g}')} "
            f"| {_fmt(m.get('waste_frac'), '{:.1%}')} |")
    lines.append("")
    return lines


def _render_rollup_table(title, rollup, count_key="requests"):
    lines = [f"## {title}", ""]
    if not rollup:
        return lines + ["_nothing attributed_", ""]
    lines += [f"| label | {count_key} | billed dev-s | effective | "
              "waste frac | goodput |",
              "|---|---:|---:|---:|---:|---:|"]
    for label, m in rollup.items():
        lines.append(
            f"| `{label}` | {m.get(count_key, '-')} "
            f"| {_fmt(m.get('billed_device_seconds'))} "
            f"| {_fmt(m.get('effective_device_seconds'))} "
            f"| {_fmt(m.get('waste_frac'), '{:.1%}')} "
            f"| {_fmt(m.get('goodput_cell_iters_per_device_second'), '{:.3g}')} |")
    lines.append("")
    return lines


# ---------------------------------------------------------------------------
# verbs
# ---------------------------------------------------------------------------

def cmd_report(args):
    source = load_source(args.path)
    total = merge_meters(source["meters"])
    doc = {"source": str(args.path), "meter": total,
           "conservation_gap": conservation_gap(total),
           "conservation_ok":
               conservation_gap(total) <= CONSERVATION_TOL}
    if source.get("requests"):
        doc["requests"] = [
            {k: r.get(k) for k in ("request_id", "tenant", "bucket",
                                   "status", "wall_seconds")}
            | {"meter": r.get("meter")}
            for r in source["requests"]]
    if args.json:
        print(json.dumps(doc, indent=1))  # pertlint: disable=PL008
        return 0
    lines = [f"# PERT cost report — `{source['label']}`", ""]
    lines += render_waterfall(total)
    lines += _render_request_table(source.get("requests") or [])
    sys.stdout.write("\n".join(lines) + "\n")
    return _check_exit(args, [total])


def cmd_attribution(args):
    spooled = collect_spool(args.spool)
    request_rows = spooled["requests"]
    worker_meters = [w["meter"] for w in spooled["workers"]]
    request_meters = [r["meter"] for r in request_rows if r.get("meter")]
    total = merge_meters(worker_meters + request_meters)
    by_tenant = rollup_by(request_rows, "tenant")
    by_bucket = rollup_by(request_rows, "bucket")
    ledgers = [m for m in worker_meters + request_meters if m] + [total]
    gaps = [conservation_gap(m) for m in ledgers]
    doc = {
        "spool": str(args.spool),
        "workers": len(spooled["workers"]),
        "requests": len(request_rows),
        "meter": total,
        "by_tenant": by_tenant,
        "by_bucket": by_bucket,
        "conservation_gap_max": max(gaps, default=0.0),
        "conservation_ok": all(g <= CONSERVATION_TOL for g in gaps),
    }
    if args.json:
        print(json.dumps(doc, indent=1))  # pertlint: disable=PL008
        return 0 if (doc["conservation_ok"] or not args.check) else 1
    lines = [f"# PERT cost attribution — spool `{args.spool}`", "",
             f"- **workers**: {doc['workers']}, **requests**: "
             f"{doc['requests']}",
             f"- **conservation** (every ledger + the rollup): "
             f"{'OK' if doc['conservation_ok'] else 'VIOLATED ⚠'} "
             f"(max gap {doc['conservation_gap_max']:.2e})",
             ""]
    lines += render_waterfall(total, title="Fleet rollup")
    lines += _render_rollup_table("By tenant", by_tenant)
    lines += _render_rollup_table("By bucket", by_bucket)
    lines += _render_request_table(request_rows)
    sys.stdout.write("\n".join(lines) + "\n")
    return 0 if (doc["conservation_ok"] or not args.check) else 1


def _arm_doc(path):
    source = load_source(path)
    meter = merge_meters(source["meters"])
    n = len([r for r in source.get("requests") or []
             if r.get("status") == "ok"]) or None
    doc = {"source": str(path), "meter": meter, "requests_ok": n}
    billed = meter.get("billed_device_seconds") or 0.0
    if n:
        doc["device_seconds_per_request"] = round(billed / n, 6)
    return doc


def cmd_ab(args):
    a, b = _arm_doc(args.a), _arm_doc(args.b)
    ma, mb = a["meter"], b["meter"]

    def _ratio(x, y):
        if not isinstance(x, (int, float)) \
                or not isinstance(y, (int, float)) or not x:
            return None
        return round(y / x, 4)

    doc = {
        "a": a, "b": b,
        "deltas": {
            "billed_device_seconds_ratio": _ratio(
                ma.get("billed_device_seconds"),
                mb.get("billed_device_seconds")),
            "goodput_ratio": _ratio(
                ma.get("goodput_cell_iters_per_device_second"),
                mb.get("goodput_cell_iters_per_device_second")),
            "device_seconds_per_request_ratio": _ratio(
                a.get("device_seconds_per_request"),
                b.get("device_seconds_per_request")),
            "waste_frac_delta": round(
                (mb.get("waste_frac") or 0.0)
                - (ma.get("waste_frac") or 0.0), 6),
        },
    }
    if args.json:
        print(json.dumps(doc, indent=1))  # pertlint: disable=PL008
        return 0
    lines = [f"# PERT cost A/B — A=`{pathlib.Path(str(args.a)).name}` "
             f"vs B=`{pathlib.Path(str(args.b)).name}`", ""]
    rows = [
        ("billed device-seconds", "billed_device_seconds", "{:.2f}"),
        ("effective device-seconds", "effective_device_seconds",
         "{:.2f}"),
        ("waste frac", "waste_frac", "{:.1%}"),
        ("goodput (cell-iters / dev-s)",
         "goodput_cell_iters_per_device_second", "{:.3g}"),
    ]
    lines += ["| metric | A | B | B/A |", "|---|---:|---:|---:|"]
    for label, key, fmt in rows:
        va, vb = ma.get(key), mb.get(key)
        ratio = _ratio(va, vb) if isinstance(va, (int, float)) \
            and isinstance(vb, (int, float)) else None
        lines.append(f"| {label} | {_fmt(va, fmt)} | {_fmt(vb, fmt)} "
                     f"| {_fmt(ratio, '{:.2f}x')} |")
    pa = a.get("device_seconds_per_request")
    pb = b.get("device_seconds_per_request")
    if pa or pb:
        lines.append(f"| device-seconds per ok request "
                     f"| {_fmt(pa)} | {_fmt(pb)} "
                     f"| {_fmt(_ratio(pa, pb), '{:.2f}x')} |")
    lines += ["", "Waste mix (device-seconds):", "",
              "| category | A | B |", "|---|---:|---:|"]
    wa = ma.get("waste_seconds") or {}
    wb = mb.get("waste_seconds") or {}
    for cat in sorted(set(wa) | set(wb)):
        lines.append(f"| `{cat}` | {_fmt(wa.get(cat))} "
                     f"| {_fmt(wb.get(cat))} |")
    lines.append("")
    sys.stdout.write("\n".join(lines) + "\n")
    return 0


def _check_exit(args, meters):
    if not getattr(args, "check", False):
        return 0
    bad = [m for m in meters
           if m and conservation_gap(m) > CONSERVATION_TOL]
    return 1 if bad else 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Device-cost / goodput reporting over PERT meter "
                    "ledgers (run logs, serve spools, bench artifacts)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_report = sub.add_parser(
        "report", help="efficiency waterfall of one run / results dir "
                       "/ spool: billed -> waste -> effective, goodput")
    p_report.add_argument("path")
    p_report.add_argument("--json", action="store_true")
    p_report.add_argument("--check", action="store_true",
                          help="exit 1 if conservation is violated")

    p_attr = sub.add_parser(
        "attribution", help="per-tenant / per-bucket device-time "
                            "rollup over a serve spool, with the "
                            "conservation invariant checked on every "
                            "ledger")
    p_attr.add_argument("spool")
    p_attr.add_argument("--json", action="store_true")
    p_attr.add_argument("--check", action="store_true",
                        help="exit 1 if any ledger (or the rollup) "
                             "violates conservation")

    p_ab = sub.add_parser(
        "ab", help="two-arm cost comparison: device-seconds per "
                   "request, goodput, waste mix")
    p_ab.add_argument("a")
    p_ab.add_argument("b")
    p_ab.add_argument("--json", action="store_true")

    args = ap.parse_args(argv)
    if args.cmd == "report":
        return cmd_report(args)
    if args.cmd == "attribution":
        return cmd_attribution(args)
    return cmd_ab(args)


if __name__ == "__main__":
    sys.exit(main())
