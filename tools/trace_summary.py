"""Summarise jax.profiler traces into a small committable text report.

``PertConfig(profile_dir=...)`` / ``full_pipeline_bench.py
--profile-dir`` write one TensorBoard/Perfetto trace per SVI-step fit;
the raw dumps are tens of MB, so artifacts commit this summary instead
(e.g. artifacts/PROFILE_r05_cpu_summary.txt):

    python tools/trace_summary.py <profile_dir> [--top 12] [--out FILE]

For each ``plugins/profile/<run>/*.trace.json.gz`` (or uncompressed
``*.trace.json`` — some jax versions/backends skip the gzip) the report
lists the top ops by total self-duration, with the profiler's own
bookkeeping frames (wrapper/asarray/fit_map wrappers) filtered out so
the XLA fusions the device actually ran lead the list.

When the traced programs carry ``jax.named_scope`` annotations (the hot
fit/decode/QC regions are wrapped in ``pert/<phase>`` scopes —
``infer/svi.py``, ``models/pert.py``), the report additionally groups
total time by pipeline-phase scope, answering "how much device time
went to the fit step vs the decode vs the QC pass" without reading
op-by-op output.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys

_SKIP = ("wrapper", "np.asarray", "_value", "__int__",
         "wait for completion", "fit_map", "reraise_with_filtered",
         "cache_miss", "_run_python_pjit", "pjit_call_impl",
         "compile_or_get_cached", "_cached_compilation", "from_hlo",
         "_compile_and_write_cache", "backend_compile")

# a named_scope label as it appears embedded in XLA op names / trace
# metadata: the scope prefix up to (not including) the next '/'.  Nested
# scopes concatenate ("pert/decode/pert/qc_entropy/..."), and matching
# code keys on the FULL scope path (every pert/* segment joined in
# order): keying on the innermost leaf alone silently merged same-leaf
# scopes under different parents (two pert/fetch regions inside
# different decode scopes became one row).
_SCOPE_RE = re.compile(r"pert/[A-Za-z0-9_.:-]+")


def _trace_files(profile_dir: str) -> list:
    """Every trace dump under the jax.profiler layout, gz or plain.

    The same dump may exist in both forms (e.g. after ``gunzip -k`` for
    manual inspection): keep the gz and drop its plain twin so the run
    is not summarised — and its totals double-counted — twice.
    """
    found = set()
    for pattern in ("*.trace.json.gz", "*.trace.json"):
        found.update(glob.glob(os.path.join(
            profile_dir, "plugins", "profile", "*", pattern)))
    for path in list(found):
        if path.endswith(".gz"):
            found.discard(path[:-3])
    return sorted(found)


def _load_trace(path: str) -> dict:
    if path.endswith(".gz"):
        with gzip.open(path) as fh:
            return json.load(fh)
    with open(path) as fh:
        return json.load(fh)


def _event_scope(event: dict):
    """The FULL ``pert/*`` named-scope path an event belongs to, or
    None.

    The scope string may land in the event name itself or in the args
    metadata (XLA attaches it as op metadata ``name``/``long_name``
    depending on backend/version) — scan both.  Nested scopes
    ("pert/decode/pert/qc_entropy/mul") key as the whole path
    ("pert/decode/pert/qc_entropy"): taking only the innermost leaf
    merged same-leaf scopes under DIFFERENT parents into one row,
    silently — the full path keeps them distinct while a reader can
    still aggregate by suffix.
    """
    matches = _SCOPE_RE.findall(event.get("name", ""))
    if matches:
        return "/".join(matches)
    args = event.get("args")
    if isinstance(args, dict):
        for value in args.values():
            if isinstance(value, str):
                matches = _SCOPE_RE.findall(value)
                if matches:
                    return "/".join(matches)
    return None


def scope_totals(profile_dir: str) -> dict:
    """Total device time per FULL ``pert/*`` named-scope path, in
    SECONDS, summed across every trace dump (gz or plain) under
    ``profile_dir``.  Keys are the whole scope path (nested scopes stay
    distinct under different parents — see :func:`_event_scope`).

    The machine-readable twin of the report's "named_scope groups"
    section — ``scdna_replication_tools_tpu.api`` feeds these into the
    run's metrics registry as ``pert_xla_scope_seconds`` gauges, so XLA
    scope time appears in the ``metrics_snapshot`` events and the
    Prometheus textfile.  Returns {} (never raises) when the directory
    holds no readable traces — absent gauges are the degradation
    contract.
    """
    totals: collections.Counter = collections.Counter()
    for path in _trace_files(profile_dir):
        try:
            data = _load_trace(path)
        except (OSError, ValueError):
            continue
        for event in data.get("traceEvents", []):
            if event.get("ph") != "X":
                continue
            scope = _event_scope(event)
            if scope:
                totals[scope] += event.get("dur", 0)
    return {scope: dur / 1e6 for scope, dur in totals.items()}


def summarise(profile_dir: str, top: int = 12) -> str:
    lines = [f"# jax.profiler trace summary for {profile_dir}",
             "# top ops by total self-duration per captured trace "
             "(bookkeeping frames filtered)", ""]
    traces = _trace_files(profile_dir)
    if not traces:
        raise SystemExit(
            f"trace_summary: no *.trace.json or *.trace.json.gz traces "
            f"under {profile_dir} — expected the jax.profiler layout "
            f"{profile_dir}/plugins/profile/<run>/<host>.trace.json(.gz); "
            f"write traces with PertConfig(profile_dir=...) or "
            f"full_pipeline_bench.py --profile-dir")
    for path in traces:
        data = _load_trace(path)
        events = [e for e in data.get("traceEvents", [])
                  if e.get("ph") == "X"]
        total = collections.Counter()
        scopes = collections.Counter()
        for e in events:
            total[e.get("name", "?")] += e.get("dur", 0)
            scope = _event_scope(e)
            if scope:
                scopes[scope] += e.get("dur", 0)
        lines.append(f"== {path.split(os.sep)[-2]}  ({len(events)} events)")
        shown = 0
        for name, dur in total.most_common(200):
            if any(s in name for s in _SKIP):
                continue
            lines.append(f"   {dur / 1e6:10.2f}s  {name[:100]}")
            shown += 1
            if shown >= top:
                break
        if scopes:
            lines.append("   -- named_scope groups (time by pipeline "
                         "phase) --")
            for scope, dur in scopes.most_common():
                lines.append(f"   {dur / 1e6:10.2f}s  {scope}")
        lines.append("")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("profile_dir")
    ap.add_argument("--top", type=int, default=12)
    ap.add_argument("--out", default=None)
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable scope totals "
                         "(full scope path -> device seconds) instead "
                         "of the text report — the scripting twin of "
                         "scope_totals(), e.g. for pert_trace's "
                         "counter track or an external dashboard")
    args = ap.parse_args(argv)
    if args.json:
        if not _trace_files(args.profile_dir):
            raise SystemExit(
                f"trace_summary: no *.trace.json(.gz) traces under "
                f"{args.profile_dir} — expected the jax.profiler "
                f"layout; write traces with PertConfig(profile_dir=...)")
        report = json.dumps({
            "profile_dir": str(args.profile_dir),
            "scope_seconds": {k: round(v, 6) for k, v in
                              sorted(scope_totals(
                                  args.profile_dir).items())},
        }, indent=1)
    else:
        report = summarise(args.profile_dir, args.top)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(report + "\n")
    else:
        sys.stdout.write(report + "\n")


if __name__ == "__main__":
    main()
