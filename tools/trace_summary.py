"""Summarise jax.profiler traces into a small committable text report.

``PertConfig(profile_dir=...)`` / ``full_pipeline_bench.py
--profile-dir`` write one TensorBoard/Perfetto trace per SVI-step fit;
the raw dumps are tens of MB, so artifacts commit this summary instead
(e.g. artifacts/PROFILE_r05_cpu_summary.txt):

    python tools/trace_summary.py <profile_dir> [--top 12] [--out FILE]

For each ``plugins/profile/<run>/*.trace.json.gz`` the report lists the
top ops by total self-duration, with the profiler's own bookkeeping
frames (wrapper/asarray/fit_map wrappers) filtered out so the XLA
fusions the device actually ran lead the list.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys

_SKIP = ("wrapper", "np.asarray", "_value", "__int__",
         "wait for completion", "fit_map", "reraise_with_filtered",
         "cache_miss", "_run_python_pjit", "pjit_call_impl",
         "compile_or_get_cached", "_cached_compilation", "from_hlo",
         "_compile_and_write_cache", "backend_compile")


def summarise(profile_dir: str, top: int = 12) -> str:
    lines = [f"# jax.profiler trace summary for {profile_dir}",
             "# top ops by total self-duration per captured trace "
             "(bookkeeping frames filtered)", ""]
    traces = sorted(glob.glob(os.path.join(
        profile_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not traces:
        raise SystemExit(f"no *.trace.json.gz under {profile_dir}")
    for path in traces:
        with gzip.open(path) as fh:
            data = json.load(fh)
        events = [e for e in data.get("traceEvents", [])
                  if e.get("ph") == "X"]
        total = collections.Counter()
        for e in events:
            total[e.get("name", "?")] += e.get("dur", 0)
        lines.append(f"== {path.split(os.sep)[-2]}  ({len(events)} events)")
        shown = 0
        for name, dur in total.most_common(200):
            if any(s in name for s in _SKIP):
                continue
            lines.append(f"   {dur / 1e6:10.2f}s  {name[:100]}")
            shown += 1
            if shown >= top:
                break
        lines.append("")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("profile_dir")
    ap.add_argument("--top", type=int, default=12)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    report = summarise(args.profile_dir, args.top)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(report + "\n")
    else:
        sys.stdout.write(report + "\n")


if __name__ == "__main__":
    main()
