"""Full-pipeline genome-scale benchmark: ``scRT.infer('pert')`` wall-clock.

``bench.py`` times only the steady-state step-2 SVI iteration; THIS tool
measures the north-star metric of BASELINE.md configs 3-4 — the complete
user-facing pipeline at genome scale (default 1,000 S + 250 G1 cells x
~5.4k hg19 500kb bins from ``data/example_bins.py``), on the accelerator,
INCLUDING compile time, prior construction, ``guess_times``, host pivots,
all three SVI steps, decode and pandas packaging.  The reference's own
scaling guidance for this regime: ``/root/reference/README.md:55-57``.

Writes one JSON artifact (--out) with per-phase wall-clock, per-step
iteration counts/losses, throughput, and (optionally, --profile-dir) a
``jax.profiler`` trace of the step-2 fit for roofline analysis.

Synthetic workload: 2 clones with multi-chromosome CNAs, NB reads drawn
from the PERT generative model (GC bias + replication structure), so the
run exercises realistic priors, masking and decode — not the flat etas of
bench.py.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

import numpy as np
import pandas as pd

# make the repo-root package importable when invoked as a script, without
# requiring PYTHONPATH (which can shadow the environment's sitecustomize
# and break ambient accelerator-backend registration)
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def force_cpu_backend():
    """Force the CPU jax backend before first device access.

    The ambient tunneled-TPU backend hangs ~30 min before erroring when
    the tunnel is down; jax is pre-imported by sitecustomize, so the env
    var alone cannot do this — the live config must be updated too.
    Shared by the bench tools (accuracy_sweep imports it from here).
    """
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def make_genome_workload(num_s_cells, num_g1_cells, bin_size=500_000,
                         seed=0):
    """Long-form S/G1 frames over the genome-wide example bin table.

    Reads are drawn directly from the PERT observation model (NumPy, fast
    at 10k-cell scale): per-cell tau, per-bin replication state from the
    RT profile, NB(delta, lamb) reads with a GC polynomial rate.
    """
    from scdna_replication_tools_tpu.data.example_bins import (
        make_example_bins,
    )

    rng = np.random.default_rng(seed)
    bins = make_example_bins(bin_size=bin_size, seed=seed)
    num_loci = len(bins)
    gc = bins["gc"].to_numpy()
    rt = bins["mcf7rt"].to_numpy()

    # two clones with CNAs on different chromosomes
    cn_a = np.full(num_loci, 2.0)
    cn_b = np.full(num_loci, 2.0)
    chr_arr = bins["chr"].to_numpy()
    c1 = np.flatnonzero(chr_arr == "1")
    c2 = np.flatnonzero(chr_arr == "2")
    c5 = np.flatnonzero(chr_arr == "5")
    cn_a[c1[: len(c1) // 3]] = 3.0
    cn_a[c5[: len(c5) // 4]] = 1.0
    cn_b[c2[: len(c2) // 3]] = 4.0

    lamb, a_true = 0.75, 10.0
    gc_rate = np.exp(0.5 * gc)          # betas=[0.5, 0.0]

    def draw(prefix, n, s_phase):
        clones = np.where(rng.random(n) < 0.5, "A", "B")
        cell_ids = [f"{prefix}_{clones[i]}_{i}" for i in range(n)]
        cn = np.where((clones == "A")[:, None], cn_a[None, :], cn_b[None, :])
        if s_phase:
            tau = rng.uniform(0.05, 0.95, n)
            phi = 1.0 / (1.0 + np.exp(-a_true * (tau[:, None] - (1.0 - rt)[None, :])))
            rep = (rng.random((n, num_loci)) < phi).astype(np.float32)
        else:
            tau = np.zeros(n)
            rep = np.zeros((n, num_loci), np.float32)
        chi = cn * (1.0 + rep)
        u = rng.uniform(8.0, 14.0, n)
        theta = u[:, None] * chi * gc_rate[None, :]
        delta = np.maximum(theta * (1 - lamb) / lamb, 1.0)
        reads = rng.negative_binomial(delta, 1.0 - lamb).astype(np.float64)

        frames = []
        for i in range(n):
            frames.append(pd.DataFrame({
                "cell_id": cell_ids[i], "chr": chr_arr,
                "start": bins["start"], "end": bins["end"], "gc": gc,
                "mcf7rt": rt, "library_id": "LIB0", "clone_id": clones[i],
                "reads": reads[i], "state": cn[i].astype(int),
                "copy": cn[i],
            }))
        df = pd.concat(frames, ignore_index=True)
        truth = pd.DataFrame({"cell_id": cell_ids, "true_t": tau})
        return df, truth

    df_s, truth_s = draw("s", num_s_cells, True)
    df_g, _ = draw("g", num_g1_cells, False)
    return df_s, df_g, truth_s


def run(args):
    import jax

    from scdna_replication_tools_tpu.api import scRT

    t0 = time.perf_counter()
    df_s, df_g, truth_s = make_genome_workload(args.cells, args.g1_cells,
                                               bin_size=args.bin_size,
                                               seed=args.seed)
    t_data = time.perf_counter() - t0
    num_loci = df_s.groupby(["chr", "start"]).ngroups

    scrt = scRT(df_s, df_g, input_col="reads", clone_col="clone_id",
                assign_col="copy", cn_prior_method=args.cn_prior_method,
                max_iter=args.max_iter, min_iter=args.min_iter,
                run_step3=args.run_step3, enum_impl=args.enum_impl,
                num_shards=args.num_shards, loci_shards=args.loci_shards,
                cell_chunk=args.cell_chunk,
                mirror_rescue=args.mirror_rescue,
                compile_cache_dir=args.compile_cache,
                telemetry_path=args.telemetry,
                metrics_textfile=args.metrics_textfile,
                checkpoint_dir=args.checkpoint_dir, resume=args.resume,
                watchdog_compile_seconds=args.watchdog_compile,
                watchdog_chunk_seconds=args.watchdog_chunk,
                elastic_mesh=args.elastic_mesh)
    if args.profile_dir:
        import dataclasses
        scrt.config = dataclasses.replace(scrt.config,
                                          profile_dir=args.profile_dir)

    t1 = time.perf_counter()
    cn_s_out, supp_s, cn_g1_out, supp_g1 = scrt.infer(level="pert")
    t_infer = time.perf_counter() - t1

    # per-step evidence from the supplementary table + runner step walls
    loss_s = supp_s.query("param == 'loss_s'")["value"].to_numpy()
    loss_g = supp_g1.query("param == 'loss_s'")["value"].to_numpy() \
        if supp_g1 is not None and len(supp_g1) else np.array([])

    # tau recovery against the generative truth (sanity that the run is
    # a real fit, not a degenerate one)
    per_cell = cn_s_out.groupby("cell_id").agg(tau=("model_tau", "first"))
    merged = per_cell.join(truth_s.set_index("cell_id"))
    tau_corr = float(np.corrcoef(merged["tau"], merged["true_t"])[0, 1])

    # phase ledger: where the wall actually went (trace/compile vs fit vs
    # host orchestration), plus its coverage of the measured wall — the
    # phase-schema CI smoke pins this surface.  The mirror-rescue phase
    # is device-fit-dominated (its sub-fit runs up to mirror_max_iter
    # iterations), so it counts as fit time: leaving it in non_fit would
    # make rescue-on runs apples-to-oranges against the no-rescue
    # baseline the non-fit regression gate compares to
    phases = dict(scrt.phase_report or {})
    accounted = phases.get("total_accounted", 0.0)
    non_fit = accounted - sum(
        v for k, v in phases.items()
        if k.endswith("/fit") or k.endswith("/rescue"))

    # telemetry roll-up: the run's own JSONL is the source of the memory
    # high-water and the AOT program-cache hit/miss counts (compile
    # events carry cost_analysis/memory_analysis per program); a
    # disabled run log leaves the fields null
    run_summary = None
    fleet_metrics = None
    if scrt.run_log_path:
        from scdna_replication_tools_tpu.obs.summary import (
            flat_metrics,
            summarize_run,
        )

        run_summary = summarize_run(scrt.run_log_path)
        if run_summary is not None:
            # the same flat per-run metric vector the fleet index
            # (tools/pert_fleet.py) extracts — in the artifact itself,
            # so a committed bench JSON is regression-comparable even
            # without its run log
            fleet_metrics = flat_metrics(run_summary)
    compile_info = (run_summary or {}).get("compile") or {}

    dev = jax.devices()[0]
    out = {
        "metric": "pert_full_pipeline_wall_seconds",
        "value": round(t_infer, 2),
        "phases": phases,
        "phase_coverage_of_wall": round(accounted / max(t_infer, 1e-9), 4),
        "non_fit_wall_seconds": round(non_fit, 2),
        "compile_cache": args.compile_cache,
        "run_log": scrt.run_log_path,
        "metrics_textfile": args.metrics_textfile,
        "fleet_metrics": fleet_metrics,
        # the cost plane (schema v9 run_end.meter): attributed
        # device-seconds, the waste decomposition, and goodput in
        # cell-iterations per device-second — render with
        # `python -m tools.pert_meter report <run_log>`
        "meter": (run_summary or {}).get("meter"),
        "peak_hbm_bytes": compile_info.get("peak_bytes_max"),
        "compile_cache_hits": compile_info.get("cache_hits"),
        "compile_cache_misses": compile_info.get("cache_misses"),
        "unit": f"seconds ({args.cells} S + {args.g1_cells} G1 cells x "
                f"{num_loci} bins, {args.cn_prior_method}, "
                f"max_iter={args.max_iter}, incl. compile + priors + "
                f"decode + packaging)",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "enum_impl": args.enum_impl,
        "data_gen_seconds": round(t_data, 2),
        "cells_per_second_end_to_end": round(args.cells / t_infer, 2),
        "step2_iters": int(len(loss_s)),
        "step2_final_loss": float(loss_s[-1]) if len(loss_s) else None,
        "step2_loss_decreased": bool(len(loss_s)
                                     and loss_s[-1] < loss_s[0]),
        "step3_iters": int(len(loss_g)),
        "tau_truth_correlation": round(tau_corr, 4),
        "run_step3": bool(args.run_step3),
        "bin_size": args.bin_size,
        "num_shards": args.num_shards,
        "loci_shards": args.loci_shards,
        "cell_chunk": args.cell_chunk,
        "profile_dir": args.profile_dir,
        "mirror_rescue": bool(args.mirror_rescue),
        "mirror_rescue_stats": getattr(scrt, "mirror_rescue_stats", None),
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1)
    return out


def _ensure_devices(n):
    """A CPU host has one device; a sharded run needs n virtual ones.
    Must land before the backend initialises (jax may already be
    imported by sitecustomize — the env var still works until the first
    device access).  Host-platform-only flag: harmless on TPU."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells", type=int, default=1000,
                    help="S-phase cells (BASELINE.md config 3 scale)")
    ap.add_argument("--g1-cells", type=int, default=250)
    ap.add_argument("--bin-size", type=int, default=500_000,
                    help="genome bin size; 20000 reproduces the "
                         "reference's long-genome pain point "
                         "(154,770 loci over the hg19 autosome table, "
                         "README.md:55-57)")
    ap.add_argument("--num-shards", type=int, default=1)
    ap.add_argument("--loci-shards", type=int, default=1,
                    help="2-D (cells x loci) mesh for the long-genome "
                         "regime; total devices = num_shards * loci_shards")
    ap.add_argument("--cell-chunk", type=int, default=None,
                    help="cells per lax.scan chunk inside the loss "
                         "(PertConfig.cell_chunk) — HBM fallback for "
                         "10k-cell single-chip runs")
    ap.add_argument("--max-iter", type=int, default=800)
    ap.add_argument("--min-iter", type=int, default=100)
    ap.add_argument("--cn-prior-method", default="g1_clones")
    ap.add_argument("--enum-impl", default="auto")
    ap.add_argument("--run-step3", action="store_true")
    ap.add_argument("--mirror-rescue", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="post-step-2 mirror-basin rescue for "
                         "boundary-tau cells (default ON, matching "
                         "PertConfig.mirror_rescue; --no-mirror-rescue "
                         "times the reference-faithful trajectory)")
    ap.add_argument("--compile-cache", default="auto",
                    help="persistent XLA compilation cache dir: 'auto' "
                         "(repo-local .jax_cache), a path, or 'none' — "
                         "cold-vs-warm pairs of this flag measure the "
                         "compile-cache win (PertConfig.compile_cache_dir)")
    ap.add_argument("--telemetry", default="auto",
                    help="structured JSONL run log: 'auto' (repo-local "
                         ".pert_runs/), a file/dir path, or 'none' "
                         "(PertConfig.telemetry_path); its path lands in "
                         "the JSON as run_log and feeds peak_hbm_bytes + "
                         "compile-cache hit/miss counts — render with "
                         "tools/pert_report.py")
    ap.add_argument("--metrics-textfile", default=None,
                    help="Prometheus text-exposition export of the "
                         "run's metrics registry, rewritten atomically "
                         "at every phase boundary "
                         "(PertConfig.metrics_textfile); the "
                         "metrics_snapshot events in --telemetry and "
                         "the fleet index work without it")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="durable step + in-fit checkpoints (and the "
                         "resume manifest); with --resume auto a killed "
                         "battery stage continues instead of restarting")
    ap.add_argument("--resume", default="auto",
                    choices=["auto", "force", "off"],
                    help="resume policy against --checkpoint-dir "
                         "(PertConfig.resume)")
    ap.add_argument("--watchdog-compile", type=float, default=None,
                    help="compile deadline in seconds: converts a hung "
                         "compile (dead tunnel) into a typed, resumable "
                         "abort (PertConfig.watchdog_compile_seconds)")
    ap.add_argument("--watchdog-chunk", type=float, default=None,
                    help="fit-chunk deadline in seconds "
                         "(PertConfig.watchdog_chunk_seconds)")
    ap.add_argument("--elastic-mesh",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="elastic mesh-shrink recovery rung: on "
                         "host/device loss or OOM in a sharded fit, "
                         "rebuild a smaller mesh and continue from the "
                         "last checkpoint (PertConfig.elastic_mesh)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--profile-dir", default=None)
    ap.add_argument("--out", default=None)
    ap.add_argument("--platform", default="ambient",
                    choices=["ambient", "cpu"],
                    help="'cpu' forces the CPU backend (the ambient "
                         "tunneled-TPU backend hangs ~30 min before "
                         "erroring when the tunnel is down; jax is "
                         "pre-imported by sitecustomize, so the env var "
                         "alone cannot do this)")
    args = ap.parse_args(argv)
    if args.platform == "cpu":
        force_cpu_backend()
    needed = args.num_shards * args.loci_shards
    if needed > 1:
        _ensure_devices(needed)
    run(args)


if __name__ == "__main__":
    main()
