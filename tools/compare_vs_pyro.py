"""Head-to-head: JAX framework vs the actual Pyro reference.

Run where BOTH packages are importable (pyro-ppl is not installable in
the build image — the in-repo anchor is tests/test_reference_oracle.py's
torch.distributions transcription; this script is the full-fidelity
check for CI/dev machines with network access):

    pip install pyro-ppl==1.8.2 "torch>=1.12"
    pip install git+https://github.com/shahcompbio/scdna_replication_tools
    python tools/compare_vs_pyro.py --max-iter 300 --out pyro_compare.json

It simulates one chr1-scale workload (2 clones, one CNA) with the JAX
simulator, fits BOTH implementations on the identical long-form input
(cn_prior_method='g1_clones', the reference tutorial's configuration),
and reports:

* final step-2 loss of each (matched-ELBO check, reference:
  pert_model.py:792-816 vs infer/runner.py);
* cn/rep decode agreement between the two, and each vs simulator truth;
* per-cell tau correlation between the two.

The JSON it writes is suitable for checking in as a recorded fixture.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

import numpy as np
import pandas as pd

# make the repo-root package importable when invoked as a script, without
# requiring PYTHONPATH (which can shadow the environment's sitecustomize
# and break ambient accelerator-backend registration)
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def make_workload(num_cells=40, num_loci=150, seed=11):
    """Long-form S + G1 frames with simulated NB reads (JAX simulator)."""
    from scdna_replication_tools_tpu.models.simulator import pert_simulator

    rng = np.random.default_rng(seed)
    starts = (np.arange(num_loci) * 500_000).astype(np.int64)
    gc = np.clip(0.45 + 0.08 * np.sin(np.arange(num_loci) / 9.0)
                 + rng.normal(0, 0.02, num_loci), 0.3, 0.65)
    rt = 0.5 + 0.45 * np.sin(np.arange(num_loci) / 15.0 + 1.0)

    def cells(prefix, n, clone, cn_profile):
        out = []
        for i in range(n):
            out.append(pd.DataFrame({
                "cell_id": f"{prefix}_{clone}_{i}", "chr": "1",
                "start": starts, "end": starts + 500_000, "gc": gc,
                "mcf7rt": rt, "library_id": "LIB0", "clone_id": clone,
                "true_somatic_cn": cn_profile}))
        return out

    cn_a = np.full(num_loci, 2.0)
    cn_a[:40] = 3.0
    cn_b = np.full(num_loci, 2.0)
    half = num_cells // 2
    cn_s = pd.concat(cells("s", half, "A", cn_a) + cells("s", half, "B", cn_b),
                     ignore_index=True)
    cn_g = pd.concat(cells("g", half, "A", cn_a) + cells("g", half, "B", cn_b),
                     ignore_index=True)
    cn_s, cn_g = pert_simulator(
        cn_s, cn_g, num_reads=50_000, rt_cols=["mcf7rt", "mcf7rt"],
        clones=["A", "B"], lamb=0.75, betas=[0.5, 0.0], a=10.0,
        gc_col="gc", input_cn_col="true_somatic_cn")
    for df in (cn_s, cn_g):
        df["reads"] = df["true_reads_norm"]
        df["state"] = df["true_somatic_cn"].astype(int)
        df["copy"] = df["true_somatic_cn"]
    return cn_s, cn_g


def fit_jax(cn_s, cn_g, max_iter):
    from scdna_replication_tools_tpu.api import scRT

    # mirror_rescue off: this tool's single job is a like-for-like
    # trajectory against the reference, which has no rescue mechanism
    # (the rescue is strictly objective-improving, so leaving it on
    # would bias our side of the final-loss comparison favourably)
    scrt = scRT(cn_s.copy(), cn_g.copy(), input_col="reads",
                clone_col="clone_id", assign_col="copy", rt_prior_col=None,
                cn_state_col="state", gc_col="gc",
                cn_prior_method="g1_clones", max_iter=max_iter,
                mirror_rescue=False)
    out_s, supp_s, out_g, supp_g = scrt.infer(level="pert")
    loss = supp_s.loc[supp_s["param"] == "loss_s", "value"].astype(float)
    return out_s, float(loss.iloc[-1])


def fit_pyro(cn_s, cn_g, max_iter):
    from scdna_replication_tools.infer_scRT import scRT

    scrt = scRT(cn_s.copy(), cn_g.copy(), input_col="reads",
                clone_col="clone_id", assign_col="copy", rt_prior_col=None,
                cn_state_col="state", gc_col="gc",
                cn_prior_method="g1_clones", max_iter=max_iter)
    out_s, supp_s, out_g, supp_g = scrt.infer(level="pert")
    loss = supp_s.loc[supp_s["param"] == "loss_s", "value"].astype(float) \
        if "param" in supp_s.columns else \
        supp_s["loss_s"].dropna().astype(float)
    return out_s, float(loss.iloc[-1])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-iter", type=int, default=300)
    ap.add_argument("--cells", type=int, default=40)
    ap.add_argument("--loci", type=int, default=150)
    ap.add_argument("--out", default="pyro_compare.json")
    args = ap.parse_args()

    try:
        import scdna_replication_tools  # noqa: F401
        import pyro  # noqa: F401
    except ImportError as exc:
        print(f"SKIP: reference/pyro not importable ({exc}); install "
              "pyro-ppl and shahcompbio/scdna_replication_tools first",
              file=sys.stderr)
        sys.exit(0)

    cn_s, cn_g = make_workload(args.cells, args.loci)

    jax_out, jax_loss = fit_jax(cn_s, cn_g, args.max_iter)
    ref_out, ref_loss = fit_pyro(cn_s, cn_g, args.max_iter)

    key = ["cell_id", "chr", "start"]
    merged = jax_out.merge(
        ref_out[key + ["model_rep_state", "model_cn_state", "model_tau"]],
        on=key, suffixes=("", "_ref"))

    tau = merged.groupby("cell_id").agg(
        a=("model_tau", "first"), b=("model_tau_ref", "first"))
    report = {
        "workload": {"cells": args.cells, "loci": args.loci,
                     "max_iter": args.max_iter},
        "jax_final_loss_s": jax_loss,
        "pyro_final_loss_s": ref_loss,
        "rep_agreement": float(
            (merged.model_rep_state == merged.model_rep_state_ref).mean()),
        "cn_agreement": float(
            (merged.model_cn_state == merged.model_cn_state_ref).mean()),
        "tau_correlation": float(np.corrcoef(tau.a, tau.b)[0, 1]),
        "jax_rep_acc_vs_truth": float(
            (merged.model_rep_state == merged.true_rep).mean()),
        "pyro_rep_acc_vs_truth": float(
            (merged.model_rep_state_ref == merged.true_rep).mean()),
    }
    print(json.dumps(report, indent=2))
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2)


if __name__ == "__main__":
    main()
