"""Chaos smoke: inject a mid-fit preemption, resume, assert parity.

The CI face of the durable-runs layer (ISSUE 9): runs the smoke-shaped
simulated pipeline three times —

1. **golden** — uninterrupted, no checkpointing;
2. **killed** — same workload with ``--faults preempt@step2/chunk#N``
   and periodic in-fit checkpointing; dies mid-step-2 by design;
3. **resumed** — ``resume='auto'`` against the killed run's
   checkpoint directory; must continue the step-2 fit mid-budget.

Asserts (exit 1 on any failure):

* the resumed run's final per-cell ``model_tau`` matches the golden
  run's bit-exactly;
* the resumed RunLog validates against schema v4 and carries the
  ``resume`` trail; the killed RunLog carries ``fault_injected`` and a
  ``run_end`` with status ``error``;
* the rendered report's "Resilience" section is non-placeholder.

Writes the resumed run's rendered markdown report (the "Resilience"
section CI uploads) to ``--report``.

``--multiprocess`` runs the topology-portable variant instead (ISSUE
13): the killed run is TWO ``jax.distributed``-initialised
subprocesses on CPU (gloo collectives, one forced host device each)
fitting on a 2-device cells mesh with process-scoped fault
``preempt@step2/chunk#2@proc1`` — host 1 dies mid-fit, host 0 loses
its peer; the last two-phase-committed sharded checkpoint generation
survives.  The resumed run is a SINGLE process on a 1-device mesh:
``--resume auto`` must reassemble the per-host shard files through the
commit pointer, re-place them on the shrunk topology (a ``resume``
event with ``resharded: true``), and land within parity tolerance of
the uninterrupted golden tau (cross-topology resumes are parity-gated,
not bit-exact — the reduction geometry changed).

Usage::

    python tools/chaos_smoke.py --out chaos_smoke.json \
        --report chaos_resilience.md
    python tools/chaos_smoke.py --multiprocess \
        --out chaos_mp.json --report chaos_mp_resilience.md
"""

from __future__ import annotations

import argparse
import json
import pathlib
import shutil
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from tools.full_pipeline_bench import (  # noqa: E402
    force_cpu_backend,
    make_genome_workload,
)


def _infer(df_s, df_g, telemetry, **extra):
    import numpy as np

    from scdna_replication_tools_tpu.api import scRT

    scrt = scRT(df_s.copy(), df_g.copy(), input_col="reads",
                clone_col="clone_id", assign_col="copy",
                cn_prior_method="g1_clones", max_iter=100, min_iter=25,
                rel_tol=0.0, run_step3=False, telemetry_path=telemetry,
                **extra)
    cn_s_out, _, _, _ = scrt.infer(level="pert")
    tau = cn_s_out.groupby("cell_id").agg(
        tau=("model_tau", "first")).sort_index()["tau"].to_numpy()
    return np.asarray(tau), scrt


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _mp_worker(args) -> int:
    """One host of the 2-process killed run (spawned by
    ``--multiprocess``; the parent sets JAX_PLATFORMS=cpu and forces
    one host CPU device per process via XLA_FLAGS before exec).

    Exit codes: 3 = died by the injected preemption (expected for
    proc 1), 4 = died collaterally (expected for proc 0 — its peer is
    gone, so the next collective/barrier fails), 0 = finished (a
    scenario bug: someone should have died)."""
    from scdna_replication_tools_tpu.parallel.distributed import (
        init_distributed,
    )
    from scdna_replication_tools_tpu.utils import faults as faults_mod

    init_distributed(coordinator_address=args.coordinator,
                     num_processes=2, process_id=args.mp_worker)
    work = pathlib.Path(args.workdir)
    df_s, df_g, _ = make_genome_workload(args.cells, args.g1_cells,
                                         bin_size=args.bin_size, seed=0)
    try:
        _infer(df_s, df_g,
               str(work / f"killed.p{args.mp_worker}.jsonl"),
               checkpoint_dir=str(work / "ck"), checkpoint_every=1,
               num_shards=2, elastic_mesh=False,
               watchdog_chunk_seconds=60.0,
               faults=f"preempt@{args.kill_at}@proc1")
    except faults_mod.SimulatedPreemption as exc:
        print(f"mp-worker {args.mp_worker}: preempted ({exc})",
              file=sys.stderr)
        return 3
    except BaseException as exc:  # noqa: BLE001 — the worker's whole
        # job is to report HOW it died to the parent
        print(f"mp-worker {args.mp_worker}: died collaterally "
              f"({type(exc).__name__}: {exc})", file=sys.stderr)
        return 4
    return 0


def _run_multiprocess_killed(args, work: pathlib.Path) -> dict:
    """Spawn the two killed-run workers; returns per-process facts."""
    import os
    import subprocess

    port = _free_port()
    procs = []
    for k in range(2):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        # one host device per process: the 2-device global mesh spans
        # the two processes, so every chunk's psum is a real cross-
        # process collective (gloo)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=1"
                            ).strip()
        env.pop("PERT_FAULTS", None)
        procs.append(subprocess.Popen(
            [sys.executable, __file__, "--mp-worker", str(k),
             "--coordinator", f"127.0.0.1:{port}",
             "--workdir", str(work), "--cells", str(args.cells),
             "--g1-cells", str(args.g1_cells),
             "--bin-size", str(args.bin_size),
             "--kill-at", args.kill_at],
            env=env, cwd=str(pathlib.Path(__file__).resolve().parents[1])))
    codes = []
    for p in procs:
        try:
            codes.append(p.wait(timeout=900))
        except subprocess.TimeoutExpired:
            p.kill()
            codes.append(p.wait())
            print("chaos_smoke: killed a hung mp worker (timeout)",
                  file=sys.stderr)
    return {"exit_codes": codes}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells", type=int, default=32)
    ap.add_argument("--g1-cells", type=int, default=16)
    ap.add_argument("--bin-size", type=int, default=5_000_000,
                    help="smoke default: a coarse ~620-bin genome keeps "
                         "the three runs CI-cheap; drop to 500000 for "
                         "the bench-shaped chaos run")
    ap.add_argument("--kill-at", default="step2/chunk#3",
                    help="fault site of the injected preemption")
    ap.add_argument("--workdir", default=None,
                    help="checkpoint/telemetry scratch dir (default: a "
                         "fresh temp dir)")
    ap.add_argument("--out", default=None, help="JSON verdict path")
    ap.add_argument("--report", default=None,
                    help="write the resumed run's rendered markdown "
                         "report here (the 'Resilience' section)")
    ap.add_argument("--multiprocess", action="store_true",
                    help="run the 2-process topology-portable scenario "
                         "(sharded two-phase-committed checkpoints, "
                         "process-scoped preempt, 1-process reshard "
                         "resume) instead of the single-process one")
    ap.add_argument("--mp-worker", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--coordinator", default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.mp_worker is not None:
        return _mp_worker(args)

    force_cpu_backend()

    import numpy as np

    from scdna_replication_tools_tpu.obs.schema import validate_run
    from scdna_replication_tools_tpu.utils import faults as faults_mod

    work = pathlib.Path(args.workdir) if args.workdir \
        else pathlib.Path(tempfile.mkdtemp(prefix="pert_chaos_"))
    work.mkdir(parents=True, exist_ok=True)
    ck = work / "ck"
    shutil.rmtree(ck, ignore_errors=True)

    df_s, df_g, _ = make_genome_workload(args.cells, args.g1_cells,
                                         bin_size=args.bin_size, seed=0)

    print(f"chaos_smoke: golden run ({args.cells} S cells)...",
          file=sys.stderr)
    tau_golden, _ = _infer(df_s, df_g, str(work / "golden.jsonl"))

    mp_facts = None
    if args.multiprocess:
        print(f"chaos_smoke: 2-process killed run "
              f"(preempt@{args.kill_at}@proc1)...", file=sys.stderr)
        mp_facts = _run_multiprocess_killed(args, work)
        # inspect the committed generation BEFORE the resume run: a
        # single-process resume deliberately RETIRES the commit pointer
        # when its own single-file save supersedes the sharded one
        commit = ck / "pert_step2.commit.json"
        mp_facts["commit_doc"] = json.loads(commit.read_text()) \
            if commit.exists() else {}
        mp_facts["shards_exist"] = [
            (ck / name).exists()
            for name in mp_facts["commit_doc"].get("files", [])]
    else:
        print(f"chaos_smoke: killed run (preempt@{args.kill_at})...",
              file=sys.stderr)
        preempted = False
        try:
            _infer(df_s, df_g, str(work / "killed.jsonl"),
                   checkpoint_dir=str(ck), checkpoint_every=1,
                   faults=f"preempt@{args.kill_at}")
        except faults_mod.SimulatedPreemption:
            preempted = True
        faults_mod.install(None)

    print("chaos_smoke: resumed run (--resume auto)...", file=sys.stderr)
    tau_resumed, _ = _infer(df_s, df_g, str(work / "resumed.jsonl"),
                            checkpoint_dir=str(ck), checkpoint_every=1)

    resumed_events = [json.loads(line) for line in
                      (work / "resumed.jsonl").read_text().splitlines()]
    max_abs = float(np.max(np.abs(tau_golden - tau_resumed))) \
        if len(tau_golden) == len(tau_resumed) else float("inf")

    checks = {
        "resumed_log_schema_valid": validate_run(work / "resumed.jsonl")
        == [],
        "resumed_log_has_resume_trail": any(
            ev["event"] == "resume" for ev in resumed_events),
        "resumed_schema_version_4": resumed_events[0].get(
            "schema_version", 0) >= 4,
    }
    if args.multiprocess:
        commit_doc = mp_facts["commit_doc"]
        resume_evs = [ev for ev in resumed_events
                      if ev["event"] == "resume"
                      and ev.get("action") in ("restored", "resumed")]
        # cross-topology resume is parity-gated, not bit-exact: the
        # reduction geometry changed (2-device psum -> 1 device), and
        # Adam amplifies the reassociation epsilon chaotically over the
        # remaining trajectory (see tests/test_padding_and_chunking.py).
        # The delta folds over the tau mirror symmetry, and a bounded
        # handful of boundary-extreme cells may land in either basin
        # (tests/test_topology_resume.py::_assert_tau_parity)
        if len(tau_golden) == len(tau_resumed):
            folded = np.minimum(np.abs(tau_golden - tau_resumed),
                                np.abs(tau_golden - (1.0 - tau_resumed)))
            outliers = folded >= 0.05
            tau_ok = bool(
                int(outliers.sum()) <= 2
                and np.all((tau_golden[outliers] < 0.05)
                           | (tau_golden[outliers] > 0.95)))
        else:
            tau_ok = False
        checks.update({
            "proc1_died_by_preemption": mp_facts["exit_codes"][1] == 3,
            "proc0_did_not_finish_clean": mp_facts["exit_codes"][0] != 0,
            "two_phase_commit_present": bool(commit_doc),
            "commit_names_two_hosts": int(
                commit_doc.get("process_count", 0)) == 2,
            "all_committed_shards_exist": bool(mp_facts["shards_exist"])
            and all(mp_facts["shards_exist"]),
            "resume_was_resharded": any(
                ev.get("resharded") for ev in resume_evs),
            "tau_parity_vs_golden": tau_ok,
        })
    else:
        killed_events = [json.loads(line) for line in
                         (work / "killed.jsonl").read_text().splitlines()]
        checks.update({
            "preemption_fired": preempted,
            "killed_log_has_fault_event": any(
                ev["event"] == "fault_injected" for ev in killed_events),
            "killed_run_ended_error": (
                killed_events[-1]["event"] == "run_end"
                and killed_events[-1]["status"] == "error"),
            "tau_bit_exact_vs_golden": bool(
                np.array_equal(tau_golden, tau_resumed)),
        })

    if args.report:
        from tools.pert_report import render_report

        report = render_report(work / "resumed.jsonl")
        pathlib.Path(args.report).write_text(report + "\n")
        checks["report_has_resilience_section"] = "## Resilience" in report

    verdict = {
        "metric": ("chaos_smoke_multiprocess_reshard_resume"
                   if args.multiprocess
                   else "chaos_smoke_kill_and_resume"),
        "kill_at": args.kill_at + ("@proc1" if args.multiprocess else ""),
        "cells": args.cells,
        "checks": checks,
        "tau_max_abs_delta": max_abs,
        "ok": all(checks.values()),
        "workdir": str(work),
    }
    if mp_facts is not None:
        verdict["worker_exit_codes"] = mp_facts["exit_codes"]
    print(json.dumps(verdict))
    if args.out:
        pathlib.Path(args.out).write_text(json.dumps(verdict, indent=1)
                                          + "\n")
    if not verdict["ok"]:
        failing = [k for k, v in checks.items() if not v]
        print(f"chaos_smoke: FAILED checks: {failing}", file=sys.stderr)
        return 1
    print("chaos_smoke: OK — kill-and-resume parity holds",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
