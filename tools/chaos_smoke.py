"""Chaos smoke: inject a mid-fit preemption, resume, assert parity.

The CI face of the durable-runs layer (ISSUE 9): runs the smoke-shaped
simulated pipeline three times —

1. **golden** — uninterrupted, no checkpointing;
2. **killed** — same workload with ``--faults preempt@step2/chunk#N``
   and periodic in-fit checkpointing; dies mid-step-2 by design;
3. **resumed** — ``resume='auto'`` against the killed run's
   checkpoint directory; must continue the step-2 fit mid-budget.

Asserts (exit 1 on any failure):

* the resumed run's final per-cell ``model_tau`` matches the golden
  run's bit-exactly;
* the resumed RunLog validates against schema v4 and carries the
  ``resume`` trail; the killed RunLog carries ``fault_injected`` and a
  ``run_end`` with status ``error``;
* the rendered report's "Resilience" section is non-placeholder.

Writes the resumed run's rendered markdown report (the "Resilience"
section CI uploads) to ``--report``.

Usage::

    python tools/chaos_smoke.py --out chaos_smoke.json \
        --report chaos_resilience.md
"""

from __future__ import annotations

import argparse
import json
import pathlib
import shutil
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from tools.full_pipeline_bench import (  # noqa: E402
    force_cpu_backend,
    make_genome_workload,
)


def _infer(df_s, df_g, telemetry, **extra):
    import numpy as np

    from scdna_replication_tools_tpu.api import scRT

    scrt = scRT(df_s.copy(), df_g.copy(), input_col="reads",
                clone_col="clone_id", assign_col="copy",
                cn_prior_method="g1_clones", max_iter=100, min_iter=25,
                rel_tol=0.0, run_step3=False, telemetry_path=telemetry,
                **extra)
    cn_s_out, _, _, _ = scrt.infer(level="pert")
    tau = cn_s_out.groupby("cell_id").agg(
        tau=("model_tau", "first")).sort_index()["tau"].to_numpy()
    return np.asarray(tau), scrt


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells", type=int, default=32)
    ap.add_argument("--g1-cells", type=int, default=16)
    ap.add_argument("--bin-size", type=int, default=5_000_000,
                    help="smoke default: a coarse ~620-bin genome keeps "
                         "the three runs CI-cheap; drop to 500000 for "
                         "the bench-shaped chaos run")
    ap.add_argument("--kill-at", default="step2/chunk#3",
                    help="fault site of the injected preemption")
    ap.add_argument("--workdir", default=None,
                    help="checkpoint/telemetry scratch dir (default: a "
                         "fresh temp dir)")
    ap.add_argument("--out", default=None, help="JSON verdict path")
    ap.add_argument("--report", default=None,
                    help="write the resumed run's rendered markdown "
                         "report here (the 'Resilience' section)")
    args = ap.parse_args(argv)

    force_cpu_backend()

    import numpy as np

    from scdna_replication_tools_tpu.obs.schema import validate_run
    from scdna_replication_tools_tpu.utils import faults as faults_mod

    work = pathlib.Path(args.workdir) if args.workdir \
        else pathlib.Path(tempfile.mkdtemp(prefix="pert_chaos_"))
    work.mkdir(parents=True, exist_ok=True)
    ck = work / "ck"
    shutil.rmtree(ck, ignore_errors=True)

    df_s, df_g, _ = make_genome_workload(args.cells, args.g1_cells,
                                         bin_size=args.bin_size, seed=0)

    print(f"chaos_smoke: golden run ({args.cells} S cells)...",
          file=sys.stderr)
    tau_golden, _ = _infer(df_s, df_g, str(work / "golden.jsonl"))

    print(f"chaos_smoke: killed run (preempt@{args.kill_at})...",
          file=sys.stderr)
    preempted = False
    try:
        _infer(df_s, df_g, str(work / "killed.jsonl"),
               checkpoint_dir=str(ck), checkpoint_every=1,
               faults=f"preempt@{args.kill_at}")
    except faults_mod.SimulatedPreemption:
        preempted = True
    faults_mod.install(None)

    print("chaos_smoke: resumed run (--resume auto)...", file=sys.stderr)
    tau_resumed, _ = _infer(df_s, df_g, str(work / "resumed.jsonl"),
                            checkpoint_dir=str(ck), checkpoint_every=1)

    killed_events = [json.loads(line) for line in
                     (work / "killed.jsonl").read_text().splitlines()]
    resumed_events = [json.loads(line) for line in
                      (work / "resumed.jsonl").read_text().splitlines()]

    checks = {
        "preemption_fired": preempted,
        "killed_log_has_fault_event": any(
            ev["event"] == "fault_injected" for ev in killed_events),
        "killed_run_ended_error": (killed_events[-1]["event"] == "run_end"
                                   and killed_events[-1]["status"]
                                   == "error"),
        "resumed_log_schema_valid": validate_run(work / "resumed.jsonl")
        == [],
        "resumed_log_has_resume_trail": any(
            ev["event"] == "resume" for ev in resumed_events),
        "resumed_schema_version_4": resumed_events[0].get(
            "schema_version", 0) >= 4,
        "tau_bit_exact_vs_golden": bool(
            np.array_equal(tau_golden, tau_resumed)),
    }
    max_abs = float(np.max(np.abs(tau_golden - tau_resumed))) \
        if len(tau_golden) == len(tau_resumed) else float("inf")

    if args.report:
        from tools.pert_report import render_report

        report = render_report(work / "resumed.jsonl")
        pathlib.Path(args.report).write_text(report + "\n")
        checks["report_has_resilience_section"] = "## Resilience" in report

    verdict = {
        "metric": "chaos_smoke_kill_and_resume",
        "kill_at": args.kill_at,
        "cells": args.cells,
        "checks": checks,
        "tau_max_abs_delta": max_abs,
        "ok": all(checks.values()),
        "workdir": str(work),
    }
    print(json.dumps(verdict))
    if args.out:
        pathlib.Path(args.out).write_text(json.dumps(verdict, indent=1)
                                          + "\n")
    if not verdict["ok"]:
        failing = [k for k, v in checks.items() if not v]
        print(f"chaos_smoke: FAILED checks: {failing}", file=sys.stderr)
        return 1
    print("chaos_smoke: OK — kill-and-resume parity holds",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
