"""Repo-local shim for the ``pert-serve`` console entry.

The implementation lives in the installable package
(``scdna_replication_tools_tpu/serve/cli.py`` — the ``pert-serve``
console script in pyproject.toml); this wrapper exists so repo
checkouts driven without a ``pip install -e .`` (CI steps, the TPU
window runner) can invoke the same CLI as ``python tools/pert_serve.py
...``, mirroring the other tools/ entry points.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from scdna_replication_tools_tpu.serve.cli import (  # noqa: E402
    console_main,
)

if __name__ == "__main__":
    sys.exit(console_main())
