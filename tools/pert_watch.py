"""Mission control for a running PERT fit: watch / check / report.

The write side is ``obs/heartbeat.py`` — every process of a fit (and
the serve worker) atomically publishes ``health/host_<rank>.json`` in
the durable run dir.  This tool is the read side, one view over all
hosts:

    python tools/pert_watch.py watch RUNDIR [--once] [--interval S]
    python tools/pert_watch.py check RUNDIR [--rules FILE] \
        [--metrics-textfile OUT.prom] [--json]
    python tools/pert_watch.py report RUNDIR [--out report.md]

``RUNDIR`` is either the run directory (its ``health/`` subdir is
used) or a ``health/`` directory itself.

* ``watch`` renders per-host progress bars, the freshness ladder
  (fresh/lagging/stale/presumed_lost — a lost host is flagged by
  staleness BEFORE the surviving ranks' collective deadlocks), the
  straggler spread, desync state, the ETA projection, and the live
  RunLog tail.  Without ``--once`` it polls and re-renders, flagging
  hosts whose sequence number stopped advancing (staleness without
  clock trust);
* ``check`` evaluates the declarative rule file
  (``obs/alert_rules.json`` by default, see ``obs/alerts.py`` for the
  grammar), prints one verdict JSON document, optionally exports
  ``pert_heartbeat_lag_seconds`` / ``pert_straggler_spread_chunks`` /
  ``pert_run_eta_seconds`` as a Prometheus textfile, and exits
  non-zero when any error-severity rule fires — the same gate shape as
  ``pert_fleet regress``, so CI and the TPU window runner can fail a
  battery on run health;
* ``report`` emits the markdown "Run health" section
  (``tools/pert_report.py`` embeds the same renderer).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from scdna_replication_tools_tpu.obs import alerts as alerts_mod  # noqa: E402
from scdna_replication_tools_tpu.obs import heartbeat as hb_mod  # noqa: E402
from scdna_replication_tools_tpu.obs.metrics import (  # noqa: E402
    MetricsRegistry,
)

_BAR_WIDTH = 20
_FRESH_BADGE = {
    "final": "final",
    "fresh": "fresh",
    "lagging": "LAGGING",
    "stale": "STALE",
    "presumed_lost": "PRESUMED-LOST",
}


def resolve_health_dir(path) -> pathlib.Path:
    """RUNDIR or a health dir itself -> the directory holding
    ``host_<rank>.json`` files."""
    root = pathlib.Path(path)
    if any(root.glob("host_*.json")):
        return root
    return root / "health"


def _bar(iteration, budget) -> str:
    if not budget or iteration is None:
        return "-" * _BAR_WIDTH
    frac = min(max(int(iteration) / max(int(budget), 1), 0.0), 1.0)
    done = round(frac * _BAR_WIDTH)
    return "#" * done + "-" * (_BAR_WIDTH - done)


def _fmt_eta(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    if v >= 3600:
        return f"{v / 3600:.1f}h"
    if v >= 60:
        return f"{v / 60:.1f}m"
    return f"{v:.0f}s"


def _host_line(h: dict) -> str:
    doc = h["doc"]
    it, budget = doc.get("iteration"), doc.get("budget")
    ms = doc.get("ms_per_iter_ewma")
    span = (doc.get("last_span") or {}).get("name") or "-"
    trail = doc.get("trail") or []
    return (f"  host{h['rank']:<3} {str(doc.get('state')):<8} "
            f"{str(doc.get('step') or '-'):<10} "
            f"c{str(doc.get('chunk') if doc.get('chunk') is not None else '-'):<4} "
            f"[{_bar(it, budget)}] "
            f"{it if it is not None else '-'}/{budget if budget else '-'} "
            f"{f'{ms:.1f}ms/it' if ms else '-':<10} "
            f"eta {_fmt_eta(doc.get('eta_seconds')):<7} "
            f"{_FRESH_BADGE.get(h['freshness'], h['freshness']):<13} "
            f"(lag {h['age_seconds']:.1f}s seq {h['seq']}) "
            f"span {span}"
            + (f"  trail {trail[-1]}" if trail else ""))


def runlog_tail(run_dir, limit: int = 5) -> list:
    """Last ``limit`` events of the freshest RunLog JSONL near the
    health dir (the run dir itself and its parent are searched)."""
    root = pathlib.Path(run_dir)
    candidates = []
    for base in (root, root.parent):
        try:
            candidates += [p for p in base.glob("*.jsonl")
                           if p.is_file()]
        except OSError:
            pass
    if not candidates:
        return []
    newest = max(candidates, key=lambda p: p.stat().st_mtime)
    try:
        lines = newest.read_text().splitlines()[-limit:]
    except OSError:
        return []
    out = []
    for line in lines:
        try:
            ev = json.loads(line)
        except ValueError:
            continue
        if isinstance(ev, dict) and ev.get("event"):
            out.append(ev)
    return out


def render_view(health_dir, aggregate: dict, verdicts: list,
                stalled=(), tail=()) -> str:
    lines = [f"PERT run health — {health_dir}",
             f"  hosts {aggregate['hosts_seen']}"
             f"/{aggregate['process_count'] or '?'}"
             f"  states {aggregate['states'] or '-'}"
             f"  steps {', '.join(aggregate['steps']) or '-'}"
             + ("  ** DESYNC **" if aggregate["desync"] else "")]
    for h in aggregate["hosts"]:
        mark = "  << seq stalled" if h["rank"] in stalled else ""
        lines.append(_host_line(h) + mark)
    if aggregate["missing_ranks"]:
        lines.append(f"  MISSING ranks (never wrote a heartbeat): "
                     f"{aggregate['missing_ranks']}")
    spread_c = aggregate["straggler_spread_chunks"]
    spread_i = aggregate["straggler_spread_iters"]
    lines.append(
        f"  spread {spread_c if spread_c is not None else '-'} chunks / "
        f"{spread_i if spread_i is not None else '-'} iters"
        f"  worst {aggregate['worst_freshness'] or '-'}"
        f"  max-lag {aggregate['max_lag_seconds']:.1f}s"
        f"  ETA {_fmt_eta(aggregate['eta_seconds'])}")
    fired = [v for v in verdicts if v["fired"]]
    if fired:
        lines.append("  alerts:")
        for v in fired:
            lines.append(f"    [{v['severity'].upper()}] {v['name']}: "
                         f"{v['detail']}")
    else:
        lines.append("  alerts: none firing")
    if tail:
        lines.append("  runlog tail: "
                     + " | ".join(str(ev.get("event")) for ev in tail))
    return "\n".join(lines)


def render_health_markdown(aggregate: dict, verdicts: list) -> list:
    """The markdown "Run health" section (shared with pert_report)."""
    lines = ["## Run health", ""]
    if not aggregate["hosts"]:
        lines += ["_no heartbeats found (heartbeats off, or the run "
                  "predates them)_", ""]
        return lines
    lines += ["| host | state | step | chunk | iter/budget | ms/iter "
              "| eta | freshness | lag (s) | seq |",
              "|---|---|---|---:|---:|---:|---:|---|---:|---:|"]
    for h in aggregate["hosts"]:
        doc = h["doc"]
        ms = doc.get("ms_per_iter_ewma")
        it, budget = doc.get("iteration"), doc.get("budget")
        lines.append(
            f"| {h['rank']} | {doc.get('state')} "
            f"| {doc.get('step') or '-'} "
            f"| {doc.get('chunk') if doc.get('chunk') is not None else '-'} "
            f"| {it if it is not None else '-'}"
            f"/{budget if budget else '-'} "
            f"| {f'{ms:.1f}' if ms else '-'} "
            f"| {_fmt_eta(doc.get('eta_seconds'))} "
            f"| {h['freshness']} | {h['age_seconds']:.1f} "
            f"| {h['seq']} |")
    lines.append("")
    spread_c = aggregate["straggler_spread_chunks"]
    lines.append(
        f"- **straggler spread**: "
        f"{spread_c if spread_c is not None else '-'} chunks "
        f"({aggregate['straggler_spread_iters'] if aggregate['straggler_spread_iters'] is not None else '-'} iters)")
    lines.append(f"- **desync**: "
                 f"{'YES — ' + ', '.join(aggregate['steps']) if aggregate['desync'] else 'no'}")
    if aggregate["missing_ranks"]:
        lines.append(f"- **missing ranks**: "
                     f"{aggregate['missing_ranks']}")
    lines.append(f"- **ETA**: {_fmt_eta(aggregate['eta_seconds'])}")
    fired = [v for v in verdicts if v["fired"]]
    if fired:
        lines.append("- **alerts firing**:")
        for v in fired:
            lines.append(f"  - [{v['severity']}] `{v['name']}` — "
                         f"{v['detail']}")
    else:
        lines.append("- **alerts**: none firing")
    lines.append("")
    return lines


def _aggregate_and_verdicts(health_dir, rules_path=None):
    aggregate = hb_mod.aggregate_health(health_dir)
    rules = alerts_mod.load_rules(rules_path)
    return aggregate, alerts_mod.evaluate(rules, aggregate)


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------

def cmd_watch(args) -> int:
    health_dir = resolve_health_dir(args.run_dir)
    last_seq = {}
    while True:
        aggregate, verdicts = _aggregate_and_verdicts(
            health_dir, args.rules)
        stalled = {h["rank"] for h in aggregate["hosts"]
                   if h["freshness"] not in ("final", "fresh")
                   and last_seq.get(h["rank"]) == h["seq"]}
        last_seq = {h["rank"]: h["seq"] for h in aggregate["hosts"]}
        tail = runlog_tail(health_dir) if not args.no_runlog else []
        print(render_view(health_dir, aggregate, verdicts,
                          stalled=stalled, tail=tail))
        if args.once:
            return 0
        print("-" * 78)
        sys.stdout.flush()
        time.sleep(args.interval)


def cmd_check(args) -> int:
    health_dir = resolve_health_dir(args.run_dir)
    aggregate, verdicts = _aggregate_and_verdicts(
        health_dir, args.rules)
    failing = alerts_mod.failing(verdicts)

    registry = MetricsRegistry(textfile_path=args.metrics_textfile)
    registry.gauge("pert_heartbeat_lag_seconds").set(
        float(aggregate["max_lag_seconds"]))
    spread = aggregate["straggler_spread_chunks"]
    registry.gauge("pert_straggler_spread_chunks").set(
        float(spread if spread is not None else 0))
    # a finished (or not-yet-projecting) run has no ETA; emit 0 so the
    # scrape series exists for every check, not only mid-fit ones
    eta = aggregate["eta_seconds"]
    registry.gauge("pert_run_eta_seconds").set(
        float(eta if eta is not None else 0.0))
    if args.metrics_textfile:
        registry.write_textfile()

    doc = {
        "kind": "pert_watch_check",
        "health_dir": str(health_dir),
        "ok": not failing,
        "failing": [v["name"] for v in failing],
        "verdicts": verdicts,
        "aggregate": {k: v for k, v in aggregate.items()
                      if k != "hosts"},
    }
    print(json.dumps(doc, indent=1, sort_keys=True))
    if failing:
        names = ", ".join(v["name"] for v in failing)
        print(f"pert_watch check: FAIL ({names})", file=sys.stderr)
        return 1
    return 0


def cmd_report(args) -> int:
    health_dir = resolve_health_dir(args.run_dir)
    aggregate, verdicts = _aggregate_and_verdicts(
        health_dir, args.rules)
    text = "\n".join(render_health_markdown(aggregate, verdicts))
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    else:
        sys.stdout.write(text + "\n")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Aggregate per-host heartbeats into one "
                    "mission-control view")
    sub = ap.add_subparsers(dest="cmd", required=True)

    w = sub.add_parser("watch", help="render the live view")
    w.add_argument("run_dir", help="run dir (or its health/ dir)")
    w.add_argument("--once", action="store_true",
                   help="render one frame and exit")
    w.add_argument("--interval", type=float, default=2.0,
                   help="poll interval in loop mode (seconds)")
    w.add_argument("--rules", default=None,
                   help="alert rule file (default: checked-in)")
    w.add_argument("--no-runlog", action="store_true",
                   help="skip the RunLog tail")
    w.set_defaults(fn=cmd_watch)

    c = sub.add_parser("check", help="evaluate alert rules; exit "
                                     "non-zero when an error rule fires")
    c.add_argument("run_dir")
    c.add_argument("--rules", default=None)
    c.add_argument("--metrics-textfile", default=None,
                   help="export the watch gauges here (Prometheus "
                        "textfile format)")
    c.set_defaults(fn=cmd_check)

    r = sub.add_parser("report", help="markdown 'Run health' section")
    r.add_argument("run_dir")
    r.add_argument("--rules", default=None)
    r.add_argument("--out", default=None)
    r.set_defaults(fn=cmd_report)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
