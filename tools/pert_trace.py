"""Merge RunLog span streams into one Chrome/Perfetto timeline.

The span layer (obs/spans.py, schema v8) records causality — request →
admission → fit chunks → stream-back, phases as spans, per-process
timelines — but each RunLog is still one file.  This tool is the
stitcher and exporter:

    # one timeline from any mix of run logs (a serve spool ingests the
    # worker log + every per-request run log under results/)
    python -m tools.pert_trace export --perfetto --out trace.json \\
        --spool /data/pert_spool
    python -m tools.pert_trace export --perfetto --out trace.json \\
        .pert_runs/run_p0.jsonl .pert_runs/run_p1.jsonl

    # trace-event format check (the CI trace-smoke gate)
    python -m tools.pert_trace validate trace.json

    # per-request latency decomposition (the serve A/B's waterfall)
    python -m tools.pert_trace waterfall --spool /data/pert_spool

Stitching rules:

* spans stamped with the same ``trace_id`` land on the same thread
  lane regardless of which log they came from — a serve request's
  worker-side spans (queue_wait, admission, stream_back) nest with the
  request run's own span tree because the ticket carried the trace id
  across the spool, and a multi-host run's per-process logs merge into
  per-``process_index`` rows of one timeline;
* logs WITHOUT spans (pre-v8, or tracing off) still render: their
  ``phase`` events are synthesized into slices anchored at
  ``run_start.started_unix + t`` (phase events are emitted at phase
  exit), so a stitched timeline never silently drops an untraced
  participant;
* ``--profile-dir`` ingests ``tools/trace_summary.scope_totals()`` —
  per-``pert/*``-scope XLA device seconds — as a counter track, so
  device time and host spans render in one UI.

Output is Chrome trace-event JSON (the ``traceEvents`` array format),
loadable by Perfetto (ui.perfetto.dev) and chrome://tracing.  Pure
stdlib + the obs package — runnable without jax, like the other tools.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import List, Optional

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from scdna_replication_tools_tpu.obs.summary import (  # noqa: E402
    read_events,
)

# the serve A/B's latency components, in causal order; ``classify``
# maps every span onto one of them
WATERFALL_COMPONENTS = ("queue_wait", "admission", "pad", "compile",
                        "fit", "decode", "stream_back", "other")

# container spans: the envelope of a timeline row, not a leaf cost —
# excluded from waterfall totals (their children ARE the breakdown)
_CONTAINER_SPANS = frozenset({"run", "request"})


def _warn(msg: str) -> None:
    print(f"pert_trace: warning: {msg}", file=sys.stderr)


def classify_span(name: str, attrs: Optional[dict] = None
                  ) -> Optional[str]:
    """Waterfall component of one span; None for spans that must not
    be summed (containers, and ``fit/chunk`` — the chunks decompose the
    fit phase they ride inside, double-counting it).

    Phase-derived spans map by the phase vocabulary: trace/compile →
    ``compile``; the fit (+ rescue sub-fits) → ``fit``; decode, QC and
    packaging → ``decode``; input staging — load, build, init, padding
    and the host→device transfer — → ``pad``; everything else
    (telemetry, checkpoints, metrics export) → ``other``.
    """
    if name in _CONTAINER_SPANS or name == "fit/chunk":
        return None
    if name in ("queue_wait", "admission", "stream_back"):
        return name
    if name.endswith("/trace") or name.endswith("/compile"):
        return "compile"
    if name.endswith("/fit") or "/rescue" in name:
        return "fit"
    if name.endswith("/decode") or name.endswith("/fetch") \
            or name.endswith("/package") or name.startswith("qc/") \
            or name.endswith("/qc_aggregate") \
            or name.startswith("package_"):
        return "decode"
    if name in ("load", "clone_prep", "finalize") \
            or name.endswith("/build") or name.endswith("/init") \
            or name.endswith("/h2d"):
        return "pad"
    return "other"


# ---------------------------------------------------------------------------
# log ingestion
# ---------------------------------------------------------------------------


def discover_logs(paths, spool=None) -> List[pathlib.Path]:
    """Run logs from explicit paths/directories plus a serve spool
    (worker_*.jsonl in the root + every results/*/run.jsonl)."""
    found: List[pathlib.Path] = []
    for p in paths or []:
        p = pathlib.Path(p)
        if p.is_dir():
            found.extend(sorted(p.rglob("*.jsonl")))
        elif p.exists():
            found.append(p)
        else:
            _warn(f"{p}: no such log — skipped")
    if spool:
        spool = pathlib.Path(spool)
        found.extend(sorted(spool.glob("*.jsonl")))
        found.extend(sorted(spool.glob("results/*/run.jsonl")))
    seen, out = set(), []
    for p in found:
        key = str(p.resolve())
        if key not in seen:
            seen.add(key)
            out.append(p)
    return out


def log_spans(path) -> dict:
    """One log's timeline material: its span_end payloads, the
    synthesized phase slices when it has none, and the run identity
    (trace id, process index, absolute time base)."""
    try:
        events = read_events(path)
    except OSError:
        return {"path": str(path), "spans": [], "phases": [],
                "trace_id": None, "process_index": 0}
    start = next((ev for ev in events
                  if ev.get("event") == "run_start"), {})
    spans = [ev for ev in events if ev.get("event") == "span_end"]
    phases = []
    if not spans:
        # pre-v8 / tracing-off fallback: phase events anchor at
        # started_unix + t (emitted at phase EXIT), so the slice is
        # [end - seconds, end]
        base = start.get("started_unix")
        if isinstance(base, (int, float)):
            for ev in events:
                if ev.get("event") != "phase":
                    continue
                secs = float(ev.get("seconds") or 0.0)
                end = float(base) + float(ev.get("t") or 0.0)
                phases.append({"name": str(ev.get("name")),
                               "start_unix": end - secs,
                               "duration_seconds": secs})
    return {
        "path": str(path),
        "run_name": start.get("run_name"),
        "request_id": start.get("request_id"),
        "trace_id": start.get("trace_id"),
        "process_index": int(start.get("process_index") or 0),
        "spans": spans,
        "phases": phases,
    }


# ---------------------------------------------------------------------------
# Perfetto (Chrome trace-event) export
# ---------------------------------------------------------------------------


def build_trace(logs: List[dict], scope_seconds: Optional[dict] = None
                ) -> dict:
    """Merge ingested logs into one trace-event document.

    pid = process_index (multi-host rows), tid = one lane per trace id
    (span-less logs get a lane of their own), ts normalized to the
    earliest instant across every participant so the stitched timeline
    starts at 0.
    """
    slices = []   # (start_unix, dur_s, name, pid, lane_key, args)
    lanes: dict = {}

    def _lane(key: str) -> int:
        return lanes.setdefault(key, len(lanes) + 1)

    lane_names: dict = {}
    for log in logs:
        default_lane = log.get("trace_id") \
            or f"log:{pathlib.Path(log['path']).name}"
        for ev in log["spans"]:
            lane_key = ev.get("trace_id") or default_lane
            lane = _lane(lane_key)
            lane_names.setdefault(
                lane, log.get("request_id") or lane_key)
            args = {"trace_id": ev.get("trace_id"),
                    "span_id": ev.get("span_id"),
                    "parent_id": ev.get("parent_id"),
                    "log": pathlib.Path(log["path"]).name}
            args.update(ev.get("attrs") or {})
            slices.append((float(ev.get("start_unix") or 0.0),
                           float(ev.get("duration_seconds") or 0.0),
                           str(ev.get("name")),
                           int(ev.get("process_index") or 0),
                           lane, args))
        for ph in log["phases"]:
            lane = _lane(default_lane)
            lane_names.setdefault(
                lane, log.get("request_id") or default_lane)
            slices.append((ph["start_unix"], ph["duration_seconds"],
                           ph["name"], log["process_index"], lane,
                           {"kind": "phase",
                            "log": pathlib.Path(log["path"]).name}))
    if not slices:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    t0 = min(s[0] for s in slices)
    events = []
    pids = sorted({s[3] for s in slices})
    for pid in pids:
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0,
                       "args": {"name": f"pert process {pid}"}})
    for lane, label in sorted(lane_names.items()):
        for pid in pids:
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": lane,
                           "args": {"name": str(label)}})
    # key on the scalar prefix only: the args dicts are not orderable,
    # and two same-instant same-name spans would otherwise TypeError
    # the whole export
    for start, dur, name, pid, lane, args in sorted(
            slices, key=lambda s: s[:5]):
        events.append({
            "ph": "X", "cat": "pert", "name": name,
            "pid": pid, "tid": lane,
            "ts": round((start - t0) * 1e6, 3),
            "dur": round(max(dur, 0.0) * 1e6, 3),
            "args": args,
        })
    if scope_seconds:
        # XLA named-scope device time as ONE counter track: each scope
        # is a series of the counter, so device totals render alongside
        # the host spans in the same UI
        events.append({
            "ph": "C", "name": "pert_xla_scope_seconds", "pid": pids[0],
            "ts": 0.0,
            "args": {scope: round(float(secs), 6)
                     for scope, secs in sorted(scope_seconds.items())},
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"kind": "pert_trace",
                     "base_unix": round(t0, 6),
                     "logs": [log["path"] for log in logs]},
    }


def validate_trace(doc) -> List[str]:
    """Trace-event format errors ([] = valid): the shape Perfetto and
    chrome://tracing ingest — a dict with a ``traceEvents`` list (or a
    bare list), every event an object with a ``ph``, complete ``X``
    duration events, well-formed counters and metadata."""
    errors: List[str] = []
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            return ["top-level object lacks a traceEvents array"]
    elif isinstance(doc, list):
        events = doc
    else:
        return [f"not a trace-event document: {type(doc).__name__}"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ph, str) or not ph:
            errors.append(f"{where}: missing ph")
            continue
        if ph in ("X", "B", "E", "C", "M") and not isinstance(
                ev.get("name"), str):
            errors.append(f"{where}: {ph!r} event lacks a name")
        if ph == "X":
            for field in ("ts", "dur"):
                if not isinstance(ev.get(field), (int, float)):
                    errors.append(f"{where}: X event lacks numeric "
                                  f"{field}")
            dur = ev.get("dur")
            # guard the comparison on the type: a non-numeric dur was
            # already reported above, and `"abc" < 0` would crash the
            # validator on exactly the malformed input it diagnoses
            if isinstance(dur, (int, float)) and dur < 0:
                errors.append(f"{where}: negative dur")
            for field in ("pid", "tid"):
                if not isinstance(ev.get(field), int):
                    errors.append(f"{where}: X event lacks integer "
                                  f"{field}")
        if ph == "C":
            if not isinstance(ev.get("ts"), (int, float)):
                errors.append(f"{where}: counter lacks numeric ts")
            if not isinstance(ev.get("args"), dict):
                errors.append(f"{where}: counter lacks args")
        if ph == "M" and not isinstance(ev.get("args"), dict):
            errors.append(f"{where}: metadata event lacks args")
    return errors


# ---------------------------------------------------------------------------
# per-request waterfall (the serve A/B's latency decomposition)
# ---------------------------------------------------------------------------


def request_waterfall(worker_log, request_log=None,
                      request_id: Optional[str] = None,
                      worker_spans: Optional[list] = None) -> dict:
    """Where one request's latency went: seconds per
    :data:`WATERFALL_COMPONENTS` bucket, from the worker log's
    request-scoped spans (queue_wait / admission / stream_back) plus
    the request run log's phase spans (pad / compile / fit / decode).
    ``total_seconds`` is the request span's own duration when present.
    Missing material contributes zeros — a waterfall over an untraced
    run is honest about knowing nothing, not an error.

    ``worker_spans`` (pre-parsed ``span_end`` payloads, e.g. pooled
    from EVERY worker log of a multi-worker spool) substitutes for
    re-reading ``worker_log`` — callers decomposing N requests parse
    the worker side once instead of N times."""
    out = {c: 0.0 for c in WATERFALL_COMPONENTS}
    total = None
    slab_occupancy = None
    retired_early = None

    def _consume(spans, only_request: Optional[str]):
        nonlocal total, slab_occupancy, retired_early
        for ev in spans:
            attrs = ev.get("attrs") or {}
            if only_request and attrs.get("request_id") \
                    not in (None, only_request):
                continue
            name = str(ev.get("name"))
            if name == "request" and (not only_request or attrs.get(
                    "request_id") == only_request):
                total = float(ev.get("duration_seconds") or 0.0)
                # batched-serving attribution inputs (the worker's
                # request-span close stamps them when max_batch > 1)
                if attrs.get("slab_avg_occupancy") is not None:
                    slab_occupancy = float(attrs["slab_avg_occupancy"])
                if attrs.get("retired_early") is not None:
                    retired_early = bool(attrs["retired_early"])
                continue
            comp = classify_span(name, attrs)
            if comp is not None:
                out[comp] += float(ev.get("duration_seconds") or 0.0)

    if worker_spans is not None:
        _consume(worker_spans, request_id)
    elif worker_log:
        _consume(log_spans(worker_log)["spans"], request_id)
    if request_log:
        _consume(log_spans(request_log)["spans"], None)
    waterfall = {c: round(v, 4) for c, v in out.items()}
    waterfall["total_seconds"] = round(total, 4) \
        if total is not None else None
    if slab_occupancy is not None:
        # continuous batching: K blocks' fit spans cover the SAME
        # device seconds, so summing raw fit time across a slab
        # double-counts.  fit_attributed divides each request's fit
        # wall by its time-weighted slab occupancy — the per-request
        # share of the shared fit time; summing IT across the slab
        # recovers the device wall once.  Raw ``fit`` stays as-is
        # (it is the request's own latency experience).
        waterfall["slab_avg_occupancy"] = round(slab_occupancy, 4)
        waterfall["fit_attributed"] = round(
            waterfall["fit"] / max(slab_occupancy, 1.0), 4)
        if retired_early is not None:
            waterfall["retired_early"] = retired_early
    return waterfall


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="pert_trace",
        description="Stitch RunLog span streams into one Perfetto "
                    "timeline; validate trace-event documents; "
                    "decompose serve-request latency")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_exp = sub.add_parser("export", help="merge run logs into one "
                                          "Chrome/Perfetto trace JSON")
    p_exp.add_argument("logs", nargs="*",
                       help="run-log files or directories to ingest")
    p_exp.add_argument("--spool", default=None,
                       help="pert-serve spool: ingests the worker "
                            "log(s) + every results/*/run.jsonl")
    p_exp.add_argument("--perfetto", action="store_true",
                       help="Chrome trace-event JSON (the only format; "
                            "the flag documents intent)")
    p_exp.add_argument("--profile-dir", default=None,
                       help="jax.profiler trace directory: ingests "
                            "trace_summary.scope_totals() XLA "
                            "named-scope device seconds as a counter "
                            "track")
    p_exp.add_argument("--out", required=True)

    p_val = sub.add_parser("validate", help="check a trace-event "
                                            "document (nonzero exit "
                                            "on format errors)")
    p_val.add_argument("trace")

    p_wat = sub.add_parser("waterfall",
                           help="per-request latency decomposition "
                                "from a serve spool's logs")
    p_wat.add_argument("--spool", required=True)
    p_wat.add_argument("--request", default=None,
                       help="one request id (default: every request "
                            "with a results/<id>/run.jsonl)")

    args = ap.parse_args(argv)

    if args.cmd == "export":
        paths = discover_logs(args.logs, spool=args.spool)
        if not paths:
            raise SystemExit("pert_trace: no run logs found — pass log "
                             "files/directories or --spool")
        logs = [log_spans(p) for p in paths]
        if not any(log["spans"] or log["phases"] for log in logs):
            _warn("none of the ingested logs carry spans or phases — "
                  "the timeline will be empty (run with tracing on: "
                  "--trace-spans / the serve worker's default)")
        scope_seconds = None
        if args.profile_dir:
            try:
                from tools.trace_summary import scope_totals

                scope_seconds = scope_totals(args.profile_dir) or None
            except Exception as exc:  # noqa: BLE001 — the counter
                # track is an enrichment; a missing/unreadable profile
                # dir must not block the span export
                _warn(f"--profile-dir unreadable ({exc}); exporting "
                      f"without the XLA counter track")
        doc = build_trace(logs, scope_seconds=scope_seconds)
        errors = validate_trace(doc)
        if errors:
            raise SystemExit("pert_trace: internal error — the built "
                             "trace fails its own validation: "
                             + "; ".join(errors[:5]))
        out = pathlib.Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(doc, indent=1) + "\n")
        n = sum(1 for ev in doc["traceEvents"] if ev.get("ph") == "X")
        print(f"pert_trace: {n} span slice(s) from {len(paths)} "
              f"log(s) -> {out} (open in ui.perfetto.dev)")
        return 0

    if args.cmd == "validate":
        try:
            doc = json.loads(pathlib.Path(args.trace).read_text())
        except (OSError, ValueError) as exc:
            raise SystemExit(f"pert_trace: unreadable trace "
                             f"{args.trace} ({exc})")
        errors = validate_trace(doc)
        if errors:
            for err in errors[:20]:
                print(f"pert_trace: {args.trace}: {err}",
                      file=sys.stderr)
            return 1
        events = doc.get("traceEvents", doc)
        n = sum(1 for ev in events
                if isinstance(ev, dict) and ev.get("ph") == "X")
        print(f"pert_trace: {args.trace} is a valid trace-event "
              f"document ({n} duration slices)")
        return 0

    # waterfall
    spool = pathlib.Path(args.spool)
    worker_logs = sorted(spool.glob("*.jsonl"))
    if not worker_logs:
        raise SystemExit(f"pert_trace: no worker log under {spool}")
    request_dirs = sorted(d for d in (spool / "results").glob("*")
                          if (d / "run.jsonl").exists()) \
        if (spool / "results").is_dir() else []
    if args.request:
        request_dirs = [d for d in request_dirs
                        if d.name == args.request]
    # pool the spool-side spans from EVERY worker log, once: multiple
    # workers (or a restarted one) share a spool, and a request's
    # queue_wait/admission spans live in whichever worker served it —
    # reading only the newest log would silently zero the others'
    # components.  The per-request_id filter keeps requests disjoint.
    worker_spans = [span for wl in worker_logs
                    for span in log_spans(wl)["spans"]]
    rows = {}
    for d in request_dirs:
        rows[d.name] = request_waterfall(
            None, d / "run.jsonl", request_id=d.name,
            worker_spans=worker_spans)
    print(json.dumps({"spool": str(spool), "requests": rows},
                     indent=1))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        sys.exit(0)
