#!/bin/bash
# Watch for a TPU tunnel window and run the queued round-6 measurements
# the moment one opens.  The tunnel drops for hours at a time (see
# artifacts/TPU_PROBE_r06.log); a hung backend call blocks forever with
# ~0 CPU, so every step runs under a hard timeout and the probe gates
# each attempt.  Artifacts land in artifacts/; progress is appended to
# artifacts/TPU_PROBE_r06.log.
#
# Battery (in value order; each is skipped once its artifact exists):
#   1. 300-iter kernel A/B (sparse/dense/xla) — noise-tight ms/iter
#   2. 10k-cell step-2 bench — the bandwidth-bound regime
#   3. full pipeline w/ mirror rescue on TPU — perf + accuracy headline
#   4. 5k-cell full pipeline — scale evidence beyond the 1k artifact
#   5. 20kb-bin long-genome (154,770 loci) full pipeline — the regime
#      the reference's README warns about, on-chip
#   6. 10k-cell full pipeline (cell_chunk for HBM) — best effort,
#      capped at MAX_10K_TRIES so it cannot pin the runner forever
set -u
cd "$(dirname "$0")/.."
LOG=artifacts/TPU_PROBE_r06.log
MAX_10K_TRIES=3
tries_10k=0
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

probe() {
    local out rc
    out=$(timeout 120 python -c \
        "import jax; assert jax.devices()[0].platform == 'tpu'" 2>&1)
    rc=$?
    [ $rc -eq 0 ] && return 0
    # distinguish a tunnel hang (rc=124 timeout) from a code/backend
    # error (anything else, with stderr) — round 4's silent downgrade
    # was indistinguishable from a code regression
    echo "$(stamp) window-runner: probe fail rc=${rc}: $(echo "$out" | tail -c 160 | tr '\n' ' ')" >> "$LOG"
    return 1
}

run_one() {  # run_one <name> <tpu_field> <timeout_s> <cmd...>
    # tpu_field: which JSON field must prove the run was on-chip —
    #   device_platform  for bench.py (its "platform" echoes the forced
    #                    label even after a silent jax CPU downgrade)
    #   platform         for full_pipeline_bench (measured at runtime)
    local name=$1 tpu_field=$2 tmo=$3 rc; shift 3
    if [ -s "artifacts/${name}.json" ]; then      # already landed...
        if grep -q "\"${tpu_field}\": \"tpu\"" "artifacts/${name}.json"; then
            return 0
        fi
        # ...but not on-chip (e.g. a manual dead-tunnel run): re-run it
        mv "artifacts/${name}.json" "artifacts/${name}.cpu_fallback.json"
        echo "$(stamp) window-runner: ${name} pre-existing artifact is not on-chip - kept aside, re-running" >> "$LOG"
    fi
    echo "$(stamp) window-runner: starting ${name}" >> "$LOG"
    timeout "$tmo" "$@" \
        > "artifacts/${name}.json.tmp" 2> "artifacts/${name}.err"
    rc=$?
    if [ $rc -ne 0 ]; then
        rm -f "artifacts/${name}.json.tmp"
        echo "$(stamp) window-runner: ${name} failed/timeout rc=${rc}: $(tail -c 200 artifacts/${name}.err | tr '\n' ' ')" >> "$LOG"
        return 1
    fi
    # full_pipeline_bench writes --out itself; bench.py emits the JSON
    # as its last stdout line (keep only that line, defensively)
    [ -s "artifacts/${name}.json" ] \
        || tail -n 1 "artifacts/${name}.json.tmp" > "artifacts/${name}.json"
    rm -f "artifacts/${name}.json.tmp"
    # a cpu artifact must not satisfy a TPU-named step (bench.py re-execs
    # itself on CPU when the tunnel dies mid-run and still exits 0)
    if ! grep -q "\"${tpu_field}\": \"tpu\"" "artifacts/${name}.json"; then
        mv "artifacts/${name}.json" "artifacts/${name}.cpu_fallback.json"
        echo "$(stamp) window-runner: ${name} landed as CPU fallback (tunnel died mid-run?) - kept aside, will retry" >> "$LOG"
        return 1
    fi
    echo "$(stamp) window-runner: ${name} OK: $(head -c 400 artifacts/${name}.json)" >> "$LOG"
    return 0
}

# Durable stages: every full-pipeline stage runs with per-stage
# checkpoints + `--resume auto`, so a tunnel drop mid-stage costs only
# the in-flight fit chunk — the next window continues the battery
# mid-budget instead of restarting the whole stage (and the per-stage
# `timeout` plus the in-process watchdogs convert hangs into typed,
# resumable aborts instead of rc=124 with nothing written).
# Topology-portable (checkpoint format v4): the stamp in every save
# lets a stage checkpointed on one window's mesh shape resume on
# whatever shape the NEXT window offers (fewer chips, or none —
# single-device), and --elastic-mesh keeps a stage alive in-window
# when a device drops out of the slice: the mesh shrinks (audited as
# `degrade mesh_shrink`) instead of the stage aborting.
DURABLE="--resume auto --watchdog-compile 600 --watchdog-chunk 600 --elastic-mesh"

battery() {  # returns 0 only if every step it attempted succeeded
    # --budget full: keep the production-shaped sizes on TPU (bench.py
    # defaults to --budget fast so the bare harness invocation can't
    # time out like BENCH_r05's rc=124)
    run_one BENCH_r06_tpu_300iter device_platform 900 \
        python bench.py --platform tpu --budget full --iters 300 --skip-baseline || return 1
    run_one BENCH_r06_tpu_10k device_platform 1200 \
        python bench.py --platform tpu --budget full --cells 10000 --iters 50 --skip-baseline || return 1
    # CN-encoding A/B on the chip (PR 10): dense categorical vs
    # independent-binary vs binary + fused single-sweep Adam at the
    # benchmark shape — the on-chip measurement PERF_NOTES' planes
    # model predicts (~146 -> ~56 planes/iter); the committed CPU
    # artifact is roofline-blind by nature
    run_one BENCH_r10_enum_ab_tpu platform 1500 \
        python bench.py --enum-ab --platform tpu --budget full \
            --ab-out artifacts/BENCH_r10_enum_ab_tpu.json || return 1
    # serving A/B on the chip (PR 12): N queued requests through one
    # resident shape-bucketed worker vs N cold CLI runs — on TPU the
    # cold arm's per-run trace+compile is multi-seconds-per-program
    # (the r5 profile), so this is where the residency win is
    # measured, not modelled; the committed CPU artifact
    # (BENCH_r12_serve_ab_cpu.json) is the regression anchor
    run_one BENCH_r12_serve_ab_tpu platform 2400 \
        python bench.py --serve-ab --platform tpu \
            --ab-out artifacts/BENCH_r12_serve_ab_tpu.json \
            --metrics-textfile artifacts/METRICS_serve_tpu.prom || return 1
    # executable-store restart A/B on the chip (PR 18): a fresh-process
    # worker over a warmed spool must serve its first request with
    # ZERO XLA compiles — on TPU the skipped compile is the multi-
    # second r5-profile cost, so the deserialize-vs-compile gap this
    # stage records is the headline cold-start cut; the committed CPU
    # artifact (BENCH_r18_aot_cold_cpu.json) is the regression anchor
    run_one BENCH_r18_aot_restart_tpu platform 2400 \
        python bench.py --serve-ab --restart --platform tpu \
            --ab-out artifacts/BENCH_r18_aot_restart_tpu.json || return 1
    run_one FULL_PIPELINE_r06_rescue_tpu platform 1500 \
        python tools/full_pipeline_bench.py --run-step3 --mirror-rescue \
            --checkpoint-dir artifacts/ckpt_r06_rescue $DURABLE \
            --metrics-textfile artifacts/METRICS_r06_rescue_tpu.prom \
            --out artifacts/FULL_PIPELINE_r06_rescue_tpu.json || return 1
    run_one FULL_PIPELINE_r06_5k_tpu platform 3600 \
        python tools/full_pipeline_bench.py --cells 5000 --g1-cells 500 \
            --run-step3 --mirror-rescue \
            --checkpoint-dir artifacts/ckpt_r06_5k $DURABLE \
            --metrics-textfile artifacts/METRICS_r06_5k_tpu.prom \
            --out artifacts/FULL_PIPELINE_r06_5k_tpu.json || return 1
    run_one FULL_PIPELINE_r06_20kb_tpu platform 2400 \
        python tools/full_pipeline_bench.py --cells 250 --g1-cells 60 \
            --bin-size 20000 --run-step3 --mirror-rescue \
            --checkpoint-dir artifacts/ckpt_r06_20kb $DURABLE \
            --metrics-textfile artifacts/METRICS_r06_20kb_tpu.prom \
            --out artifacts/FULL_PIPELINE_r06_20kb_tpu.json || return 1
    if [ ! -s artifacts/FULL_PIPELINE_r06_10k_tpu.json ] \
            && [ "$tries_10k" -lt "$MAX_10K_TRIES" ]; then
        tries_10k=$((tries_10k + 1))
        run_one FULL_PIPELINE_r06_10k_tpu platform 7200 \
            python tools/full_pipeline_bench.py --cells 10000 --g1-cells 1000 \
                --run-step3 --mirror-rescue --cell-chunk 2500 \
                --checkpoint-dir artifacts/ckpt_r06_10k $DURABLE \
                --metrics-textfile artifacts/METRICS_r06_10k_tpu.prom \
                --out artifacts/FULL_PIPELINE_r06_10k_tpu.json || return 1
    fi
    return 0
}

# Run-health plane: every durable stage checkpoints under
# artifacts/ckpt_*, so with heartbeat_dir=auto each one publishes
# health/host_<rank>.json there.  While a battery runs, a background
# loop appends one `pert_watch watch --once` frame per live health dir
# to the log every 60s — a tunnel-window battery left overnight shows
# WHERE it was (step/chunk/ETA, straggler spread, presumed-lost hosts)
# instead of an opaque rc=124.  `pert_watch check` verdicts ride along
# so a firing alert (hostloss, desync) is in the log the moment it
# happens, not at post-mortem.
health_snapshot() {
    local dir
    for dir in artifacts/ckpt_*/health; do
        [ -d "$dir" ] || continue
        {
            echo "$(stamp) window-runner: run-health ${dir}"
            timeout 60 python tools/pert_watch.py watch "$dir" --once
            timeout 60 python tools/pert_watch.py check "$dir" \
                > /dev/null || echo "window-runner: pert_watch check FAILING for ${dir}"
        } >> "$LOG" 2>&1
    done
}

health_watch_loop() {
    while true; do
        sleep 60
        health_snapshot
    done
}

core_done() {
    [ -s artifacts/BENCH_r06_tpu_300iter.json ] \
        && [ -s artifacts/BENCH_r06_tpu_10k.json ] \
        && [ -s artifacts/FULL_PIPELINE_r06_rescue_tpu.json ] \
        && [ -s artifacts/FULL_PIPELINE_r06_5k_tpu.json ] \
        && [ -s artifacts/FULL_PIPELINE_r06_20kb_tpu.json ]
}

for attempt in $(seq 1 200); do
    if probe; then
        echo "$(stamp) window-runner: probe ok (attempt ${attempt}) - running battery" >> "$LOG"
        health_watch_loop &
        watch_pid=$!
        battery || true   # a failed step still falls through to sleep
        kill "$watch_pid" 2>/dev/null
        wait "$watch_pid" 2>/dev/null
        health_snapshot   # final post-battery frame per stage
        if core_done && { [ -s artifacts/FULL_PIPELINE_r06_10k_tpu.json ] \
                          || [ "$tries_10k" -ge "$MAX_10K_TRIES" ]; }; then
            echo "$(stamp) window-runner: battery complete (10k tries=${tries_10k})" >> "$LOG"
            # fleet-index the battery's run logs so the TPU rounds land
            # in the same trend/regress surface as the CPU rounds
            python -m tools.pert_fleet index --roots .pert_runs artifacts \
                --out artifacts/FLEET_INDEX_r06_tpu.json >> "$LOG" 2>&1 || true
            # cost plane: one meter waterfall per battery run log —
            # device-seconds, waste taxonomy, conservation verdict —
            # concatenated next to the fleet index so a TPU window's
            # goodput is inspectable without replaying anything
            : > artifacts/METER_r20_tpu_battery.md
            find .pert_runs -name '*.jsonl' 2>/dev/null | sort | \
            while read -r rl; do
                echo "## ${rl}" >> artifacts/METER_r20_tpu_battery.md
                timeout 60 python -m tools.pert_meter report "$rl" \
                    >> artifacts/METER_r20_tpu_battery.md 2>>"$LOG" || true
            done
            exit 0
        fi
    fi
    sleep 300
done
echo "$(stamp) window-runner: gave up after 200 attempts" >> "$LOG"
