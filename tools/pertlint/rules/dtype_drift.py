"""PL004: dtype-less jnp array constructors in numerics-critical paths.

The enumeration kernel and model math are tuned for float32 (the Pallas
kernels assume it; TPU matmul units want it; the f64-vs-f32 drift
between x64-enabled hosts and TPU is a classic source of
silently-different results).  ``jnp.zeros(shape)`` et al. pick their
dtype from global config (``jax_enable_x64``) — an ambient global the
kernel code must not depend on — so in ``ops/`` and ``models/`` every
constructor states its dtype.

Scope: files whose path contains an ``ops`` or ``models`` directory
component (the rule is path-scoped; host-side pandas plumbing elsewhere
may rely on numpy defaults freely).  ``dtype=`` may be a keyword or the
constructor's positional dtype slot.  ``jnp.asarray`` is exempt — it is
a *conversion*, preserving its input's dtype, not a fresh-dtype choice.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Iterable

from tools.pertlint.core import Finding, Rule, register

SCOPED_DIRS = {"ops", "models"}

# constructor -> index of the positional dtype slot
_CONSTRUCTORS = {"array": 1, "zeros": 1, "ones": 1, "empty": 1, "full": 2,
                 "arange": None, "linspace": None}  # None: keyword-only check


def _has_dtype(call: ast.Call, pos_slot) -> bool:
    if any(kw.arg == "dtype" for kw in call.keywords):
        return True
    return pos_slot is not None and len(call.args) > pos_slot


def in_scope(path: str) -> bool:
    return bool(SCOPED_DIRS & set(pathlib.PurePosixPath(path).parts[:-1]))


@register
class DtypeDrift(Rule):
    id = "PL004"
    name = "dtype-drift"
    severity = "error"
    description = ("jnp.array/zeros/ones/full/... without an explicit "
                   "dtype in ops/ or models/ inherits the ambient x64 "
                   "config; state the dtype")

    def check(self, ctx) -> Iterable[Finding]:
        if not in_scope(ctx.path):
            return
        jnp_names = ctx.jnp_aliases
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id in jnp_names
                    and func.attr in _CONSTRUCTORS):
                continue
            if not _has_dtype(node, _CONSTRUCTORS[func.attr]):
                yield self.finding(
                    ctx, node,
                    f"jnp.{func.attr} without an explicit dtype in a "
                    f"numerics-critical path; the result dtype follows the "
                    f"ambient jax_enable_x64 config — pass dtype=jnp.float32 "
                    f"(or the intended dtype)")
