"""PL010: control_decision actions must exist in the schema's enum.

The adaptive fit controller's audit trail is only trustworthy if every
``control_decision`` event validates against the checked-in schema
(``obs/runlog_schema.json``), and the field that carries the decision —
``action`` — is an enum there.  PL009 already guarantees the event KIND
is registered; this rule closes the remaining gap for the payload: an
``emit("control_decision", action="<literal>", ...)`` call site whose
action literal is missing from the enum writes events that fail schema
validation, but only when a run actually takes that decision path —
exactly the rarely-exercised branches (NaN escalation, re-seed) where a
rot would hide longest.  Same static AST cross-check pattern as PL009.

Precision contract:

* only ``.emit("control_decision", ...)`` attribute calls on a
  recognisable RunLog receiver fire (the PL009 receiver heuristic:
  names/attributes containing ``log``, the ``current()`` accessor, or
  ``self`` inside a ``*Log*`` class);
* only a LITERAL ``action=`` keyword is checked — a non-literal action
  (``action=decision["action"]``, the runner's pass-through) cannot be
  checked statically and is left to the runtime validator;
* emit calls for other event kinds never fire this rule.
"""

from __future__ import annotations

import ast
import functools
import json
from typing import FrozenSet, Iterable, Optional

from tools.pertlint.core import Finding, Rule, register
from tools.pertlint.rules.event_kinds import (
    _SCHEMA_PATH,
    _is_runlog_receiver,
)


@functools.lru_cache(maxsize=1)
def schema_control_actions() -> FrozenSet[str]:
    """The control_decision.action enum pinned by the checked-in schema;
    empty when unreadable (the rule then stays silent — a missing schema
    is the schema tests' problem, not a lint crash)."""
    try:
        doc = json.loads(_SCHEMA_PATH.read_text())
        enum = doc["definitions"]["control_decision"]["properties"][
            "action"]["enum"]
        return frozenset(enum)
    except (OSError, KeyError, TypeError, ValueError):
        return frozenset()


@register
class UnknownControlDecisionAction(Rule):
    id = "PL010"
    name = "unknown-control-decision-action"
    severity = "error"
    description = ("RunLog .emit('control_decision', action='<literal>') "
                   "call site whose action literal is not in the "
                   "control_decision action enum of "
                   "obs/runlog_schema.json — the emitted events fail "
                   "schema validation; register the action in the enum "
                   "(and obs.controller.ACTIONS) first")

    def __init__(self, actions: Optional[Iterable[str]] = None):
        # injectable for tests; default = the checked-in schema enum
        self._actions = (schema_control_actions() if actions is None
                         else frozenset(actions))

    def check(self, ctx) -> Iterable[Finding]:
        if not self._actions:
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == "control_decision"):
                continue
            if not _is_runlog_receiver(node.func.value, node, ctx):
                continue
            for kw in node.keywords:
                if kw.arg != "action":
                    continue
                if not (isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)):
                    continue  # non-literal: runtime validator's job
                action = kw.value.value
                if action not in self._actions:
                    # anchor to the literal itself, not the (possibly
                    # multi-line) call head: the expect/suppress comment
                    # conventions are line-scoped
                    yield self.finding(
                        ctx, kw.value,
                        f"control_decision action {action!r} is not in "
                        f"the action enum of obs/runlog_schema.json — "
                        f"emitted events will fail schema validation; "
                        f"add the action to the schema enum and "
                        f"obs.controller.ACTIONS (and bump "
                        f"SCHEMA_VERSION if the vocabulary changes "
                        f"meaning)")
