"""PL005: legacy global-state numpy RNG in library code.

``np.random.rand`` / ``np.random.seed`` / ... mutate numpy's hidden
global generator: results depend on call order across the whole process,
two pipeline stages can perturb each other, and no amount of per-stage
seeding makes a run reproducible once library code touches the global
stream.  Library code must thread an explicit ``np.random.Generator``
(``np.random.default_rng(seed)``) — the package's own convention
(``api.py`` seeds one at construction) — or use ``jax.random`` keys.

Constructor calls (``default_rng``, ``Generator``, ``SeedSequence`` and
the bit generators) are exempt: they *create* explicit streams.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.pertlint.core import Finding, Rule, register

_EXPLICIT_CONSTRUCTORS = {"default_rng", "Generator", "SeedSequence",
                          "RandomState",  # legacy but still an instance
                          "PCG64", "PCG64DXSM", "Philox", "SFC64", "MT19937"}


@register
class UnseededRng(Rule):
    id = "PL005"
    name = "unseeded-rng"
    severity = "error"
    description = ("numpy.random module-level call (global hidden RNG "
                   "state) in library code; thread a "
                   "np.random.default_rng(seed) Generator instead")

    def check(self, ctx) -> Iterable[Finding]:
        np_names = ctx.numpy_aliases
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # np.random.<fn>(...) — an Attribute on Attribute('random')
            if not (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Attribute)
                    and func.value.attr == "random"
                    and isinstance(func.value.value, ast.Name)
                    and func.value.value.id in np_names):
                continue
            if func.attr in _EXPLICIT_CONSTRUCTORS:
                continue
            yield self.finding(
                ctx, node,
                f"np.random.{func.attr} uses numpy's global RNG state; "
                f"thread an explicit np.random.default_rng(seed) Generator "
                f"through instead")
