"""PL008: bare ``print(...)`` / ``logging.basicConfig(...)`` in library
modules.

The observability contract (OBSERVABILITY.md) routes everything a run
wants to say through exactly two channels: the package logger
(``utils.profiling.logger`` — one namespace the embedding application
controls) and the structured RunLog (``obs/runlog.py`` — the machine-
readable record).  A bare ``print`` bypasses both: it cannot be
filtered, captured or correlated, corrupts tools whose stdout IS the
artifact (``bench.py`` and the bench tools print exactly one JSON
line), and vanishes from the JSONL trace a BENCH round diffs.
``logging.basicConfig`` is worse — library code calling it mutates the
ROOT logger of the embedding application (handler duplication, format
hijacking); configuring logging is the application's decision.

Precision contract (what keeps this rule quiet on correct code):

* only the built-in ``print`` NAME fires — a locally-bound ``print``
  (shadowed by assignment, parameter, or import) is the author's own
  callable and exempt; attribute calls (``obj.print()``) never match;
* ``basicConfig`` fires as an attribute call on any alias of the
  ``logging`` module (``import logging as log`` included) and as the
  bare name when imported via ``from logging import basicConfig``;
* the rule is for LIBRARY modules: the lint gate runs it over
  ``scdna_replication_tools_tpu`` — scripts under ``tools/`` own their
  stdout and are not gated.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from tools.pertlint.core import Finding, Rule, register


def _logging_aliases(tree: ast.Module) -> Set[str]:
    """Names the ``logging`` module is bound to in this file."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "logging":
                    aliases.add(alias.asname or "logging")
    return aliases


def _basicconfig_names(tree: ast.Module) -> Set[str]:
    """Names ``logging.basicConfig`` is bound to via from-imports."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "logging":
                for alias in node.names:
                    if alias.name == "basicConfig":
                        names.add(alias.asname or "basicConfig")
    return names


def _binds_print(node) -> bool:
    """Does THIS scope (function params + its Store/import bindings,
    nested scopes included as an over-approximation) bind ``print``?"""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = node.args
        params = (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                  + ([a.vararg] if a.vararg else [])
                  + ([a.kwarg] if a.kwarg else []))
        if any(arg.arg == "print" for arg in params):
            return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store) \
                and sub.id == "print":
            return True
        if isinstance(sub, (ast.Import, ast.ImportFrom)):
            for alias in sub.names:
                if (alias.asname or alias.name).split(".")[0] == "print":
                    return True
    return False


def _print_is_shadowed(node: ast.Call, ctx) -> bool:
    """Walk the enclosing function scopes (plus module scope): the call
    is the builtin only when no enclosing scope rebinds ``print``."""
    cursor = node
    while cursor is not None:
        if isinstance(cursor, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _binds_print(cursor):
            return True
        cursor = ctx.parents.get(cursor)
    # module scope: only direct top-level bindings (a rebind inside some
    # OTHER function must not exempt this call)
    for stmt in ctx.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if stmt.name == "print":
                return True
            continue
        if _binds_print(stmt):
            return True
    return False


@register
class PrintInLibrary(Rule):
    id = "PL008"
    name = "print-in-library"
    severity = "error"
    description = ("bare print(...) / logging.basicConfig(...) in library "
                   "modules — route output through the package logger or "
                   "the telemetry RunLog (obs/runlog.py); basicConfig "
                   "mutates the embedding application's root logger")

    def check(self, ctx) -> Iterable[Finding]:
        log_aliases = _logging_aliases(ctx.tree)
        bc_names = _basicconfig_names(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name):
                if func.id == "print" \
                        and not _print_is_shadowed(node, ctx):
                    yield self.finding(
                        ctx, node,
                        "bare print() in a library module; use the "
                        "package logger (utils.profiling.logger) or emit "
                        "a RunLog event (obs/runlog.py)")
                elif func.id in bc_names:
                    yield self.finding(
                        ctx, node,
                        "logging.basicConfig() in a library module "
                        "mutates the embedding application's root "
                        "logger; configure handlers in the application, "
                        "log through the package logger here")
            elif (isinstance(func, ast.Attribute)
                  and func.attr == "basicConfig"
                  and isinstance(func.value, ast.Name)
                  and func.value.id in log_aliases):
                yield self.finding(
                    ctx, node,
                    "logging.basicConfig() in a library module mutates "
                    "the embedding application's root logger; configure "
                    "handlers in the application, log through the "
                    "package logger here")
