"""PL012: registry metric names must exist in the metrics manifest.

The metrics contract is the checked-in catalogue
(``scdna_replication_tools_tpu/obs/metrics_manifest.json``): every
metric the registry (obs/metrics.py) records is declared there — name,
type, labels, histogram bucket edges, regression thresholds.  A call
site recording an undeclared name still works at runtime (the registry
warns once and records anyway, because losing data over a missing
manifest row would be worse), but the metric is invisible to the
snapshot (unknown = unstable), untrended by the fleet index and
ungated by ``pert_fleet regress`` — a one-off that silently never
becomes a quantity the repo can reason about.  This rule closes the
gap statically, exactly like PL009/PL010 do for the RunLog event and
action enums: every LITERAL metric name at a registry call site is
cross-checked against the manifest at lint time.

Precision contract (what keeps this rule quiet on correct code):

* only ``.counter("<literal>")`` / ``.gauge`` / ``.histogram`` /
  ``.observe`` attribute calls fire, and only when the receiver is
  recognisably a metrics registry: a name/attribute containing
  ``metric`` or ``registry`` (``metrics``, ``self.metrics``,
  ``registry``, ``reg.metrics``), the ``current()`` accessor
  (``metrics_mod.current().counter(...)`` — the seam the RunLog emit
  hook uses), or ``self`` inside a ``*Metrics*`` class
  (``obs/metrics.py``'s own ``record_event`` dispatcher);
* non-literal names (``counter(name)``) are skipped — they cannot be
  checked statically and the runtime warning still covers them;
* other ``.observe`` APIs (rx streams, watchdogs) never match the
  receiver heuristic.
"""

from __future__ import annotations

import ast
import functools
import json
import pathlib
from typing import FrozenSet, Iterable, Optional

from tools.pertlint.core import Finding, Rule, register

_MANIFEST_PATH = (pathlib.Path(__file__).resolve().parents[3]
                  / "scdna_replication_tools_tpu" / "obs"
                  / "metrics_manifest.json")

_RECEIVER_HINTS = ("metric", "registry")
_METHODS = ("counter", "gauge", "histogram", "observe")


@functools.lru_cache(maxsize=1)
def manifest_metric_names() -> FrozenSet[str]:
    """The metric names pinned by the checked-in manifest; empty when
    the manifest is unreadable (the rule then stays silent — a missing
    manifest is the metrics tests' problem, not a lint crash)."""
    try:
        doc = json.loads(_MANIFEST_PATH.read_text())
        return frozenset(doc["metrics"])
    except (OSError, KeyError, TypeError, ValueError):
        return frozenset()


def _enclosing_metrics_class(node, ctx) -> bool:
    """Is ``node`` lexically inside a class whose name contains
    'Metrics'?"""
    cursor = ctx.parents.get(node)
    while cursor is not None:
        if isinstance(cursor, ast.ClassDef) and "Metrics" in cursor.name:
            return True
        cursor = ctx.parents.get(cursor)
    return False


def _is_registry_receiver(value, node, ctx) -> bool:
    """Does the call receiver look like a MetricsRegistry?"""
    if isinstance(value, ast.Name):
        if value.id == "self":
            return _enclosing_metrics_class(node, ctx)
        return any(h in value.id.lower() for h in _RECEIVER_HINTS)
    if isinstance(value, ast.Attribute):
        return any(h in value.attr.lower() for h in _RECEIVER_HINTS)
    if isinstance(value, ast.Call):
        func = value.func
        name = func.id if isinstance(func, ast.Name) else (
            func.attr if isinstance(func, ast.Attribute) else "")
        # the current() accessor — same shape as PL009's; the method
        # whitelist (counter/gauge/histogram/observe vs emit) is what
        # keeps the runlog and metrics seams apart
        return name == "current"
    return False


@register
class UnknownMetricName(Rule):
    id = "PL012"
    name = "unknown-metric-name"
    severity = "error"
    description = ("metrics-registry call site (.counter/.gauge/"
                   ".histogram/.observe) whose literal metric name is "
                   "not in obs/metrics_manifest.json — the metric is "
                   "excluded from snapshots, untrended by the fleet "
                   "index and ungated by pert_fleet regress; register "
                   "it (name, type, labels, buckets) in the manifest "
                   "first")

    def __init__(self, names: Optional[Iterable[str]] = None):
        # injectable for tests; default = the checked-in manifest
        self._names = (manifest_metric_names() if names is None
                       else frozenset(names))

    def check(self, ctx) -> Iterable[Finding]:
        if not self._names:
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            if not _is_registry_receiver(node.func.value, node, ctx):
                continue
            name = node.args[0].value
            if name not in self._names:
                yield self.finding(
                    ctx, node,
                    f"metric name {name!r} is not in "
                    f"obs/metrics_manifest.json — it will be excluded "
                    f"from metrics_snapshot events (unknown = "
                    f"unstable), untrended by the fleet index and "
                    f"ungated by pert_fleet regress; add it to the "
                    f"manifest (name, type, labels, buckets) first")
