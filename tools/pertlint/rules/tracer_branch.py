"""PL002: Python control flow on traced values.

A Python ``if``/``while`` inside traced code evaluates its test eagerly
at trace time; when the test depends on a traced value this raises
``ConcretizationTypeError`` — or worse, silently bakes one branch into
the compiled program when the value happens to be concrete during
tracing but data-dependent at run time.  ``lax.cond`` /
``lax.while_loop`` / ``jnp.where`` are the device-side forms.

Detection is conservative to stay precise: a test is flagged when its
expression *computes with jax* — it contains a call rooted at the
module's ``jnp``/``lax`` aliases or a ``jax.*`` attribute chain
(``if jnp.isnan(loss):``, ``while lax.lt(i, n):``, ``if x.any():`` where
``x`` came from jnp stays out of reach of an AST pass and is left to
runtime).  Static configuration branches (``if spec.sparse_etas:``,
``if mask is None:``) never match, which is exactly right — those are
legal and idiomatic under jit.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.pertlint import jitgraph
from tools.pertlint.core import Finding, Rule, register


def _jax_call_in(expr: ast.AST, jnp_names, lax_names) -> bool:
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        chain = jitgraph.attr_chain(node.func)
        if not chain:
            continue
        root = chain[0]
        if root in jnp_names or root in lax_names:
            return True
        if root == "jax":  # jax.lax.*, jax.numpy.*
            return True
    return False


@register
class TracerBranch(Rule):
    id = "PL002"
    name = "tracer-branch"
    severity = "error"
    description = ("Python if/while on a jax-computed value inside traced "
                   "code; use lax.cond/lax.while_loop/jnp.where")

    def check(self, ctx) -> Iterable[Finding]:
        jnp_names = ctx.jnp_aliases
        lax_names = ctx.lax_aliases
        for func in ctx.traced.traced:
            for node in jitgraph.owned_statements(func):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                if _jax_call_in(node.test, jnp_names, lax_names):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    repl = ("lax.cond / jnp.where"
                            if isinstance(node, ast.If)
                            else "lax.while_loop / lax.fori_loop")
                    yield self.finding(
                        ctx, node,
                        f"Python `{kind}` on a jax-computed value inside "
                        f"jit-reachable code branches at trace time; "
                        f"use {repl}")
