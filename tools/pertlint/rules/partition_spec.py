"""PL003: PartitionSpec constructed outside layout.py.

``layout.py`` is the single owner of the tensor-layout contract — every
``jax.sharding.PartitionSpec`` in the package is built there so the mesh
placement (``parallel/mesh.py``) and the ``shard_map`` call sites
(``models/pert.py``) can never disagree about which axis is which (the
round-4 state-major migration broke five modules at once precisely
because this convention was duplicated).  Constructing a raw
PartitionSpec anywhere else reintroduces that failure mode.

Detection: any call to a name bound (by import) to
``jax.sharding.PartitionSpec`` — including ``as P`` renames — or a
``jax.sharding.PartitionSpec(...)`` / ``sharding.PartitionSpec(...)``
attribute call, in any file whose name is not in the allowlist.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Iterable, Set

from tools.pertlint.core import Finding, Rule, register

ALLOWED_FILENAMES = {"layout.py"}


def _spec_aliases(tree: ast.Module) -> Set[str]:
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.endswith("sharding"):
            for a in node.names:
                if a.name == "PartitionSpec":
                    aliases.add(a.asname or a.name)
    return aliases


@register
class RawPartitionSpec(Rule):
    id = "PL003"
    name = "raw-partitionspec"
    severity = "error"
    description = ("PartitionSpec constructed outside layout.py, the "
                   "single owner of the sharding contract")

    def check(self, ctx) -> Iterable[Finding]:
        if pathlib.PurePosixPath(ctx.path).name in ALLOWED_FILENAMES:
            return
        aliases = _spec_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            direct = isinstance(func, ast.Name) and func.id in aliases
            dotted = isinstance(func, ast.Attribute) \
                and func.attr == "PartitionSpec"
            if direct or dotted:
                yield self.finding(
                    ctx, node,
                    "raw PartitionSpec constructed outside layout.py; add "
                    "a spec builder to layout.py (single source of truth "
                    "for the sharding contract) and call that instead")
