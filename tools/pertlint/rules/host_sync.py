"""PL001: host synchronisation inside jit-traced code.

``float()`` / ``int()`` / ``bool()`` / ``.item()`` / ``np.asarray`` /
``np.array`` / ``jax.device_get`` applied to a traced value forces a
device->host transfer and a blocking sync — inside the compiled training
loop it either fails at trace time (ConcretizationTypeError) or, on the
paths where jax tolerates it, silently serialises the hot loop on the
host round-trip (BENCH lineage: the whole point of the one-dispatch
``lax.while_loop`` driver in infer/svi.py is that no such sync exists).

Exemptions that keep the rule precise:

* literal arguments (``float(1e-6)``) — no tracer involved;
* names listed in the jit decoration's ``static_argnames`` — Python
  values by construction;
* ``len(...)`` / ``.shape`` / ``.ndim`` / ``.size`` / ``.dtype``
  arguments — static metadata, not traced data.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.pertlint import jitgraph
from tools.pertlint.core import Finding, Rule, register

_CASTS = {"float", "int", "bool", "complex"}
_NUMPY_SYNCS = {"asarray", "array"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}


def _is_static_expr(expr: ast.AST, statics) -> bool:
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.Name) and expr.id in statics:
        return True
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
            and expr.func.id == "len":
        return True
    # x.shape, x.shape[0], x.dtype, ...
    node = expr
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return True
    return False


@register
class HostSyncInJit(Rule):
    id = "PL001"
    name = "host-sync-in-jit"
    severity = "error"
    description = ("float()/int()/bool()/.item()/np.asarray on a traced "
                   "value inside jit/shard_map-reachable code forces a "
                   "host sync")

    def check(self, ctx) -> Iterable[Finding]:
        traced = ctx.traced
        np_names = ctx.numpy_aliases
        for func in traced.traced:
            statics = traced.statics_for(func)
            for node in jitgraph.owned_statements(func):
                if not isinstance(node, ast.Call):
                    continue
                yield from self._check_call(ctx, node, statics, np_names)

    def _check_call(self, ctx, call: ast.Call, statics, np_names):
        func = call.func
        if isinstance(func, ast.Name) and func.id in _CASTS:
            if call.args and not _is_static_expr(call.args[0], statics):
                yield self.finding(
                    ctx, call,
                    f"{func.id}() on a (potentially traced) value inside "
                    f"jit-reachable code forces a host sync; compute with "
                    f"jnp/lax ops or mark the argument static")
        elif isinstance(func, ast.Attribute):
            if func.attr == "item" and not call.args:
                yield self.finding(
                    ctx, call,
                    ".item() inside jit-reachable code forces a host sync")
            elif func.attr in _NUMPY_SYNCS \
                    and jitgraph.root_name(func) in np_names:
                yield self.finding(
                    ctx, call,
                    f"np.{func.attr}() inside jit-reachable code pulls the "
                    f"value to host; use jnp.{func.attr} (stays on device)")
            elif func.attr == "device_get":
                yield self.finding(
                    ctx, call,
                    "jax.device_get inside jit-reachable code forces a "
                    "host sync")
