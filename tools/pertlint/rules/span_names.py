"""PL014: span hygiene — registered names at tracer call sites, and
``span()`` used as a context manager.

The span vocabulary is the checked-in registry
(``scdna_replication_tools_tpu/obs/span_registry.json``), the same
discipline PL009/PL010/PL012 apply to event kinds, controller actions
and metric names: a literal span name opened at a
``tracer.span(...)`` / ``tracer.begin(...)`` /
``tracer.record_span(...)`` call site that the registry does not know
produces trace rows no timeline/waterfall consumer can join on —
discoverable only by staring at a Perfetto dump three rounds later.
Dynamic names are exempt (PhaseTimer-derived spans carry the phase
name itself; that vocabulary is owned by the phase ledger).

The second check is structural: ``tracer.span(...)`` returns a context
manager, and a span that is never closed wedges the open-span stack —
every later span parents under it and the worker's status surface
reports a forever-"in flight" phase.  The rule flags:

* a bare ``tracer.span(...)`` expression statement (created and
  dropped: the span can never close);
* ``name = tracer.span(...)`` where ``name`` is never used as a
  ``with`` context in the same function.

Code that genuinely needs a non-lexical lifetime uses the explicit
``begin()``/``end()`` pair — that is what the API split exists for.

Precision contract: only receivers that look like a tracer fire — a
name/attribute containing ``tracer``, or ``self`` inside a ``*Tracer*``
class — so unrelated ``.span``/``.begin`` APIs never match.
"""

from __future__ import annotations

import ast
import functools
import json
import pathlib
from typing import FrozenSet, Iterable, Optional

from tools.pertlint.core import Finding, Rule, register

_REGISTRY_PATH = (pathlib.Path(__file__).resolve().parents[3]
                  / "scdna_replication_tools_tpu" / "obs"
                  / "span_registry.json")

_RECEIVER_HINT = "tracer"
_NAME_METHODS = ("span", "begin", "record_span")


@functools.lru_cache(maxsize=1)
def registry_span_names() -> FrozenSet[str]:
    """Span names pinned by the checked-in registry; empty when the
    file is unreadable (the rule then stays silent — a missing registry
    is the span tests' problem, not a lint crash)."""
    try:
        doc = json.loads(_REGISTRY_PATH.read_text())
        return frozenset(doc["spans"])
    except (OSError, KeyError, TypeError, ValueError):
        return frozenset()


def _enclosing_tracer_class(node, ctx) -> bool:
    cursor = ctx.parents.get(node)
    while cursor is not None:
        if isinstance(cursor, ast.ClassDef) and "Tracer" in cursor.name:
            return True
        cursor = ctx.parents.get(cursor)
    return False


def _is_tracer_receiver(value, node, ctx) -> bool:
    if isinstance(value, ast.Name):
        if value.id == "self":
            return _enclosing_tracer_class(node, ctx)
        return _RECEIVER_HINT in value.id.lower()
    if isinstance(value, ast.Attribute):
        return _RECEIVER_HINT in value.attr.lower()
    return False


def _literal_name(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _stmt_context(node, ctx):
    """(nearest statement ancestor, True when the call sits inside a
    ``with`` item on the way up)."""
    cursor = ctx.parents.get(node)
    in_withitem = False
    while cursor is not None and not isinstance(cursor, ast.stmt):
        if isinstance(cursor, ast.withitem):
            in_withitem = True
        cursor = ctx.parents.get(cursor)
    return cursor, in_withitem


def _assign_names(stmt) -> list:
    """Plain-name targets of an assignment statement ([] otherwise)."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    return [t.id for t in targets if isinstance(t, ast.Name)]


def _with_context_names(func) -> FrozenSet[str]:
    """Names used as a ``with`` context expression anywhere in the
    function body (nested functions included — a closure managing the
    span is still a managed span)."""
    names = set()
    for node in ast.walk(func):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Name):
                    names.add(expr.id)
    return frozenset(names)


@register
class SpanHygiene(Rule):
    id = "PL014"
    name = "span-hygiene"
    severity = "error"
    description = ("tracer span call sites: literal span names must "
                   "exist in obs/span_registry.json, and span() — a "
                   "context manager — must actually be entered (a "
                   "dropped or never-with'd span wedges the open-span "
                   "stack; use begin()/end() for non-lexical "
                   "lifetimes)")

    def __init__(self, names: Optional[Iterable[str]] = None):
        # injectable for tests; default = the checked-in registry
        self._names = (registry_span_names() if names is None
                       else frozenset(names))

    def check(self, ctx) -> Iterable[Finding]:
        # pass 1 — registered names at every tracer call site
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _NAME_METHODS):
                continue
            if not _is_tracer_receiver(node.func.value, node, ctx):
                continue
            name = _literal_name(node)
            if name is not None and self._names \
                    and name not in self._names:
                yield self.finding(
                    ctx, node,
                    f"span name {name!r} is not in "
                    f"obs/span_registry.json — register it (name + "
                    f"help) so timeline/waterfall consumers can join "
                    f"on it")
        # pass 2 — unclosed spans, per function scope
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            with_names = _with_context_names(func)
            for node in ast.walk(func):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "span"):
                    continue
                if not _is_tracer_receiver(node.func.value, node, ctx):
                    continue
                stmt, in_withitem = _stmt_context(node, ctx)
                if in_withitem or stmt is None:
                    continue
                if isinstance(stmt, ast.Expr):
                    yield self.finding(
                        ctx, node,
                        "span() created and dropped — the context "
                        "manager is never entered, so the span never "
                        "closes; wrap it in `with`, or use "
                        "begin()/end() for a non-lexical lifetime")
                    continue
                assigned = _assign_names(stmt)
                if assigned and not any(n in with_names
                                        for n in assigned):
                    yield self.finding(
                        ctx, node,
                        f"span() assigned to {assigned[0]!r} but never "
                        f"used as a `with` context in this function — "
                        f"the span never closes; enter it with "
                        f"`with {assigned[0]}:`, or use begin()/end()")
